"""apexcheck tests: the jaxpr walker, the JXP contract library, the
entrypoint registry + tier-1 gate, StaticCostReport exactness, and the
predicted-vs-calibrated CostDB diff.

One positive + one negative TRACED fixture per JXP code (the jaxpr
analog of test_lint's per-rule source fixtures), walker descent through
all five higher-order primitives, hand-computed static-cost numbers, the
kind×axis parity acceptance against ``monitor.count_collective``, and
the CLI exit-code / artifact / baseline behavior of
``python -m apex_tpu.lint --jaxpr``.
"""

import json
import os

import jax
import jax.numpy as jnp
import jax.random as jr
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import monitor
from apex_tpu.lint import contracts as jc
from apex_tpu.lint import entrypoints as eps
from apex_tpu.lint import jaxpr_check as jx
from apex_tpu.lint.__main__ import main as lint_main
from apex_tpu.parallel import mesh as mesh_lib

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

K = jr.PRNGKey(3)


def _tp_mesh(n=4):
    return mesh_lib.make_mesh(tensor_model_parallel_size=n)


# --- the walker ---------------------------------------------------------------

class TestWalker:
    def _nested_program(self):
        """One program threading all five higher-order primitives the
        ISSUE names: pjit, scan, while, cond, custom_vjp — inside a
        shard_map."""

        @jax.custom_vjp
        def cv(x):
            return x * 2

        cv.defvjp(lambda x: (cv(x), x), lambda r, g: (g * 2,))

        mesh = _tp_mesh()

        def scan_body(c, x):
            return c + jax.lax.psum(x, "tp").sum(), c

        def inner(x):
            c, _ = jax.lax.scan(scan_body, jnp.float32(0), x)
            c = jax.lax.while_loop(lambda v: v < 3, lambda v: v + 1, c)
            c = jax.lax.cond(c > 1, lambda v: v + 1, lambda v: v - 1, c)
            return c + cv(x).sum()

        sm = mesh_lib.shard_map(inner, mesh=mesh,
                                in_specs=(P(None, "tp"),), out_specs=P())
        return jax.jit(sm), (jnp.zeros((5, 8)),)

    def test_descends_all_five_higher_order_primitives(self):
        fn, args = self._nested_program()
        closed = jax.make_jaxpr(fn)(*args)
        sites = list(jx.iter_sites(closed))
        prims = {s.prim for s in sites}
        for prim in ("pjit", "scan", "while", "cond",
                     "custom_vjp_call_jaxpr", "shard_map"):
            assert prim in prims, f"walker never saw {prim}"
        # eqns INSIDE each higher-order body were visited: their paths
        # carry the enclosing segment
        paths = {s.path for s in sites}
        for seg in ("scan:5", "while", "cond", "custom_vjp_call_jaxpr",
                    "shard_map"):
            assert any(seg in p for p in paths), (
                f"no site under {seg}: {sorted(paths)}")

    def test_scan_multiplier_and_while_bound(self):
        fn, args = self._nested_program()
        closed = jax.make_jaxpr(fn)(*args)
        psums = [s for s in jx.iter_sites(closed) if s.prim == "psum"]
        assert len(psums) == 1
        assert psums[0].mult == 5           # executes once per scan tick
        assert psums[0].bounded             # a scan is statically bounded
        under_while = [s for s in jx.iter_sites(closed)
                       if "while" in s.path]
        assert under_while and all(not s.bounded for s in under_while)

    def test_scan_lengths_helper(self):
        def f(xs):
            def body(c, x):
                return c + x, c
            c, _ = jax.lax.scan(body, jnp.float32(0), xs[:4])
            c2, _ = jax.lax.scan(body, c, xs)
            return c2

        lengths = jx.scan_lengths(jax.make_jaxpr(f)(jnp.zeros((6,))))
        assert sorted(lengths) == [4, 6]

    def test_as_jaxpr_rejects_non_jaxpr(self):
        with pytest.raises(TypeError, match="not a jaxpr"):
            jx.as_jaxpr(42)


# --- one positive + one negative traced fixture per JXP code ------------------

class TestContractFixtures:
    # JXP101 / JXP102 ---------------------------------------------------------
    def _two_scan_jaxpr(self):
        def f(xs):
            def body(c, x):
                return c + x, c
            c, _ = jax.lax.scan(body, jnp.float32(0), xs[:4])
            c2, _ = jax.lax.scan(body, c, xs)
            return c2

        return jax.make_jaxpr(f)(jnp.zeros((6,)))

    def test_jxp101_scan_count(self):
        closed = self._two_scan_jaxpr()
        assert jc.check_jaxpr(closed, [jc.scan_count(2)]) == []
        bad = jc.check_jaxpr(closed, [jc.scan_count(3)])
        assert [f.code for f in bad] == ["JXP101"]
        assert jc.check_jaxpr(closed, [jc.scan_count(min_count=1,
                                                     max_count=2)]) == []
        assert jc.check_jaxpr(closed, [jc.scan_count(max_count=1)])

    def test_jxp102_scan_length(self):
        closed = self._two_scan_jaxpr()
        assert jc.check_jaxpr(closed, [jc.scan_length(4),
                                       jc.scan_length(6)]) == []
        missing = jc.check_jaxpr(closed, [jc.scan_length(7)])
        assert [f.code for f in missing] == ["JXP102"]
        assert "lengths present: [4, 6]" in missing[0].message
        forbidden = jc.check_jaxpr(closed, [jc.scan_length(4, forbid=True)])
        assert [f.code for f in forbidden] == ["JXP102"]
        assert jc.check_jaxpr(closed,
                              [jc.scan_length(7, forbid=True)]) == []

    # JXP201 ------------------------------------------------------------------
    def test_jxp201_use_after_donate(self):
        donating = jax.jit(lambda x: x * 2, donate_argnums=0)

        def bad(x):
            y = donating(x)
            return y + x          # x's buffer may already be y's

        def good(x):
            y = donating(x)
            return y + 1.0

        x = jnp.zeros((4,))
        findings = jc.check_jaxpr(jax.make_jaxpr(bad)(x),
                                  [jc.donation_honored()])
        assert findings and all(f.code == "JXP201" for f in findings)
        assert jc.check_jaxpr(jax.make_jaxpr(good)(x),
                              [jc.donation_honored()]) == []

    def test_jxp201_donated_value_returned(self):
        donating = jax.jit(lambda x: x * 2, donate_argnums=0)

        def bad(x):
            y = donating(x)
            return y, x           # the dead buffer escapes to the caller

        findings = jc.check_jaxpr(jax.make_jaxpr(bad)(jnp.zeros((4,))),
                                  [jc.donation_honored()])
        assert any("returned" in f.message for f in findings)

    # JXP202 ------------------------------------------------------------------
    def test_jxp202_donated_not_rebound(self):
        bad_fn = jax.jit(lambda x: jnp.sum(x), donate_argnums=0)
        good_fn = jax.jit(lambda x: x * 2, donate_argnums=0)
        x = jnp.zeros((4,))
        findings = jc.check_jaxpr(jax.make_jaxpr(bad_fn)(x),
                                  [jc.donation_rebound()])
        assert [f.code for f in findings] == ["JXP202"]
        assert "no matching-aval output" in findings[0].message
        assert jc.check_jaxpr(jax.make_jaxpr(good_fn)(x),
                              [jc.donation_rebound()]) == []

    # JXP301 ------------------------------------------------------------------
    def test_jxp301_no_aval_matching(self):
        s = 64
        q = jnp.zeros((s, 8))
        contract = jc.no_aval_matching(
            lambda shape: sum(1 for d in shape if d >= s) >= 2,
            "two dims >= seq")

        def bad(q, k):
            scores = q @ k.T          # (s, s): the materialized score
            return jax.nn.softmax(scores, axis=-1).sum()

        def good(q, k):
            return jnp.sum(q * k)     # never forms the (s, s) tensor

        findings = jc.check_jaxpr(jax.make_jaxpr(bad)(q, q), [contract])
        assert findings and all(f.code == "JXP301" for f in findings)
        assert f"[{s}, {s}]" in findings[0].message
        assert jc.check_jaxpr(jax.make_jaxpr(good)(q, q), [contract]) == []

    # JXP401 / JXP402 ---------------------------------------------------------
    def _collective_jaxpr(self, use_gather):
        mesh = _tp_mesh()

        def gathered(x):
            return jax.lax.all_gather(x, "tp").sum()

        def ringed(x):
            perm = [(i, (i + 1) % 4) for i in range(4)]
            return jax.lax.ppermute(x, "tp", perm).sum()

        sm = mesh_lib.shard_map(gathered if use_gather else ringed,
                                mesh=mesh, in_specs=(P("tp"),),
                                out_specs=P())
        return jax.make_jaxpr(sm)(jnp.zeros((8, 4)))

    def test_jxp401_no_full_width_all_gather(self):
        contract = jc.no_full_width_all_gather("tp")
        findings = jc.check_jaxpr(self._collective_jaxpr(True), [contract])
        assert [f.code for f in findings] == ["JXP401"]
        assert jc.check_jaxpr(self._collective_jaxpr(False),
                              [contract]) == []

    def test_jxp401_other_axis_clean(self):
        # a gather on ANOTHER axis does not violate the tp contract
        findings = jc.check_jaxpr(self._collective_jaxpr(True),
                                  [jc.no_full_width_all_gather("dp")])
        assert findings == []

    def test_jxp402_ppermute_present(self):
        contract = jc.ppermute_present("tp")
        assert jc.check_jaxpr(self._collective_jaxpr(False),
                              [contract]) == []
        findings = jc.check_jaxpr(self._collective_jaxpr(True), [contract])
        assert [f.code for f in findings] == ["JXP402"]

    # JXP403 ------------------------------------------------------------------
    def test_jxp403_collective_free_region(self):
        mesh = _tp_mesh()

        def body(c, x):
            return c + jax.lax.psum(x, "tp").sum(), c

        def inner(x):
            c, _ = jax.lax.scan(body, jnp.float32(0), x)
            return c

        sm = mesh_lib.shard_map(inner, mesh=mesh,
                                in_specs=(P(None, "tp"),), out_specs=P())
        closed = jax.make_jaxpr(sm)(jnp.zeros((4, 8)))
        dirty = jc.check_jaxpr(
            closed, [jc.collective_free_region(r"(^|/)scan:4(/|$)",
                                               region="scan body")])
        assert dirty and all(f.code == "JXP403" for f in dirty)
        assert "psum" in dirty[0].message

        def clean_inner(x):
            def body2(c, v):
                return c + v.sum(), c
            c, _ = jax.lax.scan(body2, jnp.float32(0), x)
            return jax.lax.psum(c, "tp")  # collective OUTSIDE the region

        sm2 = mesh_lib.shard_map(clean_inner, mesh=mesh,
                                 in_specs=(P(None, "tp"),), out_specs=P())
        closed2 = jax.make_jaxpr(sm2)(jnp.zeros((4, 8)))
        assert jc.check_jaxpr(
            closed2, [jc.collective_free_region(r"(^|/)scan:4(/|$)",
                                                region="scan body")]) == []

    def test_jxp403_missing_region_is_a_violation(self):
        closed = jax.make_jaxpr(lambda x: x + 1)(jnp.zeros((4,)))
        findings = jc.check_jaxpr(
            closed, [jc.collective_free_region(r"scan:99",
                                               region="nonexistent")])
        assert [f.code for f in findings] == ["JXP403"]
        assert "does not exist" in findings[0].message

    # JXP501 ------------------------------------------------------------------
    def _accum_jaxpr(self, dtype):
        def f(xs):
            def body(c, x):
                return c + x, ()
            c, _ = jax.lax.scan(body, jnp.zeros((4,), dtype), xs)
            return c

        return jax.make_jaxpr(f)(jnp.zeros((6, 4), dtype))

    def test_jxp501_fp32_accumulation(self):
        contract = jc.fp32_accumulation()
        findings = jc.check_jaxpr(self._accum_jaxpr(jnp.bfloat16),
                                  [contract])
        assert [f.code for f in findings] == ["JXP501"]
        assert "bfloat16" in findings[0].message
        assert jc.check_jaxpr(self._accum_jaxpr(jnp.float32),
                              [contract]) == []

    def test_jxp501_threaded_bf16_carry_clean(self):
        # a bf16 carry that is merely threaded (not add-accumulated)
        def f(xs):
            def body(c, x):
                return jnp.minimum(c, x), c
            c, _ = jax.lax.scan(body, jnp.zeros((4,), jnp.bfloat16), xs)
            return c

        closed = jax.make_jaxpr(f)(jnp.zeros((6, 4), jnp.bfloat16))
        assert jc.check_jaxpr(closed, [jc.fp32_accumulation()]) == []

    # assert_contracts --------------------------------------------------------
    def test_assert_contracts_raises_with_rendered_findings(self):
        closed = self._two_scan_jaxpr()
        with pytest.raises(AssertionError, match="JXP102"):
            jc.assert_contracts(closed, [jc.scan_length(99)])
        jc.assert_contracts(closed, [jc.scan_length(4)])  # no raise


# --- StaticCostReport ---------------------------------------------------------

class TestStaticCost:
    def _fixture(self):
        """Two collectives + one GEMM with hand-computable numbers:
        per-shard x is (4, 8) fp32 (128 B), w is (8, 16) fp32;
        dot (4,8)@(8,16) = 2*4*8*16 = 1024 FLOPs; psum moves the
        (4, 16) fp32 product (256 B); ppermute moves x (128 B)."""
        mesh = _tp_mesh()

        def body(x, w):
            h = x @ w                              # 1024 FLOPs
            red = jax.lax.psum(h, "tp")            # 256 B over tp
            perm = [(i, (i + 1) % 4) for i in range(4)]
            nxt = jax.lax.ppermute(x, "tp", perm)  # 128 B over tp
            return red.sum() + nxt.sum()

        sm = mesh_lib.shard_map(body, mesh=mesh,
                                in_specs=(P("tp"), P()), out_specs=P())
        return jax.make_jaxpr(sm)(jnp.zeros((16, 8)), jnp.zeros((8, 16)))

    def test_exact_bytes_and_flops(self):
        cost = jx.static_cost(self._fixture(), entrypoint="fixture")
        assert cost["kind"] == "static_cost"
        assert cost["entrypoint"] == "fixture"
        assert cost["collectives"]["psum[tp]"] == {"calls": 1, "bytes": 256}
        assert cost["collectives"]["ppermute[tp]"] == {"calls": 1,
                                                       "bytes": 128}
        assert cost["gemms"]["flops_1024"] == {"calls": 1, "flops": 1024.0}
        assert cost["total_collective_bytes"] == 384
        assert cost["total_gemm_flops"] == 1024.0
        assert cost["unbounded_sites"] == 0

    def test_scan_multiplies_calls_and_bytes(self):
        mesh = _tp_mesh()

        def inner(xs):
            def body(c, x):
                return c + jax.lax.psum(x, "tp").sum(), ()
            c, _ = jax.lax.scan(body, jnp.float32(0), xs)
            return c

        sm = mesh_lib.shard_map(inner, mesh=mesh,
                                in_specs=(P(None, "tp"),), out_specs=P())
        # per-shard per-tick payload: (2,) fp32 = 8 B; 3 ticks
        cost = jx.static_cost(jax.make_jaxpr(sm)(jnp.zeros((3, 8))))
        assert cost["collectives"]["psum[tp]"] == {"calls": 3, "bytes": 24}

    def test_cond_branches_are_alternatives_not_summed(self):
        """Exactly one cond branch executes per call: the report takes
        the per-key field-wise MAX over branches — a program whose both
        branches hold one 32 B ppermute predicts 32 B, not 64."""
        mesh = _tp_mesh()
        perm = [(i, (i + 1) % 4) for i in range(4)]

        def send_small(x):
            return jax.lax.ppermute(x[:2], "tp", perm).sum()

        def send_big(x):
            return jax.lax.ppermute(x, "tp", perm).sum()

        def inner(pred, x):
            return jax.lax.cond(pred, send_big, send_small, x)

        sm = mesh_lib.shard_map(inner, mesh=mesh,
                                in_specs=(P(), P(None, "tp")),
                                out_specs=P())
        cost = jx.static_cost(
            jax.make_jaxpr(sm)(jnp.bool_(True), jnp.zeros((4, 8))))
        # per-shard payloads: big (4, 2) f32 = 32 B, small (2, 2) = 16 B
        assert cost["collectives"]["ppermute[tp]"] == {"calls": 1,
                                                       "bytes": 32}

    def test_cond_branch_adds_to_same_key_outside_the_cond(self):
        """A key that occurs both OUTSIDE and INSIDE the cond sums the
        unconditional cost with the max-over-branches cost — the branch
        alternative is never absorbed by (nor absorbs) the parent's
        running total."""
        mesh = _tp_mesh()
        perm = [(i, (i + 1) % 4) for i in range(4)]

        def branch(x):
            return jax.lax.ppermute(x, "tp", perm).sum()

        def inner(pred, x):
            unconditional = jax.lax.ppermute(x, "tp", perm).sum()
            return unconditional + jax.lax.cond(pred, branch, branch, x)

        sm = mesh_lib.shard_map(inner, mesh=mesh,
                                in_specs=(P(), P(None, "tp")),
                                out_specs=P())
        cost = jx.static_cost(
            jax.make_jaxpr(sm)(jnp.bool_(True), jnp.zeros((4, 8))))
        # per-shard payload (4, 2) f32 = 32 B: 1 unconditional + 1 branch
        assert cost["collectives"]["ppermute[tp]"] == {"calls": 2,
                                                       "bytes": 64}

    def test_gemm_under_while_is_flagged_unbounded(self):
        """The 'flagged, never silently priced' invariant covers GEMMs
        too: a dot inside a while body lands in unbounded_sites."""
        def f(x, w):
            def body(carry):
                i, acc = carry
                return i + 1, acc + x @ w
            _, acc = jax.lax.while_loop(lambda c: c[0] < 3, body,
                                        (0, jnp.zeros((4, 16))))
            return acc.sum()

        cost = jx.static_cost(
            jax.make_jaxpr(f)(jnp.zeros((4, 8)), jnp.zeros((8, 16))))
        assert cost["gemms"]  # the dot was priced (once)...
        assert cost["unbounded_sites"] >= 1  # ...and flagged

    def test_bucket_parity_with_calibrate(self):
        from apex_tpu.prof.calibrate import size_bucket
        for v in (1, 1.5, 2, 3, 1023, 1024, 1025, 7.3e9):
            assert jx.pow2_floor(v) == size_bucket(v), v

    def test_artifact_schema_valid(self):
        from apex_tpu.monitor import schema
        cost = jx.static_cost(self._fixture(), entrypoint="fixture")
        assert schema.validate(cost) == []

    def test_schema_rejects_junk_and_wrong_kind(self):
        from apex_tpu.monitor import schema
        cost = jx.static_cost(self._fixture(), entrypoint="fixture")
        junk = json.loads(json.dumps(cost))
        junk["collectives"]["psum[tp]"]["vibes"] = 1
        assert schema.validate(junk)
        wrong = json.loads(json.dumps(cost))
        wrong["kind"] = "costdb"
        assert schema.validate(wrong)  # costdb schema rejects this shape
        missing = json.loads(json.dumps(cost))
        del missing["entrypoint"]
        assert any("entrypoint" in e for e in schema.validate(missing))


class TestCountCollectiveParity:
    """The acceptance criterion: the static walker enumerates every
    collective ``count_collective`` sees — the single-axis kind×axis
    key sets are EQUAL, with bytes agreeing EXACTLY on the
    forward-only program (the hooks count ``tree_bytes(payload)`` at
    trace time; the walker reads the same avals off the jaxpr). On the
    fwd+bwd program the walker additionally sees each collective's
    autodiff TRANSPOSE (an all_gather's backward is a reduce_scatter of
    the gathered cotangent), which the hooks deliberately do not
    instrument — there, counted is a byte-wise lower bound of static.
    Composite-axis keys (shard_map's replication psums over the unused
    mesh axes) stay out of the single-axis namespace by construction."""

    @staticmethod
    def _trace_counted(grad):
        from apex_tpu.lint.entrypoints import _collective_matmul_chain

        fn, args = _collective_matmul_chain(overlap=False, grad=grad)
        reg = monitor.enable()
        try:
            closed = jax.make_jaxpr(fn)(*args)  # hooks fire during trace
            counted = {
                name[len("collective/"):-len("_bytes")]: v
                for name, v in reg.counters.items()
                if name.startswith("collective/")
                and name.endswith("_bytes")}
        finally:
            monitor.disable()
        static = {
            key: ent for key, ent in
            jx.static_cost(closed)["collectives"].items()
            if "," not in key}
        return counted, static

    def test_forward_counters_match_static_exactly(self):
        counted, static = self._trace_counted(grad=False)
        assert counted, "the blocking chain counted no collectives"
        assert set(static) == set(counted), (
            f"static {sorted(static)} != counted {sorted(counted)}")
        for key, counted_bytes in counted.items():
            assert static[key]["bytes"] == counted_bytes, (
                f"{key}: static {static[key]['bytes']} != "
                f"counted {counted_bytes}")

    def test_fwd_bwd_static_covers_counters_plus_transposes(self):
        counted, static = self._trace_counted(grad=True)
        assert set(static) == set(counted)
        for key, counted_bytes in counted.items():
            # fwd site counted once; the walker also sees its transpose
            assert static[key]["bytes"] >= counted_bytes, key
            assert static[key]["bytes"] <= 3 * counted_bytes, (
                f"{key}: static {static[key]['bytes']} is not "
                f"fwd+transpose-shaped vs counted {counted_bytes}")

    def test_ring_static_cost_sees_the_hops(self):
        closed = eps.trace("collective_matmul_ring")
        cost = jx.static_cost(closed)
        ring = cost["collectives"]["ppermute[tp]"]
        assert ring["calls"] > 0 and ring["bytes"] > 0
        assert not any(k.startswith("all_gather") for k in
                       cost["collectives"])


# --- entrypoint registry + the tier-1 gate ------------------------------------

class TestEntrypoints:
    def test_flagship_surfaces_registered(self):
        names = eps.names()
        assert "gpt_fwd_bwd" in names
        assert "collective_matmul_ring" in names
        assert "flash_bias_fwd_bwd" in names
        assert {"serve_prefill", "serve_decode"} <= set(names)
        for schedule in ("1f1b", "interleaved", "zb"):
            assert f"pipeline_{schedule}" in names
            assert f"pipeline_{schedule}_overlap" in names

    def test_get_unknown_raises(self):
        with pytest.raises(KeyError, match="registered"):
            eps.get("nope")

    def test_every_entrypoint_declares_contracts(self):
        for name in eps.names():
            contracts = eps.get(name).contracts()
            assert contracts, f"{name} declares no contracts"
            for c in contracts:
                assert c.code.startswith("JXP")


class TestJaxprGate:
    """Tier-1: `python -m apex_tpu.lint --jaxpr` over every registered
    entrypoint is CLEAN (or reason-carrying baselined) — the merge
    acceptance. Run in-process for the same wall-clock reason as the
    AST dogfood gate."""

    def test_all_entrypoints_clean_through_real_cli(self, capsys):
        rc = lint_main(["--jaxpr", "--format", "json"])
        out = capsys.readouterr().out
        assert rc == 0, f"jaxpr contract violations:\n{out}"
        report = json.loads(out)
        from apex_tpu import lint
        assert lint.validate_report(report) == []
        assert report["mode"] == "jaxpr"
        assert report["findings"] == []
        assert report["files_scanned"] == len(eps.names())

    def test_all_entrypoints_within_checked_in_memory_budgets(self,
                                                              capsys):
        """The apexmem tier-1 acceptance: every registered entrypoint's
        donation-aware liveness peak stays under its checked-in budget
        (tools/memory_budgets.json) through the real CLI — a CLEAN
        verdict per entrypoint, exit 0. A new entrypoint without a
        budget entry, or a peak regression past its budget, fails here
        as a JXP601 finding."""
        rc = lint_main(["--jaxpr", "--memory", "--budget-file",
                        os.path.join(REPO, "tools",
                                     "memory_budgets.json"),
                        "--format", "json"])
        out = capsys.readouterr().out
        assert rc == 0, f"memory budget violations:\n{out}"
        report = json.loads(out)
        assert report["findings"] == []
        mems = report["memory"]
        assert len(mems) == len(eps.names())
        for m in mems:
            assert m["verdict"] == "CLEAN", m
            assert m["peak_bytes"] <= m["budget_bytes"]
            assert sum(m["families"].values()) == m["peak_bytes"]

    def test_single_entrypoint_selection(self, capsys):
        rc = lint_main(["--jaxpr", "--entrypoint", "pipeline_zb",
                        "--format", "json"])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert report["entrypoints"] == ["pipeline_zb"]

    def test_violation_exits_1(self, capsys, monkeypatch):
        """A deliberately impossible contract on a real entrypoint must
        surface as findings + exit 1 through the full CLI path."""
        ep = eps.get("pipeline_zb")
        bad = eps.EntryPoint(
            ep.name, ep.description, ep.build,
            lambda: [jc.scan_length(123456)])
        monkeypatch.setitem(eps.REGISTRY, "pipeline_zb", bad)
        rc = lint_main(["--jaxpr", "--entrypoint", "pipeline_zb"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "JXP102" in out and "jaxpr:pipeline_zb" in out

    def test_unknown_entrypoint_exits_2(self, capsys):
        rc = lint_main(["--jaxpr", "--entrypoint", "nope"])
        assert rc == 2
        assert "registered:" in capsys.readouterr().err

    def test_paths_with_jaxpr_exits_2(self, capsys):
        rc = lint_main(["--jaxpr", "apex_tpu/"])
        assert rc == 2

    def test_baseline_suppresses_jaxpr_finding(self, tmp_path, capsys,
                                               monkeypatch):
        ep = eps.get("pipeline_zb")
        bad = eps.EntryPoint(ep.name, ep.description, ep.build,
                             lambda: [jc.scan_length(123456)])
        monkeypatch.setitem(eps.REGISTRY, "pipeline_zb", bad)
        baseline = tmp_path / "baseline.json"
        baseline.write_text(json.dumps({"version": 1, "entries": [
            {"path": "jaxpr:pipeline_zb", "code": "JXP102",
             "reason": "fixture: deliberately impossible geometry"}]}))
        rc = lint_main(["--jaxpr", "--entrypoint", "pipeline_zb",
                        "--baseline", str(baseline), "--format", "json"])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert report["findings"] == []
        assert report["suppressed_baseline"] == 1

    def test_list_entrypoints(self, capsys):
        rc = lint_main(["--list-entrypoints"])
        out = capsys.readouterr().out
        assert rc == 0
        for name in eps.names():
            assert name in out
        assert "JXP" in out  # contracts listed per entrypoint


# --- the static-cost artifact through the CLI + validator ---------------------

class TestStaticCostArtifact:
    def test_cli_writes_valid_jsonl(self, tmp_path, capsys):
        out_path = tmp_path / "static_cost.jsonl"
        rc = lint_main(["--jaxpr", "--entrypoint", "collective_matmul_ring",
                        "--entrypoint", "pipeline_zb",
                        "--static-cost", str(out_path), "--format", "json"])
        capsys.readouterr()
        assert rc == 0
        lines = [json.loads(l) for l in
                 out_path.read_text().splitlines() if l.strip()]
        assert [r["entrypoint"] for r in lines] == [
            "collective_matmul_ring", "pipeline_zb"]
        from apex_tpu.monitor import schema
        for record in lines:
            assert schema.validate(record) == []
        zb = lines[1]
        assert "ppermute[pp]" in zb["collectives"]

    def test_validate_metrics_static_cost_dispatch(self, tmp_path,
                                                   capsys):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "validate_metrics", os.path.join(REPO, "tools",
                                             "validate_metrics.py"))
        vm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vm)

        cost = jx.static_cost(
            eps.trace("pipeline_zb"), entrypoint="pipeline_zb")
        good = tmp_path / "ok.jsonl"
        good.write_text(json.dumps(cost) + "\n")
        assert vm.main(["--static-cost", str(good)]) == 0
        capsys.readouterr()

        # drift: a record that lost its kind must FAIL as a bad
        # static_cost, not pass as an unrecognized shape
        bad_kind = dict(cost)
        bad_kind.pop("kind")
        nokind = tmp_path / "nokind.json"
        nokind.write_text(json.dumps(bad_kind))
        assert vm.main(["--static-cost", str(nokind)]) == 1
        capsys.readouterr()

        # drift: junk keys inside a collectives row fail
        junk = json.loads(json.dumps(cost))
        junk["collectives"]["ppermute[pp]"]["vibes"] = 1
        junky = tmp_path / "junk.jsonl"
        junky.write_text(json.dumps(junk) + "\n")
        assert vm.main(["--static-cost", str(junky)]) == 1
        capsys.readouterr()

        # drift: a costdb artifact forced as static_cost fails
        db = tmp_path / "costdb.json"
        db.write_text(json.dumps({"schema": 1, "kind": "costdb",
                                  "collectives": {}, "gemms": {}}))
        assert vm.main(["--static-cost", str(db)]) == 1
        capsys.readouterr()

    def test_content_dispatch_without_flag(self, tmp_path, capsys):
        """A .jsonl stream containing static_cost records validates
        through the plain (unforced) path — content dispatch on kind."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "validate_metrics2", os.path.join(REPO, "tools",
                                              "validate_metrics.py"))
        vm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vm)
        cost = jx.static_cost(
            eps.trace("serve_decode"), entrypoint="serve_decode")
        stream = tmp_path / "stream.jsonl"
        stream.write_text(json.dumps(cost) + "\n")
        assert vm.main([str(stream)]) == 0
        capsys.readouterr()


# --- predicted-vs-calibrated CostDB diff --------------------------------------

def _fake_costdb():
    stat = {"n": 4, "mean": 8e9, "min": 7e9, "max": 9e9,
            "spread_pct": 28.6}
    return {
        "schema": 1, "kind": "costdb", "source": "spans",
        "collectives": {
            "ppermute[pp]": [
                {"bucket_bytes": 128,
                 "bytes": {"n": 4, "mean": 128.0, "min": 128.0,
                           "max": 128.0, "spread_pct": 0.0},
                 "bytes_per_s": stat},
                {"bucket_bytes": 1024,
                 "bytes": {"n": 4, "mean": 1500.0, "min": 1500.0,
                           "max": 1500.0, "spread_pct": 0.0},
                 "bytes_per_s": {**stat, "mean": 16e9}}]},
        "gemms": {"flops_16384": {
            "flops_per_s": {"n": 3, "mean": 1e12, "min": 9e11,
                            "max": 1.1e12, "spread_pct": 22.0},
            "predicted_flops_per_s": None}},
        "predicted_flops_per_s": None,
    }


class TestCostdbDiff:
    def test_diff_covers_and_flags(self):
        from apex_tpu.prof.calibrate import diff_static_cost
        static = {
            "schema": 1, "kind": "static_cost", "entrypoint": "x",
            "collectives": {
                "ppermute[pp]": {"calls": 9, "bytes": 9 * 160},
                "psum[tp]": {"calls": 2, "bytes": 512}},
            "gemms": {"flops_16384": {"calls": 3, "flops": 3 * 20000.0}},
        }
        diff = diff_static_cost(static, _fake_costdb())
        rows = {r["key"]: r for r in diff["rows"]}
        assert diff["uncovered"] == ["psum[tp]"]
        assert diff["covered"] == 2 and diff["total"] == 3
        pp = rows["ppermute[pp]"]
        assert pp["calibrated"] and pp["bucket"] == 128  # nearest to 160 B
        assert pp["predicted_ms"] == pytest.approx(
            1e3 * 9 * 160 / 8e9)
        gemm = rows["flops_16384"]
        assert gemm["calibrated"]
        assert gemm["predicted_ms"] == pytest.approx(1e3 * 60000.0 / 1e12)
        assert not rows["psum[tp]"]["calibrated"]

    def test_nearest_bucket_by_per_call_payload(self):
        from apex_tpu.prof.calibrate import diff_static_cost
        static = {"collectives": {"ppermute[pp]": {"calls": 2,
                                                   "bytes": 2 * 1400}},
                  "gemms": {}}
        diff = diff_static_cost(static, _fake_costdb())
        row = diff["rows"][0]
        assert row["bucket"] == 1024          # 1400 B/call sits nearer 2^10
        assert row["rate"] == 16e9

    def test_cli_costdb_table(self, tmp_path, capsys):
        db_path = tmp_path / "costdb.json"
        db_path.write_text(json.dumps(_fake_costdb()))
        rc = lint_main(["--jaxpr", "--entrypoint", "pipeline_zb",
                        "--costdb", str(db_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "static-cost vs CostDB — pipeline_zb" in out
        assert "ppermute[pp]" in out and "calibrated" in out
        # the pp psum traffic exists in the trace but not in the fake DB
        assert "UNCALIBRATED (absent from CostDB)" in out
        assert "no CostDB row" in out

    def test_cli_costdb_json_carries_diff(self, tmp_path, capsys):
        db_path = tmp_path / "costdb.json"
        db_path.write_text(json.dumps(_fake_costdb()))
        rc = lint_main(["--jaxpr", "--entrypoint", "pipeline_zb",
                        "--costdb", str(db_path), "--format", "json"])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0
        diff = report["costdb_diff"]["pipeline_zb"]
        assert {r["key"] for r in diff["rows"]} >= {"ppermute[pp]"}

    def test_cli_rejects_invalid_costdb(self, tmp_path, capsys):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"kind": "serve"}))
        rc = lint_main(["--jaxpr", "--entrypoint", "pipeline_zb",
                        "--costdb", str(bad)])
        assert rc == 2
        assert "not a valid costdb" in capsys.readouterr().err
