"""Fused-optimizer tests.

Coverage model: ``tests/L0/run_optimizers/test_fused_optimizer.py`` (fused vs
torch.optim reference at tight tolerance), ``test_lamb.py`` (vs a Python
reference LAMB), plus the multi-tensor chunk-layout machinery and the amp
multi-tensor kernel tests (``tests/L0/run_amp/test_multi_tensor_*.py``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import optimizers as opt
from apex_tpu.optimizers import multi_tensor as mt


def make_params(seed=0, dtypes=(jnp.float32,)):
    rng = np.random.RandomState(seed)
    return {
        f"layer{i}": {
            "w": jnp.asarray(rng.randn(7, 13), dt),
            "b": jnp.asarray(rng.randn(13), dt),
        }
        for i, dt in enumerate(dtypes * 2)
    }


def make_grads(params, seed=1):
    rng = np.random.RandomState(seed)
    return jax.tree.map(lambda p: jnp.asarray(rng.randn(*p.shape), p.dtype), params)


class TestChunkLayout:
    def test_roundtrip(self):
        params = make_params()
        buf, layout = mt.flatten_to_chunks(params)
        assert buf.shape[1] == mt.DEFAULT_CHUNK
        back = mt.unflatten_from_chunks(buf, layout, like=params)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params, back,
        )

    def test_multi_chunk_tensor(self):
        params = {"big": jnp.arange(3000, dtype=jnp.float32), "small": jnp.ones((3,))}
        buf, layout = mt.flatten_to_chunks(params)
        assert buf.shape[0] == 4  # 3 chunks for big + 1 for small
        np.testing.assert_array_equal(np.asarray(layout.chunk_to_tensor), [0, 0, 0, 1])
        back = mt.unflatten_from_chunks(buf, layout)
        np.testing.assert_array_equal(np.asarray(back["big"]), np.arange(3000))

    def test_per_tensor_sqnorm(self):
        params = {"a": jnp.full((2000,), 2.0), "b": jnp.full((10,), 3.0)}
        buf, layout = mt.flatten_to_chunks(params)
        sq = mt.per_tensor_sqnorm(buf, layout)
        np.testing.assert_allclose(np.asarray(sq), [4.0 * 2000, 9.0 * 10])

    def test_per_tensor_maxnorm(self):
        params = {"a": jnp.asarray([-5.0, 1.0]), "b": jnp.asarray([0.5, -0.1])}
        buf, layout = mt.flatten_to_chunks(params)
        np.testing.assert_allclose(np.asarray(mt.per_tensor_maxnorm(buf, layout)),
                                   [5.0, 0.5])

    def test_mixed_dtype_cast_back(self):
        params = {"h": jnp.ones((4,), jnp.bfloat16), "f": jnp.ones((4,), jnp.float32)}
        buf, layout = mt.flatten_to_chunks(params)
        assert buf.dtype == jnp.float32
        back = mt.unflatten_from_chunks(buf, layout, like=params)
        assert back["h"].dtype == jnp.bfloat16 and back["f"].dtype == jnp.float32


class TestMultiTensorOps:
    def test_scale_detects_inf(self):
        tree = {"a": jnp.asarray([1.0, jnp.inf])}
        scaled, finite = mt.multi_tensor_scale(tree, 0.5)
        assert not bool(finite)
        tree = {"a": jnp.asarray([1.0, 2.0])}
        scaled, finite = mt.multi_tensor_scale(tree, 0.5)
        assert bool(finite)
        np.testing.assert_allclose(np.asarray(scaled["a"]), [0.5, 1.0])

    def test_axpby(self):
        out, finite = mt.multi_tensor_axpby(
            {"a": jnp.asarray([1.0, 2.0])}, {"a": jnp.asarray([10.0, 20.0])}, 2.0, 0.5
        )
        np.testing.assert_allclose(np.asarray(out["a"]), [7.0, 14.0])
        assert bool(finite)

    def test_l2norm(self):
        tree = {"a": jnp.full((4,), 3.0), "b": jnp.full((9,), 1.0)}
        total, per = mt.multi_tensor_l2norm(tree, per_tensor=True)
        np.testing.assert_allclose(float(total), np.sqrt(36 + 9))
        np.testing.assert_allclose(np.asarray(per), [6.0, 3.0])


def run_steps(tx, params, n=5, seed=10):
    state = tx.init(params)
    for i in range(n):
        grads = make_grads(params, seed=seed + i)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    return params


class TestLayoutEquivalence:
    """per_tensor (default — measured faster on TPU, see _fused.py) and
    chunked (the multi_tensor engine / ZeRO substrate) must produce the
    same updates."""

    @pytest.mark.parametrize("maker,kwargs", [
        (opt.fused_adam, dict(weight_decay=0.01)),
        (opt.fused_lamb, dict()),
        (opt.fused_sgd, dict(momentum=0.9)),
        (opt.fused_adagrad, dict()),
        (opt.fused_novograd, dict()),
    ])
    def test_layouts_agree(self, maker, kwargs):
        params = {
            "w": jnp.linspace(-1, 1, 96).reshape(12, 8),
            "b": jnp.linspace(0.5, -0.5, 8),
        }
        grads = jax.tree.map(lambda x: 0.1 * x + 0.01, params)
        results = {}
        for layout in ("per_tensor", "chunked"):
            tx = maker(1e-2, layout=layout, **kwargs)
            p, state = params, tx.init(params)
            for _ in range(3):
                u, state = tx.update(grads, state, p)
                p = optax.apply_updates(p, u)
            results[layout] = p
        for a, e in zip(jax.tree.leaves(results["per_tensor"]),
                        jax.tree.leaves(results["chunked"])):
            np.testing.assert_allclose(a, e, rtol=1e-6, atol=1e-7)


class TestFusedAdam:
    @pytest.mark.parametrize("weight_decay", [0.0, 0.1])
    def test_matches_optax_adamw(self, weight_decay):
        params = make_params()
        ours = run_steps(opt.fused_adam(1e-2, weight_decay=weight_decay), params)
        ref = run_steps(optax.adamw(1e-2, weight_decay=weight_decay), params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6
            ),
            ours, ref,
        )

    def test_l2_mode_matches_optax_adam_on_l2_grads(self):
        # adam_w_mode=False == adam on (g + wd*p)
        params = make_params()
        wd = 0.1
        tx = opt.fused_adam(1e-2, weight_decay=wd, adam_w_mode=False)
        state = tx.init(params)
        ref_tx = optax.adam(1e-2)
        ref_state = ref_tx.init(params)
        ref_params = params
        for i in range(3):
            grads = make_grads(params, seed=20 + i)
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
            l2_grads = jax.tree.map(lambda g, p: g + wd * p, grads, ref_params)
            ref_updates, ref_state = ref_tx.update(l2_grads, ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, ref_updates)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6),
            params, ref_params,
        )

    def test_jit_and_schedule(self):
        params = make_params()
        sched = optax.linear_schedule(1e-2, 1e-3, 10)
        tx = opt.fused_adam(sched)
        state = tx.init(params)
        step = jax.jit(tx.update)
        grads = make_grads(params)
        updates, state = step(grads, state, params)
        assert int(state.count) == 1

    def test_schedule_zero_based_like_optax(self):
        # first step evaluates sched(0), matching optax convention
        sched = lambda c: jnp.where(c == 0, 1.0, 0.0)  # noqa: E731
        params = {"w": jnp.zeros((2,))}
        grads = {"w": jnp.ones((2,))}
        ours = opt.fused_sgd(sched)
        ref = optax.sgd(sched)
        u_ours, _ = ours.update(grads, ours.init(params), params)
        u_ref, _ = ref.update(grads, ref.init(params), params)
        np.testing.assert_allclose(np.asarray(u_ours["w"]), np.asarray(u_ref["w"]))


class TestFusedSGD:
    def test_matches_optax_sgd_momentum(self):
        params = make_params()
        ours = run_steps(opt.fused_sgd(0.1, momentum=0.9), params)
        ref = run_steps(optax.sgd(0.1, momentum=0.9), params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6),
            ours, ref,
        )

    def test_nesterov(self):
        params = make_params()
        ours = run_steps(opt.fused_sgd(0.1, momentum=0.9, nesterov=True), params)
        ref = run_steps(optax.sgd(0.1, momentum=0.9, nesterov=True), params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6),
            ours, ref,
        )

    def test_nesterov_validation(self):
        with pytest.raises(ValueError):
            opt.fused_sgd(0.1, nesterov=True)

    def test_fused_unscale(self):
        params = {"w": jnp.ones((4,))}
        grads = {"w": jnp.full((4,), 128.0)}
        tx = opt.fused_sgd(1.0, grad_scale=128.0)
        updates, _ = tx.update(grads, tx.init(params), params)
        np.testing.assert_allclose(np.asarray(updates["w"]), -1.0)


def reference_lamb_step(params, grads, m, v, step, lr, b1, b2, eps, wd, max_gn):
    """Pure-numpy LAMB following multi_tensor_lamb.cu (test oracle, like the
    reference's test_lamb.py RefLAMB)."""
    flat = np.concatenate([np.asarray(g).ravel() for g in jax.tree.leaves(grads)])
    gnorm = np.linalg.norm(flat)
    clip = gnorm / max_gn if gnorm > max_gn else 1.0
    new_params, new_m, new_v = {}, {}, {}
    for k in params:
        g = np.asarray(grads[k]) / clip
        p = np.asarray(params[k])
        m_t = b1 * m[k] + (1 - b1) * g
        v_t = b2 * v[k] + (1 - b2) * g * g
        m_hat = m_t / (1 - b1 ** step)
        v_hat = v_t / (1 - b2 ** step)
        update = m_hat / (np.sqrt(v_hat) + eps) + wd * p
        p_norm = np.linalg.norm(p)
        u_norm = np.linalg.norm(update)
        ratio = lr * (p_norm / u_norm) if (p_norm > 0 and u_norm > 0) else lr
        new_params[k] = p - ratio * update
        new_m[k], new_v[k] = m_t, v_t
    return new_params, new_m, new_v


class TestFusedLAMB:
    def test_matches_reference_lamb(self):
        rng = np.random.RandomState(3)
        params = {"w": jnp.asarray(rng.randn(11, 5), jnp.float32),
                  "b": jnp.asarray(rng.randn(5), jnp.float32)}
        lr, b1, b2, eps, wd, mgn = 0.01, 0.9, 0.999, 1e-6, 0.01, 1.0
        tx = opt.fused_lamb(lr, b1, b2, eps, weight_decay=wd, max_grad_norm=mgn)
        state = tx.init(params)
        ref_p = {k: np.asarray(v) for k, v in params.items()}
        ref_m = {k: np.zeros_like(v) for k, v in ref_p.items()}
        ref_v = {k: np.zeros_like(v) for k, v in ref_p.items()}
        for i in range(4):
            grads = make_grads(params, seed=30 + i)
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
            ref_p, ref_m, ref_v = reference_lamb_step(
                ref_p, grads, ref_m, ref_v, i + 1, lr, b1, b2, eps, wd, mgn)
        for k in ref_p:
            np.testing.assert_allclose(np.asarray(params[k]), ref_p[k], atol=1e-5)

    def test_no_decay_no_nvlamb_plain_adam_ratio(self):
        # wd=0, use_nvlamb=False → ratio == lr (lamb.cu:255-262)
        params = {"w": jnp.ones((4,), jnp.float32)}
        grads = {"w": jnp.full((4,), 0.5)}
        tx = opt.fused_lamb(0.1, weight_decay=0.0, max_grad_norm=1e9)
        updates, _ = tx.update(grads, tx.init(params), params)
        # first step: m_hat = g, v_hat = g^2 → update = 1/(1+eps)*sign
        np.testing.assert_allclose(np.asarray(updates["w"]), -0.1, rtol=1e-4)


class TestFusedNovoGrad:
    def test_first_step_init_norm(self):
        params = {"w": jnp.asarray([3.0, 4.0])}  # ||g||=5
        grads = {"w": jnp.asarray([3.0, 4.0])}
        tx = opt.fused_novograd(0.1, b1=0.0, grad_averaging=False, weight_decay=0.0)
        updates, state = tx.update(grads, tx.init(params), params)
        # v init to ||g||=5 (norm, not square: reference stores the norm,
        # fused_novograd.py:160-177) → denom=5+eps; update = g/5 → -0.1*g/5
        np.testing.assert_allclose(np.asarray(updates["w"]), [-0.06, -0.08], rtol=1e-5)
        np.testing.assert_allclose(float(jax.tree.leaves(state.scalars["v"])[0]), 5.0, rtol=1e-5)

    def test_inf_norm(self):
        params = {"w": jnp.asarray([3.0, -4.0])}
        grads = {"w": jnp.asarray([3.0, -4.0])}
        tx = opt.fused_novograd(0.1, b1=0.0, grad_averaging=False, norm_type=0)
        _, state = tx.update(grads, tx.init(params), params)
        np.testing.assert_allclose(float(jax.tree.leaves(state.scalars["v"])[0]), 4.0, rtol=1e-5)

    def test_ema_after_first_step(self):
        params = {"w": jnp.asarray([1.0])}
        tx = opt.fused_novograd(0.1, b2=0.5)
        state = tx.init(params)
        _, state = tx.update({"w": jnp.asarray([2.0])}, state, params)  # v=||g||=2
        _, state = tx.update({"w": jnp.asarray([4.0])}, state, params)  # v=0.5*2+0.5*4
        np.testing.assert_allclose(float(jax.tree.leaves(state.scalars["v"])[0]), 3.0, rtol=1e-5)


class TestFusedAdagrad:
    def test_matches_manual(self):
        params = {"w": jnp.asarray([1.0, 2.0])}
        grads = {"w": jnp.asarray([0.5, 0.5])}
        tx = opt.fused_adagrad(0.1, eps=0.0)
        updates, _ = tx.update(grads, tx.init(params), params)
        np.testing.assert_allclose(np.asarray(updates["w"]), -0.1, rtol=1e-6)


class TestMixedPrecisionLamb:
    def test_bf16_params_fp32_master(self):
        params = {"w": jnp.ones((8,), jnp.bfloat16)}
        tx = opt.fused_mixed_precision_lamb(1e-3, weight_decay=0.01)
        state = tx.init(params)
        assert state.master.dtype == jnp.float32
        grads = {"w": jnp.full((8,), 0.1, jnp.bfloat16)}
        updates, state = tx.update(grads, state, params)
        assert updates["w"].dtype == jnp.bfloat16
        new_params = optax.apply_updates(params, updates)
        # model lands exactly on cast(master)
        master_tree = mt.unflatten_from_chunks(state.master, state.layout, like=params)
        np.testing.assert_array_equal(np.asarray(new_params["w"]),
                                      np.asarray(master_tree["w"]))

    def test_master_advances_below_bf16_resolution(self):
        params = {"w": jnp.full((4,), 256.0, jnp.bfloat16)}
        tx = opt.fused_mixed_precision_lamb(1e-5, weight_decay=0.0, max_grad_norm=1e9)
        state = tx.init(params)
        grads = {"w": jnp.full((4,), 1e-3, jnp.bfloat16)}
        _, state = tx.update(grads, state, params)
        assert float(state.master[0, 0]) != 256.0  # master moved
