"""Tensor-parallel serving + disaggregated handoff tests (ISSUE 17).

The contracts under test:

* the eager validation door (:func:`apex_tpu.serving.tp.validate_tp`):
  every divisibility and knob check fails at CONSTRUCTION with the knob
  named — tp over the device count, ``kv_heads % tp``, ``vocab % tp``,
  ``num_slots``/``prefill_chunk`` ring chunking, the GLOBAL
  ``num_blocks`` sizing, the unsupported sampled tails;
* tp greedy parity: the tp∈{2,4} :class:`~apex_tpu.serving.
  ServingEngine` serves the scripted admit/evict/readmit churn schedule
  TOKEN-IDENTICAL to the tp=1 engine, with every jit cache pinned at 1
  and the free list exactly restored — and the same through spec
  rounds, the int8 pool, and a mid-flight weight hot-swap;
* :class:`~apex_tpu.inference.DecodeEngine` under tp: plain and
  speculative greedy generation bitwise vs tp=1;
* the disaggregated prefill→decode handoff (:mod:`apex_tpu.serving.
  disagg`): streamed block digests match the SOURCE pool's rows, the
  decode role's output is token-identical to the monolithic engine,
  corruption/format drift is loud, and the ``handoff`` lifecycle event
  carries ONE trace id across both roles;
* the ``tp_serve`` monitor record: CLOSED schema (junk key fails),
  nan-in-OK fails, reason-less SKIP fails, the ``tools/
  validate_metrics.py --tp-serve`` forced dispatch, the report line,
  and the ``tools/bench_history.py`` throughput + transfer-latency
  series.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.inference import DecodeEngine
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.plan.parallel_plan import ParallelPlan, PlanError
from apex_tpu.serving import (
    Request,
    ServeTelemetry,
    ServingEngine,
    export_handoff,
    ingest_handoff,
    prefill_requests,
    read_handoff,
    write_handoff,
)
from apex_tpu.serving.disagg import block_digest
from apex_tpu.serving.tp import validate_tp
from apex_tpu.spec import NGramDrafter

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import bench_history  # noqa: E402
import validate_metrics  # noqa: E402

K = jr.PRNGKey(13)

#: every dimension divisible by the tp values under test (the module
#: fixture in test_serving.py uses vocab 97 — prime on purpose there,
#: useless here)
_CFG = dict(vocab_size=96, max_seq_len=128, hidden_size=32,
            num_layers=2, num_heads=4, num_kv_heads=4,
            attention_impl="flash", remat=False, dropout=0.0)


@pytest.fixture(scope="module")
def tiny_tp():
    model = GPTModel(GPTConfig(**_CFG))
    return model, model.init(K)


def _reqs(n=6, seed=3, max_prompt=30, max_new=12):
    rng = np.random.default_rng(seed)
    return [Request(
        rid=i,
        prompt=np.asarray(
            rng.integers(0, 96, int(rng.integers(1, max_prompt))),
            np.int32),
        max_new_tokens=int(rng.integers(1, max_new)))
        for i in range(n)]


def _engine(model, tp=1, **over):
    kw = dict(num_slots=4, block_size=8, prefill_chunk=16,
              max_seq_len=64, num_blocks=21)
    kw.update(over)
    return ServingEngine(model, plan=ParallelPlan(tp=tp) if tp > 1
                         else None, **kw)


def _toks(done):
    return {r.rid: list(r.tokens) for r in done}


class TestValidateTP:
    """The single eager door: every illegal knob fails at construction
    with the knob NAMED (ParallelPlan.validate message style), never as
    an XLA shape error three dispatches in."""

    def _cfg(self, **over):
        return GPTModel(GPTConfig(**{**_CFG, **over})).config

    def test_non_tensor_axes_rejected(self):
        with pytest.raises(PlanError, match="dp=2 with tp=2"):
            validate_tp(ParallelPlan(dp=2, tp=2), self._cfg(),
                        engine="ServingEngine")

    def test_device_count_named(self):
        with pytest.raises(PlanError, match="one device per shard"):
            validate_tp(ParallelPlan(tp=2), self._cfg(),
                        engine="ServingEngine", devices=[object()])

    def test_kv_heads_divisibility_named(self):
        with pytest.raises(PlanError, match="kv_heads % tp == 0"):
            validate_tp(ParallelPlan(tp=4),
                        self._cfg(num_kv_heads=2, num_heads=4),
                        engine="ServingEngine")

    def test_vocab_divisibility_named(self):
        with pytest.raises(PlanError, match="vocab_size % tp == 0"):
            validate_tp(ParallelPlan(tp=4), self._cfg(vocab_size=98),
                        engine="ServingEngine")

    def test_num_slots_ring_chunking_named(self, tiny_tp):
        model, _ = tiny_tp
        with pytest.raises(PlanError, match="num_slots % tp == 0"):
            _engine(model, tp=2, num_slots=3)

    def test_prefill_chunk_ring_chunking_named(self):
        with pytest.raises(PlanError, match="prefill_chunk % tp == 0"):
            validate_tp(ParallelPlan(tp=4), self._cfg(),
                        engine="ServingEngine", prefill_chunk=6)

    def test_num_blocks_is_global_not_per_shard(self):
        """The pool-sizing check speaks in GLOBAL blocks — the sharded
        pool keeps one logical free list, num_blocks is never ×tp."""
        with pytest.raises(PlanError, match="GLOBAL"):
            validate_tp(ParallelPlan(tp=2), self._cfg(),
                        engine="ServingEngine", num_blocks=4,
                        max_blocks_per_slot=8)

    def test_sampled_tail_filters_rejected(self, tiny_tp):
        model, _ = tiny_tp
        with pytest.raises(PlanError, match="top_k"):
            _engine(model, tp=2, temperature=0.7, top_k=3)

    def test_decode_engine_sampled_rejected(self, tiny_tp):
        model, _ = tiny_tp
        with pytest.raises(ValueError, match="greedy"):
            DecodeEngine(model, temperature=0.7,
                         plan=ParallelPlan(tp=2))

    def test_spec_with_temperature_rejected_eagerly(self, tiny_tp):
        """serve(draft=...) under tp composes only the greedy verify
        tail — a sampled spec serve fails BEFORE any dispatch."""
        model, params = tiny_tp
        eng = _engine(model, tp=2, temperature=0.0)
        eng.temperature = 0.7  # past the constructor on purpose
        with pytest.raises(ValueError, match="plan.tp"):
            eng.serve(params, _reqs(1), key=K,
                      draft=NGramDrafter(k=2))


class TestTPServingParity:
    """The tentpole witness: tp shards serve the SAME tokens as tp=1
    across the full churn schedule, zero-recompile, leak-free."""

    @pytest.mark.parametrize("tp", [2, 4])
    def test_churn_schedule_bitwise_vs_tp1(self, tiny_tp, tp):
        model, params = tiny_tp
        reqs = _reqs(7)
        base = _toks(_engine(model).serve(params, _reqs(7)))
        eng = _engine(model, tp=tp)
        sched = eng.make_scheduler()
        done = eng.serve(params, reqs, scheduler=sched)
        assert _toks(done) == base
        assert eng.prefill_chunk._cache_size() == 1, "prefill re-traced"
        assert eng.decode_step._cache_size() == 1, "decode re-traced"
        # free list exactly restored: the only live blocks are the
        # prefix cache's warm residents; reclaiming them recovers the
        # fresh pool block-for-block
        alloc = sched.allocator
        alloc.check_accounting()
        assert alloc.leaked == 0
        assert alloc.num_live == alloc.num_resident
        sched.prefix_cache.clear()
        assert alloc.num_live == 0
        assert alloc.num_free == eng.num_blocks - 1

    def test_spec_rounds_bitwise_vs_plain(self, tiny_tp):
        """Speculative serving under tp: greedy output token-identical
        to the plain tp engine AND to tp=1, spec cache pinned at 1."""
        model, params = tiny_tp
        base = _toks(_engine(model).serve(params, _reqs(5, seed=9)))
        eng = _engine(model, tp=2)
        done = eng.serve(params, _reqs(5, seed=9),
                         draft=NGramDrafter(k=2))
        assert _toks(done) == base
        assert eng.spec_step._cache_size() == 1
        assert eng.decode_step._cache_size() <= 1  # spec replaces it
        assert eng.last_stats.spec_rounds > 0  # rounds actually ran

    def test_int8_pool_bitwise_vs_tp1_int8(self, tiny_tp):
        """The quantized pool shards the same way: pmax-composed amax
        scales make the int8 rows bitwise those of the unsharded pool,
        so tokens match the tp=1 int8 engine exactly."""
        model, params = tiny_tp
        base = _toks(_engine(model, kv_dtype="int8").serve(
            params, _reqs(5, seed=4)))
        eng = _engine(model, tp=2, kv_dtype="int8")
        done = eng.serve(params, _reqs(5, seed=4))
        assert _toks(done) == base
        assert eng.decode_step._cache_size() == 1

    def test_hot_swap_under_tp(self, tiny_tp):
        """Weight hot-swap composes with tp: equal-weights swap is
        token-identical with caches pinned (the swapped tree re-shards
        through the same committed layout), and different weights
        actually serve."""
        model, params = tiny_tp
        reqs = lambda: [Request(rid=0, prompt=np.zeros(4, np.int32),  # noqa: E731
                                max_new_tokens=12)]
        base = _toks(_engine(model, tp=2).serve(params, reqs()))
        eng = _engine(model, tp=2)
        clone = jax.tree.map(lambda x: jnp.array(x), params)
        eng.request_swap(clone, at_step=4, source="test-ckpt")
        done = eng.serve(params, reqs())
        assert _toks(done) == base
        assert eng.last_stats.swaps == 1
        assert eng.decode_step._cache_size() == 1
        eng2 = _engine(model, tp=2)
        eng2.request_swap(jax.tree.map(lambda x: x + 0.5, params),
                          at_step=4)
        jolted = eng2.serve(params, reqs())
        assert _toks(jolted) != base  # the new weights really serve
        assert eng2.decode_step._cache_size() == 1


class TestDecodeEngineTP:
    """The fixed-batch engine under tp: generate() bitwise vs tp=1,
    plain and speculative, every jitted body compiled once."""

    @pytest.mark.parametrize("tp", [2, 4])
    def test_generate_bitwise_vs_tp1(self, tiny_tp, tp):
        model, params = tiny_tp
        prompts = np.asarray(
            jr.randint(jr.fold_in(K, 2), (2, 9), 0, 96), np.int32)
        want = np.asarray(
            DecodeEngine(model).generate(params, jnp.asarray(prompts),
                                         10))
        eng = DecodeEngine(model, plan=ParallelPlan(tp=tp))
        got = np.asarray(eng.generate(params, jnp.asarray(prompts), 10))
        np.testing.assert_array_equal(got, want)
        assert eng.prefill._cache_size() == 1
        assert eng.decode_step._cache_size() == 1

    def test_speculative_generate_bitwise(self, tiny_tp):
        model, params = tiny_tp
        prompts = np.asarray(
            jr.randint(jr.fold_in(K, 6), (1, 12), 0, 96), np.int32)
        want = np.asarray(
            DecodeEngine(model).generate(params, jnp.asarray(prompts),
                                         12))
        eng = DecodeEngine(model, plan=ParallelPlan(tp=2))
        got = np.asarray(eng.generate(params, jnp.asarray(prompts), 12,
                                      draft=NGramDrafter(k=2)))
        np.testing.assert_array_equal(got, want)
        assert eng.spec_verify_step._cache_size() == 1


class TestDisaggHandoff:
    """Prefill role → KV stream → decode role: content-addressed block
    transfer riding the PrefixCache keys, digest-verified end to end,
    decode output token-identical to the monolithic engine."""

    def _hand_reqs(self, n=4, seed=7):
        rng = np.random.default_rng(seed)
        return [Request(
            rid=i,
            prompt=np.asarray(rng.integers(0, 96,
                                           int(rng.integers(18, 50))),
                              np.int32),
            max_new_tokens=int(rng.integers(3, 9)))
            for i in range(n)]

    @pytest.mark.parametrize("tp", [1, 2])
    def test_roundtrip_token_identical(self, tiny_tp, tmp_path, tp):
        model, params = tiny_tp
        B = 8
        mono = _toks(_engine(model, tp=tp).serve(params,
                                                 self._hand_reqs()))
        # prefill role: one token each (its TTFT), warm pool + cache
        ep = _engine(model, tp=tp)
        sp = ep.make_scheduler()
        pre = ep.serve(params, prefill_requests(self._hand_reqs()),
                       scheduler=sp)
        assert all(len(r.tokens) == 1 for r in pre)
        handoffs = [export_handoff(ep.last_pool, sp, r, block_size=B)
                    for r in pre]
        for h, r in zip(handoffs, pre):
            assert len(h.blocks) == len(r.prompt) // B
        d = str(tmp_path / "handoff")
        nbytes = write_handoff(d, handoffs)
        assert nbytes == sum(h.nbytes for h in handoffs) > 0
        streamed = read_handoff(d)
        # the streamed digests ARE the source pool's: recompute each
        # block's digest from the PREFILL pool rows the cache chain
        # names and compare to what crossed the wire
        cache = sp.prefix_cache
        for h, s in zip(handoffs, streamed):
            chain = cache.match(h.prompt, count=False)
            for e, blk in zip(chain, s.blocks):
                src = {name: np.asarray(ep.last_pool[name][:, e.block_id])
                       for name in ep.last_pool}
                assert block_digest(src) == blk.digest
                for name in src:
                    np.testing.assert_array_equal(blk.arrays[name], src[name])
        # decode role: ingest into a FRESH engine's pool + cache
        ed = _engine(model, tp=tp)
        sd = ed.make_scheduler()
        pool, stats = ingest_handoff(ed.init_pool(), sd, streamed)
        assert stats.skipped == 0
        assert stats.blocks == stats.digests_verified \
            == sum(len(h.blocks) for h in streamed)
        done = ed.serve(params, self._hand_reqs(), scheduler=sd,
                        pool=pool)
        assert _toks(done) == mono
        # admission really hit the streamed chain (prefill collapsed
        # to at most the one block holding the final prompt token —
        # admission always keeps >=1 token to produce the first logit)
        for h, r in zip(streamed, sorted(done, key=lambda r: r.rid)):
            assert r.prefix_hit_blocks \
                == min(len(h.blocks), (len(r.prompt) - 1) // B)
        assert ed.prefill_chunk._cache_size() == 1
        assert ed.decode_step._cache_size() == 1

    def test_corrupted_payload_is_loud(self, tiny_tp, tmp_path):
        model, params = tiny_tp
        ep = _engine(model)
        sp = ep.make_scheduler()
        pre = ep.serve(params, prefill_requests(self._hand_reqs(2)),
                       scheduler=sp)
        handoffs = [export_handoff(ep.last_pool, sp, r, block_size=8)
                    for r in pre]
        d = str(tmp_path / "h")
        write_handoff(d, handoffs)
        victim = next(f for f in sorted(os.listdir(d))
                      if f.endswith(".bin"))
        raw = bytearray(open(os.path.join(d, victim), "rb").read())
        raw[0] ^= 0xFF
        open(os.path.join(d, victim), "wb").write(bytes(raw))
        with pytest.raises(ValueError, match="digest mismatch"):
            read_handoff(d)

    def test_manifest_framing_is_validated(self, tiny_tp, tmp_path):
        with pytest.raises(FileNotFoundError, match="manifest"):
            read_handoff(str(tmp_path / "nowhere"))
        d = tmp_path / "junk"
        d.mkdir()
        (d / "manifest.json").write_text(json.dumps(
            {"format": "something.else", "version": 1, "requests": []}))
        with pytest.raises(ValueError, match="format"):
            read_handoff(str(d))
        (d / "manifest.json").write_text(json.dumps(
            {"format": "apex_tpu.kv_handoff", "version": 99,
             "requests": []}))
        with pytest.raises(ValueError, match="version"):
            read_handoff(str(d))

    def test_export_before_prefill_is_loud(self, tiny_tp):
        model, _ = tiny_tp
        eng = _engine(model)
        sched = eng.make_scheduler()
        with pytest.raises(ValueError, match="no cached blocks"):
            export_handoff(eng.init_pool(), sched, self._hand_reqs(1)[0],
                           block_size=8)

    def test_handoff_event_one_trace_id_across_roles(self, tiny_tp,
                                                     tmp_path):
        """The lifecycle witness: the export leg (prefill engine) and
        the ingest leg (decode engine) emit ``handoff`` events carrying
        the SAME request trace id — the id travels inside the payload."""
        model, params = tiny_tp
        ep = _engine(model)
        sp = ep.make_scheduler()
        tel_p = ServeTelemetry(slots=4, collect_events=True)
        pre = ep.serve(params, prefill_requests(self._hand_reqs(2)),
                       scheduler=sp, telemetry=tel_p)
        handoffs = [export_handoff(ep.last_pool, sp, r, block_size=8,
                                   telemetry=tel_p)
                    for r in pre]
        assert all(h.trace_id for h in handoffs)  # minted at submit
        d = str(tmp_path / "h")
        write_handoff(d, handoffs)
        ed = _engine(model)
        sd = ed.make_scheduler()
        tel_d = ServeTelemetry(slots=4, collect_events=True)
        ingest_handoff(ed.init_pool(), sd, read_handoff(d),
                       telemetry=tel_d)
        exp = {e["rid"]: e for e in tel_p.events
               if e.get("phase") == "handoff"}
        ing = {e["rid"]: e for e in tel_d.events
               if e.get("phase") == "handoff"}
        assert set(exp) == set(ing) == {r.rid for r in pre}
        for rid in exp:
            assert exp[rid]["handoff_role"] == "export"
            assert ing[rid]["handoff_role"] == "ingest"
            assert exp[rid]["trace_id"] == ing[rid]["trace_id"]
            assert exp[rid]["blocks"] == ing[rid]["blocks"] > 0
            assert exp[rid]["transfer_bytes"] \
                == ing[rid]["transfer_bytes"] > 0
        assert tel_p.handoffs == tel_d.handoffs == 2
        assert tel_d.handoff_transfer_ms > 0

    def test_handoff_event_validates_through_schema(self):
        rec = {"schema": monitor.SCHEMA_VERSION, "kind": "serve_event",
               "rid": 0, "phase": "handoff", "at_s": 0.1,
               "handoff_role": "ingest", "blocks": 3,
               "transfer_bytes": 4096, "dur_ms": 1.25,
               "trace_id": "req-abc"}
        assert monitor.validate(rec) == []
        rec["handoff_role"] = "sideways"
        assert monitor.validate(rec)

    def test_bad_role_is_loud(self):
        tel = ServeTelemetry(slots=2)
        with pytest.raises(ValueError, match="export|ingest"):
            tel.on_handoff(0, "sideways", 1, 10, 0.0)


class TestTPServeRecord:
    """The ``tp_serve`` artifact: closed schema, honesty rule, forced
    CLI dispatch, report line, bench-history series — the same drift
    battery every status record in the repo carries."""

    def _ok_fields(self):
        return dict(tp=2, tokens_per_s=120.0,
                    baseline_tokens_per_s=180.0,
                    ttft_ms_prefill_role=12.5, ttft_ms_monolithic=14.0,
                    handoff_blocks=11, handoff_transfer_bytes=180224,
                    handoff_transfer_ms=3.5, digests_verified=11,
                    collective_ppermute_calls=24,
                    collective_ppermute_bytes=55296,
                    decode_steps=16, collective_bytes_per_step=6144.0,
                    greedy_parity=True, handoff_parity=True,
                    jit_cache_ok=True, kv_dtype="float", requests=8,
                    num_blocks=33, pool_mb_per_shard=0.25,
                    pool_mb_total=0.5)

    def test_ok_record_validates(self):
        rec = monitor.MetricsRegistry().emit_tp_serve(
            "OK", **self._ok_fields())
        assert monitor.validate(rec) == []

    def test_junk_key_fails_closed_schema(self):
        rec = monitor.MetricsRegistry().emit_tp_serve(
            "OK", **self._ok_fields())
        rec["junk_key"] = 1
        assert any("unexpected key" in e for e in monitor.validate(rec))

    def test_nan_in_ok_fails(self):
        with pytest.raises(ValueError, match="non-finite"):
            monitor.MetricsRegistry().emit_tp_serve(
                "OK", tokens_per_s=float("nan"))
        rec = monitor.MetricsRegistry().emit_tp_serve(
            "OK", **self._ok_fields())
        rec["handoff_transfer_ms"] = float("nan")
        assert any("non-finite" in e for e in monitor.validate(rec))

    def test_reasonless_skip_fails(self):
        with pytest.raises(ValueError, match="reason"):
            monitor.MetricsRegistry().emit_tp_serve("SKIP")
        rec = monitor.MetricsRegistry().emit_tp_serve(
            "SKIP", reason="cpu smoke")
        del rec["reason"]
        assert any("reason" in e for e in monitor.validate(rec))

    def test_validator_cli_forced_and_content_dispatch(self, tmp_path):
        rec = monitor.MetricsRegistry().emit_tp_serve(
            "OK", **self._ok_fields())
        good = tmp_path / "tp_serve.json"
        good.write_text(json.dumps(rec))
        assert validate_metrics.main(["--tp-serve", str(good)]) == 0
        assert validate_metrics.main([str(good)]) == 0  # content
        # a file that lost its kind fails AS a tp_serve artifact
        bad = tmp_path / "lost.json"
        bad.write_text(json.dumps(
            {k: v for k, v in rec.items() if k != "kind"}))
        assert validate_metrics.main(["--tp-serve", str(bad)]) == 1
        junk = tmp_path / "junk.json"
        junk.write_text(json.dumps(dict(rec, junk=1)))
        assert validate_metrics.main(["--tp-serve", str(junk)]) == 1

    def test_report_renders_tp_serve_line(self):
        rec = monitor.MetricsRegistry().emit_tp_serve(
            "OK", **self._ok_fields())
        summary = monitor.aggregate([rec])
        assert summary["tp_serve"]["tp"] == 2
        from apex_tpu.monitor.report import render
        text = render(summary)
        assert "tp-serve" in text and "tp=2" in text
        assert "handoff" in text
        skip = monitor.aggregate([monitor.MetricsRegistry().emit_tp_serve(
            "SKIP", reason="cpu smoke")])
        assert "SKIP(cpu smoke)" in render(skip)

    def test_timeline_folds_handoff_legs(self):
        """A merged two-role stream: the row carries both legs' roles,
        block count, and summed bytes; the rendered table shows them."""
        from apex_tpu.monitor.report import (format_serve_timeline,
                                             serve_timeline)
        mk = lambda role: {"kind": "serve_event", "rid": 0,  # noqa: E731
                           "phase": "handoff", "at_s": 0.1,
                           "handoff_role": role, "blocks": 3,
                           "transfer_bytes": 2048}
        tl = serve_timeline([
            {"kind": "serve_event", "rid": 0, "phase": "submit",
             "at_s": 0.0, "prompt_len": 24}, mk("export"), mk("ingest")])
        (row,) = tl["requests"]
        assert row["handoff_roles"] == ["export", "ingest"]
        assert row["handoff_blocks"] == 3
        assert row["handoff_bytes"] == 4096
        assert "handoff export+ingest" in format_serve_timeline(tl)

    def test_bench_history_series(self):
        """An OK tp_serve record gates BOTH series: tokens/s
        (higher-is-better) and handoff_transfer_ms (lower-is-better,
        percent drift); a SKIP record claims nothing."""
        ok = monitor.MetricsRegistry().emit_tp_serve(
            "OK", **self._ok_fields())
        rows = dict((m, v) for m, v, _ in bench_history.extract_all(ok))
        assert rows["tp_serve_tokens_per_s"] == 120.0
        assert rows["tp_serve_handoff_transfer_ms"] == 3.5
        assert ("tp_serve_handoff_transfer_ms"
                in bench_history._LOWER_IS_BETTER_PCT)
        skip = monitor.MetricsRegistry().emit_tp_serve(
            "SKIP", reason="cpu smoke")
        assert bench_history.extract_all(skip) == []
        # pre-tier history: an OK record MISSING the new transfer series
        # (an old-style artifact) still gates its throughput — the new
        # series skips individually, never the whole gate
        old = {k: v for k, v in ok.items()
               if k != "handoff_transfer_ms"}
        names = [m for m, _, _ in bench_history.extract_all(old)]
        assert "tp_serve_tokens_per_s" in names
        assert "tp_serve_handoff_transfer_ms" not in names
