"""Request-scoped tracing (ISSUE 16 tentpole + satellites).

Contracts under test:

* the unified clock: one ``clock_sync`` record opens every enabled
  stream, every record carries a ``t_ns`` stamp from THE monotonic
  base (``monitor.trace.monotonic_ns``);
* trace-id continuity under churn: a preempted request keeps ONE
  ``trace_id`` across submit → evict → re-admit → resume → finish, and
  an all-rejected spec round (the rewind path) keeps it too;
* TTFT/latency attribution: the component partition of each finished
  request sums to its measured e2e latency within tolerance, on a REAL
  mixed run (spec rounds + a forced preemption);
* the anomaly flight recorder: a bounded ring fed by the registry's
  emit path (sink or no sink), dumping exactly the last N raw events
  on a scripted anomaly, deduping by reason, chaining signal handlers;
* Chrome trace-event export: the mixed run exports one named track per
  request whose queue/prefill/decode/spec/preempt slices all carry the
  request's trace id — with both jitted serving steps' cache size still
  pinned at 1 (zero-recompile holds with tracing ON);
* the CLIs: ``python -m apex_tpu.monitor trace``, ``report
  --attribution`` (incl. the explicit SKIP(reason) line on a bare
  stream), and ``tools/validate_metrics.py --trace`` family dispatch
  (closed schemas: junk keys and nan-in-OK fail).
"""

import gzip
import json
import os
import signal
import sys

import jax.random as jr
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.monitor import report as monitor_report
from apex_tpu.monitor import trace as trace_lib
from apex_tpu.serving import Request, ServeTelemetry, ServingEngine
from apex_tpu.spec import NGramDrafter

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import validate_metrics  # noqa: E402

K = jr.PRNGKey(16)


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig(vocab_size=97, max_seq_len=128, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    attention_impl="flash", remat=False, dropout=0.0)
    model = GPTModel(cfg)
    return model, model.init(K)


def _churn_serve(tmp_path, tiny, *, draft=None, name="ev", **tel_kw):
    """A real mixed serve with monitoring on and the pool sized to
    FORCE at least one preemption (3 requests x (12 prompt + 14 new)
    through 7 blocks of 8 rows). Returns (records, tel, eng, sched,
    done)."""
    model, params = tiny
    path = tmp_path / f"{name}.jsonl"
    monitor.enable(str(path))
    try:
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64,
                            num_blocks=7)
        reqs = [Request(rid=i, prompt=np.asarray(
                    jr.randint(jr.fold_in(K, i), (12,), 0, 97), np.int32),
                        max_new_tokens=14)
                for i in range(3)]
        tel = ServeTelemetry(slots=2, window_s=0.0, **tel_kw)
        sched = eng.make_scheduler()
        done = eng.serve(params, reqs, scheduler=sched, telemetry=tel,
                         draft=draft)
        assert len(done) == 3
        assert sched.preemptions >= 1, \
            "the churn recipe must force a preemption"
    finally:
        monitor.disable()
    lines = path.read_text().splitlines()
    assert monitor.validate_jsonl(lines) == []
    return [json.loads(ln) for ln in lines], tel, eng, sched, done


class TestUnifiedClock:
    def test_clock_sync_opens_stream_and_t_ns_everywhere(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        monitor.enable(str(path))
        try:
            monitor.emit_event("probe", i=1)
        finally:
            monitor.disable()
        records = [json.loads(ln)
                   for ln in path.read_text().splitlines()]
        first = records[0]
        assert first["kind"] == "clock_sync"
        assert isinstance(first["mono_ns"], int)
        assert isinstance(first["wall_s"], float)
        assert first["pid"] == os.getpid()
        assert first["clock"] == "perf_counter_ns"
        # every record is stamped on THE monotonic base
        assert all(isinstance(r.get("t_ns"), int) for r in records)
        assert monitor.validate_jsonl(
            path.read_text().splitlines()) == []

    def test_ambient_trace_id_and_explicit_wins(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        monitor.enable(str(path))
        try:
            monitor.emit_event("outside")
            with trace_lib.trace_context(trace_lib.new_trace_id("t")):
                monitor.emit_event("ambient")
                monitor.emit_event("explicit", trace_id="mine-1")
        finally:
            monitor.disable()
        by = {r["name"]: r for r in
              (json.loads(ln) for ln in path.read_text().splitlines())
              if r.get("kind") == "event"}
        assert "trace_id" not in by["outside"]
        assert by["ambient"]["trace_id"].startswith("t-")
        assert by["explicit"]["trace_id"] == "mine-1"


class TestTraceIdContinuity:
    def test_one_trace_id_survives_preemption(self, tmp_path, tiny):
        """The tentpole witness: an evicted-and-recomputed request's
        whole lifecycle — submit, evict, resumed re-admit, finish —
        carries exactly one trace id (the Request object holds it
        across the re-queue)."""
        records, tel, eng, sched, done = _churn_serve(tmp_path, tiny)
        ev_by_rid = {}
        for r in records:
            if r.get("kind") == "serve_event" and r.get("rid", -1) >= 0:
                ev_by_rid.setdefault(r["rid"], []).append(r)
        assert set(ev_by_rid) == {0, 1, 2}
        tids = {}
        for rid, evs in ev_by_rid.items():
            ids = {e.get("trace_id") for e in evs}
            assert len(ids) == 1 and None not in ids, \
                f"rid {rid} trace ids fractured: {ids}"
            tids[rid] = ids.pop()
        # distinct per request, and mirrored on the Request object
        assert len(set(tids.values())) == 3
        for r in done:
            assert r.trace_id == tids[r.rid]
        evicted = [rid for rid, evs in ev_by_rid.items()
                   if any(e["phase"] == "evict" for e in evs)]
        assert evicted, "no request went through the evict path"
        for rid in evicted:
            phases = [e["phase"] for e in ev_by_rid[rid]]
            assert "evict" in phases and phases.count("admit") >= 2
            assert any(e["phase"] == "admit" and e.get("resumed")
                       for e in ev_by_rid[rid])

    def test_all_rejected_spec_round_keeps_trace_id(self):
        """The spec-rewind path, driven directly: an all-rejected round
        emits a spec event on the SAME trace id (and attributes its
        wall time to spec_rewind_ms, not spec_ms)."""
        tel = ServeTelemetry(slots=1, window_s=0.0, collect_events=True)
        req = Request(rid=5, prompt=np.zeros(4, np.int32),
                      max_new_tokens=6)
        tel.on_submit(req, 0.0)
        tel.on_admit(req, 0, 0.010)
        tel.on_first_token(req, 0, 1, 0, 0.020)
        tel.on_spec_round(5, 0, 0, 4, 1, 0.030, dur_ms=5.0)  # rewind
        tel.on_spec_round(5, 0, 2, 4, 2, 0.050, dur_ms=5.0)
        req.tokens.extend([1] * 6)
        tel.on_finish(req, 0, 1, 3, 0.060)
        evs = [e for e in tel.events if e.get("rid") == 5]
        ids = {e.get("trace_id") for e in evs}
        assert len(ids) == 1 and None not in ids
        fields = trace_lib.serve_attribution(tel.events)
        row = fields["per_request"][0]
        assert row["spec_rewind_ms"] == pytest.approx(5.0, abs=0.01)
        assert row["spec_ms"] == pytest.approx(5.0, abs=0.01)
        assert row["trace_id"] == ids.pop()


class TestAttribution:
    def test_components_sum_to_e2e_on_mixed_run(self, tmp_path, tiny):
        """The acceptance bound: on a real spec + forced-preemption
        sweep, every finished request's component partition sums to its
        measured e2e latency within max(1%, 0.5 ms)."""
        records, tel, eng, sched, done = _churn_serve(
            tmp_path, tiny, draft=NGramDrafter(k=4), name="mixed",
            collect_events=True)
        fields = trace_lib.serve_attribution(tel.events)
        assert fields["requests"] == 3
        assert fields["unattributed"] == 0
        for row in fields["per_request"]:
            tol = max(0.01 * row["e2e_ms"], 0.5)
            assert abs(row["components_ms"] - row["e2e_ms"]) <= tol, row
        assert sum(r["evictions"] for r in fields["per_request"]) \
            == sched.preemptions
        assert sum(r["spec_rounds"] for r in fields["per_request"]) > 0
        assert fields["components"]["recompute_ms"] > 0
        # the JSONL stream and the in-memory ledger agree
        from_stream = trace_lib.serve_attribution(records)
        assert from_stream["requests"] == 3
        assert from_stream["e2e_ms_total"] == \
            pytest.approx(fields["e2e_ms_total"], rel=1e-6)

    def test_empty_stream_reports_skipped_not_zero(self):
        fields = trace_lib.serve_attribution([])
        assert fields["requests"] == 0
        assert fields["max_residual_pct"] == \
            ("skipped", "no finished requests in stream")

    def test_emitted_record_validates(self, tmp_path, tiny):
        records, tel, *_ = _churn_serve(tmp_path, tiny, name="attr",
                                        collect_events=True)
        fields = trace_lib.serve_attribution(tel.events)
        rec = monitor.MetricsRegistry().emit_serve_attribution(
            "SKIP", reason="cpu test run", **fields)
        assert monitor.validate(rec) == []


class TestFlightRecorder:
    def test_dump_holds_exactly_last_n(self, tmp_path):
        fr = trace_lib.enable_flight_recorder(capacity=4,
                                              out_dir=str(tmp_path))
        try:
            monitor.enable(str(tmp_path / "ev.jsonl"))
            try:
                for i in range(10):
                    monitor.emit_event("tick", i=i)
            finally:
                monitor.disable()
            path = trace_lib.flight_dump("scripted_anomaly")
            assert path is not None
            dump = json.load(open(path))
            assert dump["kind"] == "flight_recorder_dump"
            assert dump["num_events"] == 4
            assert [e["i"] for e in dump["events"]] == [6, 7, 8, 9]
            assert monitor.validate(dump) == []
            # once=True (the anomaly layer's mode) dedups by reason
            assert trace_lib.flight_dump("scripted_anomaly") is None
            assert trace_lib.flight_dump("other_anomaly") is not None
        finally:
            trace_lib.disable_flight_recorder()

    def test_ring_accumulates_without_a_sink(self, tmp_path):
        """The degraded-mode contract: the ring fills from the emit
        path even when the registry has NO JSONL sink attached."""
        fr = trace_lib.enable_flight_recorder(capacity=8,
                                              out_dir=str(tmp_path))
        try:
            reg = monitor.MetricsRegistry()  # sink-less
            for i in range(3):
                reg.emit("event", name="quiet", i=i)
            assert len(fr) == 3
            path = fr.dump("no_sink")
            assert json.load(open(path))["num_events"] == 3
        finally:
            trace_lib.disable_flight_recorder()

    def test_signal_handler_dumps_then_chains(self, tmp_path):
        """SIGUSR1 stand-in for SIGTERM: the installed handler writes
        the dump and the PREVIOUS handler still runs."""
        fr = trace_lib.enable_flight_recorder(capacity=4,
                                              out_dir=str(tmp_path))
        fr.record({"kind": "event", "name": "pre-crash"})
        seen = []
        prev = signal.signal(signal.SIGUSR1,
                             lambda s, f: seen.append(s))
        try:
            trace_lib.install_signal_handler(signal.SIGUSR1)
            os.kill(os.getpid(), signal.SIGUSR1)
            assert seen == [signal.SIGUSR1]
            assert len(fr.dumps) == 1
            dump = json.load(open(fr.dumps[0]))
            assert dump["reason"] == f"signal:{int(signal.SIGUSR1)}"
            assert dump["events"][0]["name"] == "pre-crash"
        finally:
            signal.signal(signal.SIGUSR1, prev)
            trace_lib.disable_flight_recorder()


class TestChromeExport:
    def test_mixed_serve_exports_one_named_track_per_request(
            self, tmp_path, tiny):
        """THE acceptance run: an off-TPU mixed sweep (chunked prefill
        + decode + spec rounds + a forced preemption) exports to
        trace-event JSON where every request is one named track whose
        slices share its trace id — and the zero-recompile contract
        held with tracing on."""
        records, tel, eng, sched, done = _churn_serve(
            tmp_path, tiny, draft=NGramDrafter(k=4), name="chrome",
            collect_events=True)
        assert eng.prefill_chunk._cache_size() == 1
        assert eng.spec_step._cache_size() == 1
        doc = trace_lib.chrome_trace(records)
        assert doc["displayTimeUnit"] == "ms"
        assert doc["otherData"]["clock_sync"]["kind"] == "clock_sync"
        json.loads(json.dumps(doc))  # loadable trace-event JSON
        names = {e["args"]["name"]: e["pid"] for e in doc["traceEvents"]
                 if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
        tids = {r.rid: r.trace_id for r in done}
        saw_preempt = saw_spec = False
        for rid in (0, 1, 2):
            label = f"req {rid} [{tids[rid]}]"
            assert label in names, f"missing request track {label}"
            slices = [e for e in doc["traceEvents"]
                      if e.get("ph") == "X" and e["pid"] == names[label]]
            assert slices, f"request track {label} has no slices"
            phases = {e["name"] for e in slices}
            assert {"queue", "prefill", "decode"} <= phases \
                   or "recompute" in phases
            assert all(e["args"].get("trace_id") == tids[rid]
                       for e in slices)
            assert all(e["dur"] > 0 for e in slices)
            saw_preempt = saw_preempt or "preempt" in phases
            saw_spec = saw_spec or "spec" in phases
        assert saw_preempt, "the forced preemption left no slice"
        assert saw_spec, "spec rounds left no slices"

    def test_write_gz_round_trips(self, tmp_path, tiny):
        records, *_ = _churn_serve(tmp_path, tiny, name="gz")
        out = str(tmp_path / "t.json.gz")
        trace_lib.write_chrome_trace(out, records)
        with gzip.open(out, "rt") as fh:
            assert json.load(fh)["traceEvents"]


class TestCLI:
    def test_trace_subcommand_writes_loadable_json(self, tmp_path, tiny,
                                                   capsys):
        records, *_ = _churn_serve(tmp_path, tiny, name="cli")
        stream = tmp_path / "cli.jsonl"
        out = str(tmp_path / "out.trace.json")
        assert monitor_report.main(["trace", str(stream),
                                    "--out", out]) == 0
        assert "request tracks" in capsys.readouterr().out
        assert json.load(open(out))["traceEvents"]

    def test_trace_subcommand_refuses_empty_export(self, tmp_path,
                                                   capsys):
        bare = tmp_path / "bare.jsonl"
        bare.write_text(json.dumps({"schema": 1, "kind": "meta"}) + "\n")
        assert monitor_report.main(["trace", str(bare)]) == 2
        assert "SKIP(" in capsys.readouterr().out

    def test_report_attribution_renders(self, tmp_path, tiny, capsys):
        _churn_serve(tmp_path, tiny, draft=NGramDrafter(k=4),
                     name="rep")
        stream = str(tmp_path / "rep.jsonl")
        assert monitor_report.main(["report", stream,
                                    "--attribution"]) == 0
        out = capsys.readouterr().out
        assert "serve attribution: 3 requests" in out
        assert "evict x" in out

    def test_report_attribution_skip_line_on_bare_stream(
            self, tmp_path, capsys):
        """Satellite 2: a requested-but-absent section prints an
        explicit SKIP(reason) line, never a silent empty section."""
        bare = tmp_path / "bare.jsonl"
        bare.write_text(json.dumps({"schema": 1, "kind": "meta"}) + "\n")
        assert monitor_report.main(["report", str(bare),
                                    "--attribution"]) == 0
        out = capsys.readouterr().out
        assert "serve attribution: SKIP(" in out

    def test_report_attribution_json_carries_record(self, tmp_path,
                                                    tiny, capsys):
        _churn_serve(tmp_path, tiny, name="repj")
        stream = str(tmp_path / "repj.jsonl")
        assert monitor_report.main(["report", stream, "--attribution",
                                    "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        rec = summary["serve_attribution"]
        assert rec["kind"] == "serve_attribution"
        assert rec["requests"] == 3
        assert monitor.validate(rec) == []


class TestValidatorTrace:
    def _attr_record(self, tmp_path, tiny):
        records, tel, *_ = _churn_serve(tmp_path, tiny, name="vm",
                                        collect_events=True)
        fields = trace_lib.serve_attribution(tel.events,
                                             per_request=False)
        return monitor.MetricsRegistry().emit_serve_attribution(
            "OK", **fields), records

    def test_trace_family_dispatch(self, tmp_path, tiny):
        rec, records = self._attr_record(tmp_path, tiny)
        good = tmp_path / "attr.json"
        good.write_text(json.dumps(rec))
        assert validate_metrics.main(["--trace", str(good)]) == 0
        # the serve stream contains a clock_sync → family satisfied
        assert validate_metrics.main(
            ["--trace", str(tmp_path / "vm.jsonl")]) == 0
        # a stream with NO tracing-family record fails the dispatch
        other = tmp_path / "other.jsonl"
        other.write_text(json.dumps({"schema": 1, "kind": "meta"}) + "\n")
        assert validate_metrics.main(["--trace", str(other)]) == 1
        # single object of the wrong kind fails as the wrong artifact
        wrong = tmp_path / "wrong.json"
        wrong.write_text(json.dumps({"schema": 1, "kind": "serve",
                                     "status": "SKIP", "reason": "x"}))
        assert validate_metrics.main(["--trace", str(wrong)]) == 1

    def test_closed_schema_rejects_junk_key(self, tmp_path, tiny):
        rec, _ = self._attr_record(tmp_path, tiny)
        bad = dict(rec)
        bad["junk_key"] = 1
        path = tmp_path / "junk.json"
        path.write_text(json.dumps(bad))
        assert validate_metrics.main(["--trace", str(path)]) == 1

    def test_nan_in_ok_record_fails_honesty(self, tmp_path, tiny):
        rec, _ = self._attr_record(tmp_path, tiny)
        assert rec["status"] == "OK"
        bad = dict(rec)
        bad["e2e_ms_total"] = float("nan")
        path = tmp_path / "nan.json"
        path.write_text(json.dumps(bad))  # json allows NaN; the gate not
        assert validate_metrics.main(["--trace", str(path)]) == 1

    def test_flight_dump_passes_trace_dispatch(self, tmp_path):
        fr = trace_lib.enable_flight_recorder(capacity=3,
                                              out_dir=str(tmp_path))
        try:
            monitor.enable(str(tmp_path / "fd.jsonl"))
            try:
                for i in range(5):
                    monitor.emit_event("tick", i=i)
            finally:
                monitor.disable()
            path = fr.dump("scripted")
        finally:
            trace_lib.disable_flight_recorder()
        assert validate_metrics.main(["--trace", path]) == 0
