"""The flagship GPTModel through the 3D-parallel machinery.

VERDICT r2 item 1: ``build_model``-style stage partitioning must drive the
*shipped* model — flash attention, grouped-query kv, vocab-parallel CE,
sequence-parallel grad sync, remat policies — through the pipeline
schedules, parity-checked against the single-device ``loss_fn`` (the
reference's ``build_model`` + schedule integration,
``pipeline_parallel/schedules/common.py:29-148``).
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.models.gpt import shard_params_for_tp
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.pipeline_parallel import GPTPipeline, build_model

K = jr.PRNGKey(77)

SMALL = dict(vocab_size=64, max_seq_len=32, hidden_size=32, num_layers=4,
             num_heads=4, dropout=0.0, remat=True)


def _tokens(key, n, b, s, vocab):
    toks = jr.randint(key, (n, b, s), 0, vocab)
    tgts = jr.randint(jr.fold_in(key, 1), (n, b, s), 0, vocab)
    return toks, tgts


def _ref_loss_and_grads(cfg_kwargs, params, toks, tgts, loss_mask=None):
    """Single-device oracle: same params, microbatches concatenated."""
    m = GPTModel(GPTConfig(**cfg_kwargs, tp_size=1))
    M, b, s = toks.shape

    def loss(p):
        lm = None if loss_mask is None else loss_mask.reshape(M * b, s)
        return m.loss_fn(p, toks.reshape(M * b, s), tgts.reshape(M * b, s),
                         loss_mask=lm)

    return jax.value_and_grad(loss)(params)


class TestGPTPipelinePartition:
    def test_partition_roundtrip(self):
        cfg = GPTConfig(**SMALL)
        model = GPTModel(cfg)
        params = model.init(K)
        for v in (1, 2):
            pipe = GPTPipeline(model, pp=2, virtual_chunks=v)
            rt = pipe.unpartition(pipe.partition(params))
            for a, e in zip(jax.tree.leaves(rt), jax.tree.leaves(params)):
                np.testing.assert_array_equal(a, e)

    def test_virtual_stage_layer_assignment(self):
        """Interleaved: device r chunk c must hold global layers of virtual
        stage c*pp + r (parallel_state.py:135-145)."""
        cfg = GPTConfig(**{**SMALL, "num_layers": 8})
        model = GPTModel(cfg)
        params = model.init(K)
        pipe = GPTPipeline(model, pp=2, virtual_chunks=2)
        part = pipe.partition(params)
        lnw = part["stages"]["ln1_w"]  # (v, pp, Lc, hid)
        ref = params["layers"]["ln1_w"]  # (L, hid)
        for c in range(2):
            for r in range(2):
                k = c * 2 + r
                np.testing.assert_array_equal(
                    lnw[c, r], ref[k * 2:(k + 1) * 2])

    def test_rejects_bad_shapes(self):
        model = GPTModel(GPTConfig(**{**SMALL, "num_layers": 6}))
        with pytest.raises(ValueError, match="divisible"):
            GPTPipeline(model, pp=4)
        with pytest.raises(ValueError, match=">= 2"):
            GPTPipeline(model, pp=1)

    def test_dropout_requires_key(self):
        model = GPTModel(GPTConfig(**{**SMALL, "dropout": 0.1}))
        pipe = GPTPipeline(model, pp=2)
        part = pipe.partition(model.init(K))
        toks, tgts = _tokens(jr.fold_in(K, 30), 2, 2, 16, 64)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        with pytest.raises(ValueError, match="key"):
            mesh_lib.shard_map(
                lambda p, a, b: pipe.loss_and_grads(
                    dict(p, stages=jax.tree.map(lambda x: x[0],
                                                p["stages"])), a, b)[0],
                mesh=mesh,
                in_specs=(pipe.param_specs(part), P(), P()),
                out_specs=P(),
            )(part, toks, tgts)

    def test_dropout_trains_with_distinct_masks(self):
        """Dropout through the pipeline: per-(tick, stage, layer) keys.
        Loss is finite, differs from the dropout-free run, and two
        different keys give different losses (masks actually vary)."""
        model = GPTModel(GPTConfig(**{**SMALL, "dropout": 0.3}))
        pipe = GPTPipeline(model, pp=2)
        params = model.init(jr.fold_in(K, 31))
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        toks, tgts = _tokens(jr.fold_in(K, 32), 4, 2, 16, 64)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)

        def run(p, toks, tgts, key):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, g = pipe.loss_and_grads(lp, toks, tgts, key=key)
            return loss, jax.tree.map(
                lambda x: jnp.sum(jnp.abs(x)), g["embed"])

        f = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(specs, P(), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P(), part["embed"]))))
        l1, _ = f(part, toks, tgts, jr.PRNGKey(1))
        l2, _ = f(part, toks, tgts, jr.PRNGKey(2))
        assert jnp.isfinite(l1) and jnp.isfinite(l2)
        assert float(l1) != float(l2)  # masks vary with the key

        model0 = GPTModel(GPTConfig(**SMALL))
        l0 = model0.loss_fn(params, toks.reshape(-1, 16),
                            tgts.reshape(-1, 16))
        assert float(l1) != float(l0)  # dropout actually applied

    def test_dropout_interleaved_schedule(self):
        """The v>1 (one-chunk-per-tick) path's tick threading under
        dropout: keys must vary per (tick, chunk) so masks differ across
        keys and the dp-rank fold decorrelates replicas."""
        model = GPTModel(GPTConfig(**{**SMALL, "num_layers": 8,
                                      "dropout": 0.3}))
        pipe = GPTPipeline(model, pp=2, virtual_chunks=2)
        params = model.init(jr.fold_in(K, 33))
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        toks, tgts = _tokens(jr.fold_in(K, 34), 4, 4, 16, 64)  # b=4: dp=4
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)

        def run(p, toks, tgts, key):
            lp = dict(p, stages=jax.tree.map(lambda x: x[:, 0],
                                             p["stages"]))
            loss, _ = pipe.loss_and_grads(lp, toks, tgts, key=key,
                                          dp_axis="dp")
            return loss

        f = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(specs, P(None, "dp"), P(None, "dp"),
                                      P()),
            out_specs=P()))
        l1 = f(part, toks, tgts, jr.PRNGKey(5))
        l2 = f(part, toks, tgts, jr.PRNGKey(6))
        assert jnp.isfinite(l1) and jnp.isfinite(l2)
        assert float(l1) != float(l2)


class TestGPTPipelineParity:
    @pytest.mark.parametrize("attention_impl", ["softmax", "flash"])
    def test_pp2_matches_single_device(self, attention_impl):
        """pp=2 (dp/tp trivial): loss AND grads equal the unpipelined
        model's."""
        cfg_kwargs = dict(SMALL, attention_impl=attention_impl)
        cfg = GPTConfig(**cfg_kwargs)
        model = GPTModel(cfg)
        params = model.init(K)
        M, b, s = 4, 2, 16
        toks, tgts = _tokens(jr.fold_in(K, 2), M, b, s, cfg.vocab_size)

        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        pipe = GPTPipeline(model, pp=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)

        def run(p, toks, tgts):
            local = jax.tree.map(lambda x: x[0], p["stages"])
            lp = {"embed": p["embed"], "stages": local, "head": p["head"]}
            loss, g = pipe.loss_and_grads(lp, toks, tgts)
            g["stages"] = jax.tree.map(lambda x: x[None], g["stages"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(), P()),
                out_specs=(P(), specs),
            ))(part, toks, tgts)

            ref_loss, ref_grads = _ref_loss_and_grads(
                cfg_kwargs, params, toks, tgts)

        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        got = pipe.unpartition(grads)
        for (pa, a), (pe, e) in zip(
                jax.tree_util.tree_leaves_with_path(got),
                jax.tree_util.tree_leaves_with_path(ref_grads)):
            np.testing.assert_allclose(
                a, e, rtol=2e-4, atol=1e-5,
                err_msg=jax.tree_util.keystr(pa))

    def test_pp2_interleaved_matches_single_device(self):
        """v=2 virtual chunks over pp=2 — 4 virtual stages."""
        cfg_kwargs = dict(SMALL, **{"num_layers": 8})
        cfg = GPTConfig(**cfg_kwargs)
        model = GPTModel(cfg)
        params = model.init(jr.fold_in(K, 3))
        M, b, s = 4, 2, 16
        toks, tgts = _tokens(jr.fold_in(K, 4), M, b, s, cfg.vocab_size)

        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        pipe = GPTPipeline(model, pp=2, virtual_chunks=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)

        def run(p, toks, tgts):
            local = jax.tree.map(lambda x: x[:, 0], p["stages"])
            lp = {"embed": p["embed"], "stages": local, "head": p["head"]}
            loss, g = pipe.loss_and_grads(lp, toks, tgts)
            g["stages"] = jax.tree.map(lambda x: x[:, None], g["stages"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(), P()),
                out_specs=(P(), specs),
            ))(part, toks, tgts)
            ref_loss, ref_grads = _ref_loss_and_grads(
                cfg_kwargs, params, toks, tgts)

        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        got = pipe.unpartition(grads)
        for a, e in zip(jax.tree.leaves(got), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=1e-5)

    def test_pp2_tp2_dp2_sp_full_3d(self):
        """The gate's configuration as a test: tp=2 with sequence
        parallelism, pp=2, dp=2, flash attention, loss mask — loss and
        unpartitioned grads match the single-device oracle."""
        cfg_kwargs = dict(SMALL, attention_impl="flash")
        cfg1 = GPTConfig(**cfg_kwargs)
        model1 = GPTModel(cfg1)
        params1 = model1.init(jr.fold_in(K, 5))

        tp, pp, dp = 2, 2, 2
        cfg = GPTConfig(**cfg_kwargs, tp_size=tp, sequence_parallel=True)
        model = GPTModel(cfg)
        mesh = mesh_lib.make_mesh(
            tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp)

        M, b, s = 4, 2, 16  # per-dp-rank batch b
        toks, tgts = _tokens(jr.fold_in(K, 6), M, b * dp, s, cfg1.vocab_size)
        loss_mask = (jr.uniform(jr.fold_in(K, 7), (M, b * dp, s)) > 0.2
                     ).astype(jnp.float32)

        pipe = GPTPipeline(model, pp=pp)
        # tp-shard the replicated init, then partition each shard for pp
        tp_params = shard_params_for_tp(params1, tp, cfg1)
        part = jax.vmap(pipe.partition)(tp_params)
        specs = pipe.param_specs(part, "tp")

        def run(p, toks, tgts, lm):
            lp = jax.tree.map(lambda x: x[0], p)  # strip tp axis
            lp["stages"] = jax.tree.map(lambda x: x[0], lp["stages"])  # pp
            loss, g = pipe.loss_and_grads(
                lp, toks, tgts, loss_mask=lm, dp_axis="dp")
            g["stages"] = jax.tree.map(lambda x: x[None, None], g["stages"])
            g["embed"] = jax.tree.map(lambda x: x[None], g["embed"])
            g["head"] = jax.tree.map(lambda x: x[None], g["head"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, "dp"), P(None, "dp"),
                          P(None, "dp")),
                out_specs=(P(), specs),
            ))(part, toks, tgts, loss_mask)

            # DDP semantics: the dp pmean averages per-rank *masked means*,
            # which differs from one global masked mean when mask counts
            # differ per rank — the oracle averages per-shard losses
            def ref_loss_fn(p):
                per = []
                for r in range(dp):
                    sl = slice(r * b, (r + 1) * b)
                    per.append(GPTModel(cfg1).loss_fn(
                        p, toks[:, sl].reshape(M * b, s),
                        tgts[:, sl].reshape(M * b, s),
                        loss_mask=loss_mask[:, sl].reshape(M * b, s)))
                return jnp.mean(jnp.stack(per))

            ref_loss, ref_grads = jax.value_and_grad(ref_loss_fn)(params1)

        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)

        # spot-check grads that are replicated across tp (LNs, biases,
        # positions): unpartition tp rank 0's tree and compare
        got = jax.vmap(pipe.unpartition)(grads)
        np.testing.assert_allclose(
            got["pos_embedding"][0], ref_grads["pos_embedding"],
            rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            got["lnf_w"][0], ref_grads["lnf_w"], rtol=2e-4, atol=1e-5)
        for name in ("ln1_w", "ln1_b", "ln2_w", "ln2_b"):
            np.testing.assert_allclose(
                got["layers"][name][0], ref_grads["layers"][name],
                rtol=2e-4, atol=2e-5, err_msg=name)
        # vocab-sharded embedding grad: concat tp shards
        emb = jnp.concatenate(list(got["embedding"]["weight"]), axis=0)
        np.testing.assert_allclose(
            emb, ref_grads["embedding"]["weight"], rtol=2e-4, atol=1e-5)
        # column-sharded mlp_up weight: concat along output features
        up = jnp.concatenate(list(got["layers"]["mlp_up"]["weight"]), axis=1)
        np.testing.assert_allclose(
            up, ref_grads["layers"]["mlp_up"]["weight"], rtol=2e-4,
            atol=1e-5)


class TestPipelineCheckpoint:
    def test_pipeline_state_roundtrips_through_model_layout(self, tmp_path):
        """Checkpoint compatibility contract: a pipeline training state
        saves in the PLAIN model layout (via unpartition) and restores
        into any other decomposition — here pp=2 state → disk → pp=2 with
        v=2 chunks, bitwise on every leaf."""
        from apex_tpu.checkpoint import (TrainState, restore_checkpoint,
                                         save_checkpoint)

        cfg = GPTConfig(**{**SMALL, "num_layers": 8})
        model = GPTModel(cfg)
        params = model.init(jr.fold_in(K, 40))
        pipe_a = GPTPipeline(model, pp=2)
        part_a = pipe_a.partition(params)

        state = TrainState(step=jnp.asarray(7),
                           params=pipe_a.unpartition(part_a),
                           opt_state={"nu": jnp.ones((3,))})
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, state)
        restored = restore_checkpoint(path, state)
        assert int(restored.step) == 7

        # re-partition for a DIFFERENT pipeline decomposition
        pipe_b = GPTPipeline(model, pp=2, virtual_chunks=2)
        part_b = pipe_b.partition(restored.params)
        rt = pipe_b.unpartition(part_b)
        for a, e in zip(jax.tree.leaves(rt), jax.tree.leaves(params)):
            np.testing.assert_array_equal(a, e)


class TestBuildModelFrontend:
    def test_from_installed_mesh(self):
        mesh_lib.initialize_model_parallel(
            tensor_model_parallel_size=1, pipeline_model_parallel_size=2,
            virtual_pipeline_model_parallel_size=2,
        )
        model = GPTModel(GPTConfig(**{**SMALL, "num_layers": 8}))
        pipe = build_model(model)
        assert pipe.pp == 2 and pipe.virtual_chunks == 2
        mesh_lib.destroy_model_parallel()


class TestContextParallelFlagship:
    """cp INSIDE the flagship program (VERDICT r3 next-round #3): ring /
    Ulysses attention as the GPTModel's attention over a cp-sharded
    sequence, composed with pp (and tp) in ONE shard_map."""

    CPKW = dict(vocab_size=64, max_seq_len=64, hidden_size=32, num_layers=2,
                num_heads=4, attention_impl="flash")

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_gpt_cp_matches_full_sequence(self, impl):
        """Model level: GPT over a cp=2-sharded sequence == the same GPT on
        the full sequence (loss + grads)."""
        from apex_tpu.ops.attention import zigzag_shard

        cfg1 = GPTConfig(**self.CPKW)
        cfg = GPTConfig(**self.CPKW, cp_axis="cp", cp_impl=impl)
        m1, m = GPTModel(cfg1), GPTModel(cfg)
        params = m1.init(jr.fold_in(K, 40))
        b, s = 2, 64
        toks = jr.randint(jr.fold_in(K, 41), (b, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 42), (b, s), 0, 64)
        mesh = mesh_lib.make_mesh(context_parallel_size=2)

        if impl == "ring":  # causal ring requires the zigzag layout
            toks_sh = zigzag_shard(toks, 2, 1)
            tgts_sh = zigzag_shard(tgts, 2, 1)
        else:
            toks_sh, tgts_sh = toks, tgts

        def run(p, t, g):
            loss, grads = jax.value_and_grad(m.loss_fn)(p, t, g)
            loss = jax.lax.pmean(loss, "cp")
            grads = jax.tree.map(lambda x: jax.lax.pmean(x, "cp"), grads)
            return loss, grads

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(jax.tree.map(lambda _: P(), params),
                          P(None, "cp"), P(None, "cp")),
                out_specs=(P(), jax.tree.map(lambda _: P(), params)),
            ))(params, toks_sh, tgts_sh)
            ref_loss, ref_g = jax.value_and_grad(m1.loss_fn)(
                params, toks, tgts)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_g)):
            np.testing.assert_allclose(a, e, rtol=5e-4, atol=2e-5)

    def test_pp2_cp2_dp2_pipeline(self):
        """dp x pp x cp through GPTPipeline in one mesh: ring attention's
        ppermute rotations run INSIDE the scanned pipeline ticks."""
        from apex_tpu.ops.attention import zigzag_shard

        cfg1 = GPTConfig(**self.CPKW)
        cfg = GPTConfig(**self.CPKW, cp_axis="cp")
        m = GPTModel(cfg)
        params = GPTModel(cfg1).init(jr.fold_in(K, 43))
        pipe = GPTPipeline(m, pp=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2,
                                  context_parallel_size=2)  # dp=2
        M, b, s = 2, 2, 64
        dp = 2
        toks = jr.randint(jr.fold_in(K, 44), (M, b * dp, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 45), (M, b * dp, s), 0, 64)
        toks_sh = zigzag_shard(toks, 2, 2)
        tgts_sh = zigzag_shard(tgts, 2, 2)

        def run(p, t, g):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, grads = pipe.loss_and_grads(lp, t, g,
                                              dp_axis=("dp", "cp"))
            grads["stages"] = jax.tree.map(lambda x: x[None],
                                           grads["stages"])
            return loss, grads

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, "dp", "cp"), P(None, "dp", "cp")),
                out_specs=(P(), specs),
            ))(part, toks_sh, tgts_sh)

            def ref_fn(p):
                per = [GPTModel(cfg1).loss_fn(
                    p, toks[i, r * b:(r + 1) * b],
                    tgts[i, r * b:(r + 1) * b])
                    for r in range(dp) for i in range(M)]
                return jnp.mean(jnp.stack(per))

            ref_loss, ref_g = jax.value_and_grad(ref_fn)(params)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        got = pipe.unpartition(grads)
        for a, e in zip(jax.tree.leaves(got), jax.tree.leaves(ref_g)):
            np.testing.assert_allclose(a, e, rtol=5e-4, atol=2e-5)

    def test_pp2_cp2_tp2_one_mesh(self):
        """pp x cp x tp in one mesh: ring attention beside Megatron-SP tp
        inside the pipeline stages — the full model-parallel composition."""
        from apex_tpu.ops.attention import zigzag_shard

        cfg1 = GPTConfig(**self.CPKW)
        cfg = GPTConfig(**self.CPKW, tp_size=2, sequence_parallel=True,
                        cp_axis="cp")
        m = GPTModel(cfg)
        params1 = GPTModel(cfg1).init(jr.fold_in(K, 46))
        pipe = GPTPipeline(m, pp=2)
        part = jax.vmap(pipe.partition)(shard_params_for_tp(params1, 2,
                                                            cfg1))
        specs = pipe.param_specs(part, "tp")
        mesh = mesh_lib.make_mesh(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2,
            context_parallel_size=2)  # dp=1
        M, b, s = 2, 2, 64
        toks = jr.randint(jr.fold_in(K, 47), (M, b, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 48), (M, b, s), 0, 64)
        toks_sh = zigzag_shard(toks, 2, 2)
        tgts_sh = zigzag_shard(tgts, 2, 2)

        def run(p, t, g):
            lp = jax.tree.map(lambda x: x[0], p)
            lp["stages"] = jax.tree.map(lambda x: x[0], lp["stages"])
            loss, grads = pipe.loss_and_grads(lp, t, g,
                                              dp_axis=("dp", "cp"))
            grads["stages"] = jax.tree.map(lambda x: x[None, None],
                                           grads["stages"])
            grads["embed"] = jax.tree.map(lambda x: x[None],
                                          grads["embed"])
            grads["head"] = jax.tree.map(lambda x: x[None], grads["head"])
            return loss, grads

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, "dp", "cp"), P(None, "dp", "cp")),
                out_specs=(P(), specs),
            ))(part, toks_sh, tgts_sh)

            def ref_fn(p):
                per = [GPTModel(cfg1).loss_fn(p, toks[i], tgts[i])
                       for i in range(M)]
                return jnp.mean(jnp.stack(per))

            ref_loss, ref_g = jax.value_and_grad(ref_fn)(params1)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        got = jax.vmap(pipe.unpartition)(grads)
        np.testing.assert_allclose(got["pos_embedding"][0],
                                   ref_g["pos_embedding"],
                                   rtol=5e-4, atol=2e-5)
        for name in ("ln1_w", "ln2_w"):
            np.testing.assert_allclose(
                got["layers"][name][0], ref_g["layers"][name],
                rtol=5e-4, atol=2e-5, err_msg=name)

    def test_cp_config_validation(self):
        with pytest.raises(ValueError, match="flash"):
            GPTConfig(**{**self.CPKW, "attention_impl": "softmax"},
                      cp_axis="cp")
        with pytest.raises(ValueError, match="cp_impl"):
            GPTConfig(**self.CPKW, cp_axis="cp", cp_impl="tree")
        # dropout composes with cp since r4 (per-(rank, step, piece) seed
        # folds in ring; rank-folded seeds in ulysses)
        GPTConfig(**self.CPKW, cp_axis="cp", dropout=0.1)

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_cp_with_dropout_trains_keyed(self, impl):
        """dropout > 0 on the cp flagship: finite keyed loss, determinism
        per key, variation across keys — through pp x cp in one mesh."""
        from apex_tpu.ops.attention import zigzag_shard

        cfg = GPTConfig(**self.CPKW, cp_axis="cp", cp_impl=impl,
                        dropout=0.2)
        m = GPTModel(cfg)
        params = GPTModel(GPTConfig(**self.CPKW)).init(jr.fold_in(K, 50))
        pipe = GPTPipeline(m, pp=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2,
                                  context_parallel_size=2)
        M, b, s, dp = 2, 2, 64, 2
        toks = jr.randint(jr.fold_in(K, 51), (M, b * dp, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 52), (M, b * dp, s), 0, 64)
        if impl == "ring":
            toks = zigzag_shard(toks, 2, 2)
            tgts = zigzag_shard(tgts, 2, 2)

        def run(p, t, g, key):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, grads = pipe.loss_and_grads(
                lp, t, g, dp_axis=("dp", "cp"), key=key)
            grads["stages"] = jax.tree.map(lambda x: x[None],
                                           grads["stages"])
            return loss, grads

        f = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(specs, P(None, "dp", "cp"), P(None, "dp", "cp"),
                      P()),
            out_specs=(P(), specs),
        ))
        l1, g1 = f(part, toks, tgts, jr.PRNGKey(1))
        l1b, _ = f(part, toks, tgts, jr.PRNGKey(1))
        l2, _ = f(part, toks, tgts, jr.PRNGKey(2))
        assert jnp.isfinite(l1)
        assert float(l1) == float(l1b)
        assert float(l1) != float(l2)
        for leaf in jax.tree.leaves(g1):
            assert bool(jnp.all(jnp.isfinite(leaf)))


class TestScheduleFeatureMatrix:
    """schedule ∈ {1F1B (v=1), interleaved (v=2)} × feature ∈ {cp-ring,
    ep, dropout, ZeRO}, each cell oracle-checked at toy shape (VERDICT r4
    next #6). The named risk is ring-in-interleaved: the cp ring's
    rotating KV state composed with the v-chunk rotation is exactly the
    index arithmetic that breaks silently — here it must reproduce the
    serial model's loss and gradients.

    Strip/restore of the stage leaves differs per schedule ((pp, ...) at
    v=1, (v, pp, ...) at v=2) — one helper pair so every cell exercises
    the same plumbing."""

    @staticmethod
    def _strip(p, v):
        sel = (lambda x: x[:, 0]) if v > 1 else (lambda x: x[0])
        return dict(p, stages=jax.tree.map(sel, p["stages"]))

    @staticmethod
    def _restore_stages(g, v):
        exp = (lambda x: x[:, None]) if v > 1 else (lambda x: x[None])
        g["stages"] = jax.tree.map(exp, g["stages"])
        return g

    @pytest.mark.parametrize("v", [1, 2])
    def test_cp_ring(self, v):
        """Ring attention inside the (interleaved) pipeline: dp x pp x cp
        with zigzag-sharded sequence; loss + grads == serial oracle."""
        from apex_tpu.ops.attention import zigzag_shard

        kw = dict(vocab_size=64, max_seq_len=64, hidden_size=32,
                  num_layers=2 * v, num_heads=4, attention_impl="flash")
        cfg1 = GPTConfig(**kw)
        cfg = GPTConfig(**kw, cp_axis="cp")
        m = GPTModel(cfg)
        params = GPTModel(cfg1).init(jr.fold_in(K, 150 + v))
        pipe = GPTPipeline(m, pp=2, virtual_chunks=v)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2,
                                  context_parallel_size=2)  # dp=2
        M, b, s, dp = 2, 2, 64, 2
        toks = jr.randint(jr.fold_in(K, 152), (M, b * dp, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 153), (M, b * dp, s), 0, 64)
        toks_sh = zigzag_shard(toks, 2, 2)
        tgts_sh = zigzag_shard(tgts, 2, 2)

        def run(p, t, g):
            loss, grads = pipe.loss_and_grads(
                self._strip(p, v), t, g, dp_axis=("dp", "cp"))
            return loss, self._restore_stages(grads, v)

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, "dp", "cp"), P(None, "dp", "cp")),
                out_specs=(P(), specs),
            ))(part, toks_sh, tgts_sh)

            def ref_fn(p):
                per = [GPTModel(cfg1).loss_fn(
                    p, toks[i, r * b:(r + 1) * b],
                    tgts[i, r * b:(r + 1) * b])
                    for r in range(dp) for i in range(M)]
                return jnp.mean(jnp.stack(per))

            ref_loss, ref_g = jax.value_and_grad(ref_fn)(params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        got = pipe.unpartition(grads)
        for (pa, a), (_, e) in zip(
                jax.tree_util.tree_leaves_with_path(got),
                jax.tree_util.tree_leaves_with_path(ref_g)):
            np.testing.assert_allclose(a, e, rtol=5e-4, atol=2e-5,
                                       err_msg=jax.tree_util.keystr(pa))

    @pytest.mark.parametrize("v", [1, 2])
    def test_ep_moe(self, v):
        """MoE expert banks over ep inside the (interleaved) pipeline."""
        kw = dict(vocab_size=64, max_seq_len=16, hidden_size=32,
                  num_layers=2 * v, num_heads=4, attention_impl="flash",
                  moe_num_experts=4, moe_top_k=2, moe_capacity_factor=2.0)
        cfg1 = GPTConfig(**kw)
        cfg = GPTConfig(**kw, ep_axis="ep")
        m = GPTModel(cfg)
        params = GPTModel(cfg1).init(jr.fold_in(K, 160 + v))
        pipe = GPTPipeline(m, pp=2, virtual_chunks=v)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2,
                                  expert_parallel_size=2)  # dp=2
        M, b, s, shards = 2, 2, 16, 4
        toks = jr.randint(jr.fold_in(K, 162), (M, b * shards, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 163), (M, b * shards, s), 0, 64)

        def run(p, t, g):
            loss, grads = pipe.loss_and_grads(
                self._strip(p, v), t, g, dp_axis="dp")
            return loss, self._restore_stages(grads, v)

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, ("dp", "ep")),
                          P(None, ("dp", "ep"))),
                out_specs=(P(), specs),
            ))(part, toks, tgts)

            def ref_fn(p):
                per = [GPTModel(cfg1).loss_fn(
                    p, toks[i, r * b:(r + 1) * b],
                    tgts[i, r * b:(r + 1) * b])
                    for r in range(shards) for i in range(M)]
                return jnp.mean(jnp.stack(per))

            ref_loss, ref_g = jax.value_and_grad(ref_fn)(params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        got = pipe.unpartition(grads)
        np.testing.assert_allclose(got["layers"]["moe"]["w1"],
                                   ref_g["layers"]["moe"]["w1"],
                                   rtol=5e-4, atol=2e-5)
        np.testing.assert_allclose(got["layers"]["moe"]["router"],
                                   ref_g["layers"]["moe"]["router"],
                                   rtol=5e-4, atol=2e-5)

    @pytest.mark.parametrize("v", [1, 2])
    def test_dropout(self, v):
        """Dropout masks under both schedules: finite, deterministic per
        key, varying across keys (no oracle exists — masks are
        schedule-keyed by design)."""
        kw = dict(SMALL, num_layers=4 * v, dropout=0.3)
        model = GPTModel(GPTConfig(**kw))
        pipe = GPTPipeline(model, pp=2, virtual_chunks=v)
        params = model.init(jr.fold_in(K, 170 + v))
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        toks, tgts = _tokens(jr.fold_in(K, 172), 4, 4, 16, 64)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)

        def run(p, t, g, key):
            loss, _ = pipe.loss_and_grads(self._strip(p, v), t, g,
                                          key=key, dp_axis="dp")
            return loss

        f = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(specs, P(None, "dp"), P(None, "dp"), P()),
            out_specs=P()))
        l1 = f(part, toks, tgts, jr.PRNGKey(8))
        l1b = f(part, toks, tgts, jr.PRNGKey(8))
        l2 = f(part, toks, tgts, jr.PRNGKey(9))
        assert jnp.isfinite(l1) and jnp.isfinite(l2)
        assert float(l1) == float(l1b)  # deterministic per key
        assert float(l1) != float(l2)  # masks vary with the key

    @pytest.mark.parametrize("v", [1, 2])
    def test_zb_schedule(self, v):
        """The zero-bubble split backward through the full GPTPipeline
        (flash attention, vocab-parallel CE, tied embedding, fp32
        main-grad), wired from GPTConfig(pp_schedule='zb'): loss and
        unpartitioned grads == the single-device oracle at both v."""
        kw = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
                  num_layers=2 * v, num_heads=4, attention_impl="flash",
                  remat=True)
        cfg = GPTConfig(**kw, pp_schedule="zb")
        model = GPTModel(cfg)
        params = GPTModel(GPTConfig(**kw)).init(jr.fold_in(K, 190 + v))
        pipe = GPTPipeline(model, pp=2, virtual_chunks=v)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        M, b, s = 4, 2, 16
        toks, tgts = _tokens(jr.fold_in(K, 192), M, b, s, 64)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)

        def run(p, t, g):
            loss, grads = pipe.loss_and_grads(self._strip(p, v), t, g)
            return loss, self._restore_stages(grads, v)

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P()),
                out_specs=(P(), specs)))(part, toks, tgts)
            ref_loss, ref_g = _ref_loss_and_grads(kw, params, toks, tgts)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        got = pipe.unpartition(grads)
        for (pa, a), (_, e) in zip(
                jax.tree_util.tree_leaves_with_path(got),
                jax.tree_util.tree_leaves_with_path(ref_g)):
            np.testing.assert_allclose(a, e, rtol=3e-4, atol=2e-5,
                                       err_msg=jax.tree_util.keystr(pa))

    def test_zb_overlap_p2p(self):
        """zb × overlap_p2p through GPTConfig: the overlapped-hop tick
        structure with the split backward still reproduces the oracle
        (grads AND loss), and the jit cache stays pinned across fresh
        data."""
        kw = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
                  num_layers=2, num_heads=4, attention_impl="flash",
                  remat=True)
        cfg = GPTConfig(**kw, pp_schedule="zb", overlap_p2p=True)
        model = GPTModel(cfg)
        params = GPTModel(GPTConfig(**kw)).init(jr.fold_in(K, 195))
        pipe = GPTPipeline(model, pp=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        M, b, s = 4, 2, 16
        toks, tgts = _tokens(jr.fold_in(K, 196), M, b, s, 64)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)

        def run(p, t, g):
            loss, grads = pipe.loss_and_grads(self._strip(p, 1), t, g)
            return loss, self._restore_stages(grads, 1)

        step = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs)))
        with jax.default_matmul_precision("highest"):
            loss, grads = step(part, toks, tgts)
            ref_loss, ref_g = _ref_loss_and_grads(kw, params, toks, tgts)
            step(part, toks + 1, tgts)  # fresh data, same geometry
            assert step._cache_size() == 1
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        got = pipe.unpartition(grads)
        for a, e in zip(jax.tree.leaves(got), jax.tree.leaves(ref_g)):
            np.testing.assert_allclose(a, e, rtol=3e-4, atol=2e-5)

    def test_pp_schedule_validated_eagerly(self):
        with pytest.raises(ValueError, match="pp_schedule"):
            GPTConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                      num_layers=2, num_heads=4, pp_schedule="zbb")

    @pytest.mark.parametrize("v", [1, 2])
    def test_zero(self, v):
        """dp-sharded optimizer state (ZeRO) updating the pipeline-layout
        params under both schedules: 4-step trajectory == unsharded fused
        Adam on the serial model."""
        import optax

        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.optimizers import fused_adam

        kw = dict(vocab_size=64, max_seq_len=16, hidden_size=32,
                  num_layers=2 * v, num_heads=4, attention_impl="flash")
        cfg1 = GPTConfig(**kw)
        m = GPTModel(cfg1)
        params1 = m.init(jr.fold_in(K, 180 + v))
        pipe = GPTPipeline(m, pp=2, virtual_chunks=v)
        part = pipe.partition(params1)
        specs = pipe.param_specs(part)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)  # dp=4
        opt = distributed_fused_adam(learning_rate=1e-2)
        M, b, s, dp = 2, 2, 16, 4
        batches = [
            (jr.randint(jr.fold_in(K, 182 + 10 * i), (M, b * dp, s), 0, 64),
             jr.randint(jr.fold_in(K, 183 + 10 * i), (M, b * dp, s), 0, 64))
            for i in range(4)]

        st = mesh_lib.shard_map(
            lambda p: opt.init(self._strip(p, v)), mesh=mesh,
            in_specs=(specs,), out_specs=P())(part)

        @jax.jit
        def step(p, st, t, g):
            def run(p, t, g, st):
                lp = self._strip(p, v)
                loss, grads = pipe.loss_and_grads(lp, t, g, dp_axis="dp")
                u, st = opt.update(grads, st, lp)
                newp = optax.apply_updates(lp, u)
                return self._restore_stages(dict(newp), v), st, loss

            return mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, "dp"), P(None, "dp"), P()),
                out_specs=(specs, P(), P()),
            )(p, t, g, st)

        losses = []
        with jax.default_matmul_precision("highest"):
            for t, g in batches:
                part, st, loss = step(part, st, t, g)
                losses.append(float(loss))

            opt1 = fused_adam(learning_rate=1e-2)
            st1 = opt1.init(params1)
            ref = []

            @jax.jit
            def ostep(p, st, toks, tgts):
                def f(p_):
                    per = [m.loss_fn(p_, toks[i, r * b:(r + 1) * b],
                                     tgts[i, r * b:(r + 1) * b])
                           for r in range(dp) for i in range(M)]
                    return jnp.mean(jnp.stack(per))
                loss, g_ = jax.value_and_grad(f)(p)
                u, st = opt1.update(g_, st, p)
                return optax.apply_updates(p, u), st, loss

            p1 = params1
            for t, g in batches:
                p1, st1, loss = ostep(p1, st1, t, g)
                ref.append(float(loss))
        np.testing.assert_allclose(losses, ref, rtol=5e-4, atol=1e-5)
