"""AMP policy-engine + loss-scaler tests.

Coverage model: the reference's ``tests/L0/run_amp`` suite —
``test_basic_casts.py`` (per-level cast behavior), ``test_promotion.py``
(O1 per-op rules), ``test_checkpointing.py`` (scaler state dicts), plus the
dynamic-scaler protocol from ``apex/amp/scaler.py:197-217``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp import lists as amp_lists


def params():
    return {"w": jnp.ones((4, 4), jnp.float32), "b": jnp.zeros((4,), jnp.float32),
            "step": jnp.zeros((), jnp.int32)}


class TestPolicy:
    def test_levels(self):
        assert amp.O0.compute_dtype == jnp.float32
        assert amp.O1.compute_dtype == jnp.bfloat16 and amp.O1.param_dtype == jnp.float32
        assert amp.O2.master_weights and amp.O2.param_dtype == jnp.bfloat16
        assert amp.O3.compute_dtype == jnp.bfloat16 and not amp.O3.keep_norm_f32

    def test_cast_skips_non_float(self):
        p = amp.O2.cast_to_compute(params())
        assert p["w"].dtype == jnp.bfloat16
        assert p["step"].dtype == jnp.int32  # ints untouched

    def test_get_policy_overrides(self):
        p = amp.get_policy("O2", keep_norm_f32=False)
        assert not p.keep_norm_f32
        p16 = amp.get_policy("O3", half_dtype=jnp.float16)
        assert p16.compute_dtype == jnp.float16
        with pytest.raises(ValueError):
            amp.get_policy("O1", master_weights=True)
        with pytest.raises(ValueError):
            amp.get_policy("O5")

    def test_run_casts_output(self):
        out = amp.O2.run(lambda p, x: x @ p["w"], params(), jnp.ones((2, 4)))
        assert out.dtype == jnp.float32  # output cast back

    def test_ambient_policy(self):
        assert amp.current_policy().name == "O0"
        with amp.with_policy(amp.O2):
            assert amp.current_policy().name == "O2"
        assert amp.current_policy().name == "O0"

    def test_op_cast_rules(self):
        assert amp_lists.op_cast_dtype("matmul", amp.O1) == jnp.bfloat16
        assert amp_lists.op_cast_dtype("softmax", amp.O1) == jnp.float32
        # promote: widest input wins
        assert amp_lists.op_cast_dtype("add", amp.O1, jnp.bfloat16, jnp.float32) == jnp.float32
        # non-per-op policy: everything in compute dtype
        assert amp_lists.op_cast_dtype("softmax", amp.O2) == jnp.bfloat16
        with pytest.raises(RuntimeError):
            amp_lists.op_cast_dtype("binary_cross_entropy", amp.O1)


class TestLossScaler:
    def test_static_scale(self):
        s = amp.init_loss_scaler(128.0)
        assert not s.dynamic
        assert float(s.loss_scale) == 128.0
        s2 = amp.update_loss_scaler(s, jnp.asarray(False))
        assert float(s2.loss_scale) == 128.0  # static never moves
        assert int(s2.skipped_steps) == 1  # overflow still counted

    def test_dynamic_backoff_and_growth(self):
        s = amp.init_loss_scaler("dynamic", init_scale=2.0 ** 16, growth_interval=2)
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        assert float(s.loss_scale) == 2.0 ** 15  # halved on overflow
        assert int(s.skipped_steps) == 1
        s = amp.update_loss_scaler(s, jnp.asarray(True))
        s = amp.update_loss_scaler(s, jnp.asarray(True))
        assert float(s.loss_scale) == 2.0 ** 16  # doubled after interval
        assert int(s.growth_tracker) == 0

    def test_bounds(self):
        s = amp.init_loss_scaler("dynamic", init_scale=1.5, min_loss_scale=1.0)
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        assert float(s.loss_scale) == 1.0
        s = dataclasses.replace(s, loss_scale=jnp.asarray(2.0 ** 24, jnp.float32),
                                growth_tracker=jnp.asarray(1999, jnp.int32))
        s = amp.update_loss_scaler(s, jnp.asarray(True))
        assert float(s.loss_scale) == 2.0 ** 24  # clamped at max

    def test_scaled_value_and_grad(self):
        p = {"w": jnp.asarray([2.0, 3.0])}
        loss_fn = lambda p, x: jnp.sum(p["w"] * x)  # noqa: E731
        g = amp.scaled_value_and_grad(loss_fn)
        scaler = amp.init_loss_scaler("dynamic", init_scale=1024.0)
        x = jnp.asarray([1.0, 2.0])
        loss, (grads, finite, new_scaler) = jax.jit(g)(scaler, p, x)
        np.testing.assert_allclose(loss, 8.0)
        np.testing.assert_allclose(grads["w"], [1.0, 2.0])  # unscaled
        assert bool(finite)

    def test_overflow_detection_and_skip(self):
        p = {"w": jnp.asarray([2.0])}
        loss_fn = lambda p, x: jnp.sum(p["w"] * x)  # noqa: E731
        g = amp.scaled_value_and_grad(loss_fn)
        scaler = amp.init_loss_scaler("dynamic", init_scale=2.0 ** 16)
        x = jnp.asarray([jnp.inf])
        _, (grads, finite, new_scaler) = g(scaler, p, x)
        assert not bool(finite)
        assert float(new_scaler.loss_scale) == 2.0 ** 15
        stepped = amp.apply_if_finite(p, {"w": p["w"] - grads["w"]}, finite)
        np.testing.assert_allclose(stepped["w"], p["w"])  # skipped

    def test_state_dict_roundtrip(self):
        s = amp.init_loss_scaler("dynamic")
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        payload = amp.state_dict(s)
        restored = amp.load_state_dict(amp.init_loss_scaler("dynamic"), payload)
        assert float(restored.loss_scale) == float(s.loss_scale)
        assert int(restored.skipped_steps) == 1

    def test_scaler_state_jits(self):
        s = amp.init_loss_scaler("dynamic")

        @jax.jit
        def step(s, finite):
            return amp.update_loss_scaler(s, finite)

        s2 = step(s, jnp.asarray(True))
        assert int(s2.growth_tracker) == 1


class TestScalerObservability:
    """The scaler's observability surface: ``skipped_steps`` and
    growth-tracker transitions across a full overflow → recovery → growth
    sequence, and the monitor hook surfacing the same numbers (the AMP half
    of the ``apex_tpu.monitor`` wiring)."""

    def _snap(self, s):
        return (float(s.loss_scale), int(s.growth_tracker),
                int(s.skipped_steps))

    def test_overflow_recovery_growth_transitions(self):
        s = amp.init_loss_scaler("dynamic", init_scale=2.0 ** 16,
                                 growth_interval=2)
        assert self._snap(s) == (2.0 ** 16, 0, 0)
        # overflow: scale halves, tracker resets, lifetime skip count +1
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        assert self._snap(s) == (2.0 ** 15, 0, 1)
        # recovery: one clean step ticks the tracker, scale holds
        s = amp.update_loss_scaler(s, jnp.asarray(True))
        assert self._snap(s) == (2.0 ** 15, 1, 1)
        # growth: second clean step hits the interval — scale doubles back,
        # tracker resets, skip count is lifetime (never resets)
        s = amp.update_loss_scaler(s, jnp.asarray(True))
        assert self._snap(s) == (2.0 ** 16, 0, 1)
        # second overflow after the growth: backoff again, count climbs
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        assert self._snap(s) == (2.0 ** 15, 0, 2)

    def test_scaler_metrics_pull(self):
        s = amp.init_loss_scaler("dynamic", init_scale=1024.0)
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        m = amp.scaler_metrics(s)
        assert m == {"loss_scale": 512.0, "growth_tracker": 0,
                     "skipped_steps": 1}
        assert all(isinstance(v, (int, float)) and not hasattr(v, "dtype")
                   for v in m.values())  # host scalars, not arrays

    def test_monitor_hook_surfaces_the_same_numbers(self):
        import io

        from apex_tpu import monitor

        buf = io.StringIO()
        monitor.enable(stream=buf)
        try:
            s = amp.init_loss_scaler("dynamic", init_scale=2.0 ** 16,
                                     growth_interval=2)
            seen = []
            for finite in (True, False, True, True):
                monitor.begin_step()
                s = amp.update_loss_scaler(s, jnp.asarray(finite))
                pulled = monitor.observe_scaler(s)
                assert pulled == amp.scaler_metrics(s)
                seen.append(monitor.end_step(dur_s=1e-3))
            reg = monitor.get_registry()
            assert reg.gauges["amp/loss_scale"] == float(s.loss_scale)
            assert reg.gauges["amp/skipped_steps_total"] == 1
            # exactly the overflow step carries the per-step overflow count
            overflow_steps = [r["step"] for r in seen
                              if r["counters"].get("amp/overflow_steps")]
            assert overflow_steps == [1]
            # the stream's gauge trajectory replays the state transitions
            scales = [r["gauges"]["amp/loss_scale"] for r in seen]
            assert scales == [2.0 ** 16, 2.0 ** 15, 2.0 ** 15, 2.0 ** 16]
        finally:
            monitor.disable()


class TestMasterWeights:
    def test_o2_roundtrip(self):
        from apex_tpu.amp import MasterWeights, apply_updates_with_master

        w = MasterWeights.create({"w": jnp.ones((4,), jnp.bfloat16)}, amp.O2)
        assert w.master["w"].dtype == jnp.float32
        assert w.model["w"].dtype == jnp.bfloat16
        # tiny update visible in fp32 master but below bf16 resolution
        w2 = apply_updates_with_master(w, {"w": jnp.full((4,), 1e-4)})
        assert float(w2.master["w"][0]) == pytest.approx(1.0001)
        # skip path
        w3 = apply_updates_with_master(w, {"w": jnp.full((4,), 1.0)},
                                       grads_finite=jnp.asarray(False))
        np.testing.assert_allclose(np.asarray(w3.master["w"]), 1.0)


class TestO1Wiring:
    """O1 per-op semantics are enforced at apex_tpu.ops call sites — the
    behavioral half of the reference's ``tests/L0/run_amp/test_basic_casts.py``
    and ``test_promotion.py`` (wrappers: ``apex/amp/wrap.py:10-130``)."""

    def test_dense_runs_half_under_o1(self):
        from apex_tpu.ops import fused_dense

        x = jnp.ones((4, 8), jnp.float32)
        w = jnp.ones((8, 8), jnp.float32)
        with amp.with_policy(amp.O1):
            y = fused_dense(x, w)
        assert y.dtype == jnp.bfloat16  # HALF-class: computed+returned in bf16

    def test_dense_untouched_outside_o1(self):
        from apex_tpu.ops import fused_dense

        x = jnp.ones((4, 8), jnp.float32)
        w = jnp.ones((8, 8), jnp.float32)
        y = fused_dense(x, w)
        assert y.dtype == jnp.float32

    def test_mlp_runs_half_under_o1(self):
        from apex_tpu.ops import mlp

        x = jnp.ones((4, 8), jnp.float32)
        w = [jnp.ones((8, 8), jnp.float32)]
        b = [jnp.zeros((8,), jnp.float32)]
        with amp.with_policy(amp.O1):
            y = mlp(x, w, b)
        assert y.dtype == jnp.bfloat16

    def test_softmax_runs_float_under_o1(self):
        from apex_tpu.ops import scaled_upper_triang_masked_softmax

        x = jnp.ones((2, 4, 4), jnp.bfloat16)
        with amp.with_policy(amp.O1):
            y = scaled_upper_triang_masked_softmax(x)
        assert y.dtype == jnp.float32  # FLOAT-class: half input cast up

    def test_layer_norm_runs_float_under_o1(self):
        from apex_tpu.ops import fused_layer_norm

        x = jnp.ones((4, 8), jnp.bfloat16)
        w = jnp.ones((8,), jnp.bfloat16)
        b = jnp.zeros((8,), jnp.bfloat16)
        with amp.with_policy(amp.O1):
            y = fused_layer_norm(x, w, b)
        assert y.dtype == jnp.float32

    def test_xent_loss_float_under_o1(self):
        from apex_tpu.ops import softmax_cross_entropy_loss

        logits = jnp.ones((4, 16), jnp.bfloat16)
        labels = jnp.zeros((4,), jnp.int32)
        with amp.with_policy(amp.O1):
            loss = softmax_cross_entropy_loss(logits, labels)
        assert loss.dtype == jnp.float32

    def test_flash_attention_half_under_o1(self):
        from apex_tpu.ops.attention import flash_attention

        q = jnp.ones((2, 8, 16), jnp.float32)
        with amp.with_policy(amp.O1):
            o = flash_attention(q, q, q, causal=True)
        assert o.dtype == jnp.bfloat16

    def test_banned_bce_raises_on_half_under_o1(self):
        from apex_tpu.ops.xentropy import binary_cross_entropy

        p = jnp.full((4,), 0.5, jnp.bfloat16)
        t = jnp.ones((4,), jnp.bfloat16)
        with amp.with_policy(amp.O1):
            with pytest.raises(RuntimeError, match="numerically unsafe"):
                binary_cross_entropy(p, t)

    def test_banned_bce_ok_in_fp32_under_o1(self):
        from apex_tpu.ops.xentropy import binary_cross_entropy

        p = jnp.full((4,), 0.5, jnp.float32)
        t = jnp.ones((4,), jnp.float32)
        with amp.with_policy(amp.O1):
            loss = binary_cross_entropy(p, t)
        np.testing.assert_allclose(loss, -np.log(0.5), rtol=1e-5)

    def test_banned_bce_ok_outside_o1(self):
        from apex_tpu.ops.xentropy import binary_cross_entropy

        p = jnp.full((4,), 0.5, jnp.bfloat16)
        t = jnp.ones((4,), jnp.bfloat16)
        loss = binary_cross_entropy(p, t)  # no amp: untouched, legal
        assert loss.dtype == jnp.bfloat16

    def test_promotion_widest_dtype(self):
        # PROMOTE-class: mixed bf16/fp32 inputs promote to fp32
        a = jnp.ones((4,), jnp.bfloat16)
        b = jnp.ones((4,), jnp.float32)
        out = amp_lists.apply_op_rules("add", a, b, policy=amp.O1)
        assert all(x.dtype == jnp.float32 for x in out)

    def test_promotion_same_dtype_kept(self):
        a = jnp.ones((4,), jnp.bfloat16)
        b = jnp.ones((4,), jnp.bfloat16)
        out = amp_lists.apply_op_rules("cat", a, b, policy=amp.O1)
        assert all(x.dtype == jnp.bfloat16 for x in out)

    def test_int_leaves_pass_through(self):
        labels = jnp.zeros((4,), jnp.int32)
        x = jnp.ones((4,), jnp.float32)
        out = amp_lists.apply_op_rules("dense", x, labels, policy=amp.O1)
        assert out[0].dtype == jnp.bfloat16 and out[1].dtype == jnp.int32

    def test_register_moves_family(self):
        amp_lists.register_float_op("mlp")
        try:
            x = jnp.ones((4, 8), jnp.bfloat16)
            out = amp_lists.apply_op_rules("mlp", x, policy=amp.O1)
            assert out[0].dtype == jnp.float32
        finally:
            amp_lists.register_half_op("mlp")

    def test_o1_grads_flow_through_casts(self):
        from apex_tpu.ops import fused_dense

        x = jnp.ones((4, 8), jnp.float32)
        w = jnp.ones((8, 8), jnp.float32)

        def loss(w):
            with amp.with_policy(amp.O1):
                return fused_dense(x, w).astype(jnp.float32).sum()

        g = jax.grad(loss)(w)
        assert g.dtype == jnp.float32  # cotangent cast back to param dtype
        np.testing.assert_allclose(g, 4.0 * jnp.ones((8, 8)), rtol=1e-2)


class TestSkipStepIfNonfinite:
    """The functional skip-step must protect the optimizer's inner state,
    not just params (reference ``handle.py:128-154`` skips the whole step;
    found by the fp16 end-to-end drive: unguarded opt.update poisons m/v
    with inf and training never recovers)."""

    def test_overflow_leaves_state_and_params_clean(self):
        import optax
        from apex_tpu.optimizers import fused_adam

        opt = amp.skip_step_if_nonfinite(fused_adam(learning_rate=1e-2))
        params = {"w": jnp.ones((4,))}
        state = opt.init(params)
        bad = {"w": jnp.array([1.0, jnp.inf, 1.0, 1.0])}
        updates, state2 = opt.update(bad, state, params)
        assert all(np.all(np.asarray(u) == 0) for u in jax.tree.leaves(updates))
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(state2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # a following finite step proceeds normally
        good = {"w": jnp.full((4,), 0.5)}
        updates, state3 = opt.update(good, state2, params)
        assert np.all(np.isfinite(np.asarray(updates["w"])))
        assert float(jnp.abs(updates["w"]).sum()) > 0

    def test_fp16_training_recovers_from_overflow(self):
        from apex_tpu.optimizers import fused_adam

        policy = amp.get_policy("O2", half_dtype=jnp.float16)
        params = {"w": jnp.ones((8,)) * 0.1}
        master = amp.MasterWeights.create(params, policy)
        opt = amp.skip_step_if_nonfinite(fused_adam(learning_rate=1e-2))
        opt_state = opt.init(master.master)
        # scale so large the first fp16 grads overflow
        scaler = amp.init_loss_scaler("dynamic", init_scale=2.0 ** 24)

        def loss_fn(p, x):
            return jnp.mean((x @ p["w"][:, None]) ** 2).astype(jnp.float32)

        x = jnp.ones((4, 8), jnp.float16) * 100.0
        losses = []
        for _ in range(6):
            loss, (grads, finite, scaler) = amp.scaled_value_and_grad(loss_fn)(
                scaler, master.model, x)
            updates, opt_state = opt.update(grads, opt_state, master.master)
            master = amp.apply_updates_with_master(master, updates, grads_finite=finite)
            losses.append(float(loss))
        assert int(scaler.skipped_steps) >= 1, "expected at least one overflow"
        assert np.isfinite(np.asarray(jax.tree.leaves(master.master))).all()
        assert np.isfinite(losses[-1])


class TestFrontend:
    """``amp.initialize`` + decorator surface (``apex/amp/frontend.py:195``,
    ``amp.py:30-57``, ``handle.py:163-167``)."""

    def _params(self):
        return {"w": jnp.ones((4, 4), jnp.float32),
                "step": jnp.zeros((), jnp.int32)}

    def test_initialize_o2_wraps_masters_and_scaler(self):
        from apex_tpu.optimizers import fused_adam

        st = amp.initialize(self._params(), fused_adam(1e-3), "O2",
                            half_dtype=jnp.float16)
        assert isinstance(st.params, amp.MasterWeights)
        assert st.params.model["w"].dtype == jnp.float16
        assert st.params.master["w"].dtype == jnp.float32
        assert st.scaler is not None and st.scaler.dynamic
        assert st.policy.master_weights

    def test_initialize_o0_is_identity_no_scaler(self):
        st = amp.initialize(self._params(), None, "O0")
        assert st.scaler is None  # loss_scale 1.0 and static => unscaled
        assert st.params["w"].dtype == jnp.float32
        assert st.params["step"].dtype == jnp.int32  # ints untouched

    def test_initialize_o1_keeps_params_fp32(self):
        st = amp.initialize(self._params(), None, "O1")
        assert st.params["w"].dtype == jnp.float32
        assert st.policy.per_op_rules

    def test_initialize_trains_end_to_end(self):
        """The returned pieces compose into a working O2 fp16 step."""
        from apex_tpu.optimizers import fused_sgd

        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8,), jnp.float32)}
        st = amp.initialize(params, fused_sgd(learning_rate=0.05), "O2",
                            half_dtype=jnp.float16)
        opt_state = st.optimizer.init(st.params.master)
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8), jnp.float16)

        def loss_fn(p, x):
            return jnp.mean((x @ p["w"]) ** 2).astype(jnp.float32)

        master, scaler = st.params, st.scaler
        losses = []
        for _ in range(10):
            loss, (g, finite, scaler) = amp.scaled_value_and_grad(loss_fn)(
                scaler, master.model, x)
            updates, opt_state = st.optimizer.update(g, opt_state, master.master)
            master = amp.apply_updates_with_master(master, updates,
                                                   grads_finite=finite)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses

    def test_half_function_decorator_casts_under_o1(self):
        seen = {}

        @amp.half_function
        def my_matmul_like_op(a, b):
            seen["dtypes"] = (a.dtype, b.dtype)
            return a @ b

        a = jnp.ones((4, 4), jnp.float32)
        with amp.with_policy(amp.get_policy("O1")):
            my_matmul_like_op(a, a)
        assert seen["dtypes"] == (jnp.bfloat16, jnp.bfloat16)
        # no ambient O1 -> untouched
        my_matmul_like_op(a, a)
        assert seen["dtypes"] == (jnp.float32, jnp.float32)

    def test_float_function_decorator_upcasts(self):
        seen = {}

        @amp.float_function
        def my_loss_like_op(a):
            seen["dtype"] = a.dtype
            return a.sum()

        with amp.with_policy(amp.get_policy("O1")):
            my_loss_like_op(jnp.ones((4,), jnp.bfloat16))
        assert seen["dtype"] == jnp.float32

    def test_disable_casts_suspends_o1(self):
        seen = {}

        @amp.half_function
        def another_op(a):
            seen["dtype"] = a.dtype
            return a

        with amp.with_policy(amp.get_policy("O1")):
            with amp.disable_casts():
                another_op(jnp.ones((2,), jnp.float32))
        assert seen["dtype"] == jnp.float32

    def test_master_params(self):
        st = amp.initialize(self._params(), None, "O2")
        leaves = amp.master_params(st)
        assert all(l.dtype in (jnp.float32, jnp.int32) for l in leaves)
        assert len(leaves) == 2

    def test_num_losses_independent_scalers(self):
        """``amp.initialize(..., num_losses=2)`` — per-loss scaler states
        (reference: per-loss ``LossScaler``s ``_initialize.py:227-231``;
        test ``test_multiple_models_optimizers_losses.py``). An overflow on
        loss 0 must back off scaler 0 only."""
        st = amp.initialize(self._params(), None, "O2",
                            half_dtype=jnp.float16, num_losses=2)
        assert isinstance(st.scaler, list) and len(st.scaler) == 2
        s0, s1 = st.scaler

        def ok_loss(p):
            return jnp.sum(p["w"].astype(jnp.float32) * 1e-3)

        def overflow_loss(p):
            # fp16 grads overflow under the big scale
            return jnp.sum((p["w"] * 3e4).astype(jnp.float32))

        p16 = {"w": jnp.ones((4, 4), jnp.float16)}
        _, (_, fin1, s1_new) = amp.scaled_value_and_grad(ok_loss)(s1, p16)
        _, (_, fin0, s0_new) = amp.scaled_value_and_grad(overflow_loss)(s0, p16)
        assert bool(fin1)
        assert not bool(fin0)
        assert float(s0_new.loss_scale) == float(s0.loss_scale) / 2
        assert float(s1_new.loss_scale) == float(s1.loss_scale)
