"""Encoder-decoder (split-rank) pipeline tests.

VERDICT r2 item 3: ``--pipeline-model-parallel-split-rank`` must change
execution. A BERT-style encoder segment feeds a GPT-style decoder segment
with cross-attention over a pp=4 two-segment pipeline, parity-checked
against serial execution (reference ``parallel_state.py:147-149,338-375``).
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops import fused_layer_norm
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_enc_dec, pipeline_spmd_forward_enc_dec)

K = jr.PRNGKey(55)
HID, HEADS = 16, 2
D = HID // HEADS


def _attn(q, k, v, causal):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / D ** 0.5
    if causal:
        n = s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((n, n), bool)), s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _heads(x):
    b, s, _ = x.shape
    return x.reshape(b, s, HEADS, D)


def enc_block(p, h):
    """Bidirectional self-attention + MLP (BERT-style)."""
    x = fused_layer_norm(h, p["e_ln1_w"], p["e_ln1_b"])
    qkv = x @ p["e_qkv"]
    q, k, v = (_heads(t) for t in jnp.split(qkv, 3, -1))
    h = h + _attn(q, k, v, False).reshape(h.shape) @ p["e_ao"]
    x = fused_layer_norm(h, p["e_ln2_w"], p["e_ln2_b"])
    return h + jax.nn.gelu(x @ p["e_up"], approximate=True) @ p["e_dn"]


def dec_block(p, h, ctx):
    """Causal self-attention + cross-attention over the encoder output +
    MLP (T5/GPT-decoder-style)."""
    x = fused_layer_norm(h, p["d_ln1_w"], p["d_ln1_b"])
    qkv = x @ p["d_qkv"]
    q, k, v = (_heads(t) for t in jnp.split(qkv, 3, -1))
    h = h + _attn(q, k, v, True).reshape(h.shape) @ p["d_ao"]
    x = fused_layer_norm(h, p["d_ln2_w"], p["d_ln2_b"])
    q = _heads(x @ p["d_xq"])
    kv = ctx @ p["d_xkv"]
    ck, cv = (_heads(t) for t in jnp.split(kv, 2, -1))
    h = h + _attn(q, ck, cv, False).reshape(h.shape) @ p["d_xo"]
    x = fused_layer_norm(h, p["d_ln3_w"], p["d_ln3_b"])
    return h + jax.nn.gelu(x @ p["d_up"], approximate=True) @ p["d_dn"]


def make_stage_params(key):
    """Union structure: every stage holds encoder AND decoder fields (the
    other segment's are dead weight — program uniformity)."""
    ks = jr.split(key, 10)
    s = 0.25
    ones, zeros = jnp.ones((HID,)), jnp.zeros((HID,))
    return {
        "e_ln1_w": ones, "e_ln1_b": zeros, "e_ln2_w": ones, "e_ln2_b": zeros,
        "e_qkv": jr.normal(ks[0], (HID, 3 * HID)) * s,
        "e_ao": jr.normal(ks[1], (HID, HID)) * s,
        "e_up": jr.normal(ks[2], (HID, 4 * HID)) * s,
        "e_dn": jr.normal(ks[3], (4 * HID, HID)) * s,
        "d_ln1_w": ones, "d_ln1_b": zeros, "d_ln2_w": ones,
        "d_ln2_b": zeros, "d_ln3_w": ones, "d_ln3_b": zeros,
        "d_qkv": jr.normal(ks[4], (HID, 3 * HID)) * s,
        "d_ao": jr.normal(ks[5], (HID, HID)) * s,
        "d_xq": jr.normal(ks[6], (HID, HID)) * s,
        "d_xkv": jr.normal(ks[7], (HID, 2 * HID)) * s,
        "d_xo": jr.normal(ks[8], (HID, HID)) * s,
        "d_up": jr.normal(ks[9], (HID, 4 * HID)) * s,
        "d_dn": jr.normal(jr.fold_in(key, 99), (4 * HID, HID)) * s,
    }


def serial_enc_dec(plist, split, enc_x, dec_x):
    h = enc_x
    for p in plist[:split]:
        h = enc_block(p, h)
    ctx, h2 = h, dec_x
    for p in plist[split:]:
        h2 = dec_block(p, h2, ctx)
    return h2


class TestSplitRankState:
    def test_spec_accessor_and_predicates(self):
        mesh_lib.initialize_model_parallel(
            pipeline_model_parallel_size=4,
            pipeline_model_parallel_split_rank=2)
        assert mesh_lib.get_pipeline_model_parallel_split_rank() == 2
        assert mesh_lib.is_pipeline_stage_before_split(rank=1)
        assert not mesh_lib.is_pipeline_stage_before_split(rank=2)
        assert mesh_lib.is_pipeline_stage_after_split(rank=2)
        assert not mesh_lib.is_pipeline_stage_after_split(rank=0)
        assert mesh_lib.is_pipeline_stage_at_split(rank=1)
        assert not mesh_lib.is_pipeline_stage_at_split(rank=2)
        mesh_lib.destroy_model_parallel()

    def test_no_split_is_single_segment(self):
        mesh_lib.initialize_model_parallel(pipeline_model_parallel_size=4)
        assert mesh_lib.get_pipeline_model_parallel_split_rank() is None
        assert mesh_lib.is_pipeline_stage_before_split(rank=3)
        assert mesh_lib.is_pipeline_stage_after_split(rank=0)
        assert not mesh_lib.is_pipeline_stage_at_split(rank=1)
        mesh_lib.destroy_model_parallel()

    def test_invalid_split_rejected(self):
        for bad in (0, 4, 7):
            with pytest.raises(ValueError, match="split_rank"):
                mesh_lib.initialize_model_parallel(
                    pipeline_model_parallel_size=4,
                    pipeline_model_parallel_split_rank=bad)


class TestArgsGlue:
    def test_split_rank_flag_reaches_the_mesh(self):
        """The whole r2 complaint: the accepted flag must change state."""
        from apex_tpu.transformer.testing import arguments

        args = arguments.parse_args(args_list=[
            "--num-layers", "4", "--hidden-size", "16",
            "--num-attention-heads", "2", "--seq-length", "8",
            "--max-position-embeddings", "8", "--micro-batch-size", "1",
            "--tensor-model-parallel-size", "1",
            "--pipeline-model-parallel-size", "4",
            "--pipeline-model-parallel-split-rank", "2",
        ])
        arguments.initialize_model_parallel_from_args(args)
        assert mesh_lib.get_pipeline_model_parallel_split_rank() == 2
        mesh_lib.destroy_model_parallel()


class TestEncDecPipeline:
    def _data(self, M=6, b=2, s=8):
        enc = jr.normal(jr.fold_in(K, 1), (M, b, s, HID))
        dec = jr.normal(jr.fold_in(K, 2), (M, b, s, HID))
        tgt = jr.normal(jr.fold_in(K, 3), (M, b, s, HID))
        return enc, dec, tgt

    @pytest.mark.parametrize("split", [1, 2, 3])
    def test_forward_matches_serial(self, split):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = [make_stage_params(jr.fold_in(K, 10 + i)) for i in range(4)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *plist)
        enc, dec, _ = self._data()

        out = mesh_lib.shard_map(
            lambda p, e, d: pipeline_spmd_forward_enc_dec(
                enc_block, dec_block, jax.tree.map(lambda x: x[0], p), e, d,
                split_rank=split, remat=False),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
            out_specs=P(),
        )(stacked, enc, dec)

        ref = jax.vmap(lambda e, d: serial_enc_dec(plist, split, e, d))(
            enc, dec)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_split_rank_changes_execution(self):
        """The r2 complaint was an accepted-but-ignored flag: different
        split ranks must now produce different outputs."""
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = [make_stage_params(jr.fold_in(K, 20 + i)) for i in range(4)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *plist)
        enc, dec, _ = self._data()

        def run(split):
            return mesh_lib.shard_map(
                lambda p, e, d: pipeline_spmd_forward_enc_dec(
                    enc_block, dec_block, jax.tree.map(lambda x: x[0], p),
                    e, d, split_rank=split, remat=False),
                mesh=mesh,
                in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
                out_specs=P(),
            )(stacked, enc, dec)

        assert float(jnp.max(jnp.abs(run(1) - run(3)))) > 1e-3

    def test_loss_and_grads_match_serial(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        split = 2
        plist = [make_stage_params(jr.fold_in(K, 30 + i)) for i in range(4)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *plist)
        enc, dec, tgt = self._data()

        def loss_head(out, t):
            return jnp.mean((out - t) ** 2)

        def run(p, e, d, t):
            loss, g = forward_backward_pipelining_enc_dec(
                enc_block, dec_block, loss_head,
                jax.tree.map(lambda x: x[0], p), e, d, t, split_rank=split)
            return loss, jax.tree.map(lambda x: x[None], g)

        loss, grads = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P(),
                      P()),
            out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
        )(stacked, enc, dec, tgt)

        def serial_loss(sp):
            pl = [jax.tree.map(lambda x: x[i], sp) for i in range(4)]
            outs = jax.vmap(
                lambda e, d: serial_enc_dec(pl, split, e, d))(enc, dec)
            return jnp.mean(jax.vmap(loss_head)(outs, tgt))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for (pa, a), (_, e) in zip(
                jax.tree_util.tree_leaves_with_path(grads),
                jax.tree_util.tree_leaves_with_path(ref_grads)):
            np.testing.assert_allclose(
                a, e, rtol=2e-4, atol=1e-5,
                err_msg=jax.tree_util.keystr(pa))

    def test_uses_installed_mesh_split(self):
        """split_rank=None resolves from the installed MeshSpec — the
        arguments-surface flag flows through initialize_model_parallel."""
        mesh_lib.initialize_model_parallel(
            pipeline_model_parallel_size=4,
            pipeline_model_parallel_split_rank=2)
        mesh = mesh_lib.get_mesh()
        plist = [make_stage_params(jr.fold_in(K, 40 + i)) for i in range(4)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *plist)
        enc, dec, _ = self._data(M=4)

        out = mesh_lib.shard_map(
            lambda p, e, d: pipeline_spmd_forward_enc_dec(
                enc_block, dec_block, jax.tree.map(lambda x: x[0], p), e, d,
                remat=False),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
            out_specs=P(),
        )(stacked, enc, dec)
        ref = jax.vmap(lambda e, d: serial_enc_dec(plist, 2, e, d))(enc, dec)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
        mesh_lib.destroy_model_parallel()
