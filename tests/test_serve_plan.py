"""apex_tpu.plan.serve: the ServePlan object, trace-replay pricing,
the search loop, the online ReplanPolicy, and the ``serve_plan``
record/CLI surface (ISSUE 20).

Fixture costs are hand-built round numbers so the pricing assertions
are exact: determinism is bit-identical, the worked single-request
walk pins the simulator's arithmetic to the same numbers
``docs/api/plan.md`` derives by hand, and the load-shift fixture pins
that the searched plan beats every fixed hand config on the SAME
replay model (tokens/s and TTFT p50 — the off-TPU half of the
acceptance gate).
"""

import dataclasses
import itertools
import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp
import jax.random as jr

from apex_tpu import monitor
from apex_tpu.inference import DecodeEngine
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.plan import (
    PlanError,
    ServeCosts,
    ServePlan,
    derive_serve_costs,
    enumerate_serve_plans,
    price_serve_plan,
    search_serve_plans,
    serve_plan_record_fields,
    split_knob_changes,
)
from apex_tpu.serving import (
    ReplanPolicy,
    Request,
    ServeTelemetry,
    ServingEngine,
    SLOPolicy,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import bench_history  # noqa: E402
import validate_metrics  # noqa: E402

K = jr.PRNGKey(20)


@dataclasses.dataclass
class _R:
    """Minimal trace row: what price_serve_plan reads off a request."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival_s: float = 0.0


def _trace(n=8, seed=0, max_prompt=24, max_new=8, spacing_s=0.0):
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for i in range(n):
        out.append(_R(rid=i,
                      prompt=np.asarray(
                          rng.integers(0, 97, rng.integers(4, max_prompt)),
                          np.int32),
                      max_new_tokens=int(rng.integers(2, max_new)),
                      arrival_s=t))
        t += spacing_s
    return out


#: hand-built, fully measured costs — every pricing assertion is exact
COSTS = ServeCosts(prefill_ms_per_token=1.0, decode_ms_per_step=2.0,
                   decode_ms_per_row=1.0, hbm_bytes_per_s=4000.0,
                   spec_round_ms=0.0, spec_acceptance=0.0,
                   num_layers=1, kv_heads=1, head_dim=1)


class TestServePlan:
    def test_roundtrip_exact(self):
        p = ServePlan(num_blocks=41, block_size=16, num_slots=4,
                      prefill_chunk=32, max_prefill_share=2,
                      drafter="ngram_tree", spec_depth=4, spec_branching=2,
                      spec_adaptive=True, kv_dtype="int8",
                      slo_ttft_ms=250.0, slo_burn_count=2,
                      admission="short_first")
        assert ServePlan.from_json(p.to_json()) == p
        assert ServePlan.from_json(json.dumps(p.to_json())) == p
        assert p.to_json() == ServePlan.from_json(p.to_json()).to_json()

    def test_from_json_rejects_unknown_fields(self):
        blob = ServePlan(num_blocks=9).to_json()
        blob["block_sizes"] = 64
        with pytest.raises(PlanError, match="block_sizes"):
            ServePlan.from_json(blob)
        with pytest.raises(PlanError, match="JSON object"):
            ServePlan.from_json([1, 2])

    @pytest.mark.parametrize("kw,needle", [
        (dict(num_blocks=1), "num_blocks=1"),
        (dict(num_blocks=9, block_size=0), "block_size=0"),
        (dict(num_blocks=9, num_slots=True), "num_slots=True"),
        (dict(num_blocks=9, prefill_chunk=24, block_size=16),
         "prefill_chunk=24"),
        (dict(num_blocks=9, drafter="oracle"), "drafter='oracle'"),
        (dict(num_blocks=9, spec_depth=3), "drafter='none'"),
        (dict(num_blocks=9, drafter="ngram", spec_depth=0),
         "needs a draft depth"),
        (dict(num_blocks=9, drafter="ngram", spec_depth=2,
              spec_branching=2), "only the tree drafter forks"),
        (dict(num_blocks=9, drafter="ngram", spec_depth=2,
              spec_adaptive=True), "adaptive ladder"),
        (dict(num_blocks=9, kv_dtype="fp4"), "kv_dtype='fp4'"),
        (dict(num_blocks=9, slo_ttft_ms=0.0), "slo_ttft_ms=0.0"),
        (dict(num_blocks=9, slo_ttft_ms=float("nan")), "slo_ttft_ms"),
        (dict(num_blocks=9, admission="lifo"), "admission='lifo'"),
    ])
    def test_validation_names_knob_and_legal_values(self, kw, needle):
        with pytest.raises(PlanError, match="legal values"):
            ServePlan(**kw)
        with pytest.raises(PlanError) as e:
            ServePlan(**kw)
        assert needle in str(e.value)

    def test_describe_and_digest(self):
        a = ServePlan(num_blocks=41, block_size=16, num_slots=4,
                      prefill_chunk=32, drafter="ngram_tree", spec_depth=3,
                      spec_branching=2, spec_adaptive=True,
                      kv_dtype="int8", slo_ttft_ms=250.0,
                      admission="short_first")
        d = a.describe()
        assert "blk16·pool41·slot4·chunk32" in d
        assert "spec[tree d3b2 adaptive]" in d
        assert "int8" in d and "slo250" in d and "short_first" in d
        # digest: content-stable, knob-sensitive, short
        assert a.digest() == ServePlan.from_json(a.to_json()).digest()
        assert a.digest() != dataclasses.replace(a, num_slots=8).digest()
        assert len(a.digest()) == 10

    def test_engine_and_telemetry_kwargs_split(self):
        p = ServePlan(num_blocks=9, block_size=8, num_slots=2,
                      prefill_chunk=16, slo_ttft_ms=100.0,
                      slo_burn_count=2)
        assert p.engine_kwargs() == dict(
            num_slots=2, block_size=8, num_blocks=9, prefill_chunk=16,
            kv_dtype=None)
        assert p.telemetry_kwargs() == dict(slo_ttft_ms=100.0,
                                            slo_burn_count=2)


class TestSplitKnobChanges:
    def test_live_only_diff(self):
        a = ServePlan(num_blocks=9, max_prefill_share=1,
                      slo_ttft_ms=100.0)
        b = dataclasses.replace(a, max_prefill_share=4, slo_ttft_ms=None,
                                admission="short_first", slo_burn_count=1)
        live, deferred = split_knob_changes(a, b)
        assert sorted(live) == ["admission", "max_prefill_share",
                                "slo_burn_count", "slo_ttft_ms"]
        assert live["max_prefill_share"] == (1, 4)
        assert deferred == {}

    def test_geometry_diffs_are_deferred(self):
        a = ServePlan(num_blocks=9, block_size=8, prefill_chunk=16)
        b = ServePlan(num_blocks=18, block_size=16, prefill_chunk=32,
                      num_slots=16, kv_dtype="int8")
        live, deferred = split_knob_changes(a, b)
        assert live == {}
        assert sorted(deferred) == ["block_size", "kv_dtype", "num_blocks",
                                    "num_slots", "prefill_chunk"]

    def test_spec_shape_live_only_between_adaptive_tree_plans(self):
        a = ServePlan(num_blocks=9, drafter="ngram_tree", spec_depth=2,
                      spec_adaptive=True)
        b = dataclasses.replace(a, spec_depth=4, spec_branching=2)
        live, deferred = split_knob_changes(a, b)
        assert sorted(live) == ["spec_branching", "spec_depth"]
        assert deferred == {}
        # not adaptive on both sides -> the same diff defers
        c = dataclasses.replace(a, spec_adaptive=False)
        d = dataclasses.replace(c, spec_depth=4)
        live, deferred = split_knob_changes(c, d)
        assert live == {} and sorted(deferred) == ["spec_depth"]
        # drafter identity changed -> everything spec defers
        e = dataclasses.replace(a, drafter="ngram", spec_branching=1,
                                spec_adaptive=False, spec_depth=4)
        live, deferred = split_knob_changes(a, e)
        assert live == {}
        assert sorted(deferred) == ["drafter", "spec_adaptive",
                                    "spec_depth"]


def _stat(mean):
    return {"n": 8, "mean": mean, "min": mean, "max": mean,
            "spread_pct": 0.0}


def _costdb(rates=None, gemm_rate=None):
    db = {"schema": 1, "kind": "costdb", "collectives": {}, "gemms": {}}
    for k, r in (rates or {}).items():
        db["collectives"][k] = [{"bucket_bytes": 1 << 20,
                                 "bytes": _stat(1 << 20),
                                 "bytes_per_s": _stat(r)}]
    if gemm_rate is not None:
        db["gemms"]["gemm_1048576"] = {"flops_per_s": _stat(gemm_rate)}
    return db


class TestDeriveServeCosts:
    GEOM = dict(hidden_size=64, num_layers=8, num_heads=4, vocab_size=512)

    def test_every_unmeasured_term_is_flagged_never_silent(self):
        c = derive_serve_costs(_costdb(), **self.GEOM,
                               default_bytes_per_s=1e9,
                               default_flops_per_s=1e11)
        assert c.uncalibrated == ("serve[decode_step_ms]",
                                  "serve[gemm_flops_per_s]",
                                  "serve[hbm_bytes_per_s]")
        assert c.spec_uncalibrated == ("serve[spec_acceptance_rate]",
                                       "serve[spec_round_ms]")
        # conservative on purpose: zero speculative benefit unmeasured
        assert c.spec_acceptance == 0.0
        assert c.hbm_bytes_per_s == 1e9
        assert c.head_dim == 64 // 4

    def test_fully_measured_is_calibrated(self):
        c = derive_serve_costs(
            _costdb(rates={"all_gather[tp]": 5e10}, gemm_rate=1e11),
            **self.GEOM,
            measured=dict(prefill_ms_per_token=0.5, decode_ms_per_step=2.0,
                          hbm_bytes_per_s=8e11, spec_round_ms=1.5,
                          spec_acceptance_rate=0.7))
        assert c.uncalibrated == () and c.spec_uncalibrated == ()
        assert c.prefill_ms_per_token == 0.5
        assert c.decode_ms_per_step == 2.0
        assert c.spec_acceptance == 0.7

    def test_measured_gemm_db_prices_prefill(self):
        c = derive_serve_costs(_costdb(gemm_rate=1e12), **self.GEOM,
                               default_bytes_per_s=1e9,
                               default_flops_per_s=1e11)
        assert "serve[gemm_flops_per_s]" not in c.uncalibrated
        flops = 2 * (12 * 8 * 64 * 64 + 64 * 512)
        assert c.prefill_ms_per_token == pytest.approx(1e3 * flops / 1e12)
        # the step floor is the per-row GEMM time when unmeasured
        assert c.decode_ms_per_step == c.decode_ms_per_row

    def test_bytes_per_ctx_token_by_kv_dtype(self):
        c = dataclasses.replace(COSTS, num_layers=2, kv_heads=2, head_dim=4)
        assert c.bytes_per_ctx_token(None) == 2 * 2 * 2 * 4 * 2
        assert c.bytes_per_ctx_token("fp8_e4m3") == 2 * 2 * 2 * 4
        # int8 additionally streams the per-block-row fp32 scale planes
        assert c.bytes_per_ctx_token("int8") == 2 * 2 * 2 * 4 + 2 * 2 * 4


class TestPriceServePlan:
    def test_worked_single_request_walk(self):
        """The docs/api/plan.md worked example, digit for digit: 8-token
        prompt, 3 new tokens, chunk=4 => two prefill chunks (TTFT 8 ms,
        first token at the FINAL chunk), then two decode steps at
        2 + 1 + ctx*1.0 ms with ctx = 9 then 10 => span 33 ms."""
        plan = ServePlan(num_blocks=4, block_size=4, num_slots=1,
                         prefill_chunk=4)
        req = _R(rid=0, prompt=np.arange(8, dtype=np.int32),
                 max_new_tokens=3)
        sprice = price_serve_plan(plan, [req], COSTS)
        assert sprice.prefill_chunks == 2 and sprice.decode_steps == 2
        assert sprice.predicted_ttft_p50_ms == 8.0
        assert sprice.predicted_ttft_p99_ms == 8.0
        assert sprice.sim_span_ms == 33.0
        assert sprice.predicted_tokens_per_s == pytest.approx(3e3 / 33.0)
        assert sprice.confidence == "calibrated"
        assert sprice.uncalibrated == ()

    def test_bit_deterministic(self):
        plan = ServePlan(num_blocks=12, block_size=8, num_slots=2,
                         prefill_chunk=8)
        tr = _trace(n=10, seed=3, spacing_s=0.001)
        a = price_serve_plan(plan, tr, COSTS)
        b = price_serve_plan(plan, tr, COSTS)
        assert a.to_json() == b.to_json()
        assert a.predicted_tokens_per_s == b.predicted_tokens_per_s
        assert a.sim_span_ms == b.sim_span_ms

    def test_monotone_in_every_rate(self):
        """A slower priced phase never predicts higher throughput (and a
        slower prefill never predicts a lower TTFT)."""
        plan = ServePlan(num_blocks=12, block_size=8, num_slots=2,
                         prefill_chunk=8)
        tr = _trace(n=10, seed=3, spacing_s=0.001)
        base = price_serve_plan(plan, tr, COSTS)
        for slow in (
            dataclasses.replace(COSTS, prefill_ms_per_token=2.0),
            dataclasses.replace(COSTS, decode_ms_per_step=4.0),
            dataclasses.replace(COSTS, decode_ms_per_row=2.0),
            dataclasses.replace(COSTS, hbm_bytes_per_s=2000.0),
        ):
            got = price_serve_plan(plan, tr, slow)
            assert got.predicted_tokens_per_s \
                <= base.predicted_tokens_per_s
        slow_prefill = price_serve_plan(
            plan, tr, dataclasses.replace(COSTS, prefill_ms_per_token=2.0))
        assert slow_prefill.predicted_ttft_p50_ms \
            >= base.predicted_ttft_p50_ms

    def test_structural_prefix_sharing_prices_cheaper(self):
        """A repeated prompt re-prices its full blocks as shared: fewer
        prefill chunks, lower p99 TTFT than two distinct prompts."""
        plan = ServePlan(num_blocks=8, block_size=4, num_slots=1,
                         prefill_chunk=4, max_prefill_share=1)
        same = np.arange(16, dtype=np.int32)
        shared_tr = [_R(0, same, 2), _R(1, same.copy(), 2)]
        distinct_tr = [_R(0, same, 2),
                       _R(1, np.arange(100, 116, dtype=np.int32), 2)]
        shared = price_serve_plan(plan, shared_tr, COSTS)
        distinct = price_serve_plan(plan, distinct_tr, COSTS)
        # the second request re-prefills only its final (unregistered)
        # block: 4 + 1 chunks vs 4 + 4
        assert shared.prefill_chunks == 5
        assert distinct.prefill_chunks == 8
        assert shared.predicted_ttft_p99_ms \
            < distinct.predicted_ttft_p99_ms

    def test_spec_plan_prices_fewer_decode_steps_iff_measured(self):
        costs = dataclasses.replace(COSTS, spec_acceptance=0.5,
                                    spec_round_ms=0.5)
        tr = _trace(n=6, seed=1)
        off = ServePlan(num_blocks=12, block_size=8, num_slots=2,
                        prefill_chunk=8)
        on = dataclasses.replace(off, drafter="ngram", spec_depth=2)
        assert price_serve_plan(on, tr, costs).decode_steps \
            < price_serve_plan(off, tr, costs).decode_steps
        # unmeasured acceptance prices to zero benefit: spec only adds
        # the round overhead, so it can never win on a blind spot
        blind = dataclasses.replace(costs, spec_acceptance=0.0,
                                    spec_uncalibrated=(
                                        "serve[spec_acceptance_rate]",))
        p_on = price_serve_plan(on, tr, blind)
        p_off = price_serve_plan(off, tr, blind)
        assert p_on.predicted_tokens_per_s <= p_off.predicted_tokens_per_s
        # the spec blind-spot flags join the price ONLY when drafting
        assert "serve[spec_acceptance_rate]" in p_on.uncalibrated
        assert p_off.uncalibrated == ()

    def test_empty_trace_and_oversized_request_are_loud(self):
        plan = ServePlan(num_blocks=4, block_size=4)
        with pytest.raises(PlanError, match="non-empty trace"):
            price_serve_plan(plan, [], COSTS)
        big = _R(0, np.arange(64, dtype=np.int32), 8)
        with pytest.raises(PlanError, match="raise num_blocks"):
            price_serve_plan(plan, [big], COSTS)


def _shift_trace():
    """Seeded calm->burst load shift: a trickle, then an arrival wave
    far denser than the calm plan's admission can drain."""
    rng = np.random.default_rng(7)
    out, t = [], 0.0
    for i in range(4):
        out.append(_R(i, np.asarray(rng.integers(0, 97, 16), np.int32),
                      6, t))
        t += 0.5
    t += 0.2
    for i in range(24):
        out.append(_R(4 + i,
                      np.asarray(rng.integers(0, 97, rng.integers(4, 24)),
                                 np.int32),
                      int(rng.integers(2, 8)), t))
        t += 0.002
    return out


class TestSearchServePlans:
    def test_enumeration_is_deterministic_and_deduped(self):
        base = ServePlan(num_blocks=9, block_size=8, num_slots=2,
                         prefill_chunk=16)
        a, _ = enumerate_serve_plans(base)
        b, _ = enumerate_serve_plans(base)
        assert [p.describe() for p in a] == [p.describe() for p in b]
        assert len({p.describe() for p in a}) == len(a)

    def test_infeasible_corners_are_rejections_with_reasons(self):
        tr = [_R(0, np.arange(60, dtype=np.int32), 8)]
        small = ServePlan(num_blocks=5, block_size=8, num_slots=2,
                          prefill_chunk=8)
        res = search_serve_plans(tr, COSTS, base=small)
        assert res.rejected and all(r for _, r in res.rejected)
        assert any("never be admitted" in r for _, r in res.rejected)
        # pool-bytes bound: every doubled-pool corner carries a reason
        bounded = search_serve_plans(tr, COSTS, base=ServePlan(
            num_blocks=12, block_size=8, num_slots=2, prefill_chunk=8),
            pool_bytes_bound=1)
        assert not bounded.ranked
        assert all("exceeds the bound" in r or "never be admitted" in r
                   for _, r in bounded.rejected)
        with pytest.raises(PlanError, match="no feasible serve plan"):
            bounded.best
        with pytest.raises(PlanError, match="base plan or an explicit"):
            search_serve_plans(tr, COSTS)
        with pytest.raises(PlanError, match="non-empty trace"):
            search_serve_plans([], COSTS, base=small)

    def test_searched_plan_beats_every_fixed_hand_config(self):
        """The off-TPU acceptance half: on the seeded load-shift trace
        the searched plan beats EVERY fixed hand config on predicted
        tokens/s AND TTFT p50, under the same bit-deterministic replay
        model."""
        tr = _shift_trace()
        hands = [
            ServePlan(num_blocks=9, block_size=8, num_slots=2,
                      prefill_chunk=8, max_prefill_share=1),
            ServePlan(num_blocks=9, block_size=8, num_slots=2,
                      prefill_chunk=8, max_prefill_share=4),
            ServePlan(num_blocks=9, block_size=8, num_slots=2,
                      prefill_chunk=16, max_prefill_share=2,
                      admission="short_first"),
        ]
        res = search_serve_plans(tr, COSTS, base=hands[0])
        best = res.best
        for hand in hands:
            hp = price_serve_plan(hand, tr, COSTS)
            assert best.price.predicted_tokens_per_s \
                > hp.predicted_tokens_per_s, hand.describe()
            assert best.price.predicted_ttft_p50_ms \
                <= hp.predicted_ttft_p50_ms, hand.describe()
        # ranking is sorted by the claim the record leads with
        tps = [c.price.predicted_tokens_per_s for c in res.ranked]
        assert tps == sorted(tps, reverse=True)


class _Tel:
    """The two live signals ReplanPolicy keys on, plus the SLO knobs
    _apply_live writes through."""

    def __init__(self):
        self.slo_burning = False
        self.queue_buildup = False
        self.slo_ttft_ms = None
        self.slo_burn_count = 3


CALM = ServePlan(num_blocks=9, block_size=8, num_slots=2, prefill_chunk=8,
                 max_prefill_share=2, slo_ttft_ms=100.0, slo_burn_count=2)
LOADED = dataclasses.replace(CALM, max_prefill_share=4,
                             admission="short_first", slo_ttft_ms=None,
                             slo_burn_count=3, num_blocks=18)


class TestReplanPolicy:
    def test_needs_a_ladder(self):
        with pytest.raises(ValueError, match="at least one"):
            ReplanPolicy(plans=())
        with pytest.raises(ValueError, match="not a plan index"):
            ReplanPolicy(plans=(CALM,), active=3)

    def test_buildup_steps_up_and_stages_the_switch(self):
        pol = ReplanPolicy(plans=(CALM, LOADED))
        tel = _Tel()
        pol.update(tel)
        assert pol.active == 0 and pol.pop_replan() is None
        tel.queue_buildup = True
        pol.update(tel)
        assert pol.active == 1 and pol.plan is LOADED
        assert pol.replans == 1 and pol.deferred_total == 1
        staged = pol.pop_replan()
        assert staged["trigger"] == "queue_buildup"
        assert staged["plan_from"] == CALM.digest()
        assert staged["plan_to"] == LOADED.digest()
        assert staged["live_knobs"] == ["admission", "max_prefill_share",
                                        "slo_burn_count", "slo_ttft_ms"]
        assert staged["deferred_knobs"] == ["num_blocks"]
        assert staged["spec_shape"] is None
        assert pol.pop_replan() is None  # at most one per window
        # the loaded plan's live knobs applied in place
        assert pol.max_prefill_share == 4
        assert pol.prefer_short_prompts  # short_first pins it on
        assert tel.slo_ttft_ms is None and tel.slo_burn_count == 3
        # at the ladder top the signal keeps widening the share only
        pol.update(tel)
        assert pol.active == 1 and pol.replans == 1

    def test_burn_steps_up_and_calm_streak_steps_down(self):
        pol = ReplanPolicy(plans=(CALM, LOADED), calm_windows=2)
        tel = _Tel()
        tel.slo_burning = True
        pol.update(tel)
        assert pol.active == 1
        assert pol.pop_replan()["trigger"] == "slo_burn"
        tel.slo_burning = False
        pol.update(tel)
        assert pol.active == 1 and pol.pop_replan() is None
        pol.update(tel)  # second clean window completes the streak
        assert pol.active == 0 and pol.replans == 2
        staged = pol.pop_replan()
        assert staged["trigger"] == "calm"
        assert tel.slo_ttft_ms == 100.0 and tel.slo_burn_count == 2
        # stepping down clamps the live share to the calm plan's bound
        assert pol.prefill_share <= pol.max_prefill_share == 2
        # a dirty window resets the streak
        tel.queue_buildup = True
        pol.update(tel)
        tel.queue_buildup = False
        pol.update(tel)
        assert pol.active == 1  # one clean window is not a streak

    def test_adaptive_tree_ladder_stages_the_spec_shape(self):
        a = dataclasses.replace(CALM, drafter="ngram_tree", spec_depth=2,
                                spec_adaptive=True)
        b = dataclasses.replace(a, spec_depth=4, spec_branching=2,
                                max_prefill_share=4)
        pol = ReplanPolicy(plans=(a, b))
        tel = _Tel()
        tel.queue_buildup = True
        pol.update(tel)
        staged = pol.pop_replan()
        assert staged["spec_shape"] == (4, 2)
        assert "spec_depth" in staged["live_knobs"]
        assert staged["deferred_knobs"] == []

    def test_slo_policy_narrows_on_any_non_buildup_window(self):
        """Regression (ISSUE 20 satellite): the share backs off on ANY
        window without queue buildup — a persistent benign anomaly
        (e.g. a TTFT burn, or one straggler flag per window) must never
        pin the share at max forever."""
        pol = SLOPolicy(max_prefill_share=4)
        tel = _Tel()
        tel.queue_buildup = True
        for _ in range(4):
            pol.update(tel)
        assert pol.prefill_share == 4
        # buildup clears but the burn persists: NOT a clean window,
        # and the share must still back off one step per window
        tel.queue_buildup = False
        tel.slo_burning = True
        pol.update(tel)
        assert pol.prefill_share == 3 and pol.prefer_short_prompts
        pol.update(tel)
        pol.update(tel)
        pol.update(tel)
        assert pol.prefill_share == 1  # floored, never 0


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig(vocab_size=97, max_seq_len=128, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    attention_impl="flash", remat=False, dropout=0.0)
    model = GPTModel(cfg)
    return model, model.init(K)


class TestLiveReplan:
    def test_mid_serve_replan_is_token_identical_and_recompile_free(
            self, tiny):
        """The live acceptance witness at test scale: a ReplanPolicy
        ladder whose plans differ only in aval-stable knobs switches
        mid-serve (an unmeetable SLO forces the burn trigger
        deterministically), at least one ``replan`` lands, greedy output
        stays token-identical to the reference engine, and both jit
        caches end at one executable."""
        model, params = tiny
        calm = ServePlan(num_blocks=13, block_size=8, num_slots=2,
                         prefill_chunk=8, max_prefill_share=1,
                         slo_ttft_ms=1e-6, slo_burn_count=1)
        loaded = dataclasses.replace(calm, max_prefill_share=4,
                                     admission="short_first",
                                     slo_ttft_ms=None)
        eng = ServingEngine(model, max_seq_len=64, **calm.engine_kwargs())
        rng = np.random.default_rng(5)
        reqs = [Request(rid=i,
                        prompt=np.asarray(rng.integers(0, 97,
                                                       rng.integers(4, 20)),
                                          np.int32),
                        max_new_tokens=int(rng.integers(2, 8)))
                for i in range(6)]
        pol = ReplanPolicy(plans=(calm, loaded))
        tel = ServeTelemetry(slots=calm.num_slots, window_s=1e-3,
                             collect_events=True,
                             **calm.telemetry_kwargs())
        counter = itertools.count()
        clock = lambda: next(counter) * 2e-4  # noqa: E731
        done = eng.serve(params, reqs, clock=clock, telemetry=tel,
                         scheduler=eng.make_scheduler(policy=pol))
        assert pol.replans >= 1
        assert tel.replans == pol.replans
        assert eng.prefill_chunk._cache_size() == 1
        assert eng.decode_step._cache_size() == 1
        replan_events = [e for e in tel.events
                         if e.get("phase") == "replan"]
        assert len(replan_events) == pol.replans
        assert replan_events[0]["replan_trigger"] == "slo_burn"
        assert replan_events[0]["plan_from"] == calm.digest()
        assert "deferred_knobs" not in replan_events[0]
        ref = DecodeEngine(model)
        for r in done:
            want = np.asarray(ref.generate(
                params, jnp.asarray(r.prompt)[None], r.max_new_tokens))[0]
            np.testing.assert_array_equal(np.asarray(r.tokens), want,
                                          err_msg=f"rid {r.rid}")


class TestServePlanRecord:
    def _fields(self, measured=False):
        tr = _shift_trace()
        res = search_serve_plans(tr, COSTS, base=ServePlan(
            num_blocks=9, block_size=8, num_slots=2, prefill_chunk=8))
        if measured:
            return serve_plan_record_fields(
                res, costdb_source="fixture", measured_tokens_per_s=512.0,
                measured_ttft_p50_ms=20.0)
        return serve_plan_record_fields(
            res, costdb_source="fixture",
            skip_reason="no TPU (backend=cpu)")

    def test_skip_record_validates_with_explicit_skip_objects(self):
        reg = monitor.MetricsRegistry()
        rec = reg.emit_serve_plan("SKIP", reason="no TPU (backend=cpu)",
                                  **self._fields())
        assert monitor.validate(rec) == []
        assert rec["measured_tokens_per_s"] == {
            "skipped": True, "reason": "no TPU (backend=cpu)"}
        assert rec["chosen"] == ServePlan.from_json(
            rec["chosen"]).to_json()
        assert rec["ranking"][0]["confidence"] in ("calibrated", "partial")

    def test_ok_record_validates_with_numbers(self):
        reg = monitor.MetricsRegistry()
        rec = reg.emit_serve_plan(
            "OK", **self._fields(measured=True), searched_beats_hand=True,
            replans=2, replan_parity=True, jit_cache_ok=True)
        assert monitor.validate(rec) == []
        assert rec["measured_tokens_per_s"] == 512.0
        # the drift series is derived from the measured half, absolute
        assert isinstance(rec["predicted_vs_measured_err_pct"], float)
        assert rec["predicted_vs_measured_err_pct"] >= 0.0

    def test_junk_key_fails_closed_schemas(self):
        reg = monitor.MetricsRegistry()
        rec = reg.emit_serve_plan("SKIP", reason="no TPU",
                                  **self._fields())
        evil = json.loads(json.dumps(rec))
        evil["chosen"]["block_sizes"] = 64
        assert any("block_sizes" in e for e in monitor.validate(evil))
        evil2 = json.loads(json.dumps(rec))
        evil2["ranking"][0]["tokens"] = 1.0
        assert monitor.validate(evil2)
        evil3 = json.loads(json.dumps(rec))
        evil3["rejected"].append({"plan": "x", "reason": "y", "junk": 1})
        assert monitor.validate(evil3)

    def test_skip_without_reason_and_nan_in_ok_are_refused(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="reason"):
            reg.emit_serve_plan("SKIP", **self._fields())
        rec = reg.emit_serve_plan("OK", **self._fields(measured=True))
        bad = json.loads(json.dumps(rec).replace("512.0", "NaN"))
        assert monitor.validate(bad)
        # a reason-less SKIP from an external stream fails validation
        ext = json.loads(json.dumps(
            reg.emit_serve_plan("SKIP", reason="x", **self._fields())))
        del ext["reason"]
        assert any("reason" in e for e in monitor.validate(ext))


class TestValidateMetricsCLI:
    def _record(self, tmp_path, name="sp.json", status="SKIP", **extra):
        reg = monitor.MetricsRegistry()
        tr = _trace(n=3, seed=2)
        res = search_serve_plans(tr, COSTS, base=ServePlan(
            num_blocks=9, block_size=8, num_slots=2, prefill_chunk=8))
        fields = serve_plan_record_fields(res, costdb_source="fixture",
                                          skip_reason="no TPU")
        fields.update(extra)
        kw = dict(reason="no TPU") if status == "SKIP" else {}
        rec = reg.emit_serve_plan(status, **kw, **fields)
        p = tmp_path / name
        p.write_text(json.dumps(rec))
        return p, rec

    def test_forced_and_content_dispatch(self, tmp_path):
        p, _ = self._record(tmp_path)
        assert validate_metrics.main(["--serve-plan", str(p)]) == 0
        assert validate_metrics.main([str(p)]) == 0  # kind dispatch

    def test_forced_flag_refuses_other_kinds(self, tmp_path):
        p = tmp_path / "serve.json"
        p.write_text(json.dumps({"kind": "serve", "schema": 1,
                                 "status": "SKIP", "reason": "x"}))
        assert validate_metrics.main(["--serve-plan", str(p)]) == 1

    def test_junk_and_reasonless_skip_fail(self, tmp_path):
        p, rec = self._record(tmp_path)
        evil = json.loads(json.dumps(rec))
        evil["chosen"]["junk"] = 1
        p.write_text(json.dumps(evil))
        assert validate_metrics.main(["--serve-plan", str(p)]) == 1
        bare = json.loads(json.dumps(rec))
        del bare["reason"]
        p.write_text(json.dumps(bare))
        assert validate_metrics.main(["--serve-plan", str(p)]) == 1


class TestBenchHistorySeries:
    """The serve_plan gate: measured tokens/s under the searched plan is
    the higher-is-better headline; the replay model's
    predicted-vs-measured error is the lower-is-better honesty series;
    pre-ServePlan history artifacts SKIP the new series only."""

    def _sp(self, tok=None, err=None, status="OK"):
        rec = {"kind": "serve_plan", "schema": 1, "status": status,
               "spread_pct": 1.0}
        if status == "SKIP":
            rec["reason"] = "no TPU"
        if tok is not None:
            rec["measured_tokens_per_s"] = tok
        if err is not None:
            rec["predicted_vs_measured_err_pct"] = err
        return rec

    def test_extract_all_carries_both_series(self):
        rows = bench_history.extract_all(self._sp(512.0, 3.5))
        assert ("serve_plan_tokens_per_s", 512.0, 1.0) in rows
        # model error gets NO spread widening from throughput variance
        assert ("serve_plan_predicted_vs_measured_err_pct", 3.5, 0.0) \
            in rows
        assert bench_history.extract_all(self._sp(status="SKIP")) == []

    def test_ok_record_missing_either_series_is_an_error(self):
        with pytest.raises(ValueError, match="measured_tokens_per_s"):
            bench_history.extract_all(self._sp(err=3.5))
        with pytest.raises(ValueError,
                           match="predicted_vs_measured_err_pct"):
            bench_history.extract_all(self._sp(tok=512.0))
        # a skip OBJECT is not a number either: still an error on OK
        rec = self._sp(err=3.5)
        rec["measured_tokens_per_s"] = {"skipped": True, "reason": "x"}
        with pytest.raises(ValueError, match="measured_tokens_per_s"):
            bench_history.extract_all(rec)

    def test_throughput_regression_fails(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._sp(512.0, 3.5)))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._sp(400.0, 3.5)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSION serve_plan_tokens_per_s" in out
        assert "OK serve_plan_predicted_vs_measured_err_pct" in out

    def test_model_error_drift_up_is_a_regression(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._sp(512.0, 3.0)))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._sp(512.0, 9.0)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSION serve_plan_predicted_vs_measured_err_pct" in out
        # a BETTER model (error down) is an improvement, not a failure
        fresh.write_text(json.dumps(self._sp(512.0, 1.0)))
        assert bench_history.main([str(fresh),
                                   "--root", str(tmp_path)]) == 0

    def test_skip_record_claims_nothing(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._sp(512.0, 3.5)))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._sp(1.0, 99.0, status="SKIP")))
        assert bench_history.main([str(fresh),
                                   "--root", str(tmp_path)]) == 0
        assert "SKIP" in capsys.readouterr().out

    def test_pre_serveplan_history_skips_the_new_series_only(
            self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(
            {"parsed": {"metric": "m_tok", "value": 100.0, "unit": "u",
                        "spread_pct": 0.5}}))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._sp(512.0, 3.5)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "SKIP: no history artifact carries metric " \
            "'serve_plan_tokens_per_s'" in out
        assert "SKIP: no history artifact carries metric " \
            "'serve_plan_predicted_vs_measured_err_pct'" in out


class TestReportTimeline:
    def test_replan_events_render_in_the_serve_timeline(self):
        from apex_tpu.monitor import report as monitor_report

        reg = monitor.MetricsRegistry()
        records = [
            reg.emit_meta(device_kind="cpu"),
            reg.emit("serve_event", rid=0, phase="submit", at_s=0.0),
            reg.emit("serve_event", rid=-1, phase="replan", at_s=0.4,
                     step=12, plan_from="aaaa111111",
                     plan_to="bbbb222222", replan_trigger="queue_buildup",
                     live_knobs=["max_prefill_share", "admission"],
                     deferred_knobs=["num_blocks"]),
            reg.emit("serve_event", rid=0, phase="finish", at_s=1.0,
                     tokens=5, slot=0, step=30),
        ]
        for r in records[1:]:
            assert monitor.validate(r) == [], r
        tl = monitor_report.serve_timeline(records)
        assert len(tl["replans"]) == 1
        rp = tl["replans"][0]
        assert rp["plan_from"] == "aaaa111111"
        assert rp["replan_trigger"] == "queue_buildup"
        text = monitor_report.format_serve_timeline(tl)
        assert "replan at step 12" in text
        assert "aaaa111111 -> bbbb222222" in text
        assert "queue_buildup" in text and "deferred: num_blocks" in text
