"""Request-level serving telemetry (ISSUE 10 tentpole a/c).

Contracts under test:

* the lifecycle event stream: one ``serve_event`` record per transition
  in order (``submit → admit → prefill_chunk*k → first_token → decode →
  finish``) with queue wait, chunk count, blocks held and per-phase
  durations — schema-valid end to end through a REAL engine serve;
* ``serve_window`` records: periodic on the serve clock, carrying the
  sliding-window quantiles / queue / occupancy / pool state and the
  ``serve_anomaly`` section, validator-clean, SKIP-honest;
* the anomaly layer in isolation (scripted inputs, no engine):
  straggler decode steps vs the rolling median, queue-buildup and
  SLO-burn flags, free-list leak accounting;
* the zero-recompile contract WITH telemetry attached (both jit caches
  stay at 1 — the acceptance witness) and the measured overhead: the
  per-step hook cost is under 1% of a measured serve step;
* ``monitor report --serve-timeline`` renders the lifecycle + window
  trail; ``tools/validate_metrics.py --serve-window`` forced dispatch
  and content dispatch on the new kinds (drift tests).
"""

import json
import os
import sys
import time

import jax.random as jr
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.monitor import report as monitor_report
from apex_tpu.serving import (
    BlockAllocator,
    Request,
    Scheduler,
    ServeTelemetry,
    ServingEngine,
)

K = jr.PRNGKey(23)


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig(vocab_size=97, max_seq_len=128, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    attention_impl="flash", remat=False, dropout=0.0)
    model = GPTModel(cfg)
    return model, model.init(K)


def _serve_with_stream(tmp_path, tiny, reqs, *, window_s=0.0, name="ev",
                       **tel_kw):
    """Run a real serve with monitoring on; returns (records, tel,
    engine, scheduler)."""
    model, params = tiny
    path = tmp_path / f"{name}.jsonl"
    monitor.enable(str(path))
    try:
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=16, max_seq_len=64)
        tel = ServeTelemetry(slots=2, window_s=window_s, **tel_kw)
        sched = eng.make_scheduler()
        done = eng.serve(params, reqs, scheduler=sched, telemetry=tel)
        assert len(done) == len(reqs)
    finally:
        monitor.disable()
    lines = path.read_text().splitlines()
    assert monitor.validate_jsonl(lines) == []
    return [json.loads(ln) for ln in lines], tel, eng, sched


class TestLifecycleStream:
    def test_event_sequence_and_payloads(self, tmp_path, tiny):
        """One request, prompt long enough for 2 chunks: the stream
        holds the full transition sequence in order with the right
        payload fields, and every record passes the schema."""
        prompt = np.asarray(jr.randint(jr.fold_in(K, 1), (20,), 0, 97),
                            np.int32)
        reqs = [Request(rid=7, prompt=prompt, max_new_tokens=4)]
        records, tel, eng, _ = _serve_with_stream(tmp_path, tiny, reqs)
        ev = [r for r in records if r.get("kind") == "serve_event"
              and r.get("rid") == 7]
        phases = [r["phase"] for r in ev]
        assert phases == ["submit", "admit", "prefill_chunk",
                          "prefill_chunk", "first_token", "decode",
                          "finish"]
        by = {r["phase"]: r for r in ev}
        assert by["submit"]["prompt_len"] == 20
        assert by["submit"]["max_new_tokens"] == 4
        assert by["admit"]["queue_wait_ms"] >= 0
        assert by["admit"]["slot"] in (0, 1)
        # chunk indices + blocks held grow with the live frontier
        chunks = [r for r in ev if r["phase"] == "prefill_chunk"]
        assert [c["chunk"] for c in chunks] == [0, 1]
        assert chunks[0]["dur_ms"] > 0
        assert chunks[-1]["blocks_held"] >= chunks[0]["blocks_held"] >= 1
        ft = by["first_token"]
        assert ft["chunks"] == 2 and ft["ttft_ms"] > 0
        assert ft["prefill_ms"] == pytest.approx(
            sum(c["dur_ms"] for c in chunks), abs=0.01)
        fin = by["finish"]
        assert fin["tokens"] == 4
        assert fin["decode_ms"] >= 0 and fin["total_ms"] >= fin["decode_ms"]
        # transitions are ordered on the serve clock and step-stamped
        at = [r["at_s"] for r in ev]
        assert at == sorted(at)
        assert all("step" in r for r in ev
                   if r["phase"] not in ("submit", "admit"))
        # cumulative histograms fed: 1 TTFT + 3 inter-token gaps
        assert tel.ttft_ms.count == 1
        assert tel.itl_ms.count == 3

    def test_queue_wait_covers_held_admission(self, tmp_path, tiny):
        """Three requests onto 2 slots: the third's admit event carries
        the wait it actually spent queued, and the admission-blocked-by
        slots counter saw the pressure."""
        rng = np.random.default_rng(0)
        reqs = [Request(rid=i, prompt=np.asarray(
            rng.integers(0, 97, 12), np.int32), max_new_tokens=6)
            for i in range(3)]
        records, tel, _, _ = _serve_with_stream(tmp_path, tiny, reqs)
        admits = {r["rid"]: r for r in records
                  if r.get("kind") == "serve_event"
                  and r.get("phase") == "admit"}
        assert set(admits) == {0, 1, 2}
        assert admits[2]["queue_wait_ms"] > admits[0]["queue_wait_ms"]
        assert tel.admission_blocked_slots > 0
        assert tel.queue_peak >= 1


class TestServeWindows:
    def test_windows_emit_and_validate(self, tmp_path, tiny):
        """A tiny window period forces several serve_window records:
        each is schema-valid, carries the anomaly section, and the
        occupancy/pool numbers are consistent with the engine."""
        rng = np.random.default_rng(1)
        reqs = [Request(rid=i, prompt=np.asarray(
            rng.integers(0, 97, 10), np.int32), max_new_tokens=8)
            for i in range(4)]
        records, tel, eng, sched = _serve_with_stream(
            tmp_path, tiny, reqs, window_s=1e-4, name="win")
        wins = [r for r in records if r.get("kind") == "serve_window"]
        assert len(wins) >= 2
        assert tel.windows_emitted == len(wins)
        for w in wins:
            assert w["status"] == "OK"
            assert 0 <= w["active_slots"] <= 2 == w["slots"]
            assert w["blocks_high_water"] <= eng.num_blocks - 1
            anom = w["serve_anomaly"]
            assert anom["leaked_blocks"] == 0
            assert "free_list_frag_pct" in anom
            # at_s: serve-clock window end, same base as request rows,
            # and consistent with the window length
            assert w["at_s"] >= w["window_s"] > 0
        # the first window's clock was primed BEFORE the first work:
        # its span covers everything from serve start, so summing
        # window token counts over window seconds can never exceed the
        # run's true rate by construction
        assert wins[0]["at_s"] == pytest.approx(wins[0]["window_s"],
                                                rel=0.5)
        # the windows ride the same stream as the lifecycle records —
        # the whole file already passed validate_jsonl in the helper

    def test_skip_windows_carry_reason(self, tmp_path, tiny):
        reqs = [Request(rid=0, prompt=np.zeros(8, np.int32),
                        max_new_tokens=6)]
        records, _, _, _ = _serve_with_stream(
            tmp_path, tiny, reqs, window_s=1e-4, name="skipwin",
            status="SKIP", reason="cpu harness run")
        wins = [r for r in records if r.get("kind") == "serve_window"]
        assert wins and all(w["status"] == "SKIP"
                            and w["reason"] == "cpu harness run"
                            for w in wins)

    def test_telemetry_requires_skip_reason(self):
        with pytest.raises(ValueError, match="reason"):
            ServeTelemetry(slots=2, status="SKIP")
        with pytest.raises(ValueError, match="OK|SKIP"):
            ServeTelemetry(slots=2, status="MAYBE")


class _FakeSched:
    """Just enough Scheduler surface for scripted window/anomaly tests."""

    def __init__(self, waiting=0, active=0, allocator=None):
        self.num_waiting = waiting
        self.num_active = active
        self.allocator = allocator or BlockAllocator(8)

    def num_queued(self, now):
        return self.num_waiting


class TestAnomalyLayer:
    def test_straggler_against_rolling_median(self):
        tel = ServeTelemetry(slots=4, window_s=0.0, straggler_ratio=3.0,
                             straggler_window=8)
        for i in range(8):  # fill the rolling window at ~1 ms
            tel.on_decode_step(0.001, 4, i, i * 0.001)
        assert tel.straggler_steps == 0
        tel.on_decode_step(0.0045, 4, 8, 0.009)  # 4.5x the median
        assert tel.straggler_steps == 1
        assert tel.straggler_last_ratio == pytest.approx(4.5, rel=0.01)
        tel.on_decode_step(0.001, 4, 9, 0.010)  # back to normal
        assert tel.straggler_steps == 1
        # the median window absorbs a LEVEL SHIFT: after enough slow
        # steps they stop being anomalies (that is the point of a
        # rolling baseline)
        for i in range(10, 30):
            tel.on_decode_step(0.0045, 4, i, i * 0.001)
        before = tel.straggler_steps
        tel.on_decode_step(0.0045, 4, 30, 0.031)
        assert tel.straggler_steps == before

    def test_slo_burn_needs_sustained_breach(self):
        tel = ServeTelemetry(slots=4, window_s=0.0, slo_ttft_ms=100.0,
                             slo_burn_count=3)
        req = Request(rid=0, prompt=np.zeros(4, np.int32),
                      max_new_tokens=2)

        def first_token(rid, ttft_s):
            r = Request(rid=rid, prompt=req.prompt, max_new_tokens=2)
            tel.on_submit(r, 0.0)
            tel.on_first_token(r, 0, 1, 0, ttft_s)

        first_token(0, 0.25)   # over, run=1
        first_token(1, 0.02)   # under: run resets
        first_token(2, 0.25)
        first_token(3, 0.25)
        assert not tel.slo_burn and tel.ttft_over_slo == 3
        first_token(4, 0.25)   # third consecutive → burn
        assert tel.slo_burn

    def test_queue_buildup_flag(self):
        tel = ServeTelemetry(slots=2, window_s=1e-9)
        for i, depth in enumerate([1, 2, 4, 7]):
            tel.maybe_window(float(i + 1), _FakeSched(waiting=depth))
        assert tel.queue_buildup
        tel.maybe_window(10.0, _FakeSched(waiting=0))
        assert not tel.queue_buildup
        assert tel.queue_peak == 7

    def test_leak_detection_when_idle(self):
        alloc = BlockAllocator(8)
        alloc.allocate(3)  # held while NOTHING is active → leak
        tel = ServeTelemetry(slots=2, window_s=1e-9)
        tel.maybe_window(1.0, _FakeSched(waiting=0, active=0,
                                         allocator=alloc))
        tel.maybe_window(2.0, _FakeSched(waiting=0, active=0,
                                         allocator=alloc))
        assert tel.leaked_blocks == 3
        anom = tel.anomaly_section(alloc)
        assert anom["leaked_blocks"] == 3

    def test_queue_depth_ignores_unarrived_replay_tail(self):
        """Arrival replay submits the whole trace upfront with future
        arrival_s: queue telemetry must count only ARRIVED waiters,
        not saturate at the trace length (review finding)."""
        s = Scheduler(num_slots=1, block_size=4, max_blocks_per_slot=8,
                      allocator=BlockAllocator(40), prefill_chunk=4)
        for i in range(5):
            s.submit(Request(rid=i, prompt=np.zeros(4, np.int32),
                             max_new_tokens=2, arrival_s=float(i)))
        assert s.num_waiting == 5          # the raw replay tail
        assert s.num_queued(0.0) == 1      # only rid 0 has arrived
        assert s.num_queued(2.5) == 3
        tel = ServeTelemetry(slots=1, window_s=0.0)
        tel.maybe_window(0.0, s)
        assert tel.queue_peak == 1         # not 5

    def test_finish_path_leak_reaches_the_final_record(self):
        """The canonical leak — the finish path stops freeing blocks —
        must surface in final_fields even though the in-loop idle check
        rarely lands on a window edge (review finding): every request
        completed, so blocks still live ARE the leak."""
        alloc = BlockAllocator(10)
        alloc.allocate(4)  # what a broken _finish would leave behind
        tel = ServeTelemetry(slots=2, window_s=0.0)
        fields = tel.final_fields(alloc)
        assert fields["serve_anomaly"]["leaked_blocks"] == 4
        assert tel.leaked_blocks == 4
        # and a clean allocator reports clean
        tel2 = ServeTelemetry(slots=2, window_s=0.0)
        assert tel2.final_fields(
            BlockAllocator(10))["serve_anomaly"]["leaked_blocks"] == 0

    def test_counter_drift_is_a_leak(self):
        alloc = BlockAllocator(8)
        ids = alloc.allocate(2)
        alloc._live.discard(ids[0])  # corrupt behind the API's back
        assert alloc.leaked == 1
        tel = ServeTelemetry(slots=2, window_s=1e-9)
        tel.maybe_window(1.0, _FakeSched(active=1, allocator=alloc))
        tel.maybe_window(2.0, _FakeSched(active=1, allocator=alloc))
        assert tel.leaked_blocks == 1


class TestEngineContracts:
    def test_jit_caches_stay_one_with_telemetry(self, tmp_path, tiny):
        """The acceptance witness: churn + full telemetry (events,
        windows, histograms) and BOTH compiled programs still have
        exactly one cache entry."""
        rng = np.random.default_rng(3)
        reqs = [Request(rid=i,
                        prompt=np.asarray(rng.integers(
                            0, 97, rng.integers(1, 30)), np.int32),
                        max_new_tokens=int(rng.integers(1, 10)))
                for i in range(6)]
        records, tel, eng, sched = _serve_with_stream(
            tmp_path, tiny, reqs, window_s=1e-4, name="churn")
        assert eng.prefill_chunk._cache_size() == 1
        assert eng.decode_step._cache_size() == 1
        # every request traced its full lifecycle and the pool is clean
        fins = [r for r in records if r.get("kind") == "serve_event"
                and r.get("phase") == "finish"]
        assert {r["rid"] for r in fins} == set(range(6))
        assert sched.allocator.leaked == 0
        assert tel.finished == 6

    def test_per_step_overhead_under_one_percent(self, tiny):
        """The <1%-of-a-serve-step budget, measured: the per-step hook
        set (one on_decode_step + one observe_itl per live slot +
        maybe_window) costs well under 1% of a measured decode step —
        even on the CPU harness where steps are ~1000x faster than the
        flagship TPU config."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=16, max_seq_len=64)
        # measure a warm serve step (no telemetry, no monitor)
        reqs = [Request(rid=0, prompt=np.zeros(8, np.int32),
                        max_new_tokens=24)]
        eng.serve(params, reqs)  # warm both programs
        t0 = time.perf_counter()
        eng.serve(params, [Request(rid=1, prompt=np.zeros(8, np.int32),
                                   max_new_tokens=24)])
        step_s = (time.perf_counter() - t0) / 25  # 24 decode + prefill
        # measure the steady per-step hook cost (no sink: the histogram
        # + detector math that runs every step; lifecycle emits happen
        # once per request boundary, not per step)
        tel = ServeTelemetry(slots=2, window_s=0.5)
        sched = _FakeSched(active=2)
        n, passes = 1000, 3

        def hook_pass(base):
            t0 = time.perf_counter()
            for i in range(base, base + n):
                tel.observe_itl(0.001)
                tel.observe_itl(0.001)
                tel.on_decode_step(0.001, 2, i, i * 0.001)
                tel.maybe_window(i * 0.001, sched)
            return (time.perf_counter() - t0) / n

        t_all0 = time.perf_counter()
        hook_pass(0)  # warm the code paths
        # min-of-passes, the bench's own convention: a descheduled
        # burst on the shared CPU harness must not fail the budget
        per_step = min(hook_pass((p + 1) * n) for p in range(passes))
        assert per_step < 0.01 * step_s, (
            f"per-step telemetry {per_step*1e6:.1f}us is not <1% of a "
            f"measured {step_s*1e3:.2f}ms serve step")
        # and the tracker's own ledger agrees with the external clock
        assert tel.overhead_s <= (time.perf_counter() - t_all0) * 1.05

    def test_telemetry_false_suppresses_auto_attach(self, tmp_path,
                                                    tiny):
        """telemetry=False opts a timed baseline run out of the
        auto-attached tracker (no lifecycle records land on the
        stream), while a plain run on the same enabled registry gets
        traces for free."""
        model, params = tiny
        path = tmp_path / "optout.jsonl"
        monitor.enable(str(path))
        try:
            eng = ServingEngine(model, num_slots=2, block_size=8,
                                prefill_chunk=8, max_seq_len=64)
            eng.serve(params, [Request(rid=0,
                                       prompt=np.zeros(5, np.int32),
                                       max_new_tokens=3)],
                      telemetry=False)
            quiet = [json.loads(ln) for ln in
                     path.read_text().splitlines()]
            assert not any(r.get("kind") == "serve_event" for r in quiet)
            eng.serve(params, [Request(rid=1,
                                       prompt=np.zeros(5, np.int32),
                                       max_new_tokens=3)])
            traced = [json.loads(ln) for ln in
                      path.read_text().splitlines()]
            assert any(r.get("kind") == "serve_event" and r["rid"] == 1
                       for r in traced)
            # a REUSED scheduler with a stale tracker attached is
            # detached too (review finding: scheduler-side hooks must
            # not keep firing into the old tracker)
            tel = ServeTelemetry(slots=2, window_s=0.0)
            sched = eng.make_scheduler()
            eng.serve(params, [Request(rid=2,
                                       prompt=np.zeros(5, np.int32),
                                       max_new_tokens=3)],
                      scheduler=sched, telemetry=tel)
            tokens_before = tel.tokens
            eng.serve(params, [Request(rid=3,
                                       prompt=np.zeros(5, np.int32),
                                       max_new_tokens=3)],
                      scheduler=sched, telemetry=False)
            assert sched.telemetry is None
            assert tel.tokens == tokens_before  # no cross-contamination
        finally:
            monitor.disable()

    def test_scheduler_attached_tracker_is_adopted(self, tmp_path, tiny):
        """A tracker attached at Scheduler construction is adopted
        fully by serve() — engine-side hooks and windows included, not
        shadowed by an auto-attached one (review finding)."""
        model, params = tiny
        path = tmp_path / "adopt.jsonl"
        monitor.enable(str(path))
        try:
            eng = ServingEngine(model, num_slots=2, block_size=8,
                                prefill_chunk=8, max_seq_len=64)
            tel = ServeTelemetry(slots=2, window_s=1e-4)
            sched = Scheduler(
                num_slots=2, block_size=8,
                max_blocks_per_slot=eng.max_blocks_per_slot,
                allocator=BlockAllocator(eng.num_blocks),
                prefill_chunk=8, telemetry=tel)
            eng.serve(params, [Request(rid=0,
                                       prompt=np.zeros(6, np.int32),
                                       max_new_tokens=4)],
                      scheduler=sched)
            assert sched.telemetry is tel  # not replaced
            # engine-side wiring reached the caller's tracker
            assert tel.decode_steps > 0 and tel.windows_emitted >= 1
            assert tel.ttft_ms.count == 1
            ev = [json.loads(ln) for ln in path.read_text().splitlines()
                  if '"serve_event"' in ln]
            assert any(r["phase"] == "submit" for r in ev)
        finally:
            monitor.disable()

    def test_monitoring_off_serve_is_unchanged(self, tiny):
        """No registry, no telemetry arg: serve runs exactly as before
        (hooks are a single is-None test) and emits nothing."""
        model, params = tiny
        assert not monitor.enabled()
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        done = eng.serve(params, [Request(
            rid=0, prompt=np.zeros(5, np.int32), max_new_tokens=3)])
        assert len(done) == 1 and len(done[0].tokens) == 3


class TestReportAndValidator:
    def _stream(self, tmp_path, tiny):
        rng = np.random.default_rng(5)
        reqs = [Request(rid=i, prompt=np.asarray(
            rng.integers(0, 97, 12), np.int32), max_new_tokens=5)
            for i in range(3)]
        records, _, _, _ = _serve_with_stream(
            tmp_path, tiny, reqs, window_s=1e-4, name="rep")
        path = tmp_path / "rep.jsonl"
        return path, records

    def test_serve_timeline_rows_and_rendering(self, tmp_path, tiny):
        path, records = self._stream(tmp_path, tiny)
        tl = monitor_report.serve_timeline(records)
        assert {r["rid"] for r in tl["requests"]} == {0, 1, 2}
        row = tl["requests"][0]
        assert row["outcome"] == "finish" and row["tokens"] == 5
        assert row["ttft_ms"] > 0 and row["chunks"] == 1
        assert len(tl["windows"]) >= 1
        text = monitor_report.format_serve_timeline(tl)
        assert "rid    0" in text and "ttft" in text and "window" in text
        # the CLI flag end to end (in-process main)
        rc = monitor_report.main([
            "report", str(path), "--serve-timeline"])
        assert rc == 0

    def test_serve_timeline_folds_last_run_only(self):
        """Appended multi-run streams (rids restart at 0 per run) fold
        the LAST run only — the same meta-split rule aggregate applies
        (review finding: cross-run folding garbles lifecycle rows)."""
        reg = monitor.MetricsRegistry()

        def run(tokens):
            return [reg.emit_meta(device_kind="cpu"),
                    reg.emit("serve_event", rid=0, phase="submit",
                             at_s=0.0),
                    reg.emit("serve_event", rid=0, phase="finish",
                             at_s=1.0, tokens=tokens, slot=0, step=3)]

        records = run(5) + run(9)
        tl = monitor_report.serve_timeline(records)
        assert len(tl["requests"]) == 1
        assert tl["requests"][0]["tokens"] == 9  # the LAST run's value

    def test_format_survives_minimal_window_and_partial_rows(self):
        """A schema-valid serve_window with only the required fields
        (no at_s/t_s/queue/occupancy) and an in-flight request row must
        render with '-' placeholders, never crash or print 'None'
        (review finding)."""
        records = [
            {"kind": "serve_event", "schema": 1, "rid": 0,
             "phase": "submit", "at_s": 0.0},
            {"kind": "serve_window", "schema": 1, "status": "SKIP",
             "reason": "x", "window_s": 0.5,
             "serve_anomaly": {"straggler_steps": 0,
                               "queue_buildup": False,
                               "slo_burn": False, "leaked_blocks": 0}},
        ]
        tl = monitor_report.serve_timeline(records)
        text = monitor_report.format_serve_timeline(tl)
        assert "in-flight" in text and "None" not in text
        assert "queue -" in text and "occ -%" in text

    def test_serve_timeline_cli_refuses_bare_stream(self, tmp_path,
                                                    capsys):
        path = tmp_path / "bare.jsonl"
        reg = monitor.MetricsRegistry()
        path.write_text(json.dumps(reg.emit_meta(device_kind="cpu"))
                        + "\n")
        rc = monitor_report.main(["report", str(path),
                                  "--serve-timeline"])
        assert rc == 2
        assert "no serve_event" in capsys.readouterr().err

    def test_aggregate_carries_window_summary_and_anomalies(
            self, tmp_path, tiny):
        path, records = self._stream(tmp_path, tiny)
        summary = monitor_report.aggregate(records)
        sw = summary["serve_window"]
        assert sw["windows"] >= 1
        assert sw["serve_anomaly"]["leaked_blocks"] == 0
        rendered = monitor_report.render(summary)
        assert "serve-win" in rendered

    def test_validator_serve_window_dispatch(self, tmp_path, capsys):
        """--serve-window forced dispatch + content dispatch drift
        tests, mirroring the --serve contract."""
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        import validate_metrics
        reg = monitor.MetricsRegistry()
        anom = dict(straggler_steps=0, queue_buildup=False,
                    slo_burn=False, leaked_blocks=0)
        rec = reg.emit_serve_window(
            "SKIP", reason="no TPU", window_s=0.5, queue_depth=0,
            serve_anomaly=anom)
        good = tmp_path / "win.jsonl"
        good.write_text(json.dumps(rec) + "\n")
        assert validate_metrics.main([str(good)]) == 0
        assert validate_metrics.main(["--serve-window", str(good)]) == 0
        capsys.readouterr()
        # a stream without a serve_window record fails forced dispatch
        other = tmp_path / "other.jsonl"
        other.write_text(json.dumps(
            reg.emit_serve("SKIP", reason="no TPU")) + "\n")
        assert validate_metrics.main(["--serve-window", str(other)]) == 1
        assert "serve_window" in capsys.readouterr().err
        # content dispatch catches a malformed window (nan inside OK)
        bad = tmp_path / "bad.jsonl"
        bad_rec = dict(rec, status="OK", tokens_per_s=float("nan"))
        bad.write_text(json.dumps(bad_rec).replace("NaN", '"nan"')
                       + "\n")
        assert validate_metrics.main([str(bad)]) == 1
        # an anomaly section with junk keys is refused (schema pins it)
        weird = dict(rec, serve_anomaly=dict(anom, surprise=1))
        assert monitor.validate(weird) != []

    def test_emitter_honesty_on_windows(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit_serve_window(
                "OK", window_s=0.5, tokens_per_s=float("nan"),
                serve_anomaly=dict(straggler_steps=0, queue_buildup=False,
                                   slo_burn=False, leaked_blocks=0))
        with pytest.raises(ValueError, match="reason"):
            reg.emit_serve_window("SKIP")


class TestSchedulerTelemetrySeam:
    def test_blocked_by_blocks_vs_slots(self):
        """The admission-pressure split: a pool too tight counts
        'blocks', a full slot array counts 'slots'."""
        tel = ServeTelemetry(slots=2, window_s=0.0)
        # pool pressure under the OPTIMISTIC gate: the pool must not
        # even cover an arrived request's first prefill chunk. rid 0's
        # prefill takes both allocatable blocks; rid 1 has a free slot
        # but no headroom for its 2-block first chunk.
        s = Scheduler(num_slots=2, block_size=4, max_blocks_per_slot=16,
                      allocator=BlockAllocator(4), prefill_chunk=8,
                      telemetry=tel)
        s.submit(Request(rid=0, prompt=np.zeros(8, np.int32),
                         max_new_tokens=2))
        assert s.admit(now=0.0) == [0]
        w = s.next_prefill(0.0)
        s.note_prefill(w, sampled_token=1, now=0.0)  # 2 blocks held
        s.submit(Request(rid=1, prompt=np.zeros(8, np.int32),
                         max_new_tokens=2))
        assert s.admit(now=0.0) == []
        assert tel.admission_blocked_blocks == 1
        assert tel.admission_blocked_slots == 0
        # slot pressure: plenty of pool, no free slot
        tel2 = ServeTelemetry(slots=1, window_s=0.0)
        s2 = Scheduler(num_slots=1, block_size=4, max_blocks_per_slot=16,
                       allocator=BlockAllocator(40), prefill_chunk=8,
                       telemetry=tel2)
        for i in range(2):
            s2.submit(Request(rid=i, prompt=np.zeros(8, np.int32),
                              max_new_tokens=4))
        s2.admit(now=0.0)
        s2.admit(now=0.0)
        assert tel2.admission_blocked_slots >= 1
        assert tel2.admission_blocked_blocks == 0


class TestServingTier2Telemetry:
    """ISSUE 13: the reserved ``evict`` event goes live, the leak
    detector learns refcounted residency, TTFT splits by prefix-cache
    outcome, and the new record fields validate + render."""

    def test_evict_lifecycle_through_real_preemption(self, tmp_path,
                                                     tiny):
        """A pool sized below worst case: the engine preempts, the
        stream carries schema-valid ``evict`` records (reason, blocks
        released, re-queue position, generated count), the victim
        re-admits as ``resumed``, and --serve-timeline RENDERS the
        eviction instead of dropping it."""
        model, params = tiny
        path = tmp_path / "evict.jsonl"
        monitor.enable(str(path))
        try:
            eng = ServingEngine(model, num_slots=2, block_size=8,
                                prefill_chunk=8, max_seq_len=64,
                                num_blocks=7)
            tel = ServeTelemetry(slots=2, window_s=0.0)
            sched = eng.make_scheduler()
            rng = np.random.default_rng(0)
            reqs = [Request(rid=i,
                            prompt=np.asarray(rng.integers(0, 97, 12),
                                              np.int32),
                            max_new_tokens=14) for i in range(3)]
            done = eng.serve(params, reqs, scheduler=sched,
                             telemetry=tel)
            assert len(done) == 3
        finally:
            monitor.disable()
        assert sched.preemptions >= 1
        assert tel.preemptions == sched.preemptions
        assert tel.resumes >= 1
        lines = path.read_text().splitlines()
        assert monitor.validate_jsonl(lines) == []
        records = [json.loads(ln) for ln in lines]
        evicts = [r for r in records if r.get("kind") == "serve_event"
                  and r.get("phase") == "evict"]
        assert len(evicts) == sched.preemptions
        ev = evicts[0]
        assert ev["evict_reason"] == "pool_pressure"
        assert ev["blocks_released"] >= 1
        assert ev["requeue_pos"] == 0
        assert ev["generated"] >= 0
        # the victim re-admits flagged resumed, then re-enters decode
        readmits = [r for r in records
                    if r.get("kind") == "serve_event"
                    and r.get("phase") == "admit" and r.get("resumed")]
        assert readmits and readmits[0]["rid"] == ev["rid"]
        # --serve-timeline renders the eviction payload, not "unknown"
        timeline = monitor_report.serve_timeline(records)
        row = next(r for r in timeline["requests"]
                   if r["rid"] == ev["rid"])
        assert row["evictions"] >= 1
        assert row["evict_reason"] == "pool_pressure"
        assert row["blocks_released"] >= 1
        assert row["requeue_pos"] == 0
        assert row["outcome"] == "finish"  # it DID finish after requeue
        rendered = monitor_report.format_serve_timeline(timeline)
        assert "evict x" in rendered
        assert "pool_pressure" in rendered
        assert "requeued at 0" in rendered

    def test_evicted_without_finish_renders_evicted_outcome(self):
        recs = [
            {"kind": "serve_event", "rid": 5, "phase": "submit",
             "at_s": 0.0, "prompt_len": 8, "max_new_tokens": 4},
            {"kind": "serve_event", "rid": 5, "phase": "evict",
             "at_s": 0.5, "evict_reason": "pool_pressure",
             "blocks_released": 3, "requeue_pos": 0, "generated": 2},
        ]
        timeline = monitor_report.serve_timeline(recs)
        assert timeline["requests"][0]["outcome"] == "evicted"
        out = monitor_report.format_serve_timeline(timeline)
        assert "evicted" in out and "3 blk released" in out

    def test_warm_prefix_cache_is_not_a_leak(self):
        """The satellite fix: refcounted resident blocks while idle are
        warm capacity — the idle leak detector must subtract them, in
        the window path AND the final record; blocks live BEYOND the
        residents still flag."""
        alloc = BlockAllocator(10)
        ids = alloc.allocate(3)
        for bid in ids:
            alloc.mark_resident(bid)   # what a PrefixCache holds
        tel = ServeTelemetry(slots=2, window_s=1e-9)
        for t in (1.0, 2.0):
            tel.maybe_window(t, _FakeSched(waiting=0, active=0,
                                           allocator=alloc))
        assert tel.leaked_blocks == 0
        assert tel.final_fields(alloc)["serve_anomaly"][
            "leaked_blocks"] == 0
        # one MORE live block with no resident flag: that IS the leak
        alloc.allocate(1)
        tel2 = ServeTelemetry(slots=2, window_s=1e-9)
        for t in (1.0, 2.0):
            tel2.maybe_window(t, _FakeSched(waiting=0, active=0,
                                            allocator=alloc))
        assert tel2.leaked_blocks == 1
        tel3 = ServeTelemetry(slots=2, window_s=0.0)
        assert tel3.final_fields(alloc)["serve_anomaly"][
            "leaked_blocks"] == 1

    def test_ttft_splits_by_prefix_outcome(self):
        tel = ServeTelemetry(slots=2, window_s=0.0)
        hit = Request(rid=0, prompt=np.zeros(8, np.int32),
                      max_new_tokens=2)
        hit.prefix_hit_blocks = 2
        miss = Request(rid=1, prompt=np.zeros(8, np.int32),
                       max_new_tokens=2)
        tel.on_submit(hit, 0.0)
        tel.on_submit(miss, 0.0)
        tel.on_first_token(hit, 0, 1, 0, 0.010)    # 10 ms
        tel.on_first_token(miss, 1, 1, 0, 0.050)   # 50 ms
        assert tel.prefix_hit_requests == 1
        assert tel.prefix_miss_requests == 1
        f = tel.final_fields()
        assert f["prefix_hit_ttft_p50_ms"] < f["prefix_miss_ttft_p50_ms"]
        assert f["prefix_hit_requests"] == 1
        assert f["prefix_miss_requests"] == 1
        # and the combined histogram still carries both
        assert tel.ttft_ms.count == 2

    def test_window_and_final_fields_validate_with_tier2_keys(
            self, tmp_path, tiny):
        """The grown schemas: prefix_hit_rate / preemptions /
        recompute_tokens / blocks_resident ride serve_window records
        and the final serve record, validator-clean; a junk value in
        the new metric field still fails (drift test)."""
        reqs = [Request(rid=i,
                        prompt=np.full(18, 3 + i, np.int32),
                        max_new_tokens=4, arrival_s=0.0)
                for i in range(3)]
        records, tel, eng, sched = _serve_with_stream(
            tmp_path, tiny, reqs, window_s=1e-6, name="tier2")
        windows = [r for r in records if r.get("kind") == "serve_window"]
        assert windows
        w = windows[-1]
        assert "prefix_hit_rate" in w
        assert w["preemptions"] == sched.preemptions
        assert "recompute_tokens" in w
        assert w["blocks_resident"] == sched.allocator.num_resident
        # the final serve record construction path: emit + validate
        reg = monitor.MetricsRegistry()
        rec = reg.emit_serve(
            "OK", tokens_per_s=100.0,
            **tel.final_fields(sched.allocator, sched))
        assert monitor.validate(rec) == []
        assert rec["preemptions"] == sched.preemptions
        # drift: a junk string inside a tier-2 metric field must fail
        bad = dict(rec, prefix_hit_rate="lots")
        assert any("prefix_hit_rate" in e for e in monitor.validate(bad))
        bad2 = dict(rec, preemptions="many")
        assert any("preemptions" in e for e in monitor.validate(bad2))

    def test_readmit_queue_wait_measured_from_eviction(self, tmp_path):
        """A re-admission's queue_wait must cover the evict→re-admit
        span only — billing the prior in-slot service time as queueing
        would inflate exactly the rows preemption analysis reads."""
        path = tmp_path / "requeue.jsonl"
        monitor.enable(str(path))
        try:
            tel = ServeTelemetry(slots=2, window_s=0.0)
            req = Request(rid=0, prompt=np.zeros(8, np.int32),
                          max_new_tokens=4)
            tel.on_submit(req, 0.0)
            tel.on_admit(req, 0, 1.0)     # queued 1 s
            tel.on_evict(req, 0, 3, "pool_pressure", 0, 5, 5.0)
            tel.on_admit(req, 1, 5.25, resumed=True)  # re-queued 0.25 s
        finally:
            monitor.disable()
        admits = [json.loads(ln) for ln in path.read_text().splitlines()
                  if '"admit"' in ln]
        assert admits[0]["queue_wait_ms"] == pytest.approx(1000.0)
        assert admits[1]["queue_wait_ms"] == pytest.approx(250.0)

    def test_slo_burning_is_live_not_sticky(self):
        tel = ServeTelemetry(slots=2, window_s=0.0, slo_ttft_ms=10.0,
                             slo_burn_count=2)

        def ft(rid, s):
            r = Request(rid=rid, prompt=np.zeros(4, np.int32),
                        max_new_tokens=2)
            tel.on_submit(r, 0.0)
            tel.on_first_token(r, 0, 1, 0, s)

        ft(0, 0.5)
        ft(1, 0.5)
        assert tel.slo_burning and tel.slo_burn
        ft(2, 0.001)  # back under SLO: the LIVE signal clears,
        assert not tel.slo_burning
        assert tel.slo_burn  # ...the sticky record flag does not
