"""KV-cached decode engine tests (ISSUE 2 acceptance criteria).

The contracts under test:

* greedy decode == teacher-forced argmax of the full (non-cached) forward,
  token for token, for MHA and GQA configs at fp32 tolerance;
* prefill cache contents == the training forward's k/v activations;
* zero recompiles: ``decode_step``'s jit cache stays at ONE executable
  across >= 8 decoded tokens (stable avals + donated cache);
* the fused decode-attention op agrees with its XLA fallback (and a dense
  oracle) across GQA/MQA/MHA, ragged lengths, and dead rows;
* sampling semantics (greedy/temperature/top-k);
* ``decode`` monitor records validate through the schema and the
  ``tools/validate_metrics.py`` CLI.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.inference import DecodeEngine, jit_encoder, sample_logits
from apex_tpu.models import BertConfig, BertModel, GPTConfig, GPTModel
from apex_tpu.ops import decode_attention

K = jr.PRNGKey(7)


def _tiny_gpt(num_kv_heads=None, **over):
    kwargs = dict(vocab_size=97, max_seq_len=128, hidden_size=32,
                  num_layers=2, num_heads=4, num_kv_heads=num_kv_heads,
                  attention_impl="flash", remat=False, dropout=0.0)
    kwargs.update(over)
    cfg = GPTConfig(**kwargs)
    model = GPTModel(cfg)
    return model, model.init(K)


class TestDecodeAttentionOp:
    def _oracle(self, q, k, v, lens):
        b, h, d = q.shape
        g = h // k.shape[1]
        out = np.zeros((b, h, d), np.float32)
        for bi in range(b):
            L = int(lens[bi])
            if L == 0:
                continue
            for hi in range(h):
                s = (np.asarray(q[bi, hi], np.float32)
                     @ np.asarray(k[bi, hi // g, :L], np.float32).T
                     / np.sqrt(d))
                p = np.exp(s - s.max())
                p /= p.sum()
                out[bi, hi] = p @ np.asarray(v[bi, hi // g, :L], np.float32)
        return out

    @pytest.mark.parametrize("h_kv", [8, 2, 1])  # MHA / GQA / MQA
    def test_xla_and_kernel_match_oracle(self, h_kv):
        b, h, max_s, d = 3, 8, 256, 64
        q = jr.normal(K, (b, h, d))
        k = jr.normal(jr.fold_in(K, 1), (b, h_kv, max_s, d))
        v = jr.normal(jr.fold_in(K, 2), (b, h_kv, max_s, d))
        lens = jnp.array([5, max_s, 0], jnp.int32)
        want = self._oracle(q, k, v, lens)
        got_xla = decode_attention(q, k, v, lens, impl="xla")
        np.testing.assert_allclose(np.asarray(got_xla), want,
                                   rtol=2e-5, atol=2e-5)
        # interpret-mode Pallas runs the real kernel code path off-TPU
        got_pl = decode_attention(q, k, v, lens, impl="pallas")
        np.testing.assert_allclose(np.asarray(got_pl), want,
                                   rtol=2e-5, atol=2e-5)

    def test_shape_validation(self):
        q = jnp.zeros((2, 4, 64))
        k = jnp.zeros((2, 2, 128, 64))
        with pytest.raises(ValueError, match="lengths"):
            decode_attention(q, k, k, jnp.zeros((3,), jnp.int32))
        with pytest.raises(ValueError, match="h_kv"):
            decode_attention(q, jnp.zeros((2, 3, 128, 64)),
                             jnp.zeros((2, 3, 128, 64)),
                             jnp.zeros((2,), jnp.int32))


class TestDecodeEngine:
    @pytest.mark.parametrize("num_kv_heads", [None, 2])  # MHA and GQA
    def test_greedy_matches_teacher_forced_full_forward(self, num_kv_heads):
        model, params = _tiny_gpt(num_kv_heads)
        engine = DecodeEngine(model)
        prompt = jr.randint(jr.fold_in(K, 3), (2, 7), 0, 97)
        n = 8
        got = engine.generate(params, prompt, n)

        seq = prompt
        want = []
        for _ in range(n):
            logits = model.logits(params, seq)  # full non-cached forward
            nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            want.append(nxt)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(jnp.stack(want, 1)))

    def test_prefill_cache_matches_training_kv(self):
        """The cache after prefill holds EXACTLY the k/v activations the
        training forward computes for the prompt — layer by layer."""
        model, params = _tiny_gpt(num_kv_heads=2)
        c = model.config
        engine = DecodeEngine(model)
        prompt = jr.randint(jr.fold_in(K, 4), (2, 9), 0, 97)
        cache, _, _ = engine.prefill(params, prompt, K)
        b, s = prompt.shape

        # training-forward k/v: the same packed projection applied to each
        # block's pre-LN input, traced independently of the engine
        from apex_tpu.ops import fused_layer_norm
        x = model.embedding(params["embedding"], prompt)
        x = x + params["pos_embedding"][:s]
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            _, k, v = model._proj_qkv_bshd(layer, h_in)
            np.testing.assert_allclose(
                np.asarray(cache["k"][i, :, :, :s]),
                np.asarray(k.transpose(0, 2, 1, 3)), rtol=1e-5, atol=1e-5)
            np.testing.assert_allclose(
                np.asarray(cache["v"][i, :, :, :s]),
                np.asarray(v.transpose(0, 2, 1, 3)), rtol=1e-5, atol=1e-5)
            x, _ = model.prefill_block(layer, x)
        # and positions >= s stay zero (pre-allocated, untouched)
        assert not np.asarray(cache["k"][:, :, :, s:]).any()

    def test_decode_step_compiles_once(self):
        """Zero recompiles in steady state: stable avals + donated cache
        keep the jit cache at ONE executable across >= 8 tokens."""
        model, params = _tiny_gpt()
        engine = DecodeEngine(model)
        prompt = jr.randint(jr.fold_in(K, 5), (2, 5), 0, 97)
        cache, tok, _ = engine.prefill(params, prompt, K)
        for t in range(8):
            cache, tok, _ = engine.decode_step(
                params, cache, tok, jnp.int32(5 + t), jr.fold_in(K, t))
            assert engine.decode_step._cache_size() == 1, \
                f"decode_step re-traced at token {t}"

    def test_sampled_generation_stays_in_topk_support(self):
        model, params = _tiny_gpt()
        engine = DecodeEngine(model, temperature=0.7, top_k=3)
        prompt = jr.randint(jr.fold_in(K, 6), (2, 4), 0, 97)
        toks = engine.generate(params, prompt, 6, key=jr.fold_in(K, 60))
        # every sampled token must be one of the step's top-3 logits; replay
        # teacher-forced on the sampled sequence to check membership
        seq = prompt
        for t in range(6):
            logits = model.logits(params, seq)[:, -1]
            top3 = jax.lax.top_k(logits, 3)[1]
            for bi in range(2):
                assert int(toks[bi, t]) in np.asarray(top3[bi])
            seq = jnp.concatenate([seq, toks[:, t:t + 1]], 1)

    def test_generate_rejects_overflow_and_missing_key(self):
        model, params = _tiny_gpt()
        engine = DecodeEngine(model)  # cache = max_seq_len = 128
        prompt = jnp.zeros((1, 124), jnp.int32)
        with pytest.raises(ValueError, match="exceeds the cache"):
            engine.generate(params, prompt, 8)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.generate(params, prompt[:, :4], 0)
        hot = DecodeEngine(model, temperature=1.0)
        with pytest.raises(ValueError, match="requires a key"):
            hot.generate(params, prompt[:, :4], 2)

    def test_cache_length_must_be_128_multiple(self):
        """The fused decode kernel streams the cache in 128-column tiles;
        a non-multiple cache used to silently drop to the XLA fallback —
        now it is an eager error naming the knob, and the ROUNDING-UP
        recipe (cache past a short position table) works."""
        model, params = _tiny_gpt()  # position table = 128
        with pytest.raises(ValueError, match="max_seq_len.*multiple.*128"):
            DecodeEngine(model, max_seq_len=100)
        # the error names the rounded-up recipe value
        with pytest.raises(ValueError, match="max_seq_len=128"):
            DecodeEngine(model, max_seq_len=100)

        # a model whose position table is NOT a 128-multiple: the default
        # cache (= the table) errors, the recipe rounds the CACHE up...
        short, sparams = _tiny_gpt(max_seq_len=100)
        with pytest.raises(ValueError, match="multiple"):
            DecodeEngine(short)
        eng = DecodeEngine(short, max_seq_len=128)
        prompt = jr.randint(jr.fold_in(K, 77), (1, 5), 0, 97)
        assert eng.generate(sparams, prompt, 4).shape == (1, 4)
        # ...but generation may still not STEP past the table: positions
        # are real, the rounding slack is tiling-only
        with pytest.raises(ValueError, match="position table"):
            eng.generate(sparams, jnp.zeros((1, 90), jnp.int32), 12)
        # and the cache cannot exceed the rounded table either
        with pytest.raises(ValueError, match="position table"):
            DecodeEngine(short, max_seq_len=256)

    def test_tp_sharded_model_rejected(self):
        model = GPTModel(GPTConfig(vocab_size=64, hidden_size=32,
                                   num_layers=1, num_heads=4, tp_size=2))
        with pytest.raises(NotImplementedError, match="single-chip"):
            DecodeEngine(model)

    def test_bert_encoder_serving(self):
        cfg = BertConfig(vocab_size=50, max_seq_len=32, hidden_size=32,
                         num_layers=2, num_heads=4, remat=False)
        m = BertModel(cfg)
        p = m.init(jr.fold_in(K, 8))
        encode = jit_encoder(m)
        toks = jr.randint(jr.fold_in(K, 9), (2, 16), 0, 50)
        mask = jnp.zeros((2, 16), bool)
        h, pooled = encode(p, toks, pad_mask=mask)
        assert h.shape == (2, 16, 32) and pooled.shape == (2, 32)
        np.testing.assert_allclose(
            np.asarray(h),
            np.asarray(m.hidden_states(p, toks, pad_mask=mask)),
            rtol=1e-6, atol=1e-6)


class TestSampling:
    def test_greedy_is_argmax(self):
        logits = jr.normal(K, (3, 11))
        np.testing.assert_array_equal(
            np.asarray(sample_logits(logits)),
            np.asarray(jnp.argmax(logits, -1)))

    def test_topk_restricts_support(self):
        logits = jr.normal(jr.fold_in(K, 1), (4, 32))
        top = np.asarray(jax.lax.top_k(logits, 5)[1])
        for i in range(50):
            toks = sample_logits(logits, jr.fold_in(K, 100 + i),
                                 temperature=1.3, top_k=5)
            for bi in range(4):
                assert int(toks[bi]) in top[bi]

    def test_temperature_sharpens(self):
        """Cold sampling concentrates on the argmax."""
        logits = jnp.array([[0.0, 1.0, 2.0, 2.5]])
        cold = np.asarray(jnp.stack([
            sample_logits(logits, jr.fold_in(K, i), temperature=0.05)[0]
            for i in range(100)]))
        assert (cold == 3).mean() > 0.95

    def test_key_required_and_validation(self):
        logits = jnp.zeros((1, 4))
        with pytest.raises(ValueError, match="PRNG key"):
            sample_logits(logits, None, temperature=1.0)
        with pytest.raises(ValueError, match="temperature"):
            sample_logits(logits, K, temperature=-1.0)
        with pytest.raises(ValueError, match="top_p"):
            sample_logits(logits, K, temperature=1.0, top_p=0.0)
        with pytest.raises(ValueError, match="top_p"):
            sample_logits(logits, K, temperature=1.0, top_p=1.5)

    @staticmethod
    def _nucleus(logits, temperature, top_p):
        """Numpy oracle: the canonical sorted-cumsum nucleus (crossing
        token included) + its renormalized distribution."""
        s = np.asarray(logits, np.float64) / temperature
        order = np.argsort(-s)
        probs = np.exp(s - s.max())
        probs /= probs.sum()
        csum = np.cumsum(probs[order])
        ncut = int(np.searchsorted(csum, top_p) + 1)
        kept = order[:ncut]
        p = np.zeros_like(probs)
        p[kept] = probs[kept] / probs[kept].sum()
        return set(kept.tolist()), p

    def test_topp_support_matches_numpy_oracle(self):
        """Every sampled token lies in the oracle nucleus, and enough
        draws cover it entirely (the filter is neither looser nor
        pathologically tighter than the sorted-cumsum definition)."""
        logits = jr.normal(jr.fold_in(K, 2), (3, 64)) * 2.0
        draw = jax.jit(lambda key: sample_logits(
            logits, key, temperature=0.8, top_p=0.7))
        seen = [set() for _ in range(3)]
        for i in range(400):
            toks = np.asarray(draw(jr.fold_in(K, 300 + i)))
            for bi in range(3):
                seen[bi].add(int(toks[bi]))
        for bi in range(3):
            oracle, _ = self._nucleus(logits[bi], 0.8, 0.7)
            assert seen[bi] == oracle, (bi, seen[bi], oracle)

    def test_topp_distribution_matches_numpy_oracle(self):
        """Empirical frequencies over the nucleus track the renormalized
        oracle probabilities at fixed seeds (loose bound: 4 sigma of the
        binomial noise at n=2000)."""
        logits = jnp.asarray([[2.0, 1.5, 1.0, 0.0, -1.0, -3.0]])
        n = 2000
        draw = jax.jit(lambda key: sample_logits(
            logits, key, temperature=1.0, top_p=0.9))
        counts = np.zeros(6)
        for i in range(n):
            counts[int(draw(jr.fold_in(K, 10_000 + i))[0])] += 1
        _, p = self._nucleus(logits[0], 1.0, 0.9)
        for j in range(6):
            sigma = (p[j] * (1 - p[j]) / n) ** 0.5
            assert abs(counts[j] / n - p[j]) < 4 * sigma + 1e-9, \
                (j, counts[j] / n, p[j])

    def test_topp_composes_with_topk(self):
        """top-k restricts FIRST, the nucleus is computed over the
        restricted distribution (documented order)."""
        logits = jnp.asarray([[3.0, 2.9, 2.8, 0.0, -1.0, -2.0]])
        # top_k=2 keeps {0, 1}; top_p=0.6 over the renormalized pair
        # keeps just the head {0} (its renormalized mass ~0.52 < 0.6 ->
        # crossing token 1 included -> both; with top_p=0.5 only 0)
        for i in range(50):
            t = int(sample_logits(logits, jr.fold_in(K, 600 + i),
                                  temperature=1.0, top_k=2, top_p=0.5)[0])
            assert t == 0
        seen = set()
        for i in range(200):
            seen.add(int(sample_logits(logits, jr.fold_in(K, 800 + i),
                                       temperature=1.0, top_k=2,
                                       top_p=0.6)[0]))
        assert seen == {0, 1}


class TestDecodeMonitorRecords:
    def test_emit_decode_roundtrip_and_validation(self, tmp_path):
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            monitor.emit_meta(device_kind="cpu")
            rec = monitor.emit_decode(
                "OK", tokens_per_s=1234.5, prefill_ms=8.1, spread_pct=0.6,
                naive_tokens_per_s=100.0, vs_naive=12.3, batch=2,
                prompt_len=32, new_tokens=16)
            assert monitor.validate(rec) == []
        finally:
            monitor.disable()
        lines = path.read_text().splitlines()
        assert monitor.validate_jsonl(lines) == []
        from apex_tpu.monitor import report as monitor_report
        summary = monitor_report.aggregate(
            monitor_report.read_records(lines))
        assert summary["decode"]["tokens_per_s"] == 1234.5
        assert summary["decode"]["status"] == "OK"

    def test_ok_decode_record_with_nan_refused(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit_decode("OK", tokens_per_s=float("nan"))

    def test_skip_needs_reason_and_skip_tuples_normalize(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="reason"):
            reg.emit_decode("SKIP")
        rec = reg.emit_decode("SKIP", reason="no TPU",
                              vs_naive=("skipped", "no TPU"))
        assert rec["vs_naive"] == {"skipped": True, "reason": "no TPU"}
        assert monitor.validate(rec) == []
        # the validator enforces it too (externally produced streams):
        bare = {k: v for k, v in rec.items() if k != "reason"}
        assert any("reason" in e for e in monitor.validate(bare))


@pytest.mark.slow
class TestDecodeBenchLeg:
    def test_bench_decode_emits_valid_skip_record_off_tpu(self, tmp_path):
        """The serving bench leg end-to-end at smoke scale: off-TPU it must
        print/emit an explicit SKIP record — schema-valid, no nan — and the
        stream must pass the validator CLI."""
        root = os.path.join(os.path.dirname(__file__), "..")
        path = tmp_path / "decode.jsonl"
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   APEX_TPU_MONITOR=str(path))
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "bench.py"), "--decode"],
            capture_output=True, text=True, env=env, cwd=root, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        record = json.loads(proc.stdout.strip().splitlines()[-1])
        assert record["kind"] == "decode" and record["status"] == "SKIP"
        assert record["vs_naive"]["skipped"] is True
        assert monitor.validate(record) == []
        assert monitor.validate_jsonl(
            path.read_text().splitlines()) == []


class TestDecodeRelativeBias:
    """T5-style bucketed relative bias at decode (the decode sibling of
    the flash kernels' in-kernel bucketed bias): the query IS position
    ``len - 1``, so the kernel derives rel_pos from the length operand it
    already carries and gathers the tiny table in VMEM."""

    def _bb(self, h, scale=0.4):
        from apex_tpu.ops.attention import BucketedBias
        tab = jr.normal(jr.fold_in(K, 40), (16, h)) * scale
        return BucketedBias(tab, bidirectional=False, max_distance=64)

    @pytest.mark.pallas
    def test_kernel_matches_xla_and_flash_oracle(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.attention import flash_attention
        b, h, hkv, d, max_s = 2, 4, 2, 64, 256
        bb = self._bb(h)
        lengths = jnp.array([200, 77], jnp.int32)
        q = jr.normal(K, (b, h, d))
        k = jr.normal(jr.fold_in(K, 41), (b, hkv, max_s, d))
        v = jr.normal(jr.fold_in(K, 42), (b, hkv, max_s, d))
        with jax.default_matmul_precision("highest"):
            o_pal = decode_attention(q, k, v, lengths, bias=bb,
                                     impl="pallas")
            o_xla = decode_attention(q, k, v, lengths, bias=bb,
                                     impl="xla")
            np.testing.assert_allclose(o_pal, o_xla, rtol=1e-4, atol=1e-4)
            # oracle: the last row of full flash attention over the live
            # prefix with the SAME bucketed bias window
            for bi in range(b):
                L = int(lengths[bi])
                qf = q[bi][:, None, :]
                kf = jnp.repeat(k[bi][:, :L], h // hkv, 0)
                vf = jnp.repeat(v[bi][:, :L], h // hkv, 0)
                o_ref = flash_attention(
                    qf, kf, vf, causal=False,
                    bias=bb.shifted(L - 1, 0), impl="xla")
                np.testing.assert_allclose(o_pal[bi], o_ref[:, 0],
                                           rtol=1e-4, atol=1e-4)

    def test_validation(self):
        from apex_tpu.ops.attention import BucketedBias
        b, h, d, max_s = 1, 2, 64, 128
        q = jnp.zeros((b, h, d))
        kv = jnp.zeros((b, h, max_s, d))
        lens = jnp.ones((b,), jnp.int32)
        with pytest.raises(ValueError, match="BucketedBias"):
            decode_attention(q, kv, kv, lens, bias=jnp.zeros((h, 1, max_s)))
        with pytest.raises(ValueError, match="causal"):
            decode_attention(q, kv, kv, lens, bias=BucketedBias(
                jnp.zeros((16, h)), bidirectional=True, max_distance=64))
        with pytest.raises(ValueError, match="heads"):
            decode_attention(q, kv, kv, lens, bias=BucketedBias(
                jnp.zeros((16, h + 2)), bidirectional=False,
                max_distance=64))

    def test_engine_threads_the_hook(self):
        """A model exposing ``decode_rel_bias`` gets the bias threaded
        into every decode_block — wiring check: a ZERO table is bitwise
        a no-op vs the hook-less engine (same executable contract), a
        nonzero table changes the logits; the jit cache stays at one
        executable either way."""
        from apex_tpu.ops.attention import BucketedBias

        model, params = _tiny_gpt()
        h = model.config.num_heads

        class RelGPT(GPTModel):
            table = None

            def decode_rel_bias(self, params):
                return BucketedBias(self.table, bidirectional=False,
                                    max_distance=32)

        def run(table):
            m = RelGPT(model.config)
            m.table = table
            eng = DecodeEngine(m)
            prompt = jr.randint(jr.fold_in(K, 43), (2, 8), 0, 97)
            cache, tok, _ = eng.prefill(params, prompt, K)
            logits = []
            for t in range(4):
                cache, tok, lg = eng.decode_step(
                    params, cache, tok, jnp.int32(8 + t), K)
                logits.append(lg)
            assert eng.decode_step._cache_size() == 1
            return jnp.stack(logits)

        plain_engine = DecodeEngine(model)
        prompt = jr.randint(jr.fold_in(K, 43), (2, 8), 0, 97)
        cache, tok, _ = plain_engine.prefill(params, prompt, K)
        base = []
        for t in range(4):
            cache, tok, lg = plain_engine.decode_step(
                params, cache, tok, jnp.int32(8 + t), K)
            base.append(lg)
        base = jnp.stack(base)

        zero = run(jnp.zeros((16, h), jnp.float32))
        np.testing.assert_array_equal(np.asarray(zero), np.asarray(base))
        biased = run(jr.normal(jr.fold_in(K, 44), (16, h)) * 0.5)
        assert bool(jnp.any(jnp.abs(biased - base) > 1e-4))
