"""L1 convergence tier: opt-level cross-product with stored-baseline compare.

The TPU-framework equivalent of the reference's L1 runs
(``tests/L1/common/run_test.sh:29-90`` — opt_level x keep_batchnorm_fp32 x
loss_scale over the ImageNet example; ``tests/L1/common/compare.py:12-25`` —
per-iteration loss curves compared across runs and against committed
baselines). One pytest entry per cross-product cell; fails on curve
divergence from the fp32 baseline.
"""

import json
import os
import sys

import numpy as np
import pytest

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
import l1_harness  # noqa: E402

BASELINE_DIR = os.path.join(os.path.dirname(__file__), "L1_baselines")

# the reference's product: opt_level x keep_bn_fp32 {None,True,False} x
# loss_scale {None, 1.0, 128.0, dynamic}; trimmed of redundant cells
# (1.0 ~ None for bf16) to keep CI time sane.
OPT_LEVELS = ["O0", "O1", "O2", "O3"]
KEEP_NORMS = [None, True, False]
LOSS_SCALES = [None, 128.0, "dynamic"]


pytestmark = pytest.mark.slow

def _cells():
    for o in OPT_LEVELS:
        for kn in KEEP_NORMS:
            if o == "O1" and kn is False:
                continue  # O1 keeps norms fp32 (frontend.py:125-131)
            for ls in LOSS_SCALES:
                yield o, kn, ls


import jax  # noqa: E402

_ON_CPU = jax.default_backend() == "cpu"


def _baseline(model, opt_level="O0"):
    path = os.path.join(BASELINE_DIR, f"{model}_{opt_level}.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _check_against_fp32(rec, base, half: bool, cell_base=None):
    losses = np.asarray(rec["loss"])
    ref = np.asarray(base["loss"])
    assert np.all(np.isfinite(losses)), "loss diverged to non-finite"
    assert rec["skipped_steps"] <= 2, f"scaler skipped {rec['skipped_steps']} steps"
    if not half:
        # fp32 configs must reproduce the committed baseline closely
        np.testing.assert_allclose(losses, ref, rtol=2e-3, atol=2e-4)
        return
    if cell_base is not None and _ON_CPU:
        # per-cell committed curve: deterministic on the same platform, so
        # the comparison is TIGHT — a subtly wrong O2 master-weight update
        # moves the curve far beyond this (the r2 envelope could hide it)
        np.testing.assert_allclose(
            losses, np.asarray(cell_base["loss"]), rtol=5e-3, atol=5e-4)
    # bf16 curves track the fp32 baseline: point-wise within an envelope
    # and the training signal (net loss decrease) preserved
    denom = np.maximum(np.abs(ref), 0.05)
    assert np.max(np.abs(losses - ref) / denom) < 0.25, (
        f"curve diverged from fp32 baseline: {losses} vs {ref}"
    )
    assert losses[-1] < losses[0] * 0.9, "no convergence"


@pytest.mark.parametrize("opt_level,keep_norm,loss_scale", list(_cells()),
                         ids=lambda v: str(v))
def test_mlp_cross_product(opt_level, keep_norm, loss_scale):
    rec = l1_harness.run_config("mlp", opt_level, keep_norm, loss_scale)
    cell = (_baseline("mlp", opt_level)
            if (keep_norm, loss_scale) == (None, "dynamic") else None)
    _check_against_fp32(rec, _baseline("mlp"), half=opt_level != "O0",
                        cell_base=cell)


@pytest.mark.parametrize("opt_level", OPT_LEVELS)
def test_cnn_opt_levels(opt_level):
    # conv+SyncBN model over the dp=8 mesh (the ResNet-50 stand-in); full
    # keep_norm/loss_scale product exercised on the MLP above
    rec = l1_harness.run_config("cnn", opt_level, None, "dynamic")
    _check_against_fp32(rec, _baseline("cnn"), half=opt_level != "O0",
                        cell_base=_baseline("cnn", opt_level))


@pytest.mark.parametrize("model", ["mlp", "cnn"])
def test_fp16_strict_cell(model):
    """VERDICT r2 item 8: the strict-fp16 path (half_dtype=float16 +
    dynamic scaler) as an L1 cell — exercises the overflow skip/recover
    machinery at training scale, not just scaler unit tests. fp16's 5-bit
    exponent makes early overflows likely at the 2^16 initial scale; the
    scaler must back off and the curve still track fp32."""
    import jax.numpy as jnp

    rec = l1_harness.run_config(model, "O2", None, "dynamic",
                                half_dtype=jnp.float16)
    losses = np.asarray(rec["loss"])
    assert np.all(np.isfinite(losses))
    # skips allowed (that's the mechanism) but bounded: recovery must work
    assert rec["skipped_steps"] <= 6, rec["skipped_steps"]
    ref = np.asarray(_baseline(model)["loss"])
    denom = np.maximum(np.abs(ref), 0.05)
    # wider envelope than the bf16 cells: the scaler's initial 2^16 scale
    # overflows fp16's 5-bit exponent on the first step(s); each skip
    # delays an update and the offset compounds through adam's moments, so
    # the curve runs parallel-but-shifted to fp32 (measured max relative
    # gap ~0.31). The cell's contract is skip/recover + convergence, both
    # asserted hard above/below
    assert np.max(np.abs(losses - ref) / denom) < 0.45, (losses, ref)
    assert losses[-1] < losses[0] * 0.9, "no convergence"


def test_o0_matches_committed_baseline_exactly():
    """The determinism anchor: same platform, same seed → same curve."""
    rec = l1_harness.run_config("mlp", "O0", None, None)
    base = _baseline("mlp")
    np.testing.assert_allclose(rec["loss"], base["loss"], rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(
        rec["grad_norm"], base["grad_norm"], rtol=5e-3, atol=2e-4)


@pytest.mark.skipif(not os.environ.get("APEX_TPU_REGEN_L1"),
                    reason="baseline regeneration only on request")
def test_regenerate_baselines():
    """Regenerate committed baselines *inside* the pytest environment so
    ambient XLA flags match future comparisons exactly:

        APEX_TPU_REGEN_L1=1 pytest tests/test_l1_convergence.py -k regen
    """
    os.makedirs(BASELINE_DIR, exist_ok=True)
    for model in ("mlp", "cnn"):
        rec = l1_harness.run_config(model, "O0", None, None)
        with open(os.path.join(BASELINE_DIR, f"{model}_O0.json"), "w") as f:
            json.dump(rec, f, indent=1)
        print(f"wrote {model}_O0.json  final loss {rec['loss'][-1]:.5f}")
        # per-cell half-precision curves (default kn, dynamic scale): the
        # tight same-platform comparison targets
        for o in ("O1", "O2", "O3"):
            rec = l1_harness.run_config(model, o, None, "dynamic")
            with open(os.path.join(BASELINE_DIR, f"{model}_{o}.json"),
                      "w") as f:
                json.dump(rec, f, indent=1)
            print(f"wrote {model}_{o}.json  final loss {rec['loss'][-1]:.5f}")
