"""Test harness: a virtual 8-device CPU mesh on a single host.

This is the TPU-framework analog of the reference's ``DistributedTestBase``
(``apex/transformer/testing/distributed_test_base.py:9-60``), which spawns one
NCCL process per local GPU. JAX needs no processes: forcing 8 host-platform
devices gives every test a real 8-way mesh with real collectives.

Must set the env vars before jax initializes its backends, hence the
module-level code in conftest (imported by pytest before test modules).
"""

import os
import resource

# XLA's CPU compiler recurses deeply (LLVM + scan-transpose lowering); the
# default 8 MB thread stack is MARGINAL for the suite's biggest programs —
# the interleaved-pipeline MoE oracle segfaulted mid-suite on it (compile
# threads inherit RLIMIT_STACK as their default pthread stack size). Raise
# the soft limit before jax spawns any threads.
_soft, _hard = resource.getrlimit(resource.RLIMIT_STACK)
_want = 256 * 1024 * 1024
if _soft != resource.RLIM_INFINITY and _soft < _want:
    if _hard == resource.RLIM_INFINITY or _hard >= _want:
        resource.setrlimit(resource.RLIMIT_STACK, (_want, _hard))

# Force CPU regardless of ambient JAX_PLATFORMS (the dev box tunnels one real
# TPU chip; tests need the 8-device virtual mesh). Set APEX_TPU_TEST_ON_TPU=1
# to run the suite on real hardware instead.
if not os.environ.get("APEX_TPU_TEST_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    # APEX_TPU_VIRTUAL_DEVICES widens the harness for ad-hoc runs (e.g.
    # 16 to debug a 4-axis composition in-process). The CHECKED-IN 16-wide
    # gate does not use it: tests/test_full_composition.py spawns
    # subprocesses that set the device-count XLA flag directly (the env
    # must be set before jax initializes — a respawn is the only reliable
    # way mid-suite). Default stays 8: the suite's shapes assume it, and
    # 16 doubles every collective's cost.
    n = os.environ.get("APEX_TPU_VIRTUAL_DEVICES", "8")
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}")

import jax  # noqa: E402
import pytest  # noqa: E402

if not os.environ.get("APEX_TPU_TEST_ON_TPU"):
    # The axon site config re-selects the TPU platform after import; the
    # config update below wins over both it and JAX_PLATFORMS.
    jax.config.update("jax_platforms", "cpu")


if os.environ.get("APEX_TPU_TEST_ON_TPU"):
    # Hardware mode validates the kernels on the real chip; tests that build
    # multi-device meshes (cp/tp/dp > available chips) skip rather than fail
    # — patch mesh construction so the "not divisible" ValueError becomes a
    # skip, mirroring the reference harness shrinking/skipping world sizes
    # (distributed_test_base.py:47-50).
    from apex_tpu.parallel import mesh as _mesh_lib

    def _skip_when_starved(fn):
        def wrapped(*args, **kwargs):
            try:
                return fn(*args, **kwargs)
            except (ValueError, RuntimeError) as e:
                if "divisible" in str(e) or "cannot host" in str(e):
                    pytest.skip(
                        f"needs a bigger mesh than the {jax.device_count()} "
                        f"real device(s): {e}")
                raise
        return wrapped

    _mesh_lib.make_mesh = _skip_when_starved(_mesh_lib.make_mesh)
    _mesh_lib.initialize_model_parallel = _skip_when_starved(
        _mesh_lib.initialize_model_parallel)


@pytest.fixture
def mesh8():
    """A dp=8 mesh, the default decomposition for DP tests."""
    from apex_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.initialize_model_parallel(1, 1)
    yield m
    mesh_lib.destroy_model_parallel()


@pytest.fixture
def mesh_tp4_dp2():
    from apex_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.initialize_model_parallel(tensor_model_parallel_size=4)
    yield m
    mesh_lib.destroy_model_parallel()


@pytest.fixture(autouse=True)
def _reset_global_mesh():
    yield
    from apex_tpu.parallel import mesh as mesh_lib

    mesh_lib.destroy_model_parallel()


def assert_devices(n: int = 8):
    assert jax.device_count() >= n, f"expected >= {n} devices, got {jax.device_count()}"
