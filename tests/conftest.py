"""Test harness: a virtual 8-device CPU mesh on a single host.

This is the TPU-framework analog of the reference's ``DistributedTestBase``
(``apex/transformer/testing/distributed_test_base.py:9-60``), which spawns one
NCCL process per local GPU. JAX needs no processes: forcing 8 host-platform
devices gives every test a real 8-way mesh with real collectives.

Must set the env vars before jax initializes its backends, hence the
module-level code in conftest (imported by pytest before test modules).
"""

import os
import resource

# XLA's CPU compiler recurses deeply (LLVM + scan-transpose lowering); the
# default 8 MB thread stack is MARGINAL for the suite's biggest programs —
# the interleaved-pipeline MoE oracle segfaulted mid-suite on it (compile
# threads inherit RLIMIT_STACK as their default pthread stack size). Raise
# the soft limit before jax spawns any threads.
_soft, _hard = resource.getrlimit(resource.RLIMIT_STACK)
_want = 256 * 1024 * 1024
if _soft != resource.RLIM_INFINITY and _soft < _want:
    if _hard == resource.RLIM_INFINITY or _hard >= _want:
        resource.setrlimit(resource.RLIMIT_STACK, (_want, _hard))
    elif _hard > _soft:
        # hard cap finite but below 256 MB: raise to the cap rather than
        # skipping the raise entirely — every byte of compile-thread stack
        # helps, and the cap is the most an unprivileged process can get
        resource.setrlimit(resource.RLIMIT_STACK, (_hard, _hard))

# Force CPU regardless of ambient JAX_PLATFORMS (the dev box tunnels one real
# TPU chip; tests need the 8-device virtual mesh). Set APEX_TPU_TEST_ON_TPU=1
# to run the suite on real hardware instead.
if not os.environ.get("APEX_TPU_TEST_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    # APEX_TPU_VIRTUAL_DEVICES widens the harness for ad-hoc runs (e.g.
    # 16 to debug a 4-axis composition in-process). The CHECKED-IN 16-wide
    # gate does not use it: tests/test_full_composition.py spawns
    # subprocesses that set the device-count XLA flag directly (the env
    # must be set before jax initializes — a respawn is the only reliable
    # way mid-suite). Default stays 8: the suite's shapes assume it, and
    # 16 doubles every collective's cost.
    n = os.environ.get("APEX_TPU_VIRTUAL_DEVICES", "8")
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}")

import jax  # noqa: E402
import pytest  # noqa: E402

if not os.environ.get("APEX_TPU_TEST_ON_TPU"):
    # The axon site config re-selects the TPU platform after import; the
    # config update below wins over both it and JAX_PLATFORMS.
    jax.config.update("jax_platforms", "cpu")


if os.environ.get("APEX_TPU_TEST_ON_TPU"):
    # Hardware mode validates the kernels on the real chip; tests that build
    # multi-device meshes (cp/tp/dp > available chips) skip rather than fail
    # — patch mesh construction so the "not divisible" ValueError becomes a
    # skip, mirroring the reference harness shrinking/skipping world sizes
    # (distributed_test_base.py:47-50).
    from apex_tpu.parallel import mesh as _mesh_lib

    def _skip_when_starved(fn):
        def wrapped(*args, **kwargs):
            try:
                return fn(*args, **kwargs)
            except (ValueError, RuntimeError) as e:
                if "divisible" in str(e) or "cannot host" in str(e):
                    pytest.skip(
                        f"needs a bigger mesh than the {jax.device_count()} "
                        f"real device(s): {e}")
                raise
        return wrapped

    _mesh_lib.make_mesh = _skip_when_starved(_mesh_lib.make_mesh)
    _mesh_lib.initialize_model_parallel = _skip_when_starved(
        _mesh_lib.initialize_model_parallel)


# --- tier-1 time budget (off-TPU) --------------------------------------------
#
# The jax-version compat shims (PR 2) un-broke ~160 seed-failing tests —
# interpret-mode kernel suites and big composition oracles that now really
# RUN on the 2-core CPU harness instead of failing fast on an
# AttributeError. Honest, but the fast tier has a hard wall-clock budget
# (ROADMAP's 870 s tier-1 command): measured at 2140 s with everything in.
# The heaviest of the rescued tests (>= ~6 s each, 1400 s combined) move to
# the `slow` tier HERE, in one tunable list, rather than scattering marks
# across 12 files. They still run in the full suite (`-m ''`) and on
# hardware (`APEX_TPU_TEST_ON_TPU=1` skips this demotion — on a real TPU
# the kernels are fast). Durations from /tmp-less honest measurement, see
# PR 2.
_SLOW_OFF_TPU = {
    "tests/test_examples.py::test_imagenet_example_synthetic",
    "tests/test_entry.py::test_dryrun_multichip_respawn_path",
    "tests/test_examples.py::test_imagenet_example_prefetched_host_data",
    "tests/test_entry.py::test_dryrun_multichip_tp_only[4]",
    "tests/test_entry.py::test_dryrun_multichip_8",
    "tests/test_megatron_surface.py::TestGPTScaling::test_tp4_scaling_runs",
    "tests/test_docs.py::test_training_guide_blocks_execute_in_order",
    "tests/test_contrib.py::TestZeroFlagship::test_zero_adam_under_moe_ep[4]",
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_ep_moe[1]",
    "tests/test_moe.py::TestMoEPipelineEP::test_interleaved_v2_pp2_ep2",
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_zero[2]",
    "tests/test_moe.py::TestMoEPipelineEP::test_five_axis_ep_pp_cp_one_mesh",
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_zero[1]",
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_ep_moe[2]",
    "tests/test_enc_dec_pipeline.py::TestEncDecPipeline::test_loss_and_grads_match_serial",
    "tests/test_entry.py::test_dryrun_multichip_2",
    "tests/test_moe.py::TestMoEPipelineEP::test_pp2_ep2_dp2_matches_serial_shards",
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_cp_ring[2]",
    "tests/test_moe.py::TestGPTMoE::test_gpt_moe_through_pipeline_matches_serial",
    "tests/test_t5.py::TestRelativePositionBias::test_relative_through_pipeline_matches_serial",
    "tests/test_pipeline.py::TestGPTBlockPipeline::test_pp4_interleaved_gpt_blocks_match_serial",
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_cp_ring[1]",
    "tests/test_contrib.py::TestZeroFlagship::test_zero_adam_under_3d_pipeline",
    "tests/test_moe.py::TestExpertParallel::test_ep_matches_single_device",
    "tests/test_moe.py::TestMoEPipelineEP::test_tp2_pp2_ep2_one_mesh",
    "tests/test_gpt_pipeline.py::TestGPTPipelineParity::test_pp2_tp2_dp2_sp_full_3d",
    "tests/test_moe.py::TestDedicatedEpAxis::test_moe_on_ep_axis_matches_single_device",
    "tests/test_gpt_pipeline.py::TestContextParallelFlagship::test_pp2_cp2_dp2_pipeline",
    "tests/test_models.py::TestGPT::test_tp2_grads_match_tp1",
    "tests/test_contrib.py::TestZeroFlagship::test_zero_adam_under_gpt_tp2[4]",
    "tests/test_attention.py::TestGPTFlashDropout::test_flash_dropout_trains_and_is_keyed",
    "tests/test_models.py::TestGPT::test_tp2_matches_tp1[False]",
    "tests/test_t5.py::TestEncDecPipelineModel::test_pipeline_matches_serial[1]",
    "tests/test_t5.py::TestEncoderPadding::test_pipeline_matches_serial_padded",
    "tests/test_t5.py::TestRematPolicies::test_encode_only_matches_blocks_through_pipeline",
    "tests/test_models.py::TestGPT::test_tp2_matches_tp1[True]",
    "tests/test_examples.py::test_simple_distributed_example",
    "tests/test_gpt_pipeline.py::TestContextParallelFlagship::test_pp2_cp2_tp2_one_mesh",
    "tests/test_contrib.py::TestDistributedOptimizers::test_zero_grad_reduce_dtype_opt_out",
    "tests/test_enc_dec_pipeline.py::TestEncDecPipeline::test_uses_installed_mesh_split",
    "tests/test_gpt_pipeline.py::TestContextParallelFlagship::test_cp_with_dropout_trains_keyed[ring]",
    "tests/test_gpt_pipeline.py::TestGPTPipelineParity::test_pp2_matches_single_device[softmax]",
    "tests/test_moe.py::TestGPTMoE::test_gpt_moe_tp2_matches_tp1[False]",
    "tests/test_t5.py::TestEncDecPipelineModel::test_pipeline_matches_serial[2]",
    "tests/test_gpt_pipeline.py::TestGPTPipelinePartition::test_dropout_trains_with_distinct_masks",
    "tests/test_contrib.py::TestDistributedOptimizers::test_zero_lamb_runs_and_differs_from_adam",
    "tests/test_pipeline.py::TestPipelineSPMD::test_interleaved_matches_serial",
    "tests/test_attention.py::TestRingBshd::test_bshd_ring_pallas_bwd_matches_xla_dispatch",
    "tests/test_enc_dec_pipeline.py::TestEncDecPipeline::test_split_rank_changes_execution",
    "tests/test_attention.py::TestRingAttention::test_grouped_kv_grads_match_dense",
    "tests/test_attention.py::TestFlashBias::test_bshd_composed_gqa_varlen_dropout",
    "tests/test_transformer_tp.py::TestTP8Flagship::test_gpt_tp8_loss_and_grads_match_tp1",
    "tests/test_gpt_pipeline.py::TestGPTPipelineParity::test_pp2_interleaved_matches_single_device",
    "tests/test_gpt_pipeline.py::TestGPTPipelineParity::test_pp2_matches_single_device[flash]",
    "tests/test_contrib.py::TestDistributedOptimizers::test_zero_adam_matches_fused_adam",
    "tests/test_pipeline.py::TestPipelineSPMD::test_1f1b_loss_and_grads_match_serial",
    "tests/test_attention.py::TestFlashDropout::test_packed_fused_matches_bshd_same_seed",
    "tests/test_gpt_pipeline.py::TestContextParallelFlagship::test_gpt_cp_matches_full_sequence[ring]",
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_dropout[2]",
    "tests/test_attention.py::TestVarlenFastPath::test_packed_fused_varlen_matches_bshd",
    "tests/test_transformer_tp.py::TestColumnRowParallel::test_headwise_matches_flat_call",
    # r7 (tp-overlap PR): the ring-overlap parity matrix joins tier-1, so
    # the heaviest remaining tests with a cheaper tier-1 sibling move here
    # (same rule as above — they still run under `-m ''` and on hardware):
    # each row names the sibling that keeps the family covered in tier-1.
    # (several of the un-jitted whales were instead made ~3-10x faster by
    # jitting their interpret-mode grads — see test_attention/test_t5.)
    "tests/test_t5.py::TestBucketedRelativeBias::test_bucketed_matches_materialized_flash",  # kernel-level: TestBucketedBias::test_kernel_fwd_bwd_vs_materialized
    "tests/test_models.py::TestResNet::test_train_and_eval_modes",  # examples: test_dcgan_example; resnet fwd: TestResNet shape tests
    "tests/test_moe.py::TestGPTMoE::test_gpt_moe_tp2_matches_tp1[True]",  # sibling [False] demoted in PR 2; dense parity: test_identical_experts_match_dense_gpt
    "tests/test_inference.py::TestDecodeEngine::test_greedy_matches_teacher_forced_full_forward[None]",  # see GQA [2] row below
    "tests/test_t5.py::TestRematPolicies::test_encode_only_matches_blocks",  # pipeline variant demoted in PR 2; policy parity: TestGPTAttentionAndRematVariants
    "tests/test_t5.py::TestRelativePositionBias::test_relative_model_trains_and_bias_matters",  # parity: test_relative_flash_matches_softmax stays
    "tests/test_permutation.py::TestSearch::test_exhaustive_finds_global_optimum",  # TestGreedyVsExhaustive stays tier-1
    "tests/test_pipeline.py::TestInterleavedV3Uneven::test_v3_uneven_grads_match_serial",  # v=2/v=4 interleaved parity stays (TestPipelineSPMD fast rows)
    "tests/test_examples.py::test_dcgan_example_o2",  # test_dcgan_example (O0) stays
    "tests/test_t5.py::TestEncoderPadding::test_padded_matches_unpadded_softmax",  # flash sibling test_flash_matches_softmax_padded_grads stays
    # r7 second pass: the full suite measured 997s on this host against the
    # 870s tier-1 wall, so the heaviest remaining redundantly-covered rows
    # move here too (same contract: `-m ''` and hardware still run them;
    # each row names the sibling that keeps its family covered in tier-1):
    "tests/test_inference.py::TestDecodeEngine::test_greedy_matches_teacher_forced_full_forward[2]",  # test_prefill_cache_matches_training_kv + test_decode_step_compiles_once + TestSampling::test_greedy_is_argmax stay
    "tests/test_attention.py::TestRingAttention::test_grads_match_dense[True]",  # [False] grads + test_matches_dense_full_sequence[True] (causal fwd) stay
    "tests/test_enc_dec_pipeline.py::TestEncDecPipeline::test_forward_matches_serial[1]",  # split [3] stays
    "tests/test_enc_dec_pipeline.py::TestEncDecPipeline::test_forward_matches_serial[2]",  # split [3] stays
    "tests/test_contrib.py::TestMultiheadAttn::test_fmha_varlen_cu_seqlens",  # kernel varlen: TestVarlenAttention::test_pallas_kernel_varlen_fwd_bwd stays
    "tests/test_inference.py::TestDecodeRelativeBias::test_engine_threads_the_hook",  # test_kernel_matches_xla_and_flash_oracle stays
    "tests/test_inference.py::TestDecodeEngine::test_sampled_generation_stays_in_topk_support",  # TestSampling::test_topk_restricts_support stays
    "tests/test_docs.py::test_amp_worked_example_executes",  # test_training_guide_blocks_execute_in_order still executes every guide block
    "tests/test_contrib.py::TestZeroHardening::test_zero_bf16_allgather_converges_close",  # test_zero_bf16_params_fp32_masters + test_zero_e5m2_allgather_converges stay
    "tests/test_attention.py::TestBucketedBias::test_kernel_fwd_bwd_vs_materialized[False-True]",  # [True-False] + remaining combos stay
    "tests/test_models.py::TestResNet::test_param_count_matches_torchvision",  # TestResNet shape tests stay
    "tests/test_contrib.py::TestBottleneckConv::test_spatial_bottleneck_strided_matches_unsharded",  # unstrided test_spatial_bottleneck_matches_unsharded stays
    "tests/test_attention.py::TestGroupedQueryAttention::test_bshd_layout_kernels_match_dense[4-4-128-False]",  # gqa ratios [4-1-128] and [4-2-128] stay
    "tests/test_attention.py::TestGroupedQueryAttention::test_bshd_layout_kernels_match_dense[1-1-64-False]",  # gqa ratios [4-1-128] and [4-2-128] stay
    "tests/test_attention.py::TestFlashBias::test_kernel_fwd_bwd_vs_dense[1-False]",  # [2-False]/[2-True] stay
    "tests/test_t5.py::TestEncoderPadding::test_padded_matches_unpadded_flash",  # test_flash_matches_softmax_padded_grads stays
    "tests/test_attention.py::TestCpDropout::test_ring_dropout_grads_match_autodiff",  # bshd sibling TestRingBshd::test_bshd_ring_dropout_grads_match_autodiff stays
    "tests/test_models.py::TestGPT::test_remat_matches_no_remat",  # TestGPTAttentionAndRematVariants::test_remat_policies_identical_loss_and_grads stays
    "tests/test_attention.py::TestRingBshd::test_bshd_ring_matches_flash[2]",  # [1] stays
    "tests/test_attention.py::TestLseCarrierForms::test_sliced_vs_carrier_identical",  # bshd variant test_bshd_sliced_vs_carrier_identical stays
    "tests/test_attention.py::TestGroupedQueryAttention::test_fused_qkv_attention_matches_composition[4-True]",  # [2-True] stays
    "tests/test_contrib.py::TestTransducer::test_loss_grad_finite",  # test_loss_matches_brute_force (alignment-enumeration oracle) stays
    "tests/test_attention.py::TestVarlenFastPath::test_bshd_kernel_varlen_matches_dense[2]",  # [1] + test_bert_varlen_rides_bshd_kernels stay
    "tests/test_attention.py::TestFlashDropout::test_kernel_matches_dense_same_mask[False]",  # [True] stays
    # r8 (continuous-batching serving PR): the heavy serving sweeps move
    # here (same contract: `-m ''` and hardware still run them; each row
    # names the sibling that keeps its family covered in tier-1):
    "tests/test_serving.py::TestServeBenchLeg::test_bench_serve_emits_valid_skip_record_off_tpu",  # subprocess sweep; record/CLI contract: TestServeRecord; engine churn: test_churn_schedule_recompile_free_and_leak_free stays
    "tests/test_serving.py::TestServingEngine::test_sampled_serving_uses_fused_tail_support",  # fused-tail support: TestFusedSample::test_topk_support stays; engine wiring: greedy parity test stays
    "tests/test_serving.py::TestPagedDecodeAttention::test_paged_with_bucketed_bias",  # unbiased paged parity test_paged_matches_contiguous stays; decode bias: test_inference TestDecodeRelativeBias stays
    # r9 (zero-bubble pipeline PR): the heaviest cells of the zb
    # schedule×feature matrix move here (same contract: `-m ''` and
    # hardware still run them; each row names the sibling that keeps its
    # family covered in tier-1):
    "tests/test_pipeline.py::TestZeroBubble::test_pp2_v1[True]",  # overlap at v=1: test_recompile_free_geometry_reuse[True] + pp2_v3[True] (overlap×interleaved) stay
    "tests/test_pipeline.py::TestZeroBubble::test_pp2_v3[False]",  # blocking interleaved zb: pp2_v3[True] + test_zb_v3_uneven_layer_count stay
    "tests/test_pipeline.py::TestZeroBubble::test_pp4_v1[True]",  # pp4 zb: pp4_v1[False] stays; overlap: pp2_v3[True] stays
    "tests/test_pipeline.py::TestZeroBubble::test_pp4_v3[False]",  # deepest matrix corner: pp4_v1[False] (pp4) + pp2_v3[True] (v=3) stay
    "tests/test_pipeline.py::TestZeroBubble::test_pp4_v3[True]",  # deepest matrix corner: same siblings as above
    "tests/test_pipeline.py::TestZeroBubble::test_zb_bf16_params_accumulate_fp32_main_grad",  # 1f1b bf16 sibling + GPT-level fp32-accum zb parity (test_zb_schedule[1]) stay
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_zb_schedule[2]",  # [1] stays; interleaved zb parity: test_pipeline pp2_v3[True] stays
    "tests/test_monitor.py::TestPipelineBenchLeg::test_bench_pipeline_emits_valid_skip_record_off_tpu",  # record/validator/report contract: test_pipeline_record_emits_validates_and_reports stays
    # r10 (serving-telemetry PR): the heaviest full-engine telemetry
    # sweeps move here (same contract: `-m ''` and hardware still run
    # them; each row names the sibling that keeps its family covered in
    # tier-1):
    "tests/test_serve_telemetry.py::TestServeWindows::test_skip_windows_carry_reason",  # window emission: test_windows_emit_and_validate stays; SKIP-reason contract: test_telemetry_requires_skip_reason + TestReportAndValidator::test_emitter_honesty_on_windows stay
    "tests/test_serve_telemetry.py::TestReportAndValidator::test_aggregate_carries_window_summary_and_anomalies",  # timeline/report path: test_serve_timeline_rows_and_rendering stays; serve-record aggregation: test_serving TestServeRecord stays
    "tests/test_serve_telemetry.py::TestLifecycleStream::test_queue_wait_covers_held_admission",  # lifecycle stream: test_event_sequence_and_payloads stays; blocked-by counters: TestSchedulerTelemetrySeam::test_blocked_by_blocks_vs_slots stays (engine-free)
    # r11 (speculative-decoding PR): the heaviest full-engine spec
    # sweeps move here (same contract: `-m ''` and hardware still run
    # them; each row names the sibling that keeps its family covered
    # in tier-1):
    "tests/test_spec.py::TestServingSpec::test_churn_parity_model_drafter",  # model-drafter parity: TestDecodeEngineSpec::test_greedy_parity_both_drafters stays; serve churn parity: test_churn_parity_ngram stays
    "tests/test_spec.py::TestServingSpec::test_churn_parity_under_pool_pressure",  # preempt-during-spec rewind: TestRewindContract::test_all_rejected_round_restores_pool_state stays; plain churn parity: test_churn_parity_ngram stays
    "tests/test_spec.py::TestServingSpec::test_int8_spec_matches_int8_plain",  # int8 pool: TestQuantizedKV::test_logit_error_bounded_vs_float_oracle + test_quantized_serve_stream_is_reasonable stay; spec churn: test_churn_parity_ngram stays
    "tests/test_spec.py::TestDecodeEngineSpec::test_self_drafter_accepts_everything",  # parity: test_greedy_parity_both_drafters stays; acceptance accounting: TestServingSpec::test_spec_telemetry_events_and_acceptance stays
    "tests/test_spec.py::TestDecodeEngineSpec::test_sampled_spec_generates_within_bounds",  # sampled verify semantics: TestFusedVerify::test_kernel_matches_fallback_sampled + test_sampled_acceptance_is_exact_for_sure_things stay
    "tests/test_spec.py::TestDrafters::test_model_drafter_single_compile_across_streams",  # drafter-step cache pin: test_greedy_parity_both_drafters asserts md.engine.decode_step._cache_size() == 1
    "tests/test_spec.py::TestFusedVerify::test_kernel_handles_long_drafts[32]",  # [8] (the first broken lane width) stays tier-1; 32 is the same 128-lane block
    # r12 (TP serving PR): the heaviest tp shard_map sweeps move here
    # (same contract: `-m ''` and hardware still run them; each row
    # names the sibling that keeps its family covered in tier-1):
    "tests/test_tp_serving.py::TestTPServingParity::test_churn_schedule_bitwise_vs_tp1[4]",  # [2] (same churn schedule, same asserts) stays
    "tests/test_tp_serving.py::TestTPServingParity::test_hot_swap_under_tp",  # tp=1 swap: test_serving TestHotSwap stays; tp re-shard path: churn [2] runs _prepare_params
    "tests/test_tp_serving.py::TestTPServingParity::test_int8_pool_bitwise_vs_tp1_int8",  # int8 pool semantics: test_spec TestQuantizedKV stays; tp parity: churn [2] stays
    "tests/test_tp_serving.py::TestDisaggHandoff::test_roundtrip_token_identical[2]",  # [1] (same digest/parity asserts) stays; tp serving parity: churn [2] stays
    "tests/test_tp_serving.py::TestDecodeEngineTP::test_generate_bitwise_vs_tp1[4]",  # [2] stays
    "tests/test_tp_serving.py::TestDecodeEngineTP::test_speculative_generate_bitwise",  # serving spec under tp: TestTPServingParity::test_spec_rounds_bitwise_vs_plain stays
    # r12 second pass: with the tp shard_map sweeps in, the full suite
    # measured ~1100s on this host against the 870s tier-1 wall, so the
    # heaviest remaining redundantly-covered rows move here too (same
    # contract: `-m ''` and hardware still run them; each row names the
    # sibling that keeps its family covered in tier-1):
    "tests/test_docs.py::test_inference_api_blocks_execute_in_order",  # needle test test_inference_doc_covers_serving_contract stays; every engine claim the blocks make is a tier-1 test in test_serving/test_tp_serving; like the guide blocks, `-m ''` still executes them
    "tests/test_docs.py::test_prof_api_blocks_execute_in_order",  # test_observability_blocks_execute_in_order (capture->report->calibrate superset) stays; `-m ''` still executes the prof blocks
    "tests/test_ckpt.py::TestHotSwapFromCheckpoint::test_restore_params_swaps_token_identically",  # swap contract: test_serving TestHotSwap equal/different-weights rows stay; restore fidelity: TestShardedSameDp::test_fp32_params_ride_the_params_buffer stays
    "tests/test_ckpt.py::TestCkptBenchLeg::test_in_process_smoke",  # record/validator contract: TestCkptRecord::test_emit_and_validate_ok stays; history gating: test_bench_history_gates_save_overhead stays
    "tests/test_ckpt.py::TestShardedSameDp::test_bitwise_resume_bf16_masters",  # fp32-path bitwise restore rows (test_fp32_params_ride_the_params_buffer + TestScalerOverflowRoundtrip) stay; bf16-master semantics: test_contrib TestZeroHardening::test_zero_bf16_params_fp32_masters stays
    "tests/test_ckpt.py::TestElasticResize::test_trajectory_parity_dp8_to_dp4",  # the grow direction test_trajectory_parity_dp4_to_dp8 stays
    "tests/test_pipeline.py::TestZeroBubble::test_pp2_v1[False]",  # blocking v=1 zb: pp4_v1[False] stays; GPT-level zb parity: test_gpt_pipeline test_zb_schedule[1] stays
    "tests/test_pipeline.py::TestZeroBubble::test_per_device_work_counters_show_v2_bubble_shrink",  # counter closed form: test_zb_work_counters_closed_form[True] stays
    "tests/test_pipeline.py::TestBuildSchedule::test_end_to_end_with_calculator",  # schedule choice rows (test_picks_microbatches_and_schedule + test_interleaved_partial) stay; calculator pricing: test_plan TestCalculator rows stay
    "tests/test_monitor.py::TestProfileBenchLeg::test_bench_profile_emits_valid_skip_record_off_tpu",  # record/validator contract: TestProfileRecord::test_emit_roundtrip_and_validation stays
    "tests/test_monitor.py::TestSpans::test_overlap_ring_emits_ring_span",  # ring-collective accounting: TestTPCollectiveCounts::test_overlap_ring_ppermute_counted stays
    "tests/test_plan.py::TestPlanConsumption::test_planned_config_grad_parity_vs_hand_config",  # plan->config routing: test_gpt_config_routes_through_plan + test_make_mesh_consumes_plan stay; the underlying configs' grad parity is test_models territory
    "tests/test_trace.py::TestValidatorTrace::test_trace_family_dispatch",  # subprocess CLI sweep; schema/honesty rows (test_closed_schema_rejects_junk_key + test_nan_in_ok_record_fails_honesty) stay
    "tests/test_collective_matmul.py::TestLayerParityMatrix::test_overlap_matches_blocking[sp-3]",  # [sp-2] + GPT-level [sp] stay
    "tests/test_collective_matmul.py::TestLayerParityMatrix::test_overlap_matches_blocking[sp-4]",  # [sp-2] + GPT-level [sp] stay
    "tests/test_collective_matmul.py::TestLayerParityMatrix::test_overlap_matches_blocking[nosp-3]",  # [nosp-2] + GPT-level [nosp] stay
    "tests/test_collective_matmul.py::TestLayerParityMatrix::test_overlap_matches_blocking[nosp-4]",  # [nosp-2] + GPT-level [nosp] stay
    "tests/test_models.py::TestGPTAttentionAndRematVariants::test_gqa_flash_matches_softmax_impl",  # kernel-level GQA parity (TestGroupedQueryAttention ratios [4-1-128]/[4-2-128]) + test_attention_impls_agree stay
    "tests/test_attention.py::TestBucketedBias::test_ring_bias_and_kv_lens_match_flash",  # kernel vs materialized: test_kernel_fwd_bwd_vs_materialized[True-False] stays; ring parity: TestRingBshd::test_bshd_ring_matches_flash[1] stays
    "tests/test_attention.py::TestBucketedBias::test_bshd_composed_gqa_varlen_dropout",  # kernel vs materialized row stays; varlen+dropout composition: TestVarlenFastPath::test_bshd_varlen_with_dropout stays
    "tests/test_attention.py::TestGroupedQueryAttention::test_fused_qkv_attention_matches_composition[4-False]",  # [2-True] stays
    "tests/test_attention.py::TestGroupedQueryAttention::test_bshd_layout_kernels_match_dense[4-4-128-True]",  # gqa ratios [4-1-128] and [4-2-128] stay
    "tests/test_attention.py::TestGroupedQueryAttention::test_bshd_layout_kernels_match_dense[1-1-64-True]",  # gqa ratios [4-1-128] and [4-2-128] stay
    "tests/test_attention.py::TestFlashBias::test_kernel_fwd_bwd_vs_dense[1-True]",  # [2-False]/[2-True] stay
    "tests/test_attention.py::TestCpDropout::test_ring_dropout_deterministic_and_live",  # keyed ring dropout: TestRingBshd::test_bshd_ring_dropout_grads_match_autodiff stays
    "tests/test_t5.py::TestEncoderDecoderModel::test_trains",  # test_loss_finite_and_deterministic + causality/cross-attn rows stay; enc-dec training parity: TestEncDecPipeline stays under `-m ''`
    "tests/test_t5.py::TestEncoderPadding::test_padding_composes_with_relative_bias",  # test_flash_matches_softmax_padded_grads + test_relative_flash_matches_softmax stay
    "tests/test_moe.py::TestGPTMoE::test_gpt_moe_trains_and_surfaces_drops",  # dense parity: test_identical_experts_match_dense_gpt stays; grads: TestMoEGrads::test_grads_flow_to_experts_and_router stays
    "tests/test_moe.py::TestRouter::test_identical_experts_reduce_to_dense_mlp",  # GPT-level test_identical_experts_match_dense_gpt stays
    "tests/test_gpt_pipeline.py::TestScheduleFeatureMatrix::test_zb_overlap_p2p",  # overlap x interleaved zb: test_pipeline pp2_v3[True] stays; GPT-level zb parity: test_zb_schedule[1] stays
    "tests/test_contrib.py::TestZeroLossScaling::test_overflow_composes_with_zb_pipeline_across_dp_tp_pp",  # scaler semantics: test_fp16_grads_keep_fp32_reduction stays; zb bf16 accum: test_pipeline 1f1b bf16 row stays
    "tests/test_contrib.py::TestZeroHardening::test_zero_adam_50_step_convergence_matches_unsharded",  # test_zero_bf16_params_fp32_masters + test_zero_e5m2_allgather_converges stay
    "tests/test_contrib.py::TestMultiheadAttn::test_additive_attn_mask_fused",  # test_probs_dropout_semantics stays; kernel-level bias path: TestFlashBias [2-True] stays
    "tests/test_serving.py::TestHotSwap::test_unreached_swap_is_dropped_not_leaked",  # equal-weights + different-weights swap rows stay
    "tests/test_serve_telemetry.py::TestServingTier2Telemetry::test_window_and_final_fields_validate_with_tier2_keys",  # window validation: TestServeWindows::test_windows_emit_and_validate stays; tier-2 lifecycle: test_evict_lifecycle_through_real_preemption stays
    "tests/test_spec.py::TestDecodeEngineSpec::test_all_rejected_drafter_still_exact",  # rewind contract: TestRewindContract::test_all_rejected_round_restores_pool_state stays; parity: test_greedy_parity_both_drafters stays
    "tests/test_inference.py::TestDecodeAttentionOp::test_xla_and_kernel_match_oracle[8]",  # [1] stays
    "tests/test_ops.py::TestXentropy::test_loss_and_grad[0.0]",  # smoothing [0.1] stays
    "tests/test_transformer_tp.py::TestVocabParallelCrossEntropy::test_matches_unsharded[0.0]",  # test_grad_matches_unsharded + kernel-path [0.0]/[0.1] rows stay
    "tests/test_aux.py::TestRNN::test_shapes_and_grads[LSTM]",  # [GRU]/[mLSTM] factory rows stay
    "tests/test_megatron_surface.py::TestGPTScaling::test_width_depth_scaling[128-4]",  # [64-2] stays
    "tests/test_permutation.py::TestSearch::test_greedy_on_random_conv_net",  # TestGreedyVsExhaustive stays tier-1
    "tests/test_serving.py::TestServingTier2::test_prefix_hit_parity_and_skipped_chunks",  # prefix-cache rows test_whole_prompt_cached_recomputes_last_block + test_preemption_roundtrip_token_identical stay; hit accounting: test_tp_serving TestDisaggHandoff roundtrip [1] asserts prefix_hit_blocks
    "tests/test_t5.py::TestRelativePositionBias::test_relative_decoder_ignores_future",  # causality: TestEncoderDecoderModel::test_decoder_is_causal stays; relative-bias parity: test_relative_flash_matches_softmax stays
    "tests/test_docs.py::test_ckpt_api_blocks_execute_in_order",  # needle test test_ckpt_doc_covers_the_contract stays; `-m ''` still executes the blocks
    "tests/test_trace.py::TestAttribution::test_emitted_record_validates",  # test_components_sum_to_e2e_on_mixed_run stays; record validation: TestValidatorTrace junk/nan rows stay
    "tests/test_attention.py::TestRingBshd::test_bshd_ring_grads_match_flat_ring",  # test_bshd_ring_matches_flash[1] + the bshd ring dropout grads row stay
    "tests/test_models.py::TestBert::test_flash_impl_matches_softmax_on_suffix_padding",  # kernel-level bert padding path: test_attention test_bert_varlen_rides_bshd_kernels stays
    "tests/test_gpt_pipeline.py::TestGPTPipelinePartition::test_dropout_requires_key",  # keyed-dropout contract: test_dropout_interleaved_schedule stays
    "tests/test_attention.py::TestUlyssesAttention::test_matches_dense_full_sequence[False]",  # ulysses grads row test_grads_match_dense stays
}


def pytest_collection_modifyitems(config, items):
    if os.environ.get("APEX_TPU_TEST_ON_TPU"):
        return
    for item in items:
        if item.nodeid in _SLOW_OFF_TPU:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def mesh8():
    """A dp=8 mesh, the default decomposition for DP tests."""
    from apex_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.initialize_model_parallel(1, 1)
    yield m
    mesh_lib.destroy_model_parallel()


@pytest.fixture
def mesh_tp4_dp2():
    from apex_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.initialize_model_parallel(tensor_model_parallel_size=4)
    yield m
    mesh_lib.destroy_model_parallel()


@pytest.fixture(autouse=True)
def _reset_global_mesh():
    yield
    from apex_tpu.parallel import mesh as mesh_lib

    mesh_lib.destroy_model_parallel()


def assert_devices(n: int = 8):
    assert jax.device_count() >= n, f"expected >= {n} devices, got {jax.device_count()}"
