"""tools/bench_history.py — the trajectory regression gate (ISSUE 10
satellite): tolerance-bounded tokens/s comparison against the
checked-in ``BENCH_r*.json`` artifacts, one-line verdicts, SKIP-record
honesty, and the off-TPU schema-only smoke over the REAL repo history.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import bench_history  # noqa: E402

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _hist(tmp_path, rounds):
    """Write BENCH_r<N>.json driver envelopes into tmp_path."""
    for n, (value, spread) in enumerate(rounds, 1):
        payload = {"parsed": {"metric": "m_tok", "value": value,
                              "unit": "tokens/s/chip",
                              "spread_pct": spread}}
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(
            json.dumps(payload))


def _fresh(tmp_path, value, spread=0.1, name="fresh.json"):
    p = tmp_path / name
    p.write_text(json.dumps({"metric": "m_tok", "value": value,
                             "unit": "tokens/s/chip",
                             "spread_pct": spread}))
    return str(p)


class TestGate:
    def test_in_tolerance_passes(self, tmp_path, capsys):
        _hist(tmp_path, [(100.0, 0.5), (110.0, 0.5)])
        rc = bench_history.main([_fresh(tmp_path, 108.0),
                                 "--root", str(tmp_path)])
        assert rc == 0
        assert "OK m_tok" in capsys.readouterr().out

    def test_regression_fails_with_one_line_diff(self, tmp_path, capsys):
        _hist(tmp_path, [(100.0, 0.5), (110.0, 0.5)])
        rc = bench_history.main([_fresh(tmp_path, 90.0),
                                 "--root", str(tmp_path)])
        assert rc == 1
        out = capsys.readouterr().out.strip()
        assert out.count("\n") == 0  # ONE line
        assert out.startswith("REGRESSION m_tok")
        assert "BENCH_r02.json" in out and "-18.18%" in out

    def test_compares_latest_not_best(self, tmp_path):
        """The trajectory's newest point is the reference — an old
        outlier round must not move the bar."""
        _hist(tmp_path, [(140.0, 0.5), (110.0, 0.5)])
        assert bench_history.main([_fresh(tmp_path, 108.0),
                                   "--root", str(tmp_path)]) == 0

    def test_spread_widens_the_band(self, tmp_path):
        _hist(tmp_path, [(110.0, 4.0)])  # noisy history
        # 8% down: outside tol 3% alone, inside 3 + 4 + 2
        assert bench_history.main([_fresh(tmp_path, 101.2, spread=2.0),
                                   "--root", str(tmp_path)]) == 0
        assert bench_history.main([_fresh(tmp_path, 99.0, spread=0.0),
                                   "--root", str(tmp_path),
                                   "--tolerance-pct", "1"]) == 1

    def test_round_ordering_is_numeric(self, tmp_path):
        """r10 is newer than r9 (lexicographic sort would invert)."""
        for n, v in [(9, 100.0), (10, 200.0)]:
            (tmp_path / f"BENCH_r{n}.json").write_text(json.dumps(
                {"parsed": {"metric": "m_tok", "value": v,
                            "unit": "u", "spread_pct": 0.0}}))
        assert bench_history.main([_fresh(tmp_path, 100.0),
                                   "--root", str(tmp_path)]) == 1

    def test_serve_record_and_skip_honesty(self, tmp_path, capsys):
        """Monitor records gate too — and a SKIP record claims nothing,
        so it can never regress."""
        hist = tmp_path / "BENCH_r01.json"
        hist.write_text(json.dumps(
            {"kind": "serve", "schema": 1, "status": "OK",
             "tokens_per_s": 5000.0}))
        fresh = tmp_path / "serve.json"
        fresh.write_text(json.dumps(
            {"kind": "serve", "schema": 1, "status": "OK",
             "tokens_per_s": 3000.0}))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        assert rc == 1
        assert "serve_tokens_per_s" in capsys.readouterr().out
        skip = tmp_path / "skip.json"
        skip.write_text(json.dumps(
            {"kind": "serve", "schema": 1, "status": "SKIP",
             "reason": "no TPU", "tokens_per_s": 1.0}))
        rc = bench_history.main([str(skip), "--root", str(tmp_path)])
        assert rc == 0
        assert "SKIP" in capsys.readouterr().out

    def test_jsonl_stream_uses_last_record(self, tmp_path):
        _hist(tmp_path, [(100.0, 0.0)])
        stream = tmp_path / "run.jsonl"
        stream.write_text(
            json.dumps({"kind": "meta", "schema": 1}) + "\n"
            + json.dumps({"metric": "m_tok", "value": 99.0,
                          "unit": "u"}) + "\n")
        assert bench_history.main([str(stream),
                                   "--root", str(tmp_path)]) == 0

    def test_no_matching_history_is_skip(self, tmp_path, capsys):
        _hist(tmp_path, [(100.0, 0.0)])
        fresh = tmp_path / "other.json"
        fresh.write_text(json.dumps({"metric": "other", "value": 1.0,
                                     "unit": "u"}))
        assert bench_history.main([str(fresh),
                                   "--root", str(tmp_path)]) == 0
        assert "SKIP" in capsys.readouterr().out

    def test_unreadable_fresh_is_usage_error(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert bench_history.main([str(bad),
                                   "--root", str(tmp_path)]) == 2


class TestTier1Smoke:
    def test_schema_only_over_real_repo_history(self, tmp_path, capsys):
        """The off-TPU tier-1 smoke the ISSUE wires in: the gate's
        plumbing (extraction + shared monitor schema) validates the
        REAL checked-in BENCH_r*.json trajectory, no throughput claim
        involved."""
        fresh = _fresh(tmp_path, 1.0)
        rc = bench_history.main(["--schema-only", fresh, "--root", ROOT])
        assert rc == 0
        assert "SCHEMA-ONLY OK" in capsys.readouterr().out

    def test_real_history_extracts_a_trajectory(self):
        rows = bench_history.collect_history("BENCH_r*.json", ROOT)
        assert len(rows) >= 4  # r02..r05 share the flagship metric
        metrics = {m for _, m, _, _ in rows}
        assert "gpt_medium_train_step_throughput" in metrics
        values = [v for _, m, v, _ in rows
                  if m == "gpt_medium_train_step_throughput"]
        assert all(v > 0 for v in values)

    def test_schema_only_catches_a_broken_artifact(self, tmp_path):
        bad = tmp_path / "fresh.json"
        bad.write_text(json.dumps({"metric": "m", "unit": "u"}))  # no value
        assert bench_history.main(["--schema-only", str(bad),
                                   "--root", str(tmp_path)]) == 2

    def test_schema_only_truncated_history_is_exit_2_not_traceback(
            self, tmp_path, capsys):
        """A killed run's half-written artifact must produce one
        diagnostic line and exit 2, never a traceback (review
        finding: CI keys on exit 2 = broken artifact)."""
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps({"metric": "m_tok", "value": 1.0,
                                     "unit": "u"}))
        (tmp_path / "BENCH_r01.json").write_text('{"parsed": {"met')
        rc = bench_history.main(["--schema-only", str(fresh),
                                 "--root", str(tmp_path)])
        assert rc == 2
        assert "unreadable" in capsys.readouterr().err

    def test_jsonl_stream_prefers_last_claim_record(self, tmp_path):
        """A telemetry stream trailing with windows/meta after the
        serve record still extracts the claim record."""
        _hist(tmp_path, [(100.0, 0.0)])
        stream = tmp_path / "run.jsonl"
        stream.write_text(
            json.dumps({"metric": "m_tok", "value": 99.5,
                        "unit": "u"}) + "\n"
            + json.dumps({"kind": "meta", "schema": 1}) + "\n"
            + json.dumps({"kind": "serve_window", "schema": 1,
                          "status": "SKIP", "reason": "x",
                          "window_s": 0.5}) + "\n")
        assert bench_history.main([str(stream),
                                   "--root", str(tmp_path)]) == 0


class TestPrefixHitLatencySeries:
    """ISSUE 13 satellite: an OK serve record's prefix_hit_ttft_p50_ms
    gates as a LOWER-is-better series next to its throughput."""

    def _serve(self, tok, hit_ms=None, status="OK"):
        rec = {"kind": "serve", "schema": 1, "status": status,
               "tokens_per_s": tok}
        if status == "SKIP":
            rec["reason"] = "no TPU"
        if hit_ms is not None:
            rec["prefix_hit_ttft_p50_ms"] = hit_ms
        return rec

    def test_extract_all_carries_both_series(self):
        rows = bench_history.extract_all(self._serve(5000.0, 12.0))
        assert ("serve_tokens_per_s", 5000.0, 0.0) in rows
        assert ("serve_prefix_hit_ttft_p50_ms", 12.0, 0.0) in rows
        # pre-tier-2 records (no hit field) carry throughput only
        assert bench_history.extract_all(self._serve(5000.0)) == [
            ("serve_tokens_per_s", 5000.0, 0.0)]
        # a skip OBJECT (no hit landed) is not a number: not gated
        rec = self._serve(5000.0)
        rec["prefix_hit_ttft_p50_ms"] = {"skipped": True,
                                         "reason": "no hits"}
        assert len(bench_history.extract_all(rec)) == 1
        # extract() still returns the PRIMARY claim
        assert bench_history.extract(self._serve(5000.0, 12.0))[0] == \
            "serve_tokens_per_s"

    def test_hit_ttft_drift_up_is_a_regression(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._serve(5000.0, 10.0)))
        fresh = tmp_path / "fresh.json"
        # throughput holds, hit TTFT +50%: lower-is-better fails
        fresh.write_text(json.dumps(self._serve(5000.0, 15.0)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "OK serve_tokens_per_s" in out
        assert "REGRESSION serve_prefix_hit_ttft_p50_ms" in out
        # faster hits (drift DOWN) are an improvement, not a regression
        fresh.write_text(json.dumps(self._serve(5000.0, 7.0)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        assert rc == 0
        assert "OK serve_prefix_hit_ttft_p50_ms" in \
            capsys.readouterr().out

    def test_throughput_regression_still_gates_with_both(self, tmp_path,
                                                         capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._serve(5000.0, 10.0)))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._serve(3000.0, 10.0)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSION serve_tokens_per_s" in out
        assert "OK serve_prefix_hit_ttft_p50_ms" in out

    def test_skip_record_still_claims_nothing(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._serve(5000.0, 10.0)))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(
            self._serve(1.0, 99999.0, status="SKIP")))
        assert bench_history.main([str(fresh),
                                   "--root", str(tmp_path)]) == 0
        assert "SKIP" in capsys.readouterr().out

    def test_no_hit_history_is_skip_for_that_series_only(self, tmp_path,
                                                         capsys):
        """Fresh record carries the new series but the trajectory
        predates it: the latency series SKIPs, throughput still
        gates."""
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._serve(5000.0)))  # pre-tier-2 history
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._serve(4950.0, 12.0)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "OK serve_tokens_per_s" in out
        assert "SKIP: no history artifact carries metric " \
            "'serve_prefix_hit_ttft_p50_ms'" in out


class TestSpecSeries:
    """ISSUE 15 satellite: an OK spec record gates its per-request
    throughput (higher-is-better) AND its acceptance rate as a tracked
    series; pre-spec history artifacts SKIP the new series only."""

    def _spec(self, tps, rate=None, status="OK", spread=0.0):
        rec = {"kind": "spec", "schema": 1, "status": status,
               "tokens_per_s_request": tps, "spread_pct": spread}
        if status == "SKIP":
            rec["reason"] = "no TPU"
        if rate is not None:
            rec["acceptance_rate"] = rate
        return rec

    def test_extract_all_carries_both_series(self):
        rows = bench_history.extract_all(self._spec(900.0, 0.8))
        assert ("spec_tokens_per_s_request", 900.0, 0.0) in rows
        assert ("spec_acceptance_rate", 0.8, 0.0) in rows
        # the per-request throughput is the PRIMARY claim
        assert bench_history.extract(self._spec(900.0, 0.8))[0] == \
            "spec_tokens_per_s_request"
        # a rate that rode as a skip object is not gated
        rec = self._spec(900.0)
        rec["acceptance_rate"] = {"skipped": True, "reason": "no rounds"}
        assert bench_history.extract_all(rec) == [
            ("spec_tokens_per_s_request", 900.0, 0.0)]

    def test_ok_record_without_throughput_is_an_error(self):
        with pytest.raises(ValueError, match="tokens_per_s_request"):
            bench_history.extract_all(
                {"kind": "spec", "schema": 1, "status": "OK"})

    def test_throughput_regression_fails(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._spec(1000.0, 0.8)))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._spec(800.0, 0.8)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSION spec_tokens_per_s_request" in out
        assert "OK spec_acceptance_rate" in out

    def test_acceptance_collapse_fails(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._spec(1000.0, 0.8)))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._spec(1000.0, 0.4)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "OK spec_tokens_per_s_request" in out
        assert "REGRESSION spec_acceptance_rate" in out

    def test_skip_record_claims_nothing(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(self._spec(1000.0, 0.8)))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._spec(1.0, 0.01, status="SKIP")))
        assert bench_history.main([str(fresh),
                                   "--root", str(tmp_path)]) == 0
        assert "SKIP" in capsys.readouterr().out

    def test_pre_spec_history_skips_the_new_series_only(self, tmp_path,
                                                        capsys):
        """The REAL upgrade path: the checked-in trajectory predates
        the spec leg entirely — a fresh OK spec record must SKIP both
        of its series (exit 0), while a flagship artifact in the same
        history still gates its own metric (regression-tested: the
        pre-spec artifacts are untouched, only the spec series are
        absent)."""
        _hist(tmp_path, [(100.0, 0.5)])  # pre-spec flagship history
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(self._spec(900.0, 0.8)))
        rc = bench_history.main([str(fresh), "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "SKIP: no history artifact carries metric " \
            "'spec_tokens_per_s_request'" in out
        assert "SKIP: no history artifact carries metric " \
            "'spec_acceptance_rate'" in out
        # the flagship series still gates against the same history
        assert bench_history.main([_fresh(tmp_path, 90.0),
                                   "--root", str(tmp_path)]) == 1
