"""Streaming-histogram contracts (ISSUE 10 tentpole b).

The acceptance anchor: quantiles from the bounded-memory log-bucket
histogram match the REMOVED sample-list ``np.percentile`` math within
one bucket width on a fixed trace — at p50 and p99, across
distributions shaped like real latency traces (lognormal tails,
bimodal prefill/decode mixes, constants). Plus the structural
contracts: bounded memory, exact min/max/mean, merge, and input
validation.
"""

import math

import numpy as np
import pytest

from apex_tpu.monitor.histogram import StreamingHistogram


def _parity(samples, ps=(50, 90, 99)):
    h = StreamingHistogram()
    for s in samples:
        h.add(float(s))
    for p in ps:
        want = float(np.percentile(samples, p))
        got = h.percentile(p)
        width = h.bucket_width(got)
        assert abs(got - want) <= width, (
            f"p{p}: histogram {got} vs sample-list {want} differ by "
            f"{abs(got - want)} > one bucket width {width}")


class TestQuantileParity:
    def test_lognormal_trace(self):
        """The canonical latency shape: heavy right tail."""
        _parity(np.random.default_rng(0).lognormal(0.5, 1.0, 2000))

    def test_bimodal_trace(self):
        """Prefill-interleave jitter: fast decode steps + slow chunks."""
        rng = np.random.default_rng(1)
        fast = rng.normal(1.0, 0.05, 1500).clip(0.5)
        slow = rng.normal(12.0, 1.0, 500).clip(8)
        _parity(np.concatenate([fast, slow]))

    def test_uniform_trace(self):
        _parity(np.random.default_rng(2).uniform(0.1, 50.0, 3000))

    def test_small_trace(self):
        _parity(np.asarray([1.0, 2.0, 3.0, 4.0, 100.0]), ps=(50,))

    def test_constant_trace_is_exact(self):
        h = StreamingHistogram()
        for _ in range(100):
            h.add(7.25)
        # min == max pins every quantile exactly (the clamp)
        for q in (0.0, 0.5, 0.99, 1.0):
            assert h.quantile(q) == 7.25

    def test_extreme_quantiles_exact(self):
        samples = np.random.default_rng(3).lognormal(0, 1, 500)
        h = StreamingHistogram()
        for s in samples:
            h.add(float(s))
        assert h.quantile(0.0) == samples.min()
        assert h.quantile(1.0) == samples.max()
        assert h.mean == pytest.approx(samples.mean(), rel=1e-9)


class TestStructure:
    def test_memory_is_bounded_by_construction(self):
        """A million-sample-scale ingest allocates nothing: the counts
        array is sized at construction."""
        h = StreamingHistogram()
        n_slots = len(h._counts)
        for i in range(10000):
            h.add(0.001 * (i + 1))
        assert len(h._counts) == n_slots
        assert h.count == 10000
        # the default geometry stays under ~1k ints
        assert n_slots < 1024

    def test_relative_width_is_uniform(self):
        h = StreamingHistogram(bins_per_decade=64)
        g = 10 ** (1 / 64)
        for v in (0.01, 1.0, 123.0, 1e5):
            low, high = h.bucket_edges(v)
            assert low <= v < high
            assert high / low == pytest.approx(g, rel=1e-9)

    def test_under_overflow_clamp_to_tracked_extremes(self):
        h = StreamingHistogram(lo=1.0, hi=10.0)
        h.add(0.25)   # underflow
        h.add(2.0)
        h.add(400.0)  # overflow
        assert h.quantile(0.0) == 0.25
        assert h.quantile(1.0) == 400.0
        assert h.count == 3

    def test_merge(self):
        rng = np.random.default_rng(4)
        a_s, b_s = rng.lognormal(0, 1, 400), rng.lognormal(1, 0.5, 600)
        a, b = StreamingHistogram(), StreamingHistogram()
        for s in a_s:
            a.add(float(s))
        for s in b_s:
            b.add(float(s))
        a.merge(b)
        both = np.concatenate([a_s, b_s])
        assert a.count == 1000
        assert a.min == both.min() and a.max == both.max()
        want = float(np.percentile(both, 99))
        got = a.quantile(0.99)
        assert abs(got - want) <= a.bucket_width(got)

    def test_reset_keeps_geometry(self):
        h = StreamingHistogram()
        for v in (0.5, 5.0, 50.0):
            h.add(v)
        h.reset()
        assert h.count == 0 and h.min is None and h.quantile(0.5) is None
        h.add(2.0)
        assert h.quantile(0.5) == 2.0 and h.count == 1

    def test_merge_rejects_geometry_mismatch(self):
        with pytest.raises(ValueError, match="geometries differ"):
            StreamingHistogram().merge(StreamingHistogram(lo=1.0))

    def test_summary_block(self):
        h = StreamingHistogram()
        assert h.summary() == {}  # empty → caller encodes explicit skip
        for v in (1.0, 2.0, 3.0):
            h.add(v)
        s = h.summary(prefix="itl_")
        assert s["itl_count"] == 3 and s["itl_max"] == 3.0
        assert s["itl_mean"] == pytest.approx(2.0)
        assert all(math.isfinite(v) for v in s.values())

    def test_validation(self):
        with pytest.raises(ValueError, match="lo < hi"):
            StreamingHistogram(lo=2.0, hi=1.0)
        with pytest.raises(ValueError, match="bins_per_decade"):
            StreamingHistogram(bins_per_decade=0)
        with pytest.raises(ValueError, match="nan"):
            StreamingHistogram().add(float("nan"))
        with pytest.raises(ValueError, match="q must be"):
            StreamingHistogram().quantile(1.5)
        h = StreamingHistogram()
        assert h.quantile(0.5) is None  # empty: no fabricated number
        h.add(1.0, n=0)  # non-positive weights are dropped
        assert h.count == 0
