"""Tests for the auxiliary tiers: fp16_utils, RNN, reparameterization,
pipeline utils, batch samplers, arguments, checkpoint, model-parallel scaler.

Mirrors the reference's ``tests/L0/run_fp16util``, RNN-cast tests,
``test_batch_sampler.py``, ``test_microbatches.py``, and the checkpointing
tests (``test_checkpointing.py``).
"""

import os

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib

K = jr.PRNGKey(77)


class TestFP16Utils:
    def test_network_to_half_and_convert(self):
        from apex_tpu.fp16_utils import convert_network, network_to_half

        params = {"w": jnp.ones((4, 4)), "bn_scale": jnp.ones((4,)),
                  "step": jnp.zeros((), jnp.int32)}
        half = network_to_half(params)
        assert half["w"].dtype == jnp.bfloat16
        assert half["bn_scale"].dtype == jnp.bfloat16
        assert half["step"].dtype == jnp.int32  # non-float untouched

        conv = convert_network(params)
        assert conv["w"].dtype == jnp.bfloat16
        assert conv["bn_scale"].dtype == jnp.float32  # BN exempt

    def test_fp16_model_wrapper(self):
        """``FP16Model`` (``apex/fp16_utils/fp16util.py:73-83``): params
        converted batchnorm-safe, floating inputs cast before the forward."""
        from apex_tpu.fp16_utils import FP16Model

        params = {"w": jnp.ones((4, 4)), "bn_scale": jnp.ones((4,))}

        def apply_fn(p, x):
            assert x.dtype == jnp.bfloat16  # inputs arrive half
            return (x @ p["w"]) * p["bn_scale"]

        model = FP16Model(apply_fn, params)
        assert model.params["w"].dtype == jnp.bfloat16
        assert model.params["bn_scale"].dtype == jnp.float32  # exempt
        y = model(jnp.ones((2, 4), jnp.float32))
        assert y.shape == (2, 4)

    def test_fp16_optimizer_step_and_overflow_skip(self):
        from apex_tpu.fp16_utils import FP16_Optimizer

        params = {"w": jnp.ones((4,), jnp.bfloat16)}
        opt = FP16_Optimizer(optax.sgd(0.1), params,
                             dynamic_loss_scale=True,
                             dynamic_loss_args=dict(init_scale=4.0, scale_window=2))
        grads = {"w": jnp.full((4,), 2.0, jnp.bfloat16) * 4.0}  # scaled by 4
        new = opt.step(grads)
        np.testing.assert_allclose(np.asarray(new["w"], np.float32), 0.8, rtol=1e-2)
        assert not opt.overflow

        # overflow: inf grads → skip + scale halves
        bad = {"w": jnp.array([jnp.inf] * 4, jnp.bfloat16)}
        before = jax.tree.map(lambda x: x, opt.master_params)
        new2 = opt.step(bad)
        assert opt.overflow
        assert opt.loss_scale == 2.0
        np.testing.assert_array_equal(new2["w"], new["w"])
        np.testing.assert_array_equal(opt.master_params["w"], before["w"])

    def test_fp16_optimizer_state_dict_roundtrip(self):
        from apex_tpu.fp16_utils import FP16_Optimizer

        params = {"w": jnp.ones((4,), jnp.bfloat16)}
        opt = FP16_Optimizer(optax.adam(0.1), params, static_loss_scale=8.0)
        opt.step({"w": jnp.ones((4,), jnp.bfloat16) * 8.0})
        sd = opt.state_dict()

        opt2 = FP16_Optimizer(optax.adam(0.1), params, static_loss_scale=8.0)
        opt2.load_state_dict(sd)
        for a, e in zip(jax.tree.leaves(opt2.master_params),
                        jax.tree.leaves(opt.master_params)):
            np.testing.assert_array_equal(a, e)


class TestRNN:
    @pytest.mark.parametrize("factory", ["LSTM", "GRU", "ReLU", "Tanh", "mLSTM"])
    def test_shapes_and_grads(self, factory):
        import apex_tpu.rnn as rnn_lib

        rnn = getattr(rnn_lib, factory)(8, 16, num_layers=2)
        params = rnn.init(K)
        x = jr.normal(jr.fold_in(K, 1), (3, 5, 8))
        y, finals = rnn(params, x)
        assert y.shape == (3, 5, 16)
        g = jax.grad(lambda p: jnp.sum(rnn(p, x)[0] ** 2))(params)
        assert all(bool(jnp.all(jnp.isfinite(l))) for l in jax.tree.leaves(g))

    def test_lstm_matches_manual_single_step(self):
        from apex_tpu.rnn import LSTMCell

        cell = LSTMCell(4, 4)
        p = cell.init(K)
        x = jr.normal(jr.fold_in(K, 2), (1, 4))
        (h, c), y = cell.step(p, cell.initial_state(1), x)
        gates = x @ p["w_ih"].T + p["b_ih"] + p["b_hh"]
        i, f, g, o = jnp.split(gates, 4, -1)
        c_ref = jax.nn.sigmoid(i) * jnp.tanh(g)
        h_ref = jax.nn.sigmoid(o) * jnp.tanh(c_ref)
        np.testing.assert_allclose(h, h_ref, atol=1e-6)

    def test_bidirectional(self):
        from apex_tpu.rnn import GRU
        from apex_tpu.rnn.backend import bidirectional

        init, apply = bidirectional(GRU(8, 16))
        params = init(K)
        y, _ = apply(params, jr.normal(K, (2, 6, 8)))
        assert y.shape == (2, 6, 32)

    def test_o1_casts_rnn_to_half(self):
        """RNN participates in the O1 cast engine — the reference's
        rnn_cast machinery (``apex/amp/wrap.py:157-265``; test:
        ``tests/L0/run_amp/test_rnn.py``): fp32 weights+inputs run the
        cells in the policy's compute dtype."""
        from apex_tpu import amp
        from apex_tpu.rnn import LSTM

        rnn = LSTM(8, 16)
        params = rnn.init(K)  # fp32
        x = jr.normal(jr.fold_in(K, 3), (2, 5, 8))  # fp32
        with amp.with_policy(amp.get_policy("O1")):
            y, _ = rnn(params, x)
        assert y.dtype == jnp.bfloat16
        y32, _ = rnn(params, x)  # no ambient policy: untouched
        assert y32.dtype == jnp.float32
        np.testing.assert_allclose(
            y.astype(jnp.float32), y32, rtol=2e-2, atol=2e-2)


class TestReparameterization:
    def test_weight_norm_roundtrip(self):
        from apex_tpu.reparameterization import (
            apply_weight_norm, remove_weight_norm,
        )

        params = {"layer": {"weight": jr.normal(K, (8, 4)), "bias": jnp.zeros(8)}}
        wn = apply_weight_norm(params)
        assert set(wn["layer"]["weight"].keys()) == {"g", "v"}
        back = remove_weight_norm(wn)
        np.testing.assert_allclose(back["layer"]["weight"],
                                   params["layer"]["weight"], rtol=1e-6)

    def test_norm_is_g(self):
        from apex_tpu.reparameterization import weight_norm_compose

        v = jr.normal(K, (4, 6))
        g = jnp.full((4, 1), 3.0)
        w = weight_norm_compose(g, v)
        np.testing.assert_allclose(jnp.linalg.norm(w, axis=1), 3.0, rtol=1e-5)


class TestPipelineUtils:
    def test_ltor_masks(self):
        from apex_tpu.transformer.pipeline_parallel.utils import (
            get_ltor_masks_and_position_ids,
        )

        tokens = jnp.array([[5, 1, 7, 9], [2, 2, 1, 3]])  # eod=1
        att, loss_mask, pos = get_ltor_masks_and_position_ids(
            tokens, eod_token=1, reset_position_ids=True,
            reset_attention_mask=True, eod_mask_loss=True,
        )
        assert att.shape == (2, 1, 4, 4)
        # loss masked at EODs
        np.testing.assert_array_equal(loss_mask, [[1, 0, 1, 1], [1, 1, 0, 1]])
        # positions reset after EOD
        np.testing.assert_array_equal(pos[0], [0, 1, 0, 1])
        # attention cannot cross document boundary: token 2 (doc 1) vs 0 (doc 0)
        assert bool(att[0, 0, 2, 0]) and bool(att[0, 0, 2, 1])
        assert not bool(att[0, 0, 3, 2])  # same doc, causal-visible

    def test_timers(self):
        from apex_tpu.transformer.pipeline_parallel.utils import get_timers

        t = get_timers()
        t("fwd").start()
        t("fwd").stop()
        log = t.log(["fwd"])
        assert "fwd" in log

    def test_report_memory_runs(self):
        from apex_tpu.transformer.pipeline_parallel.utils import report_memory

        assert isinstance(report_memory("test"), str)


class TestBatchSamplers:
    def test_sequential_rank_slices(self):
        from apex_tpu.transformer._data import MegatronPretrainingSampler

        batches_r0 = list(MegatronPretrainingSampler(
            total_samples=16, consumed_samples=0, micro_batch_size=2,
            data_parallel_rank=0, data_parallel_size=2))
        batches_r1 = list(MegatronPretrainingSampler(
            total_samples=16, consumed_samples=0, micro_batch_size=2,
            data_parallel_rank=1, data_parallel_size=2))
        assert batches_r0[0] == [0, 1] and batches_r1[0] == [2, 3]
        assert len(batches_r0) == 4
        # disjoint coverage
        seen = sorted(i for b in batches_r0 + batches_r1 for i in b)
        assert seen == list(range(16))

    def test_resume_from_consumed(self):
        from apex_tpu.transformer._data import MegatronPretrainingSampler

        b = list(MegatronPretrainingSampler(
            total_samples=16, consumed_samples=8, micro_batch_size=2,
            data_parallel_rank=0, data_parallel_size=2))
        assert b[0] == [8, 9]

    def test_random_sampler_deterministic(self):
        from apex_tpu.transformer._data import MegatronPretrainingRandomSampler

        def run():
            return list(MegatronPretrainingRandomSampler(
                total_samples=32, consumed_samples=0, micro_batch_size=2,
                data_parallel_rank=1, data_parallel_size=2, seed=7))
        assert run() == run()
        flat = [i for b in run() for i in b]
        assert all(16 <= i < 32 for i in flat)  # rank-1 bucket


class TestPrefetch:
    def test_prefetch_order_and_device(self):
        from apex_tpu.transformer._data import prefetch_to_device

        batches = [{"x": np.full((4, 3), i, np.float32)} for i in range(7)]
        out = list(prefetch_to_device(iter(batches), size=2))
        assert len(out) == 7
        for i, b in enumerate(out):
            assert isinstance(b["x"], jax.Array)
            np.testing.assert_array_equal(b["x"], batches[i]["x"])

    def test_data_parallel_iterator_shards_batch(self):
        from apex_tpu.transformer._data import data_parallel_iterator
        from apex_tpu.parallel import mesh as mesh_lib

        mesh_lib.initialize_model_parallel()
        batches = ({"x": np.arange(16 * 2, dtype=np.float32).reshape(16, 2)}
                   for _ in range(3))
        out = list(data_parallel_iterator(batches))
        assert len(out) == 3
        dp = mesh_lib.get_data_parallel_world_size()
        shard_shapes = {s.data.shape for s in out[0]["x"].addressable_shards}
        assert shard_shapes == {(16 // dp, 2)}  # 16 rows over dp

    def test_size_validation(self):
        from apex_tpu.transformer._data import prefetch_to_device

        with pytest.raises(ValueError):
            list(prefetch_to_device(iter([]), size=0))


class TestArguments:
    BASE = ["--num-layers", "4", "--hidden-size", "64",
            "--num-attention-heads", "4", "--max-position-embeddings", "128",
            "--seq-length", "128", "--micro-batch-size", "2"]

    def test_parse_and_singleton(self):
        from apex_tpu.transformer.testing import get_args, parse_args, set_args

        args = parse_args(args_list=self.BASE + [
            "--tensor-model-parallel-size", "2", "--vocab-size", "1000",
            "--world-size", "8",
        ])
        assert args.num_layers == 4
        assert args.padded_vocab_size == 1024  # padded to 128*tp
        assert args.data_parallel_size == 4   # 8 / (tp=2 * pp=1)
        set_args(args)
        assert get_args().num_layers == 4

    def test_derived_defaults(self):
        from apex_tpu.transformer.testing import parse_args

        args = parse_args(args_list=self.BASE + ["--world-size", "1"])
        assert args.ffn_hidden_size == 256          # 4*hidden
        assert args.kv_channels == 16               # hidden/heads
        assert args.encoder_seq_length == 128       # from seq-length
        assert args.global_batch_size == 2          # micro * dp

    def test_bf16_forces_fp32_grad_accumulation(self):
        import jax.numpy as jnp

        from apex_tpu.transformer.testing import parse_args

        args = parse_args(args_list=self.BASE + ["--bf16", "--world-size", "1"])
        assert args.params_dtype == jnp.bfloat16
        assert args.accumulate_allreduce_grads_in_fp32

    def test_virtual_pipeline_derivation(self):
        from apex_tpu.transformer.testing import parse_args

        args = parse_args(args_list=[
            "--num-layers", "16", "--hidden-size", "64",
            "--num-attention-heads", "4", "--max-position-embeddings", "128",
            "--seq-length", "128", "--micro-batch-size", "2",
            "--pipeline-model-parallel-size", "4",
            "--num-layers-per-virtual-pipeline-stage", "2",
            "--world-size", "8",
        ])
        assert args.virtual_pipeline_model_parallel_size == 2  # (16/4)/2

    def test_rejections(self):
        from apex_tpu.transformer.testing import parse_args

        with pytest.raises(ValueError, match="mutually exclusive"):
            parse_args(args_list=self.BASE + ["--fp16", "--bf16",
                                              "--world-size", "1"])
        with pytest.raises(ValueError, match="no longer valid"):
            parse_args(args_list=self.BASE + ["--batch-size", "4",
                                              "--world-size", "1"])
        with pytest.raises(ValueError, match="not divisible"):
            parse_args(args_list=self.BASE + [
                "--tensor-model-parallel-size", "3", "--world-size", "8"])
        with pytest.raises(ValueError, match="min lr"):
            parse_args(args_list=self.BASE + [
                "--lr", "0.001", "--min-lr", "0.01", "--world-size", "1"])


class TestCheckpoint:
    def test_train_state_roundtrip(self, tmp_path):
        from apex_tpu.checkpoint import TrainState, restore_checkpoint, save_checkpoint

        params = {"w": jr.normal(K, (4, 4)), "b": jnp.zeros((4,))}
        opt = optax.adam(1e-3)
        state = TrainState(
            step=jnp.asarray(7), params=params, opt_state=opt.init(params),
        )
        path = os.path.join(str(tmp_path), "ckpt")
        save_checkpoint(path, state)
        template = jax.tree.map(jnp.zeros_like, state)
        restored = restore_checkpoint(path, template)
        assert int(restored.step) == 7
        for a, e in zip(jax.tree.leaves(restored.params), jax.tree.leaves(params)):
            np.testing.assert_array_equal(a, e)  # bitwise

    def test_checkpoint_manager_rotation_and_async(self, tmp_path):
        """CheckpointManager: async saves land, rotation keeps max_to_keep,
        restore-latest round-trips bitwise."""
        from apex_tpu.checkpoint import CheckpointManager, TrainState

        params = {"w": jr.normal(K, (4, 4))}
        template = TrainState(step=jnp.asarray(0),
                              params=jax.tree.map(jnp.zeros_like, params),
                              opt_state=())
        with CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2) as mgr:
            for s in (1, 2, 3):
                st = TrainState(step=jnp.asarray(s),
                                params=jax.tree.map(lambda x: x * s, params),
                                opt_state=())
                assert mgr.save(s, st)
            mgr.wait_until_finished()
            assert mgr.latest_step() == 3
            restored = mgr.restore(template)
            assert int(restored.step) == 3
            np.testing.assert_array_equal(restored.params["w"],
                                          params["w"] * 3)
            # rotation: step 1 gone, step 2 restorable
            with pytest.raises(Exception):
                mgr.restore(template, step=1)
            assert int(mgr.restore(template, step=2).step) == 2

    def test_autoresume_sigterm_saves_and_resumes(self, tmp_path):
        """Preemption protocol: SIGTERM sets the flag, check_and_save writes
        the TrainState, a fresh run restores it bitwise (reference's ADLR
        auto-resume stub, here self-contained)."""
        import signal

        from apex_tpu.checkpoint import (
            AutoResume, TrainState, restore_checkpoint)
        from apex_tpu.transformer.pipeline_parallel.utils import (
            check_adlr_autoresume_termination, get_autoresume)

        guard = AutoResume()
        try:
            params = {"w": jr.normal(K, (3, 3))}
            state = TrainState(step=jnp.asarray(11), params=params,
                               opt_state=())
            path = os.path.join(str(tmp_path), "preempt")
            assert not guard.termination_requested()
            assert guard.check_and_save(path, state) is False
            os.kill(os.getpid(), signal.SIGTERM)  # simulated preemption
            assert guard.termination_requested()
            assert guard.check_and_save(path, state) is True
            restored = restore_checkpoint(
                path, jax.tree.map(jnp.zeros_like, state))
            assert int(restored.step) == 11
            np.testing.assert_array_equal(restored.params["w"], params["w"])
        finally:
            guard.uninstall()

        # reference-spelling wrapper honours the check interval
        g = get_autoresume()
        try:
            assert check_adlr_autoresume_termination(
                3, state, os.path.join(str(tmp_path), "p2"), interval=2) is False
            g.request_termination()
            assert check_adlr_autoresume_termination(
                4, state, os.path.join(str(tmp_path), "p2"), interval=2) is True
        finally:
            g.uninstall()

    def test_amp_state_dict_parity(self):
        from apex_tpu.amp.scaler import init_loss_scaler
        from apex_tpu.checkpoint import amp_load_state_dict, amp_state_dict

        s = init_loss_scaler(init_scale=1024.0)
        sd = amp_state_dict([s, s])
        assert set(sd) == {"loss_scaler0", "loss_scaler1"}
        restored = amp_load_state_dict(sd, [init_loss_scaler(), init_loss_scaler()])
        assert float(restored[0].loss_scale) == 1024.0


class TestModelParallelScaler:
    def test_skip_agreed_across_tp(self):
        from apex_tpu.amp.scaler import init_loss_scaler
        from apex_tpu.transformer.amp import update_scaler_model_parallel

        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)

        def run(grads):
            # only rank 0's shard has an inf — every rank must see finite=False
            rank = jax.lax.axis_index("tp")
            g = jnp.where(rank == 0, jnp.inf, 1.0) * grads
            state = init_loss_scaler(init_scale=16.0)
            new_state, finite = update_scaler_model_parallel(
                state, {"g": g}, axes=("tp",))
            return new_state.loss_scale, finite.astype(jnp.int32)

        scale, finite = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=P(), out_specs=(P(), P()),
        )(jnp.ones((4,)))
        assert float(scale) == 8.0  # backed off on every rank
        assert int(finite) == 0


class TestMemoryBuffer:
    """MemoryBuffer/RingMemBuffer parity (reference
    ``tensor_parallel/memory.py:23-133``) + the donation evidence the module
    docstring cites: on TPU the allocator-fragmentation problem the CUDA
    buffer solves is handled by XLA donation aliasing."""

    def test_add_get_reset_roundtrip(self):
        from apex_tpu.transformer.tensor_parallel.memory import MemoryBuffer

        buf = MemoryBuffer.create(64)
        x = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
        buf, off0 = buf.add(x)
        y = jnp.ones((8,), jnp.float32)
        buf, off1 = buf.add(y)
        assert int(off0) == 0 and int(off1) == 12
        np.testing.assert_array_equal(buf.get(off0, (3, 4)), x)
        np.testing.assert_array_equal(buf.get(off1, (8,)), y)
        buf = buf.reset()
        assert int(buf.start) == 0
        assert buf.numel == 64  # storage retained

    def test_overflow_raises_eagerly(self):
        from apex_tpu.transformer.tensor_parallel.memory import MemoryBuffer

        buf = MemoryBuffer.create(8)
        buf, _ = buf.add(jnp.ones((6,), jnp.float32))
        with pytest.raises(ValueError, match="overflow"):
            buf.add(jnp.ones((6,), jnp.float32))  # 6 + 6 > 8

    def test_buffer_works_under_jit_and_scan(self):
        from apex_tpu.transformer.tensor_parallel.memory import MemoryBuffer

        def stash_all(xs):
            def body(buf, x):
                buf, off = buf.add(x)
                return buf, off

            buf, offs = jax.lax.scan(body, MemoryBuffer.create(32), xs)
            return buf, offs

        xs = jnp.arange(24, dtype=jnp.float32).reshape(4, 6)
        buf, offs = jax.jit(stash_all)(xs)
        np.testing.assert_array_equal(np.asarray(offs), [0, 6, 12, 18])
        np.testing.assert_array_equal(buf.get(offs[2], (6,)), xs[2])

    def test_ring_buffer_rotates(self):
        from apex_tpu.transformer.tensor_parallel.memory import RingMemBuffer

        ring = RingMemBuffer(2, 16)
        a, b, c = (ring.get_next_buffer() for _ in range(3))
        assert a is c and a is not b

    def test_registry(self):
        from apex_tpu.transformer.tensor_parallel import memory as mem

        mem.destroy_mem_buffs()
        buf = mem.allocate_mem_buff("acts", 128)
        assert mem.get_mem_buff("acts") is buf
        with pytest.raises(ValueError, match="already allocated"):
            mem.allocate_mem_buff("acts", 128)
        mem.destroy_mem_buffs()

    def test_donation_aliases_buffers(self):
        """The evidence: donated inputs alias outputs (alias bytes > 0), so
        a training step reuses its parameter/optimizer buffers in place —
        the role the reference's preallocated buffer plays."""
        params = {"w": jnp.ones((256, 256))}

        @jax.jit
        def step_plain(p):
            return jax.tree.map(lambda x: x * 0.9, p)

        step_donated = jax.jit(
            lambda p: jax.tree.map(lambda x: x * 0.9, p), donate_argnums=0)

        plain = step_plain.lower(params).compile().memory_analysis()
        donated = step_donated.lower(params).compile().memory_analysis()
        assert donated.alias_size_in_bytes > 0
        assert donated.alias_size_in_bytes > plain.alias_size_in_bytes
