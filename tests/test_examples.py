"""Smoke-run every example script the way a user would (VERDICT r1 item 10:
'examples never executed by CI').

Each runs as a subprocess with the virtual 8-device CPU mesh, few iters,
synthetic data; pass = exit 0 and the script's own success markers.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout,
    )
    assert proc.returncode == 0, f"{' '.join(args)}\n{proc.stderr[-3000:]}"
    return proc.stdout


def test_simple_distributed_example():
    out = _run(["examples/simple/distributed/run.py",
                "--opt-level", "O2", "--steps", "25"])
    assert "loss" in out
    # loss printed at step 0 and the last step; it must decrease
    lines = [ln for ln in out.splitlines() if ln.strip().startswith("step")]
    first = float(lines[0].split("loss")[1].split()[0])
    last = float(lines[-1].split("loss")[1].split()[0])
    assert last < first


def test_imagenet_example_synthetic():
    out = _run(["examples/imagenet/main_amp.py", "--synthetic",
                "--opt-level", "O2", "--sync-bn", "--iters", "3",
                "--batch-size", "16", "--image-size", "32",
                "--num-classes", "10"])
    assert "img/s" in out or "loss" in out.lower()


def test_imagenet_example_prefetched_host_data():
    """The non-synthetic path: host numpy batches through the
    double-buffered dp-sharded prefetcher."""
    out = _run(["examples/imagenet/main_amp.py",
                "--opt-level", "O2", "--iters", "3", "--lr", "0.001",
                "--batch-size", "16", "--image-size", "32",
                "--num-classes", "10"])
    assert "img/s" in out


def test_dcgan_example():
    out = _run(["examples/dcgan/main_amp.py", "--niter", "2",
                "--iters-per-epoch", "2", "--imageSize", "16",
                "--batchSize", "8", "--ngf", "8", "--ndf", "8"])
    assert "done" in out


def test_dcgan_example_o2():
    out = _run(["examples/dcgan/main_amp.py", "--niter", "1",
                "--iters-per-epoch", "2", "--imageSize", "16",
                "--batchSize", "8", "--ngf", "8", "--ndf", "8",
                "--opt_level", "O2"])
    assert "done" in out
