"""L1 convergence tier over the REAL examples (VERDICT r2 item 5).

The reference's L1 runs the actual ImageNet example binary across the
opt-level cross product and diffs per-iteration loss curves against
committed baselines (``tests/L1/common/run_test.sh:29-90``,
``compare.py:12-25``). Here the examples expose an importable ``train()``
so the cells run in-process on the 8-device CPU mesh:

* ``examples/imagenet/main_amp.py --deterministic`` — ResNet-50 (tiny
  shapes) under every opt level, curve-checked against the committed
  per-cell baseline (platform-deterministic on CPU) AND the fp32 curve
  (cross-precision envelope);
* ``examples/dcgan/main_amp.py`` — the multiple-losses/multiple-scalers
  surface, D/G curves per cell.

Baselines regenerate with::

    APEX_TPU_REGEN_L1=1 pytest tests/test_l1_examples.py -k regen
"""

import json
import os
import sys
import types

import jax
import numpy as np
import pytest

_here = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_here)
BASELINE_DIR = os.path.join(_here, "L1_baselines")
OPT_LEVELS = ["O0", "O1", "O2", "O3"]
_ON_CPU = jax.default_backend() == "cpu"


pytestmark = pytest.mark.slow

def _load_example(rel):
    import importlib.util

    path = os.path.join(REPO, rel)
    name = rel.replace("/", "_").replace(".py", "")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def imagenet():
    return _load_example("examples/imagenet/main_amp.py")


@pytest.fixture(scope="module")
def dcgan():
    return _load_example("examples/dcgan/main_amp.py")


def _imagenet_args(imagenet, opt_level, **over):
    argv = ["--deterministic", "--synthetic", "--opt-level", opt_level,
            "--iters", "16", "--batch-size", "16", "--image-size", "32",
            "--num-classes", "10", "--lr", "0.005", "--sync-bn"]
    for k, v in over.items():
        argv += [f"--{k}", str(v)]
    return imagenet.parse_args(argv)


def _dcgan_args(dcgan, opt_level):
    return dcgan.parse_args([
        "--niter", "2", "--iters-per-epoch", "6", "--imageSize", "16",
        "--batchSize", "32", "--ngf", "16", "--ndf", "16", "--nz", "32",
        "--opt_level", opt_level,
    ])


def _baseline(name):
    path = os.path.join(BASELINE_DIR, f"{name}.json")
    if not os.path.exists(path):
        pytest.skip(f"baseline {name}.json not committed")
    with open(path) as f:
        return json.load(f)


def _teardown_mesh():
    from apex_tpu.parallel import mesh as mesh_lib

    mesh_lib.destroy_model_parallel()


class TestImagenetExampleL1:
    @staticmethod
    def _sanity(losses, rec):
        assert np.all(np.isfinite(losses))
        assert rec["skipped_steps"] <= 2
        # 16 SGD iters of a scratch ResNet-50 give a NOISY but bounded and
        # deterministic curve (the reference's L1 likewise diffs curves,
        # not convergence, compare.py:12-25); blowup = divergence caught
        assert float(np.max(losses)) < 30.0, losses

    @staticmethod
    def _envelope_vs_o0(losses):
        # cross-precision check: half curves must TRACK the fp32 curve over
        # the early iterations; beyond that, bf16-vs-fp32 rounding feeds
        # through SyncBN statistics + momentum chaotically and pointwise
        # comparison stops being meaningful (same reason the reference
        # compares like-for-like cells)
        ref = np.asarray(_baseline("imagenet_O0")["loss"])[:6]
        got = losses[:6]
        denom = np.maximum(np.abs(ref), 0.05)
        assert np.max(np.abs(got - ref) / denom) < 0.25, (got, ref)

    @pytest.mark.parametrize("opt_level", OPT_LEVELS)
    def test_opt_level_cell(self, imagenet, opt_level):
        rec = imagenet.train(_imagenet_args(imagenet, opt_level))
        _teardown_mesh()
        losses = np.asarray(rec["loss"])
        self._sanity(losses, rec)
        # per-cell committed curve (platform-deterministic) — the tight
        # check the r2 envelope couldn't give
        if _ON_CPU:
            base = np.asarray(_baseline(f"imagenet_{opt_level}")["loss"])
            np.testing.assert_allclose(losses, base, rtol=5e-3, atol=5e-4)
        self._envelope_vs_o0(losses)

    def test_keep_batchnorm_fp32_cell(self, imagenet):
        """The reference cross product's keep_batchnorm_fp32 dimension on
        the real example (O2 + BN fp32 is its canonical pairing)."""
        rec = imagenet.train(_imagenet_args(
            imagenet, "O2", **{"keep-batchnorm-fp32": "True"}))
        _teardown_mesh()
        losses = np.asarray(rec["loss"])
        self._sanity(losses, rec)
        self._envelope_vs_o0(losses)

    def test_static_loss_scale_cell(self, imagenet):
        rec = imagenet.train(_imagenet_args(
            imagenet, "O2", **{"loss-scale": "128.0"}))
        _teardown_mesh()
        losses = np.asarray(rec["loss"])
        self._sanity(losses, rec)
        self._envelope_vs_o0(losses)


class TestDcganExampleL1:
    @pytest.mark.parametrize("opt_level", OPT_LEVELS)
    def test_opt_level_cell(self, dcgan, opt_level):
        rec = dcgan.train(_dcgan_args(dcgan, opt_level), verbose=False)
        d = np.asarray(rec["loss_d"])
        g = np.asarray(rec["loss_g"])
        assert np.all(np.isfinite(d)) and np.all(np.isfinite(g))
        assert rec["skipped_steps"] <= 3
        # the D/G equilibrium keeps losses near 2·ln2; bounded = healthy
        assert float(np.max(d)) < 5.0 and float(np.max(g)) < 5.0
        if _ON_CPU:
            base = _baseline(f"dcgan_{opt_level}")
            np.testing.assert_allclose(d, base["loss_d"], rtol=5e-3,
                                       atol=5e-4)
            np.testing.assert_allclose(g, base["loss_g"], rtol=5e-3,
                                       atol=5e-4)


@pytest.mark.skipif(not os.environ.get("APEX_TPU_REGEN_L1"),
                    reason="baseline regeneration only on request")
def test_regenerate_example_baselines(imagenet, dcgan):
    os.makedirs(BASELINE_DIR, exist_ok=True)
    for o in OPT_LEVELS:
        rec = imagenet.train(_imagenet_args(imagenet, o))
        _teardown_mesh()
        with open(os.path.join(BASELINE_DIR, f"imagenet_{o}.json"), "w") as f:
            json.dump(rec, f, indent=1)
        print(f"imagenet_{o}: final {rec['loss'][-1]:.4f}")
    for o in OPT_LEVELS:
        rec = dcgan.train(_dcgan_args(dcgan, o), verbose=False)
        with open(os.path.join(BASELINE_DIR, f"dcgan_{o}.json"), "w") as f:
            json.dump(rec, f, indent=1)
        print(f"dcgan_{o}: final D {rec['loss_d'][-1]:.4f}")
