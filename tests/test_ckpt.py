"""Elastic sharded checkpointing tests (ISSUE 14 acceptance criteria).

The contracts under test:

* pure-numpy npz pytree round-trip (the orbax-free fallback — no
  checkpoint test is environment-dependent anymore);
* sharded ZeRO save/restore: SAME-dp resume is bitwise (masters + m/v
  + scaler identical, continued trajectory identical to the
  uninterrupted run), ELASTIC dp-resize (4→8 and 8→4) re-slices the
  chunk-row space exactly and the continued losses match the
  uninterrupted run;
* restore error paths are eager and knob-naming: missing manifest,
  digest mismatch, junk manifest keys, a padded row space the
  manifest's dp cannot divide, template/layout mismatch — never a deep
  reshape traceback;
* fp16 x ZeRO overflow state round-trips: save mid-recovery (scale
  512), restore, and the scaler trajectory (512 → 512 → 1024)
  continues bitwise as if never saved;
* async off-step saves commit ATOMICALLY: a SIGKILL-equivalent fault
  at any stage mid-save leaves the previous checkpoint restorable;
  ZeroCheckpointManager rotation/thinning/auto-resume ride the format;
* the ``ckpt`` monitor record: emitter honesty, schema (closed
  manifest section — junk keys fail), ``tools/validate_metrics.py
  --ckpt`` forced dispatch, report line, and the
  ``tools/bench_history.py`` lower-is-better ``save_overhead_pct``
  gate;
* the serving hot-swap integration: params restored from a sharded
  checkpoint swap into a live engine between dispatch steps
  (token-identical streams for equal weights, jit caches pinned at 1
  — engine-level swap tests live in ``tests/test_serving.py``).
"""

import dataclasses
import glob
import io
import json
import os
import sys

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import ckpt as ckpt_lib
from apex_tpu import monitor
from apex_tpu.contrib.optimizers import distributed_fused_adam
from apex_tpu.contrib.optimizers.distributed import (export_zero_shard,
                                                     gather_zero_state,
                                                     scatter_zero_state,
                                                     shard_row_range)
from apex_tpu.parallel import mesh as mesh_lib

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import bench_history  # noqa: E402
import validate_metrics  # noqa: E402

K = jr.PRNGKey(7)
CHUNK = 256


def _problem(param_dtype=None):
    params = {
        "w1": jr.normal(K, (16, 64)) * 0.1, "b1": jnp.zeros((64,)),
        "w2": jr.normal(jr.fold_in(K, 1), (64, 16)) * 0.1,
    }
    if param_dtype is not None:
        params = jax.tree.map(lambda x: x.astype(param_dtype), params)
    w_true = jr.normal(jr.fold_in(K, 2), (16, 16))
    return params, w_true


def _loss_fn(p, x, y):
    h = jnp.tanh(x @ p["w1"].astype(jnp.float32) + p["b1"].astype(
        jnp.float32))
    return jnp.mean((h @ p["w2"].astype(jnp.float32) - y) ** 2)


class _Trainer:
    """A ZeRO-Adam MLP train loop at width ``dp`` whose state crosses
    the host between steps (the checkpointing-natural shape): the step
    is ONE jitted shard_map application, global data splits over dp via
    ``P('dp')``, and the ZeroState rides in the rank-local layout the
    training loop always holds (gather/scatter views convert at the
    checkpoint boundary)."""

    def __init__(self, dp, *, param_dtype=None, lr=1e-2):
        self.dp = dp
        self.mesh = mesh_lib.make_mesh(devices=jax.devices()[:dp])
        self.opt = distributed_fused_adam(learning_rate=lr,
                                          chunk_size=CHUNK)
        self.params, self.w_true = _problem(param_dtype)
        self.zstate = mesh_lib.shard_map(
            lambda p: self.opt.init(p), mesh=self.mesh, in_specs=P(),
            out_specs=P())(self.params)

        def run(params, x, y, zstate):
            loss, grads = jax.value_and_grad(_loss_fn)(params, x, y)
            loss = jax.lax.pmean(loss, "dp")
            updates, zstate = self.opt.update(grads, zstate, params)
            return optax.apply_updates(params, updates), zstate, loss

        self.step = jax.jit(mesh_lib.shard_map(
            run, mesh=self.mesh,
            in_specs=(P(), P("dp"), P("dp"), P()),
            out_specs=(P(), P(), P())))

    def data(self, i):
        x = jr.normal(jr.fold_in(K, 100 + i), (32, 16))
        return x, jnp.tanh(x @ self.w_true)

    def run(self, steps, start=0):
        losses = []
        for i in range(start, start + steps):
            x, y = self.data(i)
            self.params, self.zstate, loss = self.step(
                self.params, x, y, self.zstate)
            losses.append(float(loss))
        return losses

    def gathered(self):
        return gather_zero_state(self.zstate, self.mesh)

    def adopt(self, global_state, params):
        """Install a restored (global-view) state + params."""
        self.zstate = scatter_zero_state(global_state, self.mesh)
        self.params = params


class TestPytreeIO:
    """The orbax-free npz engine."""

    def test_train_state_roundtrip_without_orbax(self, tmp_path,
                                                 monkeypatch):
        from apex_tpu.ckpt import state as state_mod

        monkeypatch.setattr(state_mod, "_HAS_ORBAX", False)
        params = {"w": jr.normal(K, (4, 4)),
                  "b": jnp.zeros((4,), jnp.bfloat16)}
        st = state_mod.TrainState(step=jnp.asarray(7), params=params,
                                  opt_state={"nu": jnp.ones((3,))})
        path = str(tmp_path / "ck")
        state_mod.save_checkpoint(path, st)
        assert os.path.isfile(path + ".npz")
        restored = state_mod.restore_checkpoint(
            path, jax.tree.map(jnp.zeros_like, st))
        assert int(restored.step) == 7
        for a, e in zip(jax.tree.leaves(restored),
                        jax.tree.leaves(st)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(e))
            assert np.asarray(a).dtype == np.asarray(e).dtype

    def test_manager_rotation_without_orbax(self, tmp_path, monkeypatch):
        from apex_tpu.ckpt import state as state_mod

        monkeypatch.setattr(state_mod, "_HAS_ORBAX", False)
        params = {"w": jr.normal(K, (4, 4))}
        template = state_mod.TrainState(
            step=jnp.asarray(0),
            params=jax.tree.map(jnp.zeros_like, params), opt_state=())
        with state_mod.CheckpointManager(str(tmp_path / "m"),
                                         max_to_keep=2) as mgr:
            for s in (1, 2, 3):
                st = state_mod.TrainState(
                    step=jnp.asarray(s),
                    params=jax.tree.map(lambda x, s=s: x * s, params),
                    opt_state=())
                assert mgr.save(s, st)
            assert mgr.latest_step() == 3
            restored = mgr.restore(template)
            np.testing.assert_array_equal(restored.params["w"],
                                          params["w"] * 3)
            with pytest.raises(FileNotFoundError):
                mgr.restore(template, step=1)
            assert int(mgr.restore(template, step=2).step) == 2

    def test_template_mismatch_is_named(self, tmp_path, monkeypatch):
        from apex_tpu.ckpt import state as state_mod

        monkeypatch.setattr(state_mod, "_HAS_ORBAX", False)
        st = state_mod.TrainState(step=jnp.asarray(1),
                                  params={"w": jnp.ones((4,))},
                                  opt_state=())
        path = str(tmp_path / "ck")
        state_mod.save_checkpoint(path, st)
        bad_shape = dataclasses.replace(st, params={"w": jnp.ones((5,))})
        with pytest.raises(ValueError, match="shape"):
            state_mod.restore_checkpoint(path, bad_shape)
        bad_count = dataclasses.replace(
            st, params={"w": jnp.ones((4,)), "x": jnp.ones((1,))})
        with pytest.raises(ValueError, match="leaves"):
            state_mod.restore_checkpoint(path, bad_count)


class TestShardedSameDp:
    """Acceptance witness 1: bitwise resume at the same dp — masters +
    m/v + trajectory identical to the uninterrupted run."""

    def test_bitwise_resume_bf16_masters(self, tmp_path):
        # bf16 params → the state carries SHARDED fp32 masters; the
        # checkpoint needs no params= (masters rebuild them)
        base = _Trainer(8, param_dtype=jnp.bfloat16)
        base_losses = base.run(6)

        t = _Trainer(8, param_dtype=jnp.bfloat16)
        t.run(3)
        g = t.gathered()
        assert "master" in g.buffers
        d = str(tmp_path / "ck")
        # params= rides along even with masters present: the live bf16
        # image is p + (new - p) in bf16, NOT the master's cast — the
        # bitwise witness needs the params themselves
        ckpt_lib.save_zero_sharded(d, g, dp=8, step=3, params=t.params)

        # a "fresh process": new trainer, params from the checkpoint
        fresh = _Trainer(8, param_dtype=jnp.bfloat16)
        restored_params = ckpt_lib.restore_params(d, like=fresh.params)
        st, restored = ckpt_lib.load_zero_state(d, fresh.params, dp=8)
        assert restored.count == 3 and restored.step == 3
        # the restored GLOBAL buffers are bitwise the saved ones
        for k in g.buffers:
            np.testing.assert_array_equal(np.asarray(g.buffers[k]),
                                          np.asarray(st.buffers[k]))
        fresh.adopt(st, restored_params)
        resumed_losses = fresh.run(3, start=3)
        # trajectory: bitwise equal to the uninterrupted run
        assert resumed_losses == base_losses[3:]
        for a, e in zip(jax.tree.leaves(fresh.params),
                        jax.tree.leaves(base.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(e))
        gf = gather_zero_state(fresh.zstate, fresh.mesh)
        gb = gather_zero_state(base.zstate, base.mesh)
        for k in gb.buffers:
            np.testing.assert_array_equal(
                np.asarray(gf.buffers[k]), np.asarray(gb.buffers[k]),
                err_msg=f"sharded {k} diverged after resume")

    def test_fp32_params_ride_the_params_buffer(self, tmp_path):
        t = _Trainer(8)
        t.run(2)
        g = t.gathered()
        assert "master" not in g.buffers
        d = str(tmp_path / "ck")
        with pytest.raises(ValueError, match="params"):
            ckpt_lib.save_zero_sharded(d, g, dp=8)  # not self-contained
        man = ckpt_lib.save_zero_sharded(d, g, dp=8, params=t.params)
        assert "params" in man.buffers
        rp = ckpt_lib.restore_params(d, like=t.params)
        for a, e in zip(jax.tree.leaves(rp), jax.tree.leaves(t.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(e))

    def test_export_view_matches_shard_files(self, tmp_path):
        t = _Trainer(8)
        t.run(1)
        g = t.gathered()
        d = str(tmp_path / "ck")
        ckpt_lib.save_zero_sharded(d, g, dp=8, params=t.params)
        man = ckpt_lib.read_manifest(d)
        for rank in (0, 3, 7):
            view = export_zero_shard(g, rank, 8)
            disk = ckpt_lib.restore_zero_shard(d, rank, 8)
            for k in view:
                np.testing.assert_array_equal(view[k], disk[k])
        lo, hi = shard_row_range(man.n_chunks, 8, 2)
        assert hi - lo == man.rows_per_rank


class TestElasticResize:
    """Acceptance witness 2: restore at dp' != dp re-slices the global
    chunk-row space; the continued trajectory matches the uninterrupted
    run."""

    def test_rows_reslice_exactly_4_to_8_and_back(self, tmp_path):
        t = _Trainer(4)
        t.run(2)
        g4 = t.gathered()
        d = str(tmp_path / "ck")
        man = ckpt_lib.save_zero_sharded(d, g4, dp=4, params=t.params)
        n = man.n_chunks
        for dp_new in (8, 2, 1, 3):
            r = ckpt_lib.restore_zero_sharded(d, dp=dp_new)
            for k in ("m", "v"):
                got = r.buffers[k]
                assert got.shape[0] == n + ((-n) % dp_new)
                np.testing.assert_array_equal(
                    got[:n], np.asarray(g4.buffers[k])[:n],
                    err_msg=f"{k} rows moved at dp={dp_new}")
                assert not got[n:].any(), "padding rows must be zeros"

    def test_trajectory_parity_dp4_to_dp8(self, tmp_path):
        """THE headline: train at dp=4, save, restore at dp=8, continue
        — the losses match the uninterrupted dp=8 run (the global
        gradient/update math is dp-independent; only float-summation
        grouping differs, so parity is allclose-tight, and the
        bitwise claim stays with same-dp resume)."""
        base = _Trainer(8)
        base_losses = base.run(6)

        t4 = _Trainer(4)
        t4.run(3)
        d = str(tmp_path / "ck")
        ckpt_lib.save_zero_sharded(d, t4.gathered(), dp=4,
                                   params=t4.params, step=3)

        t8 = _Trainer(8)
        rp = ckpt_lib.restore_params(d, like=t8.params)
        st, restored = ckpt_lib.load_zero_state(d, t8.params, dp=8)
        assert restored.count == 3
        t8.adopt(st, rp)
        resumed = t8.run(3, start=3)
        np.testing.assert_allclose(resumed, base_losses[3:], rtol=1e-4,
                                   atol=1e-6)
        for a, e in zip(jax.tree.leaves(t8.params),
                        jax.tree.leaves(base.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       rtol=1e-4, atol=1e-6)

    def test_trajectory_parity_dp8_to_dp4(self, tmp_path):
        """Shrink too (the preempted-fleet direction): dp 8 → 4."""
        base = _Trainer(4)
        base_losses = base.run(5)

        t8 = _Trainer(8)
        t8.run(2)
        d = str(tmp_path / "ck")
        ckpt_lib.save_zero_sharded(d, t8.gathered(), dp=8,
                                   params=t8.params, step=2)
        t4 = _Trainer(4)
        rp = ckpt_lib.restore_params(d, like=t4.params)
        st, _ = ckpt_lib.load_zero_state(d, t4.params, dp=4)
        t4.adopt(st, rp)
        resumed = t4.run(3, start=2)
        np.testing.assert_allclose(resumed, base_losses[2:], rtol=1e-4,
                                   atol=1e-6)


class TestRestoreErrorPaths:
    """Satellite: every failure is eager and names its knob."""

    @pytest.fixture()
    def saved(self, tmp_path):
        t = _Trainer(4)
        t.run(1)
        d = str(tmp_path / "ck")
        ckpt_lib.save_zero_sharded(d, t.gathered(), dp=4,
                                   params=t.params)
        return d, t

    def test_missing_manifest(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="manifest.json"):
            ckpt_lib.read_manifest(str(tmp_path / "nope"))
        os.makedirs(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError, match="never finished"):
            ckpt_lib.restore_zero_sharded(str(tmp_path / "empty"), dp=4)

    def test_digest_mismatch_names_buffer_and_rank(self, saved):
        d, _ = saved
        sh = os.path.join(d, "shard_00001.npz")
        with np.load(sh) as zf:
            arrs = {k: zf[k].copy() for k in zf.files}
        arrs["m"][0, 0] += 1.0
        from apex_tpu.ckpt.pytree_io import savez_atomic
        savez_atomic(sh, arrs)
        with pytest.raises(ValueError, match=r"digest mismatch.*'m'.*"
                                             r"shard_00001"):
            ckpt_lib.restore_zero_sharded(d, dp=4)
        # forensic escape hatch still reads it
        r = ckpt_lib.restore_zero_sharded(d, dp=4, verify=False)
        assert r.buffers["m"].shape[1] == CHUNK

    def test_corrupt_shard_zip_is_named(self, saved):
        d, _ = saved
        sh = os.path.join(d, "shard_00000.npz")
        data = bytearray(open(sh, "rb").read())
        data[-3] ^= 0xFF
        open(sh, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="corrupt"):
            ckpt_lib.restore_zero_sharded(d, dp=4)

    def _edit_manifest(self, d, **kv):
        mp = os.path.join(d, "manifest.json")
        m = json.load(open(mp))
        m.update(kv)
        json.dump(m, open(mp, "w"))

    def test_dp_that_cannot_divide_padded_rows(self, saved):
        d, _ = saved
        # hand-edit pad_rows so n_chunks + pad_rows is NOT a dp
        # multiple: the manifest self-check names dp and the row count,
        # never a downstream reshape traceback
        man = ckpt_lib.read_manifest(d)
        self._edit_manifest(d, pad_rows=man.pad_rows + 1,
                            rows_per_rank=man.rows_per_rank)
        with pytest.raises(ValueError, match=r"pad_rows|divide"):
            ckpt_lib.restore_zero_sharded(d, dp=8)

    def test_junk_manifest_keys_fail(self, saved):
        d, _ = saved
        self._edit_manifest(d, junk_knob=1)
        with pytest.raises(ValueError, match="junk_knob"):
            ckpt_lib.read_manifest(d)

    def test_newer_format_version_is_refused(self, saved):
        d, _ = saved
        self._edit_manifest(d, version=99)
        with pytest.raises(ValueError, match="version 99 is newer"):
            ckpt_lib.read_manifest(d)

    def test_dp_validation(self, saved):
        d, _ = saved
        with pytest.raises(ValueError, match="dp must be >= 1"):
            ckpt_lib.restore_zero_sharded(d, dp=0)

    def test_template_mismatch_names_leaf_and_chunk_size(self, saved):
        d, t = saved
        bad = dict(t.params, w1=jnp.zeros((8, 8)))
        with pytest.raises(ValueError, match=r"leaf 1.*\[8, 8\]"):
            ckpt_lib.load_zero_state(d, bad, dp=4)
        from apex_tpu.ckpt.sharded import _validate_layout
        from apex_tpu.optimizers import multi_tensor as mt
        man = ckpt_lib.read_manifest(d)
        layout = mt.make_layout(t.params, 128)
        with pytest.raises(ValueError, match="chunk_size"):
            _validate_layout(man, layout, chunk_size=128)

    def test_save_collision_is_loud(self, saved):
        d, t = saved
        with pytest.raises(FileExistsError, match="already exists"):
            ckpt_lib.save_zero_sharded(d, t.gathered(), dp=4,
                                       params=t.params)
        # overwrite=True replaces atomically
        ckpt_lib.save_zero_sharded(d, t.gathered(), dp=4,
                                   params=t.params, overwrite=True)

    def test_gather_shape_mismatch_names_the_view(self, saved):
        _, t = saved
        local = t.zstate  # rank-local layout: rows are 1/dp of global
        with pytest.raises(ValueError, match="gather_zero_state"):
            ckpt_lib.save_zero_sharded("/tmp/never-written", local,
                                       dp=4, params=t.params)


class TestScalerOverflowRoundtrip:
    """Satellite: fp16 x ZeRO overflow state round-trips — save
    mid-recovery (scale 512), restore, and the 512 → 512 → 1024
    recovery continues bitwise as if never saved."""

    def _build(self, dp=8):
        from apex_tpu.amp.scaler import (LossScalerState, init_loss_scaler,
                                         unscale_grads)
        from apex_tpu.transformer.amp import update_scaler_model_parallel

        mesh = mesh_lib.make_mesh(devices=jax.devices()[:dp])
        params = {
            "w1": (jr.normal(jr.fold_in(K, 70), (16, 24)) * 0.1
                   ).astype(jnp.float16),
            "b1": jnp.zeros((24,), jnp.float16),
            "w2": (jr.normal(jr.fold_in(K, 71), (24, 8)) * 0.1
                   ).astype(jnp.float16),
        }
        base_g = jax.tree.map(
            lambda x: jr.normal(jr.fold_in(K, 72), x.shape) * 0.05,
            params)
        zopt = distributed_fused_adam(learning_rate=1e-2,
                                      chunk_size=CHUNK)
        init_scale = 1024.0
        grads16 = jax.tree.map(
            lambda g: (g * init_scale).astype(jnp.float16), base_g)

        def one_step(params, zstate, sstate, grads16, inject):
            rank = jax.lax.axis_index("dp")
            g16 = grads16
            if inject:
                g16 = dict(g16, w1=jnp.where(
                    rank == 1, jnp.full_like(g16["w1"], jnp.inf),
                    g16["w1"]))
            ug = unscale_grads(sstate, g16)
            sstate, finite = update_scaler_model_parallel(
                sstate, ug, axes=("dp",))
            safe = jax.tree.map(
                lambda x: jnp.where(jnp.isfinite(x), x, 0.0), ug)
            updates, new_z = zopt.update(safe, zstate, params)
            new_params = optax.apply_updates(params, updates)
            params = jax.tree.map(
                lambda a, b: jnp.where(finite, a, b), new_params, params)
            zstate = jax.tree.map(
                lambda a, b: jnp.where(finite, a, b), new_z, zstate)
            return params, zstate, sstate

        steps = {}
        for inject in (False, True):
            steps[inject] = jax.jit(mesh_lib.shard_map(
                lambda p, z, s, g, inject=inject: one_step(
                    p, z, s, g, inject),
                mesh=mesh, in_specs=(P(), P(), P(), P()),
                out_specs=(P(), P(), P())))
        zstate = mesh_lib.shard_map(lambda p: zopt.init(p), mesh=mesh,
                                    in_specs=P(), out_specs=P())(params)
        sstate = init_loss_scaler(init_scale=init_scale,
                                  growth_interval=2)
        return (mesh, params, zstate, sstate, grads16, steps,
                init_loss_scaler)

    def test_mid_recovery_save_restore_continues_bitwise(self, tmp_path):
        from apex_tpu.amp.scaler import load_state_dict

        (mesh, params, zstate, sstate, grads16, steps,
         init_loss_scaler) = self._build()

        # steps 1 (finite, 1024) and 2 (overflow → 512)
        p, z, s = steps[False](params, zstate, sstate, grads16)
        p, z, s = steps[True](p, z, s, grads16)
        assert float(s.loss_scale) == 512.0
        assert int(s.skipped_steps) == 1

        # uninterrupted continuation: 512 (tracker 1) → 1024 (growth)
        pu, zu, su = steps[False](p, z, s, grads16)
        scale3 = float(su.loss_scale)
        pu2, zu2, su2 = steps[False](pu, zu, su, grads16)
        assert (scale3, float(su2.loss_scale)) == (512.0, 1024.0)

        # save MID-RECOVERY (scale 512) with the scaler in the manifest
        # and the live fp16 params riding as the params buffer
        d = str(tmp_path / "ck")
        g = gather_zero_state(z, mesh)
        ckpt_lib.save_zero_sharded(d, g, dp=8, scaler_state=s, step=2,
                                   params=p)

        # "fresh process": restore state + scaler, continue
        st, restored = ckpt_lib.load_zero_state(d, params, dp=8)
        assert restored.scaler is not None
        s2 = load_state_dict(init_loss_scaler(growth_interval=2),
                             restored.scaler)
        assert float(s2.loss_scale) == 512.0
        rp = ckpt_lib.restore_params(d, like=params)  # fp16 via masters
        z2 = scatter_zero_state(st, mesh)
        pr, zr, sr = steps[False](rp, z2, s2, grads16)
        assert float(sr.loss_scale) == 512.0  # tracker mid-recovery
        pr2, zr2, sr2 = steps[False](pr, zr, sr, grads16)
        assert float(sr2.loss_scale) == 1024.0  # recovery completed

        # bitwise: params and sharded buffers equal the uninterrupted run
        for a, e in zip(jax.tree.leaves(pr2), jax.tree.leaves(pu2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(e))
        ga = gather_zero_state(zr2, mesh)
        ge = gather_zero_state(zu2, mesh)
        assert set(ga.buffers) == {"m", "v", "master"}
        for k in ge.buffers:
            np.testing.assert_array_equal(
                np.asarray(ga.buffers[k]), np.asarray(ge.buffers[k]),
                err_msg=f"{k} diverged across the save")
        assert int(sr2.skipped_steps) == int(su2.skipped_steps)
        assert int(sr2.growth_tracker) == int(su2.growth_tracker)


class TestAsyncSaveAndManager:
    """Atomic commit + crash injection + rotation + auto-resume."""

    def _state(self, dp=8):
        t = _Trainer(dp)
        t.run(1)
        return t

    def test_async_save_timings_and_commit(self, tmp_path):
        t = self._state()
        root = str(tmp_path / "mgr")
        with ckpt_lib.ZeroCheckpointManager(root) as mgr:
            assert mgr.save(1, t.gathered(), dp=8, params=t.params)
            snap = mgr.last_timings
            assert "snapshot_ms" in snap  # measured on the step path
            mgr.wait_until_finished()
            assert "write_ms" in mgr.last_timings  # measured off it
            assert mgr.latest_step() == 1

    @pytest.mark.parametrize("stage", ["shard:0", "shard:3", "manifest",
                                       "commit"])
    def test_crash_at_every_stage_keeps_prior_checkpoint(self, tmp_path,
                                                         stage):
        """THE atomic-commit witness: a SIGKILL-equivalent fault at any
        point mid-async-save leaves the previous checkpoint restorable
        and the interrupted step undiscoverable."""
        t = self._state()
        root = str(tmp_path / "mgr")
        with ckpt_lib.ZeroCheckpointManager(root) as mgr:
            mgr.save(1, t.gathered(), dp=8, params=t.params)
            mgr.wait_until_finished()
        g_saved = t.gathered()

        def fault(s, stage=stage):
            if s == stage:
                raise ckpt_lib.SimulatedCrash(s)

        t.run(1)  # advance so step 2's state differs
        mgr2 = ckpt_lib.ZeroCheckpointManager(root, fault=fault)
        mgr2.save(2, t.gathered(), dp=8, params=t.params, force=True)
        mgr2.wait_until_finished()
        assert mgr2.crashed
        assert mgr2.all_steps() == [1]  # step 2 never committed
        # tmp litter looks exactly like a killed process...
        assert any(".tmp-" in n for n in os.listdir(root))
        # ...and the NEXT manager (the restarted job) sweeps it and
        # restores the prior checkpoint bitwise
        mgr3 = ckpt_lib.ZeroCheckpointManager(root)
        assert not any(".tmp-" in n for n in os.listdir(root))
        st, restored = mgr3.restore(t.params, dp=8)
        assert restored.step == 1
        # the restored buffers equal the STEP-1 state, not the newer one
        for k in st.buffers:
            np.testing.assert_array_equal(
                np.asarray(st.buffers[k]),
                np.asarray(g_saved.buffers[k]))
        assert int(np.asarray(st.count)) == 1

    def test_rotation_and_interval(self, tmp_path):
        t = self._state()
        root = str(tmp_path / "mgr")
        with ckpt_lib.ZeroCheckpointManager(
                root, max_to_keep=2, save_interval_steps=2) as mgr:
            assert mgr.save(0, t.gathered(), dp=8, params=t.params)
            assert not mgr.save(1, t.gathered(), dp=8,
                                params=t.params)  # thinned
            assert mgr.save(2, t.gathered(), dp=8, params=t.params)
            assert mgr.save(4, t.gathered(), dp=8, params=t.params)
            mgr.wait_until_finished()
            assert mgr.all_steps() == [2, 4]  # 0 rotated out
            st, restored = mgr.restore(t.params, dp=8, step=2)
            assert restored.step == 2

    def test_stale_tmp_sweep_spares_live_foreign_writers(self, tmp_path):
        """The sweep only removes litter whose embedded pid is DEAD (or
        our own): a resuming job sharing the root with a still-draining
        fleet must not rmtree a save out from under its writer."""
        from apex_tpu.ckpt.async_save import cleanup_stale_tmp

        from apex_tpu.ckpt import sharded as sharded_mod

        root = str(tmp_path / "mgr")
        os.makedirs(os.path.join(root, "step_00000009.tmp-1"))  # pid 1:
        # alive (init) and not ours — a live foreign writer
        os.makedirs(os.path.join(root, "step_00000008.tmp-999999999"))
        os.makedirs(os.path.join(root, f"step_00000007.tmp-{os.getpid()}"))
        # our own pid, but ACTIVELY writing (a second manager built over
        # the same root mid-save): spared while registered, swept after
        active = os.path.join(root, f"step_00000006.tmp-{os.getpid()}")
        os.makedirs(active)
        sharded_mod._ACTIVE_TMP.add(os.path.abspath(active))
        try:
            removed = cleanup_stale_tmp(root)
            left = sorted(os.listdir(root))
            assert removed == 2
            assert left == [f"step_00000006.tmp-{os.getpid()}",
                            "step_00000009.tmp-1"]
        finally:
            sharded_mod._ACTIVE_TMP.discard(os.path.abspath(active))
        assert cleanup_stale_tmp(root) == 1  # now it IS dead litter
        assert sorted(os.listdir(root)) == ["step_00000009.tmp-1"]

    def test_autoresume_skips_resave_when_step_already_durable(
            self, tmp_path):
        """SIGTERM landing right after the scheduled save of the same
        step: the preemption path must return True on the existing
        durable checkpoint, not die on FileExistsError."""
        t = self._state()
        root = str(tmp_path / "mgr")
        guard = ckpt_lib.AutoResume(signals=())
        try:
            with ckpt_lib.ZeroCheckpointManager(root) as mgr:
                mgr.save(7, t.gathered(), dp=8, params=t.params)
                mgr.wait_until_finished()
                guard.request_termination()
                assert guard.check_and_save_sharded(
                    mgr, 7, t.gathered(), dp=8, params=t.params) is True
                assert mgr.all_steps() == [7]
        finally:
            guard.uninstall()

    def test_autoresume_sharded(self, tmp_path):
        t = self._state()
        root = str(tmp_path / "mgr")
        guard = ckpt_lib.AutoResume(signals=())
        try:
            with ckpt_lib.ZeroCheckpointManager(
                    root, save_interval_steps=100) as mgr:
                assert guard.check_and_save_sharded(
                    mgr, 5, t.gathered(), dp=8, params=t.params) is False
                guard.request_termination()
                # force=True bypasses the interval; the save is durable
                # (committed) before the call returns
                assert guard.check_and_save_sharded(
                    mgr, 5, t.gathered(), dp=8, params=t.params) is True
                assert mgr.latest_step() == 5
        finally:
            guard.uninstall()
        st, restored = ckpt_lib.ZeroCheckpointManager(root).restore(
            t.params, dp=8)
        assert restored.step == 5


class TestCkptRecord:
    """The ``ckpt`` monitor record: emitter honesty, closed manifest
    schema, validator dispatch, report line, bench_history gate."""

    def _fields(self, **over):
        man = {"format": "apex_tpu.zero_sharded", "version": 1,
               "step": 3, "count": 3, "dp": 8, "chunk_size": 1024,
               "n_chunks": 126, "pad_rows": 2, "rows_per_rank": 16,
               "buffers": ["m", "params", "v"],
               "digest_algo": "sha256"}
        f = dict(save_overhead_pct=1.5, step_ms=20.0,
                 step_ms_saving=20.3, snapshot_ms=1.1, write_ms=30.0,
                 restore_ms=9.0, bytes_written=1000000, steps=8,
                 saves=4, save_every=2, dp=8, async_save=True,
                 bitwise_resume_ok=True, elastic_resume_ok=True,
                 manifest=man, spread_pct=0.4, backend="tpu")
        f.update(over)
        return f

    def test_emit_and_validate_ok(self):
        reg = monitor.MetricsRegistry()
        rec = reg.emit_ckpt("OK", **self._fields())
        assert monitor.validate(rec) == []
        assert rec["kind"] == "ckpt"

    def test_nan_in_ok_fails(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit_ckpt("OK", **self._fields(
                save_overhead_pct=float("nan")))
        # the explicit skip-object spelling is the honest form
        rec = reg.emit_ckpt("OK", **self._fields(
            write_ms=("skipped", "no async save landed")))
        assert monitor.validate(rec) == []

    def test_skip_needs_reason(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="reason"):
            reg.emit_ckpt("SKIP", **self._fields())
        rec = reg.emit_ckpt("SKIP", reason="cpu smoke",
                            **self._fields())
        assert monitor.validate(rec) == []
        # externally-produced reason-less SKIP fails the validator too
        bad = dict(rec)
        bad.pop("reason")
        assert any("reason" in e for e in monitor.validate(bad))

    def test_junk_manifest_key_fails_validation(self):
        reg = monitor.MetricsRegistry()
        rec = reg.emit_ckpt("OK", **self._fields())
        rec["manifest"] = dict(rec["manifest"], junk=1)
        errs = monitor.validate(rec)
        assert any("junk" in e or "additional" in e.lower()
                   for e in errs), errs

    def test_validator_cli_forced_dispatch(self, tmp_path, capsys):
        reg = monitor.MetricsRegistry()
        good = reg.emit_ckpt("OK", **self._fields())
        p_ok = tmp_path / "ok.jsonl"
        p_ok.write_text(json.dumps(good) + "\n")
        assert validate_metrics.main(["--ckpt", str(p_ok)]) == 0
        capsys.readouterr()
        # wrong kind under --ckpt fails as a bad ckpt artifact
        p_bad = tmp_path / "bad.json"
        p_bad.write_text(json.dumps({"kind": "serve", "schema": 1,
                                     "status": "OK"}))
        assert validate_metrics.main(["--ckpt", str(p_bad)]) == 1
        assert "expected a 'ckpt'" in capsys.readouterr().err
        # nan inside an OK record fails
        evil = dict(good, step_ms="nan")
        p_evil = tmp_path / "evil.json"
        p_evil.write_text(json.dumps(evil))
        assert validate_metrics.main(["--ckpt", str(p_evil)]) == 1

    def test_report_renders_ckpt_line(self):
        from apex_tpu.monitor.report import aggregate, render

        reg = monitor.MetricsRegistry()
        rec = reg.emit_ckpt("OK", **self._fields())
        out = render(aggregate([rec]))
        assert "ckpt" in out
        assert "save overhead 1.50%/step" in out
        assert "bitwise-resume ok" in out
        skip = reg.emit_ckpt("SKIP", reason="cpu smoke",
                             **self._fields())
        assert "SKIP(cpu smoke)" in render(aggregate([skip]))

    def test_bench_history_gates_save_overhead(self, tmp_path, capsys):
        reg = monitor.MetricsRegistry()
        hist = reg.emit_ckpt("OK", **self._fields(
            save_overhead_pct=1.0))
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(hist))

        fresh_ok = reg.emit_ckpt("OK", **self._fields(
            save_overhead_pct=1.5))
        p = tmp_path / "fresh.json"
        p.write_text(json.dumps(fresh_ok))
        rc = bench_history.main([str(p), "--root", str(tmp_path),
                                 "--tolerance-pct", "3"])
        out = capsys.readouterr().out
        assert rc == 0 and "ckpt_save_overhead_pct" in out

        # drift UP beyond tolerance+spread regresses (lower-is-better,
        # absolute points)
        fresh_bad = reg.emit_ckpt("OK", **self._fields(
            save_overhead_pct=6.0))
        p.write_text(json.dumps(fresh_bad))
        rc = bench_history.main([str(p), "--root", str(tmp_path),
                                 "--tolerance-pct", "3"])
        assert rc == 1
        assert "REGRESSION" in capsys.readouterr().out

        # a SKIP record claims nothing
        skip = reg.emit_ckpt("SKIP", reason="cpu smoke",
                             **self._fields())
        p.write_text(json.dumps(skip))
        rc = bench_history.main([str(p), "--root", str(tmp_path)])
        assert rc == 0
        assert "SKIP" in capsys.readouterr().out


class TestCkptBenchLeg:
    """``bench.py --ckpt`` end-to-end at smoke scale: off-TPU it must
    still run the whole leg (train, async saves, both resume
    witnesses) and emit an explicit SKIP(reason) record — never an OK
    claim from a CPU."""

    def test_in_process_smoke(self, capsys):
        import importlib.util

        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_for_ckpt", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        bench.ckpt_main()
        out = capsys.readouterr().out.strip().splitlines()[-1]
        rec = json.loads(out)
        assert rec["kind"] == "ckpt"
        assert rec["status"] == "SKIP" and rec["reason"]
        assert rec["bitwise_resume_ok"] is True
        assert rec["elastic_resume_ok"] is True
        assert rec["saves"] >= 1
        assert rec["manifest"]["dp"] == rec["dp"]
        assert monitor.validate(rec) == []


class TestHotSwapFromCheckpoint:
    """The ckpt → serving integration: params restored from a sharded
    checkpoint hot-swap into a live engine (engine-level swap
    semantics are covered in tests/test_serving.py)."""

    def test_restore_params_swaps_token_identically(self, tmp_path):
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.serving import Request, ServingEngine

        cfg = GPTConfig(vocab_size=97, max_seq_len=128, hidden_size=32,
                        num_layers=2, num_heads=4, num_kv_heads=2,
                        attention_impl="flash", remat=False, dropout=0.0)
        model = GPTModel(cfg)
        params = model.init(K)

        # checkpoint the model's params through the sharded format
        mesh = mesh_lib.make_mesh()
        zopt = distributed_fused_adam(learning_rate=1e-3,
                                      chunk_size=CHUNK)
        zstate = mesh_lib.shard_map(lambda p: zopt.init(p), mesh=mesh,
                                    in_specs=P(), out_specs=P())(params)
        g = gather_zero_state(zstate, mesh)
        d = str(tmp_path / "ck")
        ckpt_lib.save_zero_sharded(d, g, dp=8, params=params, step=0)
        new_params = ckpt_lib.restore_params(d, like=params)
        for a, e in zip(jax.tree.leaves(new_params),
                        jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(e))

        def serve(swap):
            eng = ServingEngine(model, num_slots=2, block_size=8,
                                prefill_chunk=8, max_seq_len=64)
            if swap:
                eng.request_swap(new_params, at_step=4,
                                 source="step_00000000")
            reqs = [Request(
                rid=i,
                prompt=np.asarray(jr.randint(jr.fold_in(K, 30 + i),
                                             (6,), 0, 97), np.int32),
                max_new_tokens=8) for i in range(2)]
            done = eng.serve(params, reqs)
            assert eng.prefill_chunk._cache_size() == 1
            assert eng.decode_step._cache_size() == 1
            return ({r.rid: list(r.tokens) for r in done},
                    eng.last_stats.swaps)

        toks_base, swaps_base = serve(False)
        toks_swap, swaps_swap = serve(True)
        assert swaps_base == 0 and swaps_swap == 1
        assert toks_base == toks_swap  # equal weights → identical streams
