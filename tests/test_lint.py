"""apexlint tests: one positive + one negative fixture per rule, CLI
exit-code and JSON-schema behavior, and the tier-1 dogfood gate — the
linter runs clean over ``apex_tpu/`` with the committed baseline, so any
new finding fails CI until it is fixed or baselined with a reason.
"""

import json
import os
import sys

import pytest

from apex_tpu import lint
from apex_tpu.lint.__main__ import main as lint_main

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
BASELINE = os.path.join(REPO, "tools", "apexlint_baseline.json")

# --- per-rule fixtures --------------------------------------------------------
# (bad triggers the code, good is the nearest legitimate idiom — drawn from
# real patterns in this repo wherever one exists)

FIXTURES = {
    "APX101": (
        """
import jax
@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
""",
        """
import jax
import jax.numpy as jnp
@jax.jit
def f(x, mask=None):
    if mask is None:          # static pytree-structure check: fine
        mask = jnp.ones_like(x)
    if x.shape[0] > 2:        # shape is static under trace: fine
        x = x * 2
    return jnp.where(x > 0, x, -x)
""",
    ),
    "APX102": (
        """
import jax
@jax.jit
def f(x):
    return x * int(x)
""",
        """
import jax
@jax.jit
def f(x):
    return x * int(x.shape[0])
""",
    ),
    "APX103": (
        """
import jax
import numpy as np
@jax.jit
def f(x):
    return np.sum(x)
""",
        """
import jax
import numpy as np
@jax.jit
def f(x):
    return x * np.prod(x.shape)
""",
    ),
    "APX104": (
        """
import jax
def g(a, b):
    return a + b
h = jax.jit(g, static_argnums=(5,))
""",
        """
import jax
def g(a, b):
    return a + b
h = jax.jit(g, static_argnums=(1,))
""",
    ),
    "APX105": (
        """
def is_kernel_available(mask, b, np, sq, sk):
    return sk % 128 == 0
""",
        """
def is_kernel_available(mask, b, nh, sq, sk):
    return sk % 128 == 0
""",
    ),
    "APX106": (
        """
import jax
def score(m):
    return m * 2
def search(m):
    fn = jax.jit(score)
    return fn(m)
""",
        """
import jax
def score(m):
    return m * 2
_score = jax.jit(score)
def search(m):
    return _score(m)
""",
    ),
    "APX107": (
        """
import jax
@jax.jit
def apply_all(params, x):
    total = x
    for k in set(params):
        total = total + params[k]
    return total
""",
        """
import jax
@jax.jit
def apply_all(params, x):
    total = x
    for k in sorted(params):
        total = total + params[k]
    for v in params.values():
        total = total + v
    return total
""",
    ),
    "APX201": (
        """
import jax
def f(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    out = step(x, y)
    return out + x
""",
        """
import jax
def f(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    x = step(x, y)
    return x + y
""",
    ),
    "APX202": (
        """
import jax
def train(params, batches):
    step = jax.jit(lambda p, b: p, donate_argnums=(0,))
    for b in batches:
        loss = step(params, b)
    return loss
""",
        """
import jax
def train(params, batches):
    step = jax.jit(lambda p, b: (p, 0.0), donate_argnums=(0,))
    for b in batches:
        params, loss = step(params, b)
    return params, loss
""",
    ),
    "APX301": (
        """
from jax.experimental import pallas as pl
spec = pl.BlockSpec((8, 100), lambda i: (i, 0))
""",
        """
from jax.experimental import pallas as pl
bn = 100
specs = [pl.BlockSpec((8, 128), lambda i: (i, 0)),
         pl.BlockSpec((1, 1, 128), lambda i: (i, 0, 0)),
         pl.BlockSpec((8, bn), lambda i: (i, 0))]
""",
    ),
    "APX302": (
        """
from jax.experimental import pallas as pl
def f(k, x):
    return pl.pallas_call(
        k, grid=(4, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
        out_shape=None, interpret=True)(x)
""",
        """
from jax.experimental import pallas as pl
def f(k, x):
    return pl.pallas_call(
        k, grid=(4, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
        out_shape=None, interpret=True)(x)
""",
    ),
    "APX303": (
        """
from jax.experimental import pallas as pl
def f(k, x):
    return pl.pallas_call(k, grid=(4,), out_shape=None)(x)
""",
        """
from jax.experimental import pallas as pl
def f(k, x, interpret=False):
    return pl.pallas_call(k, grid=(4,), out_shape=None,
                          interpret=interpret)(x)
""",
    ),
    "APX304": (
        """
from apex_tpu.models.t5 import relative_bias
from apex_tpu.ops.attention import flash_attention
def f(q, k, v, table, s):
    bias = relative_bias(table, s, s, bidirectional=True,
                         num_buckets=32, max_distance=128)
    return flash_attention(q, k, v, causal=False, bias=bias[0])
""",
        """
from apex_tpu.ops.attention import BucketedBias, flash_attention
def f(q, k, v, table):
    return flash_attention(
        q, k, v, causal=False,
        bias=BucketedBias(table, bidirectional=True, max_distance=128))
""",
    ),
    "APX403": (
        """
import jax
import jax.numpy as jnp
def f(x, w):
    xg = jax.lax.all_gather(x, "tp", axis=0, tiled=True)
    return jnp.dot(xg, w.T)
""",
        """
from apex_tpu.ops.collective_matmul import all_gather_matmul
def f(x, w):
    return all_gather_matmul(x, w, axis_name="tp", seq_dim=0)
""",
    ),
    "APX404": (
        """
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
def tick(stage_fn, params, y_prev):
    x = p2p.send_forward(y_prev)
    return stage_fn(params, x)
""",
        """
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
def tick(stage_fn, params, x, y_prev):
    sent, y = p2p.rotate_overlapped(y_prev, lambda: stage_fn(params, x))
    return sent, y
""",
    ),
    "APX405": (
        """
import jax
def hot(x):
    return jax.lax.psum(x, "tp")
def cold(x):
    return x
def step(pred, x):
    return jax.lax.cond(pred, hot, cold, x)
""",
        """
import jax
def hot(x):
    return jax.lax.psum(x, "tp")
def cold(x):
    return jax.lax.psum(x * 0.0, "tp")
def step(pred, x):
    return jax.lax.cond(pred, hot, cold, x)
""",
    ),
    "APX401": (
        """
import jax
def f(x):
    return jax.lax.psum(x, "dpp")
""",
        """
import jax
def f(x, axis_name="dp"):
    return jax.lax.psum(x, axis_name) + jax.lax.psum(x, "tp")
""",
    ),
    "APX402": (
        """
from jax.sharding import PartitionSpec as P
spec = P("model", None)
""",
        """
from jax.sharding import PartitionSpec as P
spec = P("dp", None, "tp")
""",
    ),
    "APX501": (
        """
def attn(q, k, v, dropout=0.1, is_training=True):
    return q
""",
        """
def attn(q, k, v, dropout=0.1, is_training=True, key=None):
    if dropout > 0 and is_training and key is None:
        raise ValueError("dropout needs a key")
    return q
""",
    ),
    "APX502": (
        """
import jax
def make_stream():
    return jax.random.PRNGKey(42)
""",
        """
import jax
def make_stream(seed):
    return jax.random.PRNGKey(seed)
""",
    ),
    "APX503": (
        """
import jax.numpy as jnp
def f(a, b):
    return a.astype(jnp.bfloat16) * b.astype(jnp.float32)
""",
        """
import jax.numpy as jnp
def f(a, b):
    return (a.astype(jnp.float32) * b.astype(jnp.float32)
            ).astype(jnp.bfloat16)
""",
    ),
}


class TestRuleFixtures:
    @pytest.mark.parametrize("code", sorted(FIXTURES))
    def test_positive(self, code):
        bad, _ = FIXTURES[code]
        findings, _ = lint.lint_source(bad, path="apex_tpu/fixture.py")
        assert code in {f.code for f in findings}, (
            f"{code} failed to fire on its bad fixture: {findings}")

    @pytest.mark.parametrize("code", sorted(FIXTURES))
    def test_negative(self, code):
        _, good = FIXTURES[code]
        findings, _ = lint.lint_source(good, path="apex_tpu/fixture.py")
        assert code not in {f.code for f in findings}, (
            f"{code} false-positived on its good fixture: "
            f"{[f.render() for f in findings if f.code == code]}")

    def test_every_registered_rule_has_fixtures(self):
        codes = {r.code for r in lint.iter_rules()}
        assert codes - {lint.PARSE_ERROR_CODE} == set(FIXTURES)

    def test_rule_breadth_meets_acceptance(self):
        """>= 10 distinct codes spanning all five APX families."""
        codes = sorted(FIXTURES)
        assert len(codes) >= 10
        families = {c[:4] for c in codes}
        assert families == {"APX1", "APX2", "APX3", "APX4", "APX5"}

    def test_apx502_skips_test_paths(self):
        bad, _ = FIXTURES["APX502"]
        findings, _ = lint.lint_source(bad, path="tests/test_fixture.py")
        assert "APX502" not in {f.code for f in findings}

    def test_parse_error_is_a_finding(self):
        findings, _ = lint.lint_source("def broken(:\n", path="x.py")
        assert [f.code for f in findings] == [lint.PARSE_ERROR_CODE]

    def test_apx201_same_statement_read_after_call(self):
        src = """
import jax
def f(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    out = step(x, y) + x
    return out
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX201" in {f.code for f in findings}

    def test_apx201_skips_sibling_exclusive_branch(self):
        src = """
import jax
def f(x, y, cond):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    if cond:
        out = step(x, y)
    else:
        out = x * 2
    return out
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX201" not in {f.code for f in findings}

    def test_apx202_fires_for_donate_argnames_too(self):
        src = """
import jax
def f(cache, x):
    return cache, x
step = jax.jit(f, donate_argnames=("cache",))
def loop(cache, xs):
    for x in xs:
        out = step(cache, x)
    return out
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX202" in {f.code for f in findings}

    def test_apx201_same_branch_read_flagged_at_true_line(self):
        src = """
import jax
def f(c, x, flag):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    if flag:
        out = step(c, x)
        print(c)
        return out
    return c
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        hits = [f for f in findings if f.code == "APX201"]
        # the dead read is print(c) at line 7, same branch; the return c
        # at line 9 runs only on the no-donation path and must NOT be the
        # cited line
        assert len(hits) == 1 and "line 7" in hits[0].message

    def test_apx201_post_branch_read_after_conditional_donation_not_flagged(
            self):
        src = """
import jax
def f(c, x, flag):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    if flag:
        out = step(c, x)
        return out
    return c * 2
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX201" not in {f.code for f in findings}

    def test_negative_static_argnums_parse_and_resolve(self):
        src = """
import jax
import functools
@functools.partial(jax.jit, static_argnums=(-1,))
def f(x, mode):
    if mode == "fast":
        return x * 2
    return x
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        codes = {f.code for f in findings}
        # -1 resolves to `mode` (static): no APX104, and the branch on the
        # static param is not a tracing hazard
        assert "APX104" not in codes and "APX101" not in codes

    def test_getattr_does_not_launder_taint(self):
        src = """
import jax
@jax.jit
def f(x):
    if getattr(x, "T").sum():
        return x
    return -x
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX101" in {f.code for f in findings}
        good = src.replace('getattr(x, "T").sum()',
                           'getattr(x, "ndim") > 1')
        findings, _ = lint.lint_source(good, path="apex_tpu/fixture.py")
        assert "APX101" not in {f.code for f in findings}

    def test_disable_all_is_case_insensitive(self):
        src = ('from jax.experimental import pallas as pl\n'
               'spec = pl.BlockSpec((8, 100), lambda i: (i, 0))'
               '  # apexlint: disable=ALL\n')
        findings, suppressed = lint.lint_source(src, path="x.py")
        assert not findings and suppressed == 1

    def test_apx201_read_in_rebinding_statement_still_flagged(self):
        src = """
import jax
def f(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    out = step(x, y)
    x = x * 2
    return out + x
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        hits = [f for f in findings if f.code == "APX201"]
        assert len(hits) == 1 and "line 6" in hits[0].message

    def test_apx502_not_disabled_by_testlike_checkout_prefix(self):
        bad, _ = FIXTURES["APX502"]
        findings, _ = lint.lint_source(
            bad, path="/home/testuser/repo/apex_tpu/engine.py")
        assert "APX502" in {f.code for f in findings}
        # exact test-directory components still exempt
        findings, _ = lint.lint_source(bad, path="repo/tests/helper.py")
        assert "APX502" not in {f.code for f in findings}

    def test_apx401_axis_kwarg_is_a_dimension_not_a_name(self):
        src = """
import jax
def f(x):
    return jax.lax.all_gather(x, "dpp", axis=0)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX401" in {f.code for f in findings}

    def test_apx302_star_args_index_map_exempt(self):
        src = """
from jax.experimental import pallas as pl
def f(k, x):
    return pl.pallas_call(
        k, grid=(4, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda *ixs: (ixs[0], 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
        out_shape=None, interpret=True)(x)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX302" not in {f.code for f in findings}

    def test_apx501_bare_rate_is_not_dropout(self):
        src = """
def apply_decay(step, rate, train):
    return rate if train else 0.0
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX501" not in {f.code for f in findings}

    def test_apx104_int_valued_name_element_is_legal(self):
        src = """
import jax
AXIS = 1
def g(a, b):
    return a + b
h = jax.jit(g, static_argnums=(AXIS,))
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX104" not in {f.code for f in findings}

    def test_apx202_loop_target_is_a_fresh_buffer(self):
        src = """
import jax
def f(bufs):
    step = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    outs = []
    for b in bufs:
        outs.append(step(b))
    return outs
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX202" not in {f.code for f in findings}

    def test_apx401_pmap_positional_axis_name_allowed(self):
        src = """
import jax
def inner(x):
    return jax.lax.psum(x, "batch")
g = jax.pmap(inner, "batch")
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX401" not in {f.code for f in findings}

    def test_directive_inside_string_literal_is_not_a_directive(self):
        src = ('import jax\n'
               'def f():\n'
               '    m = "docs: # apexlint: disable=all"; '
               'k = jax.random.PRNGKey(0)\n'
               '    return m, k\n')
        findings, suppressed = lint.lint_source(
            src, path="apex_tpu/fixture.py")
        assert "APX502" in {f.code for f in findings} and suppressed == 0

    def test_empty_registry_refuses_to_run(self, monkeypatch):
        monkeypatch.setattr(lint.core, "REGISTRY", {})
        with pytest.raises(RuntimeError, match="no rules registered"):
            lint.lint_source("x = 1\n", path="x.py")

    def test_decorated_method_static_argnums_count_self(self):
        # jit decorating a METHOD wraps the unbound function: index 0 is
        # self, index 1 is `n` — neither APX104 nor APX101 may fire
        src = """
import jax
import functools
class E:
    @functools.partial(jax.jit, static_argnums=(1,))
    def step(self, n, x):
        if n > 0:
            return x * n
        return x
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        codes = {f.code for f in findings}
        assert "APX104" not in codes and "APX101" not in codes

    def test_decorated_method_donation_shifts_to_call_site(self):
        # donate_argnums=(1,) on a decorated method donates `cache`,
        # which is call-site position 0 of self.step(cache, tok)
        src = """
import jax
import functools
class E:
    @functools.partial(jax.jit, donate_argnums=(1,))
    def step(self, cache, tok):
        return cache, tok
    def serve(self, cache, toks):
        for t in toks:
            out, _ = self.step(cache, t)
        return out
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        hits = [f for f in findings if f.code == "APX202"]
        assert len(hits) == 1 and "`cache`" in hits[0].message

    def test_testlike_exemption_scoped_below_scan_root(self, tmp_path):
        # a checkout under .../examples/... must not disable APX502:
        # test-likeness is judged on the path below the scanned argument
        pkg = tmp_path / "examples" / "repo" / "mylib"
        pkg.mkdir(parents=True)
        (pkg / "engine.py").write_text(
            "import jax\ndef f():\n    return jax.random.PRNGKey(0)\n")
        findings, _ = lint.lint_paths([str(pkg)])
        assert "APX502" in {f.code for f in findings}
        # while a tests/ dir INSIDE the scanned tree stays exempt
        (pkg / "tests").mkdir()
        (pkg / "tests" / "helper.py").write_text(
            "import jax\ndef f():\n    return jax.random.PRNGKey(0)\n")
        findings, _ = lint.lint_paths([str(pkg)])
        assert len([f for f in findings if f.code == "APX502"]) == 1

    def test_shard_map_wrapped_functions_are_traced(self):
        # ISSUE spec: 'decorated or wrapped with jax.jit/pjit/shard_map'
        src = """
from apex_tpu.parallel import mesh as mesh_lib
def body(x):
    if x.sum() > 0:
        return x
    return -x
run = mesh_lib.shard_map(body, mesh=None, in_specs=None, out_specs=None)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX101" in {f.code for f in findings}

    def test_pmap_wrapped_functions_are_traced(self):
        src = """
import jax
def body(x):
    return x * float(x)
g = jax.pmap(body)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX102" in {f.code for f in findings}

    def test_non_utf8_file_is_a_finding_not_a_crash(self, tmp_path):
        p = tmp_path / "legacy.py"
        p.write_bytes(b"# coding: latin-1\n# caf\xe9\nx = 1\n")
        bad = tmp_path / "broken.py"
        bad.write_bytes(b"\xff\xfe garbage not a coding\n")
        findings, _ = lint.lint_paths([str(tmp_path)])
        # the PEP-263 latin-1 file decodes fine; the undecodable one
        # becomes an APX000 finding instead of an uncaught traceback
        assert [f.code for f in findings] == [lint.PARSE_ERROR_CODE]
        assert "broken.py" in findings[0].path

    def test_apx201_augassign_reads_the_dead_buffer(self):
        src = """
import jax
def f(a, b):
    step = jax.jit(lambda x, y: x + y, donate_argnums=(0,))
    out = step(a, b)
    a += 1
    return out
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX201" in {f.code for f in findings}

    def test_multiple_wraps_intersect_statics(self):
        # one static wrap must not silence the hazard the plain wrap traces
        src = """
import jax
def f(n, x):
    if n > 0:
        return x
    return -x
g1 = jax.jit(f, static_argnums=(0,))
g2 = jax.jit(f)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX101" in {f.code for f in findings}

    def test_apx104_static_argnums_none_is_legal(self):
        src = """
import jax
def g(a, b):
    return a + b
h = jax.jit(g, static_argnums=None)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX104" not in {f.code for f in findings}

    def test_apx201_del_after_donation_is_not_a_read(self):
        src = """
import jax
def f(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    out = step(x, y)
    del x
    return out
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX201" not in {f.code for f in findings}

    def test_apx401_binder_bound_axis_allowed(self):
        src = """
import jax
def inner(x):
    return jax.lax.psum(x, "batch")
f = jax.pmap(inner, axis_name="batch")
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX401" not in {f.code for f in findings}

    def test_apx401_402_mesh_positional_axis_names_allowed(self):
        src = """
import jax
from jax.sharding import Mesh, PartitionSpec
def build(devices, v):
    mesh = Mesh(devices, ("x", "y"))
    spec = PartitionSpec("x", "y")
    return mesh, spec, jax.lax.psum(v, "x")
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        codes = {f.code for f in findings}
        assert "APX401" not in codes and "APX402" not in codes

    def test_apx502_keyword_seed_spelling_flagged(self):
        src = """
import jax
def make_stream():
    return jax.random.PRNGKey(seed=7)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX502" in {f.code for f in findings}

    def test_apx106_skips_once_per_instance_attribute_wrap(self):
        src = """
import jax
def step(p, g):
    return p - g
class Engine:
    def __init__(self):
        self.step = jax.jit(step, static_argnums=(1,))
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/fixture.py")
        assert "APX106" not in {f.code for f in findings}


class TestSuppression:
    def test_inline_disable(self):
        src = ('from jax.experimental import pallas as pl\n'
               'spec = pl.BlockSpec((8, 100), lambda i: (i, 0))'
               '  # apexlint: disable=APX301\n')
        findings, suppressed = lint.lint_source(src, path="x.py")
        assert not findings and suppressed == 1

    def test_inline_disable_all(self):
        src = ('from jax.experimental import pallas as pl\n'
               'spec = pl.BlockSpec((8, 100), lambda i: (i, 0))'
               '  # apexlint: disable=all\n')
        findings, suppressed = lint.lint_source(src, path="x.py")
        assert not findings and suppressed == 1

    def test_trailing_prose_after_code_still_suppresses(self):
        src = ('from jax.experimental import pallas as pl\n'
               'spec = pl.BlockSpec((8, 100), lambda i: (i, 0))'
               '  # apexlint: disable=APX301 - ragged edge is masked\n')
        findings, suppressed = lint.lint_source(src, path="x.py")
        assert not findings and suppressed == 1

    def test_typod_long_code_does_not_prefix_suppress(self):
        # 'APX3019' must not silently suppress APX301 via prefix match
        src = ('from jax.experimental import pallas as pl\n'
               'spec = pl.BlockSpec((8, 100), lambda i: (i, 0))'
               '  # apexlint: disable=APX3019\n')
        findings, suppressed = lint.lint_source(src, path="x.py")
        assert [f.code for f in findings] == ["APX301"] and suppressed == 0

    def test_wrong_code_does_not_suppress(self):
        src = ('from jax.experimental import pallas as pl\n'
               'spec = pl.BlockSpec((8, 100), lambda i: (i, 0))'
               '  # apexlint: disable=APX999\n')
        findings, suppressed = lint.lint_source(src, path="x.py")
        assert [f.code for f in findings] == ["APX301"] and suppressed == 0


class TestBaseline:
    def test_match_and_unused(self):
        f1 = lint.Finding("apex_tpu/a.py", 3, 0, "APX301", "m")
        f2 = lint.Finding("apex_tpu/b.py", 9, 0, "APX502", "m")
        entries = [
            {"path": "apex_tpu/a.py", "code": "APX301", "reason": "r"},
            {"path": "apex_tpu/zz.py", "code": "APX101", "reason": "r"},
        ]
        kept, baselined, unused = lint.apply_baseline([f1, f2], entries)
        assert kept == [f2] and baselined == 1
        assert unused == [entries[1]]

    def test_reason_required(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps(
            {"version": 1,
             "entries": [{"path": "a.py", "code": "APX301"}]}))
        with pytest.raises(ValueError, match="reason"):
            lint.load_baseline(str(p))

    def test_committed_baseline_entries_all_carry_reasons(self):
        entries = lint.load_baseline(BASELINE)  # raises if malformed
        assert all(len(e["reason"]) > 20 for e in entries), (
            "baseline reasons must actually explain the intent")


class TestCLI:
    def _run(self, argv, capsys):
        rc = lint_main(argv)
        out = capsys.readouterr()
        return rc, out.out, out.err

    def test_exit_1_on_findings(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(FIXTURES["APX301"][0])
        rc, out, _ = self._run([str(bad)], capsys)
        assert rc == 1 and "APX301" in out

    def test_exit_0_on_clean(self, tmp_path, capsys):
        good = tmp_path / "good.py"
        good.write_text(FIXTURES["APX301"][1])
        rc, out, _ = self._run([str(good)], capsys)
        assert rc == 0 and "0 finding(s)" in out

    def test_exit_2_on_missing_path(self, tmp_path, capsys):
        rc, _, err = self._run([str(tmp_path / "nope.xyz")], capsys)
        assert rc == 2 and "error" in err

    def test_exit_2_on_no_args(self, capsys):
        rc, _, err = self._run([], capsys)
        assert rc == 2

    def test_select_and_ignore(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(FIXTURES["APX301"][0] + FIXTURES["APX502"][0])
        rc, out, _ = self._run([str(bad), "--select", "APX3"], capsys)
        assert rc == 1 and "APX301" in out and "APX502" not in out
        rc, out, _ = self._run([str(bad), "--ignore", "APX3,APX5"], capsys)
        assert rc == 0

    def test_json_report_validates(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(FIXTURES["APX301"][0] + FIXTURES["APX502"][0])
        rc, out, _ = self._run([str(bad), "--format", "json"], capsys)
        assert rc == 1
        report = json.loads(out)
        assert lint.validate_report(report) == []
        assert report["counts"]["APX301"] == 1
        assert report["files_scanned"] == 1

    def test_list_rules(self, capsys):
        rc, out, _ = self._run(["--list-rules"], capsys)
        assert rc == 0
        for r in lint.iter_rules():
            assert r.code in out

    def test_baseline_flag(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(FIXTURES["APX301"][0])
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"version": 1, "entries": [
            {"path": str(bad).replace(os.sep, "/"), "code": "APX301",
             "reason": "fixture: documented-intentional"}]}))
        rc, out, _ = self._run(
            [str(bad), "--baseline", str(bl)], capsys)
        assert rc == 0 and "1 baselined" in out


class TestReportSchema:
    def test_rejects_corruption(self):
        report = lint.build_report(
            [lint.Finding("a.py", 2, 0, "APX101", "m")],
            {"files_scanned": 1, "suppressed_inline": 0})
        assert lint.validate_report(report) == []
        for mutate in (
            lambda r: r.update(tool="other"),
            lambda r: r.update(version=99),
            lambda r: r["findings"][0].update(line=0),
            lambda r: r["findings"][0].update(code="E501"),
            lambda r: r["findings"][0].update(message=""),
            lambda r: r.update(counts={"APX101": 7}),
            lambda r: r.update(files_scanned=-1),
            lambda r: r.pop("counts"),
        ):
            broken = json.loads(json.dumps(report))
            mutate(broken)
            assert lint.validate_report(broken), mutate

    def test_not_an_object(self):
        assert lint.validate_report([1, 2]) != []


class TestValidateMetricsLintReport:
    """tools/validate_metrics.py --lint-report gates the lint artifact the
    same way bench/gate artifacts are gated."""

    def _vm(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import validate_metrics
        finally:
            sys.path.pop(0)
        return validate_metrics

    def test_roundtrip_from_cli_report(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(FIXTURES["APX301"][0])
        assert lint_main([str(bad), "--format", "json"]) == 1
        report_path = tmp_path / "lint.json"
        report_path.write_text(capsys.readouterr().out)
        vm = self._vm()
        assert vm.main(["--lint-report", str(report_path)]) == 0
        capsys.readouterr()

    def test_content_dispatch_without_flag(self, tmp_path, capsys):
        report = lint.build_report(
            [], {"files_scanned": 3, "suppressed_inline": 0})
        p = tmp_path / "lint.json"
        p.write_text(json.dumps(report))
        vm = self._vm()
        assert vm.main([str(p)]) == 0
        capsys.readouterr()

    def test_corrupt_report_fails(self, tmp_path, capsys):
        report = lint.build_report(
            [lint.Finding("a.py", 2, 0, "APX101", "m")],
            {"files_scanned": 1, "suppressed_inline": 0})
        report["counts"] = {"APX101": 99}
        p = tmp_path / "lint.json"
        p.write_text(json.dumps(report))
        vm = self._vm()
        assert vm.main(["--lint-report", str(p)]) == 1
        err = capsys.readouterr().err
        assert "disagree" in err

    def test_flag_forces_lint_interpretation(self, tmp_path, capsys):
        # a report that lost its tool key: content dispatch would call it
        # an unrecognized shape; --lint-report must fail it AS a lint report
        p = tmp_path / "lint.json"
        p.write_text(json.dumps({"findings": []}))
        vm = self._vm()
        assert vm.main(["--lint-report", str(p)]) == 1
        assert "tool" in capsys.readouterr().err


class TestDogfoodGate:
    """The tier-1 gate: apexlint over apex_tpu/ must be clean modulo the
    committed baseline. A new hazard anywhere in the library fails the
    suite until fixed or baselined-with-reason."""

    def test_apex_tpu_lints_clean_through_real_cli(self, monkeypatch,
                                                   capsys):
        """The acceptance-criterion invocation — `python -m apex_tpu.lint
        apex_tpu/` with no flags — driven through the CLI entry point
        (argparse, exit codes, default package-relative baseline). Run
        in-process rather than via subprocess purely to keep the tier-1
        wall-clock down (a subprocess re-pays the jax import)."""
        monkeypatch.chdir(REPO)
        rc = lint_main(["apex_tpu/", "--format", "json"])
        out = capsys.readouterr().out
        assert rc == 0, (
            f"apexlint found non-baselined findings:\n{out}\n"
            "fix them or baseline with a reason in "
            "tools/apexlint_baseline.json")
        report = json.loads(out)
        assert lint.validate_report(report) == []
        assert report["findings"] == []
        assert report["suppressed_baseline"] >= 1
        assert report["files_scanned"] > 100

    def test_no_baseline_resurfaces_the_baselined_finding(self, capsys):
        rc = lint_main([os.path.join(REPO, "apex_tpu", "inference",
                                     "engine.py"), "--no-baseline"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "APX502" in out  # the engine's documented dummy key

    def test_committed_baseline_has_no_stale_entries(self, capsys):
        """Every committed baseline entry still matches a live finding —
        checked on the one file the baseline names (cheap), with the
        explicit --baseline path so unused-entry warnings engage."""
        entries = lint.load_baseline(BASELINE)
        paths = sorted({os.path.join(REPO, e["path"]) for e in entries})
        rc = lint_main(paths + ["--baseline", BASELINE])
        captured = capsys.readouterr()
        assert rc == 0
        assert "unused baseline entry" not in captured.err

    def test_gate_scope_has_no_inline_all_suppressions(self):
        """`disable=all` is for fixtures/docs, not the library: every
        library suppression must name its code (reviewable intent)."""
        for root, dirs, names in os.walk(os.path.join(REPO, "apex_tpu")):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for n in names:
                if not n.endswith(".py"):
                    continue
                text = open(os.path.join(root, n), encoding="utf-8").read()
                assert "apexlint: disable=all" not in text, (
                    os.path.join(root, n))


class TestDocsCatalogue:
    """docs/api/lint.md is under the enforced docs tier: every registered
    rule appears with a bad + good snippet."""

    def test_every_rule_documented(self):
        path = os.path.join(REPO, "docs", "api", "lint.md")
        text = open(path, encoding="utf-8").read()
        for r in lint.iter_rules():
            assert f"### {r.code}" in text, f"{r.code} missing from lint.md"
        n_rules = len(lint.iter_rules())
        assert text.count("```python") >= 2 * n_rules, (
            "each rule needs a bad and a good snippet")
        for needle in ("--baseline", "apexlint: disable=", "--format json",
                       "tools/apexlint_baseline.json"):
            assert needle in text, f"lint.md lost its {needle} workflow"

    def test_every_jxp_contract_documented(self):
        """The jaxpr-contract catalogue is under the same docs
        discipline: every JXP code gets a ### entry with a bad and a
        good trace snippet, and the --jaxpr workflow needles stay."""
        from apex_tpu.lint.contracts import JXP_CODES
        path = os.path.join(REPO, "docs", "api", "lint.md")
        text = open(path, encoding="utf-8").read()
        for code in JXP_CODES:
            assert f"### {code}" in text, f"{code} missing from lint.md"
        n_total = len(lint.iter_rules()) + len(JXP_CODES)
        assert text.count("```python") >= 2 * n_total, (
            "each APX rule AND each JXP contract needs a bad and a "
            "good snippet")
        for needle in ("--jaxpr", "--entrypoint", "--static-cost",
                       "--costdb", "--list-entrypoints",
                       "jaxpr:", "assert_contracts"):
            assert needle in text, f"lint.md lost its {needle} workflow"


class TestAPX304MaterializedBias:
    """Beyond the fixture pair: the taint survives name hops and
    subscripts, .materialize() counts as a materializer, and the
    positional bias slot of fused_qkv_attention is covered."""

    def test_materialize_method_into_ring(self):
        src = """
from apex_tpu.ops.attention import ring_attention
def f(q, k, v, bb, s):
    return ring_attention(q, k, v, bias=bb.materialize(s, s))
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX304" in {f.code for f in findings}

    def test_taint_through_subscript_and_positional_fused(self):
        src = """
from apex_tpu.models import t5
from apex_tpu.ops.attention import fused_qkv_attention
def f(x, w, b, wo, table, s, h, d):
    arr = t5.relative_bias(table, s, s, bidirectional=False,
                           num_buckets=32, max_distance=128)
    full = arr[0]
    return fused_qkv_attention(x, w, b, wo, full, None, None, h, 1, d,
                               1.0, True)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX304" in {f.code for f in findings}

    def test_oracle_materialize_without_attention_is_clean(self):
        src = """
from apex_tpu.ops.attention import BucketedBias
def oracle(bb, s):
    return bb.materialize(s, s)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX304" not in {f.code for f in findings}

    def test_unknown_provenance_param_is_clean(self):
        src = """
from apex_tpu.ops.attention import flash_attention
def f(q, k, v, bias):
    return flash_attention(q, k, v, bias=bias)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX304" not in {f.code for f in findings}

    def test_inline_suppression(self):
        src = """
from apex_tpu.models.t5 import relative_bias
from apex_tpu.ops.attention import flash_attention
def f(q, k, v, t, s):
    bias = relative_bias(t, s, s, bidirectional=True, num_buckets=32,
                         max_distance=128)
    return flash_attention(q, k, v, bias=bias[0])  # apexlint: disable=APX304
"""
        findings, suppressed = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX304" not in {f.code for f in findings}
        assert suppressed == 1


class TestAPX404BlockingP2PFeedsStage:
    """Beyond the fixture pair: the raw lax.ppermute spelling, taint
    through a name hop, and the idioms that must stay clean — the
    collective-matmul rings' per-chunk GEMM on an arrived piece (the
    overlapped pattern itself) and `rotate_overlapped` (the cure)."""

    def test_raw_ppermute_into_matmul(self):
        src = """
import jax
import jax.numpy as jnp
def f(x, w, perm):
    got = jax.lax.ppermute(x, "pp", perm)
    return jnp.dot(got, w)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX404" in {f.code for f in findings}

    def test_helper_through_name_hop_into_stage(self):
        src = """
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
def f(run_block, params, g):
    got = p2p.recv_backward(g)
    gg = got * 2.0
    return run_block(params, gg)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX404" in {f.code for f in findings}

    def test_fused_helper_fires(self):
        # the canonical 1F1B spelling: both directions in one fused hop
        src = """
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
def tick(stage_fn, params, dy, y):
    g, x = p2p.send_backward_recv_forward(dy, y)
    return stage_fn(params, x)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX404" in {f.code for f in findings}

    def test_ring_chunk_gemm_stays_clean(self):
        # the collective-matmul rings' shape: chunk GEMMs on arrived
        # pieces ARE the overlap — "chunk" is deliberately not a stage
        # fragment
        src = """
import jax
def ring(chunk_fn, x, perm):
    fwd = jax.lax.ppermute(x, "tp", perm)
    return chunk_fn(fwd)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX404" not in {f.code for f in findings}

    def test_rotate_overlapped_stays_clean(self):
        src = """
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
def tick(stage_fn, params, x, y_prev):
    sent, y = p2p.rotate_overlapped(y_prev, lambda: stage_fn(params, x))
    return sent, y
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX404" not in {f.code for f in findings}

    def test_inline_disable(self):
        src = """
import jax
def f(stage_fn, p, x, perm):
    got = jax.lax.ppermute(x, "pp", perm)
    return stage_fn(p, got)  # apexlint: disable=APX404
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX404" not in {f.code for f in findings}


class TestAPX405CollectiveUnderDivergentCond:
    """Beyond the fixture pair: lambda branches, lax.switch literal
    branch lists, the shapes that must stay silent (matched collective
    sets, collective-free branches, unresolvable branch expressions —
    never a guess), and the inline disable."""

    def test_lambda_branches_fire(self):
        src = """
from jax import lax
def f(pred, x):
    return lax.cond(pred, lambda v: lax.all_gather(v, "tp"),
                    lambda v: v, x)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX405" in {f.code for f in findings}

    def test_switch_literal_branch_list_fires(self):
        src = """
from jax import lax
def f(i, x):
    return lax.switch(i, [lambda v: lax.psum(v, "dp"),
                          lambda v: v + 1.0], x)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX405" in {f.code for f in findings}

    def test_matched_collective_sets_stay_clean(self):
        # the cure: the cheap branch psums a zero so every chip
        # participates regardless of its predicate
        src = """
from jax import lax
def f(pred, x):
    return lax.cond(pred, lambda v: lax.psum(v, "tp"),
                    lambda v: lax.psum(v * 0.0, "tp"), x)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX405" not in {f.code for f in findings}

    def test_collective_free_branches_stay_clean(self):
        src = """
from jax import lax
def f(pred, x):
    return lax.cond(pred, lambda v: v + 1.0, lambda v: v - 1.0, x)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX405" not in {f.code for f in findings}

    def test_unresolvable_branch_stays_silent(self):
        # a branch we cannot see into (subscript, partial, attribute)
        # must never produce a guess
        src = """
from jax import lax
def f(pred, x, fns):
    return lax.cond(pred, fns[0], fns[1], x)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX405" not in {f.code for f in findings}

    def test_axis_index_is_not_synchronizing(self):
        # axis_index is a local query — branch-dependent use cannot
        # deadlock the mesh
        src = """
from jax import lax
def f(pred, x):
    return lax.cond(pred, lambda v: v + lax.axis_index("tp"),
                    lambda v: v, x)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX405" not in {f.code for f in findings}

    def test_inline_disable(self):
        # the directive rides the line the finding anchors to — the
        # `lax.cond(` call line
        src = """
from jax import lax
def hot(x):
    return lax.psum(x, "tp")
def f(pred, x):
    return lax.cond(pred, hot, lambda v: v, x)  # apexlint: disable=APX405
"""
        findings, suppressed = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX405" not in {f.code for f in findings}
        assert suppressed == 1


class TestAPX403BlockingCollectiveMatmul:
    """Beyond the fixture pair: both directions of the pattern, the
    einsum sink, taint through name hops, and the idioms that must stay
    clean (the blocking oracle keeps its gather and matmul in separate
    functions; a psum_scatter of a non-matmul value is not the pattern)."""

    def test_matmul_into_psum_scatter(self):
        src = """
import jax
import jax.numpy as jnp
def f(x, w):
    y = jnp.dot(x, w.T)
    return jax.lax.psum_scatter(y, "tp", scatter_dimension=0, tiled=True)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX403" in {f.code for f in findings}

    def test_gather_into_einsum_through_name_hop(self):
        src = """
import jax
import jax.numpy as jnp
def f(x, w):
    xg = jax.lax.all_gather(x, "tp", axis=1, tiled=True)
    xx = xg * 2.0
    return jnp.einsum("bsh,oh->bso", xx, w)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX403" in {f.code for f in findings}

    def test_direct_nesting_fires(self):
        src = """
import jax
import jax.numpy as jnp
def f(x, w):
    return jnp.matmul(jax.lax.all_gather(x, "tp", tiled=True), w)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX403" in {f.code for f in findings}

    def test_gather_and_matmul_in_separate_scopes_clean(self):
        # the blocking oracle's shape: _sp_all_gather_seq returns the
        # gather, the dot lives in __call__ — separate taint scopes
        src = """
import jax
import jax.numpy as jnp
def gather(x):
    return jax.lax.all_gather(x, "tp", axis=0, tiled=True)
def matmul(xg, w):
    return jnp.dot(xg, w.T)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX403" not in {f.code for f in findings}

    def test_psum_scatter_of_non_matmul_clean(self):
        src = """
import jax
def f(g):
    return jax.lax.psum_scatter(g, "tp", scatter_dimension=0, tiled=True)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX403" not in {f.code for f in findings}

    def test_inline_suppression(self):
        src = """
import jax
import jax.numpy as jnp
def f(x, w):
    xg = jax.lax.all_gather(x, "tp", axis=0, tiled=True)
    return jnp.dot(xg, w.T)  # apexlint: disable=APX403
"""
        findings, suppressed = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX403" not in {f.code for f in findings}
        assert suppressed == 1


class TestAPX107UnorderedIteration:
    """Beyond the fixture pair: the unordered taint follows assignments
    and dict views, scanned bodies count as traced, and sorted()
    launders."""

    def test_scan_body_counts_as_traced(self):
        src = """
import jax
def body(carry, x):
    total = carry
    for k in set(x):
        total = total + k
    return total, total
def run(xs):
    return jax.lax.scan(body, 0.0, xs)
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX107" in {f.code for f in findings}

    def test_set_ordered_dict_view_flagged(self):
        src = """
import jax
@jax.jit
def f(params):
    acc = {k: 0.0 for k in set(params)}
    out = 0.0
    for v in acc.values():
        out = out + v
    return out
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX107" in {f.code for f in findings}

    def test_set_algebra_on_keys_flagged(self):
        src = """
import jax
@jax.jit
def f(params, x):
    for k in params.keys() - {"bias"}:
        x = x + params[k]
    return x
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX107" in {f.code for f in findings}

    def test_list_wrap_preserves_disorder(self):
        src = """
import jax
@jax.jit
def f(params, x):
    for k in list(set(params)):
        x = x + params[k]
    return x
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX107" in {f.code for f in findings}

    def test_laundering_reassignment_delaunders(self):
        """Applying the rule's own recommended fix through a named
        variable must not keep firing: `ks = sorted(ks)` launders ks,
        cascading to names derived from it."""
        src = """
import jax
@jax.jit
def f(params, x):
    ks = set(params)
    ks = sorted(ks)
    pairs = list(ks)
    for k in pairs:
        x = x + params[k]
    return x
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX107" not in {f.code for f in findings}

    def test_sorted_launders_and_plain_dict_clean(self):
        src = """
import jax
@jax.jit
def f(params, x):
    for k in sorted(set(params)):
        x = x + params[k]
    for k, v in params.items():
        x = x + v
    return x
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX107" not in {f.code for f in findings}

    def test_untraced_function_clean(self):
        src = """
def host_tool(params):
    return {k for k in set(params)}
"""
        findings, _ = lint.lint_source(src, path="apex_tpu/x.py")
        assert "APX107" not in {f.code for f in findings}
