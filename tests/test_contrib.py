"""Contrib-tier tests, mirroring ``apex/contrib/test/``'s per-module suites."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib

K = jr.PRNGKey(55)


class TestDistributedOptimizers:
    def _setup(self):
        mesh = mesh_lib.make_mesh()  # dp=8
        params = {
            "w1": jr.normal(K, (32, 48)),
            "b1": jnp.zeros((48,)),
            "w2": jr.normal(jr.fold_in(K, 1), (48, 8)),
        }
        grads = jax.tree.map(lambda x: jr.normal(jr.fold_in(K, 2), x.shape) * 0.1, params)
        return mesh, params, grads

    def test_zero_adam_matches_fused_adam(self):
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.optimizers import fused_adam

        mesh, params, grads = self._setup()
        zopt = distributed_fused_adam(learning_rate=1e-2, weight_decay=0.01)

        def run(params, grads):
            state = zopt.init(params)
            updates, state = zopt.update(grads, state, params)
            # identical grads on every dp rank ⇒ reduce-scatter mean == grads
            return optax.apply_updates(params, updates)

        new_params = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        )(params, grads)

        ref_opt = fused_adam(learning_rate=1e-2, weight_decay=0.01)
        st = ref_opt.init(params)
        up, _ = ref_opt.update(grads, st, params)
        ref_params = optax.apply_updates(params, up)
        for a, e in zip(jax.tree.leaves(new_params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(a, e, rtol=1e-5, atol=1e-6)

    def test_zero_grad_reduce_dtype_opt_out(self):
        """bf16 grads reduce-scatter in bf16 by default (halved wire
        bytes); ``grad_reduce_dtype=float32`` restores the fp32 reduction
        (the reference DDP's ``allreduce_always_fp32``,
        ``apex/parallel/distributed.py:166``) — with identical grads per
        rank the fp32-forced trajectory matches the unsharded fused Adam
        on the bf16 grads exactly (no low-precision sum in the path)."""
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.optimizers import fused_adam

        mesh, params, grads = self._setup()
        bparams = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        bgrads = jax.tree.map(lambda x: x.astype(jnp.bfloat16), grads)

        def run(opt, params, grads):
            def step(params, grads):
                state = opt.init(params)
                updates, _ = opt.update(grads, state, params)
                return optax.apply_updates(params, updates)
            return mesh_lib.shard_map(
                step, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            )(params, grads)

        forced = run(distributed_fused_adam(
            learning_rate=1e-2, grad_reduce_dtype=jnp.float32),
            bparams, bgrads)
        ref_opt = fused_adam(learning_rate=1e-2)
        st = ref_opt.init(bparams)
        up, _ = ref_opt.update(bgrads, st, bparams)
        ref = optax.apply_updates(bparams, up)
        for a, e in zip(jax.tree.leaves(forced), jax.tree.leaves(ref)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(e, np.float32),
                rtol=1e-2, atol=1e-5)
        # the default (bf16 reduce) still lands within bf16 rounding of it
        default = run(distributed_fused_adam(learning_rate=1e-2),
                      bparams, bgrads)
        for a, e in zip(jax.tree.leaves(default), jax.tree.leaves(forced)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(e, np.float32),
                rtol=2e-2, atol=1e-4)
        with pytest.raises(ValueError, match="grad_reduce_dtype"):
            distributed_fused_adam(grad_reduce_dtype=jnp.float16)

    def test_zero_state_is_sharded(self):
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.optimizers import multi_tensor as mt

        mesh, params, grads = self._setup()
        zopt = distributed_fused_adam()
        full_buf, _ = mt.flatten_to_chunks(params)
        n_chunks = full_buf.shape[0]

        def state_rows(params):
            st = zopt.init(params)
            return jnp.asarray(st.buffers["m"].shape[0])

        dp = mesh.shape["dp"]
        rows = mesh_lib.shard_map(
            state_rows, mesh=mesh, in_specs=P(), out_specs=P(),
        )(params)
        padded = n_chunks + ((-n_chunks) % dp)
        assert int(rows) == padded // dp  # 1/dp of the chunk rows

    def test_zero_lamb_runs_and_differs_from_adam(self):
        from apex_tpu.contrib.optimizers import distributed_fused_lamb

        mesh, params, grads = self._setup()
        zopt = distributed_fused_lamb(learning_rate=1e-2, max_grad_norm=1.0)

        def run(params, grads):
            state = zopt.init(params)
            updates, _ = zopt.update(grads, state, params)
            return optax.apply_updates(params, updates)

        new_params = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        )(params, grads)
        for a, p in zip(jax.tree.leaves(new_params), jax.tree.leaves(params)):
            assert not np.allclose(a, p)
            assert np.all(np.isfinite(a))


class TestMultiheadAttn:
    def test_self_attn_matches_manual(self):
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        m = SelfMultiheadAttn(embed_dim=32, num_heads=4, bias=True)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 3), (2, 16, 32))
        out = m(params, x, is_training=False)

        qkv = x @ params["qkv_weight"].T + params["qkv_bias"]
        q, k, v = jnp.split(qkv, 3, -1)
        def heads(t):
            return t.reshape(2, 16, 4, 8).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", heads(q), heads(k)) / jnp.sqrt(8.0)
        p = jax.nn.softmax(s, -1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, heads(v))
        o = o.transpose(0, 2, 1, 3).reshape(2, 16, 32)
        ref = o @ params["out_weight"].T + params["out_bias"]
        # hardware MXU default precision carries ~3e-4 rounding both sides
        tol = 2e-5 if jax.default_backend() != "tpu" else 1e-3
        np.testing.assert_allclose(out, ref, rtol=tol, atol=tol)

    def test_norm_add_residual(self):
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        m = SelfMultiheadAttn(embed_dim=32, num_heads=4, include_norm_add=True)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 4), (1, 8, 32))
        out = m(params, x, is_training=False)
        # zeroing the out projection must reduce to the residual
        params2 = dict(params, out_weight=jnp.zeros_like(params["out_weight"]))
        np.testing.assert_allclose(m(params2, x, is_training=False), x, atol=1e-6)

    def test_encdec(self):
        from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn

        m = EncdecMultiheadAttn(embed_dim=32, num_heads=4, bias=True)
        params = m.init(K)
        q = jr.normal(jr.fold_in(K, 5), (2, 8, 32))
        mem = jr.normal(jr.fold_in(K, 6), (2, 24, 32))
        out = m(params, q, mem, is_training=False)
        assert out.shape == (2, 8, 32)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_probs_dropout_semantics(self):
        """Training dropout acts on the attention WEIGHTS (the reference's
        ``fast_mask_softmax_dropout``), is unbiased in expectation, and
        vanishes at eval."""
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        m = SelfMultiheadAttn(embed_dim=16, num_heads=2, dropout=0.3)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 7), (2, 8, 16))
        o_eval = m(params, x, is_training=False)
        o1 = m(params, x, key=jr.fold_in(K, 8), is_training=True)
        o2 = m(params, x, key=jr.fold_in(K, 9), is_training=True)
        assert not np.allclose(o1, o2)       # stochastic
        assert not np.allclose(o1, o_eval)   # actually drops
        # expectation over many keys approaches the eval output
        outs = jnp.stack([m(params, x, key=jr.fold_in(K, 100 + i))
                          for i in range(200)])
        np.testing.assert_allclose(outs.mean(0), o_eval, atol=0.08)

    def _dense_ref(self, m, params, x, *, causal=False, add_mask=None,
                   pad_mask=None):
        """Materialized-scores oracle for SelfMultiheadAttn (no dropout)."""
        qkv = x @ params["qkv_weight"].T
        if "qkv_bias" in params:
            qkv = qkv + params["qkv_bias"]
        q, k, v = jnp.split(qkv, 3, -1)
        b, s, e = x.shape
        h, d = m.num_heads, m.head_dim

        def heads(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        sc = jnp.einsum("bhqd,bhkd->bhqk", heads(q), heads(k)) / jnp.sqrt(
            float(d))
        if add_mask is not None:          # additive (hb, sq, sk), hb | h
            am = add_mask[None] if add_mask.ndim == 2 else add_mask
            sc = sc + jnp.broadcast_to(
                jnp.tile(am, (h // am.shape[0], 1, 1)), sc.shape)
        if pad_mask is not None:          # (b, sk) nonzero = exclude
            sc = jnp.where(pad_mask.astype(bool)[:, None, None, :],
                           -1e9, sc)
        if causal:
            sc = jnp.where(jnp.tril(jnp.ones((s, s), bool)), sc, -jnp.inf)
        p = jax.nn.softmax(sc, -1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, heads(v))
        o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
        o = o @ params["out_weight"].T
        if "out_bias" in params:
            o = o + params["out_bias"]
        return o

    def test_additive_attn_mask_fused(self):
        """The reference's additive-attn_mask variant
        (``self_multihead_attn.py:144-198``) rides the flash bias operand:
        output AND gradients match a materialized-scores oracle."""
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        m = SelfMultiheadAttn(embed_dim=32, num_heads=4, bias=True)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 31), (2, 16, 32))
        # a banded additive mask, shared over batch+heads (the reference's
        # time-mask shape) plus a per-head variant
        band = jnp.where(
            jnp.abs(jnp.arange(16)[:, None] - jnp.arange(16)[None]) > 4,
            -1e9, 0.0)
        per_head = jr.normal(jr.fold_in(K, 32), (4, 16, 16))
        for mask in (band, per_head):
            def loss(p, mk):
                return jnp.sum(m(p, x, attn_mask=mk, is_training=False) ** 2)

            def loss_ref(p, mk):
                return jnp.sum(self._dense_ref(m, p, x, add_mask=mk) ** 2)

            np.testing.assert_allclose(
                m(params, x, attn_mask=mask, is_training=False),
                self._dense_ref(m, params, x, add_mask=mask),
                rtol=2e-5, atol=2e-5)
            g = jax.grad(loss)(params, mask)
            g_ref = jax.grad(loss_ref)(params, mask)
            for name in g:
                np.testing.assert_allclose(g[name], g_ref[name],
                                           rtol=1e-4, atol=1e-4)
            # the mask itself is differentiable through the bias operand
            gm = jax.grad(loss, argnums=1)(params, mask)
            gm_ref = jax.grad(loss_ref, argnums=1)(params, mask)
            np.testing.assert_allclose(gm, gm_ref, rtol=1e-4, atol=1e-4)

    def test_key_padding_mask_per_batch(self):
        """(b, sk) key_padding_mask with DIFFERENT (non-suffix) patterns
        per batch row — the per-batch bias via head-major flattening —
        matches the oracle; masked keys get zero value-gradient."""
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        m = SelfMultiheadAttn(embed_dim=32, num_heads=4)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 33), (3, 16, 32))
        pad = jnp.stack([
            (jnp.arange(16) % 3 == 0),          # strided holes
            (jnp.arange(16) >= 10),             # suffix padding
            jnp.zeros((16,), bool),             # nothing masked
        ])
        out = m(params, x, key_padding_mask=pad, is_training=False)
        ref = self._dense_ref(m, params, x, pad_mask=pad)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
        # mutually exclusive with attn_mask (reference parity,
        # self_multihead_attn.py:188)
        with pytest.raises(ValueError, match="mutually exclusive"):
            m(params, x, key_padding_mask=pad,
              attn_mask=jnp.zeros((16, 16)), is_training=False)

    def test_pad_lens_varlen_fast_path(self):
        """pad_lens (the kv_lens varlen form) equals both the
        key_padding_mask suffix form and a per-row trimmed oracle, and
        composes with causal."""
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        m = SelfMultiheadAttn(embed_dim=32, num_heads=4)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 34), (2, 16, 32))
        lens = jnp.array([11, 16], jnp.int32)
        suffix = jnp.arange(16)[None] >= lens[:, None]
        for causal in (False, True):
            out = m(params, x, pad_lens=lens, causal=causal,
                    is_training=False)
            ref = m(params, x, key_padding_mask=suffix, causal=causal,
                    is_training=False)
            np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
            # rows past a batch's length are garbage-in-garbage-out for
            # that batch only; valid-region outputs must equal a run on
            # the trimmed batch
            trimmed = m(params, x[:1, :11], causal=causal,
                        is_training=False)
            np.testing.assert_allclose(out[0, :11], trimmed[0],
                                       rtol=2e-5, atol=2e-5)

    def test_pad_lens_compose_with_attn_mask_oracle(self):
        """pad_lens AND an additive attn_mask in ONE call — the documented
        composition (docstring: "pad_lens ... composes with attn_mask") —
        against a materialized-scores oracle applying both: additive mask
        on the scores, then -inf past each row's length (ADVICE r5: the
        composition was documented but never tested)."""
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        b, s, e, h = 2, 16, 32, 4
        d = e // h
        m = SelfMultiheadAttn(embed_dim=e, num_heads=h)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 40), (b, s, e))
        lens = jnp.array([11, 16], jnp.int32)
        # a per-head additive mask (h, sq, sk) — the T5/ALiBi-shaped case
        mask = 0.5 * jr.normal(jr.fold_in(K, 41), (h, s, s))

        out = m(params, x, pad_lens=lens, attn_mask=mask,
                is_training=False)

        # oracle: projections by hand, scores + mask, pad cut, softmax
        q = (x @ params["qkv_weight"].T)
        qh, kh, vh = jnp.split(q, 3, axis=-1)
        qh = qh.reshape(b, s, h, d).transpose(0, 2, 1, 3)
        kh = kh.reshape(b, s, h, d).transpose(0, 2, 1, 3)
        vh = vh.reshape(b, s, h, d).transpose(0, 2, 1, 3)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d)
        scores = scores + mask[None]
        keyok = jnp.arange(s)[None, None, None, :] < lens[:, None, None, None]
        scores = jnp.where(keyok, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
        ref = ctx.transpose(0, 2, 1, 3).reshape(b, s, e) \
            @ params["out_weight"].T
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_training_dropout_without_key_raises(self):
        """fmha_varlen parity (ADVICE r5): dropout > 0 with is_training
        and no key must raise, not silently run dropout-free."""
        from apex_tpu.contrib.multihead_attn import (EncdecMultiheadAttn,
                                                     SelfMultiheadAttn)

        m = SelfMultiheadAttn(embed_dim=16, num_heads=2, dropout=0.3)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 42), (2, 8, 16))
        with pytest.raises(ValueError, match="PRNG key"):
            m(params, x, is_training=True)
        # eval mode stays key-free
        m(params, x, is_training=False)
        me = EncdecMultiheadAttn(embed_dim=16, num_heads=2, dropout=0.3)
        pe = me.init(K)
        mem = jr.normal(jr.fold_in(K, 43), (2, 6, 16))
        with pytest.raises(ValueError, match="PRNG key"):
            me(pe, x, mem, is_training=True)

    def test_masks_compose_with_inkernel_dropout(self):
        """Dropout + mask in the SAME kernel call: eval-mode equals the
        oracle, training keeps the mask (masked keys stay excluded in
        every sample) and stays unbiased."""
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        m = SelfMultiheadAttn(embed_dim=16, num_heads=2, dropout=0.25)
        params = m.init(K)
        x = jr.normal(jr.fold_in(K, 35), (2, 8, 16))
        pad = jnp.stack([jnp.arange(8) >= 6, jnp.arange(8) % 2 == 1])
        o_eval = m(params, x, key_padding_mask=pad, is_training=False)
        outs = jnp.stack([
            m(params, x, key_padding_mask=pad, key=jr.fold_in(K, 200 + i))
            for i in range(200)])
        assert not np.allclose(outs[0], outs[1])
        np.testing.assert_allclose(outs.mean(0), o_eval, atol=0.12)
        # determinism per key
        np.testing.assert_array_equal(
            m(params, x, key_padding_mask=pad, key=jr.fold_in(K, 200)),
            outs[0])

    def test_encdec_memory_padding(self):
        """Encoder-memory padding through EncdecMultiheadAttn: pad_lens
        and key_padding_mask agree with a trimmed-memory oracle
        (``encdec_multihead_attn.py:106-119``)."""
        from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn

        m = EncdecMultiheadAttn(embed_dim=32, num_heads=4, bias=True)
        params = m.init(K)
        q = jr.normal(jr.fold_in(K, 36), (2, 8, 32))
        mem = jr.normal(jr.fold_in(K, 37), (2, 24, 32))
        lens = jnp.array([17, 24], jnp.int32)
        out = m(params, q, mem, pad_lens=lens, is_training=False)
        suffix = jnp.arange(24)[None] >= lens[:, None]
        out2 = m(params, q, mem, key_padding_mask=suffix, is_training=False)
        np.testing.assert_allclose(out, out2, rtol=2e-5, atol=2e-5)
        trimmed = m(params, q[:1], mem[:1, :17], is_training=False)
        np.testing.assert_allclose(out[0], trimmed[0], rtol=2e-5, atol=2e-5)

    def test_fmha_packed_layout(self):
        from apex_tpu.contrib.fmha import fmha

        qkv = jr.normal(K, (2, 16, 3, 4, 8))
        o = fmha(qkv, causal=True)
        assert o.shape == (2, 16, 4, 8)

    def test_fmha_varlen_cu_seqlens(self):
        """The reference's REAL interface (``fmha.py:35-46``): token-packed
        qkv + cu_seqlens. Each row's slice must equal dense attention on
        that row alone (no cross-row leakage), fwd and grads."""
        from apex_tpu.contrib.fmha import FMHA, fmha_varlen

        h, d = 2, 8
        lens = [5, 12, 1]
        cu = jnp.cumsum(jnp.array([0] + lens)).astype(jnp.int32)
        total = int(cu[-1])
        qkv = jr.normal(jr.fold_in(K, 40), (total, 3, h, d))

        def run(qkv):
            return fmha_varlen(qkv, cu, max_s=16)

        out = run(qkv)
        assert out.shape == (total, h, d)

        def row_oracle(row_qkv):
            q, k, v = (row_qkv[:, i].transpose(1, 0, 2) for i in range(3))
            s = jnp.einsum("hqd,hkd->hqk", q, k) / jnp.sqrt(float(d))
            p = jax.nn.softmax(s, -1)
            return jnp.einsum("hqk,hkd->hqd", p, v).transpose(1, 0, 2)

        starts = [0] + list(jnp.cumsum(jnp.array(lens))[:-1])
        for r, (st, ln) in enumerate(zip(starts, lens)):
            st = int(st)
            np.testing.assert_allclose(
                out[st:st + ln], row_oracle(qkv[st:st + ln]),
                rtol=2e-5, atol=2e-5)
        # gradient flows through the scatter/gather round-trip; a token's
        # grad only sees its own row (leakage would show cross-row terms)
        g = jax.grad(lambda x: jnp.sum(run(x)[: lens[0]] ** 2))(qkv)
        assert bool(jnp.all(g[lens[0] + lens[1]:] == 0))
        assert bool(jnp.any(g[: lens[0]] != 0))
        # module wrapper: flat (total, 3·h·d) in/out with in-kernel dropout
        m = FMHA(num_heads=h, head_dim=d, p_dropout=0.3)
        flat = qkv.reshape(total, 3 * h * d)
        o1 = m(flat, cu, max_s=16, key=jr.fold_in(K, 41))
        o2 = m(flat, cu, max_s=16, key=jr.fold_in(K, 42))
        assert o1.shape == (total, h * d)
        assert not np.allclose(o1, o2)
        np.testing.assert_allclose(
            m(flat, cu, max_s=16, is_training=False),
            out.reshape(total, h * d), rtol=2e-5, atol=2e-5)

    def test_fmha_varlen_max_s_too_small_raises_eagerly(self):
        """max_s < the longest row used to TRUNCATE that row silently (the
        padded-layout scatter drops out-of-bounds tokens); with a concrete
        cu_seqlens it must raise instead (ADVICE r5). Traced cu_seqlens
        cannot be checked — the docstring documents that hazard."""
        from apex_tpu.contrib.fmha import fmha_varlen

        h, d = 2, 8
        cu = jnp.array([0, 5, 17], jnp.int32)  # rows of 5 and 12
        qkv = jr.normal(jr.fold_in(K, 44), (17, 3, h, d))
        with pytest.raises(ValueError, match="max_s"):
            fmha_varlen(qkv, cu, max_s=8)
        # an adequate max_s still works
        assert fmha_varlen(qkv, cu, max_s=12).shape == (17, h, d)
        # traced path: must stay traceable (no concretization error)
        out = jax.jit(lambda q, c: fmha_varlen(q, c, max_s=12))(qkv, cu)
        assert out.shape == (17, h, d)


class TestTransducer:
    def test_joint(self):
        from apex_tpu.contrib.transducer import transducer_joint

        f = jr.normal(K, (2, 5, 8))
        g = jr.normal(jr.fold_in(K, 7), (2, 3, 8))
        h = transducer_joint(f, g, relu=True)
        ref = jnp.maximum(f[:, :, None, :] + g[:, None, :, :], 0)
        np.testing.assert_allclose(h, ref, atol=1e-6)
        # length masking
        h2 = transducer_joint(f, g, f_len=jnp.array([5, 3]), g_len=jnp.array([3, 2]))
        assert bool(jnp.all(h2[1, 3:] == 0)) and bool(jnp.all(h2[1, :, 2:] == 0))

    def test_loss_matches_brute_force(self):
        """Enumerate all monotone alignments on a tiny lattice."""
        from apex_tpu.contrib.transducer import transducer_loss
        import itertools

        B, T, U, V = 1, 3, 2, 5
        x = jr.normal(K, (B, T, U + 1, V))
        labels = jnp.array([[1, 3]])
        lp = jax.nn.log_softmax(x, -1)

        # brute force: paths of T blanks and U labels
        def path_logp(order):
            # order: tuple of 'L'/'B' moves of length T-1+U... full RNN-T:
            # T blank emissions total (one per frame advance incl. final)
            t, u, acc = 0, 0, 0.0
            for mv in order:
                if mv == "B":
                    acc += float(lp[0, t, u, 0])
                    t += 1
                else:
                    acc += float(lp[0, t, u, int(labels[0, u])])
                    u += 1
            acc += float(lp[0, t, u, 0])  # final blank at (T-1, U)
            return acc

        import math
        paths = []
        # sequences of moves: T-1 blanks + U labels in any order
        for order in set(itertools.permutations(["B"] * (T - 1) + ["L"] * U)):
            paths.append(path_logp(order))
        ref = -math.log(sum(math.exp(p) for p in paths))

        loss = transducer_loss(x, labels, jnp.array([T]), jnp.array([U]))
        np.testing.assert_allclose(float(loss[0]), ref, rtol=1e-5)

    def test_loss_grad_finite(self):
        from apex_tpu.contrib.transducer import transducer_loss

        x = jr.normal(K, (2, 4, 3, 6))
        labels = jnp.array([[1, 2], [3, 0]])
        g = jax.grad(lambda x: jnp.sum(
            transducer_loss(x, labels, jnp.array([4, 3]), jnp.array([2, 1]))
        ))(x)
        assert bool(jnp.all(jnp.isfinite(g)))


class TestASP:
    def test_mask_2to4(self):
        from apex_tpu.contrib.sparsity import mask_2to4_best

        w = jr.normal(K, (8, 16))
        m = mask_2to4_best(w)
        groups = m.reshape(8, 4, 4)
        assert bool(jnp.all(groups.sum(-1) == 2))
        # kept entries are the two largest |w| per group
        wa = jnp.abs(w).reshape(8, 4, 4)
        kept_min = jnp.min(jnp.where(groups, wa, jnp.inf), -1)
        dropped_max = jnp.max(jnp.where(~groups, wa, -jnp.inf), -1)
        assert bool(jnp.all(kept_min >= dropped_max))

    def test_pruned_stays_pruned_through_training(self):
        from apex_tpu.contrib.sparsity import ASP

        asp = ASP()
        params = {"w": jr.normal(K, (16, 32)), "b": jnp.zeros((7,))}
        masks = asp.compute_sparse_masks(params)
        params = asp.apply_masks(params, masks)
        opt = asp.wrap_optimizer(optax.adam(1e-2), masks)
        state = opt.init(params)
        for i in range(3):
            grads = jax.tree.map(
                lambda x: jr.normal(jr.fold_in(K, i), x.shape), params)
            updates, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        zeros = ~masks["w"]
        assert bool(jnp.all(params["w"][zeros] == 0))
        assert params["b"].shape == (7,)  # dense leaf untouched structurally


class TestBottleneckConv:
    def test_conv_bias_relu(self):
        from apex_tpu.contrib.conv_bias_relu import conv_bias_relu

        x = jr.normal(K, (2, 8, 8, 3))
        w = jr.normal(jr.fold_in(K, 8), (3, 3, 3, 4)) * 0.2
        b = jnp.ones((4,)) * 0.1
        y = conv_bias_relu(x, w, b)
        assert y.shape == (2, 8, 8, 4) and bool(jnp.all(y >= 0))

    def test_bottleneck_block(self):
        from apex_tpu.contrib.bottleneck import Bottleneck

        blk = Bottleneck(16, 4, 16)
        p, st = blk.init(K)
        x = jr.normal(jr.fold_in(K, 9), (2, 8, 8, 16))
        y, _ = blk(p, st, x)
        assert y.shape == x.shape

    def test_spatial_bottleneck_matches_unsharded(self):
        from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck

        mesh = mesh_lib.make_mesh(context_parallel_size=4)
        blk = Bottleneck(8, 4, 8)
        sblk = SpatialBottleneck(8, 4, 8, spatial_axis="cp")
        p, st = blk.init(K)
        x = jr.normal(jr.fold_in(K, 10), (2, 16, 8, 8))

        y_ref, _ = blk(p, st, x, training=False)
        y, _ = mesh_lib.shard_map(
            lambda p, st, x: sblk(p, st, x, training=False),
            mesh=mesh, in_specs=(P(), P(), P(None, "cp")),
            out_specs=(P(None, "cp"), P()),
        )(p, st, x)
        np.testing.assert_allclose(y, y_ref, rtol=2e-5, atol=2e-5)

    def test_spatial_bottleneck_strided_matches_unsharded(self):
        """VERDICT r1 item 8: full ResNet stages downsample — the spatial
        variant must reproduce a stride-2 block's window phase across shard
        boundaries (reference ``bottleneck.py:386+``)."""
        from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck

        mesh = mesh_lib.make_mesh(context_parallel_size=4)
        blk = Bottleneck(8, 4, 16, stride=2)
        sblk = SpatialBottleneck(8, 4, 16, stride=2, spatial_axis="cp")
        p, st = blk.init(K)
        x = jr.normal(jr.fold_in(K, 11), (2, 32, 8, 8))  # H_local=8, even

        y_ref, _ = blk(p, st, x, training=False)
        y, _ = mesh_lib.shard_map(
            lambda p, st, x: sblk(p, st, x, training=False),
            mesh=mesh, in_specs=(P(), P(), P(None, "cp")),
            out_specs=(P(None, "cp"), P()),
        )(p, st, x)
        assert y.shape == y_ref.shape == (2, 16, 4, 16)
        np.testing.assert_allclose(y, y_ref, rtol=2e-5, atol=2e-5)

    def test_spatial_conv3x3_stride2_parity(self):
        """Direct conv-level parity across every shard-boundary phase."""
        from apex_tpu.contrib.bottleneck import spatial_conv3x3

        mesh = mesh_lib.make_mesh(context_parallel_size=4)
        w = jr.normal(jr.fold_in(K, 12), (3, 3, 4, 4)) * 0.3
        x = jr.normal(jr.fold_in(K, 13), (1, 16, 6, 4))
        ref = jax.lax.conv_general_dilated(
            x, w, (2, 2), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        out = mesh_lib.shard_map(
            lambda x: spatial_conv3x3(x, w, "cp", stride=2),
            mesh=mesh, in_specs=P(None, "cp"), out_specs=P(None, "cp"),
        )(x)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_groupbn_axis_split(self):
        from apex_tpu.contrib.groupbn import split_data_axis_for_bn

        mesh = mesh_lib.make_mesh()  # dp=8
        dp = mesh.shape["dp"]
        if dp < 4 or dp % 4:
            pytest.skip("needs dp divisible by 4 (hardware mode has one chip)")
        m2 = split_data_axis_for_bn(mesh, 4)
        assert m2.shape["bn"] == 4 and m2.shape["dp_outer"] == dp // 4


class TestZeroHardening:
    """VERDICT r1 item 9: multi-step convergence, compressed all-gather,
    overlap documentation (see distributed.py module docstring)."""

    def _train(self, opt, steps=50, is_zero=False, param_dtype=None):
        """Train a small MLP on a fixed regression task; returns the final
        params and loss trajectory."""
        mesh = mesh_lib.make_mesh()
        key = jr.PRNGKey(7)
        params = {
            "w1": jr.normal(key, (16, 64)) * 0.1, "b1": jnp.zeros((64,)),
            "w2": jr.normal(jr.fold_in(key, 1), (64, 16)) * 0.1,
        }
        if param_dtype is not None:
            params = jax.tree.map(lambda x: x.astype(param_dtype), params)
        w_true = jr.normal(jr.fold_in(key, 2), (16, 16))

        def loss_fn(p, x, y):
            h = jnp.tanh(x @ p["w1"] + p["b1"])
            return jnp.mean((h @ p["w2"] - y) ** 2)

        def make_step():
            def step(params, opt_state, x, y):
                def run(params, x, y, opt_state):
                    loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
                    grads = jax.lax.pmean(grads, "dp")
                    loss = jax.lax.pmean(loss, "dp")
                    updates, opt_state = opt.update(grads, opt_state, params)
                    return optax.apply_updates(params, updates), opt_state, loss

                return mesh_lib.shard_map(
                    run, mesh=mesh,
                    in_specs=(P(), P("dp"), P("dp"), P()),
                    out_specs=(P(), P(), P()),
                )(params, x, y, opt_state)

            return jax.jit(step)

        if is_zero:
            opt_state = mesh_lib.shard_map(
                lambda p: opt.init(p), mesh=mesh, in_specs=P(), out_specs=P(),
            )(params)
        else:
            opt_state = opt.init(params)
        step = make_step()
        losses = []
        for i in range(steps):
            x = jr.normal(jr.fold_in(key, 100 + i), (32, 16))
            y = jnp.tanh(x @ w_true)
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        mesh_lib.destroy_model_parallel()
        return params, losses

    def test_zero_adam_50_step_convergence_matches_unsharded(self):
        """Sharded Adam == unsharded fused Adam over 50 steps (the
        correctness bar of ``distributed_fused_adam.py:9``'s claim that
        sharding is numerically transparent)."""
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.optimizers import fused_adam

        zp, zlosses = self._train(
            distributed_fused_adam(learning_rate=1e-2), is_zero=True)
        rp, rlosses = self._train(fused_adam(learning_rate=1e-2))
        np.testing.assert_allclose(zlosses, rlosses, rtol=1e-4, atol=1e-6)
        for a, e in zip(jax.tree.leaves(zp), jax.tree.leaves(rp)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-6)
        assert zlosses[-1] < zlosses[0] * 0.3, "did not converge"

    def test_zero_bf16_allgather_converges_close(self):
        """The e5m2-compressed-allgather analog: bf16 param all-gather
        (``distributed_fused_lamb.py:86-95``'s ``e5m2_allgather`` option)
        still converges, within bf16 tolerance of the fp32 path."""
        from apex_tpu.contrib.optimizers import distributed_fused_adam

        zp16, l16 = self._train(
            distributed_fused_adam(learning_rate=1e-2,
                                   all_gather_dtype=jnp.bfloat16),
            is_zero=True)
        zp32, l32 = self._train(
            distributed_fused_adam(learning_rate=1e-2), is_zero=True)
        assert l16[-1] < l16[0] * 0.4, "bf16 all-gather did not converge"
        # close to the fp32 trajectory but not required bitwise
        np.testing.assert_allclose(l16[-1], l32[-1], rtol=0.2, atol=5e-3)

    def test_zero_e5m2_allgather_converges(self):
        """Exact parity with the reference's fp8 option: ``e5m2_allgather``
        (``distributed_fused_lamb.py:86-95``) — params all-gathered as
        float8_e5m2. Coarser than bf16, so only convergence (not closeness
        to the fp32 trajectory) is required."""
        from apex_tpu.contrib.optimizers import distributed_fused_adam

        _, l8 = self._train(
            distributed_fused_adam(learning_rate=1e-2,
                                   all_gather_dtype=jnp.float8_e5m2),
            is_zero=True)
        assert l8[-1] < l8[0] * 0.5, f"e5m2 all-gather did not converge: {l8}"

    def test_zero_lamb_50_steps_converges(self):
        from apex_tpu.contrib.optimizers import distributed_fused_lamb

        _, losses = self._train(
            distributed_fused_lamb(learning_rate=5e-3), is_zero=True)
        assert losses[-1] < losses[0] * 0.7

    def test_zero_bf16_params_fp32_masters(self):
        """bf16 params: ZeRO keeps fp32 moments AND sharded fp32 masters
        (the reference's mixed-precision DistributedFusedAdam — fp32
        state for fp16 params, all 1/dp-sharded). The bf16 trajectory
        must converge and track the fp32 run closely (the masters absorb
        the update rounding; params are their bf16 image)."""
        from apex_tpu.contrib.optimizers import distributed_fused_adam

        p16, l16 = self._train(
            distributed_fused_adam(learning_rate=1e-2), is_zero=True,
            param_dtype=jnp.bfloat16)
        _, l32 = self._train(
            distributed_fused_adam(learning_rate=1e-2), is_zero=True)
        assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(p16))
        assert l16[-1] < l16[0] * 0.4, "bf16-master run did not converge"
        np.testing.assert_allclose(l16[-1], l32[-1], rtol=0.2, atol=5e-3)


class TestFastLayerNormLargeHidden:
    """Substantiate the FastLayerNorm claim: the reference's contrib LN
    exists for large hidden sizes (up to 65k); the Pallas LN must handle
    them by shrinking its row blocks to the VMEM budget."""

    def test_hidden_8192_fwd_bwd(self):
        from apex_tpu.contrib.layer_norm import fast_layer_norm

        x = jr.normal(K, (16, 8192), jnp.float32)
        w = jnp.ones((8192,)); b = jnp.zeros((8192,))
        y = fast_layer_norm(x, w, b)
        np.testing.assert_allclose(
            np.asarray(y.mean(-1)), np.zeros(16), atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(y.std(-1)), np.ones(16), atol=1e-2)
        g = jax.grad(lambda x: fast_layer_norm(x, w, b).sum())(x)
        assert np.isfinite(np.asarray(g)).all()


class TestZeroFlagship:
    """ZeRO under the REAL flagship models (VERDICT r3 next-round #4): the
    dp-sharded optimizer state drives GPTModel param pytrees composed with
    tp, the full 3D pipeline, and MoE+ep — trajectories match the
    unsharded fused Adam."""

    KW = dict(vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
              num_heads=4, attention_impl="flash")
    STEPS = 4

    def _oracle(self, cfg1, params, batches, lr=1e-2):
        """Unsharded fused-Adam trajectory on the single-device model."""
        from apex_tpu.models import GPTModel
        from apex_tpu.optimizers import fused_adam

        m = GPTModel(cfg1)
        opt = fused_adam(learning_rate=lr)
        st = opt.init(params)
        losses = []

        @jax.jit
        def step(p, st, toks, tgts):
            def f(p_):
                per = [m.loss_fn(p_, t, g) for t, g in
                       zip(*map(list, (toks, tgts)))]
                return jnp.mean(jnp.stack(per))
            loss, g = jax.value_and_grad(f)(p)
            u, st = opt.update(g, st, p)
            return optax.apply_updates(p, u), st, loss

        for toks, tgts in batches:
            params, st, loss = step(params, st, toks, tgts)
            losses.append(float(loss))
        return losses

    # 4 steps is the fast tier; 50 (slow) is the CONVERGENCE-length pin —
    # drift that only shows tens of steps in under sharded state would
    # pass a 4-step gate (VERDICT r4 next #5)
    @pytest.mark.parametrize(
        "steps", [4, pytest.param(50, marks=pytest.mark.slow)])
    def test_zero_adam_under_gpt_tp2(self, steps):
        """Sharded-state update of tp-sharded params: ZeRO shards m/v over
        dp=4 within each tp rank; per-(tp) param shards stay exact."""
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.models.gpt import shard_params_for_tp

        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=2)  # dp=4
        cfg1 = GPTConfig(**self.KW)
        cfg = GPTConfig(**self.KW, tp_size=2)
        m = GPTModel(cfg)
        params1 = GPTModel(cfg1).init(K)
        sharded = shard_params_for_tp(params1, 2, cfg1)
        specs = jax.tree.map(lambda _: P("tp"), sharded)
        opt = distributed_fused_adam(learning_rate=1e-2)

        b, s = 4, 16
        batches = [
            (jr.randint(jr.fold_in(K, 200 + i), (1, b, s), 0, 64),
             jr.randint(jr.fold_in(K, 300 + i), (1, b, s), 0, 64))
            for i in range(steps)]

        st = mesh_lib.shard_map(
            lambda p: opt.init(jax.tree.map(lambda x: x[0], p)),
            mesh=mesh, in_specs=(specs,), out_specs=P(),
        )(sharded)

        @jax.jit
        def step(p, st, toks, tgts):
            def run(p, toks, tgts, st):
                lp = jax.tree.map(lambda x: x[0], p)
                loss, g = jax.value_and_grad(m.loss_fn)(
                    lp, toks[0], tgts[0])
                u, st = opt.update(g, st, lp)
                newp = optax.apply_updates(lp, u)
                return jax.tree.map(lambda x: x[None], newp), st, loss

            return mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P(), P()),
                out_specs=(specs, P(), P()),
            )(p, toks, tgts, st)

        losses = []
        with jax.default_matmul_precision("highest"):
            for toks, tgts in batches:
                sharded, st, loss = step(sharded, st, toks, tgts)
                losses.append(float(loss))
            ref = self._oracle(cfg1, params1, batches)
        np.testing.assert_allclose(losses, ref, rtol=5e-4, atol=1e-5)
        assert losses[-1] < losses[0], losses
        # the ZeRO memory claim: per-device m/v rows are 1/dp of the chunks
        dp = 4
        n_chunks = st.layout.chunk_to_tensor.shape[0]
        local_rows = st.buffers["m"].shape[0]
        assert local_rows == -(-n_chunks // dp), (local_rows, n_chunks)
        mesh_lib.destroy_model_parallel()

    def test_zero_adam_under_3d_pipeline(self):
        """The 3D step (dp2 x pp2 x tp2) with dp-SHARDED optimizer state:
        pipe-layout params, ZeRO over dp inside the same shard_map as the
        schedule, trajectory == single-device fused Adam."""
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.models.gpt import shard_params_for_tp
        from apex_tpu.transformer.pipeline_parallel import GPTPipeline

        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=2,
                                  pipeline_model_parallel_size=2)  # dp=2
        cfg1 = GPTConfig(**self.KW)
        cfg = GPTConfig(**self.KW, tp_size=2, sequence_parallel=True)
        m = GPTModel(cfg)
        params1 = GPTModel(cfg1).init(K)
        pipe = GPTPipeline(m, pp=2)
        part = jax.vmap(pipe.partition)(shard_params_for_tp(params1, 2, cfg1))
        specs = pipe.param_specs(part, "tp")
        opt = distributed_fused_adam(learning_rate=1e-2)

        M, b, s, dp = 2, 2, 16, 2
        batches = [
            (jr.randint(jr.fold_in(K, 400 + i), (M, b * dp, s), 0, 64),
             jr.randint(jr.fold_in(K, 500 + i), (M, b * dp, s), 0, 64))
            for i in range(self.STEPS)]

        def local(p):
            lp = jax.tree.map(lambda x: x[0], p)
            return dict(lp, stages=jax.tree.map(lambda x: x[0],
                                                lp["stages"]))

        st = mesh_lib.shard_map(
            lambda p: opt.init(local(p)), mesh=mesh, in_specs=(specs,),
            out_specs=P(),
        )(part)

        @jax.jit
        def step(p, st, toks, tgts):
            def run(p, toks, tgts, st):
                lp = local(p)
                loss, g = pipe.loss_and_grads(lp, toks, tgts, dp_axis="dp")
                u, st = opt.update(g, st, lp)
                newp = optax.apply_updates(lp, u)
                newp["stages"] = jax.tree.map(lambda x: x[None, None],
                                              newp["stages"])
                newp["embed"] = jax.tree.map(lambda x: x[None],
                                             newp["embed"])
                newp["head"] = jax.tree.map(lambda x: x[None], newp["head"])
                return newp, st, loss

            return mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, "dp"), P(None, "dp"), P()),
                out_specs=(specs, P(), P()),
            )(p, toks, tgts, st)

        losses = []
        with jax.default_matmul_precision("highest"):
            for toks, tgts in batches:
                part, st, loss = step(part, st, toks, tgts)
                losses.append(float(loss))

            # oracle: per-(dp shard, microbatch) mean losses + fused adam
            from apex_tpu.models import GPTModel as GM
            from apex_tpu.optimizers import fused_adam
            m1 = GM(cfg1)
            opt1 = fused_adam(learning_rate=1e-2)
            st1 = opt1.init(params1)
            ref = []

            @jax.jit
            def ostep(p, st, toks, tgts):
                def f(p_):
                    per = [m1.loss_fn(p_, toks[i, r * b:(r + 1) * b],
                                      tgts[i, r * b:(r + 1) * b])
                           for r in range(dp) for i in range(M)]
                    return jnp.mean(jnp.stack(per))
                loss, g = jax.value_and_grad(f)(p)
                u, st = opt1.update(g, st, p)
                return optax.apply_updates(p, u), st, loss

            p1 = params1
            for toks, tgts in batches:
                p1, st1, loss = ostep(p1, st1, toks, tgts)
                ref.append(float(loss))

        np.testing.assert_allclose(losses, ref, rtol=5e-4, atol=1e-5)
        mesh_lib.destroy_model_parallel()

    @pytest.mark.parametrize(
        "steps", [4, pytest.param(50, marks=pytest.mark.slow)])
    def test_zero_adam_under_moe_ep(self, steps):
        """ZeRO x MoE x ep: expert banks sharded over ep, their fp32 m/v
        additionally sharded over dp — the memory lever that relaxes the
        MoE remat budget (PERF.md r4). Trajectory == unsharded Adam."""
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.models import GPTConfig, GPTModel

        mesh = mesh_lib.make_mesh(expert_parallel_size=2)  # dp=4 x ep=2
        kw = dict(self.KW, moe_num_experts=4, moe_top_k=2,
                  moe_capacity_factor=2.0)
        cfg1 = GPTConfig(**kw)
        cfg = GPTConfig(**kw, ep_axis="ep")
        m = GPTModel(cfg)
        params = GPTModel(cfg1).init(K)
        opt = distributed_fused_adam(learning_rate=1e-2)

        def leaf_spec(path, _):
            names = {q.key for q in path if hasattr(q, "key")}
            if "moe" in names and names & {"w1", "b1", "w2", "b2"}:
                return P(None, "ep")
            return P()

        pspec = jax.tree_util.tree_map_with_path(leaf_spec, params)
        b, s = 2, 16
        shards = 8  # dp*ep data shards
        batches = [
            (jr.randint(jr.fold_in(K, 600 + i), (b * shards, s), 0, 64),
             jr.randint(jr.fold_in(K, 700 + i), (b * shards, s), 0, 64))
            for i in range(self.STEPS)]

        st = mesh_lib.shard_map(
            lambda p: opt.init(p), mesh=mesh, in_specs=(pspec,),
            out_specs=P(),
        )(params)

        @jax.jit
        def step(p, st, toks, tgts):
            def run(p, toks, tgts, st):
                loss, g = jax.value_and_grad(m.loss_fn)(p, toks, tgts)
                loss = jax.lax.pmean(loss, ("dp", "ep"))

                def reduce_leaf(path, x):
                    names = {q.key for q in path if hasattr(q, "key")}
                    if "moe" in names and names & {"w1", "b1", "w2", "b2"}:
                        return jax.lax.pmean(x, "dp") / 2  # ep size
                    return jax.lax.pmean(x, ("dp", "ep"))

                g = jax.tree_util.tree_map_with_path(reduce_leaf, g)
                u, st = opt.update(g, st, p)
                return optax.apply_updates(p, u), st, loss

            return mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(pspec, P(("dp", "ep")), P(("dp", "ep")), P()),
                out_specs=(pspec, P(), P()),
            )(p, toks, tgts, st)

        losses = []
        with jax.default_matmul_precision("highest"):
            for toks, tgts in batches:
                params, st, loss = step(params, st, toks, tgts)
                losses.append(float(loss))

            # oracle over the 8 data shards
            b_sh = [
                (jnp.stack([t[r * b:(r + 1) * b] for r in range(shards)]),
                 jnp.stack([g[r * b:(r + 1) * b] for r in range(shards)]))
                for t, g in batches]
            ref = self._oracle(cfg1, GPTModel(cfg1).init(K), b_sh)
        np.testing.assert_allclose(losses, ref, rtol=5e-4, atol=1e-5)


@pytest.mark.slow
class TestZeroMoeBenchBudget:
    """The ZeRO x MoE memory claim EXECUTED, not derived (VERDICT r4 next
    #5): the MoE bench config's 891M-param step runs with
    ``distributed_fused_adam`` actually sharding fp32 moments over a dp=8
    virtual mesh, and the per-device m/v buffer bytes are measured
    against PERF.md's 7.1 GB -> 0.9 GB arithmetic."""

    def test_dp8_sharded_state_step_and_budget(self):
        import optax

        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.models import GPTConfig, GPTModel

        mesh = mesh_lib.make_mesh()  # dp=8
        # the MoE bench dims (PERF.md "GPT-MoE flagship row": hidden 1024,
        # 12 layers, E=8 top-2 cf=1.25, vocab 32768 -> 891M params). The
        # step's batch/seq are tiny — this is a virtual-mesh budget+
        # correctness execution, not a timing run (the timing lives in
        # PERF.md's single-chip rows).
        cfg = GPTConfig(vocab_size=32768, max_seq_len=1024,
                        hidden_size=1024, num_layers=12, num_heads=8,
                        moe_num_experts=8, moe_top_k=2,
                        moe_capacity_factor=1.25, attention_impl="flash",
                        remat=True, scan_layers=True)
        m = GPTModel(cfg)
        # bf16 params, as the bench runs them: ZeRO then holds fp32
        # moments AND sharded fp32 masters (the mixed-precision
        # reference semantics) — the 7.1 GB m/v arithmetic is fp32
        # moments for 891M params. (An fp32-param variant of this test
        # needs ~130 GB of host RAM for the 8-way replication — the bf16
        # configuration is both the real one and the one that fits.)
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), m.init(K))
        n_params = sum(x.size for x in jax.tree.leaves(params))
        assert 8.5e8 < n_params < 9.5e8, n_params  # the 891M-class model

        opt = distributed_fused_adam(learning_rate=1e-2)
        pspec = jax.tree.map(lambda _: P(), params)

        def run(p, toks, tgts):
            loss, g = jax.value_and_grad(m.loss_fn)(p, toks, tgts)
            loss = jax.lax.pmean(loss, "dp")
            g = jax.tree.map(lambda x: jax.lax.pmean(x, "dp"), g)
            st = opt.init(p)
            u, st = opt.update(g, st, p)
            newp = optax.apply_updates(p, u)
            # buffer shapes are static: the byte count is exact, taken
            # from the LIVE sharded state this device just updated with.
            # m/v only — the 7.1 GB arithmetic is moments; the sharded
            # fp32 masters are a separate (1/2-sized) line item.
            local_bytes = sum(st.buffers[k].size
                              * st.buffers[k].dtype.itemsize
                              for k in ("m", "v"))
            assert "master" in st.buffers  # bf16 params -> fp32 masters
            return loss, newp, jnp.int32(local_bytes // (1 << 20))  # MiB

        b, s = 8, 64
        toks = jr.randint(jr.fold_in(K, 900), (b, s), 0, cfg.vocab_size)
        tgts = jr.randint(jr.fold_in(K, 901), (b, s), 0, cfg.vocab_size)
        loss, newp, local_mib = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(pspec, P("dp"), P("dp")),
            out_specs=(P(), pspec, P()),
        ))(params, toks, tgts)
        assert bool(jnp.isfinite(loss))
        # params moved (the sharded update really applied)
        moved = any(
            bool(jnp.any(a != b_)) for a, b_ in
            zip(jax.tree.leaves(params), jax.tree.leaves(newp)))
        assert moved

        # the budget: m+v fp32 for 891M params = ~7.1 GB total; per device
        # at dp=8 = ~0.9 GB (+ chunk padding). Measured, not derived.
        total_mv_gb = n_params * 2 * 4 / 1e9
        per_dev_gb = float(local_mib) * (1 << 20) / 1e9
        np.testing.assert_allclose(per_dev_gb, total_mv_gb / 8, rtol=0.02)
        assert 0.8 < per_dev_gb < 1.0, per_dev_gb  # the "0.9 GB/device"
        mesh_lib.destroy_model_parallel()


class TestZeroLossScaling:
    """Dynamic loss scaling composed with ZeRO (VERDICT r5 missing #4 /
    next #5 — the reference's ``step_supports_amp_scaling``,
    ``distributed_fused_adam.py:9``): fp16 params with fp16 loss-scaled
    grads over dp-sharded fp32 masters + m/v. A forced overflow on ONE
    rank must make EVERY rank skip the step — sharded masters and
    moments bit-identical before/after, params untouched, scale backed
    off — and finite steps afterwards recover the scale."""

    def _build(self):
        from apex_tpu.contrib.optimizers import distributed_fused_adam

        mesh = mesh_lib.make_mesh()  # dp=8
        params = {
            "w1": (jr.normal(jr.fold_in(K, 70), (16, 24)) * 0.1
                   ).astype(jnp.float16),
            "b1": jnp.zeros((24,), jnp.float16),
            "w2": (jr.normal(jr.fold_in(K, 71), (24, 8)) * 0.1
                   ).astype(jnp.float16),
        }
        base_g = jax.tree.map(
            lambda x: jr.normal(jr.fold_in(K, 72), x.shape) * 0.05, params)
        zopt = distributed_fused_adam(learning_rate=1e-2)
        return mesh, params, base_g, zopt

    def test_overflow_skip_is_bitwise_and_scale_recovers(self):
        from apex_tpu.amp.scaler import init_loss_scaler, unscale_grads
        from apex_tpu.transformer.amp import update_scaler_model_parallel

        mesh, params, base_g, zopt = self._build()
        init_scale = 1024.0
        # loss-scaled fp16 grads — what backward emits under the scaler
        grads16 = jax.tree.map(
            lambda g: (g * init_scale).astype(jnp.float16), base_g)

        def run(params, grads16):
            zstate = zopt.init(params)
            sstate = init_loss_scaler(init_scale=init_scale,
                                      growth_interval=2)
            rank = jax.lax.axis_index("dp")

            def step(params, zstate, sstate, inject):
                g16 = grads16
                if inject:
                    # rank 1's microbatch overflowed: one inf in one leaf
                    g16 = dict(g16, w1=jnp.where(
                        rank == 1,
                        jnp.full_like(g16["w1"], jnp.inf), g16["w1"]))
                ug = unscale_grads(sstate, g16)
                # found-inf agreed over the dp axis: every rank skips
                # together (the reference GradScaler's model-parallel
                # all-reduce, grad_scaler.py:38-49, here over ZeRO's dp)
                sstate, finite = update_scaler_model_parallel(
                    sstate, ug, axes=("dp",))
                # the collectives inside zopt.update must still run on
                # every rank; inf is sanitized first and the RESULT is
                # discarded on skip (amp.skip_step_if_nonfinite's rule:
                # guarding params alone would poison m/v forever)
                safe = jax.tree.map(
                    lambda x: jnp.where(jnp.isfinite(x), x, 0.0), ug)
                updates, new_z = zopt.update(safe, zstate, params)
                new_params = optax.apply_updates(params, updates)
                params = jax.tree.map(
                    lambda a, b: jnp.where(finite, a, b), new_params,
                    params)
                zstate = jax.tree.map(
                    lambda a, b: jnp.where(finite, a, b), new_z, zstate)
                return params, zstate, sstate

            p1, z1, s1 = step(params, zstate, sstate, inject=False)
            p2, z2, s2 = step(p1, z1, s1, inject=True)
            p3, z3, s3 = step(p2, z2, s2, inject=False)
            p4, z4, s4 = step(p3, z3, s3, inject=False)
            scales = jnp.stack([s1.loss_scale, s2.loss_scale,
                                s3.loss_scale, s4.loss_scale])
            stats = {"scales": scales, "skipped": s4.skipped_steps,
                     "tracker2": s2.growth_tracker}
            return p1, p2, p4, z1.buffers, z2.buffers, stats

        out_buf = {k: P("dp") for k in ("m", "v", "master")}
        p1, p2, p4, buf1, buf2, stats = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()),
            out_specs=(P(), P(), P(), out_buf, out_buf, P()),
        ))(params, grads16)

        # the skipped step: sharded fp32 masters AND m/v BIT-identical on
        # every rank (the buffers gather rank-major over the dp axis)
        assert set(buf1) == {"m", "v", "master"}  # fp16 params keep masters
        for name in ("m", "v", "master"):
            a, b = np.asarray(buf1[name]), np.asarray(buf2[name])
            assert a.dtype == np.float32
            np.testing.assert_array_equal(
                a, b, err_msg=f"skipped step mutated sharded {name}")
        # params bitwise untouched by the skipped step
        for k in params:
            np.testing.assert_array_equal(np.asarray(p1[k]),
                                          np.asarray(p2[k]))
        # scale trajectory: 1024 (finite) → 512 (overflow backoff) → 512
        # (tracker 1) → 1024 (growth_interval=2 reached)
        np.testing.assert_allclose(np.asarray(stats["scales"]),
                                   [1024.0, 512.0, 512.0, 1024.0])
        assert int(stats["skipped"]) == 1
        assert int(stats["tracker2"]) == 0  # overflow resets the tracker
        # the finite steps really trained (params moved after the skip)
        moved = any(bool(jnp.any(a != b))
                    for a, b in zip(jax.tree.leaves(p2),
                                    jax.tree.leaves(p4)))
        assert moved

    def test_overflow_composes_with_zb_pipeline_across_dp_tp_pp(self):
        """fp16 × ZeRO × PIPELINE (ROADMAP open item 3's 'while in the
        neighborhood' / ISSUE 8 satellite): loss-scaled fp16 grads come
        from the REAL pipelined zero-bubble backward on a dp2×tp2×pp2
        mesh — a real tp stage (column/row-sharded matmuls, boundary
        psum) per pp rank. ONE (dp, tp, pp) rank overflows; found-inf is
        reduced over ALL THREE axes, so every rank skips together:
        dp-sharded fp32 masters and m/v bit-identical, params untouched,
        scale 1024→512→512→1024 recovery."""
        import jax.numpy as jnp

        from apex_tpu.amp.scaler import init_loss_scaler, unscale_grads
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        from apex_tpu.transformer.amp import update_scaler_model_parallel
        from apex_tpu.transformer.pipeline_parallel import schedules

        HID, FFN, tp, S, dp = 16, 32, 2, 2, 2
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=tp,
                                  pipeline_model_parallel_size=S)  # dp=2
        init_scale = 1024.0

        def stage_fn(p, x):
            # column-sharded w1, row-sharded w2, tp boundary psum
            h = jnp.tanh(x @ p["w1"])
            return x + jax.lax.psum(h @ p["w2"], "tp")

        def col(key, shape, axis):
            full = (jr.normal(key, shape) * 0.1).astype(jnp.float16)
            return jnp.stack(jnp.split(full, tp, axis))  # (tp, ...)

        params = {
            "w1": jnp.stack([col(jr.fold_in(K, 90 + r), (HID, FFN), 1)
                             for r in range(S)], 1),  # (tp, S, HID, FFN/tp)
            "w2": jnp.stack([col(jr.fold_in(K, 95 + r), (FFN, HID), 0)
                             for r in range(S)], 1),  # (tp, S, FFN/tp, HID)
        }
        M, b = 4, 2
        mbs = jr.normal(jr.fold_in(K, 98),
                        (M, b * dp, HID)).astype(jnp.float16)
        tgts = jr.normal(jr.fold_in(K, 99),
                         (M, b * dp, HID)).astype(jnp.float16)
        zopt = distributed_fused_adam(learning_rate=1e-2)

        def run(params, mbs, tgts):
            local = jax.tree.map(lambda x: x[0, 0], params)
            zstate = zopt.init(local)
            sstate = init_loss_scaler(init_scale=init_scale,
                                      growth_interval=2)
            rdp = jax.lax.axis_index("dp")
            rtp = jax.lax.axis_index("tp")
            rpp = jax.lax.axis_index("pp")

            def step(p, zstate, sstate, inject):
                scale = sstate.loss_scale.astype(jnp.float32)

                def loss_head(out, tgt):
                    return jnp.mean((out.astype(jnp.float32)
                                     - tgt.astype(jnp.float32)) ** 2) * scale

                # fp16 params, accum_dtype=None: the zb backward emits
                # loss-scaled fp16 grads — the reference's fp16 O2 shape
                _, g16 = schedules.forward_backward_pipelining_zero_bubble(
                    stage_fn, loss_head, p, mbs, tgts, accum_dtype=None)
                g16 = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), g16)
                if inject:
                    # this one (dp, tp, pp) rank's microbatch overflowed
                    g16 = dict(g16, w1=jnp.where(
                        (rdp == 1) & (rtp == 1) & (rpp == 0),
                        jnp.full_like(g16["w1"], jnp.inf), g16["w1"]))
                ug = unscale_grads(sstate, g16)
                # found-inf agreed over ALL data/model axes: a single
                # rank's overflow makes EVERY rank skip (the reference
                # GradScaler's model-parallel all-reduce, here dp×tp×pp)
                sstate, finite = update_scaler_model_parallel(
                    sstate, ug, axes=("dp", "tp", "pp"))
                safe = jax.tree.map(
                    lambda x: jnp.where(jnp.isfinite(x), x, 0.0), ug)
                updates, new_z = zopt.update(safe, zstate, p)
                new_p = optax.apply_updates(p, updates)
                p = jax.tree.map(lambda a, b: jnp.where(finite, a, b),
                                 new_p, p)
                zstate = jax.tree.map(lambda a, b: jnp.where(finite, a, b),
                                      new_z, zstate)
                return p, zstate, sstate

            p1, z1, s1 = step(local, zstate, sstate, inject=False)
            p2, z2, s2 = step(p1, z1, s1, inject=True)
            p3, z3, s3 = step(p2, z2, s2, inject=False)
            p4, z4, s4 = step(p3, z3, s3, inject=False)
            scales = jnp.stack([s1.loss_scale, s2.loss_scale,
                                s3.loss_scale, s4.loss_scale])
            stats = {"scales": scales, "skipped": s4.skipped_steps}
            expand = lambda t: jax.tree.map(lambda x: x[None, None], t)
            return (expand(p1), expand(p2), expand(p4),
                    z1.buffers, z2.buffers, stats)

        pspec = jax.tree.map(lambda _: P("tp", "pp"), params)
        buf_spec = {k: P(("dp", "tp", "pp")) for k in ("m", "v", "master")}
        p1, p2, p4, buf1, buf2, stats = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(pspec, P(None, "dp"), P(None, "dp")),
            out_specs=(pspec, pspec, pspec, buf_spec, buf_spec, P()),
        ))(params, mbs, tgts)

        # the skipped step left the sharded fp32 masters and moments
        # BIT-identical on every (dp, tp, pp) rank
        assert set(buf1) == {"m", "v", "master"}
        for name in ("m", "v", "master"):
            a, b_ = np.asarray(buf1[name]), np.asarray(buf2[name])
            assert a.dtype == np.float32
            np.testing.assert_array_equal(
                a, b_, err_msg=f"skipped step mutated sharded {name}")
        for k in params:  # params bitwise untouched by the skipped step
            np.testing.assert_array_equal(np.asarray(p1[k]),
                                          np.asarray(p2[k]))
        np.testing.assert_allclose(np.asarray(stats["scales"]),
                                   [1024.0, 512.0, 512.0, 1024.0])
        assert int(stats["skipped"]) == 1
        moved = any(bool(jnp.any(a != b_))
                    for a, b_ in zip(jax.tree.leaves(p2),
                                     jax.tree.leaves(p4)))
        assert moved  # the finite steps after the skip really trained
        mesh_lib.destroy_model_parallel()

    def test_fp16_grads_keep_fp32_reduction(self):
        """fp16 grads must NOT ride the bf16 reduce-scatter shortcut:
        the mega-buffer flattens them to fp32 (fp16's exponent range
        cannot carry a dp-way sum of loss-scaled grads — the reasoned
        rejection in distributed.py), so the trajectory matches the
        unsharded fused Adam on the same grads."""
        from apex_tpu.optimizers import fused_adam

        mesh, params, base_g, zopt = self._build()
        g16 = jax.tree.map(lambda g: g.astype(jnp.float16), base_g)

        def run(params, grads):
            zstate = zopt.init(params)
            updates, _ = zopt.update(grads, zstate, params)
            return optax.apply_updates(params, updates)

        new_params = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        )(params, g16)
        ref_opt = fused_adam(learning_rate=1e-2)
        st = ref_opt.init(params)
        up, _ = ref_opt.update(g16, st, params)
        ref = optax.apply_updates(params, up)
        for a, e in zip(jax.tree.leaves(new_params), jax.tree.leaves(ref)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(e, np.float32),
                                       rtol=2e-3, atol=2e-4)
