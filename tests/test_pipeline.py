"""Pipeline-parallel schedule tests.

Mirrors the reference's ``tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py``:
each schedule's loss and gradients are compared against the serial
(unpipelined) execution of the same stages.
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import microbatches as mb_lib
from apex_tpu.transformer.pipeline_parallel import schedules

K = jr.PRNGKey(11)
HID = 16


def stage_fn(params, x):
    """One pipeline stage: a residual MLP block (uniform activation shape)."""
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"]


def make_stage_params(key, n_stages):
    def one(k):
        k1, k2 = jr.split(k)
        return {
            "w1": jr.normal(k1, (HID, HID)) * 0.3,
            "b1": jnp.zeros((HID,)),
            "w2": jr.normal(k2, (HID, HID)) * 0.3,
        }
    return [one(jr.fold_in(key, i)) for i in range(n_stages)]


def serial_forward(stage_params_list, x):
    for p in stage_params_list:
        x = stage_fn(p, x)
    return x


def stack_params(plist):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *plist)


class TestMicrobatchCalculator:
    def test_constant(self):
        mb_lib.setup_microbatch_calculator(64, 4, 2)
        assert mb_lib.get_num_microbatches() == 8
        assert mb_lib.get_current_global_batch_size() == 64

    def test_constant_divisibility_error(self):
        with pytest.raises(ValueError):
            mb_lib.build_num_microbatches_calculator(10, 4, 2)

    def test_rampup(self):
        c = mb_lib.build_num_microbatches_calculator(
            64, 4, 2, rampup_batch_size=[16, 8, 600]
        )
        assert c.get_current_global_batch_size() == 16
        c.update(300, False)
        assert c.get_current_global_batch_size() == 40
        c.update(601, False)
        assert c.get_current_global_batch_size() == 64
        assert c.get() == 8


class TestPipelineSPMD:
    def test_forward_matches_serial(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(K, 4)
        stacked = stack_params(plist)
        M = 6
        mbs = jr.normal(jr.fold_in(K, 1), (M, 3, HID))

        out = mesh_lib.shard_map(
            lambda p, m: schedules.pipeline_spmd_forward(
                stage_fn, jax.tree.map(lambda x: x[0], p), m, remat=False
            ),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P()),
            out_specs=P(),
        )(stacked, mbs)

        ref = jax.vmap(lambda m: serial_forward(plist, m))(mbs)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_1f1b_loss_and_grads_match_serial(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(jr.fold_in(K, 2), 4)
        stacked = stack_params(plist)
        M = 4
        mbs = jr.normal(jr.fold_in(K, 3), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 4), (M, 2, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_without_interleaving(
                stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t
            )
            return loss, jax.tree.map(lambda x: x[None], g)

        loss, grads = mesh_lib.shard_map(
            run,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
        )(stacked, mbs, tgts)

        def serial_loss(stacked_p):
            plist_l = [jax.tree.map(lambda x: x[i], stacked_p) for i in range(4)]
            outs = jax.vmap(lambda m: serial_forward(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)

    def test_1f1b_bf16_params_accumulate_fp32_main_grad(self):
        """Pipelined schedules share the fp32 main-grad accumulation: bf16
        stage params yield fp32 grads that match the serial oracle."""
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(jr.fold_in(K, 40), 4)
        stacked = jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                               stack_params(plist))
        mbs = jr.normal(jr.fold_in(K, 41), (4, 2, HID)).astype(jnp.bfloat16)
        tgts = jr.normal(jr.fold_in(K, 42), (4, 2, HID)).astype(jnp.bfloat16)

        def loss_head(out, tgt):
            return jnp.mean((out.astype(jnp.float32)
                             - tgt.astype(jnp.float32)) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_without_interleaving(
                stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t
            )
            return loss, jax.tree.map(lambda x: x[None], g)

        loss, grads = mesh_lib.shard_map(
            run,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
        )(stacked, mbs, tgts)
        assert all(g.dtype == jnp.float32 for g in jax.tree.leaves(grads))

        def serial_loss(stacked_p):
            plist_l = [jax.tree.map(lambda x: x[i], stacked_p)
                       for i in range(4)]
            outs = jax.vmap(lambda m: serial_forward(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        _, ref_grads = jax.value_and_grad(serial_loss)(
            jax.tree.map(lambda x: x.astype(jnp.float32), stacked))
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            # bf16 per-tick rounding bounds the agreement, not accumulation
            np.testing.assert_allclose(a, e, rtol=0.06, atol=6e-3)

    def test_interleaved_matches_serial(self):
        # pp=2 devices, 2 virtual chunks each → 4 virtual stages
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        plist = make_stage_params(jr.fold_in(K, 5), 4)
        M = 4
        mbs = jr.normal(jr.fold_in(K, 6), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 7), (M, 2, HID))

        # device r holds chunks [virtual stage r, virtual stage r+S]:
        # chunk axis first (v, ...) per device → stack as (v, S, ...) and
        # shard axis 1 over pp
        S, v = 2, 2
        # virtual stage k = c*S + r → params_by_chunk[c][r] = plist[c*S + r]
        chunks = [[plist[c * S + r] for r in range(S)] for c in range(v)]
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[jax.tree.map(lambda *ys: jnp.stack(ys), *row) for row in chunks],
        )  # (v, S, ...)

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_with_interleaving(
                stage_fn, loss_head, jax.tree.map(lambda x: x[:, 0], p), m, t,
                virtual_chunks=v,
            )
            return loss, jax.tree.map(lambda x: x[:, None], g)

        loss, grads = mesh_lib.shard_map(
            run,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(None, "pp"), stacked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P(None, "pp"), stacked)),
        )(stacked, mbs, tgts)

        def serial_loss(stacked_p):
            plist_l = [
                jax.tree.map(lambda x: x[k // S, k % S], stacked_p) for k in range(4)
            ]
            outs = jax.vmap(lambda m: serial_forward(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)

    def test_no_pipelining_grad_accumulation(self):
        mesh = mesh_lib.make_mesh()  # dp=8
        w = jr.normal(K, (HID, HID)) * 0.1
        mbs = jr.normal(jr.fold_in(K, 8), (4, 2, HID))  # 4 microbatches

        def loss_fn(w, mb):
            return jnp.mean((mb @ w) ** 2)

        loss, grads = mesh_lib.shard_map(
            lambda w, m: schedules.forward_backward_no_pipelining(loss_fn, w, m),
            mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        )(w, mbs)

        def ref(w):
            return jnp.mean(jax.vmap(lambda m: loss_fn(w, m))(mbs))

        ref_loss, ref_grad = jax.value_and_grad(ref)(w)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
        np.testing.assert_allclose(grads, ref_grad, rtol=1e-5, atol=1e-6)

    def test_no_pipelining_fp32_main_grad_accumulation(self):
        """bf16 params: the accumulator is fp32 by default (the reference's
        main_grad semantics) so many small microbatch grads don't cancel in
        bf16; accum_dtype=None degrades to param-dtype accumulation."""
        w = (jr.normal(K, (HID, HID)) * 0.1).astype(jnp.bfloat16)
        # 64 microbatches of tiny grads — a bf16 accumulator swallows them
        mbs = (jr.normal(jr.fold_in(K, 9), (64, 2, HID)) * 1e-2
               ).astype(jnp.bfloat16)

        def loss_fn(w, mb):
            return jnp.mean((mb.astype(jnp.float32) @ w.astype(jnp.float32))
                            ** 2)

        loss, grads = schedules.forward_backward_no_pipelining(
            loss_fn, w, mbs)
        assert jax.tree.leaves(grads)[0].dtype == jnp.float32

        def ref(w):
            return jnp.mean(jax.vmap(lambda m: loss_fn(w, m))(mbs))

        _, ref_grad = jax.value_and_grad(ref)(w)
        rel = (jnp.abs(grads - ref_grad.astype(jnp.float32)).max()
               / jnp.abs(ref_grad).max())
        _, g_bf16 = schedules.forward_backward_no_pipelining(
            loss_fn, w, mbs, accum_dtype=None)
        assert g_bf16.dtype == jnp.bfloat16
        rel_bf16 = (jnp.abs(g_bf16.astype(jnp.float32)
                            - ref_grad.astype(jnp.float32)).max()
                    / jnp.abs(ref_grad).max())
        # each microbatch grad is itself bf16-rounded (the cotangent casts
        # back at the astype boundary), so fp32 accumulation can't be exact
        # — but it must beat accumulating in bf16 by a clear margin
        assert rel < 5e-3
        assert rel_bf16 > 2 * rel  # bf16 accumulation visibly loses bits

    def test_dispatcher(self):
        f = schedules.get_forward_backward_func(None, 1)
        assert f is schedules.forward_backward_no_pipelining
        f = schedules.get_forward_backward_func(None, 4)
        assert f is schedules.forward_backward_pipelining_without_interleaving
        f = schedules.get_forward_backward_func(2, 4)
        assert f is schedules.forward_backward_pipelining_with_interleaving


class TestP2P:
    def test_rotation_roundtrip(self):
        from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        x = jnp.arange(4.0)

        def run(x):
            fwd = p2p.send_forward(x)
            back = p2p.send_backward(fwd)
            return back

        y = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=P("pp"), out_specs=P("pp")
        )(x)
        np.testing.assert_allclose(y, x)

    def test_rotate_overlapped_matches_blocking(self):
        """The overlapped helper returns exactly (blocking rotation,
        compute result) — the overlap is a scheduling property, never a
        value change."""
        from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        x = jnp.arange(4.0)

        def run(x):
            sent, y = p2p.rotate_overlapped(x, lambda: x * 3.0)
            return sent, y

        sent, y = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=P("pp"), out_specs=(P("pp"), P("pp"))
        )(x)
        ref = mesh_lib.shard_map(
            p2p.send_forward, mesh=mesh, in_specs=P("pp"),
            out_specs=P("pp"))(x)
        np.testing.assert_allclose(sent, ref)
        np.testing.assert_allclose(y, x * 3.0)


def gpt_block_stage(params, x):
    """A real transformer block as a pipeline stage (LN -> attention ->
    residual -> LN -> MLP -> residual), activations (batch, seq, hid)."""
    from apex_tpu.ops import fused_layer_norm
    from apex_tpu.ops.attention import flash_attention

    h = fused_layer_norm(x, params["ln1_w"], params["ln1_b"])
    b, s, hid = h.shape
    heads, d = 2, hid // 2
    qkv = h @ params["qkv_w"]  # (b, s, 3*hid)
    q, k, v = jnp.split(qkv.reshape(b, s, heads, 3 * (hid // heads)), 3, -1)
    ctx = flash_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=True,
    ).transpose(0, 2, 1, 3).reshape(b, s, hid)
    x = x + ctx @ params["ao_w"]
    h = fused_layer_norm(x, params["ln2_w"], params["ln2_b"])
    h = jax.nn.gelu(h @ params["up_w"], approximate=True)
    return x + h @ params["dn_w"]


def make_gpt_stage_params(key, n_stages, hid=HID):
    def one(k):
        ks = jr.split(k, 4)
        return {
            "ln1_w": jnp.ones((hid,)), "ln1_b": jnp.zeros((hid,)),
            "ln2_w": jnp.ones((hid,)), "ln2_b": jnp.zeros((hid,)),
            "qkv_w": jr.normal(ks[0], (hid, 3 * hid)) * 0.2,
            "ao_w": jr.normal(ks[1], (hid, hid)) * 0.2,
            "up_w": jr.normal(ks[2], (hid, 4 * hid)) * 0.2,
            "dn_w": jr.normal(ks[3], (4 * hid, hid)) * 0.2,
        }
    return [one(jr.fold_in(key, i)) for i in range(n_stages)]


class TestGPTBlockPipeline:
    """VERDICT r1 item 7: a real GPT block through pp=4 with interleaving
    (parity target ``tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py``)."""

    def test_pp4_interleaved_gpt_blocks_match_serial(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        v, S = 2, 4  # 8 transformer blocks over 4 devices, 2 chunks each
        plist = make_gpt_stage_params(jr.fold_in(K, 20), v * S)
        M = 8
        mbs = jr.normal(jr.fold_in(K, 21), (M, 2, 8, HID))  # (M, b, s, hid)
        tgts = jr.normal(jr.fold_in(K, 22), (M, 2, 8, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        # device r holds chunks (r, r+S): stack (v, S, ...), shard S over pp
        chunked = jax.tree.map(
            lambda *xs: jnp.stack(xs).reshape(v, S, *xs[0].shape), *plist
        )

        def run(p, m, t):
            local = jax.tree.map(lambda x: x[:, 0], p)  # (v, ...) this device
            loss, g = schedules.forward_backward_pipelining_with_interleaving(
                gpt_block_stage, loss_head, local, m, t, virtual_chunks=v
            )
            return loss, jax.tree.map(lambda x: x[:, None], g)

        loss, grads = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(None, "pp"), chunked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P(None, "pp"), chunked)),
        )(chunked, mbs, tgts)

        def serial_loss(chunked_p):
            # virtual stage order: chunk c, device r -> stage c*S + r
            plist_l = [jax.tree.map(lambda x: x[c, r], chunked_p)
                       for c in range(v) for r in range(S)]
            outs = jax.vmap(lambda m: serial_forward_gpt(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        def serial_forward_gpt(pl, x):
            for p in pl:
                x = gpt_block_stage(p, x)
            return x

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(chunked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-4, atol=1e-5)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=5e-3, atol=5e-4)


class TestInterleavedV3Uneven:
    """VERDICT r5 Next #8: v=3 with an uneven layer count in the
    schedule×feature matrix. 5 real layers mapped onto pp=2 × v=3 = 6
    virtual stages — the last stage is an identity pad (w1=b1=w2=0 makes
    the residual-MLP stage `x + tanh(0)@0 = x`), which is how a layer
    count that does not divide v·S rides the interleaved schedule. The
    bookkeeping under test: odd v breaks the power-of-two chunk/microbatch
    index arithmetic if anything in `item()` silently assumed v | 2."""

    def _stages(self):
        plist = make_stage_params(jr.fold_in(K, 50), 5)
        pad = jax.tree.map(jnp.zeros_like, plist[0])  # identity stage
        return plist + [pad]

    def test_v3_uneven_grads_match_serial(self):
        S, v = 2, 3
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        plist = self._stages()  # 6 virtual stages, the 6th a pad
        M = 2  # the minimum M % S == 0 load: parity, not throughput
        mbs = jr.normal(jr.fold_in(K, 51), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 52), (M, 2, HID))

        # device r holds chunks [r, r+S, r+2S]: stack (v, S, ...)
        chunks = [[plist[c * S + r] for r in range(S)] for c in range(v)]
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[jax.tree.map(lambda *ys: jnp.stack(ys), *row)
              for row in chunks],
        )

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_with_interleaving(
                stage_fn, loss_head, jax.tree.map(lambda x: x[:, 0], p),
                m, t, virtual_chunks=v,
            )
            return loss, jax.tree.map(lambda x: x[:, None], g)

        loss, grads = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(None, "pp"), stacked),
                      P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P(None, "pp"), stacked)),
        )(stacked, mbs, tgts)

        def serial_loss(stacked_p):
            plist_l = [jax.tree.map(lambda x: x[k // S, k % S], stacked_p)
                       for k in range(v * S)]
            outs = jax.vmap(lambda m: serial_forward(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)
        # the identity pad really is inert: its parameter grads vanish
        # (serial agrees, so check on the pipeline's own output)
        pad = jax.tree.map(lambda x: x[v - 1, S - 1], grads)
        assert all(float(jnp.abs(g).max()) < 1e-6
                   for g in jax.tree.leaves(pad))
        # and the pipeline really ran 5 effective layers: equal to the
        # 5-real-stage serial model exactly
        plist5 = [jax.tree.map(lambda x: x[k // S, k % S], stacked)
                  for k in range(5)]
        outs5 = jax.vmap(lambda m: serial_forward(plist5, m))(mbs)
        ref5 = jnp.mean(jax.vmap(loss_head)(outs5, tgts))
        np.testing.assert_allclose(loss, ref5, rtol=1e-5, atol=1e-6)

    def test_v3_per_device_work_counters(self):
        """Same geometry through the aux contract: every device executes
        exactly M·v chunk-ticks (pads included — an identity chunk still
        occupies its schedule slot), fill is S−1 chunk-ticks."""
        S, v, M = 2, 3, 6
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        feat = 8
        mb = jr.normal(jr.fold_in(K, 53), (M, 2, feat))
        params = jnp.ones((v, 1, feat))

        def stage(p, x):
            return x * p[0], 1.0

        def run(p, mb):
            out, work = schedules.pipeline_spmd_forward(
                stage, p, mb, virtual_chunks=v, remat=False, aux_init=0.0)
            return out, work[None]

        _, work = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P("pp")),
        )(params, mb)
        # M*v real chunk-ticks per device out of the scan's M*v + S - 1
        # total (util 18/19 here); the closed form itself is validated
        # against measured counters across v in TestBubbleUtilization
        np.testing.assert_array_equal(np.asarray(work), np.full(S, M * v))


class TestPipelineMemory:
    """Substantiate the 1F1B-memory-equivalence claim (schedules.py docstring):
    with stage remat the pipeline's temp memory must be well below the
    no-remat (GPipe-like) schedule's."""

    def test_remat_bounds_pipeline_temp_memory(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(jr.fold_in(K, 30), 4)
        stacked = stack_params(plist)
        M = 16
        mbs = jr.normal(jr.fold_in(K, 31), (M, 4, HID))
        tgts = jr.normal(jr.fold_in(K, 32), (M, 4, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def make(remat):
            def run(p, m, t):
                def full_loss(local):
                    outs = schedules.pipeline_spmd_forward(
                        stage_fn, local, m, remat=remat)
                    return jnp.mean(jax.vmap(loss_head)(outs, t))
                loss, g = jax.value_and_grad(full_loss)(
                    jax.tree.map(lambda x: x[0], p))
                return loss, jax.tree.map(lambda x: x[None], g)

            return jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
                out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
            ))

        temps = {}
        for remat in (False, True):
            c = make(remat).lower(stacked, mbs, tgts).compile()
            temps[remat] = c.memory_analysis().temp_size_in_bytes
        # documented measurement: remat must cut temp memory substantially
        # (no-remat keeps every tick's residuals live)
        assert temps[True] < temps[False] * 0.7, temps


class TestBuildSchedule:
    """build_schedule glues the microbatch calculator to the schedule
    dispatcher (VERDICT r1 item 7's 'currently disconnected' fix)."""

    def test_picks_microbatches_and_schedule(self):
        fn, calc = schedules.build_schedule(
            global_batch_size=64, micro_batch_size=2, data_parallel_size=2,
            pipeline_model_parallel_size=4)
        assert calc.get() == 16
        assert fn is schedules.forward_backward_pipelining_without_interleaving

    def test_interleaved_partial(self):
        import functools

        fn, calc = schedules.build_schedule(
            global_batch_size=32, micro_batch_size=2, data_parallel_size=1,
            pipeline_model_parallel_size=4,
            virtual_pipeline_model_parallel_size=2)
        assert isinstance(fn, functools.partial)
        assert fn.keywords["virtual_chunks"] == 2
        assert calc.get() == 16

    def test_rejects_underfilled_pipeline(self):
        with pytest.raises(ValueError, match="cannot fill"):
            schedules.build_schedule(
                global_batch_size=8, micro_batch_size=4,
                data_parallel_size=1, pipeline_model_parallel_size=4)

    def test_interleaved_rejects_ragged_microbatch_count(self):
        """The group-of-S flow (and the reference's assert,
        fwd_bwd_pipelining_with_interleaving.py:87) needs M % pp == 0."""
        with pytest.raises(ValueError, match="divisible"):
            schedules.build_schedule(
                global_batch_size=12, micro_batch_size=2,
                data_parallel_size=1, pipeline_model_parallel_size=4,
                virtual_pipeline_model_parallel_size=2)

    def test_unknown_schedule_name_names_knob_and_legal_values(self):
        """ISSUE 8 satellite: a typo'd schedule= fails eagerly naming the
        knob and every legal value — not as a deep error mid-trace."""
        with pytest.raises(ValueError) as e:
            schedules.build_schedule(
                global_batch_size=32, micro_batch_size=2,
                data_parallel_size=1, pipeline_model_parallel_size=4,
                schedule="zero-bubble")
        msg = str(e.value)
        assert "schedule=" in msg
        for name in ("1f1b", "interleaved", "zb"):
            assert name in msg, msg

    def test_schedule_zb_selected(self):
        fn, calc = schedules.build_schedule(
            global_batch_size=32, micro_batch_size=2, data_parallel_size=1,
            pipeline_model_parallel_size=4, schedule="zb")
        assert fn is schedules.forward_backward_pipelining_zero_bubble
        assert calc.get() == 16

    def test_schedule_zb_interleaved_overlap_partial(self):
        import functools

        fn, _ = schedules.build_schedule(
            global_batch_size=32, micro_batch_size=2, data_parallel_size=1,
            pipeline_model_parallel_size=4,
            virtual_pipeline_model_parallel_size=3, schedule="zb",
            overlap_p2p=True)
        assert isinstance(fn, functools.partial)
        assert fn.func is schedules.forward_backward_pipelining_zero_bubble
        assert fn.keywords == {"virtual_chunks": 3, "overlap_p2p": True}

    def test_interleaved_demands_virtual_chunks(self):
        with pytest.raises(ValueError, match="virtual_pipeline"):
            schedules.build_schedule(
                global_batch_size=32, micro_batch_size=2,
                data_parallel_size=1, pipeline_model_parallel_size=4,
                schedule="interleaved")

    def test_1f1b_rejects_contradictory_virtual_chunks(self):
        with pytest.raises(ValueError, match="interleav"):
            schedules.build_schedule(
                global_batch_size=32, micro_batch_size=2,
                data_parallel_size=1, pipeline_model_parallel_size=4,
                virtual_pipeline_model_parallel_size=2, schedule="1f1b")

    def test_zb_rejects_single_stage(self):
        with pytest.raises(ValueError, match="pipeline_model_parallel"):
            schedules.build_schedule(
                global_batch_size=32, micro_batch_size=2,
                data_parallel_size=1, pipeline_model_parallel_size=1,
                schedule="zb")

    def test_overlap_doubles_interleaved_group(self):
        """M=12 divides pp=4 but not 2·pp=8: fine blocking, rejected —
        eagerly, naming overlap_p2p — with the overlapped hop."""
        kw = dict(global_batch_size=24, micro_batch_size=2,
                  data_parallel_size=1, pipeline_model_parallel_size=4,
                  virtual_pipeline_model_parallel_size=2)
        schedules.build_schedule(**kw)  # 12 microbatches, M % 4 == 0
        with pytest.raises(ValueError, match="overlap_p2p"):
            schedules.build_schedule(**kw, overlap_p2p=True)

    def test_end_to_end_with_calculator(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        fn, calc = schedules.build_schedule(
            global_batch_size=8, micro_batch_size=2, data_parallel_size=1,
            pipeline_model_parallel_size=4)
        M = calc.get()
        plist = make_stage_params(jr.fold_in(K, 40), 4)
        stacked = stack_params(plist)
        mbs = jr.normal(jr.fold_in(K, 41), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 42), (M, 2, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = fn(stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t)
            return loss, jax.tree.map(lambda x: x[None], g)

        loss, _ = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
        )(stacked, mbs, tgts)
        assert np.isfinite(float(loss))


def _chunked_stack(plist, S, v):
    """Device layout for v virtual chunks: (v, S, ...) with virtual stage
    k = c·S + r at [c, r] — the interleaved assignment."""
    chunks = [[plist[c * S + r] for r in range(S)] for c in range(v)]
    return jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[jax.tree.map(lambda *ys: jnp.stack(ys), *row) for row in chunks])


class TestZeroBubble:
    """The ``schedule="zb"`` matrix (ISSUE 8): grad parity vs the serial
    oracle over pp ∈ {2, 4} × v ∈ {1, 3} × ±overlap_p2p, fp32 main-grad
    accumulation, the deferred-dW geometry read off the jaxpr, and
    recompile-freedom across schedule-geometry reuse. The heaviest cells
    ride ``_SLOW_OFF_TPU`` (tier-1 siblings named there)."""

    def _run_case(self, S, v, overlap, M):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        plist = make_stage_params(jr.fold_in(K, 60 + S), S * v)
        mbs = jr.normal(jr.fold_in(K, 61), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 62), (M, 2, HID))
        if v == 1:
            stacked = stack_params(plist)
            strip, restore = (lambda x: x[0]), (lambda x: x[None])
            spec = jax.tree.map(lambda _: P("pp"), stacked)
        else:
            stacked = _chunked_stack(plist, S, v)
            strip, restore = (lambda x: x[:, 0]), (lambda x: x[:, None])
            spec = jax.tree.map(lambda _: P(None, "pp"), stacked)

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_zero_bubble(
                stage_fn, loss_head, jax.tree.map(strip, p), m, t,
                virtual_chunks=v, overlap_p2p=overlap)
            return loss, jax.tree.map(restore, g)

        loss, grads = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(spec, P(), P()),
            out_specs=(P(), spec))(stacked, mbs, tgts)

        def serial_loss(sp):
            if v == 1:
                pl = [jax.tree.map(lambda x: x[i], sp) for i in range(S)]
            else:
                pl = [jax.tree.map(lambda x: x[k // S, k % S], sp)
                      for k in range(v * S)]
            outs = jax.vmap(lambda m: serial_forward(pl, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("overlap", [False, True])
    def test_pp2_v1(self, overlap):
        self._run_case(2, 1, overlap, M=5)  # odd M: no grouping at v=1

    @pytest.mark.parametrize("overlap", [False, True])
    def test_pp2_v3(self, overlap):
        # overlap doubles the injection group: M % 2S == 0
        self._run_case(2, 3, overlap, M=4)

    @pytest.mark.parametrize("overlap", [False, True])
    def test_pp4_v1(self, overlap):
        self._run_case(4, 1, overlap, M=6)

    @pytest.mark.parametrize("overlap", [False, True])
    def test_pp4_v3(self, overlap):
        self._run_case(4, 3, overlap, M=8)

    def test_zb_v3_uneven_layer_count(self):
        """5 real layers on pp=2 × v=3 via the identity pad (the
        TestInterleavedV3Uneven recipe) through the zb backward: parity,
        pad grads exactly zero."""
        S, v, M = 2, 3, 2
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        plist = make_stage_params(jr.fold_in(K, 65), 5)
        plist.append(jax.tree.map(jnp.zeros_like, plist[0]))  # identity
        stacked = _chunked_stack(plist, S, v)
        mbs = jr.normal(jr.fold_in(K, 66), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 67), (M, 2, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_zero_bubble(
                stage_fn, loss_head, jax.tree.map(lambda x: x[:, 0], p),
                m, t, virtual_chunks=v)
            return loss, jax.tree.map(lambda x: x[:, None], g)

        spec = jax.tree.map(lambda _: P(None, "pp"), stacked)
        loss, grads = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(spec, P(), P()),
            out_specs=(P(), spec))(stacked, mbs, tgts)

        def serial_loss(sp):
            pl = [jax.tree.map(lambda x: x[k // S, k % S], sp)
                  for k in range(v * S)]
            outs = jax.vmap(lambda m: serial_forward(pl, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)
        pad = jax.tree.map(lambda x: x[v - 1, S - 1], grads)
        assert all(float(jnp.abs(g).max()) < 1e-6
                   for g in jax.tree.leaves(pad))

    def test_zb_bf16_params_accumulate_fp32_main_grad(self):
        """The zb dW sweep accumulates in the upcast (fp32) params'
        dtype in the same reverse order as the autodiff transpose — bf16
        stage params yield fp32 grads matching the serial oracle."""
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(jr.fold_in(K, 68), 4)
        stacked = jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                               stack_params(plist))
        mbs = jr.normal(jr.fold_in(K, 69), (4, 2, HID)).astype(jnp.bfloat16)
        tgts = jr.normal(jr.fold_in(K, 70), (4, 2, HID)).astype(jnp.bfloat16)

        def loss_head(out, tgt):
            return jnp.mean((out.astype(jnp.float32)
                             - tgt.astype(jnp.float32)) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_zero_bubble(
                stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t)
            return loss, jax.tree.map(lambda x: x[None], g)

        spec = jax.tree.map(lambda _: P("pp"), stacked)
        loss, grads = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(spec, P(), P()),
            out_specs=(P(), spec))(stacked, mbs, tgts)
        assert all(g.dtype == jnp.float32 for g in jax.tree.leaves(grads))

        def serial_loss(sp):
            pl = [jax.tree.map(lambda x: x[i], sp) for i in range(4)]
            outs = jax.vmap(lambda m: serial_forward(pl, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        _, ref_grads = jax.value_and_grad(serial_loss)(
            jax.tree.map(lambda x: x.astype(jnp.float32), stacked))
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=0.06, atol=6e-3)

    def _grad_fn(self, schedule, S=4, M=6):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        plist = make_stage_params(jr.fold_in(K, 71), S)
        stacked = stack_params(plist)
        spec = jax.tree.map(lambda _: P("pp"), stacked)

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        fwd_bwd = (schedules.forward_backward_pipelining_zero_bubble
                   if schedule == "zb" else
                   schedules.forward_backward_pipelining_without_interleaving)

        def run(p, m, t):
            loss, g = fwd_bwd(
                stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t)
            return loss, jax.tree.map(lambda x: x[None], g)

        f = mesh_lib.shard_map(run, mesh=mesh, in_specs=(spec, P(), P()),
                               out_specs=(P(), spec))
        return f, stacked, (jr.normal(jr.fold_in(K, 72), (M, 2, HID)),
                            jr.normal(jr.fold_in(K, 73), (M, 2, HID)))

    def test_dw_deferral_geometry_in_jaxpr(self):
        """The dW-deferral ORDERING asserted from trace-time geometry,
        through the shared JXP contract helpers (the one-off scan-length
        walker this test used to carry now lives in
        ``apex_tpu.lint.jaxpr_check``): the zb program contains a third
        scan of exactly M·v ticks (the deferred dW sweep, distinct from
        the two T = M·v + S − 1 sweeps) and that sweep is
        collective-free; the autodiff schedule has no M·v-length scan —
        its dW rides the full-length backward scan, garbage lanes
        included."""
        from apex_tpu.lint import contracts as jc

        S, M = 4, 6
        T = M + S - 1
        zb_f, zb_p, (m, t) = self._grad_fn("zb", S, M)
        jc.assert_contracts(jax.make_jaxpr(zb_f)(zb_p, m, t), [
            jc.scan_length(T, min_count=2),   # fwd + dX sweeps
            jc.scan_length(M),                # the deferred dW sweep...
            jc.collective_free_region(        # ...which is hop-free
                rf"(^|/)scan:{M}(\.\d+)?(/|$)", region="deferred-dW sweep"),
        ])
        base_f, base_p, (m, t) = self._grad_fn("1f1b", S, M)
        jc.assert_contracts(jax.make_jaxpr(base_f)(base_p, m, t), [
            jc.scan_length(T, min_count=2),
            jc.scan_length(M, forbid=True),   # no deferred sweep in 1f1b
        ])

    @pytest.mark.parametrize("overlap", [False, True])
    def test_recompile_free_geometry_reuse(self, overlap):
        """Acceptance: the jitted zb path stays recompile-free across
        schedule-geometry reuse — fresh data, same geometry, cache
        pinned at 1."""
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        plist = make_stage_params(jr.fold_in(K, 74), 2)
        stacked = stack_params(plist)
        spec = jax.tree.map(lambda _: P("pp"), stacked)
        mbs = jr.normal(jr.fold_in(K, 75), (4, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 76), (4, 2, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_zero_bubble(
                stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t,
                overlap_p2p=overlap)
            return loss, jax.tree.map(lambda x: x[None], g)

        step = jax.jit(mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(spec, P(), P()),
            out_specs=(P(), spec)))
        l1, _ = step(stacked, mbs, tgts)
        l2, _ = step(stacked, mbs + 1.0, tgts)
        l3, _ = step(stacked, mbs, tgts - 1.0)
        assert step._cache_size() == 1
        assert np.isfinite(float(l1) + float(l2) + float(l3))

    @pytest.mark.parametrize("overlap", [False, True])
    def test_zb_work_counters_closed_form(self, overlap):
        """Per-device work counters through the zb forward's aux
        contract: every device executes exactly M·v real chunk-ticks of
        the schedule's fwd_ticks total — the closed form
        pipeline_cost_model prices."""
        from apex_tpu.monitor import pipeline_cost_model

        S, v, M = 2, 3, 4
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        feat = 8
        mb = jr.normal(jr.fold_in(K, 77), (M, 2, feat))
        params = jnp.ones((v, 1, feat))

        def stage(p, x):
            return x * p[0], 1.0

        def run(p, mb):
            out, work = schedules.pipeline_spmd_forward(
                stage, p, mb, virtual_chunks=v, remat=False, aux_init=0.0,
                schedule="zb", overlap_p2p=overlap)
            return out, work[None]

        _, work = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P("pp")),
        )(params, mb)
        np.testing.assert_array_equal(np.asarray(work), np.full(S, M * v))
        cost = pipeline_cost_model(M, S, v, schedule="zb",
                                   overlap_p2p=overlap)
        L = 2 if overlap else 1
        assert cost["fwd_ticks"] == M * v + L * (S - 1) + (L - 1)
        assert cost["bwd_dw_ticks"] == M * v

    def test_cost_model_zb_beats_1f1b(self):
        """Acceptance: the trace-time geometry shows the zb schedule's
        smaller step bubble at pp >= 2 — closed forms pinned, and the
        ordering holds across the matrix geometries."""
        from apex_tpu.monitor import pipeline_cost_model

        # M=8, S=4, v=1: 1f1b total 33 units, zb total 30 — bubble
        # 9/33 = 27.3% vs 6/30 = 20.0%
        base = pipeline_cost_model(8, 4, 1, schedule="1f1b")
        zb = pipeline_cost_model(8, 4, 1, schedule="zb")
        np.testing.assert_allclose(base["bubble_fraction"], 9 / 33)
        np.testing.assert_allclose(zb["bubble_fraction"], 6 / 30)
        for (M, S, v) in ((8, 4, 1), (4, 2, 3), (8, 4, 3), (16, 2, 1)):
            b = pipeline_cost_model(M, S, v, schedule="1f1b")
            z = pipeline_cost_model(M, S, v, schedule="zb")
            assert z["bubble_fraction"] < b["bubble_fraction"], (M, S, v)
            assert z["ideal_units"] == b["ideal_units"] == 3 * M * v
            # recompute honesty: both zb sweeps rebuild the forward from
            # the stashed inputs — M·v units MORE than rematted 1f1b.
            # The slot-bubble win above does not hide it.
            assert z["recompute_units"] == b["recompute_units"] + M * v
            # and what the extra recompute buys: the whole dW sweep has
            # no collective on the critical path
            assert z["collective_free_ticks"] == M * v
            assert b["collective_free_ticks"] == 0
        with pytest.raises(ValueError, match="schedule="):
            pipeline_cost_model(8, 4, 1, schedule="zbb")

    def test_dispatcher_rejects_unknown_schedule(self):
        """A typo'd schedule must not silently train on the default."""
        with pytest.raises(ValueError, match="schedule="):
            schedules.get_forward_backward_func(None, 4, schedule="ZB")
        assert schedules.get_forward_backward_func(None, 4, schedule="zb") \
            is schedules.forward_backward_pipelining_zero_bubble

    def test_eager_validation_errors(self):
        """Bad geometry fails at call time naming the knob, not as a
        deep shape error mid-trace."""
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        params = jnp.ones((3, 1, HID))
        mb = jr.normal(jr.fold_in(K, 78), (6, 2, HID))

        def stage(p, x):
            return x * p[0]

        def call(**kw):
            return mesh_lib.shard_map(
                lambda p, m: schedules.pipeline_spmd_forward(
                    stage, p, m, virtual_chunks=3, remat=False, **kw),
                mesh=mesh, in_specs=(P(), P()), out_specs=P())(params, mb)

        with pytest.raises(ValueError, match="schedule="):
            call(schedule="zbb")
        # M=6: fine at v=3 S=2 blocking, ragged for the 2*S group
        with pytest.raises(ValueError, match="2\\*pipeline_size"):
            call(schedule="zb", overlap_p2p=True)
    """EMPIRICAL bubble evidence (VERDICT r3 weak #4): per-device work
    counters through the real scanned schedule. Wall-clock on the
    single-core virtual mesh measures total work, not bubble — these
    counters measure exactly the quantity interleaving trades: the share
    of a device's tick slots holding REAL (in-flight) work."""

    def _measure(self, v, M=8, S=4):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        feat = 8
        mb = jr.normal(jr.PRNGKey(0), (M, 2, feat))
        # v chunks of identity-ish params; the aux contract counts ticks
        params = jnp.ones((v, 1, feat)) if v > 1 else jnp.ones((1, feat))

        def stage(p, x):
            return x * p[0], 1.0  # aux = one unit of real work

        def run(p, mb):
            out, work = schedules.pipeline_spmd_forward(
                stage, p, mb, virtual_chunks=v, remat=False,
                aux_init=0.0)
            return out, work[None]  # rank-1 so out_specs can concat per pp

        _, work = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()),
            out_specs=(P(), P("pp")),
        )(params, mb)
        T = M * v + S - 1 if v > 1 else M + S - 1
        return np.asarray(work), T

    def test_per_device_work_counters_show_v2_bubble_shrink(self):
        M, S = 8, 4
        utils = {}
        for v in (1, 2, 3, 4):
            work, T = self._measure(v, M, S)
            # every device executes exactly its M*v real chunk-ticks —
            # the schedule wastes no slots beyond the theoretical fill
            # (odd v included: the item() arithmetic is modular, not
            # power-of-two)
            np.testing.assert_array_equal(work, np.full(S, M * v))
            utils[v] = M * v / T
        # closed form (M*v)/(M*v + S - 1): 0.727 / 0.842 / 0.889 / 0.914
        np.testing.assert_allclose(utils[1], 8 / 11)
        np.testing.assert_allclose(utils[2], 16 / 19)
        np.testing.assert_allclose(utils[3], 24 / 27)
        np.testing.assert_allclose(utils[4], 32 / 35)
        assert utils[2] > utils[1], "v=2 must shrink the bubble vs v=1"
        assert utils[4] > utils[3] > utils[2]
