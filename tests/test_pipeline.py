"""Pipeline-parallel schedule tests.

Mirrors the reference's ``tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py``:
each schedule's loss and gradients are compared against the serial
(unpipelined) execution of the same stages.
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import microbatches as mb_lib
from apex_tpu.transformer.pipeline_parallel import schedules

K = jr.PRNGKey(11)
HID = 16


def stage_fn(params, x):
    """One pipeline stage: a residual MLP block (uniform activation shape)."""
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"]


def make_stage_params(key, n_stages):
    def one(k):
        k1, k2 = jr.split(k)
        return {
            "w1": jr.normal(k1, (HID, HID)) * 0.3,
            "b1": jnp.zeros((HID,)),
            "w2": jr.normal(k2, (HID, HID)) * 0.3,
        }
    return [one(jr.fold_in(key, i)) for i in range(n_stages)]


def serial_forward(stage_params_list, x):
    for p in stage_params_list:
        x = stage_fn(p, x)
    return x


def stack_params(plist):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *plist)


class TestMicrobatchCalculator:
    def test_constant(self):
        mb_lib.setup_microbatch_calculator(64, 4, 2)
        assert mb_lib.get_num_microbatches() == 8
        assert mb_lib.get_current_global_batch_size() == 64

    def test_constant_divisibility_error(self):
        with pytest.raises(ValueError):
            mb_lib.build_num_microbatches_calculator(10, 4, 2)

    def test_rampup(self):
        c = mb_lib.build_num_microbatches_calculator(
            64, 4, 2, rampup_batch_size=[16, 8, 600]
        )
        assert c.get_current_global_batch_size() == 16
        c.update(300, False)
        assert c.get_current_global_batch_size() == 40
        c.update(601, False)
        assert c.get_current_global_batch_size() == 64
        assert c.get() == 8


class TestPipelineSPMD:
    def test_forward_matches_serial(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(K, 4)
        stacked = stack_params(plist)
        M = 6
        mbs = jr.normal(jr.fold_in(K, 1), (M, 3, HID))

        out = mesh_lib.shard_map(
            lambda p, m: schedules.pipeline_spmd_forward(
                stage_fn, jax.tree.map(lambda x: x[0], p), m, remat=False
            ),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P()),
            out_specs=P(),
        )(stacked, mbs)

        ref = jax.vmap(lambda m: serial_forward(plist, m))(mbs)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_1f1b_loss_and_grads_match_serial(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(jr.fold_in(K, 2), 4)
        stacked = stack_params(plist)
        M = 4
        mbs = jr.normal(jr.fold_in(K, 3), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 4), (M, 2, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_without_interleaving(
                stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t
            )
            return loss, jax.tree.map(lambda x: x[None], g)

        loss, grads = mesh_lib.shard_map(
            run,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
        )(stacked, mbs, tgts)

        def serial_loss(stacked_p):
            plist_l = [jax.tree.map(lambda x: x[i], stacked_p) for i in range(4)]
            outs = jax.vmap(lambda m: serial_forward(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)

    def test_1f1b_bf16_params_accumulate_fp32_main_grad(self):
        """Pipelined schedules share the fp32 main-grad accumulation: bf16
        stage params yield fp32 grads that match the serial oracle."""
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(jr.fold_in(K, 40), 4)
        stacked = jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                               stack_params(plist))
        mbs = jr.normal(jr.fold_in(K, 41), (4, 2, HID)).astype(jnp.bfloat16)
        tgts = jr.normal(jr.fold_in(K, 42), (4, 2, HID)).astype(jnp.bfloat16)

        def loss_head(out, tgt):
            return jnp.mean((out.astype(jnp.float32)
                             - tgt.astype(jnp.float32)) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_without_interleaving(
                stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t
            )
            return loss, jax.tree.map(lambda x: x[None], g)

        loss, grads = mesh_lib.shard_map(
            run,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
        )(stacked, mbs, tgts)
        assert all(g.dtype == jnp.float32 for g in jax.tree.leaves(grads))

        def serial_loss(stacked_p):
            plist_l = [jax.tree.map(lambda x: x[i], stacked_p)
                       for i in range(4)]
            outs = jax.vmap(lambda m: serial_forward(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        _, ref_grads = jax.value_and_grad(serial_loss)(
            jax.tree.map(lambda x: x.astype(jnp.float32), stacked))
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            # bf16 per-tick rounding bounds the agreement, not accumulation
            np.testing.assert_allclose(a, e, rtol=0.06, atol=6e-3)

    def test_interleaved_matches_serial(self):
        # pp=2 devices, 2 virtual chunks each → 4 virtual stages
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        plist = make_stage_params(jr.fold_in(K, 5), 4)
        M = 4
        mbs = jr.normal(jr.fold_in(K, 6), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 7), (M, 2, HID))

        # device r holds chunks [virtual stage r, virtual stage r+S]:
        # chunk axis first (v, ...) per device → stack as (v, S, ...) and
        # shard axis 1 over pp
        S, v = 2, 2
        # virtual stage k = c*S + r → params_by_chunk[c][r] = plist[c*S + r]
        chunks = [[plist[c * S + r] for r in range(S)] for c in range(v)]
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[jax.tree.map(lambda *ys: jnp.stack(ys), *row) for row in chunks],
        )  # (v, S, ...)

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_with_interleaving(
                stage_fn, loss_head, jax.tree.map(lambda x: x[:, 0], p), m, t,
                virtual_chunks=v,
            )
            return loss, jax.tree.map(lambda x: x[:, None], g)

        loss, grads = mesh_lib.shard_map(
            run,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(None, "pp"), stacked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P(None, "pp"), stacked)),
        )(stacked, mbs, tgts)

        def serial_loss(stacked_p):
            plist_l = [
                jax.tree.map(lambda x: x[k // S, k % S], stacked_p) for k in range(4)
            ]
            outs = jax.vmap(lambda m: serial_forward(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)

    def test_no_pipelining_grad_accumulation(self):
        mesh = mesh_lib.make_mesh()  # dp=8
        w = jr.normal(K, (HID, HID)) * 0.1
        mbs = jr.normal(jr.fold_in(K, 8), (4, 2, HID))  # 4 microbatches

        def loss_fn(w, mb):
            return jnp.mean((mb @ w) ** 2)

        loss, grads = mesh_lib.shard_map(
            lambda w, m: schedules.forward_backward_no_pipelining(loss_fn, w, m),
            mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        )(w, mbs)

        def ref(w):
            return jnp.mean(jax.vmap(lambda m: loss_fn(w, m))(mbs))

        ref_loss, ref_grad = jax.value_and_grad(ref)(w)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
        np.testing.assert_allclose(grads, ref_grad, rtol=1e-5, atol=1e-6)

    def test_no_pipelining_fp32_main_grad_accumulation(self):
        """bf16 params: the accumulator is fp32 by default (the reference's
        main_grad semantics) so many small microbatch grads don't cancel in
        bf16; accum_dtype=None degrades to param-dtype accumulation."""
        w = (jr.normal(K, (HID, HID)) * 0.1).astype(jnp.bfloat16)
        # 64 microbatches of tiny grads — a bf16 accumulator swallows them
        mbs = (jr.normal(jr.fold_in(K, 9), (64, 2, HID)) * 1e-2
               ).astype(jnp.bfloat16)

        def loss_fn(w, mb):
            return jnp.mean((mb.astype(jnp.float32) @ w.astype(jnp.float32))
                            ** 2)

        loss, grads = schedules.forward_backward_no_pipelining(
            loss_fn, w, mbs)
        assert jax.tree.leaves(grads)[0].dtype == jnp.float32

        def ref(w):
            return jnp.mean(jax.vmap(lambda m: loss_fn(w, m))(mbs))

        _, ref_grad = jax.value_and_grad(ref)(w)
        rel = (jnp.abs(grads - ref_grad.astype(jnp.float32)).max()
               / jnp.abs(ref_grad).max())
        _, g_bf16 = schedules.forward_backward_no_pipelining(
            loss_fn, w, mbs, accum_dtype=None)
        assert g_bf16.dtype == jnp.bfloat16
        rel_bf16 = (jnp.abs(g_bf16.astype(jnp.float32)
                            - ref_grad.astype(jnp.float32)).max()
                    / jnp.abs(ref_grad).max())
        # each microbatch grad is itself bf16-rounded (the cotangent casts
        # back at the astype boundary), so fp32 accumulation can't be exact
        # — but it must beat accumulating in bf16 by a clear margin
        assert rel < 5e-3
        assert rel_bf16 > 2 * rel  # bf16 accumulation visibly loses bits

    def test_dispatcher(self):
        f = schedules.get_forward_backward_func(None, 1)
        assert f is schedules.forward_backward_no_pipelining
        f = schedules.get_forward_backward_func(None, 4)
        assert f is schedules.forward_backward_pipelining_without_interleaving
        f = schedules.get_forward_backward_func(2, 4)
        assert f is schedules.forward_backward_pipelining_with_interleaving


class TestP2P:
    def test_rotation_roundtrip(self):
        from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        x = jnp.arange(4.0)

        def run(x):
            fwd = p2p.send_forward(x)
            back = p2p.send_backward(fwd)
            return back

        y = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=P("pp"), out_specs=P("pp")
        )(x)
        np.testing.assert_allclose(y, x)


def gpt_block_stage(params, x):
    """A real transformer block as a pipeline stage (LN -> attention ->
    residual -> LN -> MLP -> residual), activations (batch, seq, hid)."""
    from apex_tpu.ops import fused_layer_norm
    from apex_tpu.ops.attention import flash_attention

    h = fused_layer_norm(x, params["ln1_w"], params["ln1_b"])
    b, s, hid = h.shape
    heads, d = 2, hid // 2
    qkv = h @ params["qkv_w"]  # (b, s, 3*hid)
    q, k, v = jnp.split(qkv.reshape(b, s, heads, 3 * (hid // heads)), 3, -1)
    ctx = flash_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=True,
    ).transpose(0, 2, 1, 3).reshape(b, s, hid)
    x = x + ctx @ params["ao_w"]
    h = fused_layer_norm(x, params["ln2_w"], params["ln2_b"])
    h = jax.nn.gelu(h @ params["up_w"], approximate=True)
    return x + h @ params["dn_w"]


def make_gpt_stage_params(key, n_stages, hid=HID):
    def one(k):
        ks = jr.split(k, 4)
        return {
            "ln1_w": jnp.ones((hid,)), "ln1_b": jnp.zeros((hid,)),
            "ln2_w": jnp.ones((hid,)), "ln2_b": jnp.zeros((hid,)),
            "qkv_w": jr.normal(ks[0], (hid, 3 * hid)) * 0.2,
            "ao_w": jr.normal(ks[1], (hid, hid)) * 0.2,
            "up_w": jr.normal(ks[2], (hid, 4 * hid)) * 0.2,
            "dn_w": jr.normal(ks[3], (4 * hid, hid)) * 0.2,
        }
    return [one(jr.fold_in(key, i)) for i in range(n_stages)]


class TestGPTBlockPipeline:
    """VERDICT r1 item 7: a real GPT block through pp=4 with interleaving
    (parity target ``tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py``)."""

    def test_pp4_interleaved_gpt_blocks_match_serial(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        v, S = 2, 4  # 8 transformer blocks over 4 devices, 2 chunks each
        plist = make_gpt_stage_params(jr.fold_in(K, 20), v * S)
        M = 8
        mbs = jr.normal(jr.fold_in(K, 21), (M, 2, 8, HID))  # (M, b, s, hid)
        tgts = jr.normal(jr.fold_in(K, 22), (M, 2, 8, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        # device r holds chunks (r, r+S): stack (v, S, ...), shard S over pp
        chunked = jax.tree.map(
            lambda *xs: jnp.stack(xs).reshape(v, S, *xs[0].shape), *plist
        )

        def run(p, m, t):
            local = jax.tree.map(lambda x: x[:, 0], p)  # (v, ...) this device
            loss, g = schedules.forward_backward_pipelining_with_interleaving(
                gpt_block_stage, loss_head, local, m, t, virtual_chunks=v
            )
            return loss, jax.tree.map(lambda x: x[:, None], g)

        loss, grads = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(None, "pp"), chunked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P(None, "pp"), chunked)),
        )(chunked, mbs, tgts)

        def serial_loss(chunked_p):
            # virtual stage order: chunk c, device r -> stage c*S + r
            plist_l = [jax.tree.map(lambda x: x[c, r], chunked_p)
                       for c in range(v) for r in range(S)]
            outs = jax.vmap(lambda m: serial_forward_gpt(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        def serial_forward_gpt(pl, x):
            for p in pl:
                x = gpt_block_stage(p, x)
            return x

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(chunked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-4, atol=1e-5)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=5e-3, atol=5e-4)


class TestInterleavedV3Uneven:
    """VERDICT r5 Next #8: v=3 with an uneven layer count in the
    schedule×feature matrix. 5 real layers mapped onto pp=2 × v=3 = 6
    virtual stages — the last stage is an identity pad (w1=b1=w2=0 makes
    the residual-MLP stage `x + tanh(0)@0 = x`), which is how a layer
    count that does not divide v·S rides the interleaved schedule. The
    bookkeeping under test: odd v breaks the power-of-two chunk/microbatch
    index arithmetic if anything in `item()` silently assumed v | 2."""

    def _stages(self):
        plist = make_stage_params(jr.fold_in(K, 50), 5)
        pad = jax.tree.map(jnp.zeros_like, plist[0])  # identity stage
        return plist + [pad]

    def test_v3_uneven_grads_match_serial(self):
        S, v = 2, 3
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        plist = self._stages()  # 6 virtual stages, the 6th a pad
        M = 2  # the minimum M % S == 0 load: parity, not throughput
        mbs = jr.normal(jr.fold_in(K, 51), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 52), (M, 2, HID))

        # device r holds chunks [r, r+S, r+2S]: stack (v, S, ...)
        chunks = [[plist[c * S + r] for r in range(S)] for c in range(v)]
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[jax.tree.map(lambda *ys: jnp.stack(ys), *row)
              for row in chunks],
        )

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = schedules.forward_backward_pipelining_with_interleaving(
                stage_fn, loss_head, jax.tree.map(lambda x: x[:, 0], p),
                m, t, virtual_chunks=v,
            )
            return loss, jax.tree.map(lambda x: x[:, None], g)

        loss, grads = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(None, "pp"), stacked),
                      P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P(None, "pp"), stacked)),
        )(stacked, mbs, tgts)

        def serial_loss(stacked_p):
            plist_l = [jax.tree.map(lambda x: x[k // S, k % S], stacked_p)
                       for k in range(v * S)]
            outs = jax.vmap(lambda m: serial_forward(plist_l, m))(mbs)
            return jnp.mean(jax.vmap(loss_head)(outs, tgts))

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(stacked)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for a, e in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-5)
        # the identity pad really is inert: its parameter grads vanish
        # (serial agrees, so check on the pipeline's own output)
        pad = jax.tree.map(lambda x: x[v - 1, S - 1], grads)
        assert all(float(jnp.abs(g).max()) < 1e-6
                   for g in jax.tree.leaves(pad))
        # and the pipeline really ran 5 effective layers: equal to the
        # 5-real-stage serial model exactly
        plist5 = [jax.tree.map(lambda x: x[k // S, k % S], stacked)
                  for k in range(5)]
        outs5 = jax.vmap(lambda m: serial_forward(plist5, m))(mbs)
        ref5 = jnp.mean(jax.vmap(loss_head)(outs5, tgts))
        np.testing.assert_allclose(loss, ref5, rtol=1e-5, atol=1e-6)

    def test_v3_per_device_work_counters(self):
        """Same geometry through the aux contract: every device executes
        exactly M·v chunk-ticks (pads included — an identity chunk still
        occupies its schedule slot), fill is S−1 chunk-ticks."""
        S, v, M = 2, 3, 6
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        feat = 8
        mb = jr.normal(jr.fold_in(K, 53), (M, 2, feat))
        params = jnp.ones((v, 1, feat))

        def stage(p, x):
            return x * p[0], 1.0

        def run(p, mb):
            out, work = schedules.pipeline_spmd_forward(
                stage, p, mb, virtual_chunks=v, remat=False, aux_init=0.0)
            return out, work[None]

        _, work = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P("pp")),
        )(params, mb)
        # M*v real chunk-ticks per device out of the scan's M*v + S - 1
        # total (util 18/19 here); the closed form itself is validated
        # against measured counters across v in TestBubbleUtilization
        np.testing.assert_array_equal(np.asarray(work), np.full(S, M * v))


class TestPipelineMemory:
    """Substantiate the 1F1B-memory-equivalence claim (schedules.py docstring):
    with stage remat the pipeline's temp memory must be well below the
    no-remat (GPipe-like) schedule's."""

    def test_remat_bounds_pipeline_temp_memory(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        plist = make_stage_params(jr.fold_in(K, 30), 4)
        stacked = stack_params(plist)
        M = 16
        mbs = jr.normal(jr.fold_in(K, 31), (M, 4, HID))
        tgts = jr.normal(jr.fold_in(K, 32), (M, 4, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def make(remat):
            def run(p, m, t):
                def full_loss(local):
                    outs = schedules.pipeline_spmd_forward(
                        stage_fn, local, m, remat=remat)
                    return jnp.mean(jax.vmap(loss_head)(outs, t))
                loss, g = jax.value_and_grad(full_loss)(
                    jax.tree.map(lambda x: x[0], p))
                return loss, jax.tree.map(lambda x: x[None], g)

            return jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
                out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
            ))

        temps = {}
        for remat in (False, True):
            c = make(remat).lower(stacked, mbs, tgts).compile()
            temps[remat] = c.memory_analysis().temp_size_in_bytes
        # documented measurement: remat must cut temp memory substantially
        # (no-remat keeps every tick's residuals live)
        assert temps[True] < temps[False] * 0.7, temps


class TestBuildSchedule:
    """build_schedule glues the microbatch calculator to the schedule
    dispatcher (VERDICT r1 item 7's 'currently disconnected' fix)."""

    def test_picks_microbatches_and_schedule(self):
        fn, calc = schedules.build_schedule(
            global_batch_size=64, micro_batch_size=2, data_parallel_size=2,
            pipeline_model_parallel_size=4)
        assert calc.get() == 16
        assert fn is schedules.forward_backward_pipelining_without_interleaving

    def test_interleaved_partial(self):
        import functools

        fn, calc = schedules.build_schedule(
            global_batch_size=32, micro_batch_size=2, data_parallel_size=1,
            pipeline_model_parallel_size=4,
            virtual_pipeline_model_parallel_size=2)
        assert isinstance(fn, functools.partial)
        assert fn.keywords["virtual_chunks"] == 2
        assert calc.get() == 16

    def test_rejects_underfilled_pipeline(self):
        with pytest.raises(ValueError, match="cannot fill"):
            schedules.build_schedule(
                global_batch_size=8, micro_batch_size=4,
                data_parallel_size=1, pipeline_model_parallel_size=4)

    def test_interleaved_rejects_ragged_microbatch_count(self):
        """The group-of-S flow (and the reference's assert,
        fwd_bwd_pipelining_with_interleaving.py:87) needs M % pp == 0."""
        with pytest.raises(ValueError, match="divisible"):
            schedules.build_schedule(
                global_batch_size=12, micro_batch_size=2,
                data_parallel_size=1, pipeline_model_parallel_size=4,
                virtual_pipeline_model_parallel_size=2)

    def test_end_to_end_with_calculator(self):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)
        fn, calc = schedules.build_schedule(
            global_batch_size=8, micro_batch_size=2, data_parallel_size=1,
            pipeline_model_parallel_size=4)
        M = calc.get()
        plist = make_stage_params(jr.fold_in(K, 40), 4)
        stacked = stack_params(plist)
        mbs = jr.normal(jr.fold_in(K, 41), (M, 2, HID))
        tgts = jr.normal(jr.fold_in(K, 42), (M, 2, HID))

        def loss_head(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        def run(p, m, t):
            loss, g = fn(stage_fn, loss_head, jax.tree.map(lambda x: x[0], p), m, t)
            return loss, jax.tree.map(lambda x: x[None], g)

        loss, _ = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stacked), P(), P()),
            out_specs=(P(), jax.tree.map(lambda _: P("pp"), stacked)),
        )(stacked, mbs, tgts)
        assert np.isfinite(float(loss))


class TestBubbleUtilization:
    """EMPIRICAL bubble evidence (VERDICT r3 weak #4): per-device work
    counters through the real scanned schedule. Wall-clock on the
    single-core virtual mesh measures total work, not bubble — these
    counters measure exactly the quantity interleaving trades: the share
    of a device's tick slots holding REAL (in-flight) work."""

    def _measure(self, v, M=8, S=4):
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
        feat = 8
        mb = jr.normal(jr.PRNGKey(0), (M, 2, feat))
        # v chunks of identity-ish params; the aux contract counts ticks
        params = jnp.ones((v, 1, feat)) if v > 1 else jnp.ones((1, feat))

        def stage(p, x):
            return x * p[0], 1.0  # aux = one unit of real work

        def run(p, mb):
            out, work = schedules.pipeline_spmd_forward(
                stage, p, mb, virtual_chunks=v, remat=False,
                aux_init=0.0)
            return out, work[None]  # rank-1 so out_specs can concat per pp

        _, work = mesh_lib.shard_map(
            run, mesh=mesh, in_specs=(P(), P()),
            out_specs=(P(), P("pp")),
        )(params, mb)
        T = M * v + S - 1 if v > 1 else M + S - 1
        return np.asarray(work), T

    def test_per_device_work_counters_show_v2_bubble_shrink(self):
        M, S = 8, 4
        utils = {}
        for v in (1, 2, 3, 4):
            work, T = self._measure(v, M, S)
            # every device executes exactly its M*v real chunk-ticks —
            # the schedule wastes no slots beyond the theoretical fill
            # (odd v included: the item() arithmetic is modular, not
            # power-of-two)
            np.testing.assert_array_equal(work, np.full(S, M * v))
            utils[v] = M * v / T
        # closed form (M*v)/(M*v + S - 1): 0.727 / 0.842 / 0.889 / 0.914
        np.testing.assert_allclose(utils[1], 8 / 11)
        np.testing.assert_allclose(utils[2], 16 / 19)
        np.testing.assert_allclose(utils[3], 24 / 27)
        np.testing.assert_allclose(utils[4], 32 / 35)
        assert utils[2] > utils[1], "v=2 must shrink the bubble vs v=1"
        assert utils[4] > utils[3] > utils[2]
