"""apex_tpu.plan: the ParallelPlan object, CostDB-driven pricing, the
search loop, the `plan` record/CLI surface, and the consolidated
validation satellite (ISSUE 12).

Fixture CostDBs are hand-built (one bucket per key, zero spread) so
every pricing assertion is exact: determinism is bit-identical, and
the recovery tests pin which decomposition a given rate profile must
pick — the gate topology (dp2×tp2×pp2) under fast-tp/slow-hop rates
with tp capped by seq divisibility, and the 8-chip flagship (dp8, the
single-chip hand config replicated) under fast-dp rates.
"""

import dataclasses
import json
import os

import pytest

from apex_tpu.plan import (
    ParallelPlan,
    PlanError,
    Workload,
    enumerate_plans,
    estimate_memory,
    plan_record_fields,
    price_plan,
    search_plans,
)
from apex_tpu.plan import cost as plan_cost


def _stat(mean):
    return {"n": 8, "mean": mean, "min": mean, "max": mean,
            "spread_pct": 0.0}


def make_costdb(rates, gemm_rate=1e11):
    """One-bucket-per-key fixture CostDB (schema-valid)."""
    return {
        "schema": 1, "kind": "costdb",
        "collectives": {
            k: [{"bucket_bytes": 1 << 20, "bytes": _stat(1 << 20),
                 "bytes_per_s": _stat(r)}]
            for k, r in rates.items()},
        "gemms": {"flops_1": {"flops_per_s": _stat(gemm_rate)}},
    }


#: smoke workload for trace-backed pricing: seq=18 caps tp at 2 (18 % 4
#: != 0), the same way the flagship's head count caps tp on real chips
W = Workload(hidden_size=64, ffn_hidden_size=256, num_layers=8,
             vocab_size=512, seq=18, global_batch=16, micro_batch=2,
             dtype_bytes=4)

_TP_FAST = {"all_gather[tp]": 1e11, "psum_scatter[tp]": 1e11,
            "ppermute[tp]": 1e11, "psum[tp]": 1e11}


class TestParallelPlan:
    def test_roundtrip_exact(self):
        p = ParallelPlan(dp=2, tp=2, pp=2, sequence_parallel=True,
                         tp_overlap=True, pp_schedule="zb",
                         overlap_p2p=True, virtual_chunks=2, zero=True)
        assert ParallelPlan.from_json(p.to_json()) == p
        assert ParallelPlan.from_json(json.dumps(p.to_json())) == p
        # field-for-field, not just equality
        assert p.to_json() == ParallelPlan.from_json(
            p.to_json()).to_json()

    def test_from_json_rejects_unknown_fields(self):
        with pytest.raises(PlanError, match="unknown plan field"):
            ParallelPlan.from_json({"dp": 2, "banana": 1})

    @pytest.mark.parametrize("kwargs,needle", [
        (dict(tp_overlap=True), "tp_size >= 2"),
        (dict(pp_schedule="zbb"), "pp_schedule"),
        (dict(dp=3, ep=2), "must divide"),
        (dict(virtual_chunks=2), "pipeline_model_parallel_size >= 2"),
        (dict(sequence_parallel=True), "tp_size >= 2"),
        (dict(tp=2, cp=2, tp_overlap=True), "context"),
        (dict(tp=0), "tp=0"),
    ])
    def test_validation_names_knob(self, kwargs, needle):
        with pytest.raises(PlanError, match=needle):
            ParallelPlan(**kwargs)

    def test_validate_schedule_and_microbatches(self):
        with pytest.raises(PlanError, match="pipeline_model_parallel"):
            ParallelPlan(pp_schedule="zb").validate_schedule()
        with pytest.raises(PlanError, match="cannot fill"):
            ParallelPlan(pp=4).validate_microbatches(2)
        with pytest.raises(PlanError, match="divisible"):
            ParallelPlan(pp=2, virtual_chunks=2).validate_microbatches(3)
        ParallelPlan(pp=2, virtual_chunks=2).validate_microbatches(4)

    def test_world_size_and_describe(self):
        p = ParallelPlan(dp=2, tp=2, pp=2, ep=2, pp_schedule="zb")
        assert p.world_size == 8  # ep rides inside dp
        assert p.describe() == "dp2·tp2·pp2·ep2 zb"

    def test_frozen(self):
        with pytest.raises(dataclasses.FrozenInstanceError):
            ParallelPlan().dp = 2


class TestConsolidatedValidation:
    """The satellite: the same illegal combo is rejected with the same
    message whichever door it walks through."""

    def test_ep_divisibility_same_message_via_mesh(self):
        from apex_tpu.parallel import mesh as mesh_lib

        with pytest.raises(PlanError) as direct:
            ParallelPlan(dp=3, ep=2)
        with pytest.raises(ValueError) as via_spec:
            mesh_lib.MeshSpec(data_parallel_size=3,
                              expert_parallel_size=2)
        assert str(direct.value) == str(via_spec.value)

    def test_gpt_config_routes_through_plan(self):
        from apex_tpu.models import GPTConfig

        with pytest.raises(ValueError) as via_cfg:
            GPTConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                      num_layers=2, num_heads=4, pp_schedule="zbb")
        with pytest.raises(PlanError) as direct:
            ParallelPlan(pp_schedule="zbb")
        assert str(direct.value) == str(via_cfg.value)

    def test_build_schedule_routes_through_plan(self):
        from apex_tpu.transformer.pipeline_parallel import schedules

        with pytest.raises(ValueError) as via_sched:
            schedules.build_schedule(
                global_batch_size=32, micro_batch_size=2,
                data_parallel_size=1, pipeline_model_parallel_size=1,
                schedule="zb")
        with pytest.raises(PlanError) as direct:
            ParallelPlan(pp_schedule="zb").validate_schedule()
        assert str(direct.value) == str(via_sched.value)

    def test_make_mesh_consumes_plan(self):
        import jax

        from apex_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh(plan=ParallelPlan(dp=2, tp=2, pp=2))
        assert mesh.shape == {"dp": 2, "pp": 2, "cp": 1, "tp": 2}
        # dp is authoritative: the device list is sliced to world_size
        mesh = mesh_lib.make_mesh(plan=ParallelPlan(dp=1, tp=2))
        assert mesh.devices.size == 2
        with pytest.raises(RuntimeError, match="spans"):
            mesh_lib.make_mesh(
                plan=ParallelPlan(dp=2, tp=2, pp=2, cp=2),
                devices=jax.devices()[:8])

    def test_make_mesh_rejects_contradicting_loose_axis(self):
        from apex_tpu.parallel import mesh as mesh_lib

        with pytest.raises(ValueError, match="contradicts plan"):
            mesh_lib.make_mesh(tensor_model_parallel_size=4,
                               plan=ParallelPlan(dp=2, tp=2, pp=2))
        # a loose size AGREEING with the plan is fine
        mesh_lib.make_mesh(tensor_model_parallel_size=2,
                           plan=ParallelPlan(dp=2, tp=2, pp=2))

    def test_shim_normalizes_historically_inert_knobs(self):
        # sequence_parallel at tp=1 was silently inert in GPTConfig;
        # the shim keeps that caller working while direct construction
        # stays strict (asserted above)
        p = ParallelPlan.from_model_kwargs(tp_size=1,
                                           sequence_parallel=True)
        assert p.sequence_parallel is False


class TestPlanConsumption:
    def test_gpt_config_derives_loose_knobs_from_plan(self):
        from apex_tpu.models import GPTConfig

        plan = ParallelPlan(tp=2, sequence_parallel=True,
                            pp_schedule="zb", overlap_p2p=True)
        cfg = GPTConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                        num_layers=2, num_heads=4, plan=plan)
        assert cfg.tp_size == 2 and cfg.sequence_parallel
        assert cfg.pp_schedule == "zb" and cfg.overlap_p2p
        assert cfg.plan == plan

    def test_gpt_config_shim_constructs_plan(self):
        from apex_tpu.models import GPTConfig

        cfg = GPTConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                        num_layers=2, num_heads=4, tp_size=2,
                        sequence_parallel=True)
        assert cfg.plan.tp == 2 and cfg.plan.sequence_parallel

    def test_gpt_config_rejects_contradicting_loose_kwarg(self):
        from apex_tpu.models import GPTConfig

        with pytest.raises(ValueError, match="contradicts plan"):
            GPTConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                      num_layers=2, num_heads=4, tp_size=4,
                      plan=ParallelPlan(tp=2))

    def test_t5_config_rejects_tp_plan(self):
        from apex_tpu.models import T5Config

        with pytest.raises(ValueError, match="GPTConfig"):
            T5Config(plan=ParallelPlan(tp=2))
        # an explicit loose tp_overlap=True never silently merges with
        # a plan that implies False
        with pytest.raises(ValueError, match="contradicts plan"):
            T5Config(plan=ParallelPlan(), tp_overlap=True)

    def test_initialize_model_parallel_rejects_contradicting_v(self):
        from apex_tpu.parallel import mesh as mesh_lib

        try:
            with pytest.raises(ValueError, match="contradicts plan"):
                mesh_lib.initialize_model_parallel(
                    plan=ParallelPlan(pp=2),
                    virtual_pipeline_model_parallel_size=4)
            mesh_lib.initialize_model_parallel(
                plan=ParallelPlan(pp=2, virtual_chunks=2))
            assert (mesh_lib.get_mesh_spec()
                    .virtual_pipeline_model_parallel_size == 2)
        finally:
            mesh_lib.destroy_model_parallel()

    def test_planned_config_grad_parity_vs_hand_config(self):
        """Acceptance: the searched plan's model is the SAME program as
        the hand-configured one — loss and grads bitwise equal at tp=2
        (veScale-style single-semantics guarantee, enforced by the
        existing per-knob parity oracles; this pins the plan door)."""
        import jax
        import jax.numpy as jnp
        import jax.random as jr
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.models.gpt import shard_params_for_tp
        from apex_tpu.parallel import mesh as mesh_lib

        kw = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
                  num_layers=2, num_heads=4, attention_impl="flash",
                  remat=False)
        plan = ParallelPlan(tp=2, sequence_parallel=True)
        cfg_hand = GPTConfig(**kw, tp_size=2, sequence_parallel=True)
        cfg_plan = GPTConfig(**kw, plan=plan)

        params1 = GPTModel(GPTConfig(**kw, tp_size=1)).init(jr.PRNGKey(0))
        sharded = shard_params_for_tp(params1, 2, GPTConfig(**kw))
        specs = jax.tree.map(lambda _: P("tp"), sharded)
        mesh = mesh_lib.make_mesh(plan=ParallelPlan(tp=2))
        toks = jr.randint(jr.PRNGKey(1), (2, 32), 0, 64)
        tgts = jr.randint(jr.PRNGKey(2), (2, 32), 0, 64)

        def run(cfg):
            model = GPTModel(cfg)

            def f(p, t, g):
                loss, grads = jax.value_and_grad(model.loss_fn)(
                    jax.tree.map(lambda x: x[0], p), t, g)
                return loss, jax.tree.map(lambda x: x[None], grads)

            step = jax.jit(mesh_lib.shard_map(
                f, mesh=mesh, in_specs=(specs, P(), P()),
                out_specs=(P(), specs)))
            return step(sharded, toks, tgts)

        loss_h, g_h = run(cfg_hand)
        loss_p, g_p = run(cfg_plan)
        assert float(loss_h) == float(loss_p)
        for a, b in zip(jax.tree.leaves(g_h), jax.tree.leaves(g_p)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestPricing:
    def _db(self, dp=1e9, pp=1e8, gemm=1e11):
        return make_costdb({"psum[dp]": dp, "ppermute[pp]": pp,
                            **_TP_FAST}, gemm)

    def test_deterministic_bit_identical(self):
        plan = ParallelPlan(dp=2, tp=2, pp=2, sequence_parallel=True,
                            pp_schedule="zb")
        db = self._db()
        a = price_plan(plan, W, db)
        plan_cost._STATIC_CACHE.clear()  # force a fresh trace
        b = price_plan(plan, W, db)
        assert a.predicted_step_ms == b.predicted_step_ms
        assert a.to_json() == b.to_json()

    def test_monotone_in_rates(self):
        """Doubling any rate never makes any plan slower."""
        plans = [ParallelPlan(dp=2, tp=2, pp=2, sequence_parallel=True,
                              pp_schedule="zb"),
                 ParallelPlan(dp=8),
                 ParallelPlan(dp=2, tp=1, pp=4, overlap_p2p=True)]
        base_db = self._db()
        base = [price_plan(p, W, base_db).predicted_step_ms
                for p in plans]
        for key in ("psum[dp]", "ppermute[pp]", "all_gather[tp]"):
            rates = {"psum[dp]": 1e9, "ppermute[pp]": 1e8, **_TP_FAST}
            rates[key] = rates[key] * 2
            faster = make_costdb(rates)
            for p, b in zip(plans, base):
                assert price_plan(p, W, faster).predicted_step_ms <= b
        for p, b in zip(plans, base):
            assert price_plan(p, W, self._db(gemm=2e11)
                              ).predicted_step_ms <= b

    def test_uncalibrated_keys_surface(self):
        plan = ParallelPlan(dp=2, tp=2, pp=2, sequence_parallel=True)
        db = make_costdb({"psum[dp]": 1e9})  # no tp/pp rows
        price = price_plan(plan, W, db, default_bytes_per_s=1e9)
        assert price.confidence == "partial"
        assert "ppermute[pp]" in price.uncalibrated
        assert any(k.startswith("all_gather[tp]")
                   for k in price.uncalibrated)
        full = price_plan(plan, W, self._db())
        assert full.confidence == "calibrated"
        assert full.uncalibrated == ()

    def test_schedule_is_a_priced_choice(self):
        """zb vs 1f1b and overlap vs blocking price differently from
        the same traced program — the cost-model term at work."""
        base = dict(dp=2, tp=1, pp=4)
        db = self._db()
        zb = price_plan(ParallelPlan(**base, pp_schedule="zb"), W, db)
        f1 = price_plan(ParallelPlan(**base, pp_schedule="1f1b"), W, db)
        assert zb.predicted_step_ms != f1.predicted_step_ms
        assert zb.schedule_factor < f1.schedule_factor  # remat=False
        ov = price_plan(ParallelPlan(**base, pp_schedule="zb",
                                     overlap_p2p=True), W, db)
        assert ov.pp_ms == zb.pp_ms  # same traced hop bytes
        # overlap hides the hop bytes but lengthens the drain
        assert ov.schedule_factor > zb.schedule_factor

    def test_ranking_row_reconciles_with_predicted(self):
        """gemm_ms·schedule_factor + collective_ms == predicted_step_ms
        for overlap and blocking plans alike (the record's decomposition
        must sum, or a consumer cannot trust either side)."""
        db = self._db()
        for plan in (ParallelPlan(dp=2, tp=1, pp=4, pp_schedule="zb",
                                  overlap_p2p=True),
                     ParallelPlan(dp=2, tp=2, pp=2,
                                  sequence_parallel=True)):
            row = price_plan(plan, W, db).to_json()
            lhs = (row["gemm_ms"] * row["schedule_factor"]
                   + row["collective_ms"])
            assert abs(lhs - row["predicted_step_ms"]) < 2e-3

    def test_memory_estimate_scales_with_plan(self):
        dense = estimate_memory(ParallelPlan(dp=2, tp=2, pp=2,
                                             sequence_parallel=True), W)
        zero = estimate_memory(
            ParallelPlan(dp=2, tp=2, pp=2, sequence_parallel=True,
                         zero=True), W)
        assert zero.optimizer == dense.optimizer // 2
        assert zero.params == dense.params
        wide = estimate_memory(ParallelPlan(dp=8), W)
        assert wide.params > dense.params  # unsharded model per chip

    def test_nondividing_layers_raise_never_truncate(self):
        """Pricing must reject (not silently shrink) a plan whose
        pp*v does not divide the layer stack — a truncated model's
        price is not comparable with anyone else's."""
        with pytest.raises(PlanError, match="num_layers"):
            price_plan(ParallelPlan(dp=1, tp=1, pp=5), W, self._db())
        with pytest.raises(PlanError, match="num_layers"):
            estimate_memory(ParallelPlan(pp=5), W)

    def test_conservative_defaults_floor_blind_spots(self):
        from apex_tpu.plan import conservative_defaults

        empty = {"schema": 1, "kind": "costdb", "collectives": {},
                 "gemms": {}}
        assert conservative_defaults(empty) == {
            "default_bytes_per_s": 1e10, "default_flops_per_s": 1e14}
        db = make_costdb({"psum[dp]": 5e8, "ppermute[pp]": 2e7},
                         gemm_rate=3e10)
        got = conservative_defaults(db)
        # blind spots price at the SLOWEST measured rate — a plan can
        # never win because its dominant traffic was unmeasured
        assert got == {"default_bytes_per_s": 2e7,
                       "default_flops_per_s": 3e10}

    def test_bucket_rule_shared_with_calibrate(self):
        """One bucket-matching rule: the planner's collective pricing
        and diff_static_cost pick the identical rate for the same
        payload."""
        from apex_tpu.prof.calibrate import nearest_bucket_rate

        rows = [{"bucket_bytes": 1 << b, "bytes": _stat(1 << b),
                 "bytes_per_s": _stat(float(b))} for b in (10, 16, 24)]
        assert nearest_bucket_rate(rows, 3000.0) == 10.0    # near 2^10?
        assert nearest_bucket_rate(rows, 100000.0) == 16.0
        assert nearest_bucket_rate(rows, 1 << 30) == 24.0
        assert nearest_bucket_rate([], 1024.0) is None

    def test_worked_example_matches_docs(self):
        """The docs/api/plan.md worked example is THIS fixture; drift
        between the doc's numbers and the pricer fails here."""
        plan = ParallelPlan(dp=2, tp=1, pp=1)
        db = make_costdb({"psum[dp]": 1e9}, gemm_rate=1e11)
        price = price_plan(plan, W, db)
        static = plan_cost.static_cost_for_plan(plan, W)
        psum_bytes = static["collectives"]["psum[dp]"]["bytes"]
        gemm_flops = static["total_gemm_flops"]
        expect = 1e3 * gemm_flops / 1e11 + 1e3 * psum_bytes / 1e9
        assert price.schedule_factor == 1.0
        assert abs(price.predicted_step_ms - expect) < 1e-9


class TestSearch:
    def test_recovers_flagship_dp8(self):
        """Generous memory + fast dp all-reduce: the 8-chip best is the
        hand config — the single-chip flagship replicated (dp8)."""
        db = make_costdb({"psum[dp]": 1e12, "ppermute[pp]": 1e8,
                          **{k: 1e8 for k in _TP_FAST}})
        res = search_plans(8, W, db, default_bytes_per_s=1e8,
                           default_flops_per_s=1e11)
        best = res.best.plan
        assert (best.dp, best.tp, best.pp) == (8, 1, 1)

    def test_recovers_gate_topology_dp2_tp2_pp2(self):
        """Fast tp ICI, slow pp hops, medium dp, tp capped at 2 by seq
        divisibility: the 8-chip best decomposition is the multichip
        gate's hand config dp2×tp2×pp2."""
        db = make_costdb({"psum[dp]": 5e8, "ppermute[pp]": 5e7,
                          **_TP_FAST}, gemm_rate=2.2e10)
        res = search_plans(8, W, db, default_bytes_per_s=1e8,
                           default_flops_per_s=2.2e10)
        best = res.best.plan
        assert (best.dp, best.tp, best.pp) == (2, 2, 2)
        # tp4 was structurally rejected (seq=18), surfaced with reason
        assert any("tp=4" in d or "tp4" in d for d, _ in res.rejected)

    def test_heterogeneity_repricess_dp_placement(self):
        """AMP's heterogeneity term: slow dp-axis CostDB entries (DCN)
        push the winner away from dp-heavy placement."""
        fast_dp = make_costdb({"psum[dp]": 1e12, "ppermute[pp]": 1e8,
                               **{k: 1e8 for k in _TP_FAST}})
        slow_dp = make_costdb({"psum[dp]": 1e8, "ppermute[pp]": 1e8,
                               **{k: 1e8 for k in _TP_FAST}})
        kw = dict(default_bytes_per_s=1e8, default_flops_per_s=1e11)
        assert search_plans(8, W, fast_dp, **kw).best.plan.dp == 8
        assert search_plans(8, W, slow_dp, **kw).best.plan.dp < 8

    def test_memory_bound_rejects_with_reason(self):
        db = make_costdb({"psum[dp]": 1e12, "ppermute[pp]": 1e8,
                          **{k: 1e8 for k in _TP_FAST}})
        unbounded = search_plans(8, W, db, default_bytes_per_s=1e8,
                                 default_flops_per_s=1e11)
        bound = unbounded.best.price.memory.total - 1
        res = search_plans(8, W, db, memory_bound_bytes=bound,
                           default_bytes_per_s=1e8,
                           default_flops_per_s=1e11)
        assert res.best.plan != unbounded.best.plan
        assert any("exceeds the bound" in r for _, r in res.rejected)

    def test_lattice_rejections_carry_reasons(self):
        plans, rejected = enumerate_plans(8, W)
        assert plans
        # every rejection is (description, reason) — nothing silent
        assert all(d and r for d, r in rejected)

    def test_plan_record_fields_skip_half_is_explicit(self):
        db = make_costdb({"psum[dp]": 1e12}, gemm_rate=1e11)
        res = search_plans(4, W, db, default_bytes_per_s=1e9,
                           default_flops_per_s=1e11)
        fields = plan_record_fields(res, costdb_source="fixture",
                                    skip_reason="off-TPU test")
        assert fields["measured_step_ms"] == ("skipped", "off-TPU test")
        measured = plan_record_fields(res, costdb_source="fixture",
                                      measured_step_ms=2.0)
        assert isinstance(
            measured["predicted_vs_measured_err_pct"], float)


class TestPlannedEntrypoint:
    def test_registered_and_clean_by_default(self):
        from apex_tpu.lint import entrypoints as eps

        assert "planned_gpt_step" in eps.names()
        findings, cost = eps.check("planned_gpt_step")
        assert findings == []
        assert "ppermute[pp]" in cost["collectives"]  # gate default pp2

    def test_env_plan_switches_traced_program(self, monkeypatch):
        from apex_tpu.lint import entrypoints as eps

        plan = ParallelPlan(tp=4, tp_overlap=True,
                            sequence_parallel=True)
        monkeypatch.setenv("APEX_TPU_PLAN", json.dumps(plan.to_json()))
        findings, cost = eps.check("planned_gpt_step")
        assert findings == []
        assert "ppermute[tp]" in cost["collectives"]
        assert not any(k.startswith("all_gather[tp]")
                       for k in cost["collectives"])

    def test_combined_tp_pp_plan_composes_both_contract_families(
            self, monkeypatch):
        """A dp2·tp2·pp2 tp_overlap pick is checked against BOTH the
        schedule witnesses and the ring-overlap invariants in one
        traced program — the gate is never vacuous for either family."""
        from apex_tpu.lint import entrypoints as eps

        plan = ParallelPlan(dp=2, tp=2, pp=2, sequence_parallel=True,
                            tp_overlap=True, pp_schedule="zb")
        monkeypatch.setenv("APEX_TPU_PLAN", json.dumps(plan.to_json()))
        codes = {c.code for c in eps.get("planned_gpt_step").contracts()}
        assert {"JXP401", "JXP402", "JXP403", "JXP201"} <= codes
        findings, cost = eps.check("planned_gpt_step")
        assert findings == []
        keys = set(cost["collectives"])
        assert "ppermute[pp]" in keys and "ppermute[tp]" in keys

    def test_bad_env_plan_fails_loudly(self, monkeypatch):
        from apex_tpu.lint import entrypoints as eps

        monkeypatch.setenv("APEX_TPU_PLAN", '{"tp": 0}')
        with pytest.raises(PlanError):
            eps.check("planned_gpt_step")


class TestPlanRecord:
    def _fields(self):
        db = make_costdb({"psum[dp]": 1e12}, gemm_rate=1e11)
        res = search_plans(4, W, db, default_bytes_per_s=1e9,
                           default_flops_per_s=1e11)
        return plan_record_fields(res, costdb_source="fixture",
                                  measured_step_ms=2.0)

    def test_emit_validates_ok_record(self):
        from apex_tpu import monitor

        record = monitor.MetricsRegistry().emit_plan(
            "OK", **self._fields(), backend="cpu")
        assert monitor.validate(record) == []
        assert record["kind"] == "plan"

    def test_skip_requires_reason(self):
        from apex_tpu import monitor

        with pytest.raises(ValueError, match="reason"):
            monitor.MetricsRegistry().emit_plan("SKIP", **self._fields())

    def test_nan_inside_ok_fails(self):
        from apex_tpu import monitor

        fields = self._fields()
        fields["predicted_step_ms"] = float("nan")
        with pytest.raises(ValueError, match="non-finite"):
            monitor.MetricsRegistry().emit_plan("OK", **fields,
                                                backend="cpu")

    def test_junk_ranking_key_fails_validation(self):
        from apex_tpu import monitor

        record = monitor.MetricsRegistry().emit_plan(
            "OK", **self._fields(), backend="cpu")
        record["ranking"][0]["vibes"] = 11
        assert any("vibes" in e for e in monitor.validate(record))
        del record["ranking"][0]["vibes"]
        record["chosen"]["banana"] = 1
        assert any("banana" in e for e in monitor.validate(record))

    def test_wrong_kind_fails(self):
        from apex_tpu import monitor
        from apex_tpu.monitor import schema

        record = monitor.MetricsRegistry().emit_plan(
            "OK", **self._fields(), backend="cpu")
        record["kind"] = "decode"
        assert schema.validate(record, schema.PLAN_SCHEMA)

    def test_report_renders_plan_line(self):
        from apex_tpu import monitor
        from apex_tpu.monitor import report

        record = monitor.MetricsRegistry().emit_plan(
            "OK", **self._fields(), backend="cpu")
        summary = report.aggregate([record])
        assert summary["plan"]["predicted_vs_measured_err_pct"] == \
            record["predicted_vs_measured_err_pct"]
        text = report.render(summary)
        assert "plan" in text and "chose" in text and "err" in text


class TestPlanCLIs:
    def _record(self, tmp_path, status="OK", err=1.5, hbm_err=None):
        db = make_costdb({"psum[dp]": 1e12}, gemm_rate=1e11)
        res = search_plans(4, W, db, default_bytes_per_s=1e9,
                           default_flops_per_s=1e11)
        from apex_tpu import monitor

        if status == "OK":
            fields = plan_record_fields(res, costdb_source="fixture",
                                        measured_step_ms=2.0)
            fields["predicted_vs_measured_err_pct"] = err
        else:
            fields = plan_record_fields(res, costdb_source="fixture",
                                        skip_reason="off-TPU test")
            fields["reason"] = "off-TPU test"
        if hbm_err is not None:
            # the apexmem fields bench.py --plan adds on a measured run
            fields["predicted_peak_hbm_mb"] = 100.0
            fields["measured_peak_hbm_mb"] = 100.0 * (1 + hbm_err / 100)
            fields["predicted_vs_measured_hbm_err_pct"] = hbm_err
        record = monitor.MetricsRegistry().emit_plan(
            status, **fields, backend="cpu")
        path = tmp_path / f"plan_{status}_{err}_{hbm_err}.json"
        path.write_text(json.dumps(record))
        return str(path)

    def test_validate_metrics_plan_forced_dispatch(self, tmp_path,
                                                   capsys):
        import tools.validate_metrics as vm

        good = self._record(tmp_path)
        assert vm.main(["--plan", good]) == 0
        wrong = tmp_path / "decode.json"
        wrong.write_text(json.dumps({"kind": "decode", "schema": 1,
                                     "status": "SKIP", "reason": "x"}))
        assert vm.main(["--plan", str(wrong)]) == 1
        err = capsys.readouterr().err
        assert "expected a 'plan' artifact" in err

    def test_bench_history_gates_error_drift(self, tmp_path, capsys):
        import tools.bench_history as bh

        history = self._record(tmp_path, err=1.0)
        hist_dir = tmp_path
        os.rename(history, str(hist_dir / "BENCH_r90.json"))
        # fresh error within allowance: OK
        fresh_ok = self._record(tmp_path, err=2.0)
        assert bh.main([fresh_ok, "--root", str(hist_dir),
                        "--history", "BENCH_r9*.json"]) == 0
        assert "OK plan_predicted_vs_measured_err_pct" in \
            capsys.readouterr().out
        # fresh error drifted up beyond tolerance: REGRESSION
        fresh_bad = self._record(tmp_path, err=9.0)
        assert bh.main([fresh_bad, "--root", str(hist_dir),
                        "--history", "BENCH_r9*.json"]) == 1
        assert "REGRESSION" in capsys.readouterr().out
        # SKIP record claims nothing
        skip = self._record(tmp_path, status="SKIP")
        assert bh.main([skip, "--root", str(hist_dir),
                        "--history", "BENCH_r9*.json"]) == 0

    def test_bench_history_gates_hbm_err_drift(self, tmp_path, capsys):
        """The apexmem memory-honesty series rides the same trajectory
        gate as the step-time error — and a history artifact that
        predates it (no hbm field) skips ONLY the new series, never the
        whole gate."""
        import tools.bench_history as bh

        old_history = self._record(tmp_path, err=1.0)  # pre-apexmem
        os.rename(old_history, str(tmp_path / "BENCH_r90.json"))
        fresh = self._record(tmp_path, err=1.5, hbm_err=3.0)
        assert bh.main([fresh, "--root", str(tmp_path),
                        "--history", "BENCH_r9*.json"]) == 0
        out = capsys.readouterr().out
        assert "OK plan_predicted_vs_measured_err_pct" in out
        assert ("SKIP: no history artifact carries metric "
                "'plan_predicted_vs_measured_hbm_err_pct'") in out
        # once the trajectory carries the series, drift gates it
        with_hbm = self._record(tmp_path, err=1.0, hbm_err=1.0)
        os.rename(with_hbm, str(tmp_path / "BENCH_r91.json"))
        ok = self._record(tmp_path, err=1.5, hbm_err=2.0)
        assert bh.main([ok, "--root", str(tmp_path),
                        "--history", "BENCH_r9*.json"]) == 0
        assert "OK plan_predicted_vs_measured_hbm_err_pct" in \
            capsys.readouterr().out
        bad = self._record(tmp_path, err=1.5, hbm_err=9.0)
        assert bh.main([bad, "--root", str(tmp_path),
                        "--history", "BENCH_r9*.json"]) == 1
        assert ("REGRESSION plan_predicted_vs_measured_hbm_err_pct"
                in capsys.readouterr().out)

    def test_lint_strict_gates_uncalibrated(self, tmp_path, capsys):
        from apex_tpu.lint.__main__ import main as lint_main

        empty_db = tmp_path / "empty_costdb.json"
        empty_db.write_text(json.dumps(
            {"schema": 1, "kind": "costdb", "collectives": {},
             "gemms": {}}))
        rc = lint_main(["--jaxpr", "--entrypoint", "planned_gpt_step",
                        "--costdb", str(empty_db), "--strict",
                        "--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["uncalibrated"]["planned_gpt_step"]
        # a fully covered costdb passes --strict
        from apex_tpu.lint import entrypoints as eps
        _, cost = eps.check("planned_gpt_step")
        full = make_costdb(
            {k: 1e9 for k in cost["collectives"]})
        full["gemms"] = {k: {"flops_per_s": _stat(1e11)}
                         for k in cost["gemms"]}
        full_path = tmp_path / "full_costdb.json"
        full_path.write_text(json.dumps(full))
        rc = lint_main(["--jaxpr", "--entrypoint", "planned_gpt_step",
                        "--costdb", str(full_path), "--strict",
                        "--format", "json"])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert report["uncalibrated"] == {}
        # --strict without --costdb is a usage error
        assert lint_main(["--jaxpr", "--strict"]) == 2


class TestLivenessMemorySource:
    """apexmem as the planner's memory model: the donation-aware
    liveness bound of the TRACED per-chip step vs the hand closed form
    — agreement pinned on the flagship plans, the one legitimate
    schedule-knowledge disagreement documented, and the bound as a
    search-pruning predicate."""

    #: the stash-heavy geometry: 32 microbatches at pp=2 make the
    #: schedule-agnostic trace's every-tick stash dominate
    W_STASHY = Workload(vocab_size=4096, global_batch=128, micro_batch=4)

    def test_closed_form_agrees_on_flagship_plans(self):
        """The two models were reconciled term by term (the vocab-head
        logits were the closed form's big gap); on the flagship plans
        they now agree within 10% — a regression in either model breaks
        this pin."""
        from apex_tpu.plan import liveness_memory

        w = Workload()
        for plan in (ParallelPlan(dp=8),
                     ParallelPlan(dp=2, tp=2, pp=2,
                                  sequence_parallel=True,
                                  pp_schedule="zb"),
                     ParallelPlan(dp=1, tp=4, pp=2,
                                  sequence_parallel=True,
                                  pp_schedule="zb")):
            cf = estimate_memory(plan, w).total
            lv = liveness_memory(plan, w).total
            gap = 100.0 * abs(lv - cf) / cf
            assert gap < 10.0, (plan.describe(), gap)
            assert liveness_memory(plan, w).source == "liveness"

    def test_documented_1f1b_disagreement_flags_not_hides(self):
        """The ONE known legitimate disagreement: the traced program is
        schedule-AGNOSTIC (one grad over the full tick scan stashes
        every tick's input — zb-like geometry), while 1f1b's closed
        form knows only a pp-deep window of stashes is ever live. At 32
        microbatches the gap is ~33% — and the honesty contract is that
        it SURFACES as an uncalibrated flag + partial confidence, never
        silently."""
        price = price_plan(
            ParallelPlan(dp=1, pp=2, pp_schedule="1f1b"), self.W_STASHY,
            {}, default_bytes_per_s=1e9, default_flops_per_s=1e11,
            memory_source="liveness")
        assert price.memory.source == "liveness"
        assert price.memory_disagreement_pct > 25.0
        flags = [u for u in price.uncalibrated if "memory_model" in u]
        assert flags and "closed_form_vs_liveness" in flags[0]
        assert price.confidence == "partial"
        # the zb schedule matches the trace's geometry: no flag
        zb = price_plan(
            ParallelPlan(dp=1, pp=2, pp_schedule="zb"), self.W_STASHY,
            {}, default_bytes_per_s=1e9, default_flops_per_s=1e11,
            memory_source="liveness")
        assert zb.memory_disagreement_pct < 10.0
        assert not [u for u in zb.uncalibrated if "memory_model" in u]

    def test_liveness_rejects_previously_accepted_candidates(self):
        """The pruning acceptance: with the bound midway between the
        closed form and the liveness peak, closed-form search ACCEPTS
        the 1f1b candidates whose real stash geometry does not fit —
        liveness search rejects them, quoting both numbers."""
        from apex_tpu.plan import liveness_memory

        plan = ParallelPlan(dp=1, pp=2, pp_schedule="1f1b")
        cf = estimate_memory(plan, self.W_STASHY).total
        lv = liveness_memory(plan, self.W_STASHY).total
        assert lv > cf
        bound = (cf + lv) // 2
        kw = dict(memory_bound_bytes=bound, default_bytes_per_s=1e9,
                  default_flops_per_s=1e11)
        accepted_cf = {c.plan.describe() for c in
                       search_plans(2, self.W_STASHY, {}, **kw).ranked}
        res = search_plans(2, self.W_STASHY, {}, **kw,
                           memory_source="liveness")
        accepted_lv = {c.plan.describe() for c in res.ranked}
        newly_rejected = accepted_cf - accepted_lv
        assert plan.describe() in newly_rejected
        reasons = [r for d, r in res.rejected if d in newly_rejected]
        assert reasons
        assert all("liveness per-chip peak" in r
                   and "closed form said" in r for r in reasons)
        # survivors' memory column comes from the liveness analysis
        assert all(c.price.memory.source == "liveness"
                   for c in res.ranked)

    def test_memory_source_validated(self):
        with pytest.raises(PlanError, match="memory_source"):
            price_plan(ParallelPlan(dp=2), W, {},
                       default_bytes_per_s=1e9,
                       default_flops_per_s=1e11, memory_source="vibes")

    def test_record_fields_carry_memory_source(self):
        res = search_plans(2, self.W_STASHY, {},
                           default_bytes_per_s=1e9,
                           default_flops_per_s=1e11,
                           memory_source="liveness")
        fields = plan_record_fields(res, costdb_source="fixture",
                                    skip_reason="off-TPU test")
        assert fields["memory_source"] == "liveness"
        assert any("memory_disagreement_pct" in row
                   for row in fields["ranking"])

    def test_hbm_nan_inside_ok_fails(self):
        from apex_tpu import monitor

        db = make_costdb({"psum[dp]": 1e12}, gemm_rate=1e11)
        res = search_plans(4, W, db, default_bytes_per_s=1e9,
                           default_flops_per_s=1e11)
        fields = plan_record_fields(res, costdb_source="fixture",
                                    measured_step_ms=2.0)
        fields["predicted_vs_measured_hbm_err_pct"] = float("nan")
        with pytest.raises(ValueError, match="non-finite"):
            monitor.MetricsRegistry().emit_plan("OK", **fields,
                                                backend="cpu")

    def test_hbm_reasonless_skip_fails_validation(self):
        from apex_tpu import monitor

        db = make_costdb({"psum[dp]": 1e12}, gemm_rate=1e11)
        res = search_plans(4, W, db, default_bytes_per_s=1e9,
                           default_flops_per_s=1e11)
        fields = plan_record_fields(res, costdb_source="fixture",
                                    measured_step_ms=2.0)
        fields["predicted_vs_measured_hbm_err_pct"] = 1.0
        record = monitor.MetricsRegistry().emit_plan("OK", **fields,
                                                     backend="cpu")
        assert monitor.validate(record) == []
        record["predicted_vs_measured_hbm_err_pct"] = {"skipped": True}
        errors = monitor.validate(record)
        assert any("predicted_vs_measured_hbm_err_pct" in e
                   for e in errors), errors
