"""T5-class encoder-decoder model + its split-rank pipeline.

The reference carries encoder-decoder plumbing (ModelType, split rank)
but no model to drive it; this tests the seq2seq flagship standalone and
THROUGH the two-segment pipeline (the GPTPipeline depth standard applied
to the enc-dec schedule).
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import EncDecPipeline, EncoderDecoderModel, T5Config
from apex_tpu.parallel import mesh as mesh_lib

K = jr.PRNGKey(91)

SMALL = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
             num_encoder_layers=2, num_decoder_layers=2, num_heads=4)


def _data(key, M, b, s, vocab=64):
    enc = jr.randint(key, (M, b, s), 0, vocab)
    dec = jr.randint(jr.fold_in(key, 1), (M, b, s), 0, vocab)
    tgt = jr.randint(jr.fold_in(key, 2), (M, b, s), 0, vocab)
    return enc, dec, tgt


class TestEncoderDecoderModel:
    def test_loss_finite_and_deterministic(self):
        m = EncoderDecoderModel(T5Config(**SMALL))
        p = m.init(K)
        enc, dec, tgt = _data(jr.fold_in(K, 1), 1, 2, 16)
        l1 = m.loss_fn(p, enc[0], dec[0], tgt[0])
        l2 = m.loss_fn(p, enc[0], dec[0], tgt[0])
        assert jnp.isfinite(l1) and l1 == l2

    def test_flash_matches_softmax_impl(self):
        cfg_s = T5Config(**SMALL)
        cfg_f = T5Config(**SMALL, attention_impl="flash")
        m_s, m_f = EncoderDecoderModel(cfg_s), EncoderDecoderModel(cfg_f)
        p = m_s.init(K)
        enc, dec, tgt = _data(jr.fold_in(K, 2), 1, 2, 16)
        with jax.default_matmul_precision("highest"):
            np.testing.assert_allclose(
                float(m_s.loss_fn(p, enc[0], dec[0], tgt[0])),
                float(m_f.loss_fn(p, enc[0], dec[0], tgt[0])),
                rtol=2e-5)

    def test_decoder_is_causal(self):
        """Future decoder tokens must not affect earlier positions."""
        m = EncoderDecoderModel(T5Config(**SMALL))
        p = m.init(K)
        enc, dec, _ = _data(jr.fold_in(K, 3), 1, 1, 16)
        lg1 = m.logits(p, enc[0], dec[0])
        dec2 = dec[0].at[0, -1].set((dec[0][0, -1] + 1) % 64)
        lg2 = m.logits(p, enc[0], dec2)
        np.testing.assert_allclose(lg1[:, :-1], lg2[:, :-1],
                                   rtol=1e-5, atol=1e-6)

    def test_cross_attention_sees_encoder(self):
        """Changing the encoder input must change the decoder output."""
        m = EncoderDecoderModel(T5Config(**SMALL))
        p = m.init(K)
        enc, dec, _ = _data(jr.fold_in(K, 4), 1, 1, 16)
        lg1 = m.logits(p, enc[0], dec[0])
        lg2 = m.logits(p, (enc[0] + 1) % 64, dec[0])
        assert float(jnp.max(jnp.abs(lg1 - lg2))) > 1e-3

    def test_trains(self):
        import optax

        m = EncoderDecoderModel(T5Config(**SMALL))
        p = m.init(K)
        opt = optax.adam(3e-3)
        st = opt.init(p)
        enc, dec, _ = _data(jr.fold_in(K, 5), 1, 4, 16, vocab=16)
        tgt = (enc + 3) % 16  # copy-ish task through the cross attention

        @jax.jit
        def step(p, st):
            loss, g = jax.value_and_grad(m.loss_fn)(
                p, enc[0], dec[0], tgt[0])
            u, st = opt.update(g, st, p)
            return optax.apply_updates(p, u), st, loss

        losses = []
        for _ in range(25):
            p, st, loss = step(p, st)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::6]


class TestEncDecPipelineModel:
    def test_partition_shapes_and_validation(self):
        m = EncoderDecoderModel(T5Config(**{**SMALL,
                                            "num_encoder_layers": 4,
                                            "num_decoder_layers": 2}))
        pipe = EncDecPipeline(m, pp=4, split=2)
        part = pipe.partition(m.init(K))
        # enc leaves: (pp=4, 2 layers/stage, ...); dec: (4, 1, ...)
        assert part["stages"]["enc"]["qkv"].shape[:2] == (4, 2)
        assert part["stages"]["dec"]["qkv"].shape[:2] == (4, 1)
        with pytest.raises(ValueError, match="split"):
            EncDecPipeline(m, pp=4, split=0)
        with pytest.raises(ValueError, match="divide"):
            EncDecPipeline(m, pp=4, split=3)

    @pytest.mark.parametrize("split", [1, 2])
    def test_pipeline_matches_serial(self, split):
        """The REAL seq2seq model through the two-segment pipeline: loss
        and embed/head grads equal the unpipelined model's."""
        cfg = T5Config(**{**SMALL, "num_encoder_layers": split * 2,
                          "num_decoder_layers": (4 - split) * 2})
        m = EncoderDecoderModel(cfg)
        params = m.init(jr.fold_in(K, 6))
        pipe = EncDecPipeline(m, pp=4, split=split)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        M, b, s = 4, 2, 16
        enc, dec, tgt = _data(jr.fold_in(K, 7), M, b, s)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)

        def run(p, e, d2, t):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, g = pipe.loss_and_grads(lp, e, d2, t)
            g["stages"] = jax.tree.map(lambda x: x[None], g["stages"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P(), P()),
                out_specs=(P(), specs),
            ))(part, enc, dec, tgt)

            def serial(p):
                return m.loss_fn(p, enc.reshape(M * b, s),
                                 dec.reshape(M * b, s),
                                 tgt.reshape(M * b, s))

            ref_loss, ref_g = jax.value_and_grad(serial)(params)

        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            grads["embed"]["embedding"], ref_g["embedding"],
            rtol=3e-4, atol=1e-5)
        np.testing.assert_allclose(
            grads["embed"]["ln_enc_w"], ref_g["ln_enc_w"],
            rtol=3e-4, atol=1e-5)
        np.testing.assert_allclose(
            grads["head"]["ln_dec_w"], ref_g["ln_dec_w"],
            rtol=3e-4, atol=1e-5)
        # stage grads: encoder stage 0's slice vs serial encoder layers
        ne = pipe.enc_per_stage
        np.testing.assert_allclose(
            grads["stages"]["enc"]["qkv"][0],
            ref_g["encoder"]["qkv"][:ne], rtol=3e-4, atol=1e-5)
        # decoder last stage's slice vs serial decoder tail
        nd = pipe.dec_per_stage
        np.testing.assert_allclose(
            grads["stages"]["dec"]["qkv"][3],
            ref_g["decoder"]["qkv"][-nd:], rtol=3e-4, atol=1e-5)


class TestRelativePositionBias:
    """T5's relative position bias (VERDICT r3 missing #3: 'add the bias
    or stop calling it T5-class')."""

    def test_bucketing_properties(self):
        from apex_tpu.models.t5 import relative_position_bucket

        rel = jnp.arange(-64, 65)
        # bidirectional: sign split, small offsets exact, bounded buckets
        bi = relative_position_bucket(rel, bidirectional=True,
                                      num_buckets=32, max_distance=64)
        assert int(bi.min()) >= 0 and int(bi.max()) < 32
        assert int(bi[64]) == 0  # rel 0
        np.testing.assert_array_equal(
            bi[64 - 7:64], jnp.arange(7, 0, -1))  # exact small negatives
        # causal: future (key after query, rel > 0 -> n < 0) clamps to 0
        ca = relative_position_bucket(rel, bidirectional=False,
                                      num_buckets=32, max_distance=64)
        assert int(ca[64:].max()) == 0  # all future positions -> bucket 0
        assert int(ca.max()) < 32
        # distances beyond max_distance saturate at the last bucket
        far = relative_position_bucket(jnp.array([-500]),
                                       bidirectional=False,
                                       num_buckets=32, max_distance=64)
        assert int(far[0]) == 31

    def test_relative_model_trains_and_bias_matters(self):
        import optax

        cfg = T5Config(**SMALL, position_encoding="relative")
        m = EncoderDecoderModel(cfg)
        p = m.init(K)
        assert "pos_embedding" not in p
        assert p["rel_bias_enc"].shape == (32, SMALL["num_heads"])
        enc, dec, tgt = _data(jr.fold_in(K, 30), 1, 4, 32)
        enc, dec, tgt = enc[0], dec[0], tgt[0]

        loss, g = jax.value_and_grad(m.loss_fn)(p, enc, dec, tgt)
        assert jnp.isfinite(loss)
        # positions only enter via the bias: its grads must be nonzero
        assert float(jnp.abs(g["rel_bias_enc"]).sum()) > 0
        assert float(jnp.abs(g["rel_bias_dec"]).sum()) > 0

        # zeroing the bias changes the loss (the bias is live, not deco)
        p0 = dict(p, rel_bias_enc=jnp.zeros_like(p["rel_bias_enc"]),
                  rel_bias_dec=jnp.zeros_like(p["rel_bias_dec"]))
        assert float(m.loss_fn(p0, enc, dec, tgt)) != float(loss)

        opt = optax.adam(3e-3)
        st = opt.init(p)

        @jax.jit
        def step(p, st):
            loss, g = jax.value_and_grad(m.loss_fn)(
                p, enc, dec, (dec + 1) % 64)
            u, st = opt.update(g, st, p)
            return optax.apply_updates(p, u), st, loss

        losses = [float(step(p, st)[2])]
        for _ in range(10):
            p, st, loss = step(p, st)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.9

    def test_relative_decoder_ignores_future(self):
        """Causal + relative: changing future decoder tokens must not
        change earlier positions' logits."""
        cfg = T5Config(**SMALL, position_encoding="relative")
        m = EncoderDecoderModel(cfg)
        p = m.init(K)
        enc = jr.randint(jr.fold_in(K, 31), (2, 32), 0, 64)
        dec = jr.randint(jr.fold_in(K, 32), (2, 32), 0, 64)
        dec2 = dec.at[:, 20:].set((dec[:, 20:] + 3) % 64)
        l1 = m.logits(p, enc, dec)
        l2 = m.logits(p, enc, dec2)
        np.testing.assert_allclose(l1[:, :20], l2[:, :20], atol=1e-5)

    def test_relative_flash_matches_softmax(self):
        """Relative bias ON the flash path (VERDICT r4 next #1): the
        (h, s, s) bias feeds the kernels' in-kernel bias operand, dbias
        flows back through the bucket gather — loss and every gradient
        (incl. both bucket tables) must match the materialized-softmax
        composition."""
        p = EncoderDecoderModel(
            T5Config(**SMALL, position_encoding="relative")).init(K)
        enc, dec, tgt = _data(jr.fold_in(K, 36), 1, 2, 32)
        enc, dec, tgt = enc[0], dec[0], tgt[0]
        models = {
            impl: EncoderDecoderModel(
                T5Config(**SMALL, position_encoding="relative",
                         attention_impl=impl))
            for impl in ("softmax", "flash")}
        with jax.default_matmul_precision("highest"):
            l_soft, g_soft = jax.jit(jax.value_and_grad(
                models["softmax"].loss_fn))(p, enc, dec, tgt)
            l_flash, g_flash = jax.jit(jax.value_and_grad(
                models["flash"].loss_fn))(p, enc, dec, tgt)
        np.testing.assert_allclose(float(l_soft), float(l_flash),
                                   rtol=1e-5)
        jax.tree_util.tree_map_with_path(
            lambda path, a, b: np.testing.assert_allclose(
                a, b, rtol=3e-3, atol=3e-4, err_msg=str(path)),
            g_soft, g_flash)
        # the bias is live on the flash path too
        assert float(jnp.abs(g_flash["rel_bias_enc"]).sum()) > 0
        assert float(jnp.abs(g_flash["rel_bias_dec"]).sum()) > 0

    def test_relative_through_pipeline_matches_serial(self):
        """The split-rank pipeline with relative bias: the per-stack
        tables ride the replicated embed group; loss == serial."""
        cfg = T5Config(**SMALL, position_encoding="relative")
        m = EncoderDecoderModel(cfg)
        params = m.init(K)
        pipe = EncDecPipeline(m, pp=2, split=1)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        M, b, s = 2, 2, 32
        enc, dec, tgt = _data(jr.fold_in(K, 33), M, b, s)

        def run(p, e, d, t):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, g = pipe.loss_and_grads(lp, e, d, t)
            g["stages"] = jax.tree.map(lambda x: x[None], g["stages"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P(), P()),
                out_specs=(P(), specs),
            ))(part, enc, dec, tgt)
            ref = jnp.mean(jnp.stack([
                m.loss_fn(params, enc[i], dec[i], tgt[i])
                for i in range(M)]))
        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-5)
        got = grads["embed"]
        ref_g = jax.grad(lambda p: jnp.mean(jnp.stack([
            m.loss_fn(p, enc[i], dec[i], tgt[i]) for i in range(M)])))(
                params)
        np.testing.assert_allclose(got["rel_bias_enc"],
                                   ref_g["rel_bias_enc"],
                                   rtol=3e-4, atol=1e-6)
        np.testing.assert_allclose(got["rel_bias_dec"],
                                   ref_g["rel_bias_dec"],
                                   rtol=3e-4, atol=1e-6)


class TestRematPolicies:
    def test_encode_only_matches_blocks(self):
        """Re-encode-in-backward is numerically the SAME function: loss
        and grads identical to per-block remat (and to no remat)."""
        enc, dec, tgt = _data(jr.fold_in(K, 40), 1, 4, 32)
        enc, dec, tgt = enc[0], dec[0], tgt[0]
        outs = {}
        for name, kw in [("blocks", dict(remat=True)),
                         ("encode_only", dict(remat=True,
                                              remat_policy="encode_only")),
                         ("none", dict(remat=False))]:
            m = EncoderDecoderModel(T5Config(**SMALL, **kw))
            p = m.init(K)
            with jax.default_matmul_precision("highest"):
                outs[name] = jax.value_and_grad(m.loss_fn)(
                    p, enc, dec, tgt)
        for name in ("encode_only", "none"):
            np.testing.assert_allclose(float(outs[name][0]),
                                       float(outs["blocks"][0]),
                                       rtol=1e-6)
            for a, e in zip(jax.tree.leaves(outs[name][1]),
                            jax.tree.leaves(outs["blocks"][1])):
                np.testing.assert_allclose(a, e, rtol=1e-5, atol=1e-7)

    def test_bad_policy_rejected(self):
        with pytest.raises(ValueError, match="remat_policy"):
            T5Config(**SMALL, remat_policy="half")

    def test_encode_only_matches_blocks_through_pipeline(self):
        """encode_only under the split-rank pipeline (the policy's primary
        use case per PERF.md): the stage-local re-encode checkpoint must be
        numerically transparent — loss and grads == the 'blocks' pipeline."""
        M, b, s = 2, 2, 32
        enc, dec, tgt = _data(jr.fold_in(K, 41), M, b, s)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        outs = {}
        for policy in ("blocks", "encode_only"):
            m = EncoderDecoderModel(T5Config(**SMALL, remat=True,
                                             remat_policy=policy))
            params = m.init(K)
            pipe = EncDecPipeline(m, pp=2, split=1)
            part = pipe.partition(params)
            specs = pipe.param_specs(part)

            def run(p, e, d, t):
                lp = dict(p, stages=jax.tree.map(lambda x: x[0],
                                                 p["stages"]))
                loss, g = pipe.loss_and_grads(lp, e, d, t)
                g["stages"] = jax.tree.map(lambda x: x[None], g["stages"])
                return loss, g

            with jax.default_matmul_precision("highest"):
                outs[policy] = jax.jit(mesh_lib.shard_map(
                    run, mesh=mesh, in_specs=(specs, P(), P(), P()),
                    out_specs=(P(), specs),
                ))(part, enc, dec, tgt)
        np.testing.assert_allclose(float(outs["encode_only"][0]),
                                   float(outs["blocks"][0]), rtol=1e-6)
        for a, e in zip(jax.tree.leaves(outs["encode_only"][1]),
                        jax.tree.leaves(outs["blocks"][1])):
            np.testing.assert_allclose(a, e, rtol=1e-5, atol=1e-7)


class TestEncoderPadding:
    """enc_pad_lens through the enc-dec stack (VERDICT r4 next #4; the
    reference's key_padding_mask, encdec_multihead_attn.py:106-119):
    encoder self-attention and decoder cross-attention mask padded
    encoder KEY positions, on the flash fast path via kv_lens."""

    def _padded_vs_unpadded(self, impl):
        """Padded batch == mean of per-row unpadded runs: the defining
        property — padding must be invisible to valid positions."""
        cfg = T5Config(**SMALL, attention_impl=impl)
        m = EncoderDecoderModel(cfg)
        p = m.init(K)
        s = 32
        lens = [32, 20]
        enc_rows = [jr.randint(jr.fold_in(K, 40 + i), (1, L), 0, 64)
                    for i, L in enumerate(lens)]
        dec = jr.randint(jr.fold_in(K, 50), (2, s), 0, 64)
        tgt = jr.randint(jr.fold_in(K, 51), (2, s), 0, 64)

        # padded batch: rows padded to s with garbage tokens
        pad_tok = 63
        enc_pad = jnp.full((2, s), pad_tok, jnp.int32)
        for i, row in enumerate(enc_rows):
            enc_pad = enc_pad.at[i, :lens[i]].set(row[0])

        with jax.default_matmul_precision("highest"):
            got = m.loss_fn(p, enc_pad, dec, tgt,
                            enc_pad_lens=jnp.array(lens, jnp.int32))
            per_row = [
                m.loss_fn(p, enc_rows[i], dec[i:i + 1], tgt[i:i + 1])
                for i in range(2)
            ]
            ref = jnp.mean(jnp.stack(per_row))
        np.testing.assert_allclose(float(got), float(ref), rtol=2e-5)

        # padding must actually matter: without lens the garbage leaks
        leak = m.loss_fn(p, enc_pad, dec, tgt)
        assert abs(float(leak) - float(ref)) > 1e-4

    def test_padded_matches_unpadded_softmax(self):
        self._padded_vs_unpadded("softmax")

    def test_padded_matches_unpadded_flash(self):
        self._padded_vs_unpadded("flash")

    def test_flash_matches_softmax_padded_grads(self):
        """Both impls agree on a padded batch, through every gradient."""
        p = EncoderDecoderModel(T5Config(**SMALL)).init(K)
        enc = jr.randint(jr.fold_in(K, 60), (2, 32), 0, 64)
        dec = jr.randint(jr.fold_in(K, 61), (2, 32), 0, 64)
        tgt = jr.randint(jr.fold_in(K, 62), (2, 32), 0, 64)
        lens = jnp.array([32, 12], jnp.int32)
        out = {}
        for impl in ("softmax", "flash"):
            m = EncoderDecoderModel(T5Config(**SMALL, attention_impl=impl))
            with jax.default_matmul_precision("highest"):
                out[impl] = jax.value_and_grad(m.loss_fn)(
                    p, enc, dec, tgt, enc_pad_lens=lens)
        np.testing.assert_allclose(float(out["softmax"][0]),
                                   float(out["flash"][0]), rtol=1e-5)
        jax.tree_util.tree_map_with_path(
            lambda path, a, b: np.testing.assert_allclose(
                a, b, rtol=3e-3, atol=3e-4, err_msg=str(path)),
            out["softmax"][1], out["flash"][1])

    def test_padding_composes_with_relative_bias(self):
        """kv_lens + in-kernel bias together on the flash path."""
        cfgs = {impl: T5Config(**SMALL, position_encoding="relative",
                               attention_impl=impl)
                for impl in ("softmax", "flash")}
        p = EncoderDecoderModel(cfgs["softmax"]).init(K)
        enc = jr.randint(jr.fold_in(K, 63), (2, 32), 0, 64)
        dec = jr.randint(jr.fold_in(K, 64), (2, 32), 0, 64)
        tgt = jr.randint(jr.fold_in(K, 65), (2, 32), 0, 64)
        lens = jnp.array([28, 16], jnp.int32)
        with jax.default_matmul_precision("highest"):
            losses = {
                impl: float(EncoderDecoderModel(cfg).loss_fn(
                    p, enc, dec, tgt, enc_pad_lens=lens))
                for impl, cfg in cfgs.items()}
        np.testing.assert_allclose(losses["softmax"], losses["flash"],
                                   rtol=1e-5)

    def test_pipeline_matches_serial_padded(self):
        """The split-rank pipeline with (M, b) per-microbatch lens ==
        the serial model row by row (loss + embed grads)."""
        cfg = T5Config(**SMALL)
        m = EncoderDecoderModel(cfg)
        params = m.init(K)
        pipe = EncDecPipeline(m, pp=2, split=1)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)
        M, b, s = 2, 2, 32
        enc, dec, tgt = _data(jr.fold_in(K, 70), M, b, s)
        lens = jr.randint(jr.fold_in(K, 71), (M, b), 8, s + 1)

        def run(p, e, d, t, pl):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, g = pipe.loss_and_grads(lp, e, d, t, enc_pad_lens=pl)
            g["stages"] = jax.tree.map(lambda x: x[None], g["stages"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P(), P(), P()),
                out_specs=(P(), specs),
            ))(part, enc, dec, tgt, lens)

            def serial(p):
                return jnp.mean(jnp.stack([
                    m.loss_fn(p, enc[i], dec[i], tgt[i],
                              enc_pad_lens=lens[i])
                    for i in range(M)]))

            ref, ref_g = jax.value_and_grad(serial)(params)
        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-5)
        np.testing.assert_allclose(grads["embed"]["embedding"],
                                   ref_g["embedding"], rtol=3e-4,
                                   atol=1e-6)


class TestBucketedRelativeBias:
    """The r6 in-kernel path: ``relative_bias_impl='bucketed'`` (flash
    default) hands the kernels the (num_buckets, heads) table and every
    score tile recomputes its bias in-kernel — parity against the r5
    MATERIALIZED operand (kept as ``relative_bias_impl='materialized'``,
    the fallback/oracle), through the loss and every gradient including
    the bucket tables."""

    CFG = dict(vocab_size=64, max_seq_len=128, hidden_size=128,
               num_encoder_layers=1, num_decoder_layers=1, num_heads=2,
               position_encoding="relative", attention_impl="flash",
               remat=False)

    @pytest.mark.pallas
    def test_bucketed_matches_materialized_flash(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, s = 2, 128
        enc = jr.randint(jr.fold_in(K, 60), (b, s), 0, 64)
        dec = jr.randint(jr.fold_in(K, 61), (b, s), 0, 64)
        tgt = jr.randint(jr.fold_in(K, 62), (b, s), 0, 64)

        def loss_and_grads(impl):
            cfg = T5Config(**self.CFG, relative_bias_impl=impl)
            m = EncoderDecoderModel(cfg)
            p = m.init(K)
            with jax.default_matmul_precision("highest"):
                return jax.jit(jax.value_and_grad(
                    lambda p: m.loss_fn(p, enc, dec, tgt)))(p)

        l_b, g_b = loss_and_grads("bucketed")
        l_m, g_m = loss_and_grads("materialized")
        np.testing.assert_allclose(float(l_b), float(l_m), rtol=2e-5)
        flat_b = jax.tree_util.tree_leaves_with_path(g_b)
        flat_m = jax.tree.leaves(g_m)
        for (path, a), e in zip(flat_b, flat_m):
            np.testing.assert_allclose(
                a, e, rtol=5e-4, atol=5e-4,
                err_msg=jax.tree_util.keystr(path))

    def test_bucketed_composes_with_encoder_padding(self):
        """Padded batches + bucketed bias on the flash path: padded and
        cropped-unpadded runs agree on the live rows (the kv_lens ×
        BucketedBias composition inside one kernel call)."""
        cfg = T5Config(vocab_size=64, max_seq_len=32, hidden_size=32,
                       num_encoder_layers=1, num_decoder_layers=1,
                       num_heads=4, position_encoding="relative",
                       attention_impl="flash")
        m = EncoderDecoderModel(cfg)
        p = m.init(K)
        b, s, live = 2, 32, 20
        enc = jr.randint(jr.fold_in(K, 63), (b, s), 0, 64)
        lens = jnp.full((b,), live, jnp.int32)
        with jax.default_matmul_precision("highest"):
            padded = m.encode(p, enc, enc_pad_lens=lens)
            cropped = m.encode(p, enc[:, :live])
        np.testing.assert_allclose(padded[:, :live], cropped,
                                   rtol=2e-5, atol=2e-5)

    def test_impl_validation(self):
        with pytest.raises(ValueError, match="relative_bias_impl"):
            T5Config(**self.CFG, relative_bias_impl="inline")
