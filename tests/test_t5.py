"""T5-class encoder-decoder model + its split-rank pipeline.

The reference carries encoder-decoder plumbing (ModelType, split rank)
but no model to drive it; this tests the seq2seq flagship standalone and
THROUGH the two-segment pipeline (the GPTPipeline depth standard applied
to the enc-dec schedule).
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import EncDecPipeline, EncoderDecoderModel, T5Config
from apex_tpu.parallel import mesh as mesh_lib

K = jr.PRNGKey(91)

SMALL = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
             num_encoder_layers=2, num_decoder_layers=2, num_heads=4)


def _data(key, M, b, s, vocab=64):
    enc = jr.randint(key, (M, b, s), 0, vocab)
    dec = jr.randint(jr.fold_in(key, 1), (M, b, s), 0, vocab)
    tgt = jr.randint(jr.fold_in(key, 2), (M, b, s), 0, vocab)
    return enc, dec, tgt


class TestEncoderDecoderModel:
    def test_loss_finite_and_deterministic(self):
        m = EncoderDecoderModel(T5Config(**SMALL))
        p = m.init(K)
        enc, dec, tgt = _data(jr.fold_in(K, 1), 1, 2, 16)
        l1 = m.loss_fn(p, enc[0], dec[0], tgt[0])
        l2 = m.loss_fn(p, enc[0], dec[0], tgt[0])
        assert jnp.isfinite(l1) and l1 == l2

    def test_flash_matches_softmax_impl(self):
        cfg_s = T5Config(**SMALL)
        cfg_f = T5Config(**SMALL, attention_impl="flash")
        m_s, m_f = EncoderDecoderModel(cfg_s), EncoderDecoderModel(cfg_f)
        p = m_s.init(K)
        enc, dec, tgt = _data(jr.fold_in(K, 2), 1, 2, 16)
        with jax.default_matmul_precision("highest"):
            np.testing.assert_allclose(
                float(m_s.loss_fn(p, enc[0], dec[0], tgt[0])),
                float(m_f.loss_fn(p, enc[0], dec[0], tgt[0])),
                rtol=2e-5)

    def test_decoder_is_causal(self):
        """Future decoder tokens must not affect earlier positions."""
        m = EncoderDecoderModel(T5Config(**SMALL))
        p = m.init(K)
        enc, dec, _ = _data(jr.fold_in(K, 3), 1, 1, 16)
        lg1 = m.logits(p, enc[0], dec[0])
        dec2 = dec[0].at[0, -1].set((dec[0][0, -1] + 1) % 64)
        lg2 = m.logits(p, enc[0], dec2)
        np.testing.assert_allclose(lg1[:, :-1], lg2[:, :-1],
                                   rtol=1e-5, atol=1e-6)

    def test_cross_attention_sees_encoder(self):
        """Changing the encoder input must change the decoder output."""
        m = EncoderDecoderModel(T5Config(**SMALL))
        p = m.init(K)
        enc, dec, _ = _data(jr.fold_in(K, 4), 1, 1, 16)
        lg1 = m.logits(p, enc[0], dec[0])
        lg2 = m.logits(p, (enc[0] + 1) % 64, dec[0])
        assert float(jnp.max(jnp.abs(lg1 - lg2))) > 1e-3

    def test_trains(self):
        import optax

        m = EncoderDecoderModel(T5Config(**SMALL))
        p = m.init(K)
        opt = optax.adam(3e-3)
        st = opt.init(p)
        enc, dec, _ = _data(jr.fold_in(K, 5), 1, 4, 16, vocab=16)
        tgt = (enc + 3) % 16  # copy-ish task through the cross attention

        @jax.jit
        def step(p, st):
            loss, g = jax.value_and_grad(m.loss_fn)(
                p, enc[0], dec[0], tgt[0])
            u, st = opt.update(g, st, p)
            return optax.apply_updates(p, u), st, loss

        losses = []
        for _ in range(25):
            p, st, loss = step(p, st)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::6]


class TestEncDecPipelineModel:
    def test_partition_shapes_and_validation(self):
        m = EncoderDecoderModel(T5Config(**{**SMALL,
                                            "num_encoder_layers": 4,
                                            "num_decoder_layers": 2}))
        pipe = EncDecPipeline(m, pp=4, split=2)
        part = pipe.partition(m.init(K))
        # enc leaves: (pp=4, 2 layers/stage, ...); dec: (4, 1, ...)
        assert part["stages"]["enc"]["qkv"].shape[:2] == (4, 2)
        assert part["stages"]["dec"]["qkv"].shape[:2] == (4, 1)
        with pytest.raises(ValueError, match="split"):
            EncDecPipeline(m, pp=4, split=0)
        with pytest.raises(ValueError, match="divide"):
            EncDecPipeline(m, pp=4, split=3)

    @pytest.mark.parametrize("split", [1, 2])
    def test_pipeline_matches_serial(self, split):
        """The REAL seq2seq model through the two-segment pipeline: loss
        and embed/head grads equal the unpipelined model's."""
        cfg = T5Config(**{**SMALL, "num_encoder_layers": split * 2,
                          "num_decoder_layers": (4 - split) * 2})
        m = EncoderDecoderModel(cfg)
        params = m.init(jr.fold_in(K, 6))
        pipe = EncDecPipeline(m, pp=4, split=split)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        M, b, s = 4, 2, 16
        enc, dec, tgt = _data(jr.fold_in(K, 7), M, b, s)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=4)

        def run(p, e, d2, t):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, g = pipe.loss_and_grads(lp, e, d2, t)
            g["stages"] = jax.tree.map(lambda x: x[None], g["stages"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P(), P()),
                out_specs=(P(), specs),
            ))(part, enc, dec, tgt)

            def serial(p):
                return m.loss_fn(p, enc.reshape(M * b, s),
                                 dec.reshape(M * b, s),
                                 tgt.reshape(M * b, s))

            ref_loss, ref_g = jax.value_and_grad(serial)(params)

        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            grads["embed"]["embedding"], ref_g["embedding"],
            rtol=3e-4, atol=1e-5)
        np.testing.assert_allclose(
            grads["embed"]["ln_enc_w"], ref_g["ln_enc_w"],
            rtol=3e-4, atol=1e-5)
        np.testing.assert_allclose(
            grads["head"]["ln_dec_w"], ref_g["ln_dec_w"],
            rtol=3e-4, atol=1e-5)
        # stage grads: encoder stage 0's slice vs serial encoder layers
        ne = pipe.enc_per_stage
        np.testing.assert_allclose(
            grads["stages"]["enc"]["qkv"][0],
            ref_g["encoder"]["qkv"][:ne], rtol=3e-4, atol=1e-5)
        # decoder last stage's slice vs serial decoder tail
        nd = pipe.dec_per_stage
        np.testing.assert_allclose(
            grads["stages"]["dec"]["qkv"][3],
            ref_g["decoder"]["qkv"][-nd:], rtol=3e-4, atol=1e-5)
