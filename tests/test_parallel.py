"""Data-parallel stack tests: grad all-reduce semantics, SyncBatchNorm vs
reference batch-norm on the full batch, LARC arithmetic.

Coverage model: ``tests/distributed/synced_batchnorm/`` (SyncBN vs single-GPU
BN over the gathered batch) and ``tests/L0/run_amp/test_larc.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax keeps shard_map in experimental. CAUTION:
    # that implementation transposes lax.psum to psum regardless of the
    # replication check, so jax.grad taken INSIDE shard_map through an
    # explicit psum yields axis-size-scaled gradients there (see the
    # hazard note on apex_tpu.parallel.mesh.shard_map) — tests here take
    # grads OUTSIDE the wrapper, which is correct on every version.
    from jax.experimental.shard_map import shard_map

from apex_tpu.parallel import (
    BatchNormState,
    all_reduce_gradients,
    larc,
    sync_batchnorm,
)
from apex_tpu.parallel.sync_batchnorm import sync_batch_norm


class TestAllReduceGradients:
    def run_reduce(self, mesh, **kwargs):
        if mesh.shape["dp"] != 8:
            pytest.skip("test data and expectations assume exactly dp=8 "
                        "(the virtual CPU mesh)")
        grads = {"w": np.arange(8, dtype=np.float32).reshape(8, 1)}

        def f(g):
            return all_reduce_gradients(g, **kwargs)

        return jax.jit(
            shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        )(grads)

    def test_average(self, mesh8):
        out = self.run_reduce(mesh8)
        np.testing.assert_allclose(np.asarray(out["w"]).ravel(), np.full(8, 3.5))

    def test_sum(self, mesh8):
        out = self.run_reduce(mesh8, gradient_average=False)
        np.testing.assert_allclose(np.asarray(out["w"]).ravel(), np.full(8, 28.0))

    def test_predivide(self, mesh8):
        out = self.run_reduce(mesh8, gradient_predivide_factor=2.0)
        np.testing.assert_allclose(np.asarray(out["w"]).ravel(), np.full(8, 3.5),
                                   rtol=1e-6)

    def test_always_fp32_preserves_dtype(self, mesh8):
        grads = {"w": np.ones((8, 1), np.float16)}

        def f(g):
            return all_reduce_gradients(g, allreduce_always_fp32=True)

        out = jax.jit(
            shard_map(f, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
        )(grads)
        assert out["w"].dtype == jnp.float16


class TestSyncBatchNorm:
    def test_matches_global_bn(self, mesh8):
        """SyncBN over dp shards == plain BN over the gathered batch — the
        core invariant of tests/distributed/synced_batchnorm."""
        rng = np.random.RandomState(0)
        x = rng.randn(16, 4, 4, 3).astype(np.float32)
        scale = rng.rand(3).astype(np.float32) + 0.5
        bias = rng.randn(3).astype(np.float32)
        state = BatchNormState.create(3)

        def f(x):
            y, new_state = sync_batch_norm(
                x, jnp.asarray(scale), jnp.asarray(bias), state, axis_name="dp"
            )
            return y, new_state

        y, new_state = jax.jit(
            shard_map(f, mesh=mesh8, in_specs=P("dp"), out_specs=(P("dp"), P()))
        )(x)

        # reference: plain batch norm over the whole batch
        mean = x.reshape(-1, 3).mean(0)
        var = x.reshape(-1, 3).var(0)
        ref = (x - mean) / np.sqrt(var + 1e-5) * scale + bias
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4)
        np.testing.assert_allclose(np.asarray(new_state.running_mean), 0.1 * mean,
                                   atol=1e-5)

    def test_create_syncbn_process_group(self, mesh8):
        """``create_syncbn_process_group`` (``apex/parallel/__init__.py:
        58-95``): BN groups of 4 inside dp=8 — stats shared within a group,
        independent across groups."""
        from apex_tpu.parallel import create_syncbn_process_group

        if mesh8.shape["dp"] != 8:
            pytest.skip("group layout and references assume exactly dp=8")
        m2, axis = create_syncbn_process_group(4, mesh8)
        assert axis == "bn" and m2.shape["bn"] == 4 and m2.shape["dp_outer"] == 2

        rng = np.random.RandomState(3)
        x = rng.randn(16, 3).astype(np.float32)  # 2 per device
        state = BatchNormState.create(3)

        def f(x):
            y, _ = sync_batch_norm(x, None, None, state, axis_name=axis)
            return y

        y = jax.jit(shard_map(
            f, mesh=m2, in_specs=P(("dp_outer", "bn")),
            out_specs=P(("dp_outer", "bn")),
        ))(x)
        # per-group reference: first 8 rows = group 0, last 8 = group 1
        out = np.asarray(y)
        for g in range(2):
            grp = x[g * 8:(g + 1) * 8]
            ref = (grp - grp.mean(0)) / np.sqrt(grp.var(0) + 1e-5)
            np.testing.assert_allclose(out[g * 8:(g + 1) * 8], ref, atol=1e-4)

        # group_size 0 -> whole dp axis; 1 -> local BN
        _, a0 = create_syncbn_process_group(0, mesh8)
        _, a1 = create_syncbn_process_group(1, mesh8)
        assert a0 == "dp" and a1 is None

    def test_eval_uses_running_stats(self):
        x = np.random.RandomState(1).randn(4, 3).astype(np.float32)
        state = BatchNormState(
            running_mean=jnp.asarray([1.0, 2.0, 3.0]),
            running_var=jnp.asarray([4.0, 4.0, 4.0]),
            num_batches_tracked=jnp.asarray(5, jnp.int32),
        )
        y, new_state = sync_batch_norm(jnp.asarray(x), None, None, state,
                                       training=False, axis_name=None)
        ref = (x - np.array([1, 2, 3])) / np.sqrt(4 + 1e-5)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)
        assert int(new_state.num_batches_tracked) == 5

    def test_fused_relu_residual(self):
        x = jnp.asarray(np.random.RandomState(2).randn(8, 3), jnp.float32)
        res = jnp.ones((8, 3), jnp.float32) * -10.0
        state = BatchNormState.create(3)
        y, _ = sync_batch_norm(x, None, None, state, axis_name=None,
                               fuse_relu=True, residual=res)
        assert float(jnp.min(y)) == 0.0  # relu clamped everything (res=-10)

    def test_large_mean_small_std_stable(self):
        """Centered variance: no catastrophic cancellation for mean>>std data,
        even when the policy computes norms in half precision (the property
        the reference's Welford kernels guarantee)."""
        from apex_tpu import amp

        x = jnp.asarray(
            100.0 + 0.01 * np.random.RandomState(0).randn(64, 8), jnp.float32
        )
        with amp.with_policy(amp.O3):
            y, _ = sync_batch_norm(x, None, None, BatchNormState.create(8),
                                   axis_name=None)
        assert y.dtype == jnp.bfloat16  # O3: output in compute dtype
        std = float(jnp.std(y.astype(jnp.float32)))
        assert 0.5 < std < 2.0 and np.isfinite(std)

    def test_grad_matches_global_bn(self, mesh8):
        """Backward reduction falls out of autodiff — cross-check vs the
        single-device gradient (the reference hand-writes this path,
        optimized_sync_batchnorm_kernel.py:74-119)."""
        rng = np.random.RandomState(3)
        x = rng.randn(16, 3).astype(np.float32)
        state = BatchNormState.create(3)

        def local_fwd(x):
            y, _ = sync_batch_norm(x, None, None, state, axis_name="dp")
            return y

        # differentiate THROUGH the shard_map (grad outside): the backward
        # reduction across shards still flows through the psum'd batch
        # statistics, and the formulation is stable across jax's shard_map
        # psum-transpose revisions
        def total_loss(x):
            y = shard_map(local_fwd, mesh=mesh8,
                          in_specs=P("dp"), out_specs=P("dp"))(x)
            return jnp.sum(y ** 2)

        grad_sharded = jax.jit(jax.grad(total_loss))(jnp.asarray(x))

        def global_loss(x):
            y, _ = sync_batch_norm(x, None, None, state, axis_name=None)
            return jnp.sum(y ** 2)

        grad_global = jax.grad(global_loss)(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(grad_sharded),
                                   np.asarray(grad_global), atol=1e-4)


class TestLARC:
    def test_clip_mode_scales_small_trust(self):
        params = {"w": jnp.asarray([10.0, 0.0])}
        grads = {"w": jnp.asarray([1.0, 1.0])}
        tx = larc(learning_rate=1.0, trust_coefficient=0.02)
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        # adaptive_lr = 0.02*10/sqrt(2) ≈ 0.1414 < lr=1 → grads scaled by it
        expected = 0.02 * 10.0 / np.sqrt(2.0)
        np.testing.assert_allclose(np.asarray(updates["w"]),
                                   expected * np.ones(2), rtol=1e-5)

    def test_clip_mode_caps_at_one(self):
        params = {"w": jnp.asarray([1000.0])}
        grads = {"w": jnp.asarray([0.001])}
        tx = larc(learning_rate=1.0)
        updates, _ = tx.update(grads, tx.init(params), params)
        np.testing.assert_allclose(np.asarray(updates["w"]), [0.001])  # factor 1

    def test_zero_param_untouched(self):
        params = {"w": jnp.zeros((2,))}
        grads = {"w": jnp.asarray([1.0, 2.0])}
        tx = larc(learning_rate=1.0)
        updates, _ = tx.update(grads, tx.init(params), params)
        np.testing.assert_allclose(np.asarray(updates["w"]), [1.0, 2.0])

    def test_zero_grad_gets_no_weight_decay(self):
        # frozen layer: grad 0 stays 0 even with wd (reference applies decay
        # only inside the nonzero-norm guard, LARC.py:92-102)
        params = {"w": jnp.asarray([5.0, 5.0])}
        grads = {"w": jnp.zeros((2,))}
        tx = larc(learning_rate=1.0, weight_decay=0.1)
        updates, _ = tx.update(grads, tx.init(params), params)
        np.testing.assert_allclose(np.asarray(updates["w"]), 0.0)

    def test_chained_with_sgd(self):
        params = {"w": jnp.asarray([10.0, 10.0])}
        tx = optax.chain(larc(learning_rate=0.1), optax.sgd(0.1))
        state = tx.init(params)
        grads = {"w": jnp.asarray([1.0, 1.0])}
        updates, state = tx.update(grads, state, params)
        new_params = optax.apply_updates(params, updates)
        assert np.all(np.asarray(new_params["w"]) < 10.0)

    def test_requires_params(self):
        tx = larc()
        with pytest.raises(ValueError):
            tx.update({"w": jnp.ones(2)}, tx.init({"w": jnp.ones(2)}), None)
