"""ASP channel-permutation search tests.

Spec: the reference's permutation search improves 2:4 magnitude retention
(``apex/contrib/sparsity/permutation_lib.py``, kernels under
``permutation_search_kernels/``); its own test is magnitude-based too.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.sparsity import permutation as plib


def _retention(m):
    return float(plib.sum_after_2_to_4(jnp.asarray(m)))


def _brute_force_best(m):
    import itertools

    c = m.shape[1]
    best = -np.inf
    for p in itertools.permutations(range(c)):
        best = max(best, _retention(m[:, list(p)]))
    return best


class TestRetentionMetric:
    def test_matches_manual(self):
        m = np.array([[1.0, -2.0, 3.0, 0.5, 4.0, 0.1, 0.2, 0.3]])
        # stripe 1: keep |3|,|2|; stripe 2: keep 4, 0.3
        assert _retention(m) == pytest.approx(3 + 2 + 4 + 0.3)

    def test_invariant_to_sign(self):
        m = np.random.RandomState(0).randn(16, 8)
        assert _retention(m) == pytest.approx(_retention(-m), rel=1e-6)


class TestSwapScores:
    def test_delta_matrix_matches_brute_force(self):
        rng = np.random.RandomState(1)
        m = rng.randn(8, 12).astype(np.float32)
        delta = np.asarray(plib._swap_improvements(jnp.asarray(m)))
        base = _retention(m)
        for i in range(12):
            for j in range(12):
                if i // 4 == j // 4:
                    assert delta[i, j] == -np.inf
                    continue
                sw = m.copy()
                sw[:, [i, j]] = sw[:, [j, i]]
                assert delta[i, j] == pytest.approx(
                    _retention(sw) - base, abs=1e-3
                ), (i, j)


class TestSearch:
    def test_exhaustive_finds_global_optimum(self):
        rng = np.random.RandomState(2)
        m = rng.randn(6, 8).astype(np.float32)
        perm, imp = plib.exhaustive_search(jnp.asarray(m))
        assert _retention(m[:, perm]) == pytest.approx(_brute_force_best(m), rel=1e-5)
        assert imp >= 0

    def test_greedy_strictly_improves_structured_case(self):
        # two "large" channels per stripe-pair arranged adversarially: the
        # identity grouping wastes one large channel per stripe
        rng = np.random.RandomState(3)
        c = 32
        m = rng.randn(64, c).astype(np.float32) * 0.01
        # columns 0..7 large, all in the first two stripes
        m[:, :8] += rng.randn(64, 8).astype(np.float32) * 3
        perm, imp = plib.greedy_swap_search(jnp.asarray(m))
        assert imp > 0
        assert _retention(m[:, perm]) > _retention(m) + 1e-3

    def test_greedy_on_random_conv_net(self):
        """VERDICT item 5 acceptance: searched permutation strictly improves
        2:4 mask magnitude retention on a random conv net vs no permute."""
        rng = np.random.RandomState(4)
        convs = [rng.randn(3 * 3 * 16, 32), rng.randn(3 * 3 * 32, 64)]
        for w in convs:
            mat = w.T.astype(np.float32)  # (out, in*k*k): permute reduction dim
            perm, imp = plib.search_for_good_permutation(jnp.asarray(mat))
            assert imp > 0, "search failed to improve retention"
            assert _retention(mat[:, perm]) > _retention(mat)

    def test_permutation_is_valid(self):
        rng = np.random.RandomState(5)
        m = rng.randn(16, 16).astype(np.float32)
        perm, _ = plib.search_for_good_permutation(jnp.asarray(m))
        assert sorted(perm.tolist()) == list(range(16))
        inv = plib.invert_permutation(perm)
        np.testing.assert_array_equal(perm[inv], np.arange(16))

    def test_apply_permutation_roundtrip(self):
        rng = np.random.RandomState(6)
        m = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        perm, _ = plib.search_for_good_permutation(m)
        permuted = plib.apply_permutation(m, perm)
        restored = plib.apply_permutation(permuted, plib.invert_permutation(perm))
        np.testing.assert_allclose(np.asarray(restored), np.asarray(m))


class TestGreedyVsExhaustive:
    """The module docstring's measured scope claim (VERDICT r5 Weak #6 /
    Next #9): on a real 2:4-pruned layer, the vectorized greedy descent
    retains ≥99% of the exhaustive optimum's magnitude. Blockwise at C=8
    — the largest width where exhaustive (35 canonical assignments) is
    tractable, same bail-out logic as the reference's
    ``exhaustive_search.py:93-99``."""

    def _real_layer(self):
        from apex_tpu.models import GPTConfig, GPTModel

        cfg = GPTConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                        num_layers=2, num_heads=4, tp_size=1)
        params = GPTModel(cfg).init(jax.random.PRNGKey(21))
        # mlp_down: the 4h→h projection — its 128-wide reduction dim is
        # the one ASP permutes on a torch Linear
        return np.asarray(params["layers"]["mlp_down"]["weight"][0],
                          np.float32)  # (32, 128)

    def test_greedy_retains_99pct_of_exhaustive_on_real_layer(self):
        w = self._real_layer()
        base = greedy = exhaustive = 0.0
        # 6 of the 16 blocks keep the tier-1 cost down; the full-width
        # measurement (all 16: ratio 0.9994, worst 0.996) is quoted in the
        # module docstring and reproduces by dropping this slice
        for b in range(6):
            blk = w[:, b * 8:(b + 1) * 8]
            p_ex, _ = plib.exhaustive_search(jnp.asarray(blk))
            p_gr, _ = plib.greedy_swap_search(jnp.asarray(blk))
            r_ex, r_gr = _retention(blk[:, p_ex]), _retention(blk[:, p_gr])
            # exhaustive is the optimum: greedy can never beat it
            assert r_gr <= r_ex + 1e-4, b
            base += _retention(blk)
            greedy += r_gr
            exhaustive += r_ex
        assert exhaustive > base, "permutation must help on a real layer"
        # docstring's measured claim (observed 0.9994 total, 0.996 worst
        # block); 0.99 leaves room for init-stream drift, not regression
        assert greedy / exhaustive >= 0.99
        # and the greedy improvement is the bulk of what is achievable
        assert (greedy - base) / (exhaustive - base) >= 0.9


class TestASPIntegration:
    def test_asp_permute_then_mask_retains_more(self):
        from apex_tpu.contrib.sparsity import ASP

        rng = np.random.RandomState(7)
        params = {
            "dense": jnp.asarray(rng.randn(64, 32).astype(np.float32) *
                                 np.r_[np.full(8, 4.0), np.full(24, 0.02)]),
            "bias": jnp.asarray(rng.randn(64).astype(np.float32)),
        }
        asp = ASP()
        perms = asp.search_permutations(params)
        permuted = asp.permute_params(params, perms)
        # bias untouched (identity perm)
        np.testing.assert_allclose(np.asarray(permuted["bias"]),
                                   np.asarray(params["bias"]))
        before = _retention(np.asarray(params["dense"]))
        after = _retention(np.asarray(permuted["dense"]))
        assert after > before
        # and the 2:4 mask on the permuted weight keeps that magnitude
        masks = asp.compute_sparse_masks(permuted)
        pruned = asp.apply_masks(permuted, masks)
        kept = float(jnp.sum(jnp.abs(pruned["dense"])))
        assert kept == pytest.approx(after, rel=1e-5)
