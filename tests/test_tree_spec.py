"""Tree speculative decoding (ISSUE 19): the fused tree-verify tail,
static draft-tree topologies, the serving tree round's rewind contract,
drafter KV as first-class paged-pool state, acceptance-adaptive
(depth, branching) selection, and the fp8 KV pool satellite.

The load-bearing witnesses:

* fused tree verify: the deepest fully-accepted root path wins (ties
  to the LOWEST node index — at branching 1 the semantics degenerate
  to the chain), and the Pallas kernel == the XLA fallback
  token-for-token on shared noise, greedy AND sampled;
* scripted all-rejected and partial-path tree rounds under churn
  restore block tables / lengths / the allocator free list exactly,
  and the resumed stream is token-identical to non-speculative decode
  (length masking IS the rewind — rejected nodes never touch the
  pool);
* a PagedModelDrafter's blocks live in the scheduler's OWN allocator:
  ``check_accounting()`` stays exact across churn INCLUDING preemption
  of a stream with live drafter blocks, and every drafter block is
  back on the free list when serving drains;
* the adaptive controller converges on a scripted bimodal acceptance
  trace — easy streams climb to the deepest choice, hard streams pin
  the shallowest, one adjustment per full window (hysteresis);
* eager tree-shape validation names the knob (MAX_DRAFT_K / depth /
  chain_k) — never a deep XLA shape error;
* ``kv_dtype="fp8_e4m3"`` rides the same per-block-row scale layout
  as int8 (1 byte/cell), serves end to end, composes with tree
  speculation, and the illegal-value error names the legal set.
"""

import numpy as np
import pytest

import jax.numpy as jnp
import jax.random as jr

from apex_tpu.inference import DecodeEngine
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops import fused_verify_tree
from apex_tpu.serving import Request, ServingEngine
from apex_tpu.spec import (AdaptiveSpecController, NGramTreeDrafter,
                           PagedModelDrafter, draft_tree, is_tree_drafter)

_CFG = dict(vocab_size=256, max_seq_len=256, hidden_size=64,
            num_layers=2, num_heads=4, tp_size=1, remat=False,
            attention_impl="flash")


def _model(seed=0, **over):
    cfg = GPTConfig(**{**_CFG, **over})
    model = GPTModel(cfg)
    return model, model.init(jr.PRNGKey(seed))


def _requests(n=6, seed=0, vocab=256, prompt_rng=(4, 40), newtok=(2, 10)):
    rng = np.random.default_rng(seed)
    return [Request(
        rid=i,
        prompt=rng.integers(0, vocab, int(rng.integers(*prompt_rng))
                            ).astype(np.int32),
        max_new_tokens=int(rng.integers(*newtok)))
        for i in range(n)]


# --- the static topology ------------------------------------------------------

class TestDraftTree:
    def test_topology_invariants(self):
        t = draft_tree(3, 2)  # 3 branches x depth 2
        assert t.n1 == 7 and t.num_nodes == 6
        # level-0 nodes hang off the root; deeper nodes chain
        assert list(t.parents) == [0, 0, 1, 0, 3, 0, 5]
        # anc is ancestor-OR-SELF including the root
        assert list(t.anc[4]) == [1, 0, 0, 1, 1, 0, 0]
        assert list(t.depths) == [0, 1, 2, 1, 2, 1, 2]
        # one cached instance per shape — one compiled program downstream
        assert draft_tree(3, 2) is t

    def test_path_tokens_checks_verdict_against_topology(self):
        t = draft_tree(2, 2)
        toks = [10, 11, 12, 13]  # drafted nodes 1..4
        assert t.path_tokens(toks, 2, 2, 99) == [10, 11, 99]
        assert t.path_tokens(toks, 1, 3, 99) == [12, 99]
        assert t.path_tokens(toks, 0, 0, 99) == [99]
        with pytest.raises(ValueError, match="disagrees"):
            t.path_tokens(toks, 2, 3, 99)  # node 3 is depth 1, not 2

    def test_oversized_shape_names_the_knob(self):
        with pytest.raises(ValueError, match="MAX_DRAFT_K"):
            draft_tree(8, 8)  # 64 nodes > the verify-row ceiling
        with pytest.raises(ValueError, match="branching"):
            draft_tree(0, 4)
        with pytest.raises(ValueError, match="chain_k"):
            NGramTreeDrafter(depth=3, branching=2, chain_k=5)


# --- the fused tree-verify op -------------------------------------------------

class TestFusedVerifyTree:
    def _setup(self, b=1, branching=2, depth=2, V=256, seed=0):
        # V is a 128-multiple: the kernel's lane-tiling floor
        t = draft_tree(branching, depth)
        logits = jr.normal(jr.PRNGKey(seed), (b, t.n1, V))
        cand = np.asarray(jnp.argmax(logits, -1))
        parents, anc = t.operands(b)
        return t, logits, cand, parents, anc

    def test_greedy_deepest_path_wins(self):
        t, logits, cand, parents, anc = self._setup()
        V = logits.shape[-1]
        # branch 0 (nodes 1,2) rejected at level 0; branch 1 (nodes
        # 3,4) fully accepted: node j accepts iff its token is the
        # argmax of its PARENT's row
        tokens = np.zeros((1, t.n1), np.int32)
        tokens[0, 1] = (cand[0, 0] + 1) % V
        tokens[0, 3] = cand[0, 0]
        tokens[0, 4] = cand[0, 3]
        a, j, nxt = fused_verify_tree(logits, jnp.asarray(tokens),
                                      jnp.asarray(parents),
                                      jnp.asarray(anc))
        assert int(a[0]) == 2 and int(j[0]) == 4
        assert int(nxt[0]) == cand[0, 4]  # bonus from the terminal row

    def test_greedy_tie_breaks_to_lowest_index(self):
        t, logits, cand, parents, anc = self._setup(seed=1)
        # BOTH branches fully accepted -> the winner is the lower-index
        # terminal (branch 0's leaf, node 2)
        tokens = np.zeros((1, t.n1), np.int32)
        tokens[0, 1] = cand[0, 0]
        tokens[0, 2] = cand[0, 1]
        tokens[0, 3] = cand[0, 0]
        tokens[0, 4] = cand[0, 3]
        a, j, nxt = fused_verify_tree(logits, jnp.asarray(tokens),
                                      jnp.asarray(parents),
                                      jnp.asarray(anc))
        assert int(a[0]) == 2 and int(j[0]) == 2
        assert int(nxt[0]) == cand[0, 2]

    def test_all_rejected_emits_the_corrected_root_token(self):
        t, logits, cand, parents, anc = self._setup(seed=2)
        V = logits.shape[-1]
        tokens = np.full((1, t.n1), 0, np.int32)
        for b in range(t.branching):  # every level-0 node wrong
            tokens[0, 1 + b * t.depth] = (cand[0, 0] + 1 + b) % V
        a, j, nxt = fused_verify_tree(logits, jnp.asarray(tokens),
                                      jnp.asarray(parents),
                                      jnp.asarray(anc))
        assert int(a[0]) == 0 and int(j[0]) == 0
        assert int(nxt[0]) == cand[0, 0]

    @pytest.mark.parametrize("branching,depth", [(1, 4), (2, 3), (4, 2)])
    def test_kernel_matches_fallback_greedy(self, branching, depth):
        t, logits, cand, parents, anc = self._setup(
            b=3, branching=branching, depth=depth, seed=depth)
        tokens = np.array(jr.randint(
            jr.PRNGKey(7), (3, t.n1), 0, 64), np.int32)
        tokens[0, 1:] = cand[0, [int(p) for p in t.parents[1:]]]
        args = (logits, jnp.asarray(tokens), jnp.asarray(parents),
                jnp.asarray(anc))
        a1, j1, n1 = fused_verify_tree(*args, impl="xla")
        a2, j2, n2 = fused_verify_tree(*args, impl="pallas")
        assert (np.asarray(a1) == np.asarray(a2)).all()
        assert (np.asarray(j1) == np.asarray(j2)).all()
        assert (np.asarray(n1) == np.asarray(n2)).all()

    @pytest.mark.parametrize("top_k,top_p", [(0, 1.0), (13, 0.9)])
    def test_kernel_matches_fallback_sampled(self, top_k, top_p):
        """Shared-noise discipline: the edge-wise rejection rule agrees
        token-for-token across impls."""
        t, logits, cand, parents, anc = self._setup(b=4, seed=5)
        tokens = np.asarray(jr.randint(
            jr.PRNGKey(9), (4, t.n1), 0, 64), np.int32)
        key = jr.PRNGKey(11)
        args = (logits, jnp.asarray(tokens), jnp.asarray(parents),
                jnp.asarray(anc), key)
        kw = dict(temperature=0.7, top_k=top_k, top_p=top_p)
        a1, j1, n1 = fused_verify_tree(*args, impl="xla", **kw)
        a2, j2, n2 = fused_verify_tree(*args, impl="pallas", **kw)
        assert (np.asarray(a1) == np.asarray(a2)).all()
        assert (np.asarray(j1) == np.asarray(j2)).all()
        assert (np.asarray(n1) == np.asarray(n2)).all()


# --- the serving tree round's rewind contract ---------------------------------

class TestTreeRewindContract:
    def _prefill(self, eng, sched, params, req):
        key = jr.PRNGKey(0)
        sched.submit(req)
        sched.admit(0.0)
        pool = eng.init_pool()
        while True:
            w = sched.next_prefill(0.0)
            if w is None:
                break
            pool, tok, _ = eng.prefill_chunk(
                params, pool, jnp.asarray(sched.tables.row(w.slot)),
                jnp.asarray(w.tokens), jnp.int32(w.start),
                jnp.int32(w.live), key)
            sched.note_prefill(w, int(tok), 0.0)
        return pool

    def _one_tree_round(self, eng, sched, params, pool, tree, node_toks):
        """Dispatch ONE manual tree round with scripted node tokens and
        commit it through note_spec_tokens; returns (pool, a, emitted)."""
        (slot,) = sched.decoding_slots()
        toks, lens = sched.decode_batch(0.0, lookahead=tree.depth)
        tok_mat = np.zeros((eng.num_slots, tree.n1), np.int32)
        tok_mat[:, 0] = toks
        tok_mat[slot, 1:] = node_toks
        parents, anc = tree.operands(eng.num_slots)
        levels = np.arange(tree.depth + 1, dtype=np.int32)
        pool, acc, jst, nxt = eng.spec_tree_step(
            params, pool, jnp.asarray(sched.tables.asarray()),
            jnp.asarray(tok_mat), jnp.asarray(lens),
            jnp.asarray(parents), jnp.asarray(anc),
            jnp.asarray(levels), jr.PRNGKey(0))
        a = int(np.asarray(acc)[slot])
        emitted = tree.path_tokens(node_toks, a,
                                   int(np.asarray(jst)[slot]),
                                   int(np.asarray(nxt)[slot]))
        sched.note_spec_tokens({slot: emitted}, 0.0)
        return pool, a, emitted

    def _finish_plain(self, eng, sched, params, pool):
        key = jr.PRNGKey(0)
        while True:
            batch = sched.decode_batch(0.0)
            if batch is None:
                break
            toks, lens = batch
            pool, sampled, _ = eng.decode_step(
                params, pool, jnp.asarray(sched.tables.asarray()),
                jnp.asarray(toks), jnp.asarray(lens), key)
            sched.note_decode(np.asarray(sampled), 0.0)
        return pool

    @pytest.mark.parametrize("accept_levels", [0, 2])
    def test_scripted_round_restores_pool_state(self, accept_levels):
        """All-rejected (0) and partial-path (2 of 3 levels down branch
        1) rounds: tables/lengths/free list land exactly where plain
        decode of the emitted tokens would have, and the resumed stream
        is token-identical to the non-speculative stream. A 14-token
        prompt makes the depth-3 reservation cross the 16-row block
        boundary, so the rewind really frees blocks."""
        import apex_tpu.serving.kv_blocks as kvb
        model, params = _model()
        mk = lambda: ServingEngine(model, num_slots=2, block_size=16,  # noqa: E731
                                   prefill_chunk=16)
        ref_eng = mk()
        base = ref_eng.serve(
            params, _requests(1, prompt_rng=(14, 15), newtok=(8, 9)),
            telemetry=False)
        base_tokens = list(base[0].tokens)

        eng = mk()
        sched = eng.make_scheduler()
        (req,) = _requests(1, prompt_rng=(14, 15), newtok=(8, 9))
        pool = self._prefill(eng, sched, params, req)
        (slot,) = sched.decoding_slots()
        free_before = list(sched.allocator._free)
        table_before = sched.tables.asarray().copy()
        len_before = sched.slot_length(slot)

        # branch 1 carries the baseline stream for accept_levels
        # levels then goes wrong; branch 0 is wrong at level 0 (its
        # level-0 token collides with nothing: +1 mod V of the truth)
        tree = draft_tree(2, 3)
        node_toks = np.zeros((tree.num_nodes,), np.int32)
        for lv in range(tree.depth):  # branch 0: all wrong
            node_toks[0 * tree.depth + lv] = (base_tokens[lv] + 1) % 256
        for lv in range(tree.depth):  # branch 1: right for a levels
            right = base_tokens[1 + lv]  # round starts after token 0
            node_toks[1 * tree.depth + lv] = (
                right if lv < accept_levels else (right + 1) % 256)
        # NOTE: the round's pending token (column 0) is base_tokens[0],
        # so branch truth at level lv is base_tokens[1 + lv]... except
        # the decode_batch pending token IS base_tokens[0] only on the
        # first round — assert it to keep the script honest
        pool, a, emitted = self._one_tree_round(
            eng, sched, params, pool, tree, node_toks)
        assert a == accept_levels
        # the emitted tokens are exactly the baseline's next a+1
        assert emitted == base_tokens[1:1 + a] + [base_tokens[1 + a]]

        # pool-state exactness: lengths advanced by exactly a+1; blocks
        # the stream held BEFORE the round are untouched, blocks the
        # frontier now needs came off the free list LIFO, and entries
        # past the frontier rewound to the dead block
        assert sched.slot_length(slot) == len_before + a + 1
        keep = kvb.blocks_needed(sched.slot_length(slot), 16)
        had = kvb.blocks_needed(len_before, 16)
        table_now = sched.tables.asarray()
        assert (table_now[slot, :had] == table_before[slot, :had]).all()
        assert (table_now[slot, keep:] == kvb.DEAD_BLOCK).all()
        claimed = keep - had
        assert list(table_now[slot, had:keep]) == \
            free_before[len(free_before) - claimed:][::-1]
        assert sched.allocator._free == free_before[:len(free_before)
                                                    - claimed]
        sched.allocator.check_accounting()

        # resume WITHOUT speculation: token-identical to baseline
        self._finish_plain(eng, sched, params, pool)
        assert list(req.tokens) == base_tokens
        assert eng.spec_tree_step._cache_size() == 1


# --- drafter KV in the shared paged pool --------------------------------------

class TestDrafterPoolAccounting:
    def _drafter(self, depth=3, branching=2):
        dm, dp = _model(seed=9, num_layers=1, hidden_size=32, num_heads=2)
        return PagedModelDrafter(dm, dp, depth=depth, branching=branching)

    def test_blocks_accounted_across_churn(self):
        """Serve a full trace with the drafter allocating from the
        scheduler's own allocator: parity with the plain baseline,
        exact accounting at drain, zero live drafter blocks after."""
        model, params = _model()
        mk = lambda: ServingEngine(model, num_slots=3, block_size=16,  # noqa: E731
                                   prefill_chunk=16)
        base = mk().serve(params, _requests(6), telemetry=False)
        want = {r.rid: list(r.tokens) for r in base}
        eng = mk()
        draft = self._drafter()
        out = eng.serve(params, _requests(6), telemetry=False, draft=draft)
        assert all(list(r.tokens) == want[r.rid] for r in out)
        assert draft.peak_blocks > 0  # the drafter really used the pool
        assert draft.pool_blocks() == 0  # ...and gave every block back
        assert eng.spec_tree_step._cache_size() == 1

    def test_preemption_evicts_drafter_blocks(self):
        """An undersized pool forces preemption of streams WITH live
        drafter blocks (the scheduler calls evict_stream from
        _preempt): accounting stays exact, the resumed streams match
        the equally-pressured non-speculative baseline, and the ladder
        degraded at least one round rather than stalling."""
        model, params = _model()
        mk = lambda n: ServingEngine(model, num_slots=3, block_size=16,  # noqa: E731
                                     prefill_chunk=16, num_blocks=n)
        base = mk(8).serve(params, _requests(8), telemetry=False)
        want = {r.rid: list(r.tokens) for r in base}
        eng = mk(8)
        draft = self._drafter()
        out = eng.serve(params, _requests(8), telemetry=False, draft=draft)
        assert all(list(r.tokens) == want[r.rid] for r in out)
        assert draft.pool_blocks() == 0
        assert any(r.evictions > 0 for r in out), \
            "pool pressure never preempted a stream"
        assert eng.last_stats.spec_degraded > 0, \
            "the headroom ladder never ran"

    def test_unbound_drafter_names_the_fix(self):
        draft = self._drafter()
        with pytest.raises(ValueError, match="bind"):
            draft.propose_tree(0, [1, 2, 3])


# --- acceptance-adaptive (depth, branching) -----------------------------------

class TestAdaptiveController:
    def test_bimodal_convergence_and_hysteresis(self):
        """Scripted bimodal trace: the easy stream climbs one rung per
        FULL window up to the deepest choice; the hard stream pins the
        shallowest; a single lucky round never flaps the choice."""
        ctl = AdaptiveSpecController(choices=((2, 1), (4, 1), (4, 2)),
                                     window=4)
        for r in range(12):
            d, _ = ctl.choice(0)
            ctl.note_round(0, d, d)      # easy: everything accepted
            d, _ = ctl.choice(1)
            ctl.note_round(1, 0, d)      # hard: everything rejected
        assert ctl.choice(0) == (4, 2)   # climbed the whole ladder
        assert ctl.choice(1) == (2, 1)   # pinned at the floor
        # hysteresis: after an adjustment a fresh window must fill
        # before the next one — 12 rounds / window 4 = at most 3 steps
        assert ctl.adjustments <= 3

        # one lucky round inside a bad stretch does not flap upward
        ctl2 = AdaptiveSpecController(choices=((2, 1), (4, 1)), window=4)
        for r in range(8):
            d, _ = ctl2.choice(0)
            ctl2.note_round(0, d if r == 3 else 0, d)
        assert ctl2.choice(0) == (2, 1)

    def test_round_shape_is_shallowest_live(self):
        ctl = AdaptiveSpecController(choices=((2, 1), (4, 2)), window=1)
        for _ in range(2):
            ctl.note_round(0, 2, 2)      # stream 0 climbs
        assert ctl.choice(0) == (4, 2)
        assert ctl.round_shape([0]) == (4, 2)
        assert ctl.round_shape([0, 1]) == (2, 1)  # stream 1 drags down
        ctl.release(0)
        assert ctl.round_shape([]) == (2, 1)

    def test_serve_adaptive_parity(self):
        """End to end: adaptive tree serving is token-identical to the
        plain baseline (the controller only changes SHAPES, never
        verdicts) and every choice's program is pinned."""
        model, params = _model()
        mk = lambda: ServingEngine(model, num_slots=3, block_size=16,  # noqa: E731
                                   prefill_chunk=16)
        base = mk().serve(params, _requests(6), telemetry=False)
        want = {r.rid: list(r.tokens) for r in base}
        eng = mk()
        out = eng.serve(params, _requests(6), telemetry=False,
                        draft=NGramTreeDrafter(depth=4, branching=2),
                        adaptive=AdaptiveSpecController(window=2))
        assert all(list(r.tokens) == want[r.rid] for r in out)
        # one executable per (depth, branching) in use, never more than
        # the choice set
        assert 1 <= eng.spec_tree_step._cache_size() <= 3

    def test_adaptive_choice_deeper_than_drafter_refused(self):
        model, params = _model()
        eng = ServingEngine(model, num_slots=2, block_size=16,
                            prefill_chunk=16)
        with pytest.raises(ValueError, match="depth"):
            eng.serve(params, _requests(1), telemetry=False,
                      draft=NGramTreeDrafter(depth=2, branching=2),
                      adaptive=AdaptiveSpecController(
                          choices=((2, 1), (4, 1))))


# --- serving integration ------------------------------------------------------

class TestServingTree:
    def test_tree_churn_parity_ngram(self):
        model, params = _model()
        mk = lambda: ServingEngine(model, num_slots=3, block_size=16,  # noqa: E731
                                   prefill_chunk=16)
        base = mk().serve(params, _requests(6), telemetry=False)
        want = {r.rid: list(r.tokens) for r in base}
        eng = mk()
        draft = NGramTreeDrafter(depth=3, branching=2)
        assert is_tree_drafter(draft)
        out = eng.serve(params, _requests(6), telemetry=False, draft=draft)
        assert all(list(r.tokens) == want[r.rid] for r in out)
        stats = eng.last_stats
        assert stats.tree_rounds > 0
        assert stats.spec_nodes >= stats.spec_accepted
        assert 0.0 < stats.spec_efficiency <= 1.0
        assert eng.spec_tree_step._cache_size() == 1
        assert eng.prefill_chunk._cache_size() == 1

    def test_tree_tp_refused_eagerly(self):
        """The tree-verify step has no sharded twin yet: a tree drafter
        under tp>1 must be refused before any dispatch, naming the
        chain alternative."""
        model, params = _model()
        eng = ServingEngine(model, num_slots=2, block_size=16,
                            prefill_chunk=16)
        eng.tp = 2  # a tp=2 engine without devices: serve checks first
        with pytest.raises(ValueError, match="tp=1"):
            eng.serve(params, _requests(1), telemetry=False,
                      draft=NGramTreeDrafter(depth=2, branching=2))


# --- the fp8 KV pool satellite ------------------------------------------------

class TestFp8KV:
    def test_pool_layout_matches_int8(self):
        """Same per-block-row scale planes, same 1 byte/cell — only the
        cell dtype differs."""
        model, params = _model()
        q8 = ServingEngine(model, num_slots=2, block_size=16,
                           kv_dtype="int8")
        qf8 = ServingEngine(model, num_slots=2, block_size=16,
                            kv_dtype="fp8_e4m3")
        p8, pf8 = q8.init_pool(), qf8.init_pool()
        assert pf8["k"].dtype == jnp.float8_e4m3fn
        assert pf8["k_scale"].shape == p8["k_scale"].shape
        assert pf8["k_scale"].dtype == p8["k_scale"].dtype
        assert qf8.pool_bytes() == q8.pool_bytes()

    def test_fp8_serve_end_to_end(self):
        model, params = _model()
        eng = ServingEngine(model, num_slots=2, block_size=16,
                            prefill_chunk=16, kv_dtype="fp8_e4m3")
        done = eng.serve(params, _requests(4), telemetry=False)
        assert len(done) == 4
        assert all(len(r.tokens) == r.max_new_tokens for r in done)
        assert eng.decode_step._cache_size() == 1

    def test_fp8_composes_with_tree_spec(self):
        """fp8 + tree speculation is token-identical to fp8 without
        speculation (the composition's parity oracle — the fp8 stream
        itself may differ from float, quantization is lossy)."""
        model, params = _model()
        mk = lambda: ServingEngine(model, num_slots=2, block_size=16,  # noqa: E731
                                   prefill_chunk=16, kv_dtype="fp8_e4m3")
        base = mk().serve(params, _requests(4), telemetry=False)
        want = {r.rid: list(r.tokens) for r in base}
        out = mk().serve(params, _requests(4), telemetry=False,
                         draft=NGramTreeDrafter(depth=3, branching=2))
        assert all(list(r.tokens) == want[r.rid] for r in out)

    def test_eager_validation_names_the_legal_set(self):
        model, params = _model()
        with pytest.raises(ValueError, match="fp8_e4m3"):
            ServingEngine(model, num_slots=2, block_size=16,
                          kv_dtype="fp8_e5m2")
        with pytest.raises(ValueError, match="int8"):
            ServingEngine(model, num_slots=2, block_size=16,
                          kv_dtype="bogus")

    def test_fp8_tp_refused(self):
        """The tensor-parallel quantize path is int8-specific: fp8
        under a tp>1 plan is refused in __init__, before the tp plan
        itself is even validated (the knob error comes first)."""
        import types
        model, params = _model()
        with pytest.raises(ValueError, match="tp=1 only"):
            ServingEngine(model, num_slots=2, block_size=16,
                          kv_dtype="fp8_e4m3",
                          plan=types.SimpleNamespace(tp=2))
