"""transformer.functional + transformer.utils parity tests
(``apex/transformer/functional/fused_softmax.py``, ``transformer/utils.py``;
reference test: ``tests/L0/run_transformer/test_fused_softmax.py``)."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax
from apex_tpu.transformer.utils import (gather_split_1d_tensor,
                                        split_tensor_into_1d_equal_chunks)

K = jr.PRNGKey(5)


def _mask_func(scores, mask):
    return jnp.where(mask, -1e30, scores)


class TestFusedScaleMaskSoftmax:
    def _ref(self, scores, mask, scale, causal):
        s = scores.astype(jnp.float32) * scale
        if causal:
            sq, sk = s.shape[-2], s.shape[-1]
            cm = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
            s = jnp.where(cm, s, -1e30)
        if mask is not None:
            s = jnp.where(mask, -1e30, s)
        return jax.nn.softmax(s, -1).astype(scores.dtype)

    @pytest.mark.parametrize("fusion", [True, False])
    def test_causal_matches_reference(self, fusion):
        scores = jr.normal(K, (2, 4, 128, 128), jnp.bfloat16)
        m = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            attn_mask_type=AttnMaskType.causal,
            scaled_masked_softmax_fusion=fusion,
            mask_func=None, softmax_in_fp32=True, scale=0.5)
        out = m(scores, None)
        assert out.dtype == scores.dtype
        np.testing.assert_allclose(
            out.astype(jnp.float32),
            self._ref(scores, None, 0.5, True).astype(jnp.float32),
            rtol=2e-2, atol=2e-3)

    @pytest.mark.parametrize("fusion", [True, False])
    def test_padding_mask_matches_reference(self, fusion):
        scores = jr.normal(K, (2, 4, 64, 128), jnp.bfloat16)
        mask = jr.bernoulli(jr.fold_in(K, 1), 0.3, (2, 1, 64, 128))
        m = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            attn_mask_type=AttnMaskType.padding,
            scaled_masked_softmax_fusion=fusion,
            mask_func=_mask_func, softmax_in_fp32=True, scale=None)
        out = m(scores, mask)
        np.testing.assert_allclose(
            out.astype(jnp.float32),
            self._ref(scores, mask, 1.0, False).astype(jnp.float32),
            rtol=2e-2, atol=2e-3)

    def test_no_sequence_cap(self):
        """The reference kernel refuses sk > 2048
        (``fused_softmax.py:166``); ours must not."""
        m = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            attn_mask_type=AttnMaskType.causal,
            scaled_masked_softmax_fusion=True,
            mask_func=None, softmax_in_fp32=True, scale=None)
        assert m.is_kernel_available(None, 1, 1, 4096, 4096)
        # unaligned softmax axis falls back, never errors
        assert not m.is_kernel_available(None, 1, 1, 100, 100)
        out = m(jr.normal(K, (1, 1, 100, 100), jnp.bfloat16), None)
        np.testing.assert_allclose(float(jnp.sum(out, -1).mean()), 1.0, rtol=1e-2)

    def test_padding_mask_never_dropped_without_mask_func(self):
        """mask_func=None must still apply the mask (the reference calls
        mask_func unconditionally; silently attending to padding is the
        worst failure mode)."""
        scores = jr.normal(K, (1, 1, 8, 128), jnp.bfloat16)
        mask = jnp.zeros((1, 1, 8, 128), bool).at[..., 64:].set(True)
        m = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            attn_mask_type=AttnMaskType.padding,
            scaled_masked_softmax_fusion=False,  # force the fallback
            mask_func=None, softmax_in_fp32=True, scale=None)
        out = m(scores, mask)
        assert float(jnp.max(out[..., 64:])) == 0.0

    def test_rectangular_causal_takes_fallback_consistently(self):
        """sq != sk causal: kernel ineligible (the reference kernel assumes
        square scores), and the fallback's triangle matches the kernel's
        top-left alignment at square shapes."""
        m = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            attn_mask_type=AttnMaskType.causal,
            scaled_masked_softmax_fusion=True,
            mask_func=None, softmax_in_fp32=True, scale=None)
        assert not m.is_kernel_available(None, 2, 4, 64, 128)
        out = m(jr.normal(K, (1, 1, 64, 128), jnp.bfloat16), None)
        # row 0 attends only to column 0 (top-left convention)
        np.testing.assert_allclose(float(out[0, 0, 0, 0]), 1.0, rtol=1e-3)
        assert float(jnp.max(out[0, 0, 0, 1:])) == 0.0

    def test_invalid_flag_combinations_raise(self):
        with pytest.raises(RuntimeError, match="both fp16 and bf16"):
            FusedScaleMaskSoftmax(True, True, AttnMaskType.causal, True,
                                  None, True, None)
        with pytest.raises(RuntimeError, match="fp32 when scaled"):
            FusedScaleMaskSoftmax(True, False, AttnMaskType.causal, True,
                                  None, False, 2.0)


class TestSplitGather1D:
    def test_roundtrip_over_tp(self):
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        x = jr.normal(K, (8, 16))

        def f(x):
            chunk = split_tensor_into_1d_equal_chunks(x, axis_name="tp")
            # each rank holds numel/4
            full = gather_split_1d_tensor(chunk, axis_name="tp")
            return full.reshape(x.shape)

        y = mesh_lib.shard_map(
            f, mesh=mesh, in_specs=(P(),), out_specs=P(),
        )(x)
        np.testing.assert_array_equal(y, x)

    def test_uneven_split_raises(self):
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        x = jnp.ones((3, 5))  # 15 elements, not divisible by 4
        with pytest.raises(ValueError, match="does not split evenly"):
            mesh_lib.shard_map(
                lambda x: split_tensor_into_1d_equal_chunks(x, axis_name="tp"),
                mesh=mesh, in_specs=(P(),), out_specs=P("tp"),
            )(x)
