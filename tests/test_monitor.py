"""Monitor subsystem: registry semantics, hook wiring, schema validation,
report aggregation, artifact honesty.

The fast tier-1 loop for the telemetry layer: emit → validate → report
round-trips in-process (no subprocesses, no mesh), plus the bench-parity
contract — `monitor report` must reproduce tokens/s from the same records
``bench.py`` emits — and the VERDICT r5 weak-#1 regression guard: no
artifact path can put ``nan`` inside a line/record that claims OK.
"""

import importlib.util
import io
import json
import os
import sys

import jax.numpy as jnp
import pytest

from apex_tpu import amp, monitor
from apex_tpu.monitor import report as monitor_report
from apex_tpu.monitor import schema as monitor_schema

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture
def registry():
    buf = io.StringIO()
    reg = monitor.enable(stream=buf)
    try:
        yield reg, buf
    finally:
        monitor.disable()


def records_of(buf: io.StringIO):
    # the clock_sync epoch record framing every enabled stream is
    # covered by tests/test_trace.py; the payload tests here count
    # only the records they emitted
    return [r for r in (json.loads(line)
                        for line in buf.getvalue().splitlines())
            if r.get("kind") != "clock_sync"]


class TestRegistry:
    def test_disabled_hooks_are_noops(self):
        assert not monitor.enabled()
        # none of these may touch their argument while disabled
        monitor.counter("x")
        monitor.gauge("y", 1.0)
        assert monitor.observe_scaler(object()) is None
        assert monitor.observe_grads(object()) is None
        assert monitor.observe_updates(object()) is None
        assert monitor.end_step() is None
        with monitor.timer("t"):
            pass

    def test_counters_gauges_timers(self, registry):
        reg, _ = registry
        reg.counter("c")
        reg.counter("c", 2)
        reg.gauge("g", 3.5)
        reg.gauge("g", 4.5)  # last value wins
        with reg.timer("t"):
            pass
        assert reg.counters["c"] == 3
        assert reg.gauges["g"] == 4.5
        assert reg.timers["t"][0] == 1
        assert reg.timers["t"][1] >= 0

    def test_step_records_carry_deltas(self, registry):
        reg, buf = registry
        reg.counter("collective/psum[dp]_calls", 5)
        reg.begin_step()
        reg.counter("collective/psum[dp]_calls", 2)
        rec = reg.end_step(tokens=128, dur_s=0.5)
        # only the in-window delta, not the lifetime total
        assert rec["counters"] == {"collective/psum[dp]_calls": 2}
        assert rec["step"] == 0
        reg.begin_step()
        rec2 = reg.end_step(dur_s=0.25)
        assert rec2["step"] == 1
        assert rec2["counters"] == {}
        assert len(records_of(buf)) == 2

    def test_counters_total_survive_pre_step_counting(self, registry):
        """Trace-time collective counts land during warm-up, BEFORE the
        first step window — the lifetime totals in the step record are how
        they reach the report."""
        reg, _ = registry
        reg.counter("collective/ppermute[pp]_calls", 11)  # "during tracing"
        reg.begin_step()
        rec = reg.end_step(dur_s=0.1)
        assert rec["counters"] == {}  # nothing inside the window
        assert rec["counters_total"]["collective/ppermute[pp]_calls"] == 11
        from apex_tpu.monitor.report import aggregate

        summary = aggregate([rec])
        assert summary["collectives"]["ppermute[pp]"]["calls"] == 11

    def test_repeated_end_step_does_not_double_count(self, registry):
        reg, _ = registry
        reg.begin_step()
        reg.counter("amp/overflow_steps", 1)
        rec1 = reg.end_step(dur_s=0.1)
        rec2 = reg.end_step(dur_s=0.1)  # no begin_step: fresh baseline
        assert rec1["counters"] == {"amp/overflow_steps": 1}
        assert rec2["counters"] == {}

    def test_enable_truncates_by_default(self, tmp_path):
        path = tmp_path / "events.jsonl"
        for _ in range(2):
            reg = monitor.enable(str(path))
            reg.emit_event("run")
            monitor.disable()
        # one run, one file: each enable() opens with its clock_sync
        assert len(path.read_text().splitlines()) == 2
        reg = monitor.enable(str(path), append=True)
        reg.emit_event("run")
        monitor.disable()
        assert len(path.read_text().splitlines()) == 4

    def test_report_aggregates_last_run_of_appended_file(self, tmp_path):
        from apex_tpu.monitor.report import aggregate, read_records

        path = tmp_path / "events.jsonl"
        for best_dur, tokens in ((0.01, 100), (0.02, 100)):
            reg = monitor.enable(str(path), append=True)
            reg.emit_meta(device_kind="cpu")
            reg.begin_step()
            reg.end_step(dur_s=best_dur, tokens=tokens)
            monitor.disable()
        summary = aggregate(read_records(path.read_text().splitlines()))
        # the stale (faster) first run must NOT leak into the headline
        assert summary["runs_in_file"] == 2
        assert summary["num_steps"] == 1
        assert summary["tokens_per_s"]["best"] == pytest.approx(100 / 0.02)

    def test_rank_tagging(self, registry):
        from apex_tpu.utils.logging import set_rank_info

        reg, _ = registry
        set_rank_info("dp0/pp1/cp0/tp0")
        try:
            rec = reg.emit_event("x")
        finally:
            set_rank_info("")
        assert rec["rank"] == "dp0/pp1/cp0/tp0"
        assert isinstance(rec["process"], int)

    def test_enable_from_env_path(self, tmp_path, monkeypatch):
        path = tmp_path / "events.jsonl"
        monkeypatch.setenv("APEX_TPU_MONITOR", str(path))
        reg = monitor.enable_from_env()
        try:
            assert reg is not None
            reg.emit_event("hello")
        finally:
            monitor.disable()
        assert monitor.validate_jsonl(path.read_text().splitlines()) == []


class TestHonesty:
    def test_success_record_with_nan_refused(self, registry):
        reg, _ = registry
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit("gate", name="g", ok=True,
                     metrics={"loss": float("nan")})

    def test_ok_status_with_inf_refused(self, registry):
        reg, _ = registry
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit("event", name="e", status="OK", value=float("inf"))

    def test_non_success_records_may_carry_nonfinite(self, registry):
        reg, buf = registry
        reg.begin_step()
        reg.end_step(dur_s=0.1, loss=float("nan"))  # diverged loss: allowed
        (rec,) = records_of(buf)
        assert rec["loss"] == "nan"  # stringified — the stream stays JSON

    def test_gate_metrics_rejects_nonfinite(self):
        with pytest.raises(ValueError, match="skipped"):
            monitor.gate_metrics({"x": float("nan")})

    def test_gate_metrics_skip_objects(self):
        out = monitor.gate_metrics(
            {"a": 1.5, "b": ("skipped", "needs n % 16 == 0")})
        assert out == {"a": 1.5,
                       "b": {"skipped": True, "reason": "needs n % 16 == 0"}}

    def test_validator_flags_stringified_nan_in_success(self):
        errs = monitor_schema.validate(
            {"schema": 1, "kind": "gate", "name": "g", "ok": True,
             "metrics": {"loss": "nan"}})
        assert any("nan" in e or "non-finite" in e for e in errs)


class TestHooks:
    def test_observe_scaler_matches_state(self, registry):
        reg, _ = registry
        s = amp.init_loss_scaler("dynamic", init_scale=2.0 ** 16)
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        m = monitor.observe_scaler(s)
        assert m == amp.scaler_metrics(s)
        assert reg.gauges["amp/loss_scale"] == 2.0 ** 15
        assert reg.gauges["amp/skipped_steps_total"] == 1
        # delta counting: the second observation adds only the new overflow
        s = amp.update_loss_scaler(s, jnp.asarray(False))
        monitor.observe_scaler(s)
        assert reg.counters["amp/overflow_steps"] == 1

    def test_observe_grads_and_updates(self, registry):
        reg, _ = registry
        g = {"w": jnp.asarray([3.0, 4.0]), "step": jnp.zeros((), jnp.int32)}
        assert monitor.observe_grads(g) == pytest.approx(5.0)
        assert monitor.observe_updates({"w": jnp.zeros((2,))}) == 0.0
        assert reg.gauges["optim/grad_norm"] == pytest.approx(5.0)
        assert reg.gauges["optim/update_norm"] == 0.0
        out = monitor.observe_optimizer_step(grads=g)
        assert out["grad_norm"] == pytest.approx(5.0)

    def test_bubble_fraction_matches_schedule_theory(self):
        # forward sweep is M*v + S - 1 chunk-ticks, S - 1 of them fill/drain
        # (tests/test_pipeline.py::TestBubbleUtilization measures the same
        # numbers from the schedule's validity masks)
        assert monitor.pipeline_bubble_fraction(8, 4, 1) == pytest.approx(
            3 / 11)
        assert monitor.pipeline_bubble_fraction(8, 4, 4) == pytest.approx(
            3 / 35)

    def test_record_pipeline_schedule(self, registry):
        reg, buf = registry
        monitor.record_pipeline_schedule(
            num_microbatches=8, pipeline_size=4, virtual_chunks=2,
            tick_bytes=1024, axis="pp")
        assert reg.gauges["pipeline/bubble_fraction"] == pytest.approx(3 / 19)
        assert reg.counters["collective/ppermute[pp]_calls"] == 19
        assert reg.counters["collective/ppermute[pp]_bytes"] == 19 * 1024
        (rec,) = records_of(buf)
        assert rec["name"] == "pipeline_schedule" and rec["ticks"] == 19
        assert rec["schedule"] == "1f1b" and rec["overlap_p2p"] is False

    def test_pipeline_cost_model_closed_forms(self, registry):
        """The unit-cost (F=B=W=1) full-step geometry: the autodiff
        schedule pays B+W on every backward tick; zb defers dW into an
        M·v real-items-only sweep — the (S−1)·W drain term is gone."""
        base = monitor.pipeline_cost_model(8, 4, 1, schedule="1f1b")
        zb = monitor.pipeline_cost_model(8, 4, 1, schedule="zb")
        assert base["total_units"] == 33 and zb["total_units"] == 30
        assert base["bubble_fraction"] == pytest.approx(9 / 33)
        assert zb["bubble_fraction"] == pytest.approx(6 / 30)
        # overlap: L=2 — fwd ticks M*v + 2(S-1) + 1, dW sweep unchanged
        ov = monitor.pipeline_cost_model(8, 4, 1, schedule="zb",
                                         overlap_p2p=True)
        assert ov["fwd_ticks"] == 8 + 2 * 3 + 1
        assert ov["bwd_dw_ticks"] == 8
        # recompute priced separately and honestly: zb = 1f1b + M*v
        assert zb["recompute_units"] == base["recompute_units"] + 8
        assert zb["collective_free_ticks"] == 8
        # the schedule-aware gauge/event carry the step bubble
        reg, buf = registry
        monitor.record_pipeline_schedule(
            num_microbatches=8, pipeline_size=4, schedule="zb")
        assert reg.gauges["pipeline/bubble_fraction_step"] == \
            pytest.approx(6 / 30)
        (rec,) = records_of(buf)
        assert rec["schedule"] == "zb"
        assert rec["bwd_dw_ticks"] == 8 and rec["bwd_dx_ticks"] == 11

    def test_count_collective_and_tree_bytes(self, registry):
        reg, _ = registry
        tree = {"a": jnp.zeros((4, 8), jnp.float32),
                "b": jnp.zeros((2,), jnp.bfloat16)}
        nbytes = monitor.tree_bytes(tree)
        assert nbytes == 4 * 8 * 4 + 2 * 2
        monitor.count_collective("psum", bytes=nbytes, axis="dp")
        assert reg.counters["collective/psum[dp]_bytes"] == nbytes


class TestRoundTrip:
    """emit → validate → report, the tier-1 loop of the ISSUE."""

    def _simulate(self, path):
        reg = monitor.enable(str(path))
        try:
            monitor.emit_meta(device_kind="TPU v5p",
                              model_flops_per_token=1e9,
                              batch=4, seq=256)
            monitor.record_pipeline_schedule(
                num_microbatches=8, pipeline_size=4, tick_bytes=64)
            scaler = amp.init_loss_scaler("dynamic", init_scale=2.0 ** 16,
                                          growth_interval=2)
            durs = [0.02, 0.0199, 0.0201, 0.0198]
            # overflow on step 1 (after the baseline observation on step 0),
            # then recovery and growth back at growth_interval=2
            finites = [True, False, True, True]
            for dur, finite in zip(durs, finites):
                monitor.begin_step()
                scaler = amp.update_loss_scaler(scaler, jnp.asarray(finite))
                monitor.observe_scaler(scaler)
                # the pattern a pipelined loop uses: time the blocking
                # fwd/bwd so the report can derive per-tick wall time
                monitor.observe_seconds("pipeline/fwd_bwd", dur * 0.8)
                monitor.end_step(dur_s=dur, tokens=4 * 256, loss=4.5)
            return durs
        finally:
            monitor.disable()

    def test_emit_validate_report(self, tmp_path):
        path = tmp_path / "events.jsonl"
        durs = self._simulate(path)
        lines = path.read_text().splitlines()
        assert monitor.validate_jsonl(lines) == []

        summary = monitor.aggregate(monitor_report.read_records(lines))
        assert summary["num_steps"] == 4
        # tokens/s headline = best step, the bench's min-of-passes rule
        expect = 4 * 256 / min(durs)
        assert summary["tokens_per_s"]["best"] == pytest.approx(
            expect, rel=5e-3)
        # MFU via the shared spec-peak table
        peak = monitor.PEAK_FLOPS_BY_DEVICE["TPU v5p"]
        assert summary["mfu"] == pytest.approx(1e9 * expect / peak, rel=1e-6)
        assert summary["overflow_rate"] == pytest.approx(1 / 4)
        assert summary["pipeline"]["bubble_fraction"] == pytest.approx(
            3 / 11, abs=1e-5)
        # per-(microbatch, stage) wall time: timed fwd/bwd calls / ticks
        expect_tick = sum(d * 0.8 for d in durs) / 4 / 11
        assert summary["pipeline"]["per_tick_wall_s"] == pytest.approx(
            expect_tick, rel=1e-6)
        # scaler halved on the overflow, then grew back at the interval
        assert summary["loss_scale_last"] == 2.0 ** 16

    def test_report_cli(self, tmp_path, capsys):
        path = tmp_path / "events.jsonl"
        self._simulate(path)
        assert monitor_report.main(["report", str(path)]) == 0
        out = capsys.readouterr().out
        assert "tokens/s" in out and "overflow" in out and "bubble" in out
        assert monitor_report.main(["report", str(path), "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["num_steps"] == 4


def _load_validate_tool():
    tool_path = os.path.join(os.path.dirname(__file__), "..", "tools",
                             "validate_metrics.py")
    spec = importlib.util.spec_from_file_location("validate_metrics",
                                                  tool_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestValidateTool:
    def test_clean_stream_passes(self, tmp_path):
        tool = _load_validate_tool()
        path = tmp_path / "events.jsonl"
        reg = monitor.enable(str(path))
        try:
            reg.emit_event("x")
            reg.begin_step()
            reg.end_step(dur_s=0.1)
        finally:
            monitor.disable()
        assert tool.validate_file(str(path)) == []

    def test_bench_wrapper_passes(self, tmp_path):
        tool = _load_validate_tool()
        wrapper = {"n": 5, "rc": 0, "tail": "...",
                   "parsed": {"metric": "m", "value": 1.0, "unit": "u"}}
        p = tmp_path / "BENCH_x.json"
        p.write_text(json.dumps(wrapper))
        assert tool.validate_file(str(p)) == []

    def test_nan_inside_ok_line_fails(self, tmp_path):
        """The VERDICT r5 weak-#1 artifact shape must be flagged."""
        tool = _load_validate_tool()
        wrapper = {"n_devices": 8, "rc": 0, "ok": True,
                   "tail": "dryrun_multichip(n=8): loss=4.37 "
                           "tpcp_4axis_loss=nan OK\n"}
        p = tmp_path / "MULTICHIP_x.json"
        p.write_text(json.dumps(wrapper))
        problems = tool.validate_file(str(p))
        assert problems and "non-finite" in problems[0]

    def test_skip_token_inside_ok_line_passes(self, tmp_path):
        tool = _load_validate_tool()
        wrapper = {"n_devices": 8, "rc": 0, "ok": True,
                   "tail": "dryrun_multichip(n=8): loss=4.37 "
                           "tpcp_4axis_loss=SKIP(needs-n%16==0) OK\n"}
        p = tmp_path / "MULTICHIP_x.json"
        p.write_text(json.dumps(wrapper))
        assert tool.validate_file(str(p)) == []

    def test_cli_over_fresh_stream_with_decode_records(self, tmp_path):
        """Tier-1 schema-drift gate (ISSUE 2 satellite): the validator CLI
        must pass a freshly emitted stream carrying every record kind —
        including the serving-bench ``decode`` records (OK and SKIP forms)
        — so a schema/emitter drift fails in-suite, not at bench time."""
        tool = _load_validate_tool()
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            monitor.emit_meta(device_kind="cpu", model_flops_per_token=1e6)
            monitor.begin_step()
            monitor.end_step(dur_s=0.01, tokens=128)
            monitor.emit_decode(
                "OK", tokens_per_s=5000.0, prefill_ms=12.5, spread_pct=0.4,
                naive_tokens_per_s=400.0, vs_naive=12.5, batch=4,
                prompt_len=64, new_tokens=32)
            monitor.emit_decode(
                "SKIP", reason="no TPU attached",
                vs_naive=("skipped", "no TPU attached"))
        finally:
            monitor.disable()
        assert tool.main([str(path)]) == 0

        # drift guard: an OK decode record carrying nan (hand-forged past
        # the emitter) must fail the CLI
        bad = next(r for r in (json.loads(ln)
                               for ln in path.read_text().splitlines())
                   if r.get("kind") == "decode" and r["status"] == "OK")
        bad["tokens_per_s"] = "nan"
        bad_path = tmp_path / "bad.jsonl"
        bad_path.write_text(json.dumps(bad) + "\n")
        assert tool.main([str(bad_path)]) == 1

    def test_repo_bench_artifacts_validate(self):
        tool = _load_validate_tool()
        root = os.path.join(os.path.dirname(__file__), "..")
        bench_files = sorted(
            f for f in os.listdir(root)
            if f.startswith("BENCH_") and f.endswith(".json"))
        assert bench_files, "repo lost its bench artifacts"
        for name in bench_files:
            assert tool.validate_file(os.path.join(root, name)) == [], name


class TestLoggingSatellite:
    """The logging fixes riding with the monitor PR: APEX_TPU_LOG_LEVEL is
    re-applied on every get_logger call, and the rank fallback can come
    from jax.process_index() once the backend is up."""

    def test_env_level_reapplied_after_first_configuration(self, monkeypatch):
        import logging

        from apex_tpu.utils.logging import get_logger

        name = "apex_tpu.test_monitor.env_level"
        monkeypatch.delenv("APEX_TPU_LOG_LEVEL", raising=False)
        assert get_logger(name).level == logging.WARNING
        monkeypatch.setenv("APEX_TPU_LOG_LEVEL", "DEBUG")
        assert get_logger(name).level == logging.DEBUG  # took effect late
        monkeypatch.setenv("APEX_TPU_LOG_LEVEL", "ERROR")
        assert get_logger(name).level == logging.ERROR

    def test_explicit_level_pins_against_env(self, monkeypatch):
        import logging

        from apex_tpu.utils.logging import get_logger

        name = "apex_tpu.test_monitor.pinned"
        get_logger(name, level=logging.INFO)
        monkeypatch.setenv("APEX_TPU_LOG_LEVEL", "CRITICAL")
        assert get_logger(name).level == logging.INFO

    def test_process_index_from_jax_when_backend_up(self):
        import jax

        import apex_tpu.utils.logging as log_util

        log_util._PROCESS_INDEX = None  # drop the cache
        try:
            jax.devices()  # backend definitely initialized now
            assert log_util.process_index() == jax.process_index()
        finally:
            log_util._PROCESS_INDEX = None

    def test_rank_filter_uses_fallback(self):
        import logging

        from apex_tpu.utils.logging import RankInfoFilter, get_rank_info

        assert get_rank_info() == ""  # no mesh in this test
        record = logging.LogRecord("n", logging.INFO, "p", 1, "m", (), None)
        assert RankInfoFilter().filter(record)
        assert record.rank_info.startswith("p")


class TestGateReporting:
    """__graft_entry__'s gate artifact: SKIP sentinels, schema'd record."""

    def test_report_gate_renders_skips_not_nan(self, capsys):
        import __graft_entry__ as graft

        graft._report_gate(
            4, dp=2, pp=2, tp=1, cp=2,
            loss=4.5, moe_4axis_loss=4.4,
            cp_pipe_loss=4.3,
            t5_loss=18.8,
            tpcp_4axis_loss=graft._SKIP("needs n_devices % 16 == 0"),
            moe_16wide_loss=4.31,
            ring_vs_flash=3e-7,
            ring_bias_vs_flash=graft._SKIP("16-wide respawn timed out"),
            zb_vs_1f1b=0.0,
        )
        out = capsys.readouterr().out
        gate_line = [l for l in out.splitlines() if l.endswith(" OK")][0]
        assert "nan" not in gate_line
        assert "tpcp_4axis_loss=SKIP(needs-n_devices-%-16-==-0)" in gate_line
        assert "ring_bias_vs_flash=SKIP(16-wide-respawn-timed-out)" in \
            gate_line
        assert "zb_vs_1f1b=0.00e+00" in gate_line  # the ISSUE-8 witness
        json_line = [l for l in out.splitlines()
                     if l.startswith("MULTICHIP_GATE ")][0]
        record = json.loads(json_line[len("MULTICHIP_GATE "):])
        assert monitor.validate(record) == []
        assert record["metrics"]["tpcp_4axis_loss"] == {
            "skipped": True, "reason": "needs n_devices % 16 == 0"}
        assert record["metrics"]["loss"] == 4.5

    def test_report_gate_refuses_nan_measurement(self, capsys):
        import __graft_entry__ as graft

        with pytest.raises(ValueError, match="skipped"):
            graft._report_gate(
                4, dp=2, pp=2, tp=1, cp=2,
                loss=float("nan"), moe_4axis_loss=4.4, cp_pipe_loss=4.3,
                t5_loss=18.8, tpcp_4axis_loss=graft._SKIP("x"),
                ring_vs_flash=3e-7,
            )
        assert " OK" not in capsys.readouterr().out


class TestTPCollectiveCounts:
    """ISSUE 5 satellite: the tensor-parallel mappings/layers collectives
    emit ``count_collective`` (bytes + axis) like ``all_reduce_gradients``
    and the pipeline ``_rotate`` already do — the tp axis shows up in
    ``monitor report``'s collective traffic line. Counting is trace-time:
    one un-jitted shard_map call registers the counters."""

    def _mesh(self):
        from apex_tpu.parallel import mesh as mesh_lib
        return mesh_lib.make_mesh(tensor_model_parallel_size=4)

    def test_sp_layer_collectives_counted(self, registry):
        import jax.random as jr
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel import mesh as mesh_lib
        from apex_tpu.transformer import tensor_parallel as tp_lib

        reg, _ = registry
        mesh = self._mesh()
        col = tp_lib.ColumnParallelLinear(8, 16, tp_size=4, bias=False,
                                          sequence_parallel=True)
        row = tp_lib.RowParallelLinear(16, 8, tp_size=4, bias=False,
                                       sequence_parallel=True)
        x = jr.normal(jr.PRNGKey(0), (4, 2, 8))
        wc = jr.normal(jr.PRNGKey(1), (16, 8))
        wr = jr.normal(jr.PRNGKey(2), (8, 16))
        mesh_lib.shard_map(
            lambda x, wc, wr: row({"weight": wr}, col({"weight": wc}, x)),
            mesh=mesh,
            in_specs=(P("tp"), P("tp", None), P(None, "tp")),
            out_specs=P("tp"),
        )(x, wc, wr)
        c = reg.counters
        assert c.get("collective/all_gather[tp]_calls", 0) >= 1
        assert c.get("collective/all_gather[tp]_bytes", 0) > 0
        assert c.get("collective/psum_scatter[tp]_calls", 0) >= 1
        assert c.get("collective/psum_scatter[tp]_bytes", 0) > 0

    def test_mappings_psum_counted(self, registry):
        import jax
        import jax.random as jr
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel import mesh as mesh_lib
        from apex_tpu.transformer import tensor_parallel as tp_lib

        reg, _ = registry
        mesh = self._mesh()
        x = jr.normal(jr.PRNGKey(3), (4, 8))

        def f(x):
            # forward psum (reduce_from) + backward psum (copy_to's VJP)
            y = tp_lib.reduce_from_tensor_model_parallel_region(x, "tp")
            return jax.grad(lambda x: (
                tp_lib.copy_to_tensor_model_parallel_region(x, "tp") ** 2
            ).sum())(y)

        mesh_lib.shard_map(f, mesh=mesh, in_specs=P(), out_specs=P())(x)
        assert reg.counters.get("collective/psum[tp]_calls", 0) >= 2

    def test_overlap_ring_ppermute_counted(self, registry):
        import jax.random as jr
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel import mesh as mesh_lib
        from apex_tpu.transformer import tensor_parallel as tp_lib

        reg, _ = registry
        mesh = self._mesh()
        col = tp_lib.ColumnParallelLinear(8, 16, tp_size=4, bias=False,
                                          sequence_parallel=True,
                                          overlap_comm=True)
        x = jr.normal(jr.PRNGKey(4), (4, 2, 8))
        wc = jr.normal(jr.PRNGKey(5), (16, 8))
        mesh_lib.shard_map(
            lambda x, wc: col({"weight": wc}, x), mesh=mesh,
            in_specs=(P("tp"), P("tp", None)), out_specs=P("tp"))(x, wc)
        c = reg.counters
        # tp=4 bidirectional ag ring: 2 fwd + 1 bwd ppermute steps
        assert c.get("collective/ppermute[tp]_calls", 0) >= 3
        assert c.get("collective/ppermute[tp]_bytes", 0) > 0
        # the overlapped path replaced the blocking gather entirely
        assert "collective/all_gather[tp]_calls" not in c


class TestTPOverlapRecords:
    """The ``tp_overlap`` bench record (``bench.py --tp-overlap``):
    overlapped vs blocking boundary collectives — same status/honesty
    contract as the decode and longseq_bias records."""

    def test_emit_roundtrip_and_validation(self, tmp_path):
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            rec = monitor.emit_tp_overlap(
                "OK", tokens_per_s=61000.0, tokens_per_s_blocking=52000.0,
                vs_blocking=1.173, tp=4, batch=8, seq=1024,
                sequence_parallel=True, spread_pct=0.4,
                pass_times_ms=[134.2, 134.5, 134.9], backend="tpu")
            assert monitor.validate(rec) == []
        finally:
            monitor.disable()
        assert monitor.validate_jsonl(path.read_text().splitlines()) == []

    def test_ok_with_nan_refused_and_skip_needs_reason(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit_tp_overlap("OK", tokens_per_s=float("nan"))
        with pytest.raises(ValueError, match="reason"):
            reg.emit_tp_overlap("SKIP")
        rec = reg.emit_tp_overlap(
            "SKIP", reason="cpu smoke run",
            vs_blocking=("skipped", "cpu smoke run"))
        assert rec["vs_blocking"] == {"skipped": True,
                                      "reason": "cpu smoke run"}
        assert monitor.validate(rec) == []
        # the validator enforces the reason on external streams too
        bare = {k: v for k, v in rec.items() if k != "reason"}
        assert any("reason" in e for e in monitor.validate(bare))

    def test_report_aggregates_and_renders(self):
        reg = monitor.MetricsRegistry()
        ok = reg.emit_tp_overlap(
            "OK", tokens_per_s=61000.0, tokens_per_s_blocking=52000.0,
            vs_blocking=1.173, tp=4, batch=8, seq=1024)
        summary = monitor_report.aggregate([ok])
        assert summary["tp_overlap"]["vs_blocking"] == 1.173
        text = monitor_report.render(summary)
        assert "tp-overlap" in text and "1.17x vs blocking" in text
        skip = reg.emit_tp_overlap("SKIP", reason="no TPU")
        text = monitor_report.render(monitor_report.aggregate([skip]))
        assert "tp-overlap  SKIP(no TPU)" in text


@pytest.mark.slow
class TestTPOverlapBenchLeg:
    def test_bench_tp_overlap_emits_valid_skip_record_off_tpu(
            self, tmp_path):
        """The tp-overlap leg end-to-end at smoke scale: off-TPU it runs
        both impls on the virtual mesh and must print/emit an explicit
        SKIP record — schema-valid, no nan — that the validator CLI
        accepts."""
        import subprocess
        root = os.path.join(os.path.dirname(__file__), "..")
        path = tmp_path / "tpoverlap.jsonl"
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   APEX_TPU_MONITOR=str(path))
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "bench.py"),
             "--tp-overlap"],
            capture_output=True, text=True, env=env, cwd=root, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        record = json.loads(proc.stdout.strip().splitlines()[-1])
        assert record["kind"] == "tp_overlap"
        assert record["status"] == "SKIP" and record["reason"]
        assert record["tokens_per_s"] > 0
        assert record["tokens_per_s_blocking"] > 0
        assert monitor.validate(record) == []
        tool = _load_validate_tool()
        assert tool.main([str(path)]) == 0


class TestLongseqBiasRecords:
    """The ``longseq_bias`` bench record (``bench.py --longseq-bias``):
    in-kernel bucketed bias vs the materialized baseline — same status/
    honesty contract as the decode record."""

    def test_emit_roundtrip_and_validation(self, tmp_path):
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            rec = monitor.emit_longseq_bias(
                "OK", tokens_per_s=52000.0,
                tokens_per_s_materialized=31000.0, vs_materialized=1.677,
                hbm_peak_mb=900.5, hbm_peak_materialized_mb=2400.0,
                bias_bytes=768, bias_bytes_materialized=1610612736,
                seq=8192, batch=1, heads=6, head_dim=128, num_buckets=32,
                causal=False, spread_pct=0.3)
            assert monitor.validate(rec) == []
        finally:
            monitor.disable()
        assert monitor.validate_jsonl(path.read_text().splitlines()) == []

    def test_ok_with_nan_refused_and_skip_needs_reason(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit_longseq_bias("OK", tokens_per_s=float("nan"))
        with pytest.raises(ValueError, match="reason"):
            reg.emit_longseq_bias("SKIP")
        rec = reg.emit_longseq_bias(
            "SKIP", reason="no TPU",
            hbm_peak_mb=("skipped", "no memory_stats"))
        assert rec["hbm_peak_mb"] == {"skipped": True,
                                      "reason": "no memory_stats"}
        assert monitor.validate(rec) == []
        # the validator enforces the reason too (external streams)
        bare = {k: v for k, v in rec.items() if k != "reason"}
        assert any("reason" in e for e in monitor.validate(bare))


@pytest.mark.slow
class TestLongseqBiasBenchLeg:
    def test_bench_longseq_bias_emits_valid_skip_record_off_tpu(
            self, tmp_path):
        """The long-seq bias leg end-to-end at smoke scale: off-TPU it
        must print/emit an explicit SKIP record — schema-valid, no nan —
        and the stream must pass the validator CLI."""
        import subprocess
        root = os.path.join(os.path.dirname(__file__), "..")
        path = tmp_path / "longseq.jsonl"
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   APEX_TPU_MONITOR=str(path))
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "bench.py"),
             "--longseq-bias"],
            capture_output=True, text=True, env=env, cwd=root, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        record = json.loads(proc.stdout.strip().splitlines()[-1])
        assert record["kind"] == "longseq_bias"
        assert record["status"] == "SKIP" and record["reason"]
        assert record["hbm_peak_mb"]["skipped"] is True
        assert monitor.validate(record) == []
        tool = _load_validate_tool()
        assert tool.main([str(path)]) == 0


class TestSpans:
    """The step-anatomy span API (monitor.spans): host enter/exit records
    riding the JSONL stream, named-scope join keys into device traces,
    near-no-op when disabled, ``traced`` honesty inside jit."""

    def test_disabled_span_is_noop(self):
        assert not monitor.enabled()
        with monitor.span("step", step=0):
            pass
        assert monitor.span_path() == ""

    def test_span_records_path_time_and_attrs(self, registry):
        reg, buf = registry
        with monitor.span("step", step=3):
            assert monitor.span_path() == "step"
            with monitor.span("fwd_bwd"):
                assert monitor.span_path() == "step/fwd_bwd"
        assert monitor.span_path() == ""
        recs = records_of(buf)
        assert [r["name"] for r in recs] == ["step/fwd_bwd", "step"]
        for r in recs:
            assert r["kind"] == "span"
            assert r["dur_ns"] >= 0 and r["t0_ns"] > 0
            assert "traced" not in r  # host phase
            assert monitor.validate(r) == []
        assert recs[1]["step"] == 3
        # nesting: the inner window is inside the outer one
        assert recs[0]["t0_ns"] >= recs[1]["t0_ns"]

    def test_traced_span_is_flagged(self, registry):
        import jax
        import jax.numpy as jnp

        reg, buf = registry

        def f(x):
            with monitor.span("fwd_bwd"):
                return x * 2

        jax.jit(f)(jnp.ones(4))
        spans = [r for r in records_of(buf) if r["kind"] == "span"]
        assert spans and all(s["traced"] is True for s in spans)
        assert all(monitor.validate(s) == [] for s in spans)

    def test_collective_span_attrs_and_none_axis(self, registry):
        import jax.numpy as jnp

        reg, buf = registry
        with monitor.collective_span("psum", jnp.zeros((4, 8)), "tp"):
            pass
        with monitor.collective_span("psum", jnp.zeros((4, 8)), None):
            pass  # tp=1 fallthrough: no record
        spans = [r for r in records_of(buf) if r["kind"] == "span"]
        assert len(spans) == 1
        s = spans[0]
        assert s["name"] == "psum_tp"
        assert s["coll"] == "psum" and s["axis"] == "tp"
        assert s["bytes"] == 4 * 8 * 4

    def test_mappings_emit_collective_spans(self, registry):
        import jax.random as jr
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel import mesh as mesh_lib
        from apex_tpu.transformer import tensor_parallel as tp_lib

        reg, buf = registry
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        x = jr.normal(jr.PRNGKey(3), (4, 8))
        mesh_lib.shard_map(
            lambda x: tp_lib.reduce_from_tensor_model_parallel_region(
                x, "tp"),
            mesh=mesh, in_specs=P(), out_specs=P())(x)
        spans = [r for r in records_of(buf) if r["kind"] == "span"]
        psums = [s for s in spans if s["name"].endswith("psum_tp")]
        assert psums, spans
        assert psums[0]["coll"] == "psum"
        assert psums[0]["bytes"] > 0
        assert psums[0]["traced"] is True  # shard_map traces the fn

    def test_overlap_ring_emits_ring_span(self, registry):
        import jax.random as jr
        from jax.sharding import PartitionSpec as P

        from apex_tpu.ops.collective_matmul import all_gather_matmul
        from apex_tpu.parallel import mesh as mesh_lib

        reg, buf = registry
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        x = jr.normal(jr.PRNGKey(0), (4, 2, 8))
        w = jr.normal(jr.PRNGKey(1), (4, 8))
        mesh_lib.shard_map(
            lambda x, w: all_gather_matmul(x, w, axis_name="tp"),
            mesh=mesh, in_specs=(P("tp"), P("tp", None)),
            out_specs=P(None, None, "tp"))(x, w)
        spans = [r for r in records_of(buf) if r["kind"] == "span"]
        rings = [s for s in spans if "ag_matmul_ring_tp" in s["name"]]
        assert rings, spans
        assert rings[0]["coll"] == "ag_matmul_ring"
        # per-hop payload: the local (1, 2, 8) fp32 shard
        assert rings[0]["bytes"] == 1 * 2 * 8 * 4


class TestProfileRecord:
    """The ``profile`` bench record (``bench.py --profile``): same
    status/honesty contract as decode/longseq_bias/tp_overlap."""

    def test_emit_roundtrip_and_validation(self, tmp_path):
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            rec = monitor.emit_profile(
                "OK", steps=5, compute_pct=71.2,
                collective_exposed_pct=9.1, bubble_pct=12.4,
                host_gap_pct=7.3, step_wall_ms=177.1,
                tokens_per_s=115000.0, costdb_collective_rows=6,
                costdb_gemm_classes=4, backend="tpu")
            assert monitor.validate(rec) == []
        finally:
            monitor.disable()
        assert monitor.validate_jsonl(path.read_text().splitlines()) == []

    def test_ok_with_nan_refused_and_skip_needs_reason(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit_profile("OK", compute_pct=float("nan"))
        with pytest.raises(ValueError, match="reason"):
            reg.emit_profile("SKIP")
        rec = reg.emit_profile(
            "SKIP", reason="host-only trace",
            compute_pct=("skipped", "host-only trace"))
        assert rec["compute_pct"] == {"skipped": True,
                                      "reason": "host-only trace"}
        assert monitor.validate(rec) == []
        bare = {k: v for k, v in rec.items() if k != "reason"}
        assert any("reason" in e for e in monitor.validate(bare))


def _write_synthetic_trace(tmp_path, events):
    import gzip

    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    os.makedirs(run)
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def _anatomy_fixture(tmp_path):
    """One host span stream + one device trace with hand-checkable
    anatomy: step 0 wall 120 us (compute 70, exposed collective 20,
    bubble 10, host gap 20), step 1 wall 100 us (compute 50, exposed 20,
    bubble 10, host gap 20)."""
    meta = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
    ]
    def X(name, ts, dur, cat=None):
        e = {"ph": "X", "pid": 3, "tid": 3, "ts": ts, "dur": dur,
             "name": name, "args": {}}
        if cat:
            e["args"]["hlo_category"] = cat
        return e
    events = meta + [
        X("step/fwd_bwd/dot.1", 0.0, 60.0),
        X("step/fwd_bwd/all-gather.2", 40.0, 40.0, "all-gather"),
        X("step/optimizer/fusion.3", 90.0, 10.0),
        X("step/fwd_bwd/dot.1", 1000.0, 50.0),
        X("step/fwd_bwd/all-gather.2", 1060.0, 20.0, "all-gather"),
    ]
    logdir = _write_synthetic_trace(tmp_path / "trace", events)
    stream = tmp_path / "events.jsonl"
    reg = monitor.enable(str(stream))
    try:
        for i, dur_us in enumerate((120, 100)):
            reg.emit("span", name="step", step=i,
                     t0_ns=1_000_000 * (1 + i), dur_ns=dur_us * 1000)
        reg.emit("span", name="step/fwd_bwd", t0_ns=1, dur_ns=1,
                 traced=True)
    finally:
        monitor.disable()
    return str(stream), logdir


class TestAnatomyReportCLI:
    """`monitor report --anatomy` must reproduce the per-step breakdown
    from a synthetic host+device fixture exactly (the ISSUE acceptance
    line)."""

    def test_report_anatomy_exact(self, tmp_path, capsys):
        stream, logdir = _anatomy_fixture(tmp_path)
        rc = monitor_report.main(["report", stream, "--anatomy",
                                  "--trace", logdir, "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        rows = summary["anatomy"]
        assert len(rows) == 2
        r0, r1 = rows
        assert r0["compute_pct"] == pytest.approx(100 * 70 / 120)
        assert r0["collective_exposed_pct"] == pytest.approx(
            100 * 20 / 120)
        assert r0["bubble_pct"] == pytest.approx(100 * 10 / 120)
        assert r0["host_gap_pct"] == pytest.approx(100 * 20 / 120)
        assert r1["compute_pct"] == pytest.approx(50.0)
        assert r1["collective_exposed_pct"] == pytest.approx(20.0)
        assert r1["bubble_pct"] == pytest.approx(10.0)
        assert r1["host_gap_pct"] == pytest.approx(20.0)
        # the four components cover the wall exactly
        for r in rows:
            assert (r["compute_pct"] + r["collective_exposed_pct"]
                    + r["bubble_pct"] + r["host_gap_pct"]) == \
                pytest.approx(100.0)

    def test_report_anatomy_text_table(self, tmp_path, capsys):
        stream, logdir = _anatomy_fixture(tmp_path)
        rc = monitor_report.main(["report", stream, "--anatomy",
                                  "--trace", logdir])
        assert rc == 0
        out = capsys.readouterr().out
        assert "step anatomy" in out
        assert "/device:TPU:0" in out

    def test_report_anatomy_missing_trace_exits_2(self, tmp_path, capsys):
        stream, _ = _anatomy_fixture(tmp_path)
        rc = monitor_report.main(["report", stream, "--anatomy",
                                  "--trace", str(tmp_path / "nope")])
        assert rc == 2
        assert "searched" in capsys.readouterr().err


class TestValidateProfileArtifacts:
    """`tools/validate_metrics.py --profile/--costdb` gate the new
    artifacts like bench/gate records."""

    def test_costdb_flag_accepts_and_rejects(self, tmp_path):
        from apex_tpu.prof.calibrate import build_costdb, write_costdb

        tool = _load_validate_tool()
        db = build_costdb([], [], device_kind="TPU v5p", backend="tpu")
        p = tmp_path / "costdb.json"
        write_costdb(str(p), db)
        assert tool.main(["--costdb", str(p)]) == 0
        other = tmp_path / "bench.json"
        other.write_text(json.dumps({"metric": "m", "value": 1.0,
                                     "unit": "u"}))
        assert tool.main(["--costdb", str(other)]) == 1

    def test_pipeline_record_emits_validates_and_reports(self, tmp_path,
                                                         capsys):
        """Schema-drift gate for the ``pipeline`` bench record: freshly
        emitted OK and SKIP forms pass the validator CLI (content AND
        ``--pipeline`` forced dispatch), a hand-forged nan fails, a
        reason-free SKIP fails, and ``monitor report`` renders the
        pipeline-bench line from the same stream."""
        tool = _load_validate_tool()
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            monitor.emit_pipeline(
                "OK", schedule="zb", pipeline_size=4, virtual_chunks=1,
                num_microbatches=8, overlap_p2p=False,
                tokens_per_s=90000.0, tokens_per_s_1f1b=82000.0,
                vs_1f1b=1.0976, bubble_pct=14.2, bubble_pct_1f1b=24.8,
                bubble_pct_geometry=20.0, bubble_pct_1f1b_geometry=27.27,
                p2p_bytes_per_step=1 << 20, jit_cache_ok=True)
            monitor.emit_pipeline(
                "SKIP", reason="no TPU attached", schedule="zb",
                bubble_pct=("skipped", "no device trace"),
                bubble_pct_geometry=20.0)
        finally:
            monitor.disable()
        assert tool.main([str(path)]) == 0
        assert tool.main(["--pipeline", str(path)]) == 0

        pipes = [r for r in (json.loads(ln)
                             for ln in path.read_text().splitlines())
                 if r.get("kind") == "pipeline"]
        bad = dict(pipes[0])
        bad["tokens_per_s"] = "nan"
        bad_path = tmp_path / "bad.jsonl"
        bad_path.write_text(json.dumps(bad) + "\n")
        assert tool.main([str(bad_path)]) == 1
        noreason = dict(pipes[1])
        del noreason["reason"]
        nr_path = tmp_path / "nr.jsonl"
        nr_path.write_text(json.dumps(noreason) + "\n")
        assert tool.main([str(nr_path)]) == 1
        # a stream without any pipeline record fails the forced dispatch
        bare = tmp_path / "bare.jsonl"
        monitor.enable(str(bare))
        try:
            monitor.emit_event("x")
        finally:
            monitor.disable()
        assert tool.main(["--pipeline", str(bare)]) == 1

        assert monitor_report.main(["report", str(path)]) == 0
        out = capsys.readouterr().out
        assert "pipeline-bench" in out and "SKIP(no TPU attached)" in out
        summary = monitor_report.aggregate(
            monitor_report.read_records(open(path)))
        assert summary["pipeline_bench"]["status"] == "SKIP"

    def test_profile_flag_requires_profile_record(self, tmp_path):
        tool = _load_validate_tool()
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            monitor.emit_profile("SKIP", reason="host-only trace")
        finally:
            monitor.disable()
        assert tool.main(["--profile", str(path)]) == 0
        bare = tmp_path / "bare.jsonl"
        monitor.enable(str(bare))
        try:
            monitor.emit_event("x")
        finally:
            monitor.disable()
        assert tool.main(["--profile", str(bare)]) == 1


class TestPipelineBenchLeg:
    def test_bench_pipeline_emits_valid_skip_record_off_tpu(
            self, tmp_path, monkeypatch, capsys):
        """The pipeline-schedule leg end-to-end at smoke scale,
        in-process: off-TPU the record must be an explicit SKIP —
        schema-valid, no nan — carrying both schedules' smoke tokens/s,
        the geometry bubbles with zb < 1f1b, skip-objects for the
        measured bubbles, and the recompile-free witness."""
        import importlib.util

        monkeypatch.delenv("APEX_TPU_MONITOR", raising=False)
        root = os.path.join(os.path.dirname(__file__), "..")
        spec = importlib.util.spec_from_file_location(
            "bench_pipeline_leg", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        try:
            bench.pipeline_main()
        finally:
            monitor.disable()
        record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert record["kind"] == "pipeline"
        assert record["status"] == "SKIP" and record["reason"]
        assert record["schedule"] == "zb"
        assert record["tokens_per_s"] > 0
        assert record["tokens_per_s_1f1b"] > 0
        assert record["bubble_pct"]["skipped"] is True
        assert (record["bubble_pct_geometry"]
                < record["bubble_pct_1f1b_geometry"])
        assert record["jit_cache_ok"] is True
        assert monitor.validate(record) == []


class TestProfileBenchLeg:
    def test_bench_profile_emits_valid_skip_record_off_tpu(
            self, tmp_path, monkeypatch, capsys):
        """The step-anatomy leg end-to-end at smoke scale, in-process
        (the subprocess import tax would blow the tier-1 budget): off-TPU
        the trace is host-only, so the record must be an explicit SKIP —
        schema-valid, no nan — with the costdb and merged timeline
        artifacts written and validator-clean."""
        import importlib.util

        monkeypatch.delenv("APEX_TPU_MONITOR", raising=False)
        root = os.path.join(os.path.dirname(__file__), "..")
        spec = importlib.util.spec_from_file_location(
            "bench_profile_leg", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        logdir = str(tmp_path / "prof")
        try:
            bench.profile_main(["--logdir", logdir])
        finally:
            monitor.disable()
        record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert record["kind"] == "profile"
        assert record["status"] == "SKIP" and record["reason"]
        assert record["steps"] >= 1
        assert record["step_wall_ms"] > 0
        assert record["compute_pct"]["skipped"] is True
        assert monitor.validate(record) == []
        assert os.path.exists(record["costdb_path"])
        assert os.path.exists(record["timeline_path"])
        tool = _load_validate_tool()
        assert tool.main(["--costdb", record["costdb_path"]]) == 0
        assert tool.main([os.path.join(logdir, "events.jsonl")]) == 0
