"""Tensor-parallel layer tests on the virtual 8-device mesh.

Mirrors the reference's ``tests/L0/run_transformer/test_layers.py``,
``test_mapping.py``, ``test_cross_entropy.py``: every sharded computation is
compared against the unsharded jnp equivalent.
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import tensor_parallel as tp

K = jr.PRNGKey(7)


def tp_mesh(tp_size=4):
    return mesh_lib.make_mesh(tensor_model_parallel_size=tp_size)


class TestMappings:
    def test_copy_identity_fwd_allreduce_bwd(self):
        mesh = tp_mesh(4)
        x = jr.normal(K, (4, 8))

        def per_shard_grad(x):
            # gradient of a *local* loss through the copy: the copy's
            # backward must psum the per-shard cotangents (2x each) over
            # the 4 tp shards → 8x on every shard
            local = lambda x: jnp.sum(tp.copy_to_tensor_model_parallel_region(x) ** 2)
            return jax.grad(local)(x)

        g = mesh_lib.shard_map(per_shard_grad, mesh=mesh, in_specs=P(), out_specs=P())(x)
        np.testing.assert_allclose(g, 8 * x, rtol=1e-6)

    def test_scatter_gather_roundtrip(self):
        mesh = tp_mesh(4)
        x = jr.normal(K, (2, 16))

        def run(x):
            s = tp.scatter_to_tensor_model_parallel_region(x)
            return tp.gather_from_tensor_model_parallel_region(s)

        y = mesh_lib.shard_map(run, mesh=mesh, in_specs=P(), out_specs=P())(x)
        np.testing.assert_allclose(y, x, rtol=1e-6)

    def test_gather_grad_is_split(self):
        mesh = tp_mesh(4)
        x = jr.normal(K, (2, 4))  # per-shard input

        def loss(x):
            g = tp.gather_from_tensor_model_parallel_region(x)  # (2, 16)
            w = jnp.arange(16.0)
            return jnp.sum(g * w)

        run = mesh_lib.shard_map(
            lambda x: jax.grad(loss)(x), mesh=mesh,
            in_specs=P(None, "tp"), out_specs=P(None, "tp"),
        )
        gx = run(jnp.tile(x, (1, 4)))
        # each shard's grad is its slice of w
        w = jnp.arange(16.0)
        np.testing.assert_allclose(gx, jnp.broadcast_to(w, (2, 16)), rtol=1e-6)


class TestColumnRowParallel:
    def test_column_then_row_matches_dense(self):
        """The canonical Megatron MLP pattern: Column(gather=False) →
        Row(input_is_parallel=True) must equal the unsharded two-layer MLP."""
        tp_size = 4
        mesh = tp_mesh(tp_size)
        din, dhid = 32, 64
        col = tp.ColumnParallelLinear(din, dhid, tp_size=tp_size, bias=True)
        row = tp.RowParallelLinear(dhid, din, tp_size=tp_size, bias=True)

        # build full weights then shard, so we can compare against dense
        wc = jr.normal(K, (dhid, din)) * 0.1
        bc = jr.normal(jr.fold_in(K, 1), (dhid,)) * 0.1
        wr = jr.normal(jr.fold_in(K, 2), (din, dhid)) * 0.1
        br = jr.normal(jr.fold_in(K, 3), (din,)) * 0.1
        x = jr.normal(jr.fold_in(K, 4), (8, din))

        def run(x, wc, bc, wr, br):
            h = col({"weight": wc, "bias": bc}, x)
            h = jnp.maximum(h, 0)
            return row({"weight": wr, "bias": br}, h)

        y = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=(P(), P("tp", None), P("tp"), P(None, "tp"), P()),
            out_specs=P(),
        )(x, wc, bc, wr, br)

        ref = jnp.maximum(x @ wc.T + bc, 0) @ wr.T + br
        np.testing.assert_allclose(y, ref, rtol=2e-5, atol=2e-5)

    def test_headwise_matches_flat_call(self):
        """Column/Row ``headwise`` (the transpose-free attention-layout
        projections) == ``__call__`` + explicit reshapes/transposes, under a
        real tp axis with bias and grads."""
        tp_size = 2
        mesh = tp_mesh(tp_size)
        b, s, H, heads, d = 2, 8, 16, 4, 4  # h*d == H
        h_loc = heads // tp_size
        col = tp.ColumnParallelLinear(H, 3 * H, tp_size=tp_size, bias=True)
        row = tp.RowParallelLinear(H, H, tp_size=tp_size, bias=True)
        wc = jr.normal(K, (3 * H, H)) * 0.1
        bc = jr.normal(jr.fold_in(K, 1), (3 * H,)) * 0.1
        wr = jr.normal(jr.fold_in(K, 2), (H, H)) * 0.1
        br = jr.normal(jr.fold_in(K, 3), (H,)) * 0.1
        x = jr.normal(jr.fold_in(K, 4), (b, s, H))

        def via_headwise(x, wc, bc, wr, br):
            qkv = col.headwise({"weight": wc, "bias": bc}, x, 3 * h_loc)
            ctx = qkv.reshape(b, 3, h_loc, s, d)[:, 0]  # take "q"
            return row.headwise({"weight": wr, "bias": br}, ctx)

        def via_flat(x, wc, bc, wr, br):
            y = col({"weight": wc, "bias": bc}, x)  # (b, s, 3*h_loc*d)
            q = y.reshape(b, s, 3, h_loc, d)[:, :, 0].transpose(0, 2, 1, 3)
            return row({"weight": wr, "bias": br},
                       q.transpose(0, 2, 1, 3).reshape(b, s, h_loc * d))

        specs = (P(), P("tp", None), P("tp"), P(None, "tp"), P())
        args = (x, wc, bc, wr, br)
        y1 = mesh_lib.shard_map(via_headwise, mesh=mesh, in_specs=specs,
                                out_specs=P())(*args)
        y2 = mesh_lib.shard_map(via_flat, mesh=mesh, in_specs=specs,
                                out_specs=P())(*args)
        np.testing.assert_allclose(y1, y2, rtol=2e-5, atol=2e-5)

        def loss(f):
            def inner(x, wc, bc, wr, br):
                out = f(x, wc, bc, wr, br)
                return jnp.sum(jnp.sin(out))
            return mesh_lib.shard_map(
                lambda *a: jax.grad(inner, argnums=(0, 1, 2))(*a),
                mesh=mesh, in_specs=specs,
                out_specs=(P(), P("tp", None), P("tp")))(*args)

        for g1, g2 in zip(loss(via_headwise), loss(via_flat)):
            np.testing.assert_allclose(g1, g2, rtol=2e-5, atol=2e-5)

    def test_column_gather_output(self):
        tp_size = 4
        mesh = tp_mesh(tp_size)
        col = tp.ColumnParallelLinear(32, 64, tp_size=tp_size, gather_output=True)
        w = jr.normal(K, (64, 32)) * 0.1
        x = jr.normal(jr.fold_in(K, 5), (4, 32))

        y = mesh_lib.shard_map(
            lambda x, w: col({"weight": w, "bias": jnp.zeros(16)}, x),
            mesh=mesh, in_specs=(P(), P("tp", None)), out_specs=P(),
        )(x, w)
        np.testing.assert_allclose(y, x @ w.T, rtol=2e-5, atol=2e-5)

    def test_grads_match_dense(self):
        tp_size = 4
        mesh = tp_mesh(tp_size)
        col = tp.ColumnParallelLinear(16, 32, tp_size=tp_size, bias=False)
        row = tp.RowParallelLinear(32, 16, tp_size=tp_size, bias=False)
        wc = jr.normal(K, (32, 16)) * 0.2
        wr = jr.normal(jr.fold_in(K, 6), (16, 32)) * 0.2
        x = jr.normal(jr.fold_in(K, 7), (4, 16))

        def loss(wc, wr, x):
            h = col({"weight": wc}, x)
            return jnp.sum(jnp.tanh(row({"weight": wr}, h)))

        g = mesh_lib.shard_map(
            lambda wc, wr, x: jax.grad(loss, argnums=(0, 1))(wc, wr, x),
            mesh=mesh,
            in_specs=(P("tp", None), P(None, "tp"), P()),
            out_specs=(P("tp", None), P(None, "tp")),
        )(wc, wr, x)

        gref = jax.grad(
            lambda wc, wr: jnp.sum(jnp.tanh((x @ wc.T) @ wr.T)), argnums=(0, 1)
        )(wc, wr)
        np.testing.assert_allclose(g[0], gref[0], rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(g[1], gref[1], rtol=2e-5, atol=2e-5)


class TestVocabParallelEmbedding:
    def test_matches_dense_embedding(self):
        tp_size = 4
        mesh = tp_mesh(tp_size)
        vocab, dim = 64, 16
        emb = tp.VocabParallelEmbedding(vocab, dim, tp_size=tp_size)
        w = jr.normal(K, (vocab, dim))
        ids = jr.randint(jr.fold_in(K, 8), (4, 10), 0, vocab)

        y = mesh_lib.shard_map(
            lambda w, ids: emb({"weight": w}, ids),
            mesh=mesh, in_specs=(P("tp", None), P()), out_specs=P(),
        )(w, ids)
        np.testing.assert_allclose(y, w[ids], rtol=1e-6)

    def test_grad_scatters_to_owner_shard(self):
        tp_size = 4
        mesh = tp_mesh(tp_size)
        vocab, dim = 16, 8
        emb = tp.VocabParallelEmbedding(vocab, dim, tp_size=tp_size)
        w = jr.normal(K, (vocab, dim))
        ids = jnp.array([[0, 5, 11, 15]])

        def loss(w, ids):
            return jnp.sum(emb({"weight": w}, ids) ** 2)

        g = mesh_lib.shard_map(
            lambda w, ids: jax.grad(loss)(w, ids),
            mesh=mesh, in_specs=(P("tp", None), P()), out_specs=P("tp", None),
        )(w, ids)
        gref = jax.grad(lambda w: jnp.sum(w[ids] ** 2))(w)
        np.testing.assert_allclose(g, gref, rtol=1e-6)


class TestVocabParallelCrossEntropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_matches_unsharded(self, smoothing):
        tp_size = 4
        mesh = tp_mesh(tp_size)
        vocab = 32
        logits = jr.normal(K, (6, vocab)) * 2
        target = jr.randint(jr.fold_in(K, 9), (6,), 0, vocab)

        loss = mesh_lib.shard_map(
            lambda l, t: tp.vocab_parallel_cross_entropy(l, t, smoothing),
            mesh=mesh, in_specs=(P(None, "tp"), P()), out_specs=P(),
        )(logits, target)

        lse = jax.nn.logsumexp(logits, -1)
        nll = lse - jnp.take_along_axis(logits, target[:, None], -1)[:, 0]
        if smoothing:
            # reference smoothing: (1-ε)·nll + ε/V·Σ_i (lse - logit_i)
            ref = (1 - smoothing) * nll + smoothing / vocab * jnp.sum(
                lse[:, None] - logits, -1
            )
        else:
            ref = nll
        np.testing.assert_allclose(loss, ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_kernel_path_matches_unsharded(self, smoothing, monkeypatch):
        """The fused-stats kernel path under a real tp axis: guards the
        owning-shard-only max rebase (``t_logit = psum(t_raw - where(in_shard,
        m, 0))``) and the ``l_loc * exp(m_loc - m)`` sum-exp rebase, which
        axis_name=None tests never exercise. Shard vocab 512/tp=4 = 128
        columns — the kernel's minimum tileable block."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        tp_size = 4
        mesh = tp_mesh(tp_size)
        vocab = 512
        logits = jr.normal(K, (8, vocab)) * 2 + 3  # shift: exposes rebase bugs
        # include an out-of-vocab sentinel no shard owns
        target = jr.randint(jr.fold_in(K, 11), (8,), 0, vocab).at[3].set(-100)

        loss = mesh_lib.shard_map(
            lambda l, t: tp.vocab_parallel_cross_entropy(
                l, t, smoothing, impl="pallas"),
            mesh=mesh, in_specs=(P(None, "tp"), P()), out_specs=P(),
        )(logits, target)

        lse = jax.nn.logsumexp(logits, -1)
        safe_t = jnp.clip(target, 0, vocab - 1)
        # sentinel rows: both dispatch paths yield t_logit == 0 *relative to
        # the global row max*, i.e. loss = lse - max — encode that here
        t_logit = jnp.where(
            (target >= 0) & (target < vocab),
            jnp.take_along_axis(logits, safe_t[:, None], -1)[:, 0],
            jnp.max(logits, -1))
        nll = lse - t_logit
        if smoothing:
            ref = (1 - smoothing) * nll + smoothing / vocab * jnp.sum(
                lse[:, None] - logits, -1)
        else:
            ref = nll
        np.testing.assert_allclose(loss, ref, rtol=1e-5, atol=1e-5)

    def test_grad_matches_unsharded(self):
        tp_size = 4
        mesh = tp_mesh(tp_size)
        vocab = 32
        logits = jr.normal(K, (6, vocab)) * 2
        target = jr.randint(jr.fold_in(K, 10), (6,), 0, vocab)

        def sharded_loss(l, t):
            return jnp.mean(tp.vocab_parallel_cross_entropy(l, t))

        g = mesh_lib.shard_map(
            lambda l, t: jax.grad(sharded_loss)(l, t),
            mesh=mesh, in_specs=(P(None, "tp"), P()), out_specs=P(None, "tp"),
        )(logits, target)

        def ref_loss(l):
            lse = jax.nn.logsumexp(l, -1)
            return jnp.mean(lse - jnp.take_along_axis(l, target[:, None], -1)[:, 0])

        np.testing.assert_allclose(g, jax.grad(ref_loss)(logits), rtol=1e-5, atol=1e-6)


class TestRandom:
    def test_model_parallel_keys_differ_across_tp(self):
        mesh = tp_mesh(4)
        base = jr.PRNGKey(0)

        keys = mesh_lib.shard_map(
            lambda: tp.model_parallel_rng_key(base)[None],
            mesh=mesh, in_specs=(), out_specs=P("tp"),
        )()
        # 4 distinct keys
        assert len({tuple(np.asarray(k)) for k in keys}) == 4

    def test_tracker_streams(self):
        from apex_tpu.transformer.tensor_parallel.random import model_parallel_seed

        t = tp.RngTracker()
        model_parallel_seed(123, t)
        k1 = t.key("model-parallel-rng")
        k2 = t.key("data-parallel-rng")
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))
        with pytest.raises(RuntimeError):
            t.key("nope")

    def test_checkpoint_replays_dropout(self):
        key = jr.PRNGKey(3)
        x = jr.normal(K, (8, 16))

        def block(x, key):
            mask = jr.bernoulli(key, 0.5, x.shape)
            return jnp.sum(jnp.where(mask, x, 0) ** 2)

        g1 = jax.grad(lambda x: tp.checkpoint(block, x, key))(x)
        g2 = jax.grad(lambda x: block(x, key))(x)
        np.testing.assert_allclose(g1, g2, rtol=1e-6)


class TestUtils:
    def test_divide_and_split(self):
        assert tp.divide(12, 4) == 3
        with pytest.raises(ValueError):
            tp.divide(10, 4)
        x = jnp.arange(12.0).reshape(2, 6)
        parts = tp.split_tensor_along_last_dim(x, 3)
        assert len(parts) == 3 and parts[1][0, 0] == 2.0

    def test_vocab_utility(self):
        assert tp.VocabUtility.vocab_range_from_global_vocab_size(100, 2, 4) == (50, 75)


class TestTP8Flagship:
    """BASELINE.md's 'GPT tensor-parallel TP=8 functional' row: the full
    GPTModel at tp=8 (the whole 8-device mesh as one TP group, ICI
    all-reduce linears + vocab-parallel embedding/CE + SP) reproduces the
    unsharded loss and per-rank grads."""

    def test_gpt_tp8_loss_and_grads_match_tp1(self):
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.models.gpt import shard_params_for_tp

        kw = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
                  num_layers=2, num_heads=8)
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=8)
        cfg1 = GPTConfig(**kw, tp_size=1)
        cfg8 = GPTConfig(**kw, tp_size=8, sequence_parallel=True)
        m1, m8 = GPTModel(cfg1), GPTModel(cfg8)
        params1 = m1.init(K)
        toks = jr.randint(jr.fold_in(K, 70), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 71), (2, 16), 0, 64)

        sharded = shard_params_for_tp(params1, 8, cfg1)
        specs = jax.tree.map(lambda _: P("tp"), sharded)

        def run(p, t, g):
            loss, grads = jax.value_and_grad(m8.loss_fn)(
                jax.tree.map(lambda x: x[0], p), t, g)
            grads = m8.sp_grad_sync(grads)
            return loss, jax.tree.map(lambda x: x[None], grads)

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P()),
                out_specs=(P(), specs),
            ))(sharded, toks, tgts)
            ref_loss, ref = jax.value_and_grad(m1.loss_fn)(
                params1, toks, tgts)

        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            grads["lnf_w"][0], ref["lnf_w"], rtol=3e-4, atol=1e-5)
        emb = jnp.concatenate(list(grads["embedding"]["weight"]), axis=0)
        np.testing.assert_allclose(
            emb, ref["embedding"]["weight"], rtol=3e-4, atol=1e-5)
        up = jnp.concatenate(list(grads["layers"]["mlp_up"]["weight"]),
                             axis=1)
        np.testing.assert_allclose(
            up, ref["layers"]["mlp_up"]["weight"], rtol=3e-4, atol=1e-5)
