"""Model-level tests: GPT/BERT/ResNet forward+training, TP/SP equivalence.

The TP-equivalence tests mirror the reference's
``run_gpt_minimal_test.py``/``gpt_scaling_test.py`` intent: the sharded model
must compute the same loss/grads as its unsharded counterpart.
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import (
    BertConfig, BertModel, GPTConfig, GPTModel, ResNet50, ResNetConfig,
)
from apex_tpu.parallel import mesh as mesh_lib

K = jr.PRNGKey(21)

SMALL = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
             num_layers=2, num_heads=4)


class TestGPT:
    def test_forward_deterministic_and_finite(self):
        cfg = GPTConfig(**SMALL, tp_size=1)
        m = GPTModel(cfg)
        params = m.init(K)
        toks = jr.randint(jr.fold_in(K, 1), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 2), (2, 16), 0, 64)
        l1 = m.loss_fn(params, toks, tgts)
        l2 = m.loss_fn(params, toks, tgts)
        assert jnp.isfinite(l1) and l1 == l2

    def test_remat_matches_no_remat(self):
        cfg_r = GPTConfig(**SMALL, tp_size=1, remat=True)
        cfg_n = GPTConfig(**SMALL, tp_size=1, remat=False)
        m_r, m_n = GPTModel(cfg_r), GPTModel(cfg_n)
        params = m_r.init(K)
        toks = jr.randint(jr.fold_in(K, 3), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 4), (2, 16), 0, 64)
        g_r = jax.grad(m_r.loss_fn)(params, toks, tgts)
        g_n = jax.grad(m_n.loss_fn)(params, toks, tgts)
        for a, e in zip(jax.tree.leaves(g_r), jax.tree.leaves(g_n)):
            np.testing.assert_allclose(a, e, rtol=1e-5, atol=1e-6)

    def test_unrolled_matches_scan(self):
        """scan_layers=False (the bench's measured-faster unrolled loop)
        must be numerically identical to the scan formulation."""
        cfg_s = GPTConfig(**SMALL, tp_size=1, scan_layers=True)
        cfg_u = GPTConfig(**SMALL, tp_size=1, scan_layers=False)
        m_s, m_u = GPTModel(cfg_s), GPTModel(cfg_u)
        params = m_s.init(K)
        toks = jr.randint(jr.fold_in(K, 8), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 9), (2, 16), 0, 64)
        np.testing.assert_allclose(
            m_s.loss_fn(params, toks, tgts), m_u.loss_fn(params, toks, tgts),
            rtol=1e-6)
        g_s = jax.grad(m_s.loss_fn)(params, toks, tgts)
        g_u = jax.grad(m_u.loss_fn)(params, toks, tgts)
        for a, e in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_u)):
            np.testing.assert_allclose(a, e, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("sp", [False, True])
    def test_tp2_matches_tp1(self, sp):
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=2)
        cfg1 = GPTConfig(**SMALL, tp_size=1)
        cfg2 = GPTConfig(**SMALL, tp_size=2, sequence_parallel=sp)
        m1, m2 = GPTModel(cfg1), GPTModel(cfg2)
        params1 = m1.init(K)
        toks = jr.randint(jr.fold_in(K, 5), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 6), (2, 16), 0, 64)
        ref_loss = m1.loss_fn(params1, toks, tgts)

        from apex_tpu.models.gpt import shard_params_for_tp
        sharded = shard_params_for_tp(params1, 2, cfg1)
        specs = jax.tree.map(lambda _: P("tp"), sharded)

        loss = mesh_lib.shard_map(
            lambda p, t, g: m2.loss_fn(jax.tree.map(lambda x: x[0], p), t, g),
            mesh=mesh,
            in_specs=(specs, P(), P()),
            out_specs=P(),
        )(sharded, toks, tgts)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-5)

    def test_tp2_grads_match_tp1(self):
        """Per-rank grads computed INSIDE shard_map (the training-step
        formulation) must match the unsharded model's — exercises the
        copy-to-region transpose before the tied unembedding, without which
        every upstream gradient is a partial vocab-shard sum."""
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=2)
        cfg1 = GPTConfig(**SMALL, tp_size=1)
        cfg2 = GPTConfig(**SMALL, tp_size=2)
        m1, m2 = GPTModel(cfg1), GPTModel(cfg2)
        params1 = m1.init(K)
        toks = jr.randint(jr.fold_in(K, 15), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 16), (2, 16), 0, 64)

        from apex_tpu.models.gpt import shard_params_for_tp
        sharded = shard_params_for_tp(params1, 2, cfg1)
        specs = jax.tree.map(lambda _: P("tp"), sharded)

        def run(p, t, g):
            loss, grads = jax.value_and_grad(m2.loss_fn)(
                jax.tree.map(lambda x: x[0], p), t, g)
            return loss, jax.tree.map(lambda x: x[None], grads)

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P()),
                out_specs=(P(), specs),
            ))(sharded, toks, tgts)
            ref_loss, ref = jax.value_and_grad(m1.loss_fn)(
                params1, toks, tgts)

        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        # replicated leaves: each tp shard must hold the full grad
        np.testing.assert_allclose(
            grads["lnf_w"][0], ref["lnf_w"], rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            grads["pos_embedding"][0], ref["pos_embedding"],
            rtol=2e-4, atol=1e-5)
        for n in ("ln1_w", "ln1_b", "ln2_w", "ln2_b"):
            np.testing.assert_allclose(
                grads["layers"][n][0], ref["layers"][n], rtol=2e-4,
                atol=1e-5, err_msg=n)
        # sharded leaves reassemble to the full grad
        emb = jnp.concatenate(list(grads["embedding"]["weight"]), axis=0)
        np.testing.assert_allclose(
            emb, ref["embedding"]["weight"], rtol=2e-4, atol=1e-5)
        up = jnp.concatenate(
            list(grads["layers"]["mlp_up"]["weight"]), axis=1)
        np.testing.assert_allclose(
            up, ref["layers"]["mlp_up"]["weight"], rtol=2e-4, atol=1e-5)


class TestBert:
    def test_mlm_loss_and_padding_mask(self):
        cfg = BertConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                         num_layers=2, num_heads=4)
        m = BertModel(cfg)
        params = m.init(K)
        toks = jr.randint(jr.fold_in(K, 7), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 8), (2, 16), 0, 64)
        loss_mask = jnp.ones((2, 16))
        pad = jnp.zeros((2, 16), bool)
        loss = m.mlm_loss(params, toks, tgts, loss_mask, pad_mask=pad)
        assert jnp.isfinite(loss)
        # masking out the second half of positions changes the loss
        lm2 = loss_mask.at[:, 8:].set(0.0)
        loss2 = m.mlm_loss(params, toks, tgts, lm2, pad_mask=pad)
        assert loss != loss2

    def test_flash_impl_matches_softmax_on_suffix_padding(self):
        """BERT's flash path converts the suffix pad mask to per-row kv
        lengths (varlen flash); on standard suffix-padded batches it must
        agree with the mask-tensor softmax path at masked-out-loss parity."""
        kw = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
                  num_layers=2, num_heads=4)
        m_soft = BertModel(BertConfig(**kw))
        m_flash = BertModel(BertConfig(attention_impl="flash", **kw))
        params = m_soft.init(K)
        toks = jr.randint(jr.fold_in(K, 7), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 8), (2, 16), 0, 64)
        # suffix padding: rows valid to 16 and 10
        pad = jnp.zeros((2, 16), bool).at[1, 10:].set(True)
        loss_mask = (~pad).astype(jnp.float32)
        with jax.default_matmul_precision("highest"):
            l1 = m_soft.mlm_loss(params, toks, tgts, loss_mask, pad_mask=pad)
            l2 = m_flash.mlm_loss(params, toks, tgts, loss_mask, pad_mask=pad)
        # the two masked softmaxes differ only in the -10000-additive vs
        # -inf masking of dead columns — loss over VALID positions agrees
        assert float(l1) == pytest.approx(float(l2), rel=2e-3)
        g = jax.grad(lambda p: m_flash.mlm_loss(
            p, toks, tgts, loss_mask, pad_mask=pad))(params)
        assert all(bool(jnp.all(jnp.isfinite(x))) for x in jax.tree.leaves(g))

    def test_flash_impl_rejects_interior_mask_eagerly(self):
        """ADVICE r2: the flash path's first-True length conversion would
        silently truncate an interior (non-suffix) mask — eager calls must
        raise instead (float 0/1 masks included)."""
        kw = dict(vocab_size=64, max_seq_len=16, hidden_size=32,
                  num_layers=1, num_heads=2)
        m = BertModel(BertConfig(attention_impl="flash", **kw))
        params = m.init(K)
        toks = jr.randint(jr.fold_in(K, 9), (1, 16), 0, 64)
        pad = jnp.zeros((1, 16)).at[0, 12:].set(1.0)  # float suffix: fine
        m.hidden_states(params, toks, pad_mask=pad)
        with pytest.raises(ValueError, match="suffix padding"):
            m.hidden_states(params, toks,
                            pad_mask=pad.at[0, 5].set(1.0))

    def test_pooler(self):
        cfg = BertConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                         num_layers=1, num_heads=4)
        m = BertModel(cfg)
        params = m.init(K)
        toks = jr.randint(K, (2, 8), 0, 64)
        h = m.hidden_states(params, toks)
        pooled = m.pooled(params, h)
        assert pooled.shape == (2, 32)


class TestResNet:
    def test_train_and_eval_modes(self):
        rn = ResNet50(ResNetConfig(num_classes=10))
        params, state = rn.init(K)
        x = jr.normal(jr.fold_in(K, 9), (2, 32, 32, 3))
        logits, new_state = rn.apply(params, state, x, training=True)
        assert logits.shape == (2, 10)
        assert int(new_state["bn1"].num_batches_tracked) == 1
        logits_eval, st = rn.apply(params, new_state, x, training=False)
        assert jnp.all(st["bn1"].running_mean == new_state["bn1"].running_mean)

    def test_param_count_matches_torchvision(self):
        rn = ResNet50(ResNetConfig(num_classes=1000))
        params, _ = rn.init(K)
        n = sum(x.size for x in jax.tree.leaves(params))
        assert n == 25_557_032  # torchvision resnet50 exactly


class TestGPTAttentionAndRematVariants:
    """Pin the bench-critical config paths: all attention impls agree and
    every remat policy computes identical loss/grads."""

    def _small(self, **kw):
        from apex_tpu.models import GPTConfig, GPTModel

        cfg = GPTConfig(vocab_size=128, max_seq_len=128, hidden_size=64,
                        num_layers=2, num_heads=2, **kw)
        return GPTModel(cfg)

    def test_attention_impls_agree(self):
        import jax.random as jr

        models = {impl: self._small(attention_impl=impl)
                  for impl in ("softmax", "flash", "naive")}
        params = models["softmax"].init(jr.PRNGKey(0))
        toks = jr.randint(jr.PRNGKey(1), (2, 128), 0, 128)
        losses = {impl: float(m.loss_fn(params, toks, toks))
                  for impl, m in models.items()}
        assert losses["softmax"] == pytest.approx(losses["naive"], rel=1e-5)
        assert losses["softmax"] == pytest.approx(losses["flash"], rel=1e-3)

    def test_remat_policies_identical_loss_and_grads(self):
        import jax.random as jr

        ref = None
        for pol in ("full", "save_attn", "save_attn_mlp"):
            m = self._small(remat=True, remat_policy=pol, attention_impl="flash")
            params = m.init(jr.PRNGKey(0))
            toks = jr.randint(jr.PRNGKey(1), (2, 128), 0, 128)
            loss, grads = jax.value_and_grad(m.loss_fn)(params, toks, toks)
            flat = np.concatenate([np.asarray(g, np.float32).ravel()
                                   for g in jax.tree.leaves(grads)])
            if ref is None:
                ref = (float(loss), flat)
            else:
                assert float(loss) == pytest.approx(ref[0], rel=1e-6), pol
                np.testing.assert_allclose(flat, ref[1], rtol=1e-5, atol=1e-7)

    def test_invalid_config_strings_rejected(self):
        from apex_tpu.models import GPTConfig

        with pytest.raises(ValueError, match="attention_impl"):
            GPTConfig(attention_impl="Flash")
        with pytest.raises(ValueError, match="remat_policy"):
            GPTConfig(remat_policy="save-attn")

    def test_gqa_flash_matches_softmax_impl(self):
        """Grouped-query attention cross-check: the flash path broadcasts kv
        through the kernel's index maps, the softmax path via jnp.repeat —
        identical weights must give identical loss and grads."""
        import jax.random as jr

        models = {impl: self._small(attention_impl=impl, num_kv_heads=1)
                  for impl in ("softmax", "flash")}
        params = models["softmax"].init(jr.PRNGKey(0))
        toks = jr.randint(jr.PRNGKey(1), (2, 128), 0, 128)
        with jax.default_matmul_precision("highest"):
            l1, g1 = jax.value_and_grad(models["softmax"].loss_fn)(params, toks, toks)
            l2, g2 = jax.value_and_grad(models["flash"].loss_fn)(params, toks, toks)
        assert float(l1) == pytest.approx(float(l2), rel=1e-4)
        for a, e in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(e, np.float32),
                                       rtol=1e-3, atol=1e-4)

    def test_gqa_config_validation(self):
        from apex_tpu.models import GPTConfig

        with pytest.raises(ValueError, match="num_kv_heads"):
            GPTConfig(num_heads=4, num_kv_heads=3)
        with pytest.raises(ValueError, match="num_kv_heads"):
            GPTConfig(num_heads=8, num_kv_heads=1, tp_size=2)
        cfg = GPTConfig(num_heads=8, num_kv_heads=2)
        assert cfg.qkv_features == (8 + 4) * cfg.head_dim


class TestGPTLossMask:
    def test_loss_mask_weights_the_mean(self):
        """loss_fn(loss_mask=...) consumes get_ltor_masks_and_position_ids'
        loss mask: masked positions drop out of the mean exactly."""
        cfg = GPTConfig(vocab_size=64, max_seq_len=32, hidden_size=32,
                        num_layers=1, num_heads=2, remat=False)
        m = GPTModel(cfg)
        params = m.init(K)
        toks = jr.randint(jr.fold_in(K, 1), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 2), (2, 16), 0, 64)
        full = m.loss_fn(params, toks, tgts)
        ones = m.loss_fn(params, toks, tgts, loss_mask=jnp.ones((2, 16)))
        assert float(full) == pytest.approx(float(ones), rel=1e-6)
        # mask half: equals the mean over the kept positions
        mask = jnp.zeros((2, 16)).at[:, :8].set(1.0)
        masked = m.loss_fn(params, toks, tgts, loss_mask=mask)
        logits = m.logits(params, toks)
        from apex_tpu.transformer import tensor_parallel as tp
        per_tok = tp.vocab_parallel_cross_entropy(logits, tgts, axis_name=None)
        ref = float(jnp.mean(per_tok[:, :8]))
        assert float(masked) == pytest.approx(ref, rel=1e-5)
        # all-masked: finite (denominator clamped), not NaN
        z = m.loss_fn(params, toks, tgts, loss_mask=jnp.zeros((2, 16)))
        assert float(z) == 0.0
