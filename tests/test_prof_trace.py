"""Trace post-processor tests (pyprof.parse/prof analog).

The reader is validated against a synthetic chrome trace with the exact
shape ``jax.profiler`` writes (M metadata rows naming processes/threads, X
complete-events on the device's "XLA Ops" track); real-trace validation
runs on TPU via ``tools/profile_bench.py``.
"""

import gzip
import json
import os

import pytest

from apex_tpu.prof import trace_reader


def _write_trace(tmp_path, events):
    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    os.makedirs(run)
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


@pytest.fixture
def logdir(tmp_path):
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
         "args": {"name": "python"}},
        # device ops: a fusion executed twice, a dot once, named with scopes
        {"ph": "X", "pid": 3, "tid": 3, "ts": 10.0, "dur": 100.0,
         "name": "gpt/block/attention/dot.7", "args": {"flops": 2.0e9}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 120.0, "dur": 50.0,
         "name": "gpt/block/mlp/fusion.3", "args": {}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 200.0, "dur": 50.0,
         "name": "gpt/block/mlp/fusion.3", "args": {}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 300.0, "dur": 25.0,
         "name": "copy.1", "args": {}},
        # host event must be excluded
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 9999.0,
         "name": "PjitFunction(train_step)"},
    ]
    return _write_trace(tmp_path, events)


def test_read_trace_resolves_processes(logdir):
    evs = trace_reader.read_trace(logdir)
    assert len(evs) == 5
    dev = trace_reader.device_op_events(evs)
    assert len(dev) == 4
    assert all(e.device == "/device:TPU:0" for e in dev)


def test_op_records_fold_repeats(logdir):
    recs = trace_reader.op_records(trace_reader.read_trace(logdir))
    by_name = {r["name"]: r for r in recs}
    fus = by_name["gpt/block/mlp/fusion.3"]
    assert fus["count"] == 2
    assert fus["time_s"] == pytest.approx(100e-6)
    assert fus["scope"] == "gpt/block/mlp"
    assert by_name["gpt/block/attention/dot.7"]["flops"] == pytest.approx(2.0e9)


def test_summarize_ranks_time_sinks(logdir):
    sinks, fams = trace_reader.summarize(logdir, top=2)
    assert sinks[0]["name"] == "gpt/block/attention/dot.7"
    assert sinks[1]["name"] == "gpt/block/mlp/fusion.3"
    # families: dot -> gemm, fusion -> fusion, copy -> memory
    assert fams["gemm"].flops == pytest.approx(2.0e9)
    assert fams["fusion"].count == 1  # one folded record
    assert "memory" in fams


def test_format_report_names_top_sinks(logdir):
    text = trace_reader.format_report(logdir, top=3)
    assert "attention/dot.7" in text
    assert "gemm" in text


def test_missing_run_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        trace_reader.read_trace(str(tmp_path))


@pytest.fixture
def xprof_logdir(tmp_path):
    """Events shaped like a real TPU XProf export: hlo_category,
    model_flops/bytes_accessed as strings, source call-sites, and a
    while-loop container row spanning its children."""
    meta = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
    ]
    events = meta + [
        {"ph": "X", "pid": 3, "tid": 3, "ts": 0.0, "dur": 300.0,
         "name": "while.6",
         "args": {"hlo_category": "while", "model_flops": "4000000000",
                  "bytes_accessed": "900", "source": "/repo/m/gpt.py:286"}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 10.0, "dur": 200.0,
         "name": "fusion.276",
         "args": {"hlo_category": "convolution fusion",
                  "model_flops": "3000000000", "bytes_accessed": "1000",
                  "source": "/repo/m/gpt.py:284"}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 220.0, "dur": 50.0,
         "name": "fusion.9",
         "args": {"hlo_category": "loop fusion", "model_flops": "0",
                  "bytes_accessed": "500", "source": "/repo/m/gpt.py:284"}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 280.0, "dur": 10.0,
         "name": "copy.3", "args": {"hlo_category": "copy-start"}},
    ]
    return _write_trace(tmp_path, events)


def test_xprof_metadata_classification(xprof_logdir):
    recs = trace_reader.op_records(trace_reader.read_trace(xprof_logdir))
    by_name = {r["name"]: r for r in recs}
    # hlo_category is authoritative: "convolution fusion" -> gemm even
    # though the op is named fusion.*; flops/bytes parsed from strings
    assert by_name["fusion.276"]["flops"] == pytest.approx(3.0e9)
    assert by_name["fusion.276"]["bytes"] == pytest.approx(1000.0)
    sinks, fams = trace_reader.summarize(xprof_logdir, top=10)
    assert fams["gemm"].flops == pytest.approx(3.0e9)
    assert "control" in fams
    # the while container must not rank as a sink
    assert all(r["name"] != "while.6" for r in sinks)
    assert sinks[0]["name"] == "fusion.276"


def test_by_source_rollup_excludes_containers(xprof_logdir):
    recs = trace_reader.op_records(trace_reader.read_trace(xprof_logdir))
    rolled = trace_reader.by_source(recs)
    # both fusions fold onto gpt.py:284; the while row (gpt.py:286) is a
    # container and must not appear
    assert [r["source"] for r in rolled] == ["/repo/m/gpt.py:284"]
    assert rolled[0]["time_s"] == pytest.approx(250e-6)
    assert rolled[0]["flops"] == pytest.approx(3.0e9)


def test_format_report_shows_sources(xprof_logdir):
    text = trace_reader.format_report(xprof_logdir, top=3)
    assert "m/gpt.py:284" in text
    assert "source lines" in text


def test_native_parser_matches_python(xprof_logdir):
    """csrc/trace_parser.cpp (the native IO stage) must produce the same
    resolved device events as the pure-Python gzip+json path."""
    from apex_tpu import native

    if not native.available() and not native.build():
        pytest.skip("native build unavailable")

    evs_native = trace_reader.read_trace(xprof_logdir)
    saved = (native._lib, native._tried)
    native._lib, native._tried = None, True
    try:
        evs_py = trace_reader.read_trace(xprof_logdir)
    finally:
        native._lib, native._tried = saved

    assert len(evs_native) == len(evs_py)
    for a, b in zip(sorted(evs_native, key=lambda e: e.start_us),
                    sorted(evs_py, key=lambda e: e.start_us)):
        assert (a.name, a.device, a.track) == (b.name, b.device, b.track)
        assert a.start_us == pytest.approx(b.start_us)
        assert a.dur_us == pytest.approx(b.dur_us)
        assert a.args == b.args
