"""Trace post-processor tests (pyprof.parse/prof analog).

The reader is validated against a synthetic chrome trace with the exact
shape ``jax.profiler`` writes (M metadata rows naming processes/threads, X
complete-events on the device's "XLA Ops" track); real-trace validation
runs on TPU via ``tools/profile_bench.py``.
"""

import gzip
import json
import os

import pytest

from apex_tpu.prof import trace_reader


def _write_trace(tmp_path, events):
    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    os.makedirs(run)
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


@pytest.fixture
def logdir(tmp_path):
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
         "args": {"name": "python"}},
        # device ops: a fusion executed twice, a dot once, named with scopes
        {"ph": "X", "pid": 3, "tid": 3, "ts": 10.0, "dur": 100.0,
         "name": "gpt/block/attention/dot.7", "args": {"flops": 2.0e9}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 120.0, "dur": 50.0,
         "name": "gpt/block/mlp/fusion.3", "args": {}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 200.0, "dur": 50.0,
         "name": "gpt/block/mlp/fusion.3", "args": {}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 300.0, "dur": 25.0,
         "name": "copy.1", "args": {}},
        # host event must be excluded
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 9999.0,
         "name": "PjitFunction(train_step)"},
    ]
    return _write_trace(tmp_path, events)


def test_read_trace_resolves_processes(logdir):
    evs = trace_reader.read_trace(logdir)
    assert len(evs) == 5
    dev = trace_reader.device_op_events(evs)
    assert len(dev) == 4
    assert all(e.device == "/device:TPU:0" for e in dev)


def test_op_records_fold_repeats(logdir):
    recs = trace_reader.op_records(trace_reader.read_trace(logdir))
    by_name = {r["name"]: r for r in recs}
    fus = by_name["gpt/block/mlp/fusion.3"]
    assert fus["count"] == 2
    assert fus["time_s"] == pytest.approx(100e-6)
    assert fus["scope"] == "gpt/block/mlp"
    assert by_name["gpt/block/attention/dot.7"]["flops"] == pytest.approx(2.0e9)


def test_summarize_ranks_time_sinks(logdir):
    sinks, fams = trace_reader.summarize(logdir, top=2)
    assert sinks[0]["name"] == "gpt/block/attention/dot.7"
    assert sinks[1]["name"] == "gpt/block/mlp/fusion.3"
    # families: dot -> gemm, fusion -> fusion, copy -> memory
    assert fams["gemm"].flops == pytest.approx(2.0e9)
    assert fams["fusion"].count == 1  # one folded record
    assert "memory" in fams


def test_format_report_names_top_sinks(logdir):
    text = trace_reader.format_report(logdir, top=3)
    assert "attention/dot.7" in text
    assert "gemm" in text


def test_missing_run_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        trace_reader.read_trace(str(tmp_path))


@pytest.fixture
def xprof_logdir(tmp_path):
    """Events shaped like a real TPU XProf export: hlo_category,
    model_flops/bytes_accessed as strings, source call-sites, and a
    while-loop container row spanning its children."""
    meta = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
    ]
    events = meta + [
        {"ph": "X", "pid": 3, "tid": 3, "ts": 0.0, "dur": 300.0,
         "name": "while.6",
         "args": {"hlo_category": "while", "model_flops": "4000000000",
                  "bytes_accessed": "900", "source": "/repo/m/gpt.py:286"}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 10.0, "dur": 200.0,
         "name": "fusion.276",
         "args": {"hlo_category": "convolution fusion",
                  "model_flops": "3000000000", "bytes_accessed": "1000",
                  "source": "/repo/m/gpt.py:284"}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 220.0, "dur": 50.0,
         "name": "fusion.9",
         "args": {"hlo_category": "loop fusion", "model_flops": "0",
                  "bytes_accessed": "500", "source": "/repo/m/gpt.py:284"}},
        {"ph": "X", "pid": 3, "tid": 3, "ts": 280.0, "dur": 10.0,
         "name": "copy.3", "args": {"hlo_category": "copy-start"}},
    ]
    return _write_trace(tmp_path, events)


def test_xprof_metadata_classification(xprof_logdir):
    recs = trace_reader.op_records(trace_reader.read_trace(xprof_logdir))
    by_name = {r["name"]: r for r in recs}
    # hlo_category is authoritative: "convolution fusion" -> gemm even
    # though the op is named fusion.*; flops/bytes parsed from strings
    assert by_name["fusion.276"]["flops"] == pytest.approx(3.0e9)
    assert by_name["fusion.276"]["bytes"] == pytest.approx(1000.0)
    sinks, fams = trace_reader.summarize(xprof_logdir, top=10)
    assert fams["gemm"].flops == pytest.approx(3.0e9)
    assert "control" in fams
    # the while container must not rank as a sink
    assert all(r["name"] != "while.6" for r in sinks)
    assert sinks[0]["name"] == "fusion.276"


def test_by_source_rollup_excludes_containers(xprof_logdir):
    recs = trace_reader.op_records(trace_reader.read_trace(xprof_logdir))
    rolled = trace_reader.by_source(recs)
    # both fusions fold onto gpt.py:284; the while row (gpt.py:286) is a
    # container and must not appear
    assert [r["source"] for r in rolled] == ["/repo/m/gpt.py:284"]
    assert rolled[0]["time_s"] == pytest.approx(250e-6)
    assert rolled[0]["flops"] == pytest.approx(3.0e9)


def test_format_report_shows_sources(xprof_logdir):
    text = trace_reader.format_report(xprof_logdir, top=3)
    assert "m/gpt.py:284" in text
    assert "source lines" in text


def test_native_parser_matches_python(xprof_logdir):
    """csrc/trace_parser.cpp (the native IO stage) must produce the same
    resolved device events as the pure-Python gzip+json path."""
    from apex_tpu import native

    if not native.available() and not native.build():
        pytest.skip("native build unavailable")

    evs_native = trace_reader.read_trace(xprof_logdir)
    saved = (native._lib, native._tried)
    native._lib, native._tried = None, True
    try:
        evs_py = trace_reader.read_trace(xprof_logdir)
    finally:
        native._lib, native._tried = saved

    assert len(evs_native) == len(evs_py)
    for a, b in zip(sorted(evs_native, key=lambda e: e.start_us),
                    sorted(evs_py, key=lambda e: e.start_us)):
        assert (a.name, a.device, a.track) == (b.name, b.device, b.track)
        assert a.start_us == pytest.approx(b.start_us)
        assert a.dur_us == pytest.approx(b.dur_us)
        assert a.args == b.args


def _span(name, t0_ns=1, dur_ns=1, **attrs):
    return {"schema": 1, "kind": "span", "name": name, "t0_ns": t0_ns,
            "dur_ns": dur_ns, "process": 0, "rank": "", **attrs}


def _dev(name, ts, dur, cat=None, **args):
    if cat:
        args["hlo_category"] = cat
    return trace_reader.TraceEvent(
        name=name, start_us=ts, dur_us=dur, device="/device:TPU:0",
        track="XLA Ops", args=args)


class TestSpanCorrelation:
    """Host↔device join: span scope paths prefix device op names."""

    def test_correlate_joins_by_scope_prefix(self):
        spans = [_span("step/fwd_bwd", traced=True),
                 _span("step/optimizer", traced=True)]
        events = [
            _dev("step/fwd_bwd/dot.1", 0, 50, flops=1e9),
            _dev("step/fwd_bwd/fusion.2", 60, 20),
            _dev("step/optimizer/fusion.9", 90, 10),
            _dev("unscoped/copy.1", 200, 5),
        ]
        corr = trace_reader.correlate(spans, events)
        assert corr["step/fwd_bwd"]["count"] == 2
        assert corr["step/fwd_bwd"]["time_s"] == pytest.approx(70e-6)
        assert corr["step/fwd_bwd"]["flops"] == pytest.approx(1e9)
        assert corr["step/optimizer"]["count"] == 1
        # prefix match is on path segments: "step/fwd_bwd2/..." must NOT
        # join onto "step/fwd_bwd"
        corr2 = trace_reader.correlate(
            spans, [_dev("step/fwd_bwd2/dot.1", 0, 10)])
        assert corr2["step/fwd_bwd"]["count"] == 0

    def test_split_steps_at_largest_gaps(self):
        events = [_dev("a.1", 0, 10), _dev("b.2", 15, 10),
                  _dev("a.1", 1000, 10), _dev("b.2", 1030, 10),
                  _dev("a.1", 2000, 10)]
        wins = trace_reader.split_steps(events, 3)
        assert [len(w) for w in wins] == [2, 2, 1]
        assert wins[1][0].start_us == 1000
        # n=1: everything in one window
        assert len(trace_reader.split_steps(events, 1)) == 1
        assert trace_reader.split_steps([], 3) == []

    def test_host_step_spans_filter_and_order(self):
        spans = [_span("step", t0_ns=2000, step=1),
                 _span("step", t0_ns=1000, step=0),
                 _span("step/fwd_bwd", traced=True),
                 _span("decode_step", traced=True)]
        steps = trace_reader.host_step_spans(spans)
        assert [s["step"] for s in steps] == [0, 1]

    def test_step_anatomy_exact(self):
        """The hand-checkable fixture: step 0 wall 120 us = 70 compute +
        20 exposed collective + 10 bubble + 20 host gap."""
        events = [
            _dev("step/fwd_bwd/dot.1", 0, 60),
            _dev("step/fwd_bwd/all-gather.2", 40, 40, "all-gather"),
            _dev("step/optimizer/fusion.3", 90, 10),
            _dev("step/fwd_bwd/dot.1", 1000, 50),
            _dev("step/fwd_bwd/all-gather.2", 1060, 20, "all-gather"),
        ]
        spans = [_span("step", t0_ns=1_000, dur_ns=120_000, step=0),
                 _span("step", t0_ns=2_000_000, dur_ns=100_000, step=1)]
        rows = trace_reader.step_anatomy(spans, events)
        assert len(rows) == 2
        r0 = rows[0]
        assert r0["step"] == 0 and r0["device"] == "/device:TPU:0"
        assert r0["compute_s"] == pytest.approx(70e-6)
        assert r0["collective_exposed_s"] == pytest.approx(20e-6)
        assert r0["bubble_s"] == pytest.approx(10e-6)
        assert r0["host_gap_s"] == pytest.approx(20e-6)
        assert r0["compute_pct"] == pytest.approx(100 * 70 / 120)
        r1 = rows[1]
        assert r1["compute_pct"] == pytest.approx(50.0)
        assert r1["collective_exposed_pct"] == pytest.approx(20.0)
        assert r1["bubble_pct"] == pytest.approx(10.0)
        assert r1["host_gap_pct"] == pytest.approx(20.0)
        # fully-overlapped collective costs nothing
        rows_overlap = trace_reader.step_anatomy(
            [_span("step", t0_ns=0, dur_ns=50_000, step=0)],
            [_dev("s/dot.1", 0, 50),
             _dev("s/all-reduce.2", 10, 20, "all-reduce")])
        assert rows_overlap[0]["collective_exposed_s"] == 0.0
        assert rows_overlap[0]["compute_pct"] == pytest.approx(100.0)

    def test_anatomy_without_steps_or_devices_is_empty(self):
        assert trace_reader.step_anatomy([], [_dev("a.1", 0, 1)]) == []
        assert trace_reader.step_anatomy(
            [_span("step", dur_ns=1000)], []) == []
        assert "anatomy" in trace_reader.format_anatomy([])

    def test_merged_timeline_holds_both_halves(self, tmp_path):
        spans = [_span("step", t0_ns=5_000_000, dur_ns=100_000, step=0),
                 _span("step/fwd_bwd", t0_ns=5_000_100, dur_ns=10,
                       traced=True)]
        events = [_dev("step/fwd_bwd/dot.1", 70_000.0, 50)]
        tl = trace_reader.merged_timeline(spans, events)
        xs = [e for e in tl["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == 3
        names = {e["name"] for e in xs}
        assert {"step", "step/fwd_bwd", "step/fwd_bwd/dot.1"} <= names
        # host step span aligned onto the first device event's start
        host_step = next(e for e in xs if e["name"] == "step")
        assert host_step["ts"] == pytest.approx(70_000.0)
        # traced spans ride a separate track from host-phase spans
        traced = next(e for e in xs if e["name"] == "step/fwd_bwd")
        assert traced["tid"] != host_step["tid"]
        procs = [e for e in tl["traceEvents"]
                 if e.get("name") == "process_name"]
        assert any("host:spans" in p["args"]["name"] for p in procs)
        assert any("/device:TPU:0" == p["args"]["name"] for p in procs)
        out = trace_reader.write_merged_timeline(
            str(tmp_path / "merged.json"), spans, events)
        with open(out) as fh:
            assert json.load(fh)["traceEvents"]

    def test_read_span_stream(self, tmp_path):
        path = tmp_path / "events.jsonl"
        path.write_text(
            json.dumps(_span("step", step=0)) + "\n"
            + json.dumps({"kind": "step", "schema": 1, "step": 0,
                          "dur_s": 0.1, "counters": {}, "gauges": {}})
            + "\n")
        spans = trace_reader.read_span_stream(str(path))
        assert len(spans) == 1 and spans[0]["name"] == "step"


class TestCostDB:
    """CostDB calibration (prof.calibrate): measured spans + counted
    bytes distilled into achieved rates, error vs ground truth bounded."""

    def _span_fixture(self):
        """Collective at a known bandwidth: 1 MiB psum over tp at
        8 GB/s ±6.25%, plus a ring hop and two GEMM executions."""
        from apex_tpu.prof import calibrate

        nbytes = 1 << 20
        rate = 8e9
        spans = [
            _span("fwd/psum_tp", coll="psum", axis="tp", bytes=nbytes,
                  traced=True),
            _span("fwd/ag_matmul_ring_tp", coll="ag_matmul_ring",
                  axis="tp", bytes=1 << 18, traced=True),
        ]
        dur_lo = nbytes / (rate * 1.0625) * 1e6  # us, fast sample
        dur_hi = nbytes / (rate * 0.9375) * 1e6  # us, slow sample
        events = [
            _dev("fwd/psum_tp/all-reduce.5", 0, dur_lo, "all-reduce"),
            _dev("fwd/psum_tp/all-reduce.5", 500, dur_hi, "all-reduce"),
            _dev("fwd/ag_matmul_ring_tp/collective-permute.3", 900, 32.768,
                 "collective-permute"),
            _dev("fwd/dot.1", 1000, 100, flops=2e9),
            _dev("fwd/dot.1", 2000, 100, flops=2e9),
        ]
        return calibrate, spans, events, nbytes, rate

    def test_build_costdb_from_spans_bounded_error(self):
        calibrate, spans, events, nbytes, rate = self._span_fixture()
        db = calibrate.build_costdb(spans, events, device_kind="TPU v5p",
                                    backend="tpu")
        assert db["source"] == "spans"
        rows = db["collectives"]["psum[tp]"]
        assert len(rows) == 1
        row = rows[0]
        assert row["bucket_bytes"] == nbytes  # exact power of two
        assert row["bytes_per_s"]["n"] == 2
        # calibration error vs the fixture's ground truth: the two
        # samples straddle 8 GB/s symmetrically, so the mean lands on it
        assert abs(row["bytes_per_s"]["mean"] - rate) / rate < 1e-6
        assert row["bytes_per_s"]["min"] == pytest.approx(rate * 0.9375)
        assert row["bytes_per_s"]["max"] == pytest.approx(rate * 1.0625)
        assert row["bytes_per_s"]["spread_pct"] == pytest.approx(
            100 * (1.0625 - 0.9375) / 0.9375)
        # the ring hop priced at its chunk size
        ring = db["collectives"]["ag_matmul_ring[tp]"][0]
        assert ring["bucket_bytes"] == 1 << 18
        assert ring["bytes_per_s"]["mean"] == pytest.approx(
            (1 << 18) / 32.768e-6)
        # GEMM class: 2e9 flops in 100us = 2e13 flops/s
        (cls, g), = db["gemms"].items()
        assert cls == f"flops_{calibrate.size_bucket(2e9)}"
        assert g["flops_per_s"]["mean"] == pytest.approx(2e13)
        assert g["flops_per_s"]["n"] == 2

    def test_costdb_roundtrips_through_validator(self, tmp_path):
        calibrate, spans, events, _, _ = self._span_fixture()
        db = calibrate.build_costdb(spans, events, device_kind="TPU v5p",
                                    backend="tpu",
                                    predicted_flops_per_s=2.5e13)
        assert calibrate.validate_costdb(db) == []
        path = calibrate.write_costdb(str(tmp_path / "costdb.json"), db)
        with open(path) as fh:
            loaded = json.load(fh)
        from apex_tpu.monitor import schema
        assert schema.validate(loaded) == []  # kind-dispatch
        assert loaded["gemms"][next(iter(loaded["gemms"]))][
            "predicted_flops_per_s"] == 2.5e13

    def test_write_refuses_invalid(self, tmp_path):
        from apex_tpu.prof import calibrate

        with pytest.raises(ValueError, match="invalid costdb"):
            calibrate.write_costdb(
                str(tmp_path / "bad.json"),
                {"schema": 1, "kind": "costdb", "collectives": "nope",
                 "gemms": {}})

    def test_counted_bytes_fallback(self):
        """Streams without collective spans price the trace's collective
        HLOs from the counted-bytes hooks — only unambiguous kinds."""
        from apex_tpu.prof import calibrate

        records = [
            {"kind": "step", "schema": 1, "step": 0, "dur_s": 0.1,
             "counters": {}, "gauges": {},
             "counters_total": {
                 "collective/all_gather[tp]_bytes": 3 * (1 << 16),
                 "collective/all_gather[tp]_calls": 3,
                 # psum counted on TWO axes: attribution is ambiguous,
                 # so psum events must produce no row
                 "collective/psum[dp]_bytes": 1024,
                 "collective/psum[dp]_calls": 1,
                 "collective/psum[tp]_bytes": 2048,
                 "collective/psum[tp]_calls": 1,
             }},
        ]
        events = [
            _dev("all-gather.7", 0, 8.192, "all-gather"),
            _dev("all-reduce.9", 100, 10, "all-reduce"),
        ]
        db = calibrate.build_costdb(records, events)
        assert db["source"] == "counters"
        assert list(db["collectives"]) == ["all_gather[tp]"]
        row = db["collectives"]["all_gather[tp]"][0]
        # 65536 bytes in 8.192us = 8e9 B/s, exactly
        assert row["bytes_per_s"]["mean"] == pytest.approx(8e9)

    def test_size_bucket(self):
        from apex_tpu.prof.calibrate import size_bucket

        assert size_bucket(1) == 1
        assert size_bucket(1023) == 512
        assert size_bucket(1024) == 1024
        assert size_bucket(1025) == 1024


class TestProfCLIExit:
    """`python -m apex_tpu.prof` on a traceless logdir exits 2 with a
    one-line error naming the searched glob (ISSUE satellite)."""

    def test_missing_logdir_exits_2(self, tmp_path, capsys):
        from apex_tpu.prof.__main__ import main

        rc = main([str(tmp_path)])
        assert rc == 2
        err = capsys.readouterr().err
        assert err.count("\n") == 1  # one line
        assert "searched" in err
        assert os.path.join("plugins", "profile", "*") in err

    def test_anatomy_and_merged_flags(self, tmp_path, capsys, logdir):
        from apex_tpu.prof.__main__ import main

        spans_path = tmp_path / "spans.jsonl"
        spans_path.write_text(
            json.dumps(_span("step", t0_ns=1000, dur_ns=500_000, step=0))
            + "\n")
        out = tmp_path / "merged.json"
        rc = main([logdir, "--spans", str(spans_path), "--anatomy",
                   "--merged", str(out)])
        assert rc == 0
        text = capsys.readouterr().out
        assert "step anatomy" in text
        assert out.exists()
        with open(out) as fh:
            assert json.load(fh)["traceEvents"]
