"""Speculative decoding subsystem (ISSUE 15): the fused
verify-and-accept tail, the drafter framework, engine integration at
batch 1 and under scheduler churn, the rewind contract, int8 KV
quantization, and the spec record/gate plumbing.

The load-bearing witnesses:

* greedy spec output TOKEN-IDENTICAL to the non-speculative baseline
  for BOTH drafters, batch 1 and under churn, with every jitted body's
  cache size pinned at 1 across spec rounds;
* the fused verify kernel == the XLA fallback token-for-token on
  shared noise (greedy and rejection-sampling modes);
* a scripted worst-case all-rejected round under churn restores block
  tables/lengths/free-list exactly and the resumed stream equals the
  non-speculative stream;
* int8-KV decode logit error bounded against the float parity oracle
  (which stays the default pool);
* eager knob-naming validation (vocab/kv_dtype/batch/bounds) — never a
  deep XLA shape error;
* the CLOSED ``spec`` schema's drift tests (nan-in-OK fails, junk keys
  fail, reason-less SKIP fails).
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.inference import DecodeEngine
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops import fused_verify
from apex_tpu.serving import Request, ServeTelemetry, ServingEngine
from apex_tpu.spec import Drafter, ModelDrafter, NGramDrafter, validate_drafter

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import validate_metrics  # noqa: E402

_CFG = dict(vocab_size=256, max_seq_len=256, hidden_size=64,
            num_layers=2, num_heads=4, tp_size=1, remat=False,
            attention_impl="flash")


def _model(seed=0, **over):
    cfg = GPTConfig(**{**_CFG, **over})
    model = GPTModel(cfg)
    return model, model.init(jr.PRNGKey(seed))


def _requests(n=6, seed=0, vocab=256, prompt_rng=(4, 40), newtok=(2, 10)):
    rng = np.random.default_rng(seed)
    return [Request(
        rid=i,
        prompt=rng.integers(0, vocab, int(rng.integers(*prompt_rng))
                            ).astype(np.int32),
        max_new_tokens=int(rng.integers(*newtok)))
        for i in range(n)]


class WrongDrafter(Drafter):
    """Adversarial scripted drafter: proposes the BASELINE stream's
    next token + 1 (mod V) at every position — guaranteed first-row
    rejection under greedy verification, so every round is the
    worst case (accept_len == 0, one corrected token emitted)."""

    def __init__(self, k, baseline_by_len, vocab):
        self.k = int(k)
        self._by_len = baseline_by_len  # context len -> true next token
        self._vocab = int(vocab)

    def propose(self, stream, context):
        nxt = self._by_len.get((stream, len(context)), 0)
        return np.full((self.k,), (nxt + 1) % self._vocab, np.int32)


# --- the fused verify op ------------------------------------------------------

class TestFusedVerify:
    def _logits(self, b=3, K=4, V=256, seed=0):
        return jax.random.normal(jr.PRNGKey(seed), (b, K + 1, V))

    def test_greedy_accept_semantics(self):
        logits = self._logits()
        cand = np.asarray(jnp.argmax(logits, -1))
        V = logits.shape[-1]
        drafted = np.zeros((3, 4), np.int32)
        drafted[0] = [cand[0, 0], cand[0, 1], (cand[0, 2] + 1) % V,
                      cand[0, 3]]
        drafted[1] = [(cand[1, 0] + 1) % V] * 4
        drafted[2] = cand[2, :4]
        a, nxt = fused_verify(logits, jnp.asarray(drafted))
        assert list(np.asarray(a)) == [2, 0, 4]
        # the corrected token is row a's candidate — a match with what
        # the non-speculative greedy loop would have produced
        assert list(np.asarray(nxt)) == [cand[0, 2], cand[1, 0],
                                         cand[2, 4]]

    def test_kernel_matches_fallback_greedy(self):
        logits = self._logits(b=5, K=3)
        drafted = jnp.asarray(
            np.asarray(jnp.argmax(logits, -1))[:, :3])  # mostly accept
        a1, t1 = fused_verify(logits, drafted, impl="xla")
        a2, t2 = fused_verify(logits, drafted, impl="pallas")
        assert (np.asarray(a1) == np.asarray(a2)).all()
        assert (np.asarray(t1) == np.asarray(t2)).all()

    @pytest.mark.parametrize("K", [8, 32])
    def test_kernel_handles_long_drafts(self, K):
        """The drafted-id/noise operands ride a full 128-lane block —
        every k validate_drafter allows must run the kernel path, not
        crash at the old 8-lane carrier width (review finding): K=8 is
        the first broken width, K=32 the MAX_DRAFT_K ceiling."""
        logits = self._logits(b=2, K=K, seed=K)
        drafted = jnp.asarray(np.asarray(jnp.argmax(logits, -1))[:, :K])
        a1, t1 = fused_verify(logits, drafted, impl="xla")
        a2, t2 = fused_verify(logits, drafted, impl="pallas")
        assert (np.asarray(a1) == np.asarray(a2)).all()
        assert (np.asarray(t1) == np.asarray(t2)).all()
        key = jr.PRNGKey(1)
        a3, t3 = fused_verify(logits, drafted, key, temperature=0.9,
                              top_k=11, impl="xla")
        a4, t4 = fused_verify(logits, drafted, key, temperature=0.9,
                              top_k=11, impl="pallas")
        assert (np.asarray(a3) == np.asarray(a4)).all()
        assert (np.asarray(t3) == np.asarray(t4)).all()

    @pytest.mark.parametrize("top_k,top_p", [(0, 1.0), (17, 1.0),
                                             (0, 0.9), (13, 0.85)])
    def test_kernel_matches_fallback_sampled(self, top_k, top_p):
        """Shared-noise discipline: temperature/top-k/top-p rejection
        sampling agrees token-for-token across impls (the fused_sample
        parity anchor, extended to the verify tail)."""
        logits = self._logits(b=4, K=4, seed=3)
        drafted = jnp.asarray(np.asarray(jnp.argmax(logits, -1))[:, :4])
        key = jr.PRNGKey(11)
        a1, t1 = fused_verify(logits, drafted, key, temperature=0.7,
                              top_k=top_k, top_p=top_p, impl="xla")
        a2, t2 = fused_verify(logits, drafted, key, temperature=0.7,
                              top_k=top_k, top_p=top_p, impl="pallas")
        assert (np.asarray(a1) == np.asarray(a2)).all()
        assert (np.asarray(t1) == np.asarray(t2)).all()

    def test_sampled_acceptance_is_exact_for_sure_things(self):
        """A drafted token carrying ~all filtered probability mass is
        always accepted; one the filter removed is always rejected."""
        V = 128
        logits = np.full((1, 3, V), -20.0, np.float32)
        logits[0, :, 7] = 20.0  # a near-point-mass target distribution
        drafted = np.array([[7, 3]], np.int32)  # d0 sure, d1 filtered-out
        a, nxt = fused_verify(jnp.asarray(logits), jnp.asarray(drafted),
                              jr.PRNGKey(0), temperature=1.0, top_k=1)
        assert int(np.asarray(a)[0]) == 1  # d0 accepted, d1 rejected
        # the residual excludes the rejected draft; with top_k=1 only
        # token 7 survives the filter, and 7 != 3 keeps it drawable
        assert int(np.asarray(nxt)[0]) == 7

    def test_validation_names_the_contract(self):
        logits = self._logits()
        with pytest.raises(ValueError, match=r"\(b, k\+1, V\)"):
            fused_verify(logits[0], jnp.zeros((3, 4), jnp.int32))
        with pytest.raises(ValueError, match="drafted must be"):
            fused_verify(logits, jnp.zeros((3, 2), jnp.int32))
        with pytest.raises(ValueError, match="requires a PRNG key"):
            fused_verify(logits, jnp.zeros((3, 4), jnp.int32),
                         temperature=0.5)
        with pytest.raises(ValueError, match="fused_sample"):
            fused_verify(logits[:, :1], jnp.zeros((3, 0), jnp.int32))


# --- drafters -----------------------------------------------------------------

class TestDrafters:
    def test_ngram_proposes_static_k_and_learns_repeats(self):
        d = NGramDrafter(k=4, n=2)
        ctx = [1, 2, 3, 1, 2, 3, 1, 2]
        out = d.propose(0, ctx)
        assert out.shape == (4,) and out.dtype == np.int32
        # the order-2 table maps (1, 2) -> 3, (2, 3) -> 1, (3, 1) -> 2
        assert list(out) == [3, 1, 2, 3]
        d.release(0)
        assert 0 not in d._streams

    def test_ngram_incremental_state_survives_context_growth(self):
        d = NGramDrafter(k=2, n=2)
        d.propose(7, [1, 2, 3])
        table, consumed = d._streams[7]
        assert consumed == 3
        d.propose(7, [1, 2, 3, 4, 5])
        table2, consumed2 = d._streams[7]
        assert consumed2 == 5 and table2 is table  # incremental, not rebuilt
        # a SHRUNK context (reused stream id) resets instead of aliasing
        d.propose(7, [9, 9])
        assert d._streams[7][1] == 2

    def test_model_drafter_single_compile_across_streams(self):
        dm, dp = _model(seed=5, num_layers=1, hidden_size=32, num_heads=2)
        d = ModelDrafter(dm, dp, k=3)
        for stream in range(3):
            out = d.propose(stream, [1, 2, 3, 4, 5 + stream])
            assert out.shape == (3,)
        assert d.engine.decode_step._cache_size() == 1
        d.release(1)
        assert 1 not in d._streams and 0 in d._streams

    def test_validate_drafter_names_every_knob(self):
        model, _ = _model()
        dm, dp = _model(seed=1, vocab_size=128)
        with pytest.raises(ValueError, match="vocab_size"):
            validate_drafter(ModelDrafter(dm, dp, k=2), model.config,
                             needed_rows=8)
        with pytest.raises(ValueError, match=r"draft\.k"):
            validate_drafter(NGramDrafter.__new__(NGramDrafter),
                             model.config, needed_rows=8)
        with pytest.raises(ValueError, match="block_size"):
            dm2, dp2 = _model(seed=2)
            validate_drafter(ModelDrafter(dm2, dp2, k=2, block_size=64),
                             model.config, needed_rows=8, block_size=16)
        with pytest.raises(ValueError, match="max_seq_len"):
            dm3, dp3 = _model(seed=3, max_seq_len=128)
            validate_drafter(ModelDrafter(dm3, dp3, k=2), model.config,
                             needed_rows=10_000)
        with pytest.raises(ValueError, match=r"k must be"):
            NGramDrafter(k=0)


# --- DecodeEngine speculation -------------------------------------------------

class TestDecodeEngineSpec:
    def test_greedy_parity_both_drafters(self):
        model, params = _model()
        eng = DecodeEngine(model)
        prompt = jr.randint(jr.PRNGKey(1), (1, 24), 0, 256)
        base = np.asarray(eng.generate(params, prompt, 20))
        out = np.asarray(eng.generate(params, prompt, 20,
                                      draft=NGramDrafter(k=4)))
        assert (out == base).all()
        dm, dp = _model(seed=3, num_layers=1, hidden_size=32, num_heads=2)
        md = ModelDrafter(dm, dp, k=4)  # same static k: one executable
        out2 = np.asarray(eng.generate(params, prompt, 20, draft=md))
        assert (out2 == base).all()
        # one executable for EVERY jitted body across spec rounds
        assert eng.spec_verify_step._cache_size() == 1
        assert eng.decode_step._cache_size() == 1
        assert md.engine.decode_step._cache_size() == 1

    def test_self_drafter_accepts_everything(self):
        """The exactness sanity: drafting with the TARGET model itself
        must accept every draft (the verifier reproduces the drafter's
        own greedy choices)."""
        model, params = _model()
        eng = DecodeEngine(model)
        prompt = jr.randint(jr.PRNGKey(2), (1, 16), 0, 256)
        base = np.asarray(eng.generate(params, prompt, 12))
        out = np.asarray(eng.generate(params, prompt, 12,
                                      draft=ModelDrafter(model, params,
                                                         k=3)))
        assert (out == base).all()
        assert eng.last_spec_stats.acceptance_rate == 1.0

    def test_all_rejected_drafter_still_exact(self):
        """The scripted worst case at batch 1: every round rejects at
        row 0 and emits exactly the corrected (baseline) token."""
        model, params = _model()
        eng = DecodeEngine(model)
        prompt = jr.randint(jr.PRNGKey(3), (1, 16), 0, 256)
        T = 10
        base = np.asarray(eng.generate(params, prompt, T))
        by_len = {(0, 16 + i): int(base[0, i]) for i in range(T)}
        out = np.asarray(eng.generate(params, prompt, T,
                                      draft=WrongDrafter(3, by_len, 256)))
        assert (out == base).all()
        st = eng.last_spec_stats
        assert st.accepted == 0 and st.rounds == T - 1

    def test_sampled_spec_generates_within_bounds(self):
        """temperature>0 spec runs the rejection-sampling tail; the
        output is a valid token stream of the right shape (exact
        distributional parity is the op-level test's job)."""
        model, params = _model()
        eng = DecodeEngine(model, temperature=0.8, top_k=20)
        prompt = jr.randint(jr.PRNGKey(4), (1, 16), 0, 256)
        out = np.asarray(eng.generate(params, prompt, 8,
                                      key=jr.PRNGKey(9),
                                      draft=NGramDrafter(k=3)))
        assert out.shape == (1, 8)
        assert ((out >= 0) & (out < 256)).all()

    def test_eager_validation(self):
        model, params = _model()
        eng = DecodeEngine(model)
        prompt2 = jr.randint(jr.PRNGKey(5), (2, 16), 0, 256)
        with pytest.raises(ValueError, match="batch 1"):
            eng.generate(params, prompt2, 4, draft=NGramDrafter(k=2))
        prompt = prompt2[:1]
        dm, dp = _model(seed=6, vocab_size=128)
        with pytest.raises(ValueError, match="vocab_size"):
            eng.generate(params, prompt, 4,
                         draft=ModelDrafter(dm, dp, k=2))
        with pytest.raises(ValueError, match=r"draft\.k"):
            eng.generate(params, prompt, 4,
                         draft=WrongDrafter.__new__(WrongDrafter))
        # 16 + 238 fits the cache for PLAIN decode, but the k=4 draft
        # rows push past it: the SPEC bound must fire, naming draft.k
        with pytest.raises(ValueError, match=r"draft\.k \(4\)"):
            eng.generate(params, prompt, 238, draft=NGramDrafter(k=4))


# --- ServingEngine speculation under churn ------------------------------------

class TestServingSpec:
    def _serve_pair(self, draft_factory, *, num_blocks=None, n=6,
                    kv_dtype=None):
        model, params = _model()
        mk = lambda: ServingEngine(  # noqa: E731
            model, num_slots=3, block_size=16, prefill_chunk=16,
            num_blocks=num_blocks, kv_dtype=kv_dtype)
        base_eng = mk()
        base = base_eng.serve(params, _requests(n), telemetry=False)
        spec_eng = mk()
        out = spec_eng.serve(params, _requests(n), telemetry=False,
                             draft=draft_factory())
        return base, out, spec_eng

    def test_churn_parity_ngram(self):
        base, out, eng = self._serve_pair(lambda: NGramDrafter(k=3))
        want = {r.rid: list(r.tokens) for r in base}
        assert all(list(r.tokens) == want[r.rid] for r in out)
        assert eng.last_stats.spec_rounds > 0
        assert eng.prefill_chunk._cache_size() == 1
        assert eng.spec_step._cache_size() == 1
        assert eng.decode_step._cache_size() <= 1  # may never dispatch

    def test_churn_parity_model_drafter(self):
        dm, dp = _model(seed=7, num_layers=1, hidden_size=32, num_heads=2)
        base, out, eng = self._serve_pair(
            lambda: ModelDrafter(dm, dp, k=3))
        want = {r.rid: list(r.tokens) for r in base}
        assert all(list(r.tokens) == want[r.rid] for r in out)
        assert eng.spec_step._cache_size() == 1

    def test_churn_parity_under_pool_pressure(self):
        """An undersized pool forces preemption DURING spec rounds —
        evict/readmit, drafter streams surviving eviction, block
        rewind — and the streams must still match the (equally
        pressured) non-speculative baseline."""
        base, out, eng = self._serve_pair(lambda: NGramDrafter(k=3),
                                          num_blocks=13, n=8)
        want = {r.rid: list(r.tokens) for r in base}
        assert all(list(r.tokens) == want[r.rid] for r in out)
        assert eng.spec_step._cache_size() == 1
        assert eng.prefill_chunk._cache_size() == 1

    def test_spec_telemetry_events_and_acceptance(self, tmp_path):
        """Spec rounds emit schema-valid ``spec``-phase lifecycle
        events and the serve-record fields carry the acceptance
        rollup."""
        model, params = _model()
        eng = ServingEngine(model, num_slots=2, block_size=16,
                            prefill_chunk=16)
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            tel = ServeTelemetry(slots=2, window_s=0, status="SKIP",
                                 reason="cpu test")
            eng.serve(params, _requests(2), telemetry=tel,
                      draft=NGramDrafter(k=3))
        finally:
            monitor.disable()
        lines = [json.loads(ln) for ln in path.read_text().splitlines()]
        spec_events = [r for r in lines if r.get("phase") == "spec"]
        assert spec_events, "no spec lifecycle events emitted"
        for r in spec_events:
            assert monitor.validate(r) == []
            assert 0 <= r["accepted_len"] <= r["draft_k"] == 3
        fields = tel.final_fields(None, None)
        # one lifecycle record per slot-round, mirrored in the rollup
        # (spec_slot_rounds: slot×dispatch — the engine's
        # last_stats.spec_rounds counts dispatches)
        assert fields["spec_slot_rounds"] == len(spec_events)
        assert fields["spec_drafted"] == 3 * len(spec_events)
        assert 0.0 <= fields["spec_acceptance_rate"] <= 1.0
        assert fields["draft_k"] == 3

    def test_int8_spec_matches_int8_plain(self):
        """Speculation composes with the quantized pool: int8+spec is
        token-identical to int8 without spec (the parity oracle for
        the composition)."""
        base, out, eng = self._serve_pair(lambda: NGramDrafter(k=3),
                                          kv_dtype="int8")
        want = {r.rid: list(r.tokens) for r in base}
        assert all(list(r.tokens) == want[r.rid] for r in out)


class TestRewindContract:
    def test_all_rejected_round_restores_pool_state(self):
        """The satellite's scripted worst case: drive ONE spec round
        whose drafts are all rejected and assert block tables, lengths,
        and the allocator free list are exactly what a plain decode
        step would have left — then that the resumed stream is
        token-identical to non-speculative decode."""
        model, params = _model()
        # baseline stream for the adversarial drafter and the final
        # check; a 14-token prompt makes the k=3 reservation CROSS a
        # block boundary, so the rewind really frees a block
        ref_eng = ServingEngine(model, num_slots=2, block_size=16,
                                prefill_chunk=16)
        req = _requests(1, prompt_rng=(14, 15), newtok=(8, 9))
        base = ref_eng.serve(params, _requests(
            1, prompt_rng=(14, 15), newtok=(8, 9)), telemetry=False)
        base_tokens = list(base[0].tokens)
        rid = base[0].rid
        plen = len(base[0].prompt)
        by_len = {(rid, plen + i): t for i, t in enumerate(base_tokens)}

        eng = ServingEngine(model, num_slots=2, block_size=16,
                            prefill_chunk=16)
        sched = eng.make_scheduler()
        K = 3
        draft = WrongDrafter(K, by_len, 256)
        pool = eng.init_pool()
        key = jr.PRNGKey(0)
        r = req[0]
        sched.submit(r)
        sched.admit(0.0)
        while True:
            w = sched.next_prefill(0.0)
            if w is None:
                break
            pool, tok, _ = eng.prefill_chunk(
                params, pool, jnp.asarray(sched.tables.row(w.slot)),
                jnp.asarray(w.tokens), jnp.int32(w.start),
                jnp.int32(w.live), key)
            sched.note_prefill(w, int(tok), 0.0)
        (slot,) = sched.decoding_slots()
        # snapshot BEFORE the round
        free_before = list(sched.allocator._free)
        table_before = sched.tables.asarray().copy()
        len_before = sched.slot_length(slot)
        # one all-rejected spec round
        toks, lens = sched.decode_batch(0.0, lookahead=K)
        drafted = np.zeros((2, K), np.int32)
        drafted[slot] = draft.propose(rid, sched.slot_context(slot))
        tok_mat = np.zeros((2, K + 1), np.int32)
        tok_mat[:, 0] = toks
        tok_mat[:, 1:] = drafted
        pool, acc, nxt = eng.spec_step(
            params, pool, jnp.asarray(sched.tables.asarray()),
            jnp.asarray(tok_mat), jnp.asarray(lens),
            jnp.asarray(drafted), key)
        acc, nxt = np.asarray(acc), np.asarray(nxt)
        assert int(acc[slot]) == 0  # the scripted worst case engaged
        sched.note_spec(drafted, acc, nxt, 0.0)
        # the round emitted exactly the baseline's next token
        assert list(r.tokens)[-1] == base_tokens[len(r.tokens) - 1]
        # lengths advanced by exactly one (the corrected token's row)
        assert sched.slot_length(slot) == len_before + 1
        # block tables: entries past the frontier rewound to dead block,
        # entries at/below it untouched
        import apex_tpu.serving.kv_blocks as kvb
        keep = kvb.blocks_needed(sched.slot_length(slot), 16)
        table_now = sched.tables.asarray()
        assert (table_now[slot, :keep] == table_before[slot, :keep]).all()
        assert (table_now[slot, keep:] == kvb.DEAD_BLOCK).all()
        # free list EXACTLY restored minus the (possibly zero) blocks a
        # plain decode step would also have claimed for the new row
        claimed = keep - kvb.blocks_needed(len_before, 16)
        assert sched.allocator._free == free_before[:len(free_before)
                                                    - claimed]
        sched.allocator.check_accounting()
        # drive the stream to completion WITHOUT speculation: the
        # resumed stream must be the non-speculative stream
        while True:
            batch = sched.decode_batch(0.0)
            if batch is None:
                break
            toks, lens = batch
            pool, sampled, _ = eng.decode_step(
                params, pool, jnp.asarray(sched.tables.asarray()),
                jnp.asarray(toks), jnp.asarray(lens), key)
            sched.note_decode(np.asarray(sampled), 0.0)
        assert list(r.tokens) == base_tokens
        assert eng.spec_step._cache_size() == 1


# --- int8 KV quantization -----------------------------------------------------

class TestQuantizedKV:
    def test_logit_error_bounded_vs_float_oracle(self):
        """Teacher-forced decode logits through the int8 pool stay
        within a small bound of the float pool's — the parity oracle
        the record's kv_quant_logit_err field reports."""
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
        import bench
        model, params = _model()
        prompt = np.asarray(jr.randint(jr.PRNGKey(1), (32,), 0, 256),
                            np.int32)
        err, q_mb, o_mb = bench._spec_quant_err(
            model, params, prompt, 8, slots=1, block=16, chunk=16,
            cast=None)
        assert err < 0.05, f"int8 KV logit error {err} out of bound"
        assert q_mb < o_mb  # the pool really shrank

    def test_pool_layout_and_bytes(self):
        model, params = _model()
        q = ServingEngine(model, num_slots=2, block_size=16,
                          kv_dtype="int8")
        f = ServingEngine(model, num_slots=2, block_size=16)
        pool = q.init_pool()
        assert pool["k"].dtype == jnp.int8
        assert pool["k_scale"].shape == (2, q.num_blocks, 16)
        # int8 + fp32 scales still well under half the fp32 oracle
        assert q.pool_bytes() < f.pool_bytes() / 2
        # the float pool stays the default (the parity oracle)
        assert "k_scale" not in f.init_pool()

    def test_quantized_serve_stream_is_reasonable(self):
        """The int8 engine serves end to end; its streams may differ
        from the oracle's token-for-token (quantization is lossy) but
        lengths and accounting must hold."""
        model, params = _model()
        eng = ServingEngine(model, num_slots=2, block_size=16,
                            prefill_chunk=16, kv_dtype="int8")
        done = eng.serve(params, _requests(4), telemetry=False)
        assert len(done) == 4
        assert all(len(r.tokens) == r.max_new_tokens for r in done)
        assert eng.decode_step._cache_size() == 1
        assert eng.prefill_chunk._cache_size() == 1

    def test_eager_kv_dtype_validation(self):
        model, params = _model()
        with pytest.raises(ValueError, match="kv_dtype"):
            ServingEngine(model, num_slots=2, block_size=16,
                          kv_dtype="fp8")
        # a model with a decode relative bias cannot ride the int8 path
        model.decode_rel_bias = lambda p: None
        with pytest.raises(ValueError, match="relative-position bias"):
            ServingEngine(model, num_slots=2, block_size=16,
                          kv_dtype="int8")

    def test_rel_bias_models_cannot_speculate(self):
        """The spec verify bodies do not thread the bucketed decode
        bias, so both draft= paths must refuse a decode_rel_bias model
        eagerly (review finding: a silent accept/reject against
        unbiased spec logits would break the parity contract)."""
        model, params = _model()
        model.decode_rel_bias = lambda p: None
        eng = DecodeEngine(model)
        prompt = jr.randint(jr.PRNGKey(1), (1, 8), 0, 256)
        with pytest.raises(ValueError, match="relative-position bias"):
            eng.generate(params, prompt, 4, draft=NGramDrafter(k=2))
        srv = ServingEngine(model, num_slots=2, block_size=16)
        with pytest.raises(ValueError, match="relative-position bias"):
            srv.serve(params, _requests(1), telemetry=False,
                      draft=NGramDrafter(k=2))

    def test_decode_attention_scale_contract(self):
        from apex_tpu.ops import decode_attention
        q = jnp.zeros((1, 4, 64))
        pool8 = jnp.zeros((4, 2, 128, 64), jnp.int8)
        poolf = jnp.zeros((4, 2, 128, 64))
        tables = jnp.zeros((1, 2), jnp.int32)
        lengths = jnp.ones((1,), jnp.int32)
        sc = jnp.ones((4, 128))
        with pytest.raises(ValueError, match="PAGED path only"):
            decode_attention(q, pool8, pool8, lengths)
        with pytest.raises(ValueError, match="BOTH k_scale and v_scale"):
            decode_attention(q, pool8, pool8, lengths,
                             block_tables=tables, k_scale=sc)
        with pytest.raises(ValueError, match="BOTH k_scale and v_scale"):
            decode_attention(q, poolf, poolf, lengths,
                             block_tables=tables, k_scale=sc, v_scale=sc)
        with pytest.raises(ValueError, match="per-row scales"):
            decode_attention(q, pool8, pool8, lengths,
                             block_tables=tables,
                             k_scale=jnp.ones((4, 64)), v_scale=sc)


# --- the spec record / schema drift -------------------------------------------

class TestSpecRecord:
    def _ok_fields(self):
        return dict(tokens_per_s_request=100.0, acceptance_rate=0.8,
                    draft_k=4, drafter="ngram", greedy_parity=True,
                    jit_cache_ok=True, backend="cpu")

    def test_ok_record_validates(self):
        rec = monitor.MetricsRegistry().emit_spec("OK", **self._ok_fields())
        assert monitor.validate(rec) == []

    def test_nan_in_ok_fails(self):
        with pytest.raises(ValueError, match="non-finite"):
            monitor.MetricsRegistry().emit_spec(
                "OK", tokens_per_s_request=float("nan"))
        # and an externally-produced nan record fails the validator too
        rec = monitor.MetricsRegistry().emit_spec("OK",
                                                  **self._ok_fields())
        rec["acceptance_rate"] = float("nan")
        assert any("non-finite" in e for e in monitor.validate(rec))

    def test_junk_key_fails_closed_schema(self):
        rec = monitor.MetricsRegistry().emit_spec("OK", **self._ok_fields())
        rec["junk_key"] = 1
        assert any("unexpected key" in e for e in monitor.validate(rec))

    def test_reasonless_skip_fails(self):
        with pytest.raises(ValueError, match="reason"):
            monitor.MetricsRegistry().emit_spec("SKIP")
        rec = monitor.MetricsRegistry().emit_spec("SKIP", reason="x")
        del rec["reason"]
        assert any("reason" in e for e in monitor.validate(rec))

    def test_validator_cli_forced_and_content_dispatch(self, tmp_path):
        rec = monitor.MetricsRegistry().emit_spec("OK", **self._ok_fields())
        good = tmp_path / "spec.json"
        good.write_text(json.dumps(rec))
        assert validate_metrics.main(["--spec", str(good)]) == 0
        # content dispatch: no flag needed, kind routes the schema
        assert validate_metrics.main([str(good)]) == 0
        # a file that lost its kind fails AS a spec artifact
        bad = tmp_path / "lost.json"
        stripped = {k: v for k, v in rec.items() if k != "kind"}
        bad.write_text(json.dumps(stripped))
        assert validate_metrics.main(["--spec", str(bad)]) == 1
        # junk keys fail through the CLI too
        rec2 = dict(rec, junk=1)
        junk = tmp_path / "junk.json"
        junk.write_text(json.dumps(rec2))
        assert validate_metrics.main(["--spec", str(junk)]) == 1

    def test_report_renders_spec_line(self):
        rec = monitor.MetricsRegistry().emit_spec(
            "OK", **{**self._ok_fields(), "speedup": 1.5,
                     "kv_quant_logit_err": 0.01})
        summary = monitor.aggregate([rec])
        assert summary["spec"]["speedup"] == 1.5
        from apex_tpu.monitor.report import render
        text = render(summary)
        assert "spec" in text and "1.50x vs non-spec" in text
        assert "accept 80%" in text
