"""Unit tests for the fused-op tier — kernel vs jnp reference.

Mirrors the reference's L0 suites ``tests/L0/run_fused_layer_norm``,
``run_mlp``, ``run_transformer/test_fused_softmax.py`` and the contrib
xentropy/focal-loss tests: each fused op is compared against a plain jnp
composition at tight tolerances, forward and backward.

The XLA path runs for every op; the Pallas kernels additionally run in
interpret mode on tiny shapes (interpret mode is slow, so these are minimal).
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from apex_tpu import ops


K = jr.PRNGKey(42)


def ref_layer_norm(x, w, b, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    y = (x - m) / jnp.sqrt(v + eps)
    if w is not None:
        y = y * w
    if b is not None:
        y = y + b
    return y


def ref_rms_norm(x, w, eps=1e-5):
    y = x / jnp.sqrt((x * x).mean(-1, keepdims=True) + eps)
    return y * w if w is not None else y


class TestLayerNorm:
    def test_forward_matches_reference(self):
        x = jr.normal(K, (4, 9, 256)) * 3 + 1
        w = jr.normal(jr.fold_in(K, 1), (256,)) * 0.2 + 1
        b = jr.normal(jr.fold_in(K, 2), (256,)) * 0.2
        np.testing.assert_allclose(
            ops.fused_layer_norm(x, w, b), ref_layer_norm(x, w, b), atol=2e-6
        )

    def test_grads_match_reference(self):
        x = jr.normal(K, (6, 256)) * 2
        w = jnp.ones((256,)) * 1.3
        b = jnp.zeros((256,)) + 0.1
        f1 = lambda x, w, b: jnp.sum(jnp.sin(ops.fused_layer_norm(x, w, b)))
        f2 = lambda x, w, b: jnp.sum(jnp.sin(ref_layer_norm(x, w, b)))
        g1 = jax.grad(f1, argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(f2, argnums=(0, 1, 2))(x, w, b)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, atol=1e-5)

    def test_no_affine(self):
        x = jr.normal(K, (5, 128))
        np.testing.assert_allclose(
            ops.fused_layer_norm(x), ref_layer_norm(x, None, None), atol=2e-6
        )

    def test_unaligned_hidden_falls_back(self):
        # hidden=100 not a lane multiple: auto must still work (XLA path)
        x = jr.normal(K, (4, 100))
        w = jnp.ones((100,))
        b = jnp.zeros((100,))
        np.testing.assert_allclose(
            ops.fused_layer_norm(x, w, b), ref_layer_norm(x, w, b), atol=2e-6
        )

    def test_pallas_explicit_raises_on_bad_shape(self):
        x = jr.normal(K, (4, 100))
        with pytest.raises(ValueError):
            ops.fused_layer_norm(x, impl="pallas")

    def test_module_wrapper(self):
        m = ops.FusedLayerNorm(256)
        params = m.init()
        x = jr.normal(K, (3, 256))
        np.testing.assert_allclose(
            m(params, x), ref_layer_norm(x, params["weight"], params["bias"]), atol=2e-6
        )

    def test_bf16_input_fp32_stats(self):
        # mixed-dtype variant: bf16 in, fp32 statistics
        x = (jr.normal(K, (8, 256)) * 2 + 100).astype(jnp.bfloat16)
        w = jnp.ones((256,), jnp.float32)
        y = ops.fused_layer_norm(x, w, jnp.zeros((256,), jnp.float32))
        assert y.dtype == jnp.bfloat16
        ref = ref_layer_norm(x.astype(jnp.float32), w, None)
        np.testing.assert_allclose(
            y.astype(jnp.float32), ref, atol=0.1
        )  # bf16 output tolerance


class TestRMSNorm:
    def test_forward_and_grad(self):
        x = jr.normal(K, (4, 384)) * 2
        w = jr.normal(jr.fold_in(K, 3), (384,)) * 0.1 + 1
        np.testing.assert_allclose(ops.fused_rms_norm(x, w), ref_rms_norm(x, w), atol=2e-6)
        g1 = jax.grad(lambda x, w: jnp.sum(jnp.cos(ops.fused_rms_norm(x, w))), (0, 1))(x, w)
        g2 = jax.grad(lambda x, w: jnp.sum(jnp.cos(ref_rms_norm(x, w))), (0, 1))(x, w)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, atol=1e-5)


class TestSoftmax:
    def test_masked(self):
        x = jr.normal(K, (2, 4, 8, 128))
        mask = jr.bernoulli(jr.fold_in(K, 4), 0.3, (2, 1, 8, 128))
        ref = jax.nn.softmax(jnp.where(mask, -10000.0, x * 0.5), -1)
        np.testing.assert_allclose(
            ops.scaled_masked_softmax(x, mask, 0.5), ref, atol=1e-6
        )

    def test_masked_grad(self):
        x = jr.normal(K, (1, 2, 8, 128))
        mask = jr.bernoulli(jr.fold_in(K, 5), 0.2, (1, 1, 8, 128))
        g1 = jax.grad(lambda x: jnp.sum(jnp.sin(ops.scaled_masked_softmax(x, mask, 0.7))))(x)
        g2 = jax.grad(
            lambda x: jnp.sum(jnp.sin(jax.nn.softmax(jnp.where(mask, -10000.0, x * 0.7), -1)))
        )(x)
        np.testing.assert_allclose(g1, g2, atol=1e-6)

    def test_causal(self):
        x = jr.normal(K, (6, 16, 128))
        q = jnp.arange(16)[:, None]
        kk = jnp.arange(128)[None, :]
        ref = jax.nn.softmax(jnp.where(kk <= q, x * 2.0, -10000.0), -1)
        np.testing.assert_allclose(
            ops.scaled_upper_triang_masked_softmax(x, 2.0), ref, atol=1e-6
        )

    def test_no_seq_cap(self):
        # the CUDA kernels cap sk at 2048 (fused_softmax.py:166); we don't
        x = jr.normal(K, (1, 1, 2, 4096))
        ref = jax.nn.softmax(x, -1)
        np.testing.assert_allclose(ops.scaled_masked_softmax(x, None, 1.0), ref, atol=1e-6)


class TestFusedDense:
    def test_dense(self):
        x = jr.normal(K, (6, 256))
        w = jr.normal(jr.fold_in(K, 6), (128, 256)) * 0.05
        b = jr.normal(jr.fold_in(K, 7), (128,)) * 0.05
        np.testing.assert_allclose(
            ops.fused_dense(x, w, b), x @ w.T + b, atol=1e-5
        )

    def test_dense_grad(self):
        x = jr.normal(K, (6, 256))
        w = jr.normal(jr.fold_in(K, 8), (128, 256)) * 0.05
        b = jnp.zeros((128,))
        g1 = jax.grad(lambda x, w, b: jnp.sum(jnp.tanh(ops.fused_dense(x, w, b))), (0, 1, 2))(x, w, b)
        g2 = jax.grad(lambda x, w, b: jnp.sum(jnp.tanh(x @ w.T + b)), (0, 1, 2))(x, w, b)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, atol=1e-5)

    def test_dense_gelu_dense(self):
        x = jr.normal(K, (4, 256))
        w1 = jr.normal(jr.fold_in(K, 9), (512, 256)) * 0.05
        b1 = jnp.zeros((512,))
        w2 = jr.normal(jr.fold_in(K, 10), (256, 512)) * 0.05
        b2 = jnp.zeros((256,))
        ref = jax.nn.gelu(x @ w1.T + b1, approximate=True) @ w2.T + b2
        np.testing.assert_allclose(
            ops.fused_dense_gelu_dense(x, w1, b1, w2, b2), ref, atol=1e-5
        )
        f1 = lambda *a: jnp.sum(jnp.tanh(ops.fused_dense_gelu_dense(*a)))
        f2 = lambda x, w1, b1, w2, b2: jnp.sum(
            jnp.tanh(jax.nn.gelu(x @ w1.T + b1, approximate=True) @ w2.T + b2)
        )
        g1 = jax.grad(f1, argnums=tuple(range(5)))(x, w1, b1, w2, b2)
        g2 = jax.grad(f2, argnums=tuple(range(5)))(x, w1, b1, w2, b2)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, atol=2e-5)

    def test_module(self):
        m = ops.FusedDense(64, 32)
        params = m.init(jr.fold_in(K, 11))
        x = jr.normal(K, (3, 64))
        np.testing.assert_allclose(
            m(params, x), x @ params["weight"].T + params["bias"], atol=1e-6
        )


class TestMLP:
    def test_matches_reference_chain(self):
        sizes = (256, 128, 64)
        m = ops.MLP(sizes, activation="relu")
        params = m.init(jr.fold_in(K, 12))
        x = jr.normal(K, (5, 256))
        h = x
        for i in range(2):
            h = jnp.maximum(h @ params[f"weight_{i}"].T + params[f"bias_{i}"], 0)
        np.testing.assert_allclose(m(params, x), h, atol=1e-5)

    def test_sigmoid_grads(self):
        w = jr.normal(jr.fold_in(K, 13), (128, 128)) * 0.1
        b = jnp.zeros((128,))
        x = jr.normal(K, (4, 128))
        f1 = lambda x, w, b: jnp.sum(ops.mlp(x, [w], [b], "sigmoid") ** 2)
        f2 = lambda x, w, b: jnp.sum(jax.nn.sigmoid(x @ w.T + b) ** 2)
        g1 = jax.grad(f1, argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(f2, argnums=(0, 1, 2))(x, w, b)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, atol=1e-5)


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_loss_and_grad(self, smoothing):
        logits = jr.normal(K, (16, 512))
        labels = jr.randint(jr.fold_in(K, 14), (16,), 0, 512)
        loss = ops.softmax_cross_entropy_loss(logits, labels, smoothing)
        lse = jax.nn.logsumexp(logits, -1)
        nll = lse - jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
        ref = (1 - smoothing) * nll + smoothing * jnp.mean(lse[:, None] - logits, -1)
        np.testing.assert_allclose(loss, ref, atol=1e-5)

        w = jnp.linspace(0.5, 2.0, 16)
        g1 = jax.grad(
            lambda lg: jnp.sum(ops.softmax_cross_entropy_loss(lg, labels, smoothing) * w)
        )(logits)

        def ref_fn(lg):
            lse = jax.nn.logsumexp(lg, -1)
            nll = lse - jnp.take_along_axis(lg, labels[:, None], -1)[:, 0]
            return jnp.sum(((1 - smoothing) * nll + smoothing * jnp.mean(lse[:, None] - lg, -1)) * w)

        np.testing.assert_allclose(g1, jax.grad(ref_fn)(logits), atol=1e-5)

    def test_half_to_float(self):
        logits = jr.normal(K, (8, 128)).astype(jnp.bfloat16)
        labels = jr.randint(jr.fold_in(K, 15), (8,), 0, 128)
        assert ops.softmax_cross_entropy_loss(logits, labels, 0.0, True).dtype == jnp.float32
        assert ops.softmax_cross_entropy_loss(logits, labels, 0.0, False).dtype == jnp.bfloat16


class TestFocalLoss:
    def test_grad_matches_autodiff(self):
        from apex_tpu.ops import focal_loss as fl_fn
        from apex_tpu.ops.focal_loss import _fl_sum

        logits = jr.normal(K, (32, 80))
        targets = jr.randint(jr.fold_in(K, 16), (32,), 0, 81)
        loss = fl_fn(logits, targets, 80)
        assert jnp.isfinite(loss)
        g1 = jax.grad(lambda lg: fl_fn(lg, targets, 80) * 3.0)(logits)
        g2 = jax.grad(lambda lg: _fl_sum(lg, targets, 80, 0.25, 2.0, 0.0) * 3.0)(logits)
        np.testing.assert_allclose(g1, g2, atol=1e-5)


@pytest.mark.pallas
class TestPallasKernels:
    """Interpret-mode runs of the real kernels on tiny shapes."""

    def test_ln_kernel(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        x = jr.normal(K, (8, 128)) * 2 + 1
        w = jnp.ones((128,)) * 1.1
        b = jnp.zeros((128,)) + 0.2
        np.testing.assert_allclose(
            ops.fused_layer_norm(x, w, b), ref_layer_norm(x, w, b), atol=2e-6
        )
        g1 = jax.grad(lambda x, w, b: jnp.sum(jnp.sin(ops.fused_layer_norm(x, w, b))), (0, 1, 2))(x, w, b)
        g2 = jax.grad(lambda x, w, b: jnp.sum(jnp.sin(ref_layer_norm(x, w, b))), (0, 1, 2))(x, w, b)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, atol=1e-5)

    def test_softmax_kernel(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        x = jr.normal(K, (1, 2, 8, 128))
        mask = jr.bernoulli(jr.fold_in(K, 17), 0.3, (1, 1, 8, 128))
        ref = jax.nn.softmax(jnp.where(mask, -10000.0, x * 0.5), -1)
        np.testing.assert_allclose(ops.scaled_masked_softmax(x, mask, 0.5), ref, atol=1e-6)

    def test_causal_softmax_kernel(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        x = jr.normal(K, (2, 8, 128))
        q = jnp.arange(8)[:, None]
        kk = jnp.arange(128)[None, :]
        ref = jax.nn.softmax(jnp.where(kk <= q, x * 1.5, -10000.0), -1)
        np.testing.assert_allclose(
            ops.scaled_upper_triang_masked_softmax(x, 1.5), ref, atol=1e-6
        )

    def test_matmul_kernel(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        x = jr.normal(K, (8, 128))
        w = jr.normal(jr.fold_in(K, 18), (128, 128)) * 0.1
        b = jr.normal(jr.fold_in(K, 19), (128,)) * 0.1
        from apex_tpu.ops.pallas.matmul import matmul_bias_act

        y = matmul_bias_act(x, w, b, activation="gelu", interpret=True)
        ref = jax.nn.gelu(x @ w + b, approximate=True)
        np.testing.assert_allclose(y, ref, atol=1e-5)


class TestXentStatsKernel:
    """The fused CE statistics kernel vs the jnp formulation."""

    def test_stats_match_jnp(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.pallas.xentropy import xent_stats

        n, v = 16, 256
        logits = jr.normal(K, (n, v)) * 3
        labels = jr.randint(jr.fold_in(K, 1), (n,), 0, v)
        m, l, t, s = xent_stats(logits, labels, interpret=True)
        lf = np.asarray(logits, np.float32)
        np.testing.assert_allclose(m, lf.max(-1), rtol=1e-6)
        np.testing.assert_allclose(
            l, np.exp(lf - lf.max(-1, keepdims=True)).sum(-1), rtol=1e-5)
        np.testing.assert_allclose(
            t, np.take_along_axis(lf, np.asarray(labels)[:, None], -1)[:, 0],
            rtol=1e-6)
        np.testing.assert_allclose(s, lf.sum(-1), rtol=1e-5, atol=1e-4)

    def test_out_of_range_labels_contribute_zero(self, monkeypatch):
        """Vocab-parallel shards pass local ids that may fall outside
        [0, V/tp); the kernel's target stat must be 0 there so the psum
        reduction keeps only the owning shard's value."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.pallas.xentropy import xent_stats

        n, v = 8, 128
        logits = jr.normal(K, (n, v))
        labels = jnp.array([-5, -1, 0, 63, 127, 128, 500, 7], jnp.int32)
        _, _, t, _ = xent_stats(logits, labels, interpret=True)
        lf = np.asarray(logits, np.float32)
        expect = np.where(
            (np.asarray(labels) >= 0) & (np.asarray(labels) < v),
            np.take_along_axis(lf, np.clip(np.asarray(labels), 0, v - 1)[:, None], -1)[:, 0],
            0.0)
        np.testing.assert_allclose(t, expect, rtol=1e-6)

    def test_vocab_parallel_ce_kernel_path_matches(self, monkeypatch):
        """Full vocab-parallel CE through the kernel path == jnp path."""
        from apex_tpu.transformer.tensor_parallel import cross_entropy as ce

        logits = jr.normal(K, (2, 16, 256)) * 2
        tgt = jr.randint(jr.fold_in(K, 3), (2, 16), 0, 256)
        monkeypatch.setenv("APEX_TPU_PALLAS", "0")
        ref = ce.vocab_parallel_cross_entropy(logits, tgt, 0.1, None)
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        got = ce.vocab_parallel_cross_entropy(logits, tgt, 0.1, None)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        monkeypatch.setenv("APEX_TPU_PALLAS", "0")
        g_ref = jax.grad(lambda l: jnp.mean(
            ce.vocab_parallel_cross_entropy(l, tgt, 0.0, None)))(logits)
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        np.testing.assert_allclose(
            jax.grad(lambda l: jnp.mean(
                ce.vocab_parallel_cross_entropy(l, tgt, 0.0, None)))(logits),
            g_ref, rtol=1e-5, atol=1e-6)

    def test_unowned_sentinel_labels_match_jnp_path(self, monkeypatch):
        """Out-of-vocab labels (ignore/padding sentinels like -100) are owned
        by no shard; both dispatch paths must return loss == lse for them."""
        from apex_tpu.transformer.tensor_parallel import cross_entropy as ce

        logits = jr.normal(K, (8, 256)) * 2 + 5  # shifted: exposes max rebase
        tgt = jnp.array([-100, 0, 300, 17, 255, 256, -1, 3], jnp.int32)
        monkeypatch.setenv("APEX_TPU_PALLAS", "0")
        ref = ce.vocab_parallel_cross_entropy(logits, tgt, 0.0, None)
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        got = ce.vocab_parallel_cross_entropy(logits, tgt, 0.0, None)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
