"""Mesh/parallel-state tests, mirroring the reference's
``tests/L0/run_transformer/test_parallel_state.py`` coverage: initialization,
divisibility validation, accessor values, teardown."""

import jax
import numpy as np
import pytest

from apex_tpu.parallel import mesh as mesh_lib


def test_initialize_and_accessors():
    mesh_lib.initialize_model_parallel(tensor_model_parallel_size=2,
                                       pipeline_model_parallel_size=2)
    assert mesh_lib.model_parallel_is_initialized()
    assert mesh_lib.get_tensor_model_parallel_world_size() == 2
    assert mesh_lib.get_pipeline_model_parallel_world_size() == 2
    assert mesh_lib.get_data_parallel_world_size() == 2
    assert mesh_lib.get_context_parallel_world_size() == 1
    mesh = mesh_lib.get_mesh()
    assert mesh.axis_names == ("dp", "pp", "cp", "tp")
    assert mesh.shape["tp"] == 2 and mesh.shape["dp"] == 2


def test_invalid_world_size():
    with pytest.raises(RuntimeError):
        mesh_lib.initialize_model_parallel(tensor_model_parallel_size=3)


def test_virtual_pipeline_requires_pp():
    with pytest.raises(ValueError):
        mesh_lib.MeshSpec(pipeline_model_parallel_size=1,
                          virtual_pipeline_model_parallel_size=2)


def test_destroy():
    mesh_lib.initialize_model_parallel()
    mesh_lib.destroy_model_parallel()
    assert not mesh_lib.model_parallel_is_initialized()
    with pytest.raises(RuntimeError):
        mesh_lib.get_mesh()


def test_axis_rank_inside_shard_map():
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = mesh_lib.initialize_model_parallel(tensor_model_parallel_size=4)

    def f(x):
        return x + jax.lax.axis_index("tp").astype(x.dtype)

    x = np.zeros((8, 4), np.float32)
    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P(None, "tp"), out_specs=P(None, "tp"))
    )(x)
    np.testing.assert_allclose(out[0], [0, 1, 2, 3])
