"""Mesh/parallel-state tests, mirroring the reference's
``tests/L0/run_transformer/test_parallel_state.py`` coverage: initialization,
divisibility validation, accessor values, teardown."""

import jax
import numpy as np
import pytest

from apex_tpu.parallel import mesh as mesh_lib


def test_initialize_and_accessors():
    mesh_lib.initialize_model_parallel(tensor_model_parallel_size=2,
                                       pipeline_model_parallel_size=2)
    assert mesh_lib.model_parallel_is_initialized()
    assert mesh_lib.get_tensor_model_parallel_world_size() == 2
    assert mesh_lib.get_pipeline_model_parallel_world_size() == 2
    assert mesh_lib.get_data_parallel_world_size() == 2
    assert mesh_lib.get_context_parallel_world_size() == 1
    mesh = mesh_lib.get_mesh()
    assert mesh.axis_names == ("dp", "pp", "cp", "tp")
    assert mesh.shape["tp"] == 2 and mesh.shape["dp"] == 2


def test_invalid_world_size():
    with pytest.raises(RuntimeError):
        mesh_lib.initialize_model_parallel(tensor_model_parallel_size=3)


def test_virtual_pipeline_requires_pp():
    with pytest.raises(ValueError):
        mesh_lib.MeshSpec(pipeline_model_parallel_size=1,
                          virtual_pipeline_model_parallel_size=2)


def test_destroy():
    mesh_lib.initialize_model_parallel()
    mesh_lib.destroy_model_parallel()
    assert not mesh_lib.model_parallel_is_initialized()
    with pytest.raises(RuntimeError):
        mesh_lib.get_mesh()


def test_axis_rank_inside_shard_map():
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.mesh import shard_map

    mesh = mesh_lib.initialize_model_parallel(tensor_model_parallel_size=4)

    def f(x):
        return x + jax.lax.axis_index("tp").astype(x.dtype)

    x = np.zeros((8, 4), np.float32)
    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P(None, "tp"), out_specs=P(None, "tp"))
    )(x)
    np.testing.assert_allclose(out[0], [0, 1, 2, 3])


class _StubDev:
    """Duck-typed device for hybrid_device_order (pure list logic)."""

    def __init__(self, id, slice_index):
        self.id = id
        self.slice_index = slice_index

    def __repr__(self):
        return f"d{self.id}s{self.slice_index}"


class TestHybridMesh:
    def test_order_groups_slices_and_sorts_within(self):
        # two slices of 4, devices interleaved and shuffled: the order must
        # come back slice-contiguous (dp groups align with DCN boundaries)
        # and id-sorted within a slice (ICI torus order preserved)
        devs = [_StubDev(i, i % 2) for i in (5, 0, 3, 6, 1, 4, 7, 2)]
        out = mesh_lib.hybrid_device_order(devs, model_parallel=4)
        assert [(d.slice_index, d.id) for d in out] == [
            (0, 0), (0, 2), (0, 4), (0, 6), (1, 1), (1, 3), (1, 5), (1, 7)]

    def test_order_single_slice_is_identity(self):
        devs = [_StubDev(i, 0) for i in (3, 1, 2, 0)]
        assert mesh_lib.hybrid_device_order(devs, 2) == devs

    def test_order_rejects_straddling_model_group(self):
        # 3 + 5 devices over two slices: no model_parallel=4 grouping can
        # avoid crossing DCN
        devs = [_StubDev(i, 0) for i in range(3)] + [
            _StubDev(3 + i, 1) for i in range(5)]
        with pytest.raises(RuntimeError, match="straddle DCN"):
            mesh_lib.hybrid_device_order(devs, 4)

    def test_make_hybrid_mesh_on_cpu_matches_make_mesh(self):
        # CPU devices carry no slice_index -> single-slice fallback: the
        # hybrid mesh must be exactly the flat one
        m1 = mesh_lib.make_mesh(tensor_model_parallel_size=2)
        m2 = mesh_lib.make_hybrid_mesh(tensor_model_parallel_size=2)
        assert m1.axis_names == m2.axis_names
        assert (np.asarray(m1.devices) == np.asarray(m2.devices)).all()

    def test_hybrid_dp_groups_are_slice_pure(self):
        # 4 slices x 4 devices, tp=2 pp=2: after ordering, each dp row of
        # the mesh layout must sit inside ONE slice
        devs = [_StubDev(i, i // 4) for i in range(16)]
        import random
        random.Random(0).shuffle(devs)
        out = mesh_lib.hybrid_device_order(devs, model_parallel=4)
        rows = [out[i * 4:(i + 1) * 4] for i in range(4)]  # dp extent 4
        for row in rows:
            assert len({d.slice_index for d in row}) == 1

    def test_hybrid_ep_counts_toward_inner_extent(self):
        # review catch: ep sits INSIDE dp in the 5-D layout, so with
        # 2 slices x 4 devices and tp=2 ep=4 the inner block is 8 and no
        # slice can hold it -> must raise, not silently straddle DCN
        devs = [_StubDev(i, i // 4) for i in range(8)]
        with pytest.raises(RuntimeError, match="straddle DCN"):
            mesh_lib.make_hybrid_mesh(
                tensor_model_parallel_size=2, expert_parallel_size=4,
                devices=devs)
