"""apexmem tests: the donation-aware liveness analysis, byte-exact.

Hand-computed fixtures pin the model's three load-bearing mechanisms —
donation aliasing (the donated pool costs its bytes ONCE, the control
trace is bigger by EXACTLY the pool), the scan length×stash term, and
cond's family-wise branch max — to literal byte counts, so any drift in
the walk's arithmetic fails loudly. The serving fixtures assert the
same invariants on the REAL traced decode body (pool aliased once,
peak linear in ``num_blocks``), the JXP601/602 contracts are exercised
through ``assert_contracts``, and the CLI surface
(``--memory`` / ``--budget-file`` / ``--static-memory``) is driven
end-to-end including the closed-schema drift negatives.
"""

import functools
import json
import os

import jax
import jax.numpy as jnp
import jax.random as jr
import pytest

from apex_tpu.lint import contracts as jc
from apex_tpu.lint import entrypoints as eps
from apex_tpu.lint import liveness
from apex_tpu.lint.__main__ import main as lint_main
from apex_tpu.monitor import schema as mon_schema

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
BUDGETS = os.path.join(REPO, "tools", "memory_budgets.json")

F32 = jnp.float32


# --- hand-computed fixtures ---------------------------------------------------
# (every asserted number is derived in a comment — the fixtures ARE the
# liveness model's contract)

def _pool_step(pool, delta):
    return pool + delta


_POOL = jax.ShapeDtypeStruct((256, 256), F32)    # 256*256*4 = 262144 B
_DELTA = jax.ShapeDtypeStruct((256, 256), F32)   # 262144 B


def _donated_jaxpr():
    step = jax.jit(_pool_step, donate_argnums=(0,))
    return jax.make_jaxpr(step)(_POOL, _DELTA)


def _control_jaxpr():
    return jax.make_jaxpr(jax.jit(_pool_step))(_POOL, _DELTA)


class TestHandComputedPeaks:
    def test_donation_counts_the_pool_once(self):
        """Donated: pool (262144) + delta (262144) enter live; the
        donated pool dies at the pjit and its buffer becomes the
        output — zero new bytes. Peak = 524288. Control: the output is
        a fresh 262144-byte buffer on top → 786432. The difference is
        the pool's bytes EXACTLY."""
        fams = ("kv_pool", "temps")
        don = liveness.analyze(_donated_jaxpr(), arg_families=fams)
        ctl = liveness.analyze(_control_jaxpr(), arg_families=fams)
        assert don.peak_bytes == 524288
        assert ctl.peak_bytes == 786432
        assert ctl.peak_bytes - don.peak_bytes == 262144  # == pool bytes
        assert don.donation_aliased_bytes == 262144
        assert ctl.donation_aliased_bytes == 0
        # the aliased output inherits the donor's family
        assert don.families["kv_pool"] == 262144
        assert don.families["temps"] == 262144

    def test_scan_contributes_carry_plus_iter_plus_stash(self):
        """xs f32[8,128] (4096 B), c0 f32[128] (512 B); body returns
        (c+x, c*x) so ys stacks 8×512 = 4096 B of stash. Peak at the
        scan eqn = live (xs 4096 + c0 512) + out_new (carry 512 +
        stacked ys 4096) + body extra (the c*x tick output, 512)
        = 9728."""
        def scanned(xs, c0):
            def body(c, x):
                return c + x, c * x
            return jax.lax.scan(body, c0, xs)

        closed = jax.make_jaxpr(scanned)(
            jax.ShapeDtypeStruct((8, 128), F32),
            jax.ShapeDtypeStruct((128,), F32))
        rep = liveness.analyze(closed,
                               arg_families=("activations", "temps"))
        assert rep.peak_bytes == 9728
        assert rep.stash_bytes == 4096         # the stacked ys term
        # at the peak: activations = xs 4096 + ys 4096; temps = c0 512
        # + carry-out 512 + body extra 512
        assert rep.families["activations"] == 8192
        assert rep.families["temps"] == 1536
        assert rep.unbounded_stash_sites == 0

    def test_cond_branches_are_alternatives_not_summed(self):
        """pred bool[] (1 B, pinned) + a f32[32,32] (4096 B) +
        convert_element_type's i32 index (4 B) are live at the cond;
        the big branch's extra beyond its input is concatenate's
        f32[64,32] (8192 B) + the reduce scalar (4 B) = 8196, the small
        branch's is 4. The cond charges the MAX (8196), never the sum:
        peak = 1 + 4 + 4096 + 8196 = 12297."""
        def condy(pred, a):
            def big(v):
                return jnp.concatenate([v, v]).sum()

            def small(v):
                return v.sum()
            return jax.lax.cond(pred, big, small, a)

        closed = jax.make_jaxpr(condy)(
            jax.ShapeDtypeStruct((), jnp.bool_),
            jax.ShapeDtypeStruct((32, 32), F32))
        rep = liveness.analyze(closed)
        assert rep.peak_bytes == 12297
        assert rep.families["temps"] == 12297  # no labels -> all temps

    def test_while_flags_unbounded_stash(self):
        """A while body's trip count is not static: the bound charges
        ONE iteration (a f32[32,32] in, one out: 4096 + 4096 + body
        extra 4096 = 12288) and flags the site instead of silently
        multiplying."""
        def looped(x):
            return jax.lax.while_loop(
                lambda v: v.sum() < 100.0, lambda v: v * 2.0, x)

        closed = jax.make_jaxpr(looped)(jax.ShapeDtypeStruct((32, 32), F32))
        rep = liveness.analyze(closed)
        assert rep.unbounded_stash_sites == 1
        assert rep.peak_bytes == 12288

    def test_arg_families_validated(self):
        closed = _control_jaxpr()
        with pytest.raises(ValueError, match="1 labels for 2"):
            liveness.analyze(closed, arg_families=("kv_pool",))
        with pytest.raises(ValueError, match="unknown families"):
            liveness.analyze(closed, arg_families=("kv_pool", "junk"))

    def test_record_is_schema_valid(self):
        rep = liveness.analyze(_donated_jaxpr(),
                               arg_families=("kv_pool", "temps"),
                               entrypoint="fixture")
        rec = rep.record()
        assert mon_schema.validate(rec) == []
        assert rec["kind"] == "static_memory"
        assert rec["source"] == "liveness"
        assert rec["peak_bytes"] == 524288


# --- the serving pool on the REAL traced decode body --------------------------

def _decode_closed(num_blocks=None):
    """Trace the serving decode step the way the entrypoint registry
    does, at an explicit pool size."""
    from apex_tpu.lint.entrypoints import _cow_scheduler, _gpt_smoke_model
    from apex_tpu.serving import ServingEngine

    model, params = _gpt_smoke_model()
    engine = ServingEngine(model, num_slots=4, block_size=32,
                           num_blocks=num_blocks)
    sched, _, _ = _cow_scheduler(engine)
    pool = engine.init_pool()
    toks, lens = sched.decode_batch(0.0)
    tables = jnp.asarray(sched.tables.asarray())
    args = (params, pool, tables, jnp.asarray(toks), jnp.asarray(lens),
            jr.PRNGKey(0))  # apexlint: disable=APX502
    closed = jax.make_jaxpr(engine.decode_step)(*args)
    fams = eps.arg_families("serve_decode", args)
    return engine, liveness.analyze(closed, arg_families=fams)


class TestServingPool:
    def test_decode_pool_counted_once(self):
        """The registered serve_decode entrypoint: the donated paged
        pool is provably aliased input→output — the at-peak kv_pool
        family and the aliased tally both equal pool_bytes() exactly
        (a double-counted pool would double the family)."""
        engine, rep = _decode_closed()
        pb = engine.pool_bytes()
        assert rep.donation_aliased_bytes == pb
        assert rep.families["kv_pool"] == pb

    def test_peak_linear_in_num_blocks(self):
        """Growing the pool by N blocks grows the liveness peak by
        EXACTLY the pool-bytes delta — the pool appears once in the
        bound, so the slope is the per-block footprint, not 2×."""
        e1, r1 = _decode_closed(num_blocks=8)
        e2, r2 = _decode_closed(num_blocks=16)
        pool_delta = e2.pool_bytes() - e1.pool_bytes()
        assert pool_delta > 0
        # the kv_pool family IS the pool: exactly linear
        assert (r2.families["kv_pool"] - r1.families["kv_pool"]
                == pool_delta)
        # the whole peak grows by the pool delta plus only per-block
        # index bookkeeping (i32 block ids/masks — bytes, not kilobytes)
        peak_delta = r2.peak_bytes - r1.peak_bytes
        assert pool_delta <= peak_delta < pool_delta + 4096
        assert r1.donation_aliased_bytes == e1.pool_bytes()
        assert r2.donation_aliased_bytes == e2.pool_bytes()

    def test_kv_pool_bytes_matches_engine(self):
        """The planner's closed form agrees with the engine byte-for-
        byte, float and int8 pools both (the int8 scale planes were the
        gap the liveness cross-check exposed)."""
        from apex_tpu.lint.entrypoints import _gpt_smoke_model
        from apex_tpu.plan import kv_pool_bytes
        from apex_tpu.serving import ServingEngine

        model, _ = _gpt_smoke_model()
        c = model.config
        bf16 = ServingEngine(model, num_slots=4, block_size=32,
                             cache_dtype=jnp.bfloat16)
        assert kv_pool_bytes(c.num_layers, bf16.num_blocks,
                             c.local_kv_heads, bf16.block_size,
                             c.head_dim) == bf16.pool_bytes()
        q = ServingEngine(model, num_slots=4, block_size=32,
                          kv_dtype="int8")
        assert kv_pool_bytes(c.num_layers, q.num_blocks,
                             c.local_kv_heads, q.block_size, c.head_dim,
                             kv_dtype="int8") == q.pool_bytes()


# --- JXP601 / JXP602 through the contract surface -----------------------------

class TestMemoryContracts:
    def test_peak_memory_bound_passes_at_peak(self):
        jc.assert_contracts(_donated_jaxpr(), [jc.peak_memory_bound(
            524288, arg_families=("kv_pool", "temps"))])

    def test_peak_memory_bound_violation_names_families(self):
        with pytest.raises(AssertionError) as e:
            jc.assert_contracts(_donated_jaxpr(), [jc.peak_memory_bound(
                524287, arg_families=("kv_pool", "temps"))])
        msg = str(e.value)
        assert "JXP601" in msg and "524288 bytes" in msg
        assert "kv_pool" in msg  # the breakdown names the family

    def test_donation_aliased_positive(self):
        jc.assert_contracts(_donated_jaxpr(), [jc.donation_aliased(
            "fixture pool", min_bytes=262144)])

    def test_donation_aliased_negative_on_control(self):
        with pytest.raises(AssertionError) as e:
            jc.assert_contracts(_control_jaxpr(),
                                [jc.donation_aliased("fixture pool")])
        assert "JXP602" in str(e.value)


# --- the CLI gate -------------------------------------------------------------

_EP = "collective_matmul_ring"  # the cheapest entrypoint to trace


class TestMemoryCLI:
    def test_budget_file_without_memory_exits_2(self, capsys):
        rc = lint_main(["--jaxpr", "--budget-file", BUDGETS])
        assert rc == 2
        assert "--memory" in capsys.readouterr().err

    def test_memory_table_prints_peaks(self, capsys):
        rc = lint_main(["--jaxpr", "--memory", "--entrypoint", _EP])
        out = capsys.readouterr().out
        assert rc == 0
        assert "apexmem" in out and _EP in out

    def test_over_budget_is_jxp601_violation(self, tmp_path, capsys):
        peak = eps.static_memory(_EP).peak_bytes
        f = tmp_path / "budgets.json"
        f.write_text(json.dumps(
            {"version": 1, "unit": "bytes", "budgets": {_EP: peak - 1}}))
        rc = lint_main(["--jaxpr", "--memory", "--entrypoint", _EP,
                        "--budget-file", str(f)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "JXP601" in out and "VIOLATION" in out

    def test_exact_budget_is_clean(self, tmp_path, capsys):
        peak = eps.static_memory(_EP).peak_bytes
        f = tmp_path / "budgets.json"
        f.write_text(json.dumps(
            {"version": 1, "unit": "bytes", "budgets": {_EP: peak}}))
        rc = lint_main(["--jaxpr", "--memory", "--entrypoint", _EP,
                        "--budget-file", str(f)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "CLEAN" in out

    def test_missing_budget_entry_is_a_violation(self, tmp_path, capsys):
        f = tmp_path / "budgets.json"
        f.write_text(json.dumps(
            {"version": 1, "unit": "bytes", "budgets": {}}))
        rc = lint_main(["--jaxpr", "--memory", "--entrypoint", _EP,
                        "--budget-file", str(f)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "no budget entry" in out

    def test_unreadable_budget_file_exits_2(self, tmp_path, capsys):
        f = tmp_path / "budgets.json"
        f.write_text("{not json")
        rc = lint_main(["--jaxpr", "--memory", "--entrypoint", _EP,
                        "--budget-file", str(f)])
        assert rc == 2
        assert "budget file" in capsys.readouterr().err

    def test_checked_in_budgets_cover_every_entrypoint(self):
        """The committed budget file and the registry never drift: a
        new entrypoint without a budget would fail the gate, and a
        stale budget for a deleted entrypoint is dead weight."""
        with open(BUDGETS, encoding="utf-8") as fh:
            data = json.load(fh)
        assert data["unit"] == "bytes"
        assert sorted(data["budgets"]) == sorted(eps.names())
        assert all(isinstance(v, int) and v > 0
                   for v in data["budgets"].values())


class TestStaticMemoryArtifact:
    def test_cli_writes_valid_jsonl(self, tmp_path, capsys):
        out_file = tmp_path / "static_memory.jsonl"
        rc = lint_main(["--jaxpr", "--entrypoint", _EP,
                        "--static-memory", str(out_file)])
        capsys.readouterr()
        assert rc == 0
        lines = out_file.read_text().strip().splitlines()
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert mon_schema.validate(rec) == []
        assert rec["kind"] == "static_memory"
        assert rec["entrypoint"] == _EP
        assert rec["peak_bytes"] > 0
        assert sum(rec["families"].values()) == rec["peak_bytes"]

    def test_validate_metrics_dispatch_and_drift(self, tmp_path, capsys):
        """tools/validate_metrics.py --static-memory: the real record
        passes; a junk key, a float peak, and a wrong kind each FAIL —
        the schema is closed, drift cannot ride along silently."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "validate_metrics", os.path.join(REPO, "tools",
                                             "validate_metrics.py"))
        vm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(vm)

        rec = eps.static_memory(_EP).record()
        good = tmp_path / "good.jsonl"
        good.write_text(json.dumps(rec) + "\n")
        assert vm.main(["--static-memory", str(good)]) == 0
        capsys.readouterr()

        junk = dict(rec, junk=1)
        nanlike = dict(rec, peak_bytes=float(rec["peak_bytes"]) + 0.5)
        wrong = dict(rec, kind="static_cost")
        for i, bad in enumerate((junk, nanlike, wrong)):
            f = tmp_path / f"bad{i}.jsonl"
            f.write_text(json.dumps(bad) + "\n")
            assert vm.main(["--static-memory", str(f)]) == 1, bad
            capsys.readouterr()

    def test_cli_refuses_invalid_record(self, tmp_path, capsys,
                                        monkeypatch):
        """A code change that breaks the record shape must fail at
        WRITE time (exit 2), not poison the artifact trail."""
        real = eps.check

        def broken(name, *, memory=False):
            got = real(name, memory=memory)
            if memory:
                f, c, m = got
                m = dict(m, peak_bytes="oops")
                return f, c, m
            return got

        monkeypatch.setattr(eps, "check", broken)
        out_file = tmp_path / "static_memory.jsonl"
        rc = lint_main(["--jaxpr", "--entrypoint", _EP,
                        "--static-memory", str(out_file)])
        assert rc == 2
        assert "static_memory" in capsys.readouterr().err
