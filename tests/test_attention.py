"""Flash + ring attention tests.

Mirrors the reference's ``apex/contrib/test/fmha/test_fmha.py`` and
``multihead_attn`` tests: kernel vs dense-softmax reference, fwd and bwd —
plus ring attention (absent in the reference) against the same dense oracle.
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.attention import (flash_attention, ring_attention,
                                    ulysses_attention, zigzag_shard,
                                    zigzag_unshard)
from apex_tpu.parallel import mesh as mesh_lib

K = jr.PRNGKey(33)

# On real TPU, fp32 matmuls go through the MXU with bf16-rounded operands at
# the default precision — both the kernels and the dense oracle carry
# ~1e-3-scale rounding the CPU (true-fp32) run doesn't, so the hardware run
# checks kernel-vs-oracle agreement at that scale, not fp32 exactness.
_EXACT = jax.default_backend() != "tpu"
ATOL = 2e-5 if _EXACT else 3e-3
RTOL = 2e-5 if _EXACT else 3e-3
G_ATOL = 2e-5 if _EXACT else 5e-3
G_RTOL = 2e-4 if _EXACT else 5e-3


def dense_ref(q, k, v, causal, scale=None):
    d = q.shape[-1]
    scale = scale or 1.0 / d ** 0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None] + (sk - sq)
        s = jnp.where(mask, s, -1e30)
    return jnp.einsum("...qk,...kd->...qd", jax.nn.softmax(s, -1), v)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q = jr.normal(K, (2, 4, 64, 32))
        k = jr.normal(jr.fold_in(K, 1), (2, 4, 64, 32))
        v = jr.normal(jr.fold_in(K, 2), (2, 4, 64, 32))
        o = flash_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(o, dense_ref(q, k, v, causal), rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, causal):
        q = jr.normal(K, (3, 32, 16))
        k = jr.normal(jr.fold_in(K, 3), (3, 32, 16))
        v = jr.normal(jr.fold_in(K, 4), (3, 32, 16))
        f1 = lambda q, k, v: jnp.sum(jnp.sin(flash_attention(q, k, v, causal=causal)))
        f2 = lambda q, k, v: jnp.sum(jnp.sin(dense_ref(q, k, v, causal)))
        g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, rtol=G_RTOL, atol=G_ATOL)

    def test_long_sequence_beyond_reference_cap(self):
        # fmha caps at 512 and fused softmax at 2048; we run 4096
        q = jr.normal(K, (1, 4096, 16)) * 0.5
        o = flash_attention(q, q, q, causal=True)
        assert o.shape == (1, 4096, 16)
        assert bool(jnp.all(jnp.isfinite(o)))

    @pytest.mark.pallas
    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_kernel_fwd_bwd(self, causal, monkeypatch):
        # interpret mode checks the kernel's LOGIC, not hardware numerics —
        # force true-fp32 dots so the check is exact on TPU too (at default
        # precision the kernel's MXU dp and the elementwise delta disagree
        # by ~1e-3 exactly where the causal grad is identically zero)
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        q = jr.normal(K, (1, 256, 64)).astype(jnp.float32)
        k = jr.normal(jr.fold_in(K, 5), (1, 256, 64))
        v = jr.normal(jr.fold_in(K, 6), (1, 256, 64))
        with jax.default_matmul_precision("highest"):
            o = flash_attention(q, k, v, causal=causal, impl="pallas")
            np.testing.assert_allclose(o, dense_ref(q, k, v, causal),
                                       rtol=2e-5, atol=2e-5)
            f1 = lambda q, k, v: jnp.sum(jnp.cos(flash_attention(q, k, v, causal=causal, impl="pallas")))
            f2 = lambda q, k, v: jnp.sum(jnp.cos(dense_ref(q, k, v, causal)))
            g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
            g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-4)


class TestLseCarrierForms:
    """flash_bwd / flash_bwd_bshd accept lse as the sliced row vector OR
    the (…, LANES) lane carrier flash_fwd(full_lse=True) returns — both
    must produce identical grads (the custom-VJP residuals keep the
    carrier to skip a slice/re-broadcast pair per layer)."""

    @pytest.mark.pallas
    def test_sliced_vs_carrier_identical(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.pallas import attention as A

        q = jr.normal(K, (2, 256, 64)).astype(jnp.float32)
        k = jr.normal(jr.fold_in(K, 41), (2, 256, 64))
        v = jr.normal(jr.fold_in(K, 42), (2, 256, 64))
        do = jr.normal(jr.fold_in(K, 43), (2, 256, 64))
        with jax.default_matmul_precision("highest"):
            o, lse = A.flash_fwd(q, k, v, scale=0.125, causal=True,
                                 interpret=True)
            o2, lse_c = A.flash_fwd(q, k, v, scale=0.125, causal=True,
                                    full_lse=True, interpret=True)
            np.testing.assert_array_equal(o, o2)
            np.testing.assert_array_equal(lse, lse_c[..., 0])
            g_sliced = A.flash_bwd(q, k, v, o, lse, do, scale=0.125,
                                   causal=True, interpret=True)
            g_carrier = A.flash_bwd(q, k, v, o, lse_c, do, scale=0.125,
                                    causal=True, interpret=True)
        for a, e in zip(g_carrier, g_sliced):
            np.testing.assert_array_equal(a, e)

    @pytest.mark.pallas
    def test_bshd_sliced_vs_carrier_identical(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.pallas import attention as A

        q = jr.normal(K, (2, 256, 4, 16)).astype(jnp.float32)
        k = jr.normal(jr.fold_in(K, 44), (2, 256, 2, 16))
        v = jr.normal(jr.fold_in(K, 45), (2, 256, 2, 16))
        do = jr.normal(jr.fold_in(K, 46), (2, 256, 4, 16))
        with jax.default_matmul_precision("highest"):
            o, lse = A.flash_fwd_bshd(q, k, v, scale=0.25, causal=False,
                                      interpret=True)
            _, lse_c = A.flash_fwd_bshd(q, k, v, scale=0.25, causal=False,
                                        full_lse=True, interpret=True)
            np.testing.assert_array_equal(lse, lse_c[..., 0])
            g_sliced = A.flash_bwd_bshd(q, k, v, o, lse, do, scale=0.25,
                                        causal=False, interpret=True)
            g_carrier = A.flash_bwd_bshd(q, k, v, o, lse_c, do, scale=0.25,
                                         causal=False, interpret=True)
        for a, e in zip(g_carrier, g_sliced):
            np.testing.assert_array_equal(a, e)


class TestGroupedQueryAttention:
    """GQA/MQA: kv with fewer heads than q — beyond the reference's fmha
    (which requires equal head counts). Oracle: full MHA on repeated kv."""

    @pytest.mark.parametrize("kv_heads", [1, 2])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_repeated_kv(self, kv_heads, causal):
        b, hq, s, d = 2, 4, 32, 16
        q = jr.normal(K, (b, hq, s, d))
        k = jr.normal(jr.fold_in(K, 1), (b, kv_heads, s, d))
        v = jr.normal(jr.fold_in(K, 2), (b, kv_heads, s, d))
        o = flash_attention(q, k, v, causal=causal)
        rep = hq // kv_heads
        o_ref = dense_ref(q, jnp.repeat(k, rep, 1), jnp.repeat(v, rep, 1), causal)
        np.testing.assert_allclose(o, o_ref, rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_repeated_kv(self, causal):
        b, hq, kvh, s, d = 1, 4, 2, 32, 16
        q = jr.normal(K, (b, hq, s, d))
        k = jr.normal(jr.fold_in(K, 3), (b, kvh, s, d))
        v = jr.normal(jr.fold_in(K, 4), (b, kvh, s, d))
        rep = hq // kvh

        f1 = lambda q, k, v: jnp.sum(jnp.sin(flash_attention(q, k, v, causal=causal)))

        def f2(q, k, v):
            o = dense_ref(q, jnp.repeat(k, rep, 1), jnp.repeat(v, rep, 1), causal)
            return jnp.sum(jnp.sin(o))

        g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, rtol=G_RTOL, atol=G_ATOL)

    @pytest.mark.pallas
    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_kernel_gqa_fwd_bwd(self, causal, monkeypatch):
        """The kernel's zero-copy kv index maps (fwd, dq, dkv) + the
        group-summed dk/dv epilogue."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, hq, kvh, s, d = 1, 4, 2, 256, 64
        q = jr.normal(K, (b, hq, s, d)).astype(jnp.float32)
        k = jr.normal(jr.fold_in(K, 5), (b, kvh, s, d))
        v = jr.normal(jr.fold_in(K, 6), (b, kvh, s, d))
        rep = hq // kvh
        with jax.default_matmul_precision("highest"):
            o = jax.jit(lambda q, k, v: flash_attention(
                q, k, v, causal=causal, impl="pallas"))(q, k, v)
            o_ref = dense_ref(q, jnp.repeat(k, rep, 1), jnp.repeat(v, rep, 1),
                              causal)
            np.testing.assert_allclose(o, o_ref, rtol=2e-5, atol=2e-5)
            f1 = lambda q, k, v: jnp.sum(jnp.cos(
                flash_attention(q, k, v, causal=causal, impl="pallas")))

            def f2(q, k, v):
                o = dense_ref(q, jnp.repeat(k, rep, 1), jnp.repeat(v, rep, 1),
                              causal)
                return jnp.sum(jnp.cos(o))

            g1 = jax.jit(jax.grad(f1, argnums=(0, 1, 2)))(q, k, v)
            g2 = jax.jit(jax.grad(f2, argnums=(0, 1, 2)))(q, k, v)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-4)

    @pytest.mark.pallas
    def test_bf16_gqa_dkv_accumulates_fp32(self, monkeypatch):
        """ADVICE r2: the dkv kernel's per-q-head partials must be fp32 so
        the group sum doesn't round each head's contribution to bf16 first.
        With fp32 partials, bf16-input dk differs from the fp32 oracle by
        one output rounding, not by group-many."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, hq, kvh, s, d = 1, 8, 1, 128, 64  # MQA: group of 8 partials
        q32 = jr.normal(K, (b, hq, s, d))
        k32 = jr.normal(jr.fold_in(K, 7), (b, kvh, s, d))
        v32 = jr.normal(jr.fold_in(K, 8), (b, kvh, s, d))
        to16 = lambda x: x.astype(jnp.bfloat16)

        def loss(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, impl="pallas").astype(jnp.float32))

        with jax.default_matmul_precision("highest"):
            _, dk16, _ = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(
                to16(q32), to16(k32), to16(v32))
            _, dk32, _ = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(
                q32, k32, v32)
        err = jnp.max(jnp.abs(dk16.astype(jnp.float32) - dk32))
        # one bf16 rounding of the final sum: |err| <= ~2^-8 * |dk|;
        # bf16-rounded partials would accumulate ~sqrt(8) times that
        bound = float(jnp.max(jnp.abs(dk32))) * 2 ** -8
        assert float(err) <= bound * 1.5, (float(err), bound)

    @pytest.mark.pallas
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("h,kv_heads,d", [(4, 4, 128), (4, 2, 128),
                                              (4, 1, 128), (1, 1, 64)])
    def test_bshd_layout_kernels_match_dense(self, causal, h, kv_heads, d,
                                             monkeypatch):
        """Seq-major (b, s, h, d) kernels — the zero-layout-copy path the
        flagship uses — fwd + grads against the dense oracle, incl. GQA.
        Shapes restricted to the folded-layout tiling rule: d must tile
        128 lanes itself (d=64 only single-head) — see bshd_kernel_ok."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, s = 2, 256
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 13), (b, s, kv_heads, d))
        v = jr.normal(jr.fold_in(K, 14), (b, s, kv_heads, d))
        rep = h // kv_heads

        def dense(q, k, v):
            # oracle in (b, h, s, d) with repeated kv
            t = lambda x: x.transpose(0, 2, 1, 3)
            return t(dense_ref(t(q), jnp.repeat(t(k), rep, 1),
                               jnp.repeat(t(v), rep, 1), causal))

        with jax.default_matmul_precision("highest"):
            o = flash_attention(q, k, v, causal=causal, layout="bshd",
                                impl="pallas")
            np.testing.assert_allclose(o, dense(q, k, v), rtol=2e-5,
                                       atol=2e-5)

            f1 = lambda q, k, v: jnp.sum(jnp.cos(flash_attention(
                q, k, v, causal=causal, layout="bshd", impl="pallas")))
            f2 = lambda q, k, v: jnp.sum(jnp.cos(dense(q, k, v)))
            g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
            g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_bshd_xla_fallback_matches_dense(self, causal):
        """Below the crossover the bshd entry runs the XLA composition."""
        b, h, s, d = 2, 4, 32, 16
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 15), (b, s, 2, d))
        v = jr.normal(jr.fold_in(K, 16), (b, s, 2, d))
        t = lambda x: x.transpose(0, 2, 1, 3)
        o = flash_attention(q, k, v, causal=causal, layout="bshd")
        ref = t(dense_ref(t(q), jnp.repeat(t(k), 2, 1),
                          jnp.repeat(t(v), 2, 1), causal))
        np.testing.assert_allclose(o, ref, rtol=RTOL, atol=ATOL)
        g = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, causal=causal, layout="bshd") ** 2))(q)
        gref = jax.grad(lambda q: jnp.sum(t(dense_ref(
            t(q), jnp.repeat(t(k), 2, 1), jnp.repeat(t(v), 2, 1),
            causal)) ** 2))(q)
        np.testing.assert_allclose(g, gref, rtol=G_RTOL, atol=G_ATOL)

    def test_bshd_rejects_bad_lens_shape_and_bad_rank(self):
        q = jr.normal(K, (2, 32, 4, 16))
        # bshd kv_lens are per-BATCH (b,) — per-(b, h) is the bhsd form
        with pytest.raises(ValueError, match="per-batch kv_lens"):
            flash_attention(q, q, q, layout="bshd",
                            kv_lens=jnp.ones((2, 4), jnp.int32))
        with pytest.raises(ValueError, match="bshd"):
            flash_attention(q.reshape(8, 32, 16), q.reshape(8, 32, 16),
                            q.reshape(8, 32, 16), layout="bshd")

    def test_bshd_eligibility_rule(self):
        """The folded layout's d-wide blocks must tile 128 lanes — d=64
        multi-head configs are NOT kernel-eligible (would fail Mosaic's
        trailing-tile rule on hardware; caught by review r3)."""
        from apex_tpu.ops.attention import bshd_kernel_ok

        assert bshd_kernel_ok(1024, 1024, 8, 128, jnp.bfloat16)
        assert bshd_kernel_ok(1024, 1024, 1, 64, jnp.bfloat16)
        assert not bshd_kernel_ok(1024, 1024, 8, 64, jnp.bfloat16)
        assert not bshd_kernel_ok(1000, 1024, 8, 128, jnp.bfloat16)
        assert not bshd_kernel_ok(1024, 1024, 8, 128, jnp.float16)
        # d=64 multi-head with explicit pallas raises rather than lowering
        q = jr.normal(K, (2, 256, 4, 64))
        with pytest.raises(ValueError, match="tiling"):
            flash_attention(q, q, q, layout="bshd", impl="pallas")

    @pytest.mark.pallas
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_fused_qkv_attention_matches_composition(self, kv_heads, causal,
                                                     monkeypatch):
        """The flagship's zero-layout-copy block (packed projection →
        window-reading kernels → output GEMM, hand-written VJP): forward
        and EVERY cotangent (x, packed weight, packed bias, out weight)
        against the composed einsum+dense formulation."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.attention import fused_qkv_attention

        b, s, H, h, d = 2, 256, 64, 4, 16
        hkv = kv_heads
        G = h + 2 * hkv
        key = jr.fold_in(K, 31)
        x = jr.normal(key, (b, s, H))
        w_qkv = jr.normal(jr.fold_in(key, 1), (G * d, H)) * 0.1
        b_qkv = jr.normal(jr.fold_in(key, 2), (G * d,)) * 0.1
        w_out = jr.normal(jr.fold_in(key, 3), (H, h * d)) * 0.1
        scale = 1.0 / d ** 0.5

        def composed(x, w_qkv, b_qkv, w_out):
            qkv = jnp.einsum("bsH,FH->bsF", x, w_qkv) + b_qkv
            qkv = qkv.reshape(b, s, G, d)
            t = lambda z: z.transpose(0, 2, 1, 3)
            q, k, v = (t(qkv[:, :, :h]), t(qkv[:, :, h:h + hkv]),
                       t(qkv[:, :, h + hkv:]))
            rep = h // hkv
            o = dense_ref(q, jnp.repeat(k, rep, 1), jnp.repeat(v, rep, 1),
                          causal, scale)
            return jnp.einsum("bhsd,Hhd->bsH", o,
                              w_out.reshape(H, h, d))

        def fused(x, w_qkv, b_qkv, w_out):
            return fused_qkv_attention(x, w_qkv, b_qkv, w_out, None, None,
                                       None, h, hkv, d, scale, causal)

        with jax.default_matmul_precision("highest"):
            y1 = fused(x, w_qkv, b_qkv, w_out)
            y2 = composed(x, w_qkv, b_qkv, w_out)
            np.testing.assert_allclose(y1, y2, rtol=2e-5, atol=2e-5)

            loss1 = lambda *a: jnp.sum(jnp.sin(fused(*a)))
            loss2 = lambda *a: jnp.sum(jnp.sin(composed(*a)))
            g1 = jax.grad(loss1, argnums=(0, 1, 2, 3))(
                x, w_qkv, b_qkv, w_out)
            g2 = jax.grad(loss2, argnums=(0, 1, 2, 3))(
                x, w_qkv, b_qkv, w_out)
        for a, e, name in zip(g1, g2, ("dx", "dw_qkv", "db_qkv", "dw_out")):
            np.testing.assert_allclose(a, e, rtol=3e-4, atol=3e-4,
                                       err_msg=name)

    def test_causal_sq_gt_sk_raises(self):
        """ADVICE r2: bottom-right causal with sq > sk has rows attending
        nothing — reject instead of emitting exp(0) garbage."""
        q = jr.normal(K, (2, 64, 16))
        k = jr.normal(jr.fold_in(K, 9), (2, 32, 16))
        with pytest.raises(ValueError, match="sq <= sk"):
            flash_attention(q, k, k, causal=True)

    def test_mismatched_heads_raise(self):
        q = jr.normal(K, (2, 3, 32, 16))
        k = jr.normal(K, (2, 2, 32, 16))
        with pytest.raises(ValueError, match="kv heads"):
            flash_attention(q, k, k)
        # a mismatched BATCH dim must not be mistaken for a kv-head group
        q = jr.normal(K, (2, 4, 32, 16))
        k = jr.normal(K, (1, 4, 32, 16))
        with pytest.raises(ValueError, match="equal batch dims"):
            flash_attention(q, k, k)


class TestVarlenAttention:
    """Per-row kv valid lengths (padded batches) — the flash analog of the
    reference's mask-tensor softmax, expressed in O(rows)."""

    def _oracle(self, q, k, v, lens, causal):
        sk = k.shape[-2]
        s = jnp.einsum("...qd,...kd->...qk", q, k) / q.shape[-1] ** 0.5
        if causal:
            sq = s.shape[-2]
            cm = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None] + (sk - sq)
            s = jnp.where(cm, s, -1e30)
        lm = jnp.arange(sk)[None, None, :] < lens[:, None, None]
        s = jnp.where(lm, s, -1e30)
        o = jnp.einsum("...qk,...kd->...qd", jax.nn.softmax(s, -1), v)
        return jnp.where((lens == 0)[:, None, None], 0.0, o)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_masked_dense(self, causal):
        bh, s, d = 4, 32, 16
        q = jr.normal(K, (bh, s, d))
        k = jr.normal(jr.fold_in(K, 1), (bh, s, d))
        v = jr.normal(jr.fold_in(K, 2), (bh, s, d))
        lens = jnp.array([32, 17, 1, 0], jnp.int32)
        o = flash_attention(q, k, v, causal=causal, kv_lens=lens)
        np.testing.assert_allclose(o, self._oracle(q, k, v, lens, causal),
                                   rtol=RTOL, atol=ATOL)

    def test_grads_match_masked_dense(self):
        bh, s, d = 3, 32, 16
        q = jr.normal(K, (bh, s, d))
        k = jr.normal(jr.fold_in(K, 3), (bh, s, d))
        v = jr.normal(jr.fold_in(K, 4), (bh, s, d))
        lens = jnp.array([32, 9, 0], jnp.int32)
        f1 = lambda q, k, v: jnp.sum(jnp.sin(
            flash_attention(q, k, v, causal=True, kv_lens=lens)))
        f2 = lambda q, k, v: jnp.sum(jnp.sin(self._oracle(q, k, v, lens, True)))
        g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, rtol=G_RTOL, atol=G_ATOL)

    @pytest.mark.pallas
    def test_pallas_kernel_varlen_fwd_bwd(self, monkeypatch):
        """In-kernel masking + dynamic block skip + dead-row lse pinning."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        bh, s, d = 2, 256, 64
        q = jr.normal(K, (bh, s, d)).astype(jnp.float32)
        k = jr.normal(jr.fold_in(K, 5), (bh, s, d))
        v = jr.normal(jr.fold_in(K, 6), (bh, s, d))
        lens = jnp.array([256, 0], jnp.int32)  # include a DEAD row: the
        # kernel's all-blocks-skipped path + lse pinning must hold in-kernel
        with jax.default_matmul_precision("highest"):
            o = flash_attention(q, k, v, causal=True, kv_lens=lens,
                                impl="pallas")
            np.testing.assert_allclose(o, self._oracle(q, k, v, lens, True),
                                       rtol=2e-5, atol=2e-5)
            f1 = lambda q, k, v: jnp.sum(jnp.cos(flash_attention(
                q, k, v, causal=True, kv_lens=lens, impl="pallas")))
            f2 = lambda q, k, v: jnp.sum(jnp.cos(
                self._oracle(q, k, v, lens, True)))
            g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
            g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(g1, g2):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-4)

    def test_varlen_with_gqa(self):
        b, hq, kvh, s, d = 2, 4, 2, 32, 16
        q = jr.normal(K, (b, hq, s, d))
        k = jr.normal(jr.fold_in(K, 7), (b, kvh, s, d))
        v = jr.normal(jr.fold_in(K, 8), (b, kvh, s, d))
        lens = jnp.broadcast_to(jnp.array([20, 32], jnp.int32)[:, None],
                                (b, hq))
        o = flash_attention(q, k, v, kv_lens=lens)
        rep = hq // kvh
        kr, vr = jnp.repeat(k, rep, 1), jnp.repeat(v, rep, 1)
        ref = self._oracle(q.reshape(b * hq, s, d), kr.reshape(b * hq, s, d),
                           vr.reshape(b * hq, s, d), lens.reshape(-1),
                           False).reshape(b, hq, s, d)
        np.testing.assert_allclose(o, ref, rtol=RTOL, atol=ATOL)

    def test_bad_lens_shape_raises(self):
        q = jr.normal(K, (2, 4, 32, 16))
        with pytest.raises(ValueError, match="kv_lens"):
            flash_attention(q, q, q, kv_lens=jnp.ones((2,), jnp.int32))


def _ring_apply(mesh, cp, causal, q, k, v):
    """Run ring attention on globally-laid-out q/k/v: zigzag-permute for
    causal (the required layout), shard, un-permute the output."""
    if causal:
        q, k, v = (zigzag_shard(x, cp, 1) for x in (q, k, v))
    o = mesh_lib.shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=causal),
        mesh=mesh,
        in_specs=(P(None, "cp"),) * 3,
        out_specs=P(None, "cp"),
    )(q, k, v)
    return zigzag_unshard(o, cp, 1) if causal else o


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_full_sequence(self, causal):
        cp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=cp)
        S = 32  # full sequence; each device holds 8
        q = jr.normal(K, (2, S, 16))
        k = jr.normal(jr.fold_in(K, 7), (2, S, 16))
        v = jr.normal(jr.fold_in(K, 8), (2, S, 16))

        o = _ring_apply(mesh, cp, causal, q, k, v)
        np.testing.assert_allclose(
            o, dense_ref(q, k, v, causal), rtol=RTOL, atol=ATOL
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grouped_kv_matches_dense(self, causal):
        """GQA under context parallelism: the NARROW kv rotates the ring
        (the bandwidth win); result == dense on repeated kv."""
        cp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=cp)
        S, hq, kvh, d = 32, 4, 2, 16
        q = jr.normal(K, (hq, S, d))         # (bh_q, s, d) rows
        k = jr.normal(jr.fold_in(K, 7), (kvh, S, d))
        v = jr.normal(jr.fold_in(K, 8), (kvh, S, d))

        o = _ring_apply(mesh, cp, causal, q, k, v)
        rep = hq // kvh
        np.testing.assert_allclose(
            o, dense_ref(q, jnp.repeat(k, rep, 0), jnp.repeat(v, rep, 0),
                         causal),
            rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, causal):
        """Full q/k/v gradient parity against the dense oracle — exercises
        the distributed flash backward (traveling dkv accumulators)."""
        cp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=cp)
        S = 32
        q = jr.normal(K, (2, S, 16))
        k = jr.normal(jr.fold_in(K, 9), (2, S, 16))
        v = jr.normal(jr.fold_in(K, 10), (2, S, 16))

        def local_loss(q, k, v):
            # local shard's loss term; the global loss is the implicit sum
            # over shards, and the ring's reverse permutes deliver each
            # shard's cotangent contributions (psum here would double-count
            # under the conservative collective transpose)
            o = ring_attention(q, k, v, causal=causal)
            return jnp.sum(o * o)

        qs, ks, vs = ((zigzag_shard(x, cp, 1) for x in (q, k, v))
                      if causal else (q, k, v))
        g = mesh_lib.shard_map(
            lambda q, k, v: jax.grad(local_loss, argnums=(0, 1, 2))(q, k, v),
            mesh=mesh,
            in_specs=(P(None, "cp"),) * 3,
            out_specs=(P(None, "cp"),) * 3,
        )(qs, ks, vs)
        if causal:
            g = tuple(zigzag_unshard(x, cp, 1) for x in g)
        gref = jax.grad(
            lambda q, k, v: jnp.sum(dense_ref(q, k, v, causal) ** 2),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, e in zip(g, gref):
            np.testing.assert_allclose(a, e, rtol=G_RTOL, atol=G_ATOL)

    def test_grouped_kv_grads_match_dense(self):
        """GQA causal grads through the ring (narrow dkv travels the ring,
        group-summed by the kernel backward)."""
        cp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=cp)
        S, hq, kvh, d = 32, 4, 2, 16
        q = jr.normal(K, (hq, S, d))
        k = jr.normal(jr.fold_in(K, 11), (kvh, S, d))
        v = jr.normal(jr.fold_in(K, 12), (kvh, S, d))

        def local_loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, causal=True) ** 2)

        qs, ks, vs = (zigzag_shard(x, cp, 1) for x in (q, k, v))
        g = mesh_lib.shard_map(
            lambda q, k, v: jax.grad(local_loss, argnums=(0, 1, 2))(q, k, v),
            mesh=mesh,
            in_specs=(P(None, "cp"),) * 3,
            out_specs=(P(None, "cp"),) * 3,
        )(qs, ks, vs)
        g = tuple(zigzag_unshard(x, cp, 1) for x in g)
        rep = hq // kvh

        def dense_loss(q, k, v):
            return jnp.sum(dense_ref(q, jnp.repeat(k, rep, 0),
                                     jnp.repeat(v, rep, 0), True) ** 2)

        gref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(g, gref):
            np.testing.assert_allclose(a, e, rtol=G_RTOL, atol=G_ATOL)

    def test_zigzag_roundtrip(self):
        x = jr.normal(K, (3, 48, 4))
        for cp in (2, 3, 4):
            rt = zigzag_unshard(zigzag_shard(x, cp, 1), cp, 1)
            np.testing.assert_array_equal(rt, x)
        with pytest.raises(ValueError, match="stripes"):
            zigzag_shard(x, 5, 1)

    def test_causal_flops_are_lower_triangle_only(self):
        """The zigzag schedule's whole point: per ring step every rank does
        exactly TWO stripe-sized (ss) attention pieces — no full-shard
        matmuls, no masked-and-discarded work — and the only 2ss-sized dots
        are the single local diagonal. Verified on the compiled HLO's dot
        inventory (the scan body appears once)."""
        import re
        from collections import Counter

        cp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=cp)
        S, d = 512, 256
        ss = S // cp // 2  # stripe length
        q = jr.normal(K, (2, S, d))

        fn = mesh_lib.shard_map(
            lambda q, k, v: ring_attention(q, k, v, causal=True),
            mesh=mesh, in_specs=(P(None, "cp"),) * 3,
            out_specs=P(None, "cp"),
        )
        txt = jax.jit(fn).lower(q, q, q).compile().as_text()
        dots = Counter(
            m.group(1) for m in re.finditer(r"= (\S+) dot\(", txt))
        # scan body (runs cp-1 times): piece1 + piece2 = 2 QK dots (ss, ss)
        # and 2 PV dots (ss, d)
        assert dots.get(f"f32[2,{ss},{ss}]{{2,1,0}}") == 2, dots
        assert dots.get(f"f32[2,{ss},{d}]{{2,1,0}}") == 2, dots
        # the local diagonal: exactly one 2ss-sized QK + PV pair, nothing
        # bigger anywhere
        assert dots.get(f"f32[2,{2*ss},{2*ss}]{{2,1,0}}") == 1, dots
        assert dots.get(f"f32[2,{2*ss},{d}]{{2,1,0}}") == 1, dots
        assert sum(dots.values()) == 6, dots


class TestUlyssesAttention:
    """All-to-all sequence parallelism (SURVEY §2.3's absent Ulysses row)
    against the same dense oracle as flash/ring."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_full_sequence(self, causal):
        sp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=sp)
        B, S, H, D = 2, 32, 8, 16
        q = jr.normal(K, (B, S, H, D))
        k = jr.normal(jr.fold_in(K, 21), (B, S, H, D))
        v = jr.normal(jr.fold_in(K, 22), (B, S, H, D))

        o = mesh_lib.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, causal=causal),
            mesh=mesh,
            in_specs=(P(None, "cp"),) * 3,
            out_specs=P(None, "cp"),
        )(q, k, v)
        # oracle: per-head dense attention over the full sequence
        ref = dense_ref(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                        v.transpose(0, 2, 1, 3), causal).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(o, ref, rtol=RTOL, atol=ATOL)

    @pytest.mark.pallas
    def test_head_dim_64_multi_head_takes_flat_kernel(self, monkeypatch):
        """Review catch: head_dim 64 with several local heads is bshd-
        ineligible — Ulysses must route through the bh-flat kernel path
        (impl='pallas' would raise on the bshd direct call), never the
        bshd XLA fallback that materializes full gathered-seq scores."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        sp = 2
        mesh = mesh_lib.make_mesh(context_parallel_size=sp)
        B, S, H, D = 1, 256, 4, 64
        q = jr.normal(K, (B, S, H, D)).astype(jnp.float32)
        k = jr.normal(jr.fold_in(K, 61), (B, S, H, D))
        v = jr.normal(jr.fold_in(K, 62), (B, S, H, D))
        with jax.default_matmul_precision("highest"):
            o = mesh_lib.shard_map(
                lambda q, k, v: ulysses_attention(q, k, v, causal=True,
                                                  impl="pallas"),
                mesh=mesh,
                in_specs=(P(None, "cp"),) * 3,
                out_specs=P(None, "cp"),
            )(q, k, v)
            ref = dense_ref(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                            v.transpose(0, 2, 1, 3), True).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(o, ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grouped_kv_matches_dense(self, causal):
        """GQA through Ulysses: q and kv scatter their own head counts (kv
        all_to_alls move group-times less data); flash handles grouping."""
        sp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=sp)
        B, S, H, HKV, D = 2, 32, 8, 4, 16
        q = jr.normal(K, (B, S, H, D))
        k = jr.normal(jr.fold_in(K, 21), (B, S, HKV, D))
        v = jr.normal(jr.fold_in(K, 22), (B, S, HKV, D))

        o = mesh_lib.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, causal=causal),
            mesh=mesh,
            in_specs=(P(None, "cp"),) * 3,
            out_specs=P(None, "cp"),
        )(q, k, v)
        rep = H // HKV
        kr = jnp.repeat(k, rep, 2)
        vr = jnp.repeat(v, rep, 2)
        ref = dense_ref(q.transpose(0, 2, 1, 3), kr.transpose(0, 2, 1, 3),
                        vr.transpose(0, 2, 1, 3), causal).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(o, ref, rtol=RTOL, atol=ATOL)

    def test_grads_match_dense(self):
        sp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=sp)
        B, S, H, D = 1, 32, 4, 16
        q = jr.normal(K, (B, S, H, D))
        k = jr.normal(jr.fold_in(K, 23), (B, S, H, D))
        v = jr.normal(jr.fold_in(K, 24), (B, S, H, D))

        def local_loss(q, k, v):
            o = ulysses_attention(q, k, v, causal=True)
            return jnp.sum(o * o)

        g = mesh_lib.shard_map(
            lambda q, k, v: jax.grad(local_loss, argnums=(0, 1, 2))(q, k, v),
            mesh=mesh,
            in_specs=(P(None, "cp"),) * 3,
            out_specs=(P(None, "cp"),) * 3,
        )(q, k, v)
        def ref_loss(q, k, v):
            o = dense_ref(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), True)
            return jnp.sum(o * o)
        gref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(g, gref):
            np.testing.assert_allclose(a, e, rtol=G_RTOL, atol=G_ATOL)

    def test_heads_not_divisible_raises(self):
        sp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=sp)
        q = jr.normal(K, (1, 32, 6, 16))  # 6 heads, sp=4
        with pytest.raises(ValueError, match="divisible"):
            mesh_lib.shard_map(
                lambda q: ulysses_attention(q, q, q),
                mesh=mesh, in_specs=(P(None, "cp"),),
                out_specs=P(None, "cp"),
            )(q)


class TestFlashAutoDispatch:
    def test_crossover_rule(self):
        """The measured auto-dispatch thresholds (PERF.md): 1024 at d=64,
        512 from d=128 — pinned so a dispatch edit can't silently flip
        which impl serves S in [512, 1024)."""
        from apex_tpu.ops.attention import flash_auto_crossover

        assert flash_auto_crossover(64) == 1024
        assert flash_auto_crossover(128) == 512
        assert flash_auto_crossover(256) == 512


class TestFlashDropout:
    """In-kernel attention dropout (the reference's fused-kernel capability
    — fmha_api.cpp:44,80-83 — rebuilt as a stateless counter-hash mask):
    kernel vs dense reference under the SAME mask, grads, determinism,
    dispatch-invariance, statistics."""

    RATE = 0.4

    def _dense_drop_ref(self, q, k, v, causal, scale, seed, rate,
                        kv_lens=None):
        """Dense oracle using the exact mask the kernels generate."""
        from apex_tpu.ops.attention import (_dropout_apply_dense,
                                            _dropout_keep_dense,
                                            masked_scores)

        s = masked_scores(q, k, scale, causal, kv_lens)
        lse = jax.nn.logsumexp(s, axis=-1)
        p = jnp.exp(s - lse[..., None])
        keep = _dropout_keep_dense(seed, s.shape[0], s.shape[-2],
                                   s.shape[-1], rate)
        return jnp.einsum("bqk,bkd->bqd",
                          _dropout_apply_dense(p, keep, rate), v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_kernel_matches_dense_same_mask(self, causal, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        bh, s, d = 3, 256, 64
        q = jr.normal(K, (bh, s, d))
        k = jr.normal(jr.fold_in(K, 50), (bh, s, d))
        v = jr.normal(jr.fold_in(K, 51), (bh, s, d))
        seed = jnp.int32(20240731)
        scale = 1.0 / d ** 0.5

        with jax.default_matmul_precision("highest"):
            f1 = lambda q, k, v: jnp.sum(jnp.sin(flash_attention(
                q, k, v, causal=causal, impl="pallas",
                dropout_rate=self.RATE, dropout_seed=seed)))
            f2 = lambda q, k, v: jnp.sum(jnp.sin(self._dense_drop_ref(
                q, k, v, causal, scale, seed, self.RATE)))
            np.testing.assert_allclose(float(jax.jit(f1)(q, k, v)),
                                       float(jax.jit(f2)(q, k, v)),
                                       rtol=1e-5)
            g1 = jax.jit(jax.grad(f1, argnums=(0, 1, 2)))(q, k, v)
            g2 = jax.jit(jax.grad(f2, argnums=(0, 1, 2)))(q, k, v)
        for a, e, n in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-5,
                                       err_msg=n)

    def test_gqa_kernel_matches_dense_same_mask(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, h, hkv, s, d = 2, 4, 2, 128, 64
        q = jr.normal(K, (b, h, s, d))
        k = jr.normal(jr.fold_in(K, 52), (b, hkv, s, d))
        v = jr.normal(jr.fold_in(K, 53), (b, hkv, s, d))
        seed = jnp.int32(7)
        scale = 1.0 / d ** 0.5
        rep = h // hkv

        with jax.default_matmul_precision("highest"):
            o = jax.jit(lambda q, k, v: flash_attention(
                q, k, v, causal=True, impl="pallas",
                dropout_rate=self.RATE, dropout_seed=seed))(q, k, v)
            ref = self._dense_drop_ref(
                q.reshape(b * h, s, d),
                jnp.repeat(k, rep, 1).reshape(b * h, s, d),
                jnp.repeat(v, rep, 1).reshape(b * h, s, d),
                True, scale, seed, self.RATE).reshape(b, h, s, d)
        np.testing.assert_allclose(o, ref, rtol=2e-5, atol=2e-5)

    def test_varlen_composes_with_dropout(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        bh, s, d = 4, 128, 64
        q = jr.normal(K, (bh, s, d))
        k = jr.normal(jr.fold_in(K, 54), (bh, s, d))
        v = jr.normal(jr.fold_in(K, 55), (bh, s, d))
        kv_lens = jnp.array([128, 96, 17, 0], jnp.int32)
        seed = jnp.int32(99)
        scale = 1.0 / d ** 0.5
        with jax.default_matmul_precision("highest"):
            o = flash_attention(q, k, v, kv_lens=kv_lens, impl="pallas",
                                dropout_rate=self.RATE, dropout_seed=seed)
            ref = self._dense_drop_ref(q, k, v, False, scale, seed,
                                       self.RATE, kv_lens=kv_lens)
            ref = jnp.where((kv_lens == 0)[:, None, None], 0.0, ref)
        np.testing.assert_allclose(o, ref, rtol=2e-5, atol=2e-5)

    def test_xla_and_pallas_masks_identical(self, monkeypatch):
        """The impl choice must never change a training run: both dispatches
        evaluate the same counter hash."""
        bh, s, d = 2, 256, 64
        q = jr.normal(K, (bh, s, d))
        k = jr.normal(jr.fold_in(K, 56), (bh, s, d))
        v = jr.normal(jr.fold_in(K, 57), (bh, s, d))
        seed = jnp.int32(5)
        with jax.default_matmul_precision("highest"):
            monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
            o_pl = flash_attention(q, k, v, causal=True, impl="pallas",
                                   dropout_rate=self.RATE, dropout_seed=seed)
            monkeypatch.delenv("APEX_TPU_PALLAS")
            o_xla = flash_attention(q, k, v, causal=True, impl="xla",
                                    dropout_rate=self.RATE,
                                    dropout_seed=seed)
        np.testing.assert_allclose(o_pl, o_xla, rtol=2e-5, atol=2e-5)

    def test_packed_fused_matches_bshd_same_seed(self, monkeypatch):
        """fused_qkv_attention's in-kernel dropout: same q-head grid index
        => same mask as the bshd composition; fwd + all cotangents."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.attention import fused_qkv_attention

        # d=128: the bshd eligibility rule (128-lane folded blocks) must
        # hold for the composed reference path too
        b, s, H, h, d = 2, 128, 64, 2, 128
        hkv = 1
        G = h + 2 * hkv
        key = jr.fold_in(K, 58)
        x = jr.normal(key, (b, s, H))
        w_qkv = jr.normal(jr.fold_in(key, 1), (G * d, H)) * 0.1
        b_qkv = jr.normal(jr.fold_in(key, 2), (G * d,)) * 0.1
        w_out = jr.normal(jr.fold_in(key, 3), (H, h * d)) * 0.1
        scale = 1.0 / d ** 0.5
        seed = jnp.int32(11)

        def composed(x, w_qkv, b_qkv, w_out):
            qkv = jnp.einsum("bsH,FH->bsF", x, w_qkv) + b_qkv
            qkv = qkv.reshape(b, s, G, d)
            q, k, v = (qkv[:, :, :h], qkv[:, :, h:h + hkv],
                       qkv[:, :, h + hkv:])
            o = flash_attention(q, k, v, causal=True, layout="bshd",
                                impl="pallas", scale=scale,
                                dropout_rate=self.RATE, dropout_seed=seed)
            return jnp.einsum("bshd,Hhd->bsH", o, w_out.reshape(H, h, d))

        def fused(x, w_qkv, b_qkv, w_out):
            return fused_qkv_attention(x, w_qkv, b_qkv, w_out, None, seed,
                                       None, h, hkv, d, scale, True,
                                       self.RATE)

        with jax.default_matmul_precision("highest"):
            np.testing.assert_allclose(fused(x, w_qkv, b_qkv, w_out),
                                       composed(x, w_qkv, b_qkv, w_out),
                                       rtol=2e-5, atol=2e-5)
            l1 = lambda *a: jnp.sum(jnp.sin(fused(*a)))
            l2 = lambda *a: jnp.sum(jnp.sin(composed(*a)))
            g1 = jax.grad(l1, argnums=(0, 1, 2, 3))(x, w_qkv, b_qkv, w_out)
            g2 = jax.grad(l2, argnums=(0, 1, 2, 3))(x, w_qkv, b_qkv, w_out)
        for a, e, n in zip(g1, g2, ("x", "w_qkv", "b_qkv", "w_out")):
            np.testing.assert_allclose(a, e, rtol=3e-4, atol=3e-5,
                                       err_msg=n)

    def test_determinism_and_seed_sensitivity(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        bh, s, d = 2, 128, 64
        q = jr.normal(K, (bh, s, d))
        k = jr.normal(jr.fold_in(K, 60), (bh, s, d))
        v = jr.normal(jr.fold_in(K, 61), (bh, s, d))
        run = lambda sd: flash_attention(
            q, k, v, causal=True, impl="pallas", dropout_rate=self.RATE,
            dropout_seed=jnp.int32(sd))
        a, b_, c = run(3), run(3), run(4)
        np.testing.assert_array_equal(a, b_)
        assert float(jnp.max(jnp.abs(a - c))) > 0.0

    def test_mask_statistics(self):
        """Keep fraction ~ (1-rate), E[mask_scale] ~ 1 (unbiasedness), and
        the mask is unbiased per row (the softmax-probs weighting)."""
        from apex_tpu.ops.attention import (_dropout_apply_dense,
                                            _dropout_keep_dense)

        ms = _dropout_apply_dense(
            jnp.float32(1.0),
            _dropout_keep_dense(jnp.int32(123), 8, 256, 256, self.RATE),
            self.RATE)
        keep_frac = float(jnp.mean(ms > 0))
        np.testing.assert_allclose(keep_frac, 1 - self.RATE, atol=5e-3)
        np.testing.assert_allclose(float(jnp.mean(ms)), 1.0, atol=2e-2)
        # per-row means concentrate around 1 — no row systematically dark
        row_means = jnp.mean(ms, axis=-1)
        assert float(jnp.max(jnp.abs(row_means - 1.0))) < 0.25

    def test_rate_validation(self):
        q = jr.normal(K, (2, 128, 64))
        with pytest.raises(ValueError, match="requires dropout_seed"):
            flash_attention(q, q, q, dropout_rate=0.1)
        with pytest.raises(ValueError, match="dropout_rate"):
            flash_attention(q, q, q, dropout_rate=1.5,
                            dropout_seed=jnp.int32(1))


class TestGPTFlashDropout:
    """GPT trains with dropout>0 ON the flash kernel paths (VERDICT r3
    missing #1: no more materialized-scores forfeit)."""

    def test_flash_dropout_trains_and_is_keyed(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.models import GPTConfig, GPTModel

        cfg = GPTConfig(vocab_size=64, max_seq_len=128, hidden_size=64,
                        num_layers=2, num_heads=1, dropout=0.2,
                        attention_impl="flash")
        m = GPTModel(cfg)
        p = m.init(jr.fold_in(K, 70))
        toks = jr.randint(jr.fold_in(K, 71), (2, 128), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 72), (2, 128), 0, 64)

        loss_fn = lambda p, kk: m.loss_fn(p, toks, tgts, key=kk)
        l1, g = jax.value_and_grad(loss_fn)(p, jr.PRNGKey(1))
        l1b = loss_fn(p, jr.PRNGKey(1))
        l2 = loss_fn(p, jr.PRNGKey(2))
        l0 = m.loss_fn(p, toks, tgts)  # eval mode: no dropout
        assert jnp.isfinite(l1)
        assert float(l1) == float(l1b)  # keyed determinism
        assert float(l1) != float(l2)
        assert float(l1) != float(l0)
        for leaf in jax.tree.leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))


class TestVarlenFastPath:
    """kv_lens on the bshd and packed kernels (VERDICT r3 weak #5 / next
    #6): per-BATCH lengths ride the head-folded index maps; BERT's padded
    batches keep the zero-layout-copy route."""

    def _dense_varlen_ref(self, q4, k4, v4, lens, scale):
        """bhsd dense oracle from (b, s, h, d) operands + (b,) lengths."""
        b, s, h, d = q4.shape
        t = lambda z: z.transpose(0, 2, 1, 3).reshape(b * z.shape[2], s, d)
        from apex_tpu.ops.attention import _xla_attention
        o3, _ = _xla_attention(t(q4), t(k4), t(v4), scale, False,
                               jnp.repeat(lens, h))
        return o3.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    @pytest.mark.parametrize("kv_heads", [2, 1])
    def test_bshd_kernel_varlen_matches_dense(self, kv_heads, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, s, h, d = 4, 256, 2, 128
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 80), (b, s, kv_heads, d))
        v = jr.normal(jr.fold_in(K, 81), (b, s, kv_heads, d))
        lens = jnp.array([256, 130, 7, 0], jnp.int32)
        scale = 1.0 / d ** 0.5
        rep = h // kv_heads

        with jax.default_matmul_precision("highest"):
            f1 = lambda q, k, v: jnp.sum(jnp.sin(flash_attention(
                q, k, v, kv_lens=lens, layout="bshd", impl="pallas")))
            ref = lambda q, k, v: jnp.sum(jnp.sin(self._dense_varlen_ref(
                q, jnp.repeat(k, rep, 2), jnp.repeat(v, rep, 2), lens,
                scale)))
            np.testing.assert_allclose(float(f1(q, k, v)),
                                       float(ref(q, k, v)), rtol=1e-5)
            g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
            g2 = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for a, e, n in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-5,
                                       err_msg=n)

    def test_bshd_varlen_with_dropout(self, monkeypatch):
        """varlen + in-kernel dropout compose on the bshd path."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, s, h, d = 2, 128, 1, 128
        q = jr.normal(K, (b, s, h, d))
        lens = jnp.array([128, 60], jnp.int32)
        seed = jnp.int32(3)
        o = flash_attention(q, q, q, kv_lens=lens, layout="bshd",
                            impl="pallas", dropout_rate=0.3,
                            dropout_seed=seed)
        o2 = flash_attention(q, q, q, kv_lens=lens, layout="bshd",
                             impl="xla", dropout_rate=0.3,
                             dropout_seed=seed)
        np.testing.assert_allclose(o, o2, rtol=2e-5, atol=2e-5)
        # masked-out tail of row 1 contributes nothing
        assert bool(jnp.all(jnp.isfinite(o)))

    def test_packed_fused_varlen_matches_bshd(self, monkeypatch):
        """fused_qkv_attention with kv_lens == the bshd composition —
        padded/ragged batches ride the zero-layout-copy block. Multi-block
        (s=256, bq=128 via block override is not exposed — use s=256 with
        default fitting) AND the two-kernel backward (varlen skips the
        single-block fused kernel)."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.attention import fused_qkv_attention

        b, s, H, h, d = 2, 256, 64, 2, 128
        hkv = 2
        G = h + 2 * hkv
        key = jr.fold_in(K, 82)
        x = jr.normal(key, (b, s, H))
        w_qkv = jr.normal(jr.fold_in(key, 1), (G * d, H)) * 0.1
        b_qkv = jr.normal(jr.fold_in(key, 2), (G * d,)) * 0.1
        w_out = jr.normal(jr.fold_in(key, 3), (H, h * d)) * 0.1
        lens = jnp.array([256, 100], jnp.int32)
        scale = 1.0 / d ** 0.5

        def composed(x, w_qkv, b_qkv, w_out):
            qkv = jnp.einsum("bsH,FH->bsF", x, w_qkv) + b_qkv
            qkv = qkv.reshape(b, s, G, d)
            q, k, v = (qkv[:, :, :h], qkv[:, :, h:h + hkv],
                       qkv[:, :, h + hkv:])
            o = flash_attention(q, k, v, kv_lens=lens, layout="bshd",
                                impl="pallas", scale=scale, causal=True)
            return jnp.einsum("bshd,Hhd->bsH", o, w_out.reshape(H, h, d))

        def fused(x, w_qkv, b_qkv, w_out):
            return fused_qkv_attention(x, w_qkv, b_qkv, w_out, None, None,
                                       lens, h, hkv, d, scale, True)

        with jax.default_matmul_precision("highest"):
            np.testing.assert_allclose(fused(x, w_qkv, b_qkv, w_out),
                                       composed(x, w_qkv, b_qkv, w_out),
                                       rtol=2e-5, atol=2e-5)
            l1 = lambda *a: jnp.sum(jnp.sin(fused(*a)))
            l2 = lambda *a: jnp.sum(jnp.sin(composed(*a)))
            g1 = jax.grad(l1, argnums=(0, 1, 2, 3))(x, w_qkv, b_qkv, w_out)
            g2 = jax.grad(l2, argnums=(0, 1, 2, 3))(x, w_qkv, b_qkv, w_out)
        for a, e, n in zip(g1, g2, ("x", "w_qkv", "b_qkv", "w_out")):
            np.testing.assert_allclose(a, e, rtol=3e-4, atol=3e-5,
                                       err_msg=n)

    def test_bshd_rejects_wrong_lens_shape(self):
        q = jr.normal(K, (2, 128, 1, 128))
        with pytest.raises(ValueError, match="per-batch kv_lens"):
            flash_attention(q, q, q, layout="bshd",
                            kv_lens=jnp.zeros((2, 1), jnp.int32))

    def test_bert_varlen_rides_bshd_kernels(self, monkeypatch):
        """BERT with suffix padding on a bshd-eligible config (d=128):
        flash == softmax impl, and the flash path goes through the bshd
        kernels (interpret forced so the kernel code actually runs)."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.models import BertConfig, BertModel

        kw = dict(vocab_size=64, max_seq_len=128, hidden_size=256,
                  num_layers=2, num_heads=2)  # head_dim 128: bshd-eligible
        m_f = BertModel(BertConfig(**kw, attention_impl="flash"))
        m_s = BertModel(BertConfig(**kw, attention_impl="softmax"))
        params = m_f.init(jr.fold_in(K, 83))
        b, s = 2, 128
        toks = jr.randint(jr.fold_in(K, 84), (b, s), 0, 64)
        # suffix padding: row 0 full, row 1 valid through 57
        pad_mask = jnp.arange(s)[None, :] >= jnp.array([[s], [57]])
        with jax.default_matmul_precision("highest"):
            h_f = m_f.hidden_states(params, toks, pad_mask=pad_mask)
            h_s = m_s.hidden_states(params, toks, pad_mask=pad_mask)
        # only VALID positions must agree (padding rows see garbage keys
        # in neither impl but their outputs are don't-care)
        np.testing.assert_allclose(h_f[0], h_s[0], rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(h_f[1, :57], h_s[1, :57], rtol=1e-4,
                                   atol=1e-4)


class TestCpDropout:
    """Dropout x context parallelism (r4 late): ring folds a distinct mask
    stream per (rank, step, piece) and re-derives it in its hand-written
    backward; ulysses folds the cp rank into the seed."""

    RATE = 0.3

    def _mesh(self):
        return mesh_lib.make_mesh(context_parallel_size=2)

    def test_ring_dropout_grads_match_autodiff(self):
        """The exactness witness: the custom VJP (hand-written piece
        backward with re-derived seeds) against plain autodiff through the
        forward implementation — any fwd/bwd mask inconsistency breaks
        this."""
        from apex_tpu.ops.attention import _ring_fwd_impl, ring_attention

        mesh = self._mesh()
        bh, s, d = 2, 64, 16  # XLA piece path (differentiable)
        seed = jnp.int32(77)
        q = jr.normal(K, (bh, 2 * s, d))
        k = jr.normal(jr.fold_in(K, 90), (bh, 2 * s, d))
        v = jr.normal(jr.fold_in(K, 91), (bh, 2 * s, d))

        def custom(q, k, v):
            o = ring_attention(q, k, v, axis_name="cp", causal=True,
                               impl="xla", dropout_rate=self.RATE,
                               dropout_seed=seed)
            return jnp.sum(jnp.sin(o))

        def auto(q, k, v):
            o, _ = _ring_fwd_impl(q, k, v, "cp", 1.0 / d ** 0.5, True,
                                  False, self.RATE, seed)
            return jnp.sum(jnp.sin(o))

        def run(q, k, v):
            g1 = jax.grad(custom, argnums=(0, 1, 2))(q, k, v)
            g2 = jax.grad(auto, argnums=(0, 1, 2))(q, k, v)
            return g1, g2

        from apex_tpu.ops.attention import zigzag_shard
        qz, kz, vz = (zigzag_shard(x, 2, 1) for x in (q, k, v))
        with jax.default_matmul_precision("highest"):
            g1, g2 = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(P(None, "cp"),) * 3,
                out_specs=((P(None, "cp"),) * 3,) * 2,
            ))(qz, kz, vz)
        for a, e, n in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-5,
                                       err_msg=n)

    def test_ring_dropout_deterministic_and_live(self):
        from apex_tpu.ops.attention import ring_attention, zigzag_shard

        mesh = self._mesh()
        bh, s, d = 2, 128, 64
        q = jr.normal(K, (bh, 2 * s, d))
        run = lambda sd: jax.jit(mesh_lib.shard_map(
            lambda q_: ring_attention(q_, q_, q_, axis_name="cp",
                                      causal=True, impl="xla",
                                      dropout_rate=self.RATE,
                                      dropout_seed=jnp.int32(sd)),
            mesh=mesh, in_specs=P(None, "cp"), out_specs=P(None, "cp"),
        ))(zigzag_shard(q, 2, 1))
        a, b_, c = run(5), run(5), run(6)
        np.testing.assert_array_equal(a, b_)
        assert float(jnp.max(jnp.abs(a - c))) > 0.0

    def test_ulysses_dropout_matches_per_rank_reference(self, monkeypatch):
        """Each device computes its head group with seed fold(base, rank);
        the host can replay exactly that — outputs must match."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.attention import (flash_attention,
                                            fold_dropout_seed,
                                            ulysses_attention)

        mesh = self._mesh()
        b, s, h, d = 2, 128, 2, 128
        base = jnp.int32(13)
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 92), (b, s, h, d))
        v = jr.normal(jr.fold_in(K, 93), (b, s, h, d))

        o = jax.jit(mesh_lib.shard_map(
            lambda q_, k_, v_: ulysses_attention(
                q_, k_, v_, axis_name="cp", causal=True, impl="pallas",
                dropout_rate=self.RATE, dropout_seed=base),
            mesh=mesh, in_specs=(P(None, "cp"),) * 3,
            out_specs=P(None, "cp"),
        ))(q, k, v)

        # host replay: rank r holds head group r (h/cp heads each)
        with jax.default_matmul_precision("highest"):
            parts = [
                flash_attention(
                    q[:, :, r:r + 1], k[:, :, r:r + 1], v[:, :, r:r + 1],
                    causal=True, layout="bshd", impl="pallas",
                    dropout_rate=self.RATE,
                    dropout_seed=fold_dropout_seed(base, r))
                for r in range(2)]
        ref = jnp.concatenate(parts, axis=2)
        np.testing.assert_allclose(o, ref, rtol=2e-5, atol=2e-5)

    def test_ring_rejects_missing_seed(self):
        q = jr.normal(K, (2, 64, 16))
        mesh = self._mesh()
        from apex_tpu.ops.attention import ring_attention
        with pytest.raises(ValueError, match="requires dropout_seed"):
            mesh_lib.shard_map(
                lambda q_: ring_attention(q_, q_, q_, axis_name="cp",
                                          dropout_rate=0.1),
                mesh=mesh, in_specs=P(None, "cp"),
                out_specs=P(None, "cp"))(q)


class TestRingBshd:
    """Ring attention on the seq-major layout (r4 late): the stripe pieces
    ride the bshd kernels — no transpose round trip per ring step."""

    def _mesh(self):
        return mesh_lib.make_mesh(context_parallel_size=2)

    @pytest.mark.parametrize("kv_heads", [2, 1])
    def test_bshd_ring_matches_flash(self, kv_heads, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        mesh = self._mesh()
        b, s, h, d = 2, 512, 2, 128  # s_local 256, stripes 128
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 95), (b, s, kv_heads, d))
        v = jr.normal(jr.fold_in(K, 96), (b, s, kv_heads, d))

        def run(q_, k_, v_):
            return ring_attention(q_, k_, v_, axis_name="cp", causal=True,
                                  layout="bshd", impl="pallas")

        qz, kz, vz = (zigzag_shard(x, 2, 1) for x in (q, k, v))
        with jax.default_matmul_precision("highest"):
            o = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(P(None, "cp"),) * 3,
                out_specs=P(None, "cp"),
            ))(qz, kz, vz)
            o = zigzag_unshard(o, 2, 1)
            ref = flash_attention(q, k, v, causal=True, layout="bshd",
                                  impl="pallas")
        np.testing.assert_allclose(o, ref, rtol=2e-4, atol=2e-5)

    def test_bshd_ring_grads_match_flat_ring(self):
        """Same math, two layouts: grads through the bshd state machine
        must equal the flat one's (which is itself pinned to dense)."""
        mesh = self._mesh()
        b, s, h, d = 2, 128, 2, 64
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 97), (b, s, h, d))
        v = jr.normal(jr.fold_in(K, 98), (b, s, h, d))
        to_bh = lambda z: z.transpose(0, 2, 1, 3).reshape(b * h, s, d)

        def run_bshd(q_, k_, v_):
            f = lambda *a: jnp.sum(jnp.sin(ring_attention(
                *a, axis_name="cp", causal=True, layout="bshd",
                impl="xla")))
            return jax.grad(f, argnums=(0, 1, 2))(q_, k_, v_)

        def run_flat(q_, k_, v_):
            f = lambda *a: jnp.sum(jnp.sin(ring_attention(
                *a, axis_name="cp", causal=True, impl="xla")))
            return jax.grad(f, argnums=(0, 1, 2))(q_, k_, v_)

        with jax.default_matmul_precision("highest"):
            qz, kz, vz = (zigzag_shard(x, 2, 1) for x in (q, k, v))
            g4 = jax.jit(mesh_lib.shard_map(
                run_bshd, mesh=mesh, in_specs=(P(None, "cp"),) * 3,
                out_specs=(P(None, "cp"),) * 3,
            ))(qz, kz, vz)
            qf, kf, vf = (zigzag_shard(to_bh(x), 2, 1) for x in (q, k, v))
            gf = jax.jit(mesh_lib.shard_map(
                run_flat, mesh=mesh, in_specs=(P(None, "cp"),) * 3,
                out_specs=(P(None, "cp"),) * 3,
            ))(qf, kf, vf)
        for a4, af, n in zip(g4, gf, "qkv"):
            a4f = zigzag_unshard(a4, 2, 1)
            aff = zigzag_unshard(af, 2, 1).reshape(b, h, s, d
                                                   ).transpose(0, 2, 1, 3)
            np.testing.assert_allclose(a4f, aff, rtol=2e-4, atol=2e-5,
                                       err_msg=n)

    def test_bshd_ring_dropout_grads_match_autodiff(self):
        """The dropout mask-consistency witness on the bshd state machine
        (custom VJP vs autodiff through the forward)."""
        from apex_tpu.ops.attention import _ring_fwd_impl

        mesh = self._mesh()
        b, s, h, d = 1, 128, 2, 16
        seed = jnp.int32(88)
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 99), (b, s, h, d))
        v = jr.normal(jr.fold_in(K, 100), (b, s, h, d))

        def custom(q_, k_, v_):
            o = ring_attention(q_, k_, v_, axis_name="cp", causal=True,
                               layout="bshd", impl="xla",
                               dropout_rate=0.3, dropout_seed=seed)
            return jnp.sum(jnp.sin(o))

        def auto(q_, k_, v_):
            o, _ = _ring_fwd_impl(q_, k_, v_, "cp", 1.0 / d ** 0.5, True,
                                  False, 0.3, seed, True)
            return jnp.sum(jnp.sin(o))

        def run(q_, k_, v_):
            return (jax.grad(custom, argnums=(0, 1, 2))(q_, k_, v_),
                    jax.grad(auto, argnums=(0, 1, 2))(q_, k_, v_))

        qz, kz, vz = (zigzag_shard(x, 2, 1) for x in (q, k, v))
        with jax.default_matmul_precision("highest"):
            g1, g2 = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(P(None, "cp"),) * 3,
                out_specs=((P(None, "cp"),) * 3,) * 2,
            ))(qz, kz, vz)
        for a, e, n in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-5,
                                       err_msg=n)

    def test_bshd_ring_pallas_bwd_matches_xla_dispatch(self, monkeypatch):
        """The production path's backward (Pallas bshd piece kernels with
        the ring's GLOBAL lse + per-piece dropout seeds) against the XLA
        dispatch — masks are bit-identical across dispatches by design,
        so grads must agree."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        mesh = self._mesh()
        b, s, h, d = 2, 512, 2, 128
        seed = jnp.int32(21)
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 101), (b, s, 1, d))  # GQA group 2
        v = jr.normal(jr.fold_in(K, 102), (b, s, 1, d))

        def make(impl):
            def f(q_, k_, v_):
                o = ring_attention(q_, k_, v_, axis_name="cp",
                                   causal=True, layout="bshd", impl=impl,
                                   dropout_rate=0.3, dropout_seed=seed)
                return jnp.sum(jnp.sin(o))
            def run(q_, k_, v_):
                return jax.grad(f, argnums=(0, 1, 2))(q_, k_, v_)
            return jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(P(None, "cp"),) * 3,
                out_specs=(P(None, "cp"),) * 3))

        qz, kz, vz = (zigzag_shard(x, 2, 1) for x in (q, k, v))
        with jax.default_matmul_precision("highest"):
            g_pl = make("pallas")(qz, kz, vz)
            g_xla = make("xla")(qz, kz, vz)
        for a, e, n in zip(g_pl, g_xla, "qkv"):
            np.testing.assert_allclose(a, e, rtol=3e-4, atol=3e-5,
                                       err_msg=n)

    def test_bshd_ring_rejects_mismatched_seq(self):
        mesh = self._mesh()
        q = jr.normal(K, (1, 128, 2, 128))
        k = jr.normal(K, (1, 256, 2, 128))
        with pytest.raises(ValueError, match="equal q/k/v local sequence"):
            mesh_lib.shard_map(
                lambda q_, k_: ring_attention(q_, k_, k_, axis_name="cp",
                                              layout="bshd"),
                mesh=mesh, in_specs=(P(None, "cp"), P(None, "cp")),
                out_specs=P(None, "cp"))(q, k)


class TestFlashBias:
    """In-kernel additive score bias (VERDICT r4 next #1): the reference
    fuses arbitrary masks into its softmax kernels
    (``csrc/megatron/scaled_masked_softmax.cpp:85-94``) and ships additive
    attn_mask MHA variants (``contrib/multihead_attn/self_multihead_attn
    .py:144-198``); here one (hb, sq, sk) bias operand rides every flash
    layout, differentiated via the batch-innermost dbias kernel."""

    def _dense_bias(self, q, k, v, bias, causal, kv_lens=None):
        """Dense oracle: rows of the flattened leading dims read bias row
        r % hb; bias adds to the SCALED scores before masks."""
        d = q.shape[-1]
        lead = q.shape[:-2]
        sq, sk = q.shape[-2], k.shape[-2]
        q3 = q.reshape(-1, sq, d)
        k3 = k.reshape(-1, sk, d)
        v3 = v.reshape(-1, sk, d)
        g = q3.shape[0] // k3.shape[0]
        if g > 1:
            k3 = jnp.repeat(k3, g, 0)
            v3 = jnp.repeat(v3, g, 0)
        hb = bias.shape[0]
        s = jnp.einsum("bqd,bkd->bqk", q3, k3) / d ** 0.5
        s = (s.reshape(-1, hb, sq, sk) + bias).reshape(-1, sq, sk)
        if causal:
            m = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None] + (sk - sq)
            s = jnp.where(m, s, -1e30)
        if kv_lens is not None:
            s = jnp.where(jnp.arange(sk)[None, None, :]
                          < kv_lens[:, None, None], s, -1e30)
        o = jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, -1), v3)
        return o.reshape(*lead, sq, d)

    @pytest.mark.pallas
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("hb", [1, 2])  # broadcast | per-head
    def test_kernel_fwd_bwd_vs_dense(self, causal, hb, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, h, s, d = 2, 2, 128, 64
        q = jr.normal(K, (b, h, s, d))
        k = jr.normal(jr.fold_in(K, 1), (b, h, s, d))
        v = jr.normal(jr.fold_in(K, 2), (b, h, s, d))
        bias = jr.normal(jr.fold_in(K, 3), (hb, s, s)) * 0.5

        def f(q, k, v, bias):
            return jnp.sum(jnp.sin(flash_attention(
                q, k, v, causal=causal, bias=bias, impl="pallas")))

        def ref(q, k, v, bias):
            return jnp.sum(jnp.sin(self._dense_bias(q, k, v, bias, causal)))

        with jax.default_matmul_precision("highest"):
            o = flash_attention(q, k, v, causal=causal, bias=bias,
                                impl="pallas")
            np.testing.assert_allclose(
                o, self._dense_bias(q, k, v, bias, causal),
                rtol=1e-4, atol=1e-4)
            g1 = jax.grad(f, (0, 1, 2, 3))(q, k, v, bias)
            g2 = jax.grad(ref, (0, 1, 2, 3))(q, k, v, bias)
        for a, e, n in zip(g1, g2, ["dq", "dk", "dv", "dbias"]):
            np.testing.assert_allclose(a, e, rtol=5e-4, atol=5e-4,
                                       err_msg=n)

    @pytest.mark.pallas
    def test_bshd_composed_gqa_varlen_dropout(self, monkeypatch):
        """All the operands at once on the seq-major layout: per-head
        bias + grouped kv + padded batch + in-kernel dropout — Pallas
        vs XLA dispatch (same mask hash, same bias math)."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, s, h, hkv, d = 2, 256, 4, 2, 128
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 4), (b, s, hkv, d))
        v = jr.normal(jr.fold_in(K, 5), (b, s, hkv, d))
        bias = jr.normal(jr.fold_in(K, 6), (h, s, s)) * 0.5
        lens = jnp.array([200, 128], jnp.int32)

        def make(impl):
            def f(q, k, v, bias):
                return jnp.sum(jnp.sin(flash_attention(
                    q, k, v, causal=True, bias=bias, kv_lens=lens,
                    layout="bshd", impl=impl, dropout_rate=0.15,
                    dropout_seed=7)))
            return f

        with jax.default_matmul_precision("highest"):
            o1 = flash_attention(q, k, v, causal=True, bias=bias,
                                 kv_lens=lens, layout="bshd",
                                 impl="pallas", dropout_rate=0.15,
                                 dropout_seed=7)
            o2 = flash_attention(q, k, v, causal=True, bias=bias,
                                 kv_lens=lens, layout="bshd", impl="xla",
                                 dropout_rate=0.15, dropout_seed=7)
            np.testing.assert_allclose(o1, o2, rtol=5e-4, atol=5e-4)
            g1 = jax.grad(make("pallas"), (0, 1, 2, 3))(q, k, v, bias)
            g2 = jax.grad(make("xla"), (0, 1, 2, 3))(q, k, v, bias)
        for a, e, n in zip(g1, g2, ["dq", "dk", "dv", "dbias"]):
            np.testing.assert_allclose(a, e, rtol=2e-3, atol=2e-3,
                                       err_msg=n)

    @pytest.mark.pallas
    def test_packed_fused_qkv_bias_grads(self, monkeypatch):
        """fused_qkv_attention with bias == the composed bshd path,
        through every weight gradient plus dbias."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        from apex_tpu.ops.attention import (bshd_output_projection,
                                            bshd_qkv_projection,
                                            fused_qkv_attention)
        b, s, h, hkv, d = 2, 128, 2, 1, 128
        H = h * d
        x = jr.normal(K, (b, s, H)) * 0.3
        w_qkv = jr.normal(jr.fold_in(K, 7), ((h + 2 * hkv) * d, H)) * 0.05
        b_qkv = jr.normal(jr.fold_in(K, 8), ((h + 2 * hkv) * d,)) * 0.02
        w_out = jr.normal(jr.fold_in(K, 9), (H, h * d)) * 0.05
        bias = jr.normal(jr.fold_in(K, 10), (h, s, s)) * 0.5
        scale = 1.0 / d ** 0.5

        def fused(x, w_qkv, b_qkv, w_out, bias):
            return fused_qkv_attention(x, w_qkv, b_qkv, w_out, bias, None,
                                       None, h, hkv, d, scale, True).sum()

        def composed(x, w_qkv, b_qkv, w_out, bias):
            qq, kq, vq = bshd_qkv_projection(x, w_qkv, b_qkv, h, hkv, d)
            ctx = flash_attention(qq, kq, vq, causal=True, bias=bias,
                                  layout="bshd", impl="xla")
            return bshd_output_projection(ctx, w_out, h, d).sum()

        with jax.default_matmul_precision("highest"):
            ga = jax.jit(jax.grad(fused, (0, 1, 2, 3, 4)))(
                x, w_qkv, b_qkv, w_out, bias)
            gb = jax.jit(jax.grad(composed, (0, 1, 2, 3, 4)))(
                x, w_qkv, b_qkv, w_out, bias)
        for a, e, n in zip(ga, gb, ["dx", "dw_qkv", "db_qkv", "dw_out",
                                    "dbias"]):
            np.testing.assert_allclose(a, e, rtol=2e-3, atol=2e-3,
                                       err_msg=n)

    def test_bias_validation(self):
        q = jr.normal(K, (2, 4, 128, 64))
        with pytest.raises(ValueError, match="bias must be"):
            flash_attention(q, q, q, bias=jnp.zeros((4, 64, 64)))
        with pytest.raises(ValueError, match="bias rows"):
            flash_attention(q, q, q, bias=jnp.zeros((3, 128, 128)))
        qs = jr.normal(K, (2, 128, 4, 64))
        with pytest.raises(ValueError, match="dividing"):
            flash_attention(qs, qs, qs, layout="bshd",
                            bias=jnp.zeros((3, 128, 128)))


class TestBucketedBias:
    """In-kernel BUCKETED relative bias (VERDICT r5 missing #2 + #1): the
    (num_buckets, h) table rides into VMEM and every score tile
    recomputes its bias from the closed form — no (h, sq, sk) array
    exists on the kernel path (jaxpr-asserted below) — and, because the
    bias derives from GLOBAL offsets, the same operand is first-class
    under ring/ulysses context parallelism."""

    def _bb(self, tab, bidir, maxd=64):
        from apex_tpu.ops.attention import BucketedBias
        return BucketedBias(tab, bidirectional=bidir, max_distance=maxd)

    @pytest.mark.pallas
    @pytest.mark.parametrize("causal,bidir", [(False, True), (True, False)])
    def test_kernel_fwd_bwd_vs_materialized(self, causal, bidir,
                                            monkeypatch):
        """Pallas in-kernel recompute == the materialized-operand oracle,
        through dq/dk/dv AND the bucket-table grad (dtable kernel vs the
        gather VJP)."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, h, s, d = 2, 2, 128, 64
        q = jr.normal(K, (b, h, s, d))
        k = jr.normal(jr.fold_in(K, 1), (b, h, s, d))
        v = jr.normal(jr.fold_in(K, 2), (b, h, s, d))
        tab = jr.normal(jr.fold_in(K, 3), (32, h)) * 0.4

        def bucketed(q, k, v, t):
            return jnp.sum(jnp.sin(flash_attention(
                q, k, v, causal=causal, bias=self._bb(t, bidir),
                impl="pallas")))

        def oracle(q, k, v, t):
            arr = self._bb(t, bidir).materialize(s, s)
            return jnp.sum(jnp.sin(flash_attention(
                q, k, v, causal=causal, bias=arr,  # apexlint: disable=APX304
                impl="xla")))

        with jax.default_matmul_precision("highest"):
            o1 = jax.jit(lambda q, k, v, t: flash_attention(
                q, k, v, causal=causal, bias=self._bb(t, bidir),
                impl="pallas"))(q, k, v, tab)
            o2 = flash_attention(q, k, v, causal=causal,
                                 bias=self._bb(tab, bidir).materialize(s, s),  # apexlint: disable=APX304
                                 impl="xla")
            np.testing.assert_allclose(o1, o2, rtol=1e-4, atol=1e-4)
            g1 = jax.jit(jax.grad(bucketed, (0, 1, 2, 3)))(q, k, v, tab)
            g2 = jax.jit(jax.grad(oracle, (0, 1, 2, 3)))(q, k, v, tab)
        for a, e, n in zip(g1, g2, ["dq", "dk", "dv", "dtable"]):
            np.testing.assert_allclose(a, e, rtol=5e-4, atol=5e-4,
                                       err_msg=n)

    @pytest.mark.pallas
    def test_bshd_composed_gqa_varlen_dropout(self, monkeypatch):
        """All operands at once on the seq-major layout: bucketed bias +
        grouped kv + padded batch + in-kernel dropout — Pallas vs XLA
        dispatch (same hash, same closed form)."""
        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        b, s, h, hkv, d = 2, 256, 4, 2, 128
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 4), (b, s, hkv, d))
        v = jr.normal(jr.fold_in(K, 5), (b, s, hkv, d))
        tab = jr.normal(jr.fold_in(K, 6), (32, h)) * 0.4
        lens = jnp.array([200, 128], jnp.int32)

        def make(impl):
            def f(q, k, v, t):
                return jnp.sum(jnp.sin(flash_attention(
                    q, k, v, causal=True, bias=self._bb(t, False),
                    kv_lens=lens, layout="bshd", impl=impl,
                    dropout_rate=0.15, dropout_seed=7)))
            return f

        with jax.default_matmul_precision("highest"):
            g1 = jax.jit(jax.grad(make("pallas"), (0, 1, 2, 3)))(q, k, v, tab)
            g2 = jax.jit(jax.grad(make("xla"), (0, 1, 2, 3)))(q, k, v, tab)
        for a, e, n in zip(g1, g2, ["dq", "dk", "dv", "dtable"]):
            np.testing.assert_allclose(a, e, rtol=2e-3, atol=2e-3,
                                       err_msg=n)

    def test_offsets_select_the_global_window(self):
        """A shifted BucketedBias materializes the corresponding window of
        the global bias — the property the cp paths are built on."""
        tab = jr.normal(jr.fold_in(K, 7), (16, 3)) * 0.5
        bb = self._bb(tab, True, 32)
        full = bb.materialize(512, 512)
        win = bb.shifted(128, 256).materialize(64, 128)
        np.testing.assert_allclose(win, full[:, 128:192, 256:384])

    @pytest.mark.pallas
    def test_no_materialized_bias_in_jaxpr(self, monkeypatch):
        """THE memory claim, statically: the jaxpr of the bucketed kernel
        path (fwd AND grad) contains NO intermediate with two >= seq
        dims — the O(h·s²) bias (and any O(s²) score tensor) never
        exists. The 512-block cap died with it (blocks follow normal
        sizing). Asserted through the shared JXP contract helper
        (``apex_tpu.lint.contracts.no_aval_matching``), which carries
        the same Pallas-body exemption this test used to hand-roll: the
        kernel BODY works on (bq, bk) VMEM tiles — which equal (s, s)
        at this size — while the claim is about HBM arrays, i.e. the
        kernel's operands (checked at the pallas_call eqn) and
        everything outside the kernel."""
        from apex_tpu.lint import contracts as jc

        monkeypatch.setenv("APEX_TPU_PALLAS", "interpret")
        s, h, d = 256, 2, 64
        q = jr.normal(K, (h, s, d))
        tab = jr.normal(jr.fold_in(K, 8), (32, h)) * 0.4

        def fwd(q, k, v, t):
            return flash_attention(q, k, v, causal=False,
                                   bias=self._bb(t, True), impl="pallas")

        def loss(q, k, v, t):
            return jnp.sum(fwd(q, k, v, t) ** 2)

        contract = jc.no_aval_matching(
            lambda shape: sum(1 for dim in shape if dim >= s) >= 2,
            f"two dims >= seq ({s}): a materialized O(s^2) bias/score")
        for fn in (fwd, jax.grad(loss, argnums=(0, 1, 2, 3))):
            jc.assert_contracts(jax.make_jaxpr(fn)(q, q, q, tab),
                                [contract])

    def test_ring_bias_and_kv_lens_match_flash(self):
        """The cp seam (VERDICT r5 missing #1): ring attention with the
        bucketed bias + GLOBAL kv_lens (including a fully-dead row) ==
        single-chip flash with the same operands — outputs and all four
        grads, causal (zigzag stripes, step-0 three-piece decomposition)
        and full."""
        cp = 4
        mesh = mesh_lib.make_mesh(context_parallel_size=cp)
        bh, s, d, heads = 4, 16 * cp, 16, 2
        q = jr.normal(K, (bh, s, d))
        k = jr.normal(jr.fold_in(K, 9), (bh, s, d))
        v = jr.normal(jr.fold_in(K, 10), (bh, s, d))
        tab = jr.normal(jr.fold_in(K, 11), (16, heads)) * 0.4
        lens = jnp.array([s, 37, 20, 0], jnp.int32)

        for causal in (True, False):
            bidir = not causal

            def ring_loss(q, k, v, t):
                o = ring_attention(q, k, v, axis_name="cp", causal=causal,
                                   kv_lens=lens, bias=self._bb(t, bidir))
                return jnp.sum(jnp.sin(o))

            def flash_loss(q, k, v, t):
                o = flash_attention(q, k, v, causal=causal, kv_lens=lens,
                                    bias=self._bb(t, bidir))
                return jnp.sum(jnp.sin(o))

            spec = P(None, "cp", None)
            with jax.default_matmul_precision("highest"):
                if causal:
                    qs, ks, vs = (zigzag_shard(x, cp, 1)
                                  for x in (q, k, v))
                else:
                    qs, ks, vs = q, k, v
                g = jax.jit(mesh_lib.shard_map(
                    lambda q, k, v, t: jax.grad(
                        ring_loss, argnums=(0, 1, 2, 3))(q, k, v, t),
                    mesh=mesh, in_specs=(spec,) * 3 + (P(),),
                    out_specs=(spec,) * 3 + (P(),),
                ))(qs, ks, vs, tab)
                gref = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2, 3)))(
                    q, k, v, tab)
            for i, (a, e, n) in enumerate(
                    zip(g, gref, ["dq", "dk", "dv", "dtable"])):
                if causal and i < 3:
                    a = zigzag_unshard(a, cp, 1)
                np.testing.assert_allclose(
                    a, e, rtol=2e-3, atol=2e-3,
                    err_msg=f"{n} causal={causal}")

    def test_ulysses_bias_and_kv_lens_match_flash(self):
        """Ulysses: per-head table slices to each rank's head group (grad
        scatters + psums back), kv_lens rides the gathered sequence."""
        cp = 2
        mesh = mesh_lib.make_mesh(context_parallel_size=cp)
        b, s, h, d = 2, 32 * cp, 4, 16
        q = jr.normal(K, (b, s, h, d))
        k = jr.normal(jr.fold_in(K, 12), (b, s, h, d))
        v = jr.normal(jr.fold_in(K, 13), (b, s, h, d))
        tab = jr.normal(jr.fold_in(K, 14), (16, h)) * 0.4
        lens = jnp.array([40, 0], jnp.int32)

        def u_loss(q, k, v, t):
            o = ulysses_attention(q, k, v, axis_name="cp", causal=True,
                                  kv_lens=lens, bias=self._bb(t, False))
            return jnp.sum(jnp.sin(o))

        def f_loss(q, k, v, t):
            o = flash_attention(q, k, v, causal=True, kv_lens=lens,
                                bias=self._bb(t, False), layout="bshd")
            return jnp.sum(jnp.sin(o))

        spec = P(None, "cp")
        with jax.default_matmul_precision("highest"):
            g = jax.jit(mesh_lib.shard_map(
                lambda q, k, v, t: jax.grad(
                    u_loss, argnums=(0, 1, 2, 3))(q, k, v, t),
                mesh=mesh, in_specs=(spec,) * 3 + (P(),),
                out_specs=(spec,) * 3 + (P(),),
            ))(q, k, v, tab)
            gref = jax.jit(jax.grad(f_loss, argnums=(0, 1, 2, 3)))(
                q, k, v, tab)
        for a, e, n in zip(g, gref, ["dq", "dk", "dv", "dtable"]):
            np.testing.assert_allclose(a, e, rtol=2e-3, atol=2e-3,
                                       err_msg=n)

    def test_validation(self):
        from apex_tpu.ops.attention import BucketedBias
        q = jr.normal(K, (2, 4, 128, 64))
        with pytest.raises(ValueError, match="num_buckets"):
            flash_attention(q, q, q, bias=BucketedBias(
                jnp.zeros((130, 4)), True, 64))
        with pytest.raises(ValueError, match="even num_buckets"):
            flash_attention(q, q, q, bias=BucketedBias(
                jnp.zeros((15, 4)), True, 64))
        with pytest.raises(ValueError, match="divide"):
            flash_attention(q, q, q, bias=BucketedBias(
                jnp.zeros((16, 3)), True, 64))
        with pytest.raises(ValueError, match="BucketedBias"):
            ring_attention(q[:, 0], q[:, 0], q[:, 0],
                           bias=jnp.zeros((4, 128, 128)))
        with pytest.raises(ValueError, match="materialized"):
            from apex_tpu.ops.attention import fused_qkv_attention
            fused_qkv_attention(
                jnp.zeros((1, 128, 64)), jnp.zeros((192, 64)),
                jnp.zeros((192,)), jnp.zeros((64, 64)),
                BucketedBias(jnp.zeros((16, 1)), True, 64), None, None,
                1, 1, 64, 0.125, True)
