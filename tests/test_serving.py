"""Continuous-batching serving tests (ISSUE 7 acceptance criteria).

The contracts under test:

* the paged KV pool: free-list allocator invariants (dead block
  reserved, exhaustion is loud, double-free is loud, free restores);
* the scheduler: FCFS admission behind the worst-case reservation gate,
  chunked-prefill progression, eviction returns every block (no leak
  across N churn cycles);
* paged ``decode_attention`` == contiguous (bitwise on the XLA gather
  path, tolerance on the interpret-mode kernel), with and without the
  bucketed relative bias;
* the fused sampling tail: greedy == argmax, kernel == XLA fallback
  token-for-token on shared noise, top-k/top-p kept sets match the
  standalone sort/cumsum sampler's sets;
* the ServingEngine: greedy decode under paging/chunking is
  TOKEN-IDENTICAL to the single-request ``DecodeEngine``, and
  ``prefill_chunk._cache_size() == 1`` / ``decode_step._cache_size()
  == 1`` across a scripted admit/evict/length-mix churn schedule
  (recompile-freedom — the stable-aval contract);
* ``serve`` monitor records validate through the schema, the report,
  and the ``tools/validate_metrics.py --serve`` forced dispatch.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.inference import DecodeEngine, sample_logits
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops import decode_attention, fused_sample
from apex_tpu.serving import (
    DEAD_BLOCK,
    BlockAllocator,
    Request,
    Scheduler,
    ServingEngine,
    blocks_needed,
)

K = jr.PRNGKey(11)


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig(vocab_size=97, max_seq_len=128, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    attention_impl="flash", remat=False, dropout=0.0)
    model = GPTModel(cfg)
    return model, model.init(K)


@pytest.fixture(scope="module")
def reference_engine(tiny):
    model, _ = tiny
    return DecodeEngine(model)


def _req(rng, rid, max_prompt=30, max_new=12):
    return Request(
        rid=rid,
        prompt=np.asarray(rng.integers(0, 97, rng.integers(1, max_prompt)),
                          np.int32),
        max_new_tokens=int(rng.integers(1, max_new)))


class TestBlockAllocator:
    def test_dead_block_never_allocated(self):
        a = BlockAllocator(5)
        ids = a.allocate(4)
        assert sorted(ids) == [1, 2, 3, 4] and DEAD_BLOCK not in ids

    def test_exhaustion_and_restore(self):
        a = BlockAllocator(4)
        ids = a.allocate(3)
        with pytest.raises(RuntimeError, match="exhausted"):
            a.allocate(1)
        a.free(ids)
        assert a.num_free == 3 and a.num_live == 0
        assert len(a.allocate(3)) == 3

    def test_double_free_and_dead_free_are_loud(self):
        a = BlockAllocator(4)
        (bid,) = a.allocate(1)
        a.free([bid])
        with pytest.raises(ValueError, match="double free"):
            a.free([bid])
        with pytest.raises(ValueError, match="dead block"):
            a.free([DEAD_BLOCK])

    def test_needs_two_blocks_minimum(self):
        with pytest.raises(ValueError, match="dead block"):
            BlockAllocator(1)

    def test_blocks_needed(self):
        assert [blocks_needed(n, 8) for n in (1, 8, 9, 16, 17)] \
            == [1, 1, 2, 2, 3]

    # --- ISSUE 10 accounting: leak counter, high-water, fragmentation -----

    def test_leak_counter_zero_across_churn_cycles(self):
        """N scripted admit/evict cycles of mixed sizes: the leak
        counter is EXACTLY zero throughout and at the end, and the
        lifetime alloc/free totals balance."""
        import numpy as np
        a = BlockAllocator(16)
        rng = np.random.default_rng(7)
        for _ in range(50):
            n = int(rng.integers(1, 6))
            ids = a.allocate(n)
            assert a.leaked == 0
            a.check_accounting()
            a.free(ids)
            assert a.leaked == 0
        assert a.alloc_total == a.free_total > 0
        assert a.num_live == 0 and a.num_free == 15
        a.check_accounting()

    def test_high_water_is_monotone(self):
        import numpy as np
        a = BlockAllocator(20)
        rng = np.random.default_rng(8)
        held, seen = [], []
        for _ in range(40):
            if held and rng.random() < 0.5:
                a.free([held.pop()])
            else:
                if a.num_free:
                    held.extend(a.allocate(1))
            seen.append(a.high_water)
            assert a.high_water >= a.num_live
        assert seen == sorted(seen), "high_water regressed"
        assert a.high_water == max(
            seen), "high_water is not the running max"

    def test_double_free_still_loud_with_counters(self):
        """The new counters must not swallow the loud failure modes —
        and a refused free must not corrupt the ledger."""
        a = BlockAllocator(6)
        ids = a.allocate(2)
        a.free(ids)
        with pytest.raises(ValueError, match="double free"):
            a.free([ids[0]])
        with pytest.raises(ValueError, match="dead block"):
            a.free([DEAD_BLOCK])
        assert a.alloc_total == 2 and a.free_total == 2
        assert a.leaked == 0
        a.check_accounting()

    def test_accounting_check_is_loud_on_corruption(self):
        a = BlockAllocator(6)
        ids = a.allocate(3)
        a.check_accounting()
        a._live.discard(ids[0])  # cross-wire behind the API
        assert a.leaked == 1
        with pytest.raises(RuntimeError, match="accounting broken"):
            a.check_accounting()

    def test_fragmentation_accounting(self):
        a = BlockAllocator(9)
        assert a.fragmentation_pct() == 0.0  # fresh pool: one run
        ids = a.allocate(8)
        assert a.fragmentation_pct() == 0.0  # empty free list
        a.free([ids[1], ids[4], ids[6]])     # 3 scattered singletons
        assert a.fragmentation_pct() == pytest.approx(100 * (1 - 1 / 3))
        a.free([i for i in ids if i not in (ids[1], ids[4], ids[6])])
        assert a.fragmentation_pct() == 0.0  # whole pool back: one run


class TestScheduler:
    def _sched(self, num_blocks=20, num_slots=2, block=4, chunk=8):
        return Scheduler(num_slots=num_slots, block_size=block,
                         max_blocks_per_slot=16,
                         allocator=BlockAllocator(num_blocks),
                         prefill_chunk=chunk)

    def test_chunked_prefill_progression(self):
        s = self._sched()
        prompt = np.arange(19, dtype=np.int32)
        s.submit(Request(rid=0, prompt=prompt, max_new_tokens=3))
        s.admit(now=0.0)
        works = []
        while True:
            w = s.next_prefill()
            if w is None:
                break
            works.append((w.start, w.live, w.is_last))
            np.testing.assert_array_equal(
                w.tokens[:w.live], prompt[w.start:w.start + w.live])
            s.note_prefill(w, sampled_token=42, now=1.0)
        # 19 tokens in chunks of 8: (0,8) (8,8) (16,3 last)
        assert works == [(0, 8, False), (8, 8, False), (16, 3, True)]
        # blocks cover exactly the live frontier: ceil(19/4) = 5
        assert s.allocator.num_live == 5
        assert s.decoding_slots() == [0]

    def test_admission_reservation_gate_and_fcfs(self):
        # pool of 5 allocatable blocks; each request worst-cases at
        # ceil((8 + 4 - 1)/4) = 3 blocks -> only ONE admits at a time
        s = self._sched(num_blocks=6)
        for i in range(3):
            s.submit(Request(rid=i, prompt=np.zeros(8, np.int32),
                             max_new_tokens=4))
        assert s.admit(now=0.0) == [0]  # FCFS head only
        w = s.next_prefill()
        s.note_prefill(w, sampled_token=1, now=0.0)
        assert s.admit(now=0.0) == []  # still reserved: 3 + (3-2) > 5...
        # finish request 0: its blocks free, reservation clears
        for _ in range(3):
            batch = s.decode_batch()
            assert batch is not None
            s.note_decode(np.full(2, 7), now=0.0)
        assert s.completed and s.completed[0].rid == 0
        assert s.admit(now=0.0) == [0]  # rid 1 takes the freed slot

    def test_eviction_returns_every_block(self):
        """No leak across N churn cycles: after every request completes
        the free list is exactly the fresh pool."""
        s = self._sched(num_blocks=12)
        rng = np.random.default_rng(3)
        for cycle in range(6):
            s.submit(_req(rng, cycle, max_prompt=20, max_new=6))
        while not s.idle():
            s.admit(now=0.0)
            w = s.next_prefill()
            if w is not None:
                s.note_prefill(w, sampled_token=5, now=0.0)
            batch = s.decode_batch()
            if batch is not None:
                s.note_decode(np.full(2, 9), now=0.0)
        assert len(s.completed) == 6
        assert s.allocator.num_live == 0
        assert s.allocator.num_free == 11
        np.testing.assert_array_equal(
            s.tables.asarray(), np.full((2, 16), DEAD_BLOCK))

    def test_submit_validation(self):
        s = self._sched()
        with pytest.raises(ValueError, match="cache rows"):
            s.submit(Request(rid=0, prompt=np.zeros(60, np.int32),
                             max_new_tokens=10))  # 69 > 16*4
        # fits a slot but can NEVER fit the pool: refusing eagerly beats
        # the permanent admission stall it would otherwise become
        tight = Scheduler(num_slots=2, block_size=8,
                          max_blocks_per_slot=8,
                          allocator=BlockAllocator(4), prefill_chunk=8)
        with pytest.raises(ValueError, match="never be admitted"):
            tight.submit(Request(rid=0, prompt=np.zeros(33, np.int32),
                                 max_new_tokens=8))  # 5 blocks > 3
        # the error names the knob AND the rounding recipe (ISSUE 10):
        # ceil((prompt + max_new - 1)/block_size) and the num_blocks
        # floor that would make the request admissible
        with pytest.raises(ValueError) as ei:
            tight.submit(Request(rid=3, prompt=np.zeros(33, np.int32),
                                 max_new_tokens=8))
        msg = str(ei.value)
        for needle in ("num_blocks=4", "ceil((prompt 33 + max_new_tokens "
                       "8 - 1) / block_size 8)", "needs 5 blocks",
                       "Raise num_blocks to >= 6"):
            assert needle in msg, f"submit error dropped {needle!r}: {msg}"
        with pytest.raises(ValueError, match=">= 1"):
            s.submit(Request(rid=0, prompt=np.zeros(4, np.int32),
                             max_new_tokens=0))
        with pytest.raises(ValueError, match="prefill_chunk"):
            Scheduler(num_slots=1, block_size=4, max_blocks_per_slot=4,
                      allocator=BlockAllocator(4), prefill_chunk=6)

    def test_future_arrivals_wait(self):
        s = self._sched()
        s.submit(Request(rid=0, prompt=np.zeros(4, np.int32),
                         max_new_tokens=2, arrival_s=5.0))
        assert s.admit(now=1.0) == []
        assert s.next_arrival() == 5.0
        assert s.admit(now=6.0) == [0]


class TestPagedDecodeAttention:
    def _scatter(self, kc, vc, nb_max, bs):
        """Scatter a contiguous (b, h_kv, nb_max*bs, d) cache into a
        shuffled pool + tables."""
        b, h_kv, _, d = kc.shape
        num_blocks = b * nb_max + 1
        rng = np.random.default_rng(0)
        ids = rng.permutation(np.arange(1, num_blocks))
        tables = np.zeros((b, nb_max), np.int32)
        pk = np.zeros((num_blocks, h_kv, bs, d), np.float32)
        pv = np.zeros((num_blocks, h_kv, bs, d), np.float32)
        n = 0
        for bi in range(b):
            for j in range(nb_max):
                tables[bi, j] = ids[n]
                pk[ids[n]] = np.asarray(kc[bi, :, j * bs:(j + 1) * bs])
                pv[ids[n]] = np.asarray(vc[bi, :, j * bs:(j + 1) * bs])
                n += 1
        return jnp.asarray(pk), jnp.asarray(pv), jnp.asarray(tables)

    def test_paged_matches_contiguous(self):
        b, h, h_kv, d, bs, nb_max = 3, 8, 2, 64, 128, 4
        q = jr.normal(K, (b, h, d))
        kc = jr.normal(jr.fold_in(K, 1), (b, h_kv, bs * nb_max, d))
        vc = jr.normal(jr.fold_in(K, 2), (b, h_kv, bs * nb_max, d))
        lens = jnp.array([5, 300, 0], jnp.int32)  # ragged + dead row
        pk, pv, tables = self._scatter(kc, vc, nb_max, bs)
        want = decode_attention(q, kc, vc, lens, impl="xla")
        got = decode_attention(q, pk, pv, lens, impl="xla",
                               block_tables=tables)
        # the gather fallback runs the EXACT contiguous math
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
        got_pl = decode_attention(q, pk, pv, lens, impl="pallas",
                                  block_tables=tables)
        np.testing.assert_allclose(np.asarray(got_pl), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_paged_with_bucketed_bias(self):
        from apex_tpu.ops.attention import BucketedBias
        b, h, h_kv, d, bs, nb_max = 2, 4, 2, 64, 128, 2
        bb = BucketedBias(jr.normal(jr.fold_in(K, 9), (16, h)) * 0.4,
                          bidirectional=False, max_distance=64)
        q = jr.normal(K, (b, h, d))
        kc = jr.normal(jr.fold_in(K, 1), (b, h_kv, bs * nb_max, d))
        vc = jr.normal(jr.fold_in(K, 2), (b, h_kv, bs * nb_max, d))
        lens = jnp.array([200, 77], jnp.int32)
        pk, pv, tables = self._scatter(kc, vc, nb_max, bs)
        want = decode_attention(q, kc, vc, lens, impl="xla", bias=bb)
        got = decode_attention(q, pk, pv, lens, impl="xla", bias=bb,
                               block_tables=tables)
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
        got_pl = decode_attention(q, pk, pv, lens, impl="pallas", bias=bb,
                                  block_tables=tables)
        np.testing.assert_allclose(np.asarray(got_pl), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_validation(self):
        q = jnp.zeros((2, 4, 64))
        pool = jnp.zeros((5, 2, 16, 64))
        lens = jnp.zeros((2,), jnp.int32)
        with pytest.raises(ValueError, match="block_tables"):
            decode_attention(q, pool, pool, lens,
                             block_tables=jnp.zeros((3, 4), jnp.int32))
        with pytest.raises(ValueError, match="integer"):
            decode_attention(q, pool, pool, lens,
                             block_tables=jnp.zeros((2, 4)))
        with pytest.raises(ValueError, match="h_kv"):
            decode_attention(q, jnp.zeros((5, 3, 16, 64)),
                             jnp.zeros((5, 3, 16, 64)), lens,
                             block_tables=jnp.zeros((2, 4), jnp.int32))


class TestFusedSample:
    def test_greedy_is_argmax(self):
        logits = jr.normal(K, (3, 17))
        np.testing.assert_array_equal(
            np.asarray(fused_sample(logits)),
            np.asarray(jnp.argmax(logits, -1)))

    def test_validation(self):
        logits = jnp.zeros((1, 8))
        with pytest.raises(ValueError, match="requires a PRNG key"):
            fused_sample(logits, None, temperature=1.0)
        with pytest.raises(ValueError, match="temperature"):
            fused_sample(logits, K, temperature=-1.0)
        with pytest.raises(ValueError, match="top_p"):
            fused_sample(logits, K, temperature=1.0, top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            fused_sample(logits, K, temperature=1.0, top_k=-1)
        with pytest.raises(ValueError, match="\\(b, V\\)"):
            fused_sample(jnp.zeros((8,)))

    def test_kernel_matches_xla_fallback_token_for_token(self):
        """Shared noise -> the kernel's bisection thresholds select the
        SAME kept set as the fallback (they run the same helpers), so
        the sampled token agrees exactly, across knob combinations."""
        logits = jr.normal(jr.fold_in(K, 1), (4, 256)) * 2.0
        for tk, tp in [(0, 1.0), (7, 1.0), (0, 0.8), (11, 0.6)]:
            draw = jax.jit(lambda key, impl, tk=tk, tp=tp: fused_sample(
                logits, key, temperature=0.9, top_k=tk, top_p=tp,
                impl=impl), static_argnames=("impl",))
            for i in range(15):
                k = jr.fold_in(K, 1000 + i)
                np.testing.assert_array_equal(
                    np.asarray(draw(k, "xla")), np.asarray(draw(k, "pallas")),
                    err_msg=f"top_k={tk} top_p={tp} draw {i}")

    def test_topk_support(self):
        logits = jr.normal(jr.fold_in(K, 2), (4, 256))
        top = np.asarray(jax.lax.top_k(logits, 5)[1])
        draw = jax.jit(lambda key: fused_sample(
            logits, key, temperature=1.3, top_k=5, impl="pallas"))
        for i in range(40):
            toks = np.asarray(draw(jr.fold_in(K, 50 + i)))
            for bi in range(4):
                assert toks[bi] in top[bi]

    def test_topp_kept_set_matches_standalone_sampler(self):
        """The fused tail's bisection nucleus == the standalone
        sort/cumsum nucleus: over many draws both samplers' supports
        equal the numpy oracle set."""
        logits = jr.normal(jr.fold_in(K, 3), (3, 256)) * 2.0
        fused_draw = jax.jit(lambda key: fused_sample(
            logits, key, temperature=0.9, top_p=0.6, impl="pallas"))
        ref_draw = jax.jit(lambda key: sample_logits(
            logits, key, temperature=0.9, top_p=0.6))
        seen_f = [set() for _ in range(3)]
        seen_r = [set() for _ in range(3)]
        for i in range(300):
            tf = np.asarray(fused_draw(jr.fold_in(K, 5000 + i)))
            tr = np.asarray(ref_draw(jr.fold_in(K, 7000 + i)))
            for bi in range(3):
                seen_f[bi].add(int(tf[bi]))
                seen_r[bi].add(int(tr[bi]))
        s = np.asarray(logits, np.float64) / 0.9
        for bi in range(3):
            order = np.argsort(-s[bi])
            probs = np.exp(s[bi] - s[bi].max())
            probs /= probs.sum()
            csum = np.cumsum(probs[order])
            ncut = int(np.searchsorted(csum, 0.6) + 1)
            oracle = set(order[:ncut].tolist())
            assert seen_f[bi] == oracle, (bi, seen_f[bi], oracle)
            assert seen_r[bi] == oracle, (bi, seen_r[bi], oracle)

    def test_topp_composed_with_topk_filters(self):
        """Regression: top-p must still bite AFTER a top-k pass. The
        top-k filter pins the row min at the FILTERED sentinel; a
        bisection starting there never collapses, silently disabling
        top-p (caught in review). Same oracle as the standalone
        sampler's composition test: top_k=2 keeps {0, 1}; over that
        renormalized pair, top_p=0.5 keeps ONLY the head. (Vocab padded
        to the kernel's 128-lane grid with negligible-mass entries.)"""
        row = np.full(128, -20.0, np.float32)
        row[:6] = [3.0, 2.9, 2.8, 0.0, -1.0, -2.0]
        logits = jnp.asarray(row)[None]
        for impl in ("xla", "pallas"):
            draw = jax.jit(lambda key, impl=impl: fused_sample(
                logits, key, temperature=1.0, top_k=2, top_p=0.5,
                impl=impl))
            for i in range(30):
                assert int(draw(jr.fold_in(K, 900 + i))[0]) == 0, impl
        # and with top_p=0.6 the crossing token joins: both appear
        seen = set()
        draw = jax.jit(lambda key: fused_sample(
            logits, key, temperature=1.0, top_k=2, top_p=0.6,
            impl="pallas"))
        for i in range(200):
            seen.add(int(draw(jr.fold_in(K, 1200 + i))[0]))
        assert seen == {0, 1}


class TestServingEngine:
    def test_greedy_single_request_matches_decode_engine(
            self, tiny, reference_engine):
        """The acceptance anchor: a no-churn single-request workload
        through the paged, chunked engine decodes the IDENTICAL token
        sequence as DecodeEngine — and both serving programs compiled
        exactly once."""
        model, params = tiny
        prompt = np.asarray(jr.randint(jr.fold_in(K, 3), (7,), 0, 97),
                            np.int32)
        n = 8
        want = np.asarray(reference_engine.generate(
            params, jnp.asarray(prompt)[None], n))[0]
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        done = eng.serve(params, [Request(rid=0, prompt=prompt,
                                          max_new_tokens=n)])
        np.testing.assert_array_equal(np.asarray(done[0].tokens), want)
        assert eng.prefill_chunk._cache_size() == 1
        assert eng.decode_step._cache_size() == 1
        assert done[0].first_token_s is not None
        assert done[0].finish_s >= done[0].first_token_s

    def test_churn_schedule_recompile_free_and_leak_free(
            self, tiny, reference_engine):
        """The scripted churn schedule: more requests than slots, mixed
        prompt/output lengths, a pool SMALLER than worst-case-everything
        — across every admit/evict the jit caches stay at 1, every
        request still matches the single-request engine token-for-token,
        and after N cycles every block is back on the free list."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=16, max_seq_len=64,
                            num_blocks=13)
        rng = np.random.default_rng(0)
        reqs = [_req(rng, i) for i in range(7)]
        sched = eng.make_scheduler()
        done = eng.serve(params, reqs, scheduler=sched)
        assert len(done) == 7
        assert eng.prefill_chunk._cache_size() == 1, "prefill re-traced"
        assert eng.decode_step._cache_size() == 1, "decode re-traced"
        for r in done:
            assert len(r.tokens) == r.max_new_tokens
            want = np.asarray(reference_engine.generate(
                params, jnp.asarray(r.prompt)[None], r.max_new_tokens))[0]
            np.testing.assert_array_equal(np.asarray(r.tokens), want,
                                          err_msg=f"rid {r.rid}")
        # no leak: the free list is exactly the fresh pool again
        assert sched.allocator.num_live == 0
        assert sched.allocator.num_free == eng.num_blocks - 1
        # and paging did its job: the high-water stayed under the pool
        assert 0 < eng.last_stats.blocks_high_water <= eng.num_blocks - 1

    def test_arrival_replay_and_ttft_stamps(self, tiny):
        """Requests with future arrivals are held; TTFT/finish stamps
        are ordered and on the serve clock."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        reqs = [Request(rid=0, prompt=np.zeros(4, np.int32),
                        max_new_tokens=3, arrival_s=0.0),
                Request(rid=1, prompt=np.zeros(6, np.int32),
                        max_new_tokens=2, arrival_s=0.05)]
        done = eng.serve(params, reqs)
        assert {r.rid for r in done} == {0, 1}
        for r in done:
            assert r.admit_s >= r.arrival_s
            assert r.first_token_s >= r.admit_s
            assert r.finish_s >= r.first_token_s
            assert len(r.token_s) == len(r.tokens)

    def test_sampled_serving_uses_fused_tail_support(self, tiny):
        """top-k serving: every generated token of every request lies in
        the top-k of the teacher-forced logits on its own prefix."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64,
                            temperature=0.7, top_k=3)
        prompt = np.asarray(jr.randint(jr.fold_in(K, 5), (4,), 0, 97),
                            np.int32)
        done = eng.serve(params, [Request(rid=0, prompt=prompt,
                                          max_new_tokens=5)],
                         key=jr.fold_in(K, 60))
        toks = done[0].tokens
        seq = jnp.asarray(prompt)[None]
        for t in range(5):
            logits = model.logits(params, seq)[:, -1]
            top3 = np.asarray(jax.lax.top_k(logits, 3)[1])[0]
            assert toks[t] in top3
            seq = jnp.concatenate(
                [seq, jnp.asarray([[toks[t]]], jnp.int32)], axis=1)

    def test_validation(self, tiny):
        model, _ = tiny
        with pytest.raises(ValueError, match="multiple of.*block_size"):
            ServingEngine(model, num_slots=2, block_size=8, max_seq_len=60)
        with pytest.raises(ValueError, match="position table"):
            ServingEngine(model, num_slots=2, block_size=8,
                          max_seq_len=256)
        with pytest.raises(ValueError, match="prefill_chunk"):
            ServingEngine(model, num_slots=2, block_size=8,
                          max_seq_len=64, prefill_chunk=12)
        with pytest.raises(ValueError, match="num_slots"):
            ServingEngine(model, num_slots=0, block_size=8, max_seq_len=64)
        eng = ServingEngine(model, num_slots=1, block_size=8,
                            max_seq_len=64, temperature=1.0)
        with pytest.raises(ValueError, match="requires a key"):
            eng.serve({}, [])


class TestServeRecord:
    def test_emit_serve_roundtrip_report_and_validator(self, tmp_path):
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            monitor.emit_meta(device_kind="cpu")
            rec = monitor.emit_serve(
                "OK", tokens_per_s=4321.0, latency_p50_ms=1.2,
                latency_p99_ms=3.4, ttft_p50_ms=20.0, ttft_p99_ms=55.0,
                occupancy_pct=87.5, vs_single_request=1.9,
                greedy_parity=True, jit_cache_ok=True, requests=32,
                slots=8, block_size=128, blocks_high_water=40)
            assert monitor.validate(rec) == []
        finally:
            monitor.disable()
        lines = path.read_text().splitlines()
        assert monitor.validate_jsonl(lines) == []
        from apex_tpu.monitor import report as monitor_report
        summary = monitor_report.aggregate(
            monitor_report.read_records(lines))
        assert summary["serve"]["tokens_per_s"] == 4321.0
        assert summary["serve"]["status"] == "OK"
        rendered = monitor_report.render(summary)
        assert "serve" in rendered and "p50/p99 1.20/3.40" in rendered

    def test_ok_serve_record_with_nan_refused(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit_serve("OK", tokens_per_s=float("nan"))

    def test_skip_needs_reason(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="reason"):
            reg.emit_serve("SKIP")
        rec = reg.emit_serve("SKIP", reason="no TPU",
                             vs_single_request=("skipped", "no TPU"))
        assert rec["vs_single_request"] == {"skipped": True,
                                            "reason": "no TPU"}
        assert monitor.validate(rec) == []
        bare = {k: v for k, v in rec.items() if k != "reason"}
        assert any("reason" in e for e in monitor.validate(bare))

    def test_validator_cli_serve_dispatch(self, tmp_path, capsys):
        """--serve forced dispatch: a valid serve stream passes, a
        stream without a serve record fails, a wrong-kind artifact
        fails — the drift test pinning the CLI contract."""
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        import validate_metrics
        reg = monitor.MetricsRegistry()
        rec = reg.emit_serve("SKIP", reason="no TPU")
        good = tmp_path / "serve.jsonl"
        good.write_text(json.dumps(rec) + "\n")
        assert validate_metrics.main([str(good)]) == 0          # content
        assert validate_metrics.main(["--serve", str(good)]) == 0
        capsys.readouterr()
        # content dispatch catches a malformed serve record
        bad = tmp_path / "bad.jsonl"
        bad_rec = dict(rec, status="OK", tokens_per_s=float("nan"))
        bad.write_text(json.dumps(bad_rec).replace("NaN", '"nan"') + "\n")
        assert validate_metrics.main([str(bad)]) == 1
        # forced dispatch: a stream with no serve record must fail
        other = tmp_path / "other.jsonl"
        other.write_text(json.dumps(
            reg.emit_decode("SKIP", reason="no TPU")) + "\n")
        assert validate_metrics.main(["--serve", str(other)]) == 1
        err = capsys.readouterr().err
        assert "expected a 'serve' artifact" in err
        # a multi-record stream without a serve record also fails
        stream = tmp_path / "stream.jsonl"
        stream.write_text(
            json.dumps(reg.emit_decode("SKIP", reason="no TPU")) + "\n"
            + json.dumps(reg.emit_meta(device_kind="cpu")) + "\n")
        assert validate_metrics.main(["--serve", str(stream)]) == 1
        assert "no 'serve' record" in capsys.readouterr().err


class TestServeBenchLeg:
    def test_bench_serve_emits_valid_skip_record_off_tpu(self, tmp_path):
        """The serving bench leg end-to-end at smoke scale: off-TPU it
        must print/emit an explicit SKIP record — schema-valid, no nan,
        greedy parity + pinned jit caches witnessed — and the stream
        must pass the validator CLI."""
        root = os.path.join(os.path.dirname(__file__), "..")
        path = tmp_path / "serve.jsonl"
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   APEX_TPU_MONITOR=str(path))
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "bench.py"), "--serve"],
            capture_output=True, text=True, env=env, cwd=root, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        record = json.loads(proc.stdout.strip().splitlines()[-1])
        assert record["kind"] == "serve" and record["status"] == "SKIP"
        assert record["greedy_parity"] is True
        assert record["jit_cache_ok"] is True
        assert record["blocks_high_water"] >= 1
        assert monitor.validate(record) == []
        assert monitor.validate_jsonl(
            path.read_text().splitlines()) == []
